"""Render banked TPU evidence into BASELINE.md's Measured section.

Reads the three JSON-Lines evidence artifacts (written by measure_tpu.py /
tpu_watchdog.py, bench_kernels.py, bench_sampler_loop.py) and rewrites the
block between the ``<!-- measured:begin -->`` / ``<!-- measured:end -->``
markers in BASELINE.md. Raw evidence stays in the artifacts; this is the
human-readable view, regenerated whole so it can never drift from them.

    python scripts/render_measured.py          # rewrite BASELINE.md in place
    python scripts/render_measured.py --print  # preview to stdout
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
from bench import _TPU_PLATFORMS as _TPU, evidence_dir  # noqa: E402

_BEGIN, _END = "<!-- measured:begin -->", "<!-- measured:end -->"


def _lines(filename: str) -> list[dict]:
    path = os.path.join(evidence_dir(), filename)
    out: list[dict] = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return out


def _fmt_ts(ts: float | None) -> str:
    return time.strftime("%Y-%m-%d %H:%M", time.localtime(ts)) if ts else "?"


def render() -> str:
    recs = [r for r in _lines("BASELINE_measured.json")
            if r.get("platform") in _TPU and not r.get("invalid")]
    # Latest record per rung wins (earlier attempts may predate fixes).
    by_rung: dict[str, dict] = {}
    for r in recs:
        by_rung[r.get("rung", "?")] = r

    out: list[str] = []
    if not by_rung:
        out.append("No TPU-measured rungs banked yet (see the artifact capture "
                   "plan above; the watchdog banks them the moment the tunnel "
                   "is live).")
    else:
        out.append("| Rung | s/it | images/s | MFU | attention | vs 26.00 s/it | captured |")
        out.append("|---|---|---|---|---|---|---|")
        for rung, r in sorted(by_rung.items()):
            vs = r.get("vs_baseline")
            out.append(
                f"| {rung} | {r.get('value')} | {r.get('images_per_sec')} "
                f"| {r.get('mfu') if r.get('mfu') is not None else '—'} "
                f"| {r.get('attention_backend', '?')} "
                f"| {f'{vs}×' if vs is not None else '—'} "
                f"| {_fmt_ts(r.get('ts'))} |"
            )
        out.append("")
        out.append(f"{len(by_rung)} rung(s) banked on real TPU "
                   f"(platform tpu/axon; full records in BASELINE_measured.json).")

    # Usable-HBM probe (its own artifact — a GiB number, not a rung row):
    # banked when a rung OOMs with the microbatch ladder exhausted, i.e. when
    # weights+overhead alone exceed the chip (memory_stats() is None on the
    # axon device, so nothing else can report this).
    hbm = [r for r in _lines("HBM_PROBE.json")
           if r.get("platform") in _TPU and not r.get("invalid")]
    if hbm:
        r = hbm[-1]
        out.append("")
        out.append(f"Usable HBM (largest single bf16 buffer): "
                   f"**{r.get('value')} GiB** on {r.get('device_kind', '?')} "
                   f"(probe {_fmt_ts(r.get('ts'))}; why bf16 zimage_21 / "
                   f"int8 flux_16 cannot fit single-chip — HBM_PROBE.json).")

    # Latest-wins dedup, same as the rung table: the watchdog retries wedged
    # benches, and the artifacts are append-only.
    # Keyed on the shape LABEL, not seq — flux_1024_joint and flux_b4 share
    # seq=4608 and must both render.
    kern = list({r.get("shape"): r for r in _lines("KERNEL_BENCH.json")
                 if r.get("platform") in _TPU and not r.get("invalid")}.values())
    if kern:
        out.append("")
        out.append("**Pallas flash kernel vs XLA (measured)** — winners applied "
                   "to `ops/pallas/tuning.json` by `bench_kernels.py --apply`:")
        out.append("")
        out.append("| shape | batch | seq | best block_q×block_k | pallas ms | jax-pallas ms | xla ms |")
        out.append("|---|---|---|---|---|---|---|")
        for r in kern:
            xla = r.get("xla_ms")
            pj = r.get("pallas_jax_ms")
            pm = r.get("pallas_ms")
            out.append(f"| {r.get('shape')} | {r.get('b')} | {r.get('seq')} "
                       f"| {r.get('block_q')}×{r.get('block_k')} "
                       f"| {pm if pm is not None else '—'} "
                       f"| {pj if pj is not None else '—'} "
                       f"| {xla if xla is not None else 'OOM'} |")

    samp = list({r.get("workload"): r for r in _lines("SAMPLER_LOOP_BENCH.json")
                 if r.get("platform") in _TPU and not r.get("invalid")}.values())
    if samp:
        out.append("")
        out.append("**Whole-loop compiled sampler vs eager (measured)**:")
        out.append("")
        out.append("| workload | eager s | compiled s | speedup |")
        out.append("|---|---|---|---|")
        for r in samp:
            e, c = r.get("eager_s"), r.get("compiled_s")
            ratio = round(e / c, 2) if e and c else "—"
            out.append(f"| {r.get('workload', '?')} | {e} | {c} | {ratio}× |")

    return "\n".join(out)


def main() -> None:
    body = render()
    if "--print" in sys.argv:
        print(body)
        return
    path = os.path.join(evidence_dir(), "BASELINE.md")
    if not os.path.exists(path) and evidence_dir() != _REPO:
        # Redirected evidence dir (watchdog dry-run): seed the rendered copy
        # from the repo's BASELINE.md so the marker rewrite below works
        # against a fresh temp dir.
        import shutil

        shutil.copy(os.path.join(_REPO, "BASELINE.md"), path)
    text = open(path).read()
    if _BEGIN not in text or _END not in text:
        raise SystemExit(f"markers {_BEGIN} / {_END} not found in BASELINE.md")
    head, rest = text.split(_BEGIN, 1)
    _, tail = rest.split(_END, 1)
    with open(path, "w") as f:
        f.write(f"{head}{_BEGIN}\n{body}\n{_END}{tail}")
    print(f"BASELINE.md Measured section updated ({len(body.splitlines())} lines)")


if __name__ == "__main__":
    main()
