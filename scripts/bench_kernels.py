"""Flash-attention kernel benchmark: pallas (streamed K/V) vs plain XLA,
with a block-size sweep (VERDICT r2 item 7).

Run on a live TPU (the tunnel comes and goes — probe first):

    python scripts/bench_kernels.py            # measure, append KERNEL_BENCH.json
    python scripts/bench_kernels.py --apply    # ALSO write the winners into
                                               # ops/pallas/tuning.json so the
                                               # auto backend uses measured
                                               # blocks + xla-fallback ranges
    KERNEL_SWEEP=0 python scripts/bench_kernels.py   # default blocks only

Shapes cover the rungs that matter: FLUX joint attention at 1024² (4.6k tokens,
24 heads × 128) and WAN-video lengths (16k/32k tokens) where the streamed-K/V
layout is what keeps VMEM bounded. The sweep tries block_q × block_k over
{128, 256, 512}² per shape; each cell is the mean of 5 chained timed calls
after compile+warmup (see ``_time_fn`` for why chained). Appends JSON lines to
KERNEL_BENCH.json; BASELINE.md's kernel section reads from there.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# (label, batch, seq, heads, head_dim)
SHAPES = [
    ("flux_1024_joint", 1, 4608, 24, 128),
    ("flux_b4", 4, 4608, 24, 128),
    ("wan_480p_16f", 1, 16384, 12, 128),
    ("wan_long_32k", 1, 32768, 12, 128),
    # UNet-family heads: the kernel runs these zero-padded to 128 lanes
    # (flash_attention pads internally). A measured win here lets the auto
    # backend route SD-class 1024² attention (the sd15_16 rung's 8.6%-MFU
    # bottleneck) through the fused kernel; a loss keeps chunked XLA.
    ("sd15_1024_d40", 16, 16384, 8, 40),
    ("sdxl_1024_d64", 8, 4096, 10, 64),
]
if os.environ.get("PA_BENCH_TINY") == "1":
    # Watchdog dry-run: tiny shapes (one lane-aligned, one padded head dim)
    # keep the interpret-mode pallas cells cheap while the sweep/--apply
    # control flow runs for real.
    SHAPES = [
        ("tiny_128d", 1, 256, 2, 128),
        ("tiny_40d", 2, 256, 2, 40),
    ]


def _time_fn(fn, *args, iters=5):
    """Tunnel-proof mean time per call (attention maps q-shaped to q-shaped,
    so the output chains back as the first argument; see
    utils/metrics.chained_time for why per-call block_until_ready is
    untrustworthy through the axon tunnel)."""
    from comfyui_parallelanything_tpu.utils.metrics import chained_time

    sec, _ = chained_time(lambda a: fn(a, *args[1:]), args[0], iters)
    return sec


def _run_shapes(shapes, on_tpu, dev):
    """Measure the given shapes inline, appending one JSON line each to
    KERNEL_BENCH.json. Returns the per-shape tuning entries."""
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.ops.attention import (
        _chunk_threshold,
        _xla_attention,
        _xla_chunked_attention,
    )
    from comfyui_parallelanything_tpu.ops.pallas.flash_attention import (
        flash_attention,
    )

    def xla_family(a, b_, c, scale):
        # The real competitor the auto backend would pick: chunked when the
        # S×S logits would blow HBM, plain otherwise — routed on the LIVE
        # threshold (env + persisted chunk tuning), same as attention_local,
        # so pallas_wins decisions compare against production routing.
        elems = a.shape[0] * a.shape[2] * a.shape[1] * b_.shape[1]
        if elems > _chunk_threshold():
            return _xla_chunked_attention(a, b_, c, scale)
        return _xla_attention(a, b_, c, scale)

    from bench import evidence_dir

    out_path = os.path.join(evidence_dir(), "KERNEL_BENCH.json")
    sweep = on_tpu and os.environ.get("KERNEL_SWEEP", "1") != "0"
    blocks = (128, 256, 512)
    entries = []
    for label, b, s, h, d in shapes:
        k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(k1, (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(k2, (b, s, h, d), jnp.bfloat16)
        v = jax.random.normal(k3, (b, s, h, d), jnp.bfloat16)
        rec = {"shape": label, "b": b, "seq": s, "heads": h, "head_dim": d,
               "platform": dev.platform, "device_kind": dev.device_kind,
               "ts": time.time()}
        combos = (
            [(bq, bk) for bq in blocks for bk in blocks] if sweep else [(256, 256)]
        )
        best = None  # (ms, bq, bk)
        for bq, bk in combos:
            try:
                ms = _time_fn(
                    lambda a, b_, c, _bq=bq, _bk=bk: flash_attention(
                        a, b_, c, block_q=_bq, block_k=_bk
                    ),
                    q, k, v,
                ) * 1e3
            except Exception as e:  # noqa: BLE001 — record, keep sweeping
                rec[f"pallas_{bq}x{bk}_error"] = str(e)[:120]
                continue
            rec[f"pallas_{bq}x{bk}_ms"] = round(ms, 3)
            if best is None or ms < best[0]:
                best = (ms, bq, bk)
        if best is not None:
            rec["pallas_ms"] = round(best[0], 3)
            rec["block_q"], rec["block_k"] = best[1], best[2]
        if on_tpu and d % 128 == 0:
            # jax's upstream fused kernel: the second fused candidate the
            # tuning table can route auto to (ops/attention.py "pallas_jax").
            # Lane-aligned dims only; upstream block heuristics, no sweep.
            from comfyui_parallelanything_tpu.ops.attention import (
                _pallas_jax_attention,
            )

            try:
                rec["pallas_jax_ms"] = round(_time_fn(
                    lambda a, b_, c: _pallas_jax_attention(a, b_, c, d**-0.5),
                    q, k, v,
                ) * 1e3, 3)
            except Exception as e:  # noqa: BLE001 — record, keep measuring
                rec["pallas_jax_error"] = str(e)[:120]
        try:
            rec["xla_ms"] = round(
                _time_fn(lambda a, b_, c: xla_family(a, b_, c, d**-0.5),
                         q, k, v) * 1e3, 3
            )
        except Exception as e:  # noqa: BLE001 — S×S logits OOM at video lengths
            rec["xla_error"] = str(e)[:200]
        if "pallas_ms" in rec and "xla_ms" in rec:
            rec["pallas_speedup"] = round(rec["xla_ms"] / rec["pallas_ms"], 2)
        print(json.dumps(rec))
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if on_tpu and ("pallas_ms" in rec or "pallas_jax_ms" in rec):
            entries.append({
                "seq": s,
                "head_dim": d,
                "block_q": rec.get("block_q", 256),
                "block_k": rec.get("block_k", 256),
                "pallas_ms": rec.get("pallas_ms"),
                "pallas_jax_ms": rec.get("pallas_jax_ms"),
                "xla_ms": rec.get("xla_ms"),
            })
    return entries


def _entries_from_file() -> list[dict]:
    """Latest TPU-measured tuning entry per shape label from KERNEL_BENCH.json
    (the children append there; a wedged shape simply has no line)."""
    from bench import _TPU_PLATFORMS, evidence_dir

    by_label: dict[str, dict] = {}
    path = os.path.join(evidence_dir(), "KERNEL_BENCH.json")
    if os.path.exists(path):
        with open(path) as f:
            for raw in f:
                try:
                    r = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if (r.get("platform") in _TPU_PLATFORMS and not r.get("invalid")
                        and ("pallas_ms" in r or "pallas_jax_ms" in r)):
                    by_label[r.get("shape")] = r
    return [
        {"seq": r["seq"], "head_dim": r.get("head_dim"),
         "block_q": r.get("block_q", 256),
         "block_k": r.get("block_k", 256), "pallas_ms": r.get("pallas_ms"),
         "pallas_jax_ms": r.get("pallas_jax_ms"), "xla_ms": r.get("xla_ms")}
        for r in by_label.values()
    ]


def main() -> None:
    import jax

    from comfyui_parallelanything_tpu.utils import enable_compilation_cache

    enable_compilation_cache()

    from bench import _TPU_PLATFORMS

    dev = jax.devices()[0]
    # bench's tuple, not discovery's: the watchdog dry-run fakes the platform
    # here (so the sweep/--apply flow runs) without lying to the kernel's own
    # interpret-mode auto-detection.
    on_tpu = dev.platform in _TPU_PLATFORMS

    if "--shape" in sys.argv:
        label = sys.argv[sys.argv.index("--shape") + 1]
        shapes = [sh for sh in SHAPES if sh[0] == label]
        if not shapes:
            raise SystemExit(f"unknown shape {label!r}")
        _run_shapes(shapes, on_tpu, dev)
        return

    if not on_tpu:
        print("# WARNING: no TPU — interpret-mode pallas numbers are meaningless; "
              "running tiny-shape smoke only", file=sys.stderr)
        _run_shapes([("cpu_smoke", 1, 256, 2, 64)], on_tpu, dev)
        return

    # Parent mode: one bounded subprocess per shape, so a wedged pallas cell
    # (round-3 lesson: flux_16 hung 30 min inside one pallas forward through
    # the tunnel) costs one shape's timeout, not the whole sweep.
    for label, *_ in SHAPES:
        try:
            subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--shape", label],
                cwd=_REPO, timeout=1800,
            )
        except subprocess.TimeoutExpired:
            print(f"# shape {label} timed out (wedged tunnel?) — skipping",
                  file=sys.stderr)

    if "--apply" in sys.argv:
        entries = _entries_from_file()
        if not entries:
            print("# --apply skipped: no TPU measurements", file=sys.stderr)
            return
        from comfyui_parallelanything_tpu.ops.pallas.tuning import write_tuning

        # Per-shape winners live in `entries` (best_blocks picks the nearest);
        # the table-level block fields stay the neutral 256/256 default — a
        # cross-shape "fastest absolute ms" would just crown the cheapest shape.
        path = write_tuning({
            "device_kind": dev.device_kind,
            "entries": entries,
        })
        print(f"# tuning table written: {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
