"""Flash-attention kernel benchmark: pallas (streamed K/V) vs plain XLA.

Run on a live TPU (the tunnel comes and goes — probe first):

    python scripts/bench_kernels.py

Shapes cover the rungs that matter: FLUX joint attention at 1024² (4.6k tokens,
24 heads × 128) and WAN-video lengths (16k/32k tokens) where the streamed-K/V
layout is what keeps VMEM bounded. Each row reports ms/call (median of 5 after
warmup) and the speedup of the pallas path over XLA. Appends JSON lines to
KERNEL_BENCH.json; BASELINE.md's kernel section reads from there.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# (label, batch, seq, heads, head_dim)
SHAPES = [
    ("flux_1024_joint", 1, 4608, 24, 128),
    ("flux_b4", 4, 4608, 24, 128),
    ("wan_480p_16f", 1, 16384, 12, 128),
    ("wan_long_32k", 1, 32768, 12, 128),
]


def _time_fn(fn, *args, iters=5):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.utils import enable_compilation_cache

    enable_compilation_cache()

    from comfyui_parallelanything_tpu.devices.discovery import is_tpu_device
    from comfyui_parallelanything_tpu.ops.attention import _xla_attention
    from comfyui_parallelanything_tpu.ops.pallas.flash_attention import (
        flash_attention,
    )

    dev = jax.devices()[0]
    on_tpu = is_tpu_device(dev)
    if not on_tpu:
        print("# WARNING: no TPU — interpret-mode pallas numbers are meaningless; "
              "running tiny-shape smoke only", file=sys.stderr)

    out_path = os.path.join(_REPO, "KERNEL_BENCH.json")
    shapes = SHAPES if on_tpu else [("cpu_smoke", 1, 256, 2, 64)]
    for label, b, s, h, d in shapes:
        k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(k1, (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(k2, (b, s, h, d), jnp.bfloat16)
        v = jax.random.normal(k3, (b, s, h, d), jnp.bfloat16)
        rec = {"shape": label, "b": b, "seq": s, "heads": h, "head_dim": d,
               "platform": dev.platform, "device_kind": dev.device_kind,
               "ts": time.time()}
        try:
            rec["pallas_ms"] = round(
                _time_fn(lambda a, b_, c: flash_attention(a, b_, c), q, k, v) * 1e3, 3
            )
        except Exception as e:  # noqa: BLE001 — record, keep measuring
            rec["pallas_error"] = str(e)[:200]
        try:
            rec["xla_ms"] = round(
                _time_fn(lambda a, b_, c: _xla_attention(a, b_, c, d**-0.5),
                         q, k, v) * 1e3, 3
            )
        except Exception as e:  # noqa: BLE001 — S×S logits OOM at video lengths
            rec["xla_error"] = str(e)[:200]
        if "pallas_ms" in rec and "xla_ms" in rec:
            rec["pallas_speedup"] = round(rec["xla_ms"] / rec["pallas_ms"], 2)
        print(json.dumps(rec))
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
