"""Auto-parallel plan report and gate (stdlib-only, jax-free).

The planner (``comfyui_parallelanything_tpu/parallel/planner.py``) records
every routing decision it takes; bench.py and the dryrun append the
measured ones as ``kind="plan"`` perf-ledger records carrying the chosen
plan, the shadow hand-rule plan it was scored against, the per-candidate
table, and — when a measurement followed — predicted-vs-actual. This
script is the offline consumer, the same audit/gate shape as
scripts/perf_ledger.py / numerics_audit.py / roofline_report.py:

- default      one line per (rung, platform) group: chosen vs hand plan,
               predicted scores, divergence, and the measured ratio.
- ``--check``  the PLAN GATE (wired into scripts/ci_tier1.sh after the
               roofline gate): for the latest plan record per group,
               the chosen plan must MATCH-OR-BEAT the shadow hand rules
               by predicted score (``plan_predicted_s <=
               plan_hand_predicted_s`` — the planner must never pick a
               plan its own model says is worse than the ladder it
               replaced), and when an actual was measured the
               predicted-vs-actual ratio must sit in the same (0, 1.2]
               calibration band the roofline gate holds rung predictions
               to. A plan-free ledger is SKIP, never a failure.

Stays jax-free: reads only the ledger JSONL (``PA_LEDGER_DIR`` redirects,
the perf-ledger rule), so it runs over a wedged tunnel.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LEDGER_SCHEMA = "pa-perf-ledger/v1"
# The roofline gate's sane band, shared verbatim: a plan prediction more
# than 1.2x the measured step means the planner's cost model (or its
# calibration) is lying about the plans it ranks.
RATIO_BAND = (0.0, 1.2)


def ledger_path() -> str:
    ledger_dir = os.environ.get("PA_LEDGER_DIR")
    if not ledger_dir:
        evidence = os.environ.get("PA_EVIDENCE_DIR")
        ledger_dir = (
            os.path.join(evidence, "ledger") if evidence
            else os.path.join(_REPO, "ledger")
        )
    return os.path.join(ledger_dir, "perf_ledger.jsonl")


def load_records(path: str | None = None) -> list[dict]:
    path = path or ledger_path()
    out: list[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _is_plan(rec: dict) -> bool:
    return (
        rec.get("schema") == LEDGER_SCHEMA
        and rec.get("kind") == "plan"
        and not rec.get("stale")
        and not rec.get("invalid")
        and isinstance(rec.get("plan_predicted_s"), (int, float))
        and isinstance(rec.get("plan_hand_predicted_s"), (int, float))
    )


def _group(rec: dict) -> str:
    return f"{rec.get('rung') or '?'}/{rec.get('platform') or '?'}"


def latest_per_group(records: list[dict]) -> dict[str, dict]:
    groups: dict[str, dict] = {}
    for rec in records:
        if _is_plan(rec):
            groups[_group(rec)] = rec  # latest wins (file order)
    return groups


def _fmt_plan(rec: dict) -> str:
    mode = rec.get("plan_mode")
    bits = [str(mode)]
    if mode in ("replicate", "tp", "fsdp"):
        bits.append(f"dp={rec.get('plan_dp')}x tp={rec.get('plan_tp')}")
    if rec.get("plan_stages"):
        bits.append(f"{rec.get('plan_stages')} stage(s)")
    return " ".join(bits)


def report(records: list[dict]) -> int:
    groups = latest_per_group(records)
    if not groups:
        print("plan_report: no kind=plan records in the ledger")
        return 0
    for key in sorted(groups):
        rec = groups[key]
        ratio = rec.get("plan_ratio")
        print(
            f"{key:28s} chosen {_fmt_plan(rec):26s} "
            f"predicted {rec.get('plan_predicted_s'):.4g}s vs hand "
            f"{rec.get('plan_hand_mode')} "
            f"{rec.get('plan_hand_predicted_s'):.4g}s  "
            f"divergent={bool(rec.get('plan_divergent'))}  "
            f"actual={rec.get('plan_actual_s') or '-'}  "
            f"ratio={ratio if ratio is not None else '-'}"
            f"{'  [dryrun]' if rec.get('dryrun') else ''}"
        )
    return 0


def check(records: list[dict]) -> int:
    """The gate: latest plan record per (rung, platform) group must
    match-or-beat the shadow hand rules and keep predicted-vs-actual in
    the calibration band."""
    groups = latest_per_group(records)
    if not groups:
        print("plan_report: no kind=plan records in the ledger — SKIP "
              "(nothing to gate)")
        return 0
    problems: list[str] = []
    for key in sorted(groups):
        rec = groups[key]
        chosen = float(rec["plan_predicted_s"])
        hand = float(rec["plan_hand_predicted_s"])
        if chosen > hand * (1 + 1e-9):
            problems.append(
                f"{key}: chosen plan predicts {chosen:.6g}s, WORSE than the "
                f"shadow hand rules' {hand:.6g}s — the planner must "
                "match-or-beat the ladder it replaced"
            )
        actual = rec.get("plan_actual_s")
        if isinstance(actual, (int, float)) and actual > 0:
            ratio = chosen / float(actual)
            lo, hi = RATIO_BAND
            if not lo < ratio <= hi:
                problems.append(
                    f"{key}: predicted-vs-actual ratio {ratio:.4g} outside "
                    f"({lo}, {hi}] (predicted {chosen:.6g}s vs measured "
                    f"{actual:.6g}s) — the plan cost model is lying"
                )
    if problems:
        print("plan_report --check: FAIL")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(
        f"plan_report --check: OK — {len(groups)} plan group(s), every "
        "chosen plan matches-or-beats the hand rules"
        + (", ratios in band" if any(
            g.get("plan_actual_s") for g in groups.values()) else "")
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="gate mode (ci_tier1.sh): nonzero exit on a plan "
                         "that loses to the hand rules or an out-of-band "
                         "predicted-vs-actual ratio")
    ap.add_argument("--ledger", default=None,
                    help="explicit perf_ledger.jsonl path")
    args = ap.parse_args()
    records = load_records(args.ledger)
    return check(records) if args.check else report(records)


if __name__ == "__main__":
    sys.exit(main())
