"""Anomaly attribution gate: every sentinel firing must have a declared cause.

The online anomaly sentinel (``utils/anomaly.py``) appends a
``kind="anomaly"`` record to the perf ledger for every firing — signal,
observed vs baseline, z-score, and ``attributed_to`` (the fault sites and
load phase overlapping the firing window). This script is the audit over
those records, the same shape as ``scripts/numerics_audit.py`` over
fingerprints:

- default      one line per firing (signal, observed/baseline, cause)
- ``--check``  the ATTRIBUTION GATE: exit 1 if any firing has
               ``attributed == False`` — an anomaly nobody declared a
               fault plan or load phase for is either a real regression
               or a broken detector, and both block. Ledgers with no
               anomaly records at all are SKIP, never failed (a fresh
               checkout — and any clean run — must pass CI).

Stays jax-free (imports bench.py, whose module level is stdlib-only) so it
runs over a wedged tunnel or on a laptop holding just the ledger.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

LEDGER_SCHEMA = "pa-perf-ledger/v1"


def _load_jsonl(path: str) -> list[dict]:
    out: list[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def anomaly_records(records: list[dict]) -> list[dict]:
    return [r for r in records
            if r.get("kind") == "anomaly"
            and r.get("schema") == LEDGER_SCHEMA]


def _cause(rec: dict) -> str:
    at = rec.get("attributed_to") or {}
    parts = []
    if at.get("faults"):
        parts.append("faults=" + ",".join(at["faults"]))
    if at.get("phase"):
        parts.append(f"phase={at['phase']}")
    return " ".join(parts) or "UNATTRIBUTED"


def summarize(records: list[dict]) -> None:
    events = anomaly_records(records)
    if not events:
        print("anomaly_report: no anomaly records in the ledger")
        return
    print(f"{len(events)} anomaly firing(s):")
    for rec in events:
        print(f"  {rec.get('signal')}: observed {rec.get('observed')} "
              f"vs baseline {rec.get('baseline')} (z={rec.get('z')}) "
              f"on {rec.get('host') or '?'} — {_cause(rec)}"
              + (f" [postmortem {rec['postmortem']}]"
                 if rec.get("postmortem") else ""))


def check(records: list[dict]) -> int:
    events = anomaly_records(records)
    if not events:
        print("anomaly_report: SKIP — no anomaly records in the ledger "
              "(clean run or sentinel off)")
        return 0
    bad = [r for r in events if not r.get("attributed")]
    for rec in events:
        status = "FAIL " if not rec.get("attributed") else "ok   "
        print(f"{status}{rec.get('signal')}: observed {rec.get('observed')} "
              f"vs baseline {rec.get('baseline')} — {_cause(rec)}")
    if bad:
        print(f"anomaly_report: FAILED — {len(bad)}/{len(events)} "
              f"firing(s) with no declared fault/phase cause")
        return 1
    print(f"anomaly_report: ok — {len(events)} firing(s), all attributed")
    return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=None,
                    help="ledger file or directory (default: $PA_LEDGER_DIR "
                         "or <evidence dir>/ledger)")
    ap.add_argument("--check", action="store_true",
                    help="run the attribution gate (exit 1 on any "
                         "unattributed firing)")
    args = ap.parse_args()

    from bench import evidence_dir

    ledger = (args.ledger or os.environ.get("PA_LEDGER_DIR")
              or os.path.join(evidence_dir(), "ledger"))
    if not ledger.endswith(".jsonl"):
        ledger = os.path.join(ledger, "perf_ledger.jsonl")
    records = _load_jsonl(ledger)
    if args.check:
        sys.exit(check(records))
    summarize(records)


if __name__ == "__main__":
    main()
