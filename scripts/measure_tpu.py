"""Capture the full BENCH ladder on the real TPU and record it.

Run whenever the axon tunnel is up (it comes and goes — probe first):

    python scripts/measure_tpu.py [rung ...]

For each rung (default: the TPU ladder in ascending cost) this runs
``bench.py`` in a subprocess with ``BENCH_CONFIG`` set, inheriting the tunnel
env. bench.py itself probes availability and falls back honestly, so a tunnel
flap mid-ladder yields a ``platform: "cpu"`` line which is recorded but NOT
written into the measured table. Results append to ``BASELINE_measured.json``
(one JSON object per run, keyed by rung + timestamp) and the human-readable
Measured table in ``BASELINE.md`` is left for a manual/agent pass — raw
evidence first, prose second.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Ascending cost so a mid-ladder tunnel flap still banks the cheap rungs.
LADDER = (
    "smoke", "sd15_16", "sdxl_8", "hybrid_sd15", "zimage_21", "flux_16",
    "flux_16_int8", "flux_stream", "wan_video",
)


if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def run_rung(rung: str, timeout: int = 3200, extra_env: dict | None = None) -> dict:
    # timeout covers bench.py's own worst case: ≤240s TPU probe + 1800s inner
    # child + 900s CPU fallback; anything tighter kills the honest fallback
    # line mid-write and records a bare error instead.
    # The guarded metric-line scan and the platform tuple both live in
    # bench.py — one implementation, no drift.
    from bench import _TPU_PLATFORMS, _last_json_line

    env = dict(os.environ)
    env["BENCH_CONFIG"] = rung
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py")],
            env=env, cwd=_REPO, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"rung": rung, "error": f"timed out after {timeout}s"}
    line = _last_json_line(proc.stdout)
    if line is not None:
        rec = json.loads(line)
        rec["rung"] = rung
        if (
            rec.get("platform") not in _TPU_PLATFORMS or rec.get("stale")
        ) and rung != "smoke":
            # A CPU-fallback line on a TPU-sized rung means the TPU child died
            # (smoke is CPU by definition) — keep its traceback
            # (bench.py forwards the inner stderr tail) or the whole window's
            # diagnosis is lost the moment the fallback line parses. Head+tail
            # slice: XLA OOMs put the exception line BEFORE a multi-kB
            # per-buffer dump, so a tail alone keeps only dump noise.
            err = proc.stderr.strip()
            rec["fallback_stderr"] = (
                err if len(err) <= 2400 else err[:1600] + "\n...[snip]...\n" + err[-800:]
            )
        return rec
    return {"rung": rung, "error": proc.stderr.strip()[-300:]}


def record_result(rec: dict) -> dict:
    """Stamp and append one rung result to ``BASELINE_measured.json`` — the one
    writer for the evidence file (measure_tpu CLI and tpu_watchdog both go
    through here so the record format cannot drift). Stale lines (bench.py
    re-emitting ALREADY-banked evidence after a failed fresh attempt) flow
    back to the caller unwritten: re-appending them would duplicate the
    original record under a fresh timestamp and corrupt every
    most-recent-banked query."""
    from bench import evidence_dir

    rec["ts"] = time.time()
    if rec.get("stale"):
        return rec
    with open(os.path.join(evidence_dir(), "BASELINE_measured.json"), "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def main() -> None:
    from bench import _TPU_PLATFORMS

    rungs = sys.argv[1:] or list(LADDER)
    results = []
    for rung in rungs:
        rec = record_result(run_rung(rung))
        results.append(rec)
        print(json.dumps(rec))
        if rec.get("platform") not in _TPU_PLATFORMS and "error" not in rec:
            print(f"# {rung}: fell back to {rec.get('platform')} — tunnel down? "
                  "continuing (later rungs may recover)", file=sys.stderr)
    tpu_rungs = [r for r in results if r.get("platform") in _TPU_PLATFORMS]
    print(f"# captured {len(tpu_rungs)}/{len(rungs)} rungs on TPU", file=sys.stderr)


if __name__ == "__main__":
    main()
