"""palint CLI — repo-native static analysis + lock-order discipline gate.

Thin entry point over the ``scripts/palint/`` pass package (engine and
passes are documented there). Stdlib-only and jax-free by the standalone
contract it enforces: this runs over a wedged TPU tunnel, in CI before the
38-minute suite (``scripts/ci_tier1.sh`` fast-fail), and on a laptop
holding just the checkout.

Usage:
    python scripts/palint.py              # findings + ledger/palint.json
    python scripts/palint.py --check     # exit 1 on any finding (CI gate)
    python scripts/palint.py --json      # machine-readable report
    python scripts/palint.py --env-table # regenerate the README PA_* table

Passes: standalone-contract, host-sync, recompile-hazard,
registry-consistency, lock-discipline, observability. Per-line pragmas:
``# palint: allow[<pass>] <justification>`` (stale or unjustified pragmas
are themselves findings). The runtime companion is ``utils/lockcheck.py``
(``PA_LOCKCHECK=1`` lock-acquisition-order graph).
"""

from __future__ import annotations

import importlib.util
import os
import sys


def _load_engine():
    """Load scripts/palint/__init__.py as a proper package by path — the
    scripts directory is not a package, and sys.path tricks would race the
    module/package name collision (palint.py vs palint/)."""
    here = os.path.dirname(os.path.abspath(__file__))
    pkg_dir = os.path.join(here, "palint")
    spec = importlib.util.spec_from_file_location(
        "pa_palint", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["pa_palint"] = mod
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    sys.exit(_load_engine().main())
