"""Closed-loop load generator for the workflow server (stdlib-only).

Drives N concurrent clients against a running server: each client POSTs its
prompt graph, blocks until the prompt completes (polling ``/history/{id}``),
and immediately submits the next — the closed loop that makes offered load
equal to in-flight concurrency, which is the regime continuous batching
(serving/) is built for. Prints ONE JSON summary line: latency percentiles,
throughput, HTTP 429 rejections, the serving dispatch/occupancy counters,
AND server-side p50/p95 read from the ``GET /metrics`` histograms
(``server_step_*``/``server_lane_wait_*`` — what the server measured per
lockstep dispatch / lane admission, vs the client clocks which fold in
queueing + HTTP + polling) — so a run shows not just *how fast* but *how
batched* and *where the time went* (BASELINE.md "serving" metric).

The ONE summary line goes to **stdout** (ledger-appendable, `| jq`-able —
the same one-JSON-line contract bench.py keeps); the human-readable table
goes to **stderr**, so piping a fleet run into the ledger never has to strip
prose.

Usage:
    python scripts/loadgen.py --graph workflow.json \
        [--base http://127.0.0.1:8188] [--clients 4] [--requests 2] \
        [--timeout 300] [--seed-key 3:inputs:seed] [--seed 7] \
        [--hosts http://h1:8188,http://h2:8188]

``--seed-key`` (node:path:to:field) makes every submission unique by writing
the request counter into that graph field — defeating the workflow cache so
each prompt actually samples (the default for KSampler graphs: vary the
seed). ``--seed N`` makes that schedule REPRODUCIBLE: the written values
come from a seeded RNG instead of the live counter, so two runs with the
same seed submit the identical prompt set.

``--hosts`` (comma list of backend base URLs) turns on FLEET mode: ``--base``
points at a fleet router (fleet/router.py) and the summary adds per-host
sections — client-side p50/p95 grouped by the serving host (the router
stamps ``status.fleet.host_id`` on every entry), per-backend dispatch/
lane-step deltas scraped from each host's /metrics — plus the router's own
``pa_fleet_*`` deltas (dispatches, spills, failovers) and ``prompts_lost``
(router-lost + client-timeout), the number the fleet CI smoke gates on
staying zero.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re
import sys
import threading
import time
import urllib.error
import urllib.request


def _append_ledger(summary: dict, base: str) -> None:
    """Perf-ledger append (kind=loadgen) via bench.py's stdlib-only twin of
    ``utils/telemetry.append_ledger_record`` — loadgen must stay jax-free by
    contract, so it cannot import the package, but bench's module level is
    stdlib-only (scripts/perf_ledger.py imports it the same way). One copy
    of the dir-resolution/schema stamp, not three. Best-effort by that
    helper's contract: a read-only checkout must not fail the load run it
    summarizes."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from bench import _ledger_append

    _ledger_append({**summary, "base": base}, "loadgen")


def _load_retry():
    """utils/retry.py loaded standalone by file path — its module level is
    stdlib-only and free of package-relative imports by contract (the
    utils/roofline.py loader pattern), so loadgen's poll/reconnect loops ride
    the SAME policy object the fleet uses, without importing the package."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "comfyui_parallelanything_tpu", "utils", "retry.py",
    )
    spec = importlib.util.spec_from_file_location("pa_retry_loadgen", path)
    mod = importlib.util.module_from_spec(spec)
    # Registered BEFORE exec: dataclass processing under `from __future__
    # import annotations` resolves the module through sys.modules.
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_retry = _load_retry()
# History polling: the SHARED poll shape (retry.POLL — 50 ms cadence backing
# off toward 500 ms) — a long denoise no longer costs 20 HTTP polls per
# second per client, the jitter de-synchronizes N clients' polls, and a
# future tuning of the fleet's poll policy applies here automatically.
_POLL = _retry.POLL


class _Front:
    """The client's view of the front door: an ordered list of router bases
    (primary first, standbys after). A connection failure or a standby 503
    advances to the next base — the router-HA story from the CLIENT side:
    a router kill mid-run costs a reconnect, never the prompt."""

    def __init__(self, bases):
        self.bases = [b.rstrip("/") for b in bases]
        self._i = 0
        self._lock = threading.Lock()

    @property
    def base(self) -> str:
        with self._lock:
            return self.bases[self._i]

    def _advance(self, frm: str) -> None:
        with self._lock:
            if self.bases[self._i] == frm and len(self.bases) > 1:
                self._i = (self._i + 1) % len(self.bases)

    def request(self, method, path, payload=None, timeout: float = 30):
        """One HTTP call with base failover: OSError / standby-503 walks the
        base list (once around); anything else propagates."""
        last = None
        for _ in range(max(1, len(self.bases))):
            base = self.base
            try:
                if method == "GET":
                    with urllib.request.urlopen(
                        base + path, timeout=timeout
                    ) as r:
                        body = r.read()
                    ct = r.headers.get("Content-Type", "")
                    return json.loads(body) if "json" in ct else body.decode()
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    try:
                        detail = json.loads(e.read() or b"{}")
                    except ValueError:
                        detail = {}
                    if detail.get("role") == "standby":
                        last = e
                        self._advance(base)
                        continue
                raise
            except OSError as e:
                last = e
                self._advance(base)
                continue
        raise last if last is not None else OSError("no base reachable")


def _get(base: str, path: str, timeout: float = 30):
    if isinstance(base, _Front):
        return base.request("GET", path, timeout=timeout)
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        body = r.read()
    ct = r.headers.get("Content-Type", "")
    return json.loads(body) if "json" in ct else body.decode()


def _post(base: str, path: str, payload: dict, timeout: float = 30):
    if isinstance(base, _Front):
        return base.request("POST", path, payload, timeout=timeout)
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _wait_done(base, pid: str, timeout: float):
    t0 = time.time()
    attempt = 0
    while time.time() - t0 < timeout:
        try:
            hist = _get(base, f"/history/{pid}")
        except (urllib.error.URLError, OSError):
            # The front door may be mid-failover (router kill → standby
            # takeover): keep polling on the policy's backoff — the prompt
            # survives in the journal even while no router answers.
            hist = {}
        if pid in hist:
            return hist[pid]
        time.sleep(_POLL.backoff_s(attempt, key=pid))
        attempt += 1
    raise TimeoutError(f"prompt {pid} never completed")


def _set_path(graph: dict, dotted: str, value):
    """Write ``value`` at ``node:inputs:field`` (colon-separated path)."""
    parts = dotted.split(":")
    node = graph
    for p in parts[:-1]:
        node = node[p]
    node[parts[-1]] = value


def _histogram_quantile(text: str, name: str, q: float) -> float | None:
    """Quantile from a Prometheus histogram's ``_bucket`` exposition, merged
    across label sets (every MetricsRegistry histogram shares one fixed
    bucket ladder, so cumulative counts add per ``le``). Linear interpolation
    within the target bucket — the same estimate the server's in-process
    ``registry.quantile`` computes; this is the scraped twin, so a loadgen
    run reads *server-side* p50/p95 instead of only its own client clocks."""
    by_le: dict[str, float] = {}
    for m in re.finditer(
        rf'^{name}_bucket\{{[^}}]*le="([^"]+)"[^}}]*\}} ([0-9.eE+-]+)$',
        text, re.M,
    ):
        by_le[m.group(1)] = by_le.get(m.group(1), 0.0) + float(m.group(2))
    if not by_le:
        return None
    finite = sorted(
        (float(le), c) for le, c in by_le.items() if le != "+Inf"
    )
    total = by_le.get("+Inf", finite[-1][1] if finite else 0.0)
    if total <= 0:
        return None
    target = q / 100.0 * total
    lo = 0.0
    prev_cum = 0.0
    for le, cum in finite:
        if cum >= target and cum > prev_cum:
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo + (le - lo) * min(1.0, max(0.0, frac))
        lo, prev_cum = le, cum
    return lo  # +Inf bucket: clamp to the last finite bound


def _serving_counters(base: str) -> dict:
    """Scrape the serving counters from the Prometheus text endpoint."""
    try:
        text = _get(base, "/metrics")
    except (urllib.error.URLError, OSError):
        return {}
    out: dict[str, float] = {}
    for metric, key in (("pa_serving_step_seconds", "step"),
                        ("pa_serving_lane_wait_seconds", "lane_wait")):
        for q in (50, 95):
            v = _histogram_quantile(text, metric, q)
            if v is not None:
                out[f"{key}_p{q}_s"] = round(v, 6)
    for name in ("pa_serving_dispatch_total", "pa_serving_completed_total",
                 "pa_serving_cancelled_total", "pa_serving_rejected_total",
                 "pa_serving_lane_steps_total",
                 # Numerics sentinel (utils/numerics.py): non-finite
                 # observations and quarantined lanes (summed over labels),
                 # plus the enabled gauge (published at scrape time) that
                 # tells a clean 0 apart from an unwatched run.
                 "pa_numerics_nonfinite_total",
                 "pa_numerics_quarantined_total",
                 "pa_numerics_sentinel_enabled",
                 # Chaos tier (round 14): injected-fault and
                 # degradation-ladder counters (utils/faults.py,
                 # utils/degrade.py) — a chaos run's summary proves what was
                 # injected and what gracefully degraded, summed over their
                 # {site=}/{rung=} labels.
                 "pa_fault_injected_total", "pa_degradation_total",
                 # Fleet router counters (fleet/router.py) — present when
                 # --base is a router; summed over their {host=} labels.
                 "pa_fleet_dispatch_total", "pa_fleet_spill_total",
                 "pa_fleet_failover_total", "pa_fleet_completed_total",
                 "pa_fleet_prompts_lost_total"):
        total = 0.0
        found = False
        for m in re.finditer(rf"^{name}(?:\{{[^}}]*\}})? ([0-9.eE+-]+)$",
                             text, re.M):
            total += float(m.group(1))
            found = True
        if found:
            out[name] = total
    m = re.search(r"^pa_serving_batched_fraction ([0-9.eE+-]+)$", text, re.M)
    if m:
        out["pa_serving_batched_fraction"] = float(m.group(1))
    # Roofline attribution fractions (utils/roofline.py, published at scrape
    # time when the server traces): where the non-compute time goes —
    # comms (fleet hops) and host-gap alongside compute/exposed-transfer.
    for name in ("pa_roofline_compute_fraction",
                 "pa_roofline_exposed_transfer_fraction",
                 "pa_roofline_comms_fraction",
                 "pa_roofline_host_gap_fraction"):
        m = re.search(rf"^{name} ([0-9.eE+-]+)$", text, re.M)
        if m:
            out[name] = float(m.group(1))
    return out


def percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile (no numpy — stdlib-only by contract)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = max(0, min(len(s) - 1, round(q / 100.0 * (len(s) - 1))))
    return s[k]


def _host_probe(hosts: list[str]) -> dict:
    """One scrape per backend: its health identity + serving counters —
    the before/after pair fleet mode diffs for per-host dispatch deltas."""
    out: dict[str, dict] = {}
    for h in hosts:
        h = h.rstrip("/")
        probe: dict = {"base": h}
        try:
            health = _get(h, "/health", timeout=10)
            probe["host_id"] = health.get("host_id")
            probe["accepting"] = health.get("accepting")
            probe["inflight_prompts"] = health.get("inflight_prompts")
        except (urllib.error.URLError, OSError, ValueError):
            probe["host_id"] = None
        probe["counters"] = _serving_counters(h)
        out[h] = probe
    return out


def run_load(base: str, graph: dict, *, clients: int, requests: int,
             timeout: float, seed_key: str | None = None,
             extra_data: dict | None = None,
             samplers: list[str] | None = None,
             sampler_key: str | None = None,
             seed: int | None = None,
             hosts: list[str] | None = None,
             fallback_bases: list[str] | None = None) -> dict:
    """The closed loop; returns the summary dict (importable — the e2e and
    fleet-smoke tests drive in-process servers through this exact code path).

    ``samplers`` + ``sampler_key`` make the workload MIXED: prompt n runs
    ``samplers[n % len]`` (round-robin, written into the graph at
    ``sampler_key``) — the traffic shape the stateful-lane scheduler
    co-batches into one dispatch stream, whose amortization the summary
    reports (shared-dispatch counters scraped from /metrics).

    ``seed`` makes the prompt schedule reproducible: the per-prompt value
    written at ``seed_key`` comes from ``random.Random(seed)`` instead of
    the live counter. ``hosts`` turns on fleet mode (see module docstring).
    ``fallback_bases`` (router HA): standby router URLs tried in order when
    the primary stops answering or replies standby-503 — a router kill
    mid-run costs the clients a reconnect, never a prompt."""
    if fallback_bases:
        base = _Front([base, *fallback_bases])
    latencies: list[float] = []
    lat_by_host: dict = {}
    failures: list[str] = []
    rejected = [0]
    timeouts = [0]
    lock = threading.Lock()
    counter = [0]
    # Reproducible schedule: value n is a pure function of (seed, n), so two
    # runs with one seed submit the identical prompt set regardless of how
    # the client threads interleave.
    schedule = None
    if seed is not None:
        rng = random.Random(seed)
        schedule = [rng.randrange(1 << 31) for _ in range(clients * requests)]
    before = _serving_counters(base)
    hosts_before = _host_probe(hosts) if hosts else None
    t_start = time.time()

    def client(ci: int) -> None:
        for _ in range(requests):
            g = json.loads(json.dumps(graph))
            with lock:
                counter[0] += 1
                n = counter[0]
            if seed_key:
                _set_path(g, seed_key,
                          schedule[n - 1] if schedule is not None else n)
            if samplers and sampler_key:
                _set_path(g, sampler_key, samplers[n % len(samplers)])
            payload = {"prompt": g}
            if extra_data:
                payload["extra_data"] = extra_data
            t0 = time.time()
            # Submit with bounded retry (utils/retry.py shape): a 503 or a
            # refused connection can be a router mid-failover (standby
            # takeover costs ~a lease TTL) — retry on backoff until the
            # window closes, then count the failure. 429 (bounded queue) and
            # 4xx (request at fault) are never retried.
            pid = None
            post_deadline = t0 + min(60.0, timeout)
            attempt = 0
            while True:
                try:
                    pid = _post(base, "/prompt", payload)["prompt_id"]
                    break
                except urllib.error.HTTPError as e:
                    if e.code == 503 and time.time() < post_deadline:
                        time.sleep(_POLL.backoff_s(attempt, key=f"s{ci}"))
                        attempt += 1
                        continue
                    with lock:
                        if e.code == 429:
                            rejected[0] += 1
                        else:
                            failures.append(f"client {ci}: HTTP {e.code}")
                    break
                except OSError as e:
                    if time.time() < post_deadline:
                        time.sleep(_POLL.backoff_s(attempt, key=f"s{ci}"))
                        attempt += 1
                        continue
                    with lock:
                        failures.append(f"client {ci}: unreachable ({e})")
                    break
            if pid is None:
                continue
            try:
                entry = _wait_done(base, pid, timeout)
            except TimeoutError:
                # A prompt that never completes is LOST from the client's
                # view — it must count (the fleet gate), not silently kill
                # this client thread.
                with lock:
                    timeouts[0] += 1
                    failures.append(f"client {ci}: timeout ({pid})")
                continue
            dt = time.time() - t0
            status = entry.get("status") or {}
            served_by = (status.get("fleet") or {}).get("host_id") \
                or status.get("host_id")
            with lock:
                if status.get("status_str") == "success":
                    latencies.append(dt)
                    if served_by:
                        lat_by_host.setdefault(served_by, []).append(dt)
                else:
                    failures.append(
                        f"client {ci}: {status.get('status_str')}"
                    )

    threads = [threading.Thread(target=client, args=(i,)) for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t_start
    after = _serving_counters(base)
    dispatches = (
        after.get("pa_serving_dispatch_total", 0.0)
        - before.get("pa_serving_dispatch_total", 0.0)
    ) if after else None
    lane_steps = (
        after.get("pa_serving_lane_steps_total", 0.0)
        - before.get("pa_serving_lane_steps_total", 0.0)
    ) if after else None
    fleet = None
    per_host = None
    prompts_lost = None
    if hosts:
        hosts_after = _host_probe(hosts)
        per_host = {}
        for h in hosts:
            h = h.rstrip("/")
            b, a = hosts_before.get(h, {}), hosts_after.get(h, {})
            hid = a.get("host_id") or b.get("host_id") or h
            cb, ca = b.get("counters") or {}, a.get("counters") or {}
            lats = lat_by_host.get(hid, [])
            per_host[hid] = {
                "base": h,
                "completed": len(lats),
                "latency_p50_s": round(percentile(lats, 50), 3),
                "latency_p95_s": round(percentile(lats, 95), 3),
                "dispatches": (
                    ca.get("pa_serving_dispatch_total", 0.0)
                    - cb.get("pa_serving_dispatch_total", 0.0)
                ) if ca else None,
                "lane_steps": (
                    ca.get("pa_serving_lane_steps_total", 0.0)
                    - cb.get("pa_serving_lane_steps_total", 0.0)
                ) if ca else None,
                "server_step_p50_s": ca.get("step_p50_s"),
                "server_step_p95_s": ca.get("step_p95_s"),
                "accepting": a.get("accepting"),
                "reachable": a.get("host_id") is not None,
            }
        # Router-side deltas (--base is the fleet front door). A router-lost
        # prompt and a client-timeout are the same failure seen from two
        # ends; the gate number is their sum.
        def _delta(name):
            return (after.get(name, 0.0) - before.get(name, 0.0)
                    if name in after or name in before else None)

        fleet = {
            "dispatches": _delta("pa_fleet_dispatch_total"),
            "spills": _delta("pa_fleet_spill_total"),
            "failovers": _delta("pa_fleet_failover_total"),
            "completed": _delta("pa_fleet_completed_total"),
        }
        lost_router = _delta("pa_fleet_prompts_lost_total")
        prompts_lost = (lost_router or 0.0) + timeouts[0]
    elif timeouts[0]:
        prompts_lost = float(timeouts[0])
    return {
        "clients": clients,
        "requests": clients * requests,
        "seed": seed,
        "samplers": samplers or None,
        "completed": len(latencies),
        "failed": len(failures),
        "rejected_429": rejected[0],
        "wall_s": round(wall, 3),
        "throughput_rps": round(len(latencies) / wall, 3) if wall > 0 else None,
        "latency_p50_s": round(percentile(latencies, 50), 3),
        "latency_p95_s": round(percentile(latencies, 95), 3),
        "latency_max_s": round(max(latencies), 3) if latencies else 0.0,
        "serving_dispatches": dispatches,
        # Dispatch amortization: lane-steps served per compiled dispatch over
        # this run (1.0 = no sharing; N = every dispatch carried N lanes) —
        # the mixed-workload number the ROADMAP serving-on-hardware item banks.
        "serving_lane_steps": lane_steps,
        "dispatch_amortization": (
            round(lane_steps / dispatches, 3)
            if lane_steps and dispatches else None
        ),
        # End-state shared-dispatch fraction (process lifetime, not deltas —
        # the same gauge GET /health reports).
        "serving_batched_fraction": after.get("pa_serving_batched_fraction"),
        # Numerics sentinel deltas over this run (utils/numerics.py): lanes
        # quarantined by the non-finite watchdog and raw non-finite
        # observations. The counters only exist once an event fires, so an
        # absent counter with the sentinel ENABLED means a clean run (0) and
        # with the sentinel disabled means unwatched (None) — the gauge the
        # server publishes at scrape time disambiguates the two.
        "numerics_quarantined": (
            after.get("pa_numerics_quarantined_total", 0.0)
            - before.get("pa_numerics_quarantined_total", 0.0)
        ) if after.get("pa_numerics_sentinel_enabled") else None,
        "numerics_nonfinite": (
            after.get("pa_numerics_nonfinite_total", 0.0)
            - before.get("pa_numerics_nonfinite_total", 0.0)
        ) if after.get("pa_numerics_sentinel_enabled") else None,
        # Chaos tier (round 14): faults fired by the injection registry and
        # degradation-ladder rungs taken over this run (summed over
        # site/rung labels; None = the counters never existed — no plan
        # armed AND nothing degraded).
        "faults_injected": (
            after.get("pa_fault_injected_total", 0.0)
            - before.get("pa_fault_injected_total", 0.0)
        ) if ("pa_fault_injected_total" in after
              or "pa_fault_injected_total" in before) else None,
        "degradations": (
            after.get("pa_degradation_total", 0.0)
            - before.get("pa_degradation_total", 0.0)
        ) if ("pa_degradation_total" in after
              or "pa_degradation_total" in before) else None,
        # Server-side quantiles from the /metrics histograms (end-state
        # values — histograms are cumulative): what the SERVER measured per
        # lockstep dispatch / lane admission, vs the client-clock latencies
        # above which include queueing + HTTP + polling.
        "server_step_p50_s": after.get("step_p50_s"),
        "server_step_p95_s": after.get("step_p95_s"),
        "server_lane_wait_p95_s": after.get("lane_wait_p95_s"),
        # Roofline attribution fractions over the server's live trace window
        # (utils/roofline.py buckets, scraped from /metrics; None when the
        # server runs untraced): how much of the wall went to cross-host
        # comms and to host scheduling gaps rather than device compute.
        "roofline_comms_fraction": after.get("pa_roofline_comms_fraction"),
        "roofline_host_gap_fraction": after.get(
            "pa_roofline_host_gap_fraction"
        ),
        # Fleet mode (--hosts): per-host client latencies + dispatch deltas,
        # router-side placement/failover deltas, and the CI-gated loss count
        # (router-lost + client-timeout; None outside fleet mode unless a
        # timeout made the number real).
        "hosts": per_host,
        "fleet": fleet,
        "prompts_lost": prompts_lost,
        "timeouts": timeouts[0],
        "errors": failures[:5],
    }


def print_human_summary(summary: dict, stream=None) -> None:
    """The operator-facing table — stderr by contract, so stdout stays ONE
    JSON line (the same ledger-appendable discipline as bench.py)."""
    stream = stream if stream is not None else sys.stderr
    w = stream.write
    w("── loadgen summary ──────────────────────────────\n")
    w(f"  prompts   {summary['completed']}/{summary['requests']} ok"
      f"  ({summary['failed']} failed, {summary['rejected_429']} rejected,"
      f" {summary.get('timeouts', 0)} timed out)\n")
    w(f"  wall      {summary['wall_s']}s"
      f"  throughput {summary['throughput_rps']} rps\n")
    w(f"  latency   p50 {summary['latency_p50_s']}s"
      f"  p95 {summary['latency_p95_s']}s"
      f"  max {summary['latency_max_s']}s\n")
    if summary.get("dispatch_amortization") is not None:
        w(f"  serving   {summary['serving_dispatches']:.0f} dispatches,"
          f" {summary['serving_lane_steps']:.0f} lane-steps"
          f" ({summary['dispatch_amortization']}x amortized)\n")
    if summary.get("fleet"):
        f = summary["fleet"]
        w(f"  fleet     dispatches {f.get('dispatches')}"
          f"  spills {f.get('spills')}  failovers {f.get('failovers')}"
          f"  lost {summary.get('prompts_lost')}\n")
    if summary.get("faults_injected") is not None or \
            summary.get("degradations") is not None:
        w(f"  chaos     faults injected {summary.get('faults_injected')}"
          f"  degradation rungs {summary.get('degradations')}\n")
    if summary.get("roofline_comms_fraction") is not None or \
            summary.get("roofline_host_gap_fraction") is not None:
        w(f"  roofline  comms {summary.get('roofline_comms_fraction')}"
          f"  host-gap {summary.get('roofline_host_gap_fraction')}"
          f"  (fraction of traced wall)\n")
    for hid, h in (summary.get("hosts") or {}).items():
        w(f"  host {hid:<20} {h['completed']:>3} ok"
          f"  p50 {h['latency_p50_s']}s  p95 {h['latency_p95_s']}s"
          f"  dispatches {h['dispatches']}"
          f"{'' if h.get('reachable') else '  [UNREACHABLE]'}\n")
    for err in summary.get("errors") or []:
        w(f"  error     {err}\n")
    w("─────────────────────────────────────────────────\n")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base", default="http://127.0.0.1:8188")
    ap.add_argument("--graph", required=True,
                    help="workflow JSON file (ComfyUI /prompt API format)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=2,
                    help="prompts per client (closed loop)")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--seed-key", default=None,
                    help="colon path (node:inputs:seed) made unique per prompt")
    ap.add_argument("--samplers", default=None,
                    help="comma list (euler,heun,dpmpp_2m,...) assigned "
                         "round-robin per prompt — the mixed workload the "
                         "stateful-lane scheduler co-batches; requires "
                         "--sampler-key")
    ap.add_argument("--sampler-key", default=None,
                    help="colon path (node:inputs:sampler_name) the "
                         "round-robin sampler is written to")
    ap.add_argument("--priority", type=int, default=None)
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--seed", type=int, default=None,
                    help="seed the prompt schedule (the values written at "
                         "--seed-key) so a run is reproducible")
    ap.add_argument("--hosts", default=None,
                    help="comma list of backend base URLs: fleet mode — "
                         "--base is the router; summary adds per-host "
                         "latency/dispatch sections, pa_fleet_* deltas, "
                         "and the CI-gated prompts_lost count")
    ap.add_argument("--fallback-bases", default=None,
                    help="comma list of standby router base URLs (router "
                         "HA): clients fail over to them when --base stops "
                         "answering or replies standby-503")
    args = ap.parse_args()
    samplers = [s for s in (args.samplers or "").split(",") if s]
    if samplers and not args.sampler_key:
        ap.error("--samplers requires --sampler-key (where to write it)")
    hosts = [h for h in (args.hosts or "").split(",") if h]
    with open(args.graph) as f:
        graph = json.load(f)
    extra = {}
    if args.priority is not None:
        extra["priority"] = args.priority
    if args.deadline_s is not None:
        extra["deadline_s"] = args.deadline_s
    summary = run_load(
        args.base, graph, clients=args.clients, requests=args.requests,
        timeout=args.timeout, seed_key=args.seed_key,
        extra_data=extra or None,
        samplers=samplers or None, sampler_key=args.sampler_key,
        seed=args.seed, hosts=hosts or None,
        fallback_bases=[b for b in (args.fallback_bases or "").split(",")
                        if b] or None,
    )
    _append_ledger(summary, args.base)
    print_human_summary(summary)          # operator table → stderr
    print(json.dumps(summary))            # THE one JSON line → stdout


if __name__ == "__main__":
    main()
