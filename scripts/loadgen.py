"""Load generator for the workflow server (stdlib-only): closed- OR open-loop.

CLOSED loop (default): N concurrent clients, each POSTing its prompt graph,
blocking until the prompt completes (polling ``/history/{id}``), and
immediately submitting the next — offered load equals in-flight concurrency,
the regime continuous batching (serving/) is built for.

OPEN loop (``--openloop poisson|onoff|replay``, round 15): requests fire on
a seeded arrival schedule (fleet/twin.py's generator — the same one the
traffic twin replays) REGARDLESS of completions — the regime where queues
actually grow. One rung per ``--rps`` rate; the summary becomes a
latency-under-load curve (p50/p95/p99 vs offered RPS) plus the SLO stage
decomposition (admission / lane_wait / eval / decode scraped off
``pa_slo_stage_seconds``, the client-side ``collect`` residual, burn-rate
gauges, and — behind a router — ``GET /fleet/slo`` verdicts), appended to
the ledger as ``kind="openloop"`` — the record ``scripts/twin_report.py``
checks the twin's prediction against. Prints ONE JSON summary line: latency percentiles,
throughput, HTTP 429 rejections, the serving dispatch/occupancy counters,
AND server-side p50/p95 read from the ``GET /metrics`` histograms
(``server_step_*``/``server_lane_wait_*`` — what the server measured per
lockstep dispatch / lane admission, vs the client clocks which fold in
queueing + HTTP + polling) — so a run shows not just *how fast* but *how
batched* and *where the time went* (BASELINE.md "serving" metric).

The ONE summary line goes to **stdout** (ledger-appendable, `| jq`-able —
the same one-JSON-line contract bench.py keeps); the human-readable table
goes to **stderr**, so piping a fleet run into the ledger never has to strip
prose.

Usage:
    python scripts/loadgen.py --graph workflow.json \
        [--base http://127.0.0.1:8188] [--clients 4] [--requests 2] \
        [--timeout 300] [--seed-key 3:inputs:seed] [--seed 7] \
        [--hosts http://h1:8188,http://h2:8188]

``--seed-key`` (node:path:to:field) makes every submission unique by writing
the request counter into that graph field — defeating the workflow cache so
each prompt actually samples (the default for KSampler graphs: vary the
seed). ``--seed N`` makes that schedule REPRODUCIBLE: the written values
come from a seeded RNG instead of the live counter, so two runs with the
same seed submit the identical prompt set.

``--hosts`` (comma list of backend base URLs) turns on FLEET mode: ``--base``
points at a fleet router (fleet/router.py) and the summary adds per-host
sections — client-side p50/p95 grouped by the serving host (the router
stamps ``status.fleet.host_id`` on every entry), per-backend dispatch/
lane-step deltas scraped from each host's /metrics — plus the router's own
``pa_fleet_*`` deltas (dispatches, spills, failovers) and ``prompts_lost``
(router-lost + client-timeout), the number the fleet CI smoke gates on
staying zero.

Against a DISAGGREGATED fleet (backends launched with ``--role``,
fleet/roles.py) each per-host row carries its role, the summary adds a
``roles`` per-pool section (pool membership, served counts, worst p95) plus
the router's ``pa_role_dispatch_total{role=}`` stage-dispatch deltas, and
the closed-loop ledger record banks as ``kind="roles"`` — the record the
role-pool CI smoke gates.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import re
import sys
import threading
import time
import urllib.error
import urllib.request


def trace_sampled(n: int, fraction: float, seed: int | None = None) -> bool:
    """Seeded, PREFIX-STABLE trace-sampling decision for submission ``n``:
    whether prompt n is sampled depends only on (seed, n) — never on the
    total request count or thread interleaving — so growing a run keeps
    every earlier decision, and a re-run with one seed samples the identical
    prompt set (the reproducible-schedule discipline ``run_load`` already
    applies to seeds)."""
    if fraction <= 0:
        return False
    if fraction >= 1:
        return True
    h = hashlib.md5(
        f"pa-trace:{0 if seed is None else seed}:{n}".encode()
    ).hexdigest()
    return int(h[:8], 16) / float(0xFFFFFFFF) < fraction


def _append_ledger(summary: dict, base: str, kind: str = "loadgen") -> None:
    """Perf-ledger append (kind=loadgen, or kind=openloop for open-loop
    runs — the record the traffic twin replays) via bench.py's stdlib-only
    twin of ``utils/telemetry.append_ledger_record`` — loadgen must stay
    jax-free by contract, so it cannot import the package, but bench's
    module level is stdlib-only (scripts/perf_ledger.py imports it the same
    way). One copy of the dir-resolution/schema stamp, not three.
    Best-effort by that helper's contract: a read-only checkout must not
    fail the load run it summarizes."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from bench import _ledger_append

    _ledger_append({**summary, "base": base}, kind)


def _load_pkg_file(relpath: str, alias: str):
    """A package file loaded standalone by path — its module level must be
    stdlib-only and free of package-relative imports by contract (the
    utils/roofline.py loader pattern), so loadgen rides the SAME code the
    fleet/servers run, without importing the package (whose __init__ pulls
    jax — a wedged axon tunnel hangs it)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "comfyui_parallelanything_tpu", *relpath.split("/"),
    )
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    # Registered BEFORE exec: dataclass processing under `from __future__
    # import annotations` resolves the module through sys.modules.
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_retry = _load_pkg_file("utils/retry.py", "pa_retry_loadgen")
# utils/slo.py: the objective/stage vocabulary + the Prometheus-text readers
# (stage quantiles, threshold fractions) — the scraped twin of the server's
# in-process SLO registry. fleet/twin.py: the seeded arrival-process
# generator the open-loop scheduler fires and the traffic twin replays — ONE
# generator, so "the same arrival trace" is true by construction.
_slo = _load_pkg_file("utils/slo.py", "pa_slo_loadgen")
_twin = _load_pkg_file("fleet/twin.py", "pa_twin_loadgen")
# History polling: the SHARED poll shape (retry.POLL — 50 ms cadence backing
# off toward 500 ms) — a long denoise no longer costs 20 HTTP polls per
# second per client, the jitter de-synchronizes N clients' polls, and a
# future tuning of the fleet's poll policy applies here automatically.
_POLL = _retry.POLL


class _Front:
    """The client's view of the front door: an ordered list of router bases
    (primary first, standbys after). A connection failure or a standby 503
    advances to the next base — the router-HA story from the CLIENT side:
    a router kill mid-run costs a reconnect, never the prompt."""

    def __init__(self, bases):
        self.bases = [b.rstrip("/") for b in bases]
        self._i = 0
        self._lock = threading.Lock()

    @property
    def base(self) -> str:
        with self._lock:
            return self.bases[self._i]

    def _advance(self, frm: str) -> None:
        with self._lock:
            if self.bases[self._i] == frm and len(self.bases) > 1:
                self._i = (self._i + 1) % len(self.bases)

    def request(self, method, path, payload=None, timeout: float = 30):
        """One HTTP call with base failover: OSError / standby-503 walks the
        base list (once around); anything else propagates."""
        last = None
        for _ in range(max(1, len(self.bases))):
            base = self.base
            try:
                if method == "GET":
                    with urllib.request.urlopen(
                        base + path, timeout=timeout
                    ) as r:
                        body = r.read()
                    ct = r.headers.get("Content-Type", "")
                    return json.loads(body) if "json" in ct else body.decode()
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    try:
                        detail = json.loads(e.read() or b"{}")
                    except ValueError:
                        detail = {}
                    if detail.get("role") == "standby":
                        last = e
                        self._advance(base)
                        continue
                raise
            except OSError as e:
                last = e
                self._advance(base)
                continue
        raise last if last is not None else OSError("no base reachable")


def _get(base: str, path: str, timeout: float = 30):
    if isinstance(base, _Front):
        return base.request("GET", path, timeout=timeout)
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        body = r.read()
    ct = r.headers.get("Content-Type", "")
    return json.loads(body) if "json" in ct else body.decode()


def _post(base: str, path: str, payload: dict, timeout: float = 30):
    if isinstance(base, _Front):
        return base.request("POST", path, payload, timeout=timeout)
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _mark_phase(base, label: str, state: str) -> None:
    """Best-effort phase-boundary stamp into the front door's metric
    history ring (POST /history/phase, round 22) — the anomaly sentinel
    attributes firings to the open phase, so each open-loop rung stamps
    its edges. A pre-round-22 server 404s and a dead front door refuses;
    either way the rung just runs unstamped."""
    try:
        _post(base, "/history/phase", {"label": label, "state": state},
              timeout=5)
    except Exception:
        pass


def _wait_done(base, pid: str, timeout: float):
    t0 = time.time()
    attempt = 0
    while time.time() - t0 < timeout:
        try:
            hist = _get(base, f"/history/{pid}")
        except (urllib.error.URLError, OSError):
            # The front door may be mid-failover (router kill → standby
            # takeover): keep polling on the policy's backoff — the prompt
            # survives in the journal even while no router answers.
            hist = {}
        if pid in hist:
            return hist[pid]
        time.sleep(_POLL.backoff_s(attempt, key=pid))
        attempt += 1
    raise TimeoutError(f"prompt {pid} never completed")


def _set_path(graph: dict, dotted: str, value):
    """Write ``value`` at ``node:inputs:field`` (colon-separated path)."""
    parts = dotted.split(":")
    node = graph
    for p in parts[:-1]:
        node = node[p]
    node[parts[-1]] = value


def _histogram_quantile(text: str, name: str, q: float,
                        labels: dict | None = None) -> float | None:
    """Quantile from a Prometheus histogram's ``_bucket`` exposition, merged
    across (optionally label-filtered) label sets — linear interpolation
    within the target bucket, the same estimate the server's in-process
    ``registry.quantile`` computes. The implementation is utils/slo.py's
    reader (ONE parser for loadgen, the router's /fleet/slo, and
    twin_report); the wrapper keeps the name tests pin against the
    registry."""
    return _slo.histogram_quantile(text, name, q, labels=labels)


def _serving_counters(base: str) -> dict:
    """Scrape the serving counters from the Prometheus text endpoint."""
    try:
        text = _get(base, "/metrics")
    except (urllib.error.URLError, OSError):
        return {}
    out: dict[str, float] = {}
    for metric, key in (("pa_serving_step_seconds", "step"),
                        ("pa_serving_lane_wait_seconds", "lane_wait")):
        for q in (50, 95):
            v = _histogram_quantile(text, metric, q)
            if v is not None:
                out[f"{key}_p{q}_s"] = round(v, 6)
    for name in ("pa_serving_dispatch_total", "pa_serving_completed_total",
                 "pa_serving_cancelled_total", "pa_serving_rejected_total",
                 "pa_serving_lane_steps_total",
                 # Cross-request reuse (round 17): real encoder program
                 # runs (the embed-cache miss cost) and batched tail-decode
                 # dispatch/request counters (serving/decode.py).
                 "pa_encoder_invocations_total",
                 "pa_decode_dispatch_total", "pa_decode_requests_total",
                 # Numerics sentinel (utils/numerics.py): non-finite
                 # observations and quarantined lanes (summed over labels),
                 # plus the enabled gauge (published at scrape time) that
                 # tells a clean 0 apart from an unwatched run.
                 "pa_numerics_nonfinite_total",
                 "pa_numerics_quarantined_total",
                 "pa_numerics_sentinel_enabled",
                 # Chaos tier (round 14): injected-fault and
                 # degradation-ladder counters (utils/faults.py,
                 # utils/degrade.py) — a chaos run's summary proves what was
                 # injected and what gracefully degraded, summed over their
                 # {site=}/{rung=} labels.
                 "pa_fault_injected_total", "pa_degradation_total",
                 # Anomaly sentinel (round 22, utils/anomaly.py): firings
                 # and the unattributed subset (summed over {signal=}) — a
                 # run's summary proves what the telemetry plane flagged.
                 "pa_anomaly_events_total", "pa_anomaly_unattributed_total",
                 # Universal lane batching (round 16): capability seats,
                 # inline-fallback bounces (summed over reason/sampler), and
                 # control-trunk conflicts — the mixed-workload rung's gates.
                 "pa_serving_lane_capability_total",
                 "pa_serving_inline_fallback_total",
                 "pa_serving_ctrl_conflict_total",
                 # Fleet router counters (fleet/router.py) — present when
                 # --base is a router; summed over their {host=} labels.
                 "pa_fleet_dispatch_total", "pa_fleet_spill_total",
                 "pa_fleet_failover_total", "pa_fleet_completed_total",
                 "pa_fleet_prompts_lost_total",
                 # Role pools (round 20): stage dispatches / resolves per
                 # role — the disaggregated router's attribution counters.
                 "pa_role_dispatch_total", "pa_role_stage_resolved_total"):
        total = 0.0
        found = False
        for m in re.finditer(rf"^{name}(?:\{{[^}}]*\}})? ([0-9.eE+-]+)$",
                             text, re.M):
            total += float(m.group(1))
            found = True
        if found:
            out[name] = total
    m = re.search(r"^pa_serving_batched_fraction ([0-9.eE+-]+)$", text, re.M)
    if m:
        out["pa_serving_batched_fraction"] = float(m.group(1))
    # Per-kind capability seats (round 16): the {kind=} label breakdown of
    # lane seats, stored under flat "name:kind" keys so the before/after
    # diff machinery stays float-valued.
    for m in re.finditer(
        r'^pa_serving_lane_capability_total\{[^}]*kind="([^"]+)"[^}]*\} '
        r"([0-9.eE+-]+)$",
        text, re.M,
    ):
        key = f"pa_serving_lane_capability_total:{m.group(1)}"
        out[key] = out.get(key, 0.0) + float(m.group(2))
    # Per-role stage dispatches (round 20): the {role=} breakdown of the
    # disaggregated router's dispatch counter, flat "name:role" keys so the
    # before/after diff machinery stays float-valued.
    for m in re.finditer(
        r'^pa_role_dispatch_total\{[^}]*role="([^"]+)"[^}]*\} '
        r"([0-9.eE+-]+)$",
        text, re.M,
    ):
        key = f"pa_role_dispatch_total:{m.group(1)}"
        out[key] = out.get(key, 0.0) + float(m.group(2))
    # Reuse gauges (round 17): the embed cache's monotonic hit/miss/eviction
    # totals (diffed like counters — they only grow) + current bytes, and
    # the decode tail's lifetime batched fraction.
    for name in ("pa_embed_cache_hits", "pa_embed_cache_misses",
                 "pa_embed_cache_evictions", "pa_embed_cache_bytes",
                 "pa_decode_batched_fraction"):
        m = re.search(rf"^{name} ([0-9.eE+-]+)$", text, re.M)
        if m:
            out[name] = float(m.group(1))
    # Roofline attribution fractions (utils/roofline.py, published at scrape
    # time when the server traces): where the non-compute time goes —
    # comms (fleet hops) and host-gap alongside compute/exposed-transfer.
    for name in ("pa_roofline_compute_fraction",
                 "pa_roofline_exposed_transfer_fraction",
                 "pa_roofline_comms_fraction",
                 "pa_roofline_host_gap_fraction"):
        m = re.search(rf"^{name} ([0-9.eE+-]+)$", text, re.M)
        if m:
            out[name] = float(m.group(1))
    return out


WORKLOAD_KINDS = ("txt2img", "img2img", "controlnet", "lora")


def parse_workload_mix(spec: str | None) -> dict | None:
    """``txt2img,img2img,controlnet,lora:<frac>`` → ``{kind: fraction}``.

    Each comma item is ``kind`` or ``kind:frac``; explicit fractions are
    taken as-is and the remaining probability mass splits equally over the
    fraction-less kinds (so ``txt2img,lora:0.1`` is 0.9/0.1). With every
    fraction explicit the map is normalized. Unknown kinds and infeasible
    masses fail fast."""
    if not spec:
        return None
    fixed: dict[str, float] = {}
    free: list[str] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        kind, _, frac = item.partition(":")
        if kind not in WORKLOAD_KINDS:
            raise ValueError(
                f"unknown workload kind {kind!r} (want one of "
                f"{', '.join(WORKLOAD_KINDS)})"
            )
        if kind in fixed or kind in free:
            raise ValueError(f"workload kind {kind!r} given twice")
        if frac:
            f = float(frac)
            if not 0.0 <= f <= 1.0:
                raise ValueError(f"workload fraction {kind}:{f} not in [0, 1]")
            fixed[kind] = f
        else:
            free.append(kind)
    if not fixed and not free:
        return None
    rest = 1.0 - sum(fixed.values())
    if free:
        if rest <= 0.0:
            raise ValueError(
                "explicit workload fractions sum to >= 1 with "
                f"fraction-less kinds left over: {spec!r}"
            )
        fixed.update({k: rest / len(free) for k in free})
    total = sum(fixed.values())
    if total <= 0.0:
        raise ValueError(f"workload mix has zero total weight: {spec!r}")
    return {k: v / total for k, v in fixed.items()}


def workload_schedule(total: int, mix: dict, seed: int | None = 0) -> list:
    """The per-submission capability kinds: value n is a pure function of
    (seed, n) — the run_load schedule discipline, so two runs with one seed
    sample the identical kind sequence regardless of client interleaving."""
    rng = random.Random(f"workload:{seed if seed is not None else 0}")
    kinds = list(mix)
    weights = [mix[k] for k in kinds]
    return rng.choices(kinds, weights=weights, k=total)


def _capability_summary(before: dict, after: dict) -> dict:
    """The universal-lane-batching summary fields (round 16), diffed from
    the scraped counters: how many lane seats each capability kind took,
    how many sampler runs bounced to the inline eager loop, and control-
    trunk conflicts. None = the counter never existed on either scrape."""

    def delta(name):
        return (after.get(name, 0.0) - before.get(name, 0.0)
                if name in after or name in before else None)

    prefix = "pa_serving_lane_capability_total:"
    kinds = sorted(
        k[len(prefix):] for k in set(before) | set(after)
        if k.startswith(prefix)
    )
    return {
        # Lane seats by capability kind over this run ({kind=} breakdown of
        # pa_serving_lane_capability_total; None: no capability seating).
        "lane_capability": {
            k: delta(prefix + k) for k in kinds
        } or None,
        # Sampler runs that fell back to the inline eager loop with a
        # scheduler installed (reason=degraded|ineligible summed) — the
        # mixed-workload gate number: eligible traffic must keep this 0.
        "serving_inline_fallbacks": delta("pa_serving_inline_fallback_total"),
        "serving_ctrl_conflicts": delta("pa_serving_ctrl_conflict_total"),
    }


def parse_prompt_dist(spec: str | None) -> float | None:
    """``zipf:<s>`` → the exponent s (production prompt popularity is
    zipf-shaped: a few hot prompts dominate, a long tail follows)."""
    if not spec:
        return None
    kind, _, arg = spec.partition(":")
    if kind != "zipf":
        raise ValueError(f"unknown prompt distribution {spec!r} (want zipf:<s>)")
    return float(arg or "1.1")


def prompt_schedule(total: int, *, s: float | None, vocab: list[str],
                    fanout: int = 1, seed: int | None = 0) -> list[str]:
    """The per-submission prompt texts: ``ceil(total/fanout)`` GROUPS, each
    group one zipf-sampled text repeated ``fanout`` times — submissions
    within a group differ only in their --seed-key value, i.e. they are
    sibling seeds of one prompt (the serving tier's shared-cond fanout
    shape). Seeded and threading-independent: value n is a pure function of
    (seed, n), the run_load schedule discipline."""
    fanout = max(1, int(fanout))
    rng = random.Random(seed if seed is not None else 0)
    groups = (total + fanout - 1) // fanout
    if s is None:
        picks = [vocab[g % len(vocab)] for g in range(groups)]
    else:
        weights = [1.0 / (k + 1) ** s for k in range(len(vocab))]
        picks = rng.choices(vocab, weights=weights, k=groups)
    return [picks[i // fanout] for i in range(total)]


def _prompt_texts(total: int, *, prompt_key, prompt_dist, prompt_vocab,
                  seed_fanout, seed):
    """The per-submission prompt-text schedule both loops share (closed and
    open loop MUST bank records under the same schedule for the same
    flags), or None when no prompt key / no distribution is in play."""
    if not (prompt_key and (prompt_dist or seed_fanout > 1)):
        return None
    return prompt_schedule(
        total, s=parse_prompt_dist(prompt_dist),
        vocab=prompt_vocab or [f"prompt {k}" for k in range(32)],
        fanout=seed_fanout, seed=seed,
    )


def _reuse_summary(before: dict, after: dict) -> dict:
    """The cross-request-reuse summary fields, diffed from the scraped
    counters: hit rate over THIS run, real encoder invocations, and the
    decode tail's batching — the numbers the zipf/fanout CI smoke gates."""

    def delta(name):
        return (after.get(name, 0.0) - before.get(name, 0.0)
                if name in after or name in before else None)

    hits, misses = delta("pa_embed_cache_hits"), delta("pa_embed_cache_misses")
    hit_rate = None
    if hits is not None and misses is not None and hits + misses > 0:
        hit_rate = round(hits / (hits + misses), 4)
    return {
        # Fraction of encode lookups served from the content-addressed
        # cache over this run (None: cache absent or no lookups).
        "embed_cache_hit_rate": hit_rate,
        "embed_cache_evictions": delta("pa_embed_cache_evictions"),
        # Real text-encoder program runs over this run — the number the
        # zipf rung gates at <= 0.5x total prompts.
        "encoder_invocations": delta("pa_encoder_invocations_total"),
        # Decode-tail batching: requests served via shared decode dispatch
        # / total (process lifetime, the same gauge /health reports) plus
        # this run's dispatch/request deltas.
        "decode_batched_fraction": after.get("pa_decode_batched_fraction"),
        "decode_dispatches": delta("pa_decode_dispatch_total"),
        "decode_requests": delta("pa_decode_requests_total"),
    }


def percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile (no numpy — stdlib-only by contract)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = max(0, min(len(s) - 1, round(q / 100.0 * (len(s) - 1))))
    return s[k]


def _host_probe(hosts: list[str]) -> dict:
    """One scrape per backend: its health identity + serving counters —
    the before/after pair fleet mode diffs for per-host dispatch deltas."""
    out: dict[str, dict] = {}
    for h in hosts:
        h = h.rstrip("/")
        probe: dict = {"base": h}
        try:
            health = _get(h, "/health", timeout=10)
            probe["host_id"] = health.get("host_id")
            probe["accepting"] = health.get("accepting")
            probe["inflight_prompts"] = health.get("inflight_prompts")
            # Role pool (round 20): the backend's declared --role, "all"
            # when undeclared — threaded into the per-host summary rows so
            # role sections and the twin's stage pools can form.
            probe["role"] = health.get("role")
            # Worker-pool width: the twin's per-host concurrency
            # (fleet/twin.py simulates `workers` servers per host).
            probe["workers"] = (health.get("queue") or {}).get("workers")
        except (urllib.error.URLError, OSError, ValueError):
            probe["host_id"] = None
        probe["counters"] = _serving_counters(h)
        out[h] = probe
    return out


def _role_sections(per_host: dict | None) -> dict | None:
    """Per-role pool aggregation of the fleet per-host rows (round 20,
    fleet/roles.py): which hosts form each pool, how much each pool served,
    and the pool's worst client p95. None unless some backend declares a
    role other than ``all`` — homogeneous summaries gain nothing."""
    if not per_host:
        return None
    if not any((h.get("role") or "all") != "all" for h in per_host.values()):
        return None
    pools: dict[str, dict] = {}
    for hid, h in per_host.items():
        r = str(h.get("role") or "all")
        p = pools.setdefault(r, {"hosts": [], "completed": 0,
                                 "dispatches": 0.0, "p95s": []})
        p["hosts"].append(hid)
        p["completed"] += int(h.get("completed") or 0)
        if h.get("dispatches") is not None:
            p["dispatches"] += float(h["dispatches"])
        if h.get("completed") and h.get("latency_p95_s") is not None:
            p["p95s"].append(float(h["latency_p95_s"]))
    return {
        r: {
            "hosts": sorted(p["hosts"]),
            "completed": p["completed"],
            "dispatches": p["dispatches"],
            "latency_p95_s": max(p["p95s"]) if p["p95s"] else None,
        }
        for r, p in sorted(pools.items())
    }


def _role_dispatch_deltas(before: dict, after: dict) -> dict | None:
    """This run's stage dispatches per role, diffed from the router's
    ``pa_role_dispatch_total{role=}`` breakdown (flat "name:role" scrape
    keys). None outside a disaggregated fleet — the counter never exists."""
    prefix = "pa_role_dispatch_total:"
    roles = sorted(
        k[len(prefix):] for k in set(before) | set(after)
        if k.startswith(prefix)
    )
    return {
        r: after.get(prefix + r, 0.0) - before.get(prefix + r, 0.0)
        for r in roles
    } or None


def run_load(base: str, graph: dict, *, clients: int, requests: int,
             timeout: float, seed_key: str | None = None,
             extra_data: dict | None = None,
             samplers: list[str] | None = None,
             sampler_key: str | None = None,
             seed: int | None = None,
             hosts: list[str] | None = None,
             fallback_bases: list[str] | None = None,
             prompt_dist: str | None = None,
             prompt_key: str | None = None,
             prompt_vocab: list[str] | None = None,
             seed_fanout: int = 1,
             workload_mix: dict | None = None,
             workload_graphs: dict | None = None,
             trace_sample: float = 0.0) -> dict:
    """The closed loop; returns the summary dict (importable — the e2e and
    fleet-smoke tests drive in-process servers through this exact code path).

    ``samplers`` + ``sampler_key`` make the workload MIXED: prompt n runs
    ``samplers[n % len]`` (round-robin, written into the graph at
    ``sampler_key``) — the traffic shape the stateful-lane scheduler
    co-batches into one dispatch stream, whose amortization the summary
    reports (shared-dispatch counters scraped from /metrics).

    ``seed`` makes the prompt schedule reproducible: the per-prompt value
    written at ``seed_key`` comes from ``random.Random(seed)`` instead of
    the live counter. ``hosts`` turns on fleet mode (see module docstring).
    ``fallback_bases`` (router HA): standby router URLs tried in order when
    the primary stops answering or replies standby-503 — a router kill
    mid-run costs the clients a reconnect, never a prompt.

    Cross-request reuse shape (round 17): ``prompt_dist`` (``zipf:<s>``) +
    ``prompt_key`` sample each submission's prompt TEXT from
    ``prompt_vocab`` under a seeded zipf — the redundant production traffic
    the embed cache collapses; ``seed_fanout`` N groups submissions into
    N-seed siblings of one sampled prompt (the shared-cond fanout shape).
    The summary gains ``embed_cache_hit_rate`` / ``encoder_invocations`` /
    ``decode_batched_fraction`` scraped-delta fields either way.

    Mixed capability traffic (round 16): ``workload_mix`` ({kind: fraction}
    over txt2img/img2img/controlnet/lora, see parse_workload_mix) samples
    each submission's CAPABILITY kind seeded (value n pure in (seed, n))
    and submits the matching graph from ``workload_graphs`` ({kind: graph
    dict}; kinds without an entry — txt2img canonically — use the base
    ``graph``). Variant graphs must keep the base graph's node ids at
    ``seed_key``/``sampler_key``/``prompt_key`` so the per-prompt writes
    land. The summary gains ``workload_mix``/``workload_counts`` plus the
    ``lane_capability`` per-kind seat deltas and the
    ``serving_inline_fallbacks`` gate number either way.

    Request forensics (round 21): ``trace_sample`` tags a seeded,
    prefix-stable fraction of submissions for full distributed capture
    (``extra_data.pa_trace_sampled`` — the router injects a traceparent on
    every hop of a tagged prompt) and, after each tagged prompt completes,
    fetches its stitched timeline (``GET /fleet/trace`` behind a router,
    ``GET /trace`` on a plain server). The summary gains ``traced_prompts``
    + ``trace_fetch_rate`` (stitch fetch success)."""
    if fallback_bases:
        base = _Front([base, *fallback_bases])
    latencies: list[float] = []
    lat_by_host: dict = {}
    failures: list[str] = []
    rejected = [0]
    timeouts = [0]
    traced = [0]
    traced_ok = [0]
    lock = threading.Lock()
    counter = [0]
    # Reproducible schedule: value n is a pure function of (seed, n), so two
    # runs with one seed submit the identical prompt set regardless of how
    # the client threads interleave.
    schedule = None
    if seed is not None:
        rng = random.Random(seed)
        schedule = [rng.randrange(1 << 31) for _ in range(clients * requests)]
    texts = _prompt_texts(
        clients * requests, prompt_key=prompt_key, prompt_dist=prompt_dist,
        prompt_vocab=prompt_vocab, seed_fanout=seed_fanout, seed=seed,
    )
    kind_schedule = None
    kind_counts: dict[str, int] = {}
    if workload_mix:
        kind_schedule = workload_schedule(clients * requests, workload_mix,
                                          seed=seed)
        for k in kind_schedule:
            kind_counts[k] = kind_counts.get(k, 0) + 1
    before = _serving_counters(base)
    hosts_before = _host_probe(hosts) if hosts else None
    t_start = time.time()

    def client(ci: int) -> None:
        for _ in range(requests):
            with lock:
                counter[0] += 1
                n = counter[0]
            src = graph
            if kind_schedule is not None:
                src = (workload_graphs or {}).get(kind_schedule[n - 1], graph)
            g = json.loads(json.dumps(src))
            if seed_key:
                _set_path(g, seed_key,
                          schedule[n - 1] if schedule is not None else n)
            if samplers and sampler_key:
                _set_path(g, sampler_key, samplers[n % len(samplers)])
            if texts is not None:
                _set_path(g, prompt_key, texts[n - 1])
            payload = {"prompt": g}
            sampled = trace_sampled(n, trace_sample, seed)
            ed = dict(extra_data) if extra_data else {}
            if sampled:
                ed["pa_trace_sampled"] = True
            if ed:
                payload["extra_data"] = ed
            t0 = time.time()
            # Submit with bounded retry (utils/retry.py shape): a 503 or a
            # refused connection can be a router mid-failover (standby
            # takeover costs ~a lease TTL) — retry on backoff until the
            # window closes, then count the failure. 429 (bounded queue) and
            # 4xx (request at fault) are never retried.
            pid = None
            post_deadline = t0 + min(60.0, timeout)
            attempt = 0
            while True:
                try:
                    pid = _post(base, "/prompt", payload)["prompt_id"]
                    break
                except urllib.error.HTTPError as e:
                    if e.code == 503 and time.time() < post_deadline:
                        time.sleep(_POLL.backoff_s(attempt, key=f"s{ci}"))
                        attempt += 1
                        continue
                    with lock:
                        if e.code == 429:
                            rejected[0] += 1
                        else:
                            failures.append(f"client {ci}: HTTP {e.code}")
                    break
                except OSError as e:
                    if time.time() < post_deadline:
                        time.sleep(_POLL.backoff_s(attempt, key=f"s{ci}"))
                        attempt += 1
                        continue
                    with lock:
                        failures.append(f"client {ci}: unreachable ({e})")
                    break
            if pid is None:
                continue
            try:
                entry = _wait_done(base, pid, timeout)
            except TimeoutError:
                # A prompt that never completes is LOST from the client's
                # view — it must count (the fleet gate), not silently kill
                # this client thread.
                with lock:
                    timeouts[0] += 1
                    failures.append(f"client {ci}: timeout ({pid})")
                continue
            dt = time.time() - t0
            status = entry.get("status") or {}
            served_by = (status.get("fleet") or {}).get("host_id") \
                or status.get("host_id")
            fetched = None
            if sampled:
                # The stitched-capture round trip the sampling exists for:
                # a tagged prompt's distributed timeline must actually be
                # collectable, and the summary reports the hit rate.
                fetched = False
                path = (f"/fleet/trace?prompt_id={pid}" if hosts
                        else f"/trace?prompt_id={pid}")
                try:
                    doc = _get(base, path)
                    fetched = (not doc.get("error")
                               and any(e.get("ph") == "X"
                                       for e in doc.get("traceEvents") or ()))
                except (OSError, urllib.error.HTTPError, ValueError):
                    pass
            with lock:
                if sampled:
                    traced[0] += 1
                    if fetched:
                        traced_ok[0] += 1
                if status.get("status_str") == "success":
                    latencies.append(dt)
                    if served_by:
                        lat_by_host.setdefault(served_by, []).append(dt)
                else:
                    failures.append(
                        f"client {ci}: {status.get('status_str')}"
                    )

    threads = [threading.Thread(target=client, args=(i,)) for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t_start
    after = _serving_counters(base)
    dispatches = (
        after.get("pa_serving_dispatch_total", 0.0)
        - before.get("pa_serving_dispatch_total", 0.0)
    ) if after else None
    lane_steps = (
        after.get("pa_serving_lane_steps_total", 0.0)
        - before.get("pa_serving_lane_steps_total", 0.0)
    ) if after else None
    fleet = None
    per_host = None
    prompts_lost = None
    if hosts:
        hosts_after = _host_probe(hosts)
        per_host = {}
        for h in hosts:
            h = h.rstrip("/")
            b, a = hosts_before.get(h, {}), hosts_after.get(h, {})
            hid = a.get("host_id") or b.get("host_id") or h
            cb, ca = b.get("counters") or {}, a.get("counters") or {}
            lats = lat_by_host.get(hid, [])
            per_host[hid] = {
                "base": h,
                "role": a.get("role") or b.get("role") or "all",
                "completed": len(lats),
                "latency_p50_s": round(percentile(lats, 50), 3),
                "latency_p95_s": round(percentile(lats, 95), 3),
                "dispatches": (
                    ca.get("pa_serving_dispatch_total", 0.0)
                    - cb.get("pa_serving_dispatch_total", 0.0)
                ) if ca else None,
                "lane_steps": (
                    ca.get("pa_serving_lane_steps_total", 0.0)
                    - cb.get("pa_serving_lane_steps_total", 0.0)
                ) if ca else None,
                "server_step_p50_s": ca.get("step_p50_s"),
                "server_step_p95_s": ca.get("step_p95_s"),
                "accepting": a.get("accepting"),
                "reachable": a.get("host_id") is not None,
            }
        # Router-side deltas (--base is the fleet front door). A router-lost
        # prompt and a client-timeout are the same failure seen from two
        # ends; the gate number is their sum.
        def _delta(name):
            return (after.get(name, 0.0) - before.get(name, 0.0)
                    if name in after or name in before else None)

        fleet = {
            "dispatches": _delta("pa_fleet_dispatch_total"),
            "spills": _delta("pa_fleet_spill_total"),
            "failovers": _delta("pa_fleet_failover_total"),
            "completed": _delta("pa_fleet_completed_total"),
        }
        role_disp = _role_dispatch_deltas(before, after)
        if role_disp:
            fleet["role_dispatches"] = role_disp
        lost_router = _delta("pa_fleet_prompts_lost_total")
        prompts_lost = (lost_router or 0.0) + timeouts[0]
    elif timeouts[0]:
        prompts_lost = float(timeouts[0])
    return {
        "clients": clients,
        "requests": clients * requests,
        "seed": seed,
        "samplers": samplers or None,
        "prompt_dist": prompt_dist if texts is not None else None,
        "seed_fanout": (
            seed_fanout if texts is not None and seed_fanout > 1 else None
        ),
        "distinct_prompts": len(set(texts)) if texts is not None else None,
        "workload_mix": workload_mix or None,
        "workload_counts": kind_counts or None,
        **_capability_summary(before, after),
        **_reuse_summary(before, after),
        "completed": len(latencies),
        "failed": len(failures),
        "rejected_429": rejected[0],
        "wall_s": round(wall, 3),
        "throughput_rps": round(len(latencies) / wall, 3) if wall > 0 else None,
        "latency_p50_s": round(percentile(latencies, 50), 3),
        "latency_p95_s": round(percentile(latencies, 95), 3),
        "latency_max_s": round(max(latencies), 3) if latencies else 0.0,
        "serving_dispatches": dispatches,
        # Dispatch amortization: lane-steps served per compiled dispatch over
        # this run (1.0 = no sharing; N = every dispatch carried N lanes) —
        # the mixed-workload number the ROADMAP serving-on-hardware item banks.
        "serving_lane_steps": lane_steps,
        "dispatch_amortization": (
            round(lane_steps / dispatches, 3)
            if lane_steps and dispatches else None
        ),
        # End-state shared-dispatch fraction (process lifetime, not deltas —
        # the same gauge GET /health reports).
        "serving_batched_fraction": after.get("pa_serving_batched_fraction"),
        # Numerics sentinel deltas over this run (utils/numerics.py): lanes
        # quarantined by the non-finite watchdog and raw non-finite
        # observations. The counters only exist once an event fires, so an
        # absent counter with the sentinel ENABLED means a clean run (0) and
        # with the sentinel disabled means unwatched (None) — the gauge the
        # server publishes at scrape time disambiguates the two.
        "numerics_quarantined": (
            after.get("pa_numerics_quarantined_total", 0.0)
            - before.get("pa_numerics_quarantined_total", 0.0)
        ) if after.get("pa_numerics_sentinel_enabled") else None,
        "numerics_nonfinite": (
            after.get("pa_numerics_nonfinite_total", 0.0)
            - before.get("pa_numerics_nonfinite_total", 0.0)
        ) if after.get("pa_numerics_sentinel_enabled") else None,
        # Chaos tier (round 14): faults fired by the injection registry and
        # degradation-ladder rungs taken over this run (summed over
        # site/rung labels; None = the counters never existed — no plan
        # armed AND nothing degraded).
        "faults_injected": (
            after.get("pa_fault_injected_total", 0.0)
            - before.get("pa_fault_injected_total", 0.0)
        ) if ("pa_fault_injected_total" in after
              or "pa_fault_injected_total" in before) else None,
        "degradations": (
            after.get("pa_degradation_total", 0.0)
            - before.get("pa_degradation_total", 0.0)
        ) if ("pa_degradation_total" in after
              or "pa_degradation_total" in before) else None,
        # Anomaly sentinel deltas over this run (round 22,
        # utils/anomaly.py): signal firings and the unattributed subset
        # (None = the counters never existed — sentinel off or nothing
        # ever fired process-wide).
        "anomalies_fired": (
            after.get("pa_anomaly_events_total", 0.0)
            - before.get("pa_anomaly_events_total", 0.0)
        ) if ("pa_anomaly_events_total" in after
              or "pa_anomaly_events_total" in before) else None,
        "anomalies_unattributed": (
            after.get("pa_anomaly_unattributed_total", 0.0)
            - before.get("pa_anomaly_unattributed_total", 0.0)
        ) if ("pa_anomaly_unattributed_total" in after
              or "pa_anomaly_unattributed_total" in before) else None,
        # Server-side quantiles from the /metrics histograms (end-state
        # values — histograms are cumulative): what the SERVER measured per
        # lockstep dispatch / lane admission, vs the client-clock latencies
        # above which include queueing + HTTP + polling.
        "server_step_p50_s": after.get("step_p50_s"),
        "server_step_p95_s": after.get("step_p95_s"),
        "server_lane_wait_p95_s": after.get("lane_wait_p95_s"),
        # Roofline attribution fractions over the server's live trace window
        # (utils/roofline.py buckets, scraped from /metrics; None when the
        # server runs untraced): how much of the wall went to cross-host
        # comms and to host scheduling gaps rather than device compute.
        "roofline_comms_fraction": after.get("pa_roofline_comms_fraction"),
        "roofline_host_gap_fraction": after.get(
            "pa_roofline_host_gap_fraction"
        ),
        # Fleet mode (--hosts): per-host client latencies + dispatch deltas,
        # router-side placement/failover deltas, and the CI-gated loss count
        # (router-lost + client-timeout; None outside fleet mode unless a
        # timeout made the number real). "roles" (round 20): the per-role
        # pool aggregation — None unless some backend declared a role.
        "hosts": per_host,
        "roles": _role_sections(per_host),
        "fleet": fleet,
        "prompts_lost": prompts_lost,
        "timeouts": timeouts[0],
        # Request forensics (--trace-sample): prompts tagged for distributed
        # capture, and the fraction whose stitched timeline was actually
        # fetchable after completion (None = sampling off).
        "traced_prompts": traced[0] if trace_sample > 0 else None,
        "trace_fetch_rate": (
            round(traced_ok[0] / traced[0], 3)
            if trace_sample > 0 and traced[0] else
            (0.0 if trace_sample > 0 else None)
        ),
        "errors": failures[:5],
    }


def _scrape_slo(base, e2e_p50=None, e2e_p95=None) -> dict | None:
    """The SLO view of a run, scraped off ``GET /metrics``: per-stage
    latency decomposition quantiles (``pa_slo_stage_seconds``), server-side
    request residency, windowed burn-rate gauges, and — fleet mode — the
    router's merged ``GET /fleet/slo`` verdicts. The CLIENT-side residual,
    ``collect`` (history polling + HTTP + everything the server cannot
    see), is e2e minus server residency at matching quantiles — the fifth
    stage of the decomposition, computable only here.

    The scrape prefers ``GET /fleet/metrics`` (a router's merged
    host-labeled view — the backends' ``pa_slo_*`` series live THERE in a
    real multi-process fleet; the router's own registry never carries
    them) and falls back to ``GET /metrics`` on a plain server (404)."""
    text = None
    try:
        text = _get(base, "/fleet/metrics")
    except (urllib.error.URLError, OSError, ValueError):
        pass  # not a router (404) or unreachable — try the plain endpoint
    if not isinstance(text, str) or "# TYPE" not in text:
        try:
            text = _get(base, "/metrics")
        except (urllib.error.URLError, OSError):
            return None
    stages: dict[str, dict] = {}
    for stage in ("admission", "encode", "lane_wait", "eval",
                  "decode_wait", "decode"):
        p50 = _histogram_quantile(text, "pa_slo_stage_seconds", 50,
                                  labels={"stage": stage})
        if p50 is None:
            continue
        p95 = _histogram_quantile(text, "pa_slo_stage_seconds", 95,
                                  labels={"stage": stage})
        stages[stage] = {"p50_s": round(p50, 6),
                         "p95_s": round(p95, 6) if p95 is not None else None}
    req50 = _histogram_quantile(text, "pa_slo_request_seconds", 50)
    req95 = _histogram_quantile(text, "pa_slo_request_seconds", 95)
    burn: dict[str, float] = {}
    for m in re.finditer(
        r'^pa_slo_burn_rate\{[^}]*objective="([^"]+)"[^}]*\} '
        r"([0-9.eE+-]+)$",
        text, re.M,
    ):
        # Merged fleet views carry one host-labeled gauge per backend: the
        # fleet's burn rate for an objective is its WORST host's.
        burn[m.group(1)] = max(burn.get(m.group(1), 0.0),
                               float(m.group(2)))
    out: dict = {
        "stages": stages or None,
        "request_p50_s": round(req50, 6) if req50 is not None else None,
        "request_p95_s": round(req95, 6) if req95 is not None else None,
        "burn_rates": burn or None,
    }
    if e2e_p50 is not None and req50 is not None:
        out["collect_p50_s"] = round(max(0.0, e2e_p50 - req50), 6)
    if e2e_p95 is not None and req95 is not None:
        out["collect_p95_s"] = round(max(0.0, e2e_p95 - req95), 6)
    try:
        fleet_slo = _get(base, "/fleet/slo", timeout=10)
        if isinstance(fleet_slo, dict) and fleet_slo.get("objectives"):
            out["objectives"] = fleet_slo["objectives"]
    except (urllib.error.URLError, OSError, ValueError):
        pass  # not a router (plain server 404s) — gauges carry the verdict
    if not stages and req50 is None and not burn and "objectives" not in out:
        return None  # PA_SLO=0 everywhere: no SLO section, not zeros
    return out


def run_open_load(base: str, graph: dict, *, kind: str = "poisson",
                  rps_list=(4.0,), duration_s: float = 3.0,
                  timeout: float = 300.0, seed: int | None = 0,
                  seed_key: str | None = None,
                  extra_data: dict | None = None,
                  samplers: list[str] | None = None,
                  sampler_key: str | None = None,
                  hosts: list[str] | None = None,
                  fallback_bases: list[str] | None = None,
                  on_s: float = 1.0, off_s: float = 1.0,
                  arrivals_doc: dict | None = None,
                  arrivals_out: str | None = None,
                  twin_band: float = 0.5,
                  prompt_dist: str | None = None,
                  prompt_key: str | None = None,
                  prompt_vocab: list[str] | None = None,
                  seed_fanout: int = 1) -> dict:
    """OPEN-loop load: requests fire on a seeded arrival schedule
    (fleet/twin.py's generator — Poisson, bursty ON-OFF, or trace replay)
    regardless of completions, which is the regime where queues actually
    grow (the closed loop's offered load can never exceed its concurrency).
    One rung per offered rate in ``rps_list``; the summary's
    ``openloop.curve`` is latency-under-load (p50/p95/p99 vs offered RPS)
    and its ``slo`` section the stage decomposition + burn rates — together
    the ``kind="openloop"`` ledger record the traffic twin replays
    (``scripts/twin_report.py``)."""
    if fallback_bases:
        base = _Front([base, *fallback_bases])
    sched_rng = random.Random(seed if seed is not None else 0)
    before = _serving_counters(base)
    hosts_before = _host_probe(hosts) if hosts else None
    if arrivals_doc is not None:
        kind = str(arrivals_doc.get("kind") or "replay")
        rungs_in = [
            {"rps": r.get("rps"), "duration_s": float(r.get("duration_s") or 0.0),
             "offsets": [float(t) for t in r.get("offsets") or []],
             "replay": True}
            for r in arrivals_doc.get("rungs") or []
        ]
    else:
        rungs_in = [
            {"rps": float(r), "duration_s": float(duration_s),
             "offsets": _twin.gen_arrivals(
                 kind, rps=float(r), duration_s=float(duration_s),
                 seed=int(seed or 0), on_s=on_s, off_s=off_s,
             ),
             "replay": False}
            for r in rps_list
        ]
    texts = _prompt_texts(
        sum(len(r["offsets"]) for r in rungs_in), prompt_key=prompt_key,
        prompt_dist=prompt_dist, prompt_vocab=prompt_vocab,
        seed_fanout=seed_fanout, seed=seed,
    )
    all_lat: list[float] = []
    lat_by_host: dict = {}
    exec_by_host: dict = {}
    failures: list[str] = []
    rejected = [0]
    timeouts = [0]
    counter = [0]
    lock = threading.Lock()
    curve: list[dict] = []
    t_start = time.time()
    for rung_idx, rung in enumerate(rungs_in):
        offsets = rung["offsets"]
        rung_lat: list[float] = []
        rung_exec: list[float] = []
        rung_label = f"openloop-{kind}-r{rung_idx}-{rung['rps']}rps"
        _mark_phase(base, rung_label, "begin")
        rt0 = time.time()

        def fire(_rung_lat=rung_lat, _rung_exec=rung_exec):
            # Open-loop discipline: fired at the scheduled instant (the
            # scheduler thread below owns the clock), never "when the
            # previous one finished" — and never retry a refusal (a
            # dropped arrival is data, not an error to paper over).
            g = json.loads(json.dumps(graph))
            with lock:
                counter[0] += 1
                n = counter[0]
                val = sched_rng.randrange(1 << 31)
            if seed_key:
                _set_path(g, seed_key, val if seed is not None else n)
            if samplers and sampler_key:
                _set_path(g, sampler_key, samplers[n % len(samplers)])
            if texts is not None and n <= len(texts):
                _set_path(g, prompt_key, texts[n - 1])
            payload = {"prompt": g}
            if extra_data:
                payload["extra_data"] = extra_data
            t0 = time.time()
            try:
                pid = _post(base, "/prompt", payload)["prompt_id"]
            except urllib.error.HTTPError as e:
                with lock:
                    if e.code == 429:
                        rejected[0] += 1
                    else:
                        failures.append(f"openloop: HTTP {e.code}")
                return
            except OSError as e:
                with lock:
                    failures.append(f"openloop: unreachable ({e})")
                return
            try:
                entry = _wait_done(base, pid, timeout)
            except TimeoutError:
                with lock:
                    timeouts[0] += 1
                    failures.append(f"openloop: timeout ({pid})")
                return
            dt = time.time() - t0
            status = entry.get("status") or {}
            served_by = (status.get("fleet") or {}).get("host_id") \
                or status.get("host_id")
            with lock:
                if status.get("status_str") == "success":
                    _rung_lat.append(dt)
                    all_lat.append(dt)
                    ex = status.get("exec_s")
                    if isinstance(ex, (int, float)):
                        _rung_exec.append(float(ex))
                    if served_by:
                        lat_by_host.setdefault(served_by, []).append(dt)
                        if isinstance(ex, (int, float)):
                            exec_by_host.setdefault(
                                served_by, []
                            ).append(float(ex))
                else:
                    failures.append(
                        f"openloop: {status.get('status_str')}"
                    )

        # One scheduler thread owns the arrival clock and spawns a request
        # thread only AT each arrival's fire time — live threads stay
        # bounded by in-flight requests, not by the rung's total (a 60 s
        # 100-rps rung must not park 6000 stacks up front and let their
        # creation storm distort the very arrival fidelity being measured).
        threads: list[threading.Thread] = []
        for off in offsets:
            delay = rt0 + off - time.time()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=fire, daemon=True)
            threads.append(th)
            th.start()
        for th in threads:
            th.join(timeout + rung["duration_s"] + 60)
        wall = time.time() - rt0
        _mark_phase(base, rung_label, "end")
        dur = rung["duration_s"] or (max(offsets) if offsets else 0.0) or 1.0
        entry: dict = {
            "rps": rung["rps"],
            "rps_offered": round(len(offsets) / dur, 4),
            "duration_s": rung["duration_s"],
            "arrivals": len(offsets),
            "completed": len(rung_lat),
            "achieved_rps": round(len(rung_lat) / wall, 4) if wall > 0 else None,
            "latency_p50_s": round(percentile(rung_lat, 50), 6),
            "latency_p95_s": round(percentile(rung_lat, 95), 6),
            "latency_p99_s": round(percentile(rung_lat, 99), 6),
            # This rung's OWN service p50 — the overhead calibration below
            # must not subtract a contention-inflated pooled value.
            "service_p50_s": (
                round(percentile(rung_exec, 50), 6) if rung_exec else None
            ),
        }
        if kind == "onoff":
            entry["on_s"], entry["off_s"] = on_s, off_s
        if rung["replay"]:
            # Replay rungs carry their offsets verbatim — the twin cannot
            # regenerate a recorded trace from (kind, seed).
            entry["offsets"] = offsets
        curve.append(entry)
    wall = time.time() - t_start
    after = _serving_counters(base)
    if arrivals_out:
        _twin.save_arrivals(
            arrivals_out,
            [{"rps": r["rps"], "duration_s": r["duration_s"],
              "offsets": r["offsets"]} for r in rungs_in],
            kind=kind, seed=seed,
        )
    e2e_p50 = percentile(all_lat, 50) if all_lat else None
    e2e_p95 = percentile(all_lat, 95) if all_lat else None
    slo_view = _scrape_slo(base, e2e_p50=e2e_p50, e2e_p95=e2e_p95)
    all_exec = [v for vs in exec_by_host.values() for v in vs]
    # Per-host sections: fleet mode diffs the backend probes (run_load's
    # shape) + the twin's capacity fields; single-server mode synthesizes
    # one row per serving host_id from the entries alone.
    per_host: dict | None = None
    fleet = None
    prompts_lost = None
    if hosts:
        hosts_after = _host_probe(hosts)
        # Entries are attributed by the ROUTER's host id
        # (status.fleet.host_id), which for bare-URL --backends seeds is
        # URL-derived and differs from the backend's self-declared
        # /health host_id — join the two through the router's ring
        # snapshot so per-host service evidence lands either way.
        ring_map: dict[str, str] = {}
        try:
            doc = _get(base, "/fleet/hosts", timeout=10)
            for row in doc.get("ring") or []:
                if row.get("base") and row.get("host_id"):
                    ring_map[str(row["base"]).rstrip("/")] = \
                        str(row["host_id"])
        except (urllib.error.URLError, OSError, ValueError):
            pass
        per_host = {}
        for h in hosts:
            h = h.rstrip("/")
            b, a = hosts_before.get(h, {}), hosts_after.get(h, {})
            phid = a.get("host_id") or b.get("host_id")
            hid = ring_map.get(h) or phid or h
            cb, ca = b.get("counters") or {}, a.get("counters") or {}
            lats = lat_by_host.get(hid) \
                or (lat_by_host.get(phid, []) if phid else [])
            execs = exec_by_host.get(hid) \
                or (exec_by_host.get(phid, []) if phid else [])
            per_host[hid] = {
                "base": h,
                "role": a.get("role") or b.get("role") or "all",
                "completed": len(lats),
                "latency_p50_s": round(percentile(lats, 50), 3),
                "latency_p95_s": round(percentile(lats, 95), 3),
                "dispatches": (
                    ca.get("pa_serving_dispatch_total", 0.0)
                    - cb.get("pa_serving_dispatch_total", 0.0)
                ) if ca else None,
                "server_step_p50_s": ca.get("step_p50_s"),
                "server_step_p95_s": ca.get("step_p95_s"),
                # The twin's capacity inputs: per-request service p50
                # (exec_s off the history entries — same workload on every
                # host by construction) and the worker-pool width.
                "service_p50_s": (
                    round(percentile(execs, 50), 6) if execs else None
                ),
                "workers": a.get("workers") or b.get("workers"),
                "accepting": a.get("accepting"),
                "reachable": a.get("host_id") is not None,
            }

        def _delta(name):
            return (after.get(name, 0.0) - before.get(name, 0.0)
                    if name in after or name in before else None)

        fleet = {
            "dispatches": _delta("pa_fleet_dispatch_total"),
            "spills": _delta("pa_fleet_spill_total"),
            "failovers": _delta("pa_fleet_failover_total"),
            "completed": _delta("pa_fleet_completed_total"),
        }
        role_disp = _role_dispatch_deltas(before, after)
        if role_disp:
            fleet["role_dispatches"] = role_disp
        lost_router = _delta("pa_fleet_prompts_lost_total")
        prompts_lost = (lost_router or 0.0) + timeouts[0]
    elif exec_by_host:
        workers = None
        try:
            health = _get(base, "/health", timeout=10)
            workers = (health.get("queue") or {}).get("workers")
        except (urllib.error.URLError, OSError, ValueError):
            pass
        per_host = {
            hid: {
                "completed": len(lat_by_host.get(hid, [])),
                "latency_p50_s": round(
                    percentile(lat_by_host.get(hid, []), 50), 3
                ),
                "latency_p95_s": round(
                    percentile(lat_by_host.get(hid, []), 95), 3
                ),
                "service_p50_s": round(percentile(execs, 50), 6),
                "workers": workers,
            }
            for hid, execs in exec_by_host.items()
        }
    if prompts_lost is None and timeouts[0]:
        # Unconditional (not nested under any per-host branch): a run whose
        # EVERY request timed out has no exec evidence but its losses are
        # the most real of all — the closed-loop run_load discipline.
        prompts_lost = float(timeouts[0])
    total_arrivals = sum(len(r["offsets"]) for r in rungs_in)
    dispatches = (
        after.get("pa_serving_dispatch_total", 0.0)
        - before.get("pa_serving_dispatch_total", 0.0)
    ) if after else None
    lane_steps = (
        after.get("pa_serving_lane_steps_total", 0.0)
        - before.get("pa_serving_lane_steps_total", 0.0)
    ) if after else None
    # The twin's client-side constant: at the LOWEST offered rate queueing
    # is ~zero, so (client p50 − service p50) is pure transport + history
    # poll cadence — the per-request overhead the twin adds on top of its
    # queue + service model (fleet/twin.py simulate(overhead_s=...)). BOTH
    # sides of the subtraction come from the lightest rung: a pooled
    # service p50 folds in contention-inflated exec times from saturated
    # rungs and would clamp the constant toward zero.
    overall_service = (
        round(percentile(all_exec, 50), 6) if all_exec else None
    )
    client_overhead = None
    calibration_rungs = [c for c in curve if c["completed"] > 0]
    if calibration_rungs:
        lightest = min(calibration_rungs,
                       key=lambda c: c["rps_offered"] or 0.0)
        light_service = lightest.get("service_p50_s") or overall_service
        if light_service is not None:
            client_overhead = round(
                max(0.0, lightest["latency_p50_s"] - light_service), 6
            )
    return {
        "mode": "openloop",
        "openloop": {
            "kind": kind,
            "seed": seed,
            "curve": curve,
            "client_overhead_s": client_overhead,
            "twin_band": twin_band,
        },
        "twin_band": twin_band,
        "requests": total_arrivals,
        "seed": seed,
        "samplers": samplers or None,
        "prompt_dist": prompt_dist if texts is not None else None,
        "seed_fanout": (
            seed_fanout if texts is not None and seed_fanout > 1 else None
        ),
        "distinct_prompts": len(set(texts)) if texts is not None else None,
        **_reuse_summary(before, after),
        "completed": len(all_lat),
        "failed": len(failures),
        "rejected_429": rejected[0],
        "timeouts": timeouts[0],
        "wall_s": round(wall, 3),
        "throughput_rps": round(len(all_lat) / wall, 3) if wall > 0 else None,
        "latency_p50_s": round(percentile(all_lat, 50), 3),
        "latency_p95_s": round(percentile(all_lat, 95), 3),
        "latency_p99_s": round(percentile(all_lat, 99), 3),
        "latency_max_s": round(max(all_lat), 3) if all_lat else 0.0,
        "serving_dispatches": dispatches,
        "serving_lane_steps": lane_steps,
        "dispatch_amortization": (
            round(lane_steps / dispatches, 3)
            if lane_steps and dispatches else None
        ),
        "serving_batched_fraction": after.get("pa_serving_batched_fraction"),
        "service_p50_s": overall_service,
        "slo": slo_view,
        "hosts": per_host,
        "roles": _role_sections(per_host),
        "fleet": fleet,
        "prompts_lost": prompts_lost,
        "errors": failures[:5],
    }


def print_human_summary(summary: dict, stream=None) -> None:
    """The operator-facing table — stderr by contract, so stdout stays ONE
    JSON line (the same ledger-appendable discipline as bench.py)."""
    stream = stream if stream is not None else sys.stderr
    w = stream.write
    w("── loadgen summary ──────────────────────────────\n")
    w(f"  prompts   {summary['completed']}/{summary['requests']} ok"
      f"  ({summary['failed']} failed, {summary['rejected_429']} rejected,"
      f" {summary.get('timeouts', 0)} timed out)\n")
    w(f"  wall      {summary['wall_s']}s"
      f"  throughput {summary['throughput_rps']} rps\n")
    w(f"  latency   p50 {summary['latency_p50_s']}s"
      f"  p95 {summary['latency_p95_s']}s"
      f"  max {summary['latency_max_s']}s\n")
    for rung in (summary.get("openloop") or {}).get("curve") or []:
        w(f"  openloop  {rung.get('rps_offered')} rps offered"
          f" ({rung.get('completed')}/{rung.get('arrivals')} ok)"
          f"  p50 {rung.get('latency_p50_s')}s"
          f"  p95 {rung.get('latency_p95_s')}s"
          f"  p99 {rung.get('latency_p99_s')}s\n")
    slo_view = summary.get("slo") or {}
    for stage, q in (slo_view.get("stages") or {}).items():
        w(f"  slo-stage {stage:<10} p50 {q.get('p50_s')}s"
          f"  p95 {q.get('p95_s')}s\n")
    if slo_view.get("collect_p50_s") is not None:
        w(f"  slo-stage collect    p50 {slo_view['collect_p50_s']}s"
          f"  p95 {slo_view.get('collect_p95_s')}s  (client residual)\n")
    for name, burn in (slo_view.get("burn_rates") or {}).items():
        w(f"  slo-burn  {name}: {burn}"
          f"{'  [BURNING]' if burn > 1.0 else ''}\n")
    if summary.get("dispatch_amortization") is not None:
        w(f"  serving   {summary['serving_dispatches']:.0f} dispatches,"
          f" {summary['serving_lane_steps']:.0f} lane-steps"
          f" ({summary['dispatch_amortization']}x amortized)\n")
    if summary.get("workload_counts"):
        parts = ", ".join(f"{k}={v}"
                          for k, v in sorted(summary["workload_counts"].items()))
        w(f"  workload  {parts}\n")
    caps = summary.get("lane_capability")
    if caps or summary.get("serving_inline_fallbacks") is not None:
        cap_s = ", ".join(f"{k}={v:.0f}" for k, v in sorted(caps.items())) \
            if caps else "-"
        w(f"  caps      lane-steps by kind: {cap_s}\n")
        w(f"  caps      inline fallbacks "
          f"{summary.get('serving_inline_fallbacks')}"
          f"  ctrl conflicts {summary.get('serving_ctrl_conflicts')}\n")
    if summary.get("embed_cache_hit_rate") is not None or \
            summary.get("encoder_invocations") is not None:
        w(f"  reuse     embed-cache hit rate "
          f"{summary.get('embed_cache_hit_rate')}"
          f"  encoder invocations {summary.get('encoder_invocations')}"
          f" / {summary.get('requests')} prompts"
          f"  (distinct {summary.get('distinct_prompts')})\n")
    if summary.get("decode_batched_fraction") is not None:
        w(f"  reuse     decode batched fraction "
          f"{summary.get('decode_batched_fraction')}"
          f"  ({summary.get('decode_requests')} decodes in "
          f"{summary.get('decode_dispatches')} dispatches)\n")
    if summary.get("fleet"):
        f = summary["fleet"]
        w(f"  fleet     dispatches {f.get('dispatches')}"
          f"  spills {f.get('spills')}  failovers {f.get('failovers')}"
          f"  lost {summary.get('prompts_lost')}\n")
    for role, p in (summary.get("roles") or {}).items():
        disp = (summary.get("fleet") or {}).get("role_dispatches") or {}
        w(f"  role {role:<9} {len(p['hosts'])} hosts  {p['completed']:>3} ok"
          f"  p95 {p.get('latency_p95_s')}s"
          f"  stage-dispatches {disp.get(role)}\n")
    if summary.get("faults_injected") is not None or \
            summary.get("degradations") is not None:
        w(f"  chaos     faults injected {summary.get('faults_injected')}"
          f"  degradation rungs {summary.get('degradations')}\n")
    if summary.get("anomalies_fired") is not None:
        w(f"  anomaly   fired {summary.get('anomalies_fired')}"
          f"  unattributed {summary.get('anomalies_unattributed')}\n")
    if summary.get("roofline_comms_fraction") is not None or \
            summary.get("roofline_host_gap_fraction") is not None:
        w(f"  roofline  comms {summary.get('roofline_comms_fraction')}"
          f"  host-gap {summary.get('roofline_host_gap_fraction')}"
          f"  (fraction of traced wall)\n")
    for hid, h in (summary.get("hosts") or {}).items():
        # Single-server open-loop rows carry no probe fields (dispatches /
        # reachability are fleet-mode diffs) — render what exists.
        role = h.get("role")
        w(f"  host {hid:<20} {h['completed']:>3} ok"
          f"  p50 {h['latency_p50_s']}s  p95 {h['latency_p95_s']}s"
          f"  dispatches {h.get('dispatches')}"
          f"{f'  [{role}]' if role and role != 'all' else ''}"
          f"{'  [UNREACHABLE]' if h.get('reachable') is False else ''}\n")
    for err in summary.get("errors") or []:
        w(f"  error     {err}\n")
    w("─────────────────────────────────────────────────\n")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base", default="http://127.0.0.1:8188")
    ap.add_argument("--graph", required=True,
                    help="workflow JSON file (ComfyUI /prompt API format)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=2,
                    help="prompts per client (closed loop)")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--seed-key", default=None,
                    help="colon path (node:inputs:seed) made unique per prompt")
    ap.add_argument("--samplers", default=None,
                    help="comma list (euler,heun,dpmpp_2m,...) assigned "
                         "round-robin per prompt — the mixed workload the "
                         "stateful-lane scheduler co-batches; requires "
                         "--sampler-key")
    ap.add_argument("--sampler-key", default=None,
                    help="colon path (node:inputs:sampler_name) the "
                         "round-robin sampler is written to")
    ap.add_argument("--prompt-dist", default=None,
                    help="zipf:<s> — sample each submission's prompt TEXT "
                         "from a seeded zipf over the prompt vocabulary "
                         "(written at --prompt-key): the redundant "
                         "production traffic shape the embed cache "
                         "collapses")
    ap.add_argument("--prompt-key", default=None,
                    help="colon path (node:inputs:text) the sampled prompt "
                         "text is written to")
    ap.add_argument("--prompt-vocab", default=None,
                    help="comma list of prompt texts to sample from "
                         "(default: 32 synthetic 'prompt k' strings)")
    ap.add_argument("--seed-fanout", type=int, default=1,
                    help="group submissions into N-seed siblings of one "
                         "sampled prompt (same text, distinct --seed-key "
                         "values) — the shared-cond fanout shape")
    ap.add_argument("--priority", type=int, default=None)
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--seed", type=int, default=None,
                    help="seed the prompt schedule (the values written at "
                         "--seed-key) so a run is reproducible")
    ap.add_argument("--hosts", default=None,
                    help="comma list of backend base URLs: fleet mode — "
                         "--base is the router; summary adds per-host "
                         "latency/dispatch sections, pa_fleet_* deltas, "
                         "and the CI-gated prompts_lost count")
    ap.add_argument("--fallback-bases", default=None,
                    help="comma list of standby router base URLs (router "
                         "HA): clients fail over to them when --base stops "
                         "answering or replies standby-503")
    ap.add_argument("--openloop", default=None,
                    choices=["poisson", "onoff", "replay"],
                    help="OPEN-loop mode: requests fire on a seeded arrival "
                         "schedule regardless of completions — the regime "
                         "where queues grow. poisson/onoff generate from "
                         "--rps/--duration/--seed; replay needs "
                         "--arrivals-in (a saved schedule or a fleet "
                         "journal). Summary becomes a latency-under-load "
                         "curve + SLO decomposition; ledger kind=openloop")
    ap.add_argument("--rps", default="4",
                    help="comma list of offered request rates — one "
                         "open-loop rung (curve point) per rate")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds of arrivals per open-loop rung")
    ap.add_argument("--on-s", type=float, default=1.0,
                    help="onoff arrivals: busy-window seconds")
    ap.add_argument("--off-s", type=float, default=1.0,
                    help="onoff arrivals: silent-window seconds")
    ap.add_argument("--arrivals-out", default=None,
                    help="persist the generated arrival schedule "
                         "(pa-arrivals/v1 JSON) for replay / the twin")
    ap.add_argument("--arrivals-in", default=None,
                    help="replay arrivals from a pa-arrivals/v1 document "
                         "or a recorded fleet journal (submit timestamps)")
    ap.add_argument("--twin-band", type=float, default=0.5,
                    help="declared twin error band: scripts/twin_report.py "
                         "--check fails when |twin p95 - measured p95| / "
                         "measured exceeds this fraction")
    ap.add_argument("--workload-mix", default=None,
                    help="comma list of capability kinds, optional :frac "
                         "each (txt2img,img2img,controlnet,lora:0.25) — "
                         "sample each submission's KIND from the seeded "
                         "mix and submit that kind's graph (see "
                         "--workload-graph); summary gains workload counts "
                         "+ per-kind lane-capability and inline-fallback "
                         "deltas. Closed-loop only")
    ap.add_argument("--workload-graph", action="append", default=None,
                    metavar="KIND=PATH",
                    help="workflow JSON for one mix kind (repeatable); "
                         "kinds without a graph fall back to --graph")
    ap.add_argument("--trace-sample", type=float, default=0.0,
                    help="tag a seeded, prefix-stable fraction of prompts "
                         "(0..1) for full distributed trace capture and "
                         "fetch each one's stitched timeline after it "
                         "completes; summary gains traced_prompts + "
                         "trace_fetch_rate. Closed-loop only")
    args = ap.parse_args()
    if args.trace_sample and args.openloop:
        ap.error("--trace-sample is closed-loop only (no --openloop)")
    workload_mix = parse_workload_mix(args.workload_mix)  # fail fast
    workload_graphs = {}
    for spec in args.workload_graph or []:
        kind, sep, path = spec.partition("=")
        if not sep or kind not in WORKLOAD_KINDS:
            ap.error(f"--workload-graph wants KIND=PATH with KIND one of "
                     f"{', '.join(WORKLOAD_KINDS)}; got {spec!r}")
        with open(path) as f:
            workload_graphs[kind] = json.load(f)
    if (workload_mix or workload_graphs) and args.openloop:
        ap.error("--workload-mix is closed-loop only (no --openloop)")
    if workload_graphs and not workload_mix:
        ap.error("--workload-graph requires --workload-mix")
    samplers = [s for s in (args.samplers or "").split(",") if s]
    if samplers and not args.sampler_key:
        ap.error("--samplers requires --sampler-key (where to write it)")
    hosts = [h for h in (args.hosts or "").split(",") if h]
    prompt_vocab = [p for p in (args.prompt_vocab or "").split(",") if p]
    if args.prompt_dist and not args.prompt_key:
        ap.error("--prompt-dist requires --prompt-key (where to write it)")
    if args.seed_fanout > 1 and not args.prompt_key:
        # Without a prompt key no fanout schedule is built — recording
        # seed_fanout on plain traffic would bank a misleading record.
        ap.error("--seed-fanout requires --prompt-key (where to write it)")
    parse_prompt_dist(args.prompt_dist)  # fail fast on a typo'd spec
    with open(args.graph) as f:
        graph = json.load(f)
    extra = {}
    if args.priority is not None:
        extra["priority"] = args.priority
    if args.deadline_s is not None:
        extra["deadline_s"] = args.deadline_s
    fallback = [b for b in (args.fallback_bases or "").split(",") if b]
    if args.openloop:
        if args.openloop == "replay" and not args.arrivals_in:
            ap.error("--openloop replay requires --arrivals-in")
        arrivals_doc = (_twin.load_arrivals(args.arrivals_in)
                        if args.arrivals_in else None)
        summary = run_open_load(
            args.base, graph, kind=args.openloop,
            rps_list=[float(r) for r in args.rps.split(",") if r],
            duration_s=args.duration, timeout=args.timeout,
            seed=args.seed if args.seed is not None else 0,
            seed_key=args.seed_key, extra_data=extra or None,
            samplers=samplers or None, sampler_key=args.sampler_key,
            hosts=hosts or None, fallback_bases=fallback or None,
            on_s=args.on_s, off_s=args.off_s,
            arrivals_doc=arrivals_doc, arrivals_out=args.arrivals_out,
            twin_band=args.twin_band,
            prompt_dist=args.prompt_dist, prompt_key=args.prompt_key,
            prompt_vocab=prompt_vocab or None,
            seed_fanout=args.seed_fanout,
        )
        _append_ledger(summary, args.base, kind="openloop")
    else:
        summary = run_load(
            args.base, graph, clients=args.clients, requests=args.requests,
            timeout=args.timeout, seed_key=args.seed_key,
            extra_data=extra or None,
            samplers=samplers or None, sampler_key=args.sampler_key,
            seed=args.seed, hosts=hosts or None,
            fallback_bases=fallback or None,
            prompt_dist=args.prompt_dist, prompt_key=args.prompt_key,
            prompt_vocab=prompt_vocab or None,
            seed_fanout=args.seed_fanout,
            workload_mix=workload_mix,
            workload_graphs=workload_graphs or None,
            trace_sample=args.trace_sample,
        )
        # A disaggregated fleet (some backend declared a role) banks its
        # record under kind="roles" — the role-pool CI smoke's gate record;
        # homogeneous runs keep their historical kinds untouched.
        _append_ledger(summary, args.base,
                       kind="roles" if summary.get("roles")
                       else ("mixed" if workload_mix else "loadgen"))
    print_human_summary(summary)          # operator table → stderr
    print(json.dumps(summary))            # THE one JSON line → stdout


if __name__ == "__main__":
    main()
