"""Persistent TPU-evidence watchdog for the flaky axon tunnel.

The tunnel comes and goes in windows of a few minutes (observed: up at
03:45, wedged by 03:52 the same morning). A plain ascending-ladder run
(`measure_tpu.py`) can burn a whole window on cheap rungs or CPU
fallbacks, so this watchdog instead:

1. probes the tunnel in a bounded subprocess every ``--interval`` seconds
   (a wedged tunnel hangs ``import jax``, so the probe must be a child);
2. the moment the probe passes, banks the MISSING evidence artifacts in
   value order — the README-repro headline first:
       zimage_21 > sd15_16 > sdxl_8 > flux_16_int8 > flux_16 > wan_video
       > kernel sweep (bench_kernels --apply) > sampler loop
3. re-probes between artifacts so a mid-window wedge stops the ladder
   instead of cascading CPU fallbacks;
4. exits when everything is banked.

"Banked" means: a ``platform: tpu|axon`` line for the rung in
``BASELINE_measured.json``; a measured tuning table written by the kernel
sweep's ``--apply``; a TPU line in ``SAMPLER_LOOP_BENCH.json``.

Flap-vs-failure policy: a rung/script that fails while a follow-up probe
says the tunnel is STILL UP earns a strike. Strikes deprioritize (other
evidence goes first) and eventually cap at ``_MAX_FAILS``; the cap needs
three strikes because a wedge-then-recover race can hand out one unfairly.
Run it nohup'd for a whole session:

    nohup python scripts/tpu_watchdog.py > /tmp/tpu_watchdog.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
# bench.py owns the platform tuple and evidence-dir override (PA_FAKE_TPU_PLATFORM
# / PA_EVIDENCE_DIR enable the mocked end-to-end dry-run the round-3 window
# showed this pipeline needs before it runs unattended on hardware).
from bench import (  # noqa: E402
    _TPU_PLATFORMS as _TPU,
    _postmortem_path,
    evidence_dir,
    is_banked_tpu_record as _is_fresh,
)

# Highest-value first: the README-repro rung carries the vs_baseline headline
# (reference 26.00 s/it, /root/reference/README.md:54-56). hybrid_sd15 (the
# tpu:0+cpu two-platform chain, SURVEY §7 hard part 1 on real hardware) sits
# after the headline trio: cheap enough for a modest window, less valuable
# than the README repro.
RUNGS = ("zimage_21", "zimage_21_int8", "sd15_16", "sdxl_8", "hybrid_sd15",
         "flux_16_int8", "flux_stream", "flux_16", "wan_video")

def _attemptable(rung: str) -> bool:
    # Every rung survives a forced non-pallas run: the "xla" backend family
    # auto-routes HBM-sized logits through the chunked path (ops/attention.py
    # _xla_chunked_attention), so no shape is xla-unsafe anymore.
    return _FAILS.get(rung, 0) < _MAX_FAILS


sys.path.insert(0, os.path.join(_REPO, "scripts"))

_FAILS: dict[str, int] = {}
# Three strikes: a genuine crash repeats every attempt, while the
# wedge-recovers-before-the-follow-up-probe race must coincide with the same
# key three separate times to cap it unfairly.
_MAX_FAILS = 3
_PALLAS_FAILS = 0
_PALLAS_PROBED = False

# OOM-recovery ladders (VERDICT r3 next-1): when a rung's failure record shows
# resource exhaustion, the next attempt in the SAME window runs one step deeper
# on the sequential-microbatch ladder (bench.py BENCH_MICROBATCH) instead of
# burning a strike on a failure we know how to fix. First entry = the rung's
# own built-in default (no env override).
_MB_LADDERS: dict[str, tuple[int, ...]] = {
    "zimage_21": (3, 7, 21),
    "zimage_21_int8": (3, 7, 21),
    "flux_16_int8": (4, 8, 16),
    # flux_stream OOMs re-carve stage size internally (orchestrator
    # stream-oom demotion) before the microbatch ladder matters; the ladder
    # is the second lever when activations, not weights, are the peak.
    "flux_stream": (4, 8, 16),
    "flux_16": (1, 2, 4, 8),
    "sd15_16": (1, 2, 4),
    "sdxl_8": (1, 2, 4),
}
_MB_IDX: dict[str, int] = {}

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM", "Resource exhausted")


def _looks_oom(rec: dict) -> bool:
    text = f"{rec.get('fallback_stderr', '')} {rec.get('error', '')}"
    return any(m in text for m in _OOM_MARKERS)


def _rung_env(rung: str) -> dict:
    idx = _MB_IDX.get(rung, 0)
    if idx == 0 or rung not in _MB_LADDERS:
        return {}
    return {"BENCH_MICROBATCH": str(_MB_LADDERS[rung][idx])}


def _deepen(rung: str) -> bool:
    """Advance the rung's microbatch ladder; True if there was a deeper step."""
    ladder = _MB_LADDERS.get(rung, ())
    idx = _MB_IDX.get(rung, 0)
    if idx + 1 < len(ladder):
        _MB_IDX[rung] = idx + 1
        _log(f"{rung}: OOM — deepening microbatch to "
             f"{ladder[idx + 1]} for the next attempt")
        return True
    return False


def probe(timeout: int = 90) -> bool:
    code = (
        "import jax, sys; d = jax.devices(); "
        f"sys.exit(0 if d and d[0].platform in {_TPU!r} else 3)"
    )
    try:
        return subprocess.run(
            [sys.executable, "-c", code], env=dict(os.environ),
            capture_output=True, timeout=timeout,
        ).returncode == 0
    except subprocess.TimeoutExpired:
        return False


# (batch, seq, heads) per probe stage — the shapes the remaining rungs
# actually run through the auto backend (head_dim 128 throughout): a 256-token
# smoke, FLUX 1024² joint attention, WAN-video length. Round-3 lesson: the
# 256-token probe passed while the flux_16 rung then hung 30 minutes inside
# its first pallas forward at 4608 tokens — a probe that doesn't cover the
# rung shapes defends nothing.
_PALLAS_PROBE_SHAPES = ((1, 256, 2), (1, 4608, 24), (1, 16384, 12))


def probe_pallas_hardware(timeout: int = 600) -> None:
    """Run the fused flash kernel on the real chip AT THE RUNG SHAPES before
    any rung relies on it (the untuned `auto` backend picks pallas for
    lane-aligned shapes — a wedge there burns a whole 1800s bench timeout per
    attempt). Each shape runs in its own bounded subprocess, cheapest first,
    stopping at the first failure. After two failures on a live tunnel, force
    the safe XLA path for all child runs via ``PA_TPU_ATTENTION_BACKEND``
    (ops/attention.py reads it at import); two, not one, because a
    wedge-then-recover race can fake one."""
    global _PALLAS_PROBED, _PALLAS_FAILS
    if _PALLAS_PROBED or os.environ.get("PA_TPU_ATTENTION_BACKEND"):
        return
    ok, tail = True, ""
    for b, s, h in _PALLAS_PROBE_SHAPES:
        code = (
            "import jax, jax.numpy as jnp\n"
            "from comfyui_parallelanything_tpu.ops.pallas.flash_attention "
            "import flash_attention\n"
            "from comfyui_parallelanything_tpu.utils.metrics import force_ready\n"
            # Guard against the interpreter-mode false positive: a mid-probe
            # flap can land this child on CPU, where interpret=None would
            # auto-select interpreter mode and 'pass' without touching
            # hardware. force_ready, not block_until_ready: the tunnel's
            # block has returned without waiting (bench.py round-3 evidence).
            f"assert jax.devices()[0].platform in {_TPU!r}, 'not on TPU'\n"
            f"q = jnp.ones(({b}, {s}, {h}, 128), jnp.bfloat16)\n"
            "out = flash_attention(q, q, q, scale=0.09, block_q=256,\n"
            "                      block_k=256, interpret=False)\n"
            "force_ready(out)\n"
            "assert out.shape == q.shape\n"
        )
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], env=dict(os.environ), cwd=_REPO,
                capture_output=True, text=True, timeout=timeout,
            )
            ok = proc.returncode == 0
            tail = f"seq={s}: {proc.stderr.strip()[-300:]}"
        except subprocess.TimeoutExpired:
            ok, tail = False, f"pallas probe seq={s} timed out after {timeout}s"
        if not ok:
            break
        _log(f"pallas probe OK at seq={s}")
    if ok:
        _log("pallas hardware probe OK at all rung shapes")
        _PALLAS_PROBED = True
    elif probe():
        _PALLAS_FAILS += 1
        if _PALLAS_FAILS >= 2:
            # Escalation ladder: before giving up on fused attention entirely,
            # probe jax's upstream kernel at the same shapes — round 3 showed
            # the in-repo kernel can wedge where a second implementation may
            # not, and a fused path is worth ~2-5x at FLUX/video lengths.
            fallback = "pallas_jax" if _probe_pallas_jax(timeout) else "xla"
            os.environ["PA_TPU_ATTENTION_BACKEND"] = fallback
            _log(f"pallas hardware probe FAILED {_PALLAS_FAILS}x on a live "
                 f"tunnel — forcing {fallback} attention for all child runs: "
                 f"{tail}")
            _PALLAS_PROBED = True
        else:
            _log(f"pallas hardware probe failed on a live tunnel "
                 f"(1/2 before xla fallback): {tail}")
    else:
        # Tunnel flapped mid-probe: not a kernel verdict. Re-probe next window
        # rather than mislabeling a healthy kernel as broken for the session.
        _log(f"pallas probe inconclusive (tunnel flapped): {tail}")


def _probe_pallas_jax(timeout: int = 600) -> bool:
    """Bounded-subprocess probe of jax's upstream fused kernel at the rung
    shapes (the pallas_jax fallback candidate). True only if every shape runs
    on a real TPU."""
    for b, s, h in _PALLAS_PROBE_SHAPES:
        code = (
            "import jax, jax.numpy as jnp\n"
            "from comfyui_parallelanything_tpu.ops.attention "
            "import _pallas_jax_attention\n"
            "from comfyui_parallelanything_tpu.utils.metrics import force_ready\n"
            f"assert jax.devices()[0].platform in {_TPU!r}, 'not on TPU'\n"
            f"q = jnp.ones(({b}, {s}, {h}, 128), jnp.bfloat16)\n"
            "out = _pallas_jax_attention(q, q, q, 0.09)\n"
            "force_ready(out)\n"
            "assert out.shape == q.shape\n"
        )
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], env=dict(os.environ), cwd=_REPO,
                capture_output=True, text=True, timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            _log(f"pallas_jax probe seq={s} timed out")
            return False
        if proc.returncode != 0:
            _log(f"pallas_jax probe seq={s} failed: "
                 f"{proc.stderr.strip()[-200:]}")
            return False
        _log(f"pallas_jax probe OK at seq={s}")
    return True


def _tpu_records(filename: str):
    """Parsed TPU-measured records from a repo JSON-Lines artifact (all three
    evidence files append one JSON object per line)."""
    path = os.path.join(evidence_dir(), filename)
    if not os.path.exists(path):
        return
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if _is_fresh(rec):
                yield rec


def banked_rungs() -> set[str]:
    return {r.get("rung") for r in _tpu_records("BASELINE_measured.json")}


def _tuning_path() -> str:
    # Mirrors ops/pallas/tuning.py's _PATH resolution. Duplicated ON PURPOSE:
    # importing that module pulls in the package __init__ chain (jax), and a
    # wedged axon tunnel hangs `import jax` — the watchdog process must stay
    # jax-free (see probe()'s subprocess design).
    return os.environ.get("PA_TUNING_PATH") or os.path.join(
        _REPO, "comfyui_parallelanything_tpu", "ops", "pallas", "tuning.json"
    )


def kernels_banked() -> bool:
    """The sweep is banked only when ``--apply`` wrote a measured tuning table
    (its last act): per-shape KERNEL_BENCH.json lines land incrementally, so a
    mid-sweep wedge must read as incomplete, not banked."""
    path = _tuning_path()
    try:
        with open(path) as f:
            return json.load(f).get("source") == "measured"
    except (OSError, json.JSONDecodeError):
        return False


def sampler_banked() -> bool:
    return any(_tpu_records("SAMPLER_LOOP_BENCH.json"))


# Rungs whose banked number may improve once the kernel sweep's measured
# tuning table lands: their attention runs chunked XLA until a measured
# padded-kernel win flips the auto backend (ops/pallas/tuning.py pallas_wins
# head-dim gating). After --apply they get ONE re-run; latest record wins the
# rendered table.
_RETUNE_RUNGS = ("sd15_16", "sdxl_8")

# Chunked-attention sweep (the sd15_16 MFU-budget fixes, BASELINE.md): bench
# the staged {chunk threshold × softmax dtype} combos on the rung the budget
# says is scan-bound, persist the winner to ops/attn_chunk.json so future
# default-env runs (incl. the driver's end-of-round bench) ship it. Sweep
# order mirrors the budget's expectations: bigger blocks first, then bf16
# logits on top.
_CHUNK_SWEEP_RUNG = "sd15_16"
_CHUNK_COMBOS: tuple[dict, ...] = (
    {},
    {"PA_ATTN_CHUNK_ELEMS": str(2**29)},
    {"PA_ATTN_CHUNK_ELEMS": str(2**29), "PA_ATTN_BF16_SOFTMAX": "1"},
    {"PA_ATTN_CHUNK_ELEMS": str(2**30), "PA_ATTN_BF16_SOFTMAX": "1"},
)


def _chunk_tuning_path() -> str:
    return os.environ.get("PA_ATTN_CHUNK_TUNING") or os.path.join(
        _REPO, "comfyui_parallelanything_tpu", "ops", "attn_chunk.json"
    )


def chunk_sweep_banked() -> bool:
    try:
        with open(_chunk_tuning_path()) as f:
            return json.load(f).get("source") == "measured"
    except (OSError, json.JSONDecodeError):
        return False


def _combo_key(combo: dict) -> str:
    return json.dumps(combo, sort_keys=True)


def _chunk_sweep_state() -> tuple[dict[str, dict], dict[str, int]]:
    """(best TPU record per combo, failure count per combo) from
    CHUNK_SWEEP.json — the sweep's own artifact, so losing/partial combo
    measurements never pollute the rung table (latest-wins rendering reads
    BASELINE_measured.json only) and a flap-interrupted sweep resumes where
    it left off instead of re-burning measured combos."""
    path = os.path.join(evidence_dir(), "CHUNK_SWEEP.json")
    done: dict[str, dict] = {}
    fails: dict[str, int] = {}
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                key = _combo_key(rec.get("attn_env", {}))
                if _is_fresh(rec):
                    done[key] = rec
                else:
                    fails[key] = fails.get(key, 0) + 1
    return done, fails


def _run_chunk_sweep() -> None:
    """Measure the staged chunk combos on the sweep rung (resumably), persist
    the winner, then ALWAYS re-run the rung under the persisted table with
    default env — the confirmation run is the only record that lands in
    BASELINE_measured.json, so the rendered number is the shipping
    configuration's, never a losing combo's."""
    from measure_tpu import record_result, run_rung  # noqa: E402

    mb = _rung_env(_CHUNK_SWEEP_RUNG)
    sweep_path = os.path.join(evidence_dir(), "CHUNK_SWEEP.json")
    if not chunk_sweep_banked():
        done, fails = _chunk_sweep_state()
        for combo in _CHUNK_COMBOS:
            key = _combo_key(combo)
            if key in done or fails.get(key, 0) >= 2:
                continue  # measured, or twice-failed (likely OOM) — move on
            rec = run_rung(_CHUNK_SWEEP_RUNG, extra_env={**mb, **combo})
            rec["attn_env"] = combo
            rec["ts"] = time.time()
            with open(sweep_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
            if _is_fresh(rec):
                _log(f"chunk sweep {combo or 'default'}: {rec['value']} s/it")
            else:
                _log(f"chunk sweep {combo or 'default'} failed "
                     f"({rec.get('platform')})")
                if not probe():
                    _log("chunk sweep paused (tunnel down); resumes at the "
                         "unmeasured combos next window")
                    return
        done, fails = _chunk_sweep_state()
        resolved = sum(
            1 for c in _CHUNK_COMBOS
            if _combo_key(c) in done or fails.get(_combo_key(c), 0) >= 2
        )
        if not done or resolved < len(_CHUNK_COMBOS):
            return
        best_key, best_rec = min(
            done.items(), key=lambda kv: float(kv[1]["value"])
        )
        best = json.loads(best_key)
        # Keys the winning combo didn't set are OMITTED: attention.py then
        # serves its own built-in default for them (the watchdog must stay
        # jax-free, so it cannot import the canonical constant — omission is
        # how the two stay in sync when the default wins).
        table = {
            "source": "measured",
            "rung": _CHUNK_SWEEP_RUNG,
            "best_s_it": float(best_rec["value"]),
            "ts": time.time(),
        }
        if "PA_ATTN_CHUNK_ELEMS" in best:
            table["chunk_elems"] = int(best["PA_ATTN_CHUNK_ELEMS"])
        if "PA_ATTN_BF16_SOFTMAX" in best:
            table["bf16_softmax"] = best["PA_ATTN_BF16_SOFTMAX"] == "1"
        with open(_chunk_tuning_path(), "w") as f:
            json.dump(table, f, indent=1)
        _log(f"chunk sweep winner {best or 'default'} "
             f"({best_rec['value']} s/it) — persisted to "
             f"{os.path.basename(_chunk_tuning_path())}")
    # Shipping-config confirmation under the persisted table (also the resume
    # point when a previous window banked the table but lost this run).
    rec = record_result(run_rung(_CHUNK_SWEEP_RUNG, extra_env=mb))
    if _is_fresh(rec):
        _run_script("render_measured.py", timeout=120)
    else:
        _log("chunk sweep confirmation run failed; retries next window")


def stale_after_tuning() -> list[str]:
    """Rungs banked BEFORE the measured tuning table was written."""
    if not kernels_banked():
        return []
    try:
        table_ts = os.path.getmtime(_tuning_path())
    except OSError:
        return []
    stale = []
    for rung in _RETUNE_RUNGS:
        key = f"retune:{rung}"
        recs = [r for r in _tpu_records("BASELINE_measured.json")
                if r.get("rung") == rung]
        if (recs and max(float(r.get("ts", 0)) for r in recs) < table_ts
                and _FAILS.get(key, 0) < _MAX_FAILS):
            stale.append(rung)
    return stale


def _log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def _health_note(timeout: int = 90) -> dict | None:
    """Host/device health at failure time, attached to failed-attempt
    records. Scrapes ``$PA_HEALTH_URL`` (a running server's GET /health)
    when set; otherwise takes a one-shot ``telemetry.health_snapshot`` in a
    BOUNDED child — the snapshot imports jax, and a wedged tunnel hangs that
    import, so it can never run in the watchdog process itself."""
    url = os.environ.get("PA_HEALTH_URL")
    if url:
        try:
            import urllib.request

            with urllib.request.urlopen(url, timeout=timeout) as r:
                return json.loads(r.read())
        except Exception:
            return None
    code = (
        "import json\n"
        "from comfyui_parallelanything_tpu.utils.telemetry "
        "import health_snapshot\n"
        "print(json.dumps(health_snapshot()))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=dict(os.environ), cwd=_REPO,
            capture_output=True, text=True, timeout=timeout,
        )
        if proc.returncode != 0:
            return None
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, json.JSONDecodeError, IndexError):
        return None


def _attempt(rung: str) -> tuple[dict, bool]:
    """One recorded rung attempt. Failed attempts are enriched BEFORE
    banking: the inner child's postmortem-bundle path (bench.py's
    ``POSTMORTEM_BUNDLE=`` stderr marker, preserved in fallback_stderr) and
    a health snapshot — so a dead window's record says what the host and
    chip looked like, not just that the run died."""
    from measure_tpu import record_result, run_rung  # noqa: E402

    rec = run_rung(rung, extra_env=_rung_env(rung))
    ok = _is_fresh(rec)
    if not ok:
        # The bench line itself carries the bundle path on its stale / error /
        # smoke-substitution shapes; the stderr marker is only the fallback
        # (stderr goes through two tail-truncations, which a fat traceback
        # printed after the marker can push it out of).
        if not rec.get("postmortem"):
            bundle = _postmortem_path(rec.get("fallback_stderr", "") or "")
            if bundle:
                rec["postmortem"] = bundle
        note = _health_note()
        if note is not None:
            rec["health"] = note
    return record_result(rec), ok


def _strike(key: str, what: str) -> None:
    """Count a failure observed while a follow-up probe says the tunnel is
    still up — likely the item's own crash, not a flap (see module policy)."""
    if probe():
        _FAILS[key] = _FAILS.get(key, 0) + 1
        _log(f"{what} failed on a live tunnel ({_FAILS[key]}/{_MAX_FAILS})")


def bank_one() -> bool:
    """Run the single highest-value missing artifact. True if anything ran.

    Ordering: fewest strikes first, then declared value order — one unlucky
    flap deprioritizes a rung below clean ones but never blocks the ladder."""
    done = banked_rungs()
    candidates = [r for r in RUNGS if r not in done and _attemptable(r)]
    for rung in sorted(candidates, key=lambda r: (_FAILS.get(r, 0),
                                                  RUNGS.index(r))):
        _log(f"running rung {rung}")
        # _attempt applies the one shared predicate (bench.is_banked_tpu_
        # record — a stale re-emit is old banked evidence, never a fresh
        # measurement) and enriches failures with health + postmortem notes.
        rec, ok = _attempt(rung)
        if ok:
            _run_script("render_measured.py", timeout=120)
        elif _looks_oom(rec) and _deepen(rung):
            pass  # actionable failure with a known fix — no strike
        else:
            if _looks_oom(rec):
                # OOM with the microbatch ladder exhausted: activations are
                # no longer the story — weights + overhead exceed the chip.
                # Measure the chip's actual ceiling once so the evidence
                # records WHY the rung is infeasible (memory_stats() is None
                # on the axon device; nothing else can say).
                _probe_hbm_once()
            _strike(rung, f"rung {rung}")
        _log(f"rung {rung}: platform={rec.get('platform')} "
             f"value={rec.get('value')} banked={ok}")
        return True
    for label, banked, argv in (
        ("kernels", kernels_banked, ("bench_kernels.py", "--apply")),
        ("sampler", sampler_banked, ("bench_sampler_loop.py",)),
    ):
        if banked() or _FAILS.get(label, 0) >= _MAX_FAILS:
            continue
        _log(f"running {label} bench ({argv[0]})")
        _run_script(*argv)
        ok = banked()
        if ok:
            _run_script("render_measured.py", timeout=120)
        else:
            _strike(label, f"{label} bench")
        _log(f"{label} bench done, banked={ok}")
        return True
    for rung in stale_after_tuning():
        _log(f"re-running rung {rung} under the measured tuning table")
        rec, ok = _attempt(rung)
        if ok:
            _run_script("render_measured.py", timeout=120)
        else:
            _strike(f"retune:{rung}", f"retune {rung}")
        _log(f"retune {rung}: platform={rec.get('platform')} "
             f"value={rec.get('value')} banked={ok}")
        return True
    if _chunk_sweep_due():
        _log("running chunked-attention sweep (sd15_16 MFU-budget fixes)")
        _run_chunk_sweep()
        ok = chunk_sweep_banked() and _chunk_confirmed()
        if not ok:
            _strike("chunk_sweep", "chunk sweep")
        _log(f"chunk sweep done, banked={ok}")
        return True
    return False


def _chunk_confirmed() -> bool:
    """A default-env sweep-rung record postdating the persisted table — the
    shipping configuration's number is what the rendered table shows."""
    try:
        table_ts = os.path.getmtime(_chunk_tuning_path())
    except OSError:
        return False
    return any(
        float(r.get("ts", 0)) > table_ts
        for r in _tpu_records("BASELINE_measured.json")
        if r.get("rung") == _CHUNK_SWEEP_RUNG
    )


def _chunk_sweep_due() -> bool:
    """The sweep is worth a window only after the retune flow settles AND the
    chunked path still serves the sweep rung (a kernel-sweep win for 40-dim
    heads would route attention off the scan entirely). A banked table with
    no confirmation run yet keeps the sweep due — the confirmation is the
    resume point."""
    if _FAILS.get("chunk_sweep", 0) >= _MAX_FAILS:
        return False
    if chunk_sweep_banked():
        return not _chunk_confirmed()
    recs = [r for r in _tpu_records("BASELINE_measured.json")
            if r.get("rung") == _CHUNK_SWEEP_RUNG]
    if not recs:
        return False
    latest = max(recs, key=lambda r: float(r.get("ts", 0)))
    return "xla_chunked" in str(latest.get("attention_backend", ""))


_HBM_TRIES = 0
_HBM_MAX_TRIES = 3


def _hbm_probe_path() -> str:
    # Its OWN evidence file, NOT BASELINE_measured.json: a GiB record mixed
    # into the rung file would render as a bogus benchmark row and inflate
    # the banked-rung count (render_measured filters only platform/invalid).
    return os.path.join(evidence_dir(), "HBM_PROBE.json")


def _probe_hbm_once(timeout: int = 600) -> None:
    """Bisect the chip's usable HBM in a bounded child (scripts/probe_hbm.py)
    and bank the result to ``HBM_PROBE.json`` — until it succeeds once
    (bounded retries: a tunnel flap must not forfeit the measurement for the
    session, but the probe costs minutes of window so it can't retry
    forever)."""
    global _HBM_TRIES
    if _HBM_TRIES >= _HBM_MAX_TRIES or os.path.exists(_hbm_probe_path()):
        return
    _HBM_TRIES += 1
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts", "probe_hbm.py")],
            cwd=_REPO, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        _log("hbm probe timed out (wedged tunnel?) — will retry on the next "
             "exhausted-OOM")
        return
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        rec = json.loads(line)
    except json.JSONDecodeError:
        _log(f"hbm probe produced no JSON: {proc.stderr.strip()[-200:]}")
        return
    if "usable_hbm_bytes" in rec:
        rec["ts"] = time.time()
        with open(_hbm_probe_path(), "a") as f:
            f.write(json.dumps(rec) + "\n")
        _HBM_TRIES = _HBM_MAX_TRIES
        _log(f"hbm probe: usable ≈ {rec['value']} GiB "
             f"({rec.get('device_kind', '?')})")
    else:
        _log(f"hbm probe error: {rec}")


def _run_script(name: str, *args: str, timeout: int = 3600) -> None:
    """A hung child (wedged tunnel) must not take the persistent watchdog down
    with it — swallow the timeout; the banked checks decide what happens next."""
    try:
        subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts", name), *args],
            cwd=_REPO, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        _log(f"{name} timed out after {timeout}s (wedged tunnel?)")


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--interval", type=int, default=120,
                    help="seconds between tunnel probes while down")
    ap.add_argument("--skip", default="",
                    help="comma-separated rungs to treat as capped from the "
                         "start (e.g. a rung prior evidence proves infeasible "
                         "on this chip — a restart must not re-burn the "
                         "window climbing its microbatch ladder)")
    ns = ap.parse_args()
    interval = ns.interval
    for rung in filter(None, ns.skip.split(",")):
        if rung not in RUNGS:
            ap.error(f"--skip {rung!r}: not a rung (choices: {RUNGS})")
        _FAILS[rung] = _MAX_FAILS
        _log(f"skipping rung {rung} (--skip)")

    def capped(key: str) -> bool:
        return _FAILS.get(key, 0) >= _MAX_FAILS

    while True:
        done = banked_rungs()
        missing = [r for r in RUNGS if r not in done and _attemptable(r)]
        if (not missing and (kernels_banked() or capped("kernels"))
                and (sampler_banked() or capped("sampler"))
                and not stale_after_tuning()
                and not _chunk_sweep_due()):
            _log("all attemptable TPU evidence banked — exiting")
            return
        if probe():
            _log(f"tunnel UP (missing: {missing or 'kernels/sampler'})")
            probe_pallas_hardware()
            if not bank_one():
                time.sleep(interval)  # nothing attemptable right now
        else:
            _log("tunnel down")
            time.sleep(interval)


if __name__ == "__main__":
    main()
