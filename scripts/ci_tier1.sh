#!/usr/bin/env bash
# Tier-1 CI gate: the ROADMAP.md verify command (virtual-mesh CPU test
# suite), then the perf-ledger regression check (scripts/perf_ledger.py
# --check — step-time / peak-HBM drift against the banked evidence). Either
# failing fails the script, so a green run means both "tests pass" AND
# "no unexplained performance regression in the ledger".
set -o pipefail
cd "$(dirname "$0")/.."

# Static analysis FIRST (round 16): scripts/palint.py --check is stdlib-only
# and finishes in ~2s — a standalone-contract drift, an unguarded shared
# write, an undocumented metric/env/fault-site/span-cat, or a host-sync
# violation fails the run before the 38-minute suite spends a single dot.
env -u PALLAS_AXON_POOL_IPS python scripts/palint.py --check || {
    echo "ci_tier1: palint static-analysis gate FAILED" >&2; exit 1; }

# Per-run log (not a fixed /tmp name: concurrent runs must not clobber each
# other's DOTS_PASSED count, and another user's stale file must not wedge tee).
t1log=$(mktemp /tmp/_t1.XXXXXX.log)
trap 'rm -f "$t1log"' EXIT
timeout -k 10 870 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$t1log"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$t1log" | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "ci_tier1: tier-1 tests FAILED (rc=$rc)" >&2
    exit "$rc"
fi

env -u PALLAS_AXON_POOL_IPS python scripts/perf_ledger.py --check || exit $?

# Numerics drift gate (round 11): latest banked fingerprint per rung vs
# the golden bank (scripts/numerics_audit.py) — latent-fingerprint drift
# or a nonzero nonfinite_events count fails CI exactly like a perf
# regression; an empty/unfingerprinted ledger is SKIP, never a failure.
env -u PALLAS_AXON_POOL_IPS python scripts/numerics_audit.py --check || exit $?

# Roofline schema gate (round 13): the latest roofline-carrying ledger
# record per (rung, platform) must keep roofline_ratio in (0, 1.2] and its
# attribution buckets non-negative, summing to the recorded wall
# (scripts/roofline_report.py — an empty/unroofed ledger is SKIP, never a
# failure). Runs after the perf and numerics gates: same ledger, third lens.
env -u PALLAS_AXON_POOL_IPS python scripts/roofline_report.py --check || exit $?

# Plan gate (round 18): the latest kind=plan ledger record per (rung,
# platform) must match-or-beat the shadow hand-rule plan by predicted
# score and keep predicted-vs-actual inside the (0, 1.2] calibration band
# (scripts/plan_report.py reads the planner decisions bench/dryrun banked
# — a plan-free ledger is SKIP, never a failure). Runs right after the
# roofline gate: same ledger, the routing lens.
env -u PALLAS_AXON_POOL_IPS python scripts/plan_report.py --check || exit $?

# Traffic-twin gate (round 15): the latest kind=openloop ledger record per
# group must keep |twin p95 - measured p95| / measured within the record's
# declared error band (scripts/twin_report.py replays the seeded arrival
# trace through fleet/twin.py against roofline/measured per-host capacity —
# an openloop-free ledger is SKIP, never a failure). Fourth ledger lens,
# after the roofline gate whose calibration store it reads.
env -u PALLAS_AXON_POOL_IPS python scripts/twin_report.py --check || exit $?

# Anomaly-attribution gate (round 22): every kind=anomaly ledger record the
# online sentinel (utils/anomaly.py) banked must be ATTRIBUTED — explained
# by a declared fault site or load phase (scripts/anomaly_report.py — an
# anomaly-free ledger is SKIP, never a failure: a clean run firing zero is
# the other half of the contract). Fifth ledger lens, after the twin gate.
env -u PALLAS_AXON_POOL_IPS python scripts/anomaly_report.py --check || exit $?

# Sampler-coverage gate (round 10): one explicit pass over the lane-vs-solo
# equivalence matrix + the registry coverage check, so a LaneStepSpec wired
# into sampling/lane_specs.py but unverified (or missing from
# BATCHABLE_SAMPLERS) fails CI loudly even if someone narrows the main run's
# -m/-k selection. These tests are also part of the tier-1 run above; this
# rerun is the contract, not the coverage.
timeout -k 10 600 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/test_serving.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly \
    -k "LaneEquivalenceMatrix or MixedSamplerDispatch or RegistryCoverage"
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# Fleet smoke (round 12): a 2-backend fleet — router + scripts/loadgen.py
# fleet mode, ~10 prompts on CPU — gated on prompts_lost == 0 plus full
# per-host attribution (tests/test_fleet.py::TestFleetSmoke). The fleet
# tier's one non-negotiable: the front door never loses a prompt. Also part
# of the tier-1 run above; this rerun is the explicit contract.
timeout -k 10 300 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/test_fleet.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly -k "FleetSmoke or Failover"
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# Lock-order gate (round 16): the FULL fleet + serving suites under
# PA_LOCKCHECK=1 — utils/lockcheck.py wraps every repo lock construction
# and conftest's autouse fixture fails the first test whose code paths
# close a cycle in the acquisition-order graph (a potential deadlock even
# when CI never schedules the interleaving that fires it). The -k reruns
# above stay uninstrumented; THIS step is the documented zero-cycle gate
# over the threaded tier, and the chaos smoke below extends it to the
# fault-injection paths.
timeout -k 10 600 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PA_LOCKCHECK=1 \
    python -m pytest tests/test_fleet.py tests/test_serving.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# Reuse smoke (round 17): the cross-request compute-reuse gate — a
# zipf(s=1.1) prompt mix through a live 4-worker server must show the
# embed cache collapsing the encode stage (embed_cache_hit_rate > 0,
# encoder_invocations <= 0.5x prompts, prompts_lost == 0), an 8-seed
# fanout must cost exactly ceil(8/width) shared dispatches with latents
# bitwise-equal to solo (the shared-cond broadcast program), and the
# batched decode tail must be engaged — all banked as a kind="reuse"
# ledger record (tests/test_reuse.py::TestReuseSmoke). The unit tier
# (LRU byte bound, demotion correctness, decode allclose) reruns with it.
timeout -k 10 600 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/test_reuse.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# Mixed-workload smoke (round 19): scripts/loadgen.py --workload-mix drives
# txt2img + img2img(mask) + controlnet + lora traffic through one live
# 4-worker server — gated on prompts_lost == 0, run-delta shared-dispatch
# fraction >= 0.8, zero inline fallbacks / control-trunk conflicts for
# eligible shapes, every capability kind ticking its
# pa_serving_lane_capability_total delta, and the kind="mixed" ledger
# record landing (tests/test_loadgen_mix.py — slow-marked, so THIS block is
# where the universal-lane-batching contract actually runs).
timeout -k 10 600 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/test_loadgen_mix.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# Role-pool smoke (round 20): 1 encode + 2 denoise + 1 decode virtual hosts
# vs 4 homogeneous backends under the SAME mixed load at the SAME host
# count (the BASELINE "Role-pool protocol" comparison rule) — gated on
# prompts_lost == 0, strictly higher disaggregated throughput, the decode
# stage p95 dropping below the homogeneous baseline, and the kind="roles"
# ledger record landing; plus the staged-dispatch e2e tier (pool-respecting
# placement, bitwise vs single-host, mid-denoise role-host kill) and the
# decode-tier kill with standby takeover re-dispatching from the journaled
# denoise handle (tests/test_fleet.py::TestStageLineageReplay — the
# stage-lineage contract). Also part of the tier-1 run above; this rerun is
# the explicit contract.
timeout -k 10 600 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/test_roles.py tests/test_fleet.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    -k "RolePool or StageLineageReplay"
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# Forensics gate (round 21): the role-pool failover e2e reruns with
# PA_FORENSICS_DUMP set, banking its stitched /fleet/trace document + the
# client-observed wall; scripts/explain.py --check then gates the
# conservation contract on that prompt — stitched trace fetched (>= 3
# host-labeled tracks under ONE trace_id across the mid-denoise failover),
# every critical-path bucket non-negative, buckets summing to the client
# wall within 10%. The explain step is stdlib-only (standalone-contract:
# it must hold over a wedged tunnel).
fdump=$(mktemp /tmp/_forensics.XXXXXX.json)
trap 'rm -f "$t1log" "$fdump"' EXIT
timeout -k 10 300 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PA_FORENSICS_DUMP="$fdump" \
    python -m pytest tests/test_roles.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly -k "RequestForensics"
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"
env -u PALLAS_AXON_POOL_IPS python scripts/explain.py --check \
    --trace-file "$fdump" --min-hosts 3 || {
    echo "ci_tier1: request-forensics explain gate FAILED" >&2; exit 1; }

# Telemetry-plane smoke (round 22): the continuous-telemetry contract —
# history-ring byte bound + reset-aware readers, deterministic sentinel
# firing with fault attribution and a postmortem carrying the history
# window, /metrics/history + /fleet/history with a dead host serving its
# cached window marked stale, and scripts/console.py --once --json
# rendering every live host's sparkline data off a real 2-backend fleet
# (tests/test_telemetry_plane.py). Also part of the tier-1 run above;
# this rerun is the explicit contract.
timeout -k 10 600 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/test_telemetry_plane.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# Chaos smoke (round 14): a seeded fault plan (backend-http 5xx +
# slow-host, deterministic in the seed) fired against a 2-backend fleet
# while the PRIMARY ROUTER is killed mid-denoise (standby takeover off the
# durable prompt journal, fleet/journal.py) and one backend is killed —
# gated on prompts_lost == 0, every latent bitwise-equal to the fault-free
# baseline, bounded p95 inflation, and every injected fault attributable
# (pa_fault_injected_total); plus an injected stream-OOM absorbed by the
# re-carve degradation rung on a real weight-streamed model
# (tests/test_chaos.py drives scripts/chaos.py in-process). Also part of
# the tier-1 run above; this rerun is the explicit contract. Round 16 runs
# it under PA_LOCKCHECK=1: utils/lockcheck.py records the lock-acquisition-
# order graph across the whole router+standby+backends fleet under fault
# injection, the chaos verdict carries lock_cycles, and conftest fails any
# test that leaves a cycle — the dynamic half of palint's lock-discipline
# pass, gated on ZERO potential deadlocks.
timeout -k 10 600 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PA_LOCKCHECK=1 \
    python -m pytest tests/test_chaos.py -q -p no:cacheprovider \
    -p no:xdist -p no:randomly
