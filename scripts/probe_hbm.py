"""Measure the tunnel chip's USABLE HBM by binary-searching one allocation.

Why this exists: the axon TPU device returns ``memory_stats() is None``
(verify skill gotchas), so nothing reports how much HBM a rung can actually
use — and this round the bf16 ``zimage_21`` rung (10.8 GiB weights) hit
runtime RESOURCE_EXHAUSTED even fully sequential (batch-1 microbatches),
which is only explainable if usable HBM is well under a full v5e's 16 GiB.
This probe turns that inference into a measured number the evidence file can
carry: bisect the largest single bf16 buffer that places AND survives a
readback, print ONE JSON line.

Run it in a bounded subprocess (a wedged tunnel hangs ``import jax``):

    timeout 600 python scripts/probe_hbm.py

Readback, not ``block_until_ready``: the tunnel's async dispatch has returned
from ``block_until_ready`` in 2.8 ms for a 43-TFLOP step (bench.py evidence),
so only a host readback proves the buffer really exists on the chip. A single
buffer understates usable memory slightly (allocator headroom/fragmentation)
but bounds the answer the right way: what one replicated param pytree can
actually hold is at most this.
"""

from __future__ import annotations

import json
import sys

GIB = 1 << 30
RESOLUTION = 256 << 20  # 256 MiB
CEILING = 40 * GIB


def _try_alloc(nbytes: int) -> bool:
    import jax
    import jax.numpy as jnp

    n = max(nbytes // 2, 1)  # bf16 elements
    try:
        buf = jax.device_put(
            jnp.zeros((n,), jnp.bfloat16), jax.devices()[0]
        )
        # Force materialization with a tiny readback touching the far end.
        float(jnp.asarray(buf[-1].astype(jnp.float32)))
        del buf
        return True
    except Exception as e:  # noqa: BLE001 — any failure counts as "does not fit"
        markers = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                   "Resource exhausted", "OOM")
        if not any(m in str(e) for m in markers):
            raise
        return False


def main() -> None:
    import jax

    dev = jax.devices()[0]
    if dev.platform not in ("tpu", "axon"):
        print(json.dumps({"error": f"not a TPU (platform={dev.platform})"}))
        sys.exit(3)

    lo = 0  # known-fits; hi = known-doesn't-fit (or the declared ceiling)
    # Exponential phase up from 1 GiB, then bisect. Clamp hi to CEILING so
    # the bisect never wastes window time on allocations above the module's
    # own stated bound.
    probe = GIB
    while probe < CEILING and _try_alloc(probe):
        lo, probe = probe, probe * 2
    hi = min(probe, CEILING)
    while hi - lo > RESOLUTION:
        mid = (lo + hi) // 2
        if _try_alloc(mid):
            lo = mid
        else:
            hi = mid
    print(json.dumps({
        "metric": "usable HBM (largest single bf16 buffer)",
        "value": round(lo / GIB, 2),
        "unit": "GiB",
        "usable_hbm_bytes": lo,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "rung": "hbm_probe",
    }))


if __name__ == "__main__":
    main()
