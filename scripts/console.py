"""Live ops console: the fleet's telemetry plane in one refreshing screen.

Renders ``GET /fleet/history`` (the router's merged per-host metric
history, round 22) + ``GET /fleet/slo`` + ``GET /fleet/hosts`` as a
terminal dashboard: one block per host with unicode sparklines of queue
depth, SLO burn, HBM watermark, per-interval mean step time and disk
append latency, the host's ACTIVE anomaly signals
(``pa_anomaly_active``), role occupancy, and the fleet's SLO verdicts.
A dead host renders its cached window marked STALE — the console
degrades exactly like the plane it watches, never blanks.

Pointed at a plain ``server.py`` (no router), it falls back to that
host's own ``GET /metrics/history`` and renders a one-host fleet.

Modes:
- default          refresh every ``--interval`` seconds until Ctrl-C
- ``--once``       render one frame and exit (CI smoke)
- ``--once --json``  print the frame as ONE JSON document instead of a
                   screen — scriptable, diffable, no ANSI

Stdlib-only and jax-free by construction (the standalone-contract pass
checks all of ``scripts/``): it must run on a laptop holding nothing but
a URL to the front door.

Usage:
    python scripts/console.py --base http://127.0.0.1:8188
        [--window 600] [--interval 2] [--once] [--json]
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request

BLOCKS = "▁▂▃▄▅▆▇█"

# signal → (family, reduction) rendered per host, top to bottom.
# gauge-sum/max reduce the point's label values; hist-mean is the
# per-interval mean from consecutive (sum, count) histogram deltas.
SIGNALS = (
    ("queue", "pa_server_queue_pending", "gauge-sum"),
    ("burn", "pa_slo_burn_rate", "gauge-max"),
    ("hbm", "pa_hbm_utilization", "gauge-max"),
    ("step_s", "pa_serving_step_seconds", "hist-mean"),
    ("disk_s", "pa_disk_append_seconds", "hist-mean"),
)


def _get(base: str, path: str, timeout: float = 10):
    with urllib.request.urlopen(base.rstrip("/") + path,
                                timeout=timeout) as r:
        return json.loads(r.read())


def spark(series: list) -> str:
    """Min-max scaled unicode sparkline; None samples render as gaps."""
    vals = [v for v in series if v is not None]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    out = []
    for v in series:
        if v is None:
            out.append(" ")
            continue
        frac = 0.0 if hi <= lo else (v - lo) / (hi - lo)
        out.append(BLOCKS[min(len(BLOCKS) - 1,
                              int(frac * (len(BLOCKS) - 1) + 0.5))])
    return "".join(out)


def _series(fam: dict, mode: str) -> list:
    pts = fam.get("points") or []
    if mode == "hist-mean":
        out: list = []
        prev = None
        for p in pts:
            tot_sum = tot_cnt = 0.0
            for v in (p.get("values") or {}).values():
                if isinstance(v, list) and len(v) >= 2:
                    tot_sum += v[-2]
                    tot_cnt += v[-1]
            if prev is not None:
                ds, dc = tot_sum - prev[0], tot_cnt - prev[1]
                out.append(ds / dc if dc > 0 else None)
            prev = (tot_sum, tot_cnt)
        return out
    out = []
    for p in pts:
        vals = [v for v in (p.get("values") or {}).values()
                if isinstance(v, (int, float))]
        if not vals:
            out.append(None)
        elif mode == "gauge-max":
            out.append(max(vals))
        else:
            out.append(sum(vals))
    return out


def _active_anomalies(window: dict) -> list[str]:
    fam = (window.get("families") or {}).get("pa_anomaly_active") or {}
    pts = fam.get("points") or []
    if not pts:
        return []
    out = []
    for lbl, v in (pts[-1].get("values") or {}).items():
        if isinstance(v, (int, float)) and v >= 1:
            m = re.search(r'signal="([^"]*)"', lbl)
            out.append(m.group(1) if m else lbl)
    return sorted(out)


def _host_view(window: dict | None) -> dict:
    """One host's console block from its pa-history/v1 window."""
    if not window:
        return {"signals": {}, "anomalies": [], "points": 0}
    fams = window.get("families") or {}
    signals = {}
    for name, family, mode in SIGNALS:
        fam = fams.get(family)
        if not fam:
            continue
        series = _series(fam, mode)
        shown = [None if v is None else round(float(v), 6) for v in series]
        last = next((v for v in reversed(shown) if v is not None), None)
        signals[name] = {"family": family, "last": last,
                         "series": shown, "spark": spark(shown)}
    return {
        "signals": signals,
        "anomalies": _active_anomalies(window),
        "points": (window.get("stats") or {}).get("points", 0),
        "phases": [p.get("label") for p in (window.get("phases") or [])
                   if p.get("state") == "begin"][-3:],
    }


def build_frame(base: str, window_s: float | None) -> dict:
    """One console frame: fetch + reduce. Raises only when even the
    single-host fallback is unreachable."""
    q = f"?window={window_s:g}" if window_s else ""
    fleet = None
    try:
        fleet = _get(base, "/fleet/history" + q)
    except (urllib.error.URLError, OSError, ValueError):
        fleet = None
    if fleet is None or "hosts" not in fleet:
        # Single-host fallback: a plain server.py front door.
        own = _get(base, "/metrics/history" + q)
        fleet = {"schema": "pa-fleet-history/v1",
                 "router_id": None,
                 "enabled": own.get("enabled"),
                 "hosts": {own.get("host") or base: {
                     "window": own, "stale": False, "age_s": 0.0}}}
    hosts = {}
    for hid, h in sorted((fleet.get("hosts") or {}).items()):
        view = _host_view(h.get("window"))
        view["stale"] = bool(h.get("stale"))
        view["age_s"] = h.get("age_s")
        hosts[hid] = view
    slo = None
    try:
        slo = _get(base, "/fleet/slo")
    except (urllib.error.URLError, OSError, ValueError):
        pass
    roles = None
    try:
        doc = _get(base, "/fleet/hosts")
        roles = (doc.get("roles") or {}) or None
    except (urllib.error.URLError, OSError, ValueError):
        pass
    frame = {
        "schema": "pa-console/v1",
        "base": base,
        "router_id": fleet.get("router_id"),
        "enabled": fleet.get("enabled"),
        "hosts": hosts,
        "roles": roles,
    }
    if isinstance(slo, dict):
        frame["slo"] = {
            "objectives": [
                {"name": o.get("name"), "ok": o.get("ok"),
                 "burn_rate": o.get("burn_rate"),
                 "achieved_fraction": o.get("achieved_fraction")}
                for o in slo.get("objectives") or []
            ],
        }
    if "router" in (fleet or {}):
        frame["router"] = _host_view(fleet["router"])
    return frame


def render(frame: dict, out=sys.stdout) -> None:
    w = out.write
    w(f"── pa console ── {frame['base']}"
      f"{'  (history disabled)' if frame.get('enabled') is False else ''}\n")
    for o in (frame.get("slo") or {}).get("objectives") or []:
        mark = {True: "ok", False: "VIOLATED", None: "—"}[o.get("ok")]
        w(f"  slo {o['name']:<14} {mark:<9}"
          f" burn {o.get('burn_rate')}"
          f"  achieved {o.get('achieved_fraction')}\n")
    for role, p in (frame.get("roles") or {}).items():
        if isinstance(p, dict):
            n = len(p.get("hosts") or []) or p.get("n_hosts")
            w(f"  role {role:<10} {n} host(s)\n")
    for hid, h in (frame.get("hosts") or {}).items():
        tag = " [STALE]" if h.get("stale") else ""
        anom = (" ⚠ " + ",".join(h["anomalies"])) if h.get("anomalies") \
            else ""
        w(f"  host {hid}{tag}{anom}  ({h.get('points')} samples"
          f"{', phases ' + '>'.join(h['phases']) if h.get('phases') else ''}"
          f")\n")
        for name, s in (h.get("signals") or {}).items():
            w(f"    {name:<7} {s['spark']:<24} last {s['last']}\n")
    w("──\n")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base", default="http://127.0.0.1:8188",
                    help="router (or plain server) base URL")
    ap.add_argument("--window", type=float, default=600.0,
                    help="history window in seconds")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh cadence (loop mode)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--json", action="store_true",
                    help="with --once: print the frame as one JSON doc")
    args = ap.parse_args()

    if args.once:
        try:
            frame = build_frame(args.base, args.window)
        except (urllib.error.URLError, OSError, ValueError) as e:
            sys.stderr.write(f"console: {args.base} unreachable: {e}\n")
            return 1
        if args.json:
            print(json.dumps(frame))
        else:
            render(frame)
        return 0
    try:
        while True:
            try:
                frame = build_frame(args.base, args.window)
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                render(frame)
            except (urllib.error.URLError, OSError, ValueError) as e:
                sys.stdout.write(f"console: {args.base} unreachable: {e}\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
