"""Per-layer time breakdown of a captured trace — stdlib-only.

Reads a Chrome/Perfetto trace-event JSON (``bench.py --trace-out``, the
server's ``GET /trace``, or a ``utils/tracing.py`` export written to disk)
and prints where the time went: total/mean span time per layer (the ``cat``
field: server / graph / sampling / serving / stream / bench), the busiest
span names, the trace-derived aggregates — stream overlap efficiency,
lane-wait p95, host gap — and the numerics sentinel's counters (non-finite
events by site, quarantines) recorded as instant ``numerics``-cat spans.

Stdlib-only by contract (it must run on a laptop holding just the trace
file, no jax): the aggregate math re-implements
``utils/tracing.trace_aggregates``; ``tests/test_observability.py`` pins the
two against each other on the same fixture so they cannot drift.

Usage:
    python scripts/trace_summary.py trace.json [--json] [--prompt-id ID]
"""

from __future__ import annotations

import argparse
import json
from collections import defaultdict

# The span-category vocabulary (the ``cat`` field of every emitted span) —
# this tuple is the OWNING REGISTRY: palint's registry-consistency pass
# fails CI on any span site whose category is missing here, and on any
# entry no span site uses, so the per-layer table above can never grow a
# silent `?` row. One entry per layer:
SPAN_CATEGORIES = (
    "host",       # utils/tracing.py default — uncategorized host work
    "server",     # server.py prompt / admission-wait spans
    "graph",      # host.py workflow-node spans
    "sampling",   # sampling/runner.py sampler-run + eager step spans
    "serving",    # serving/bucket.py dispatch/lane/step spans
    "stream",     # parallel/streaming.py run/prefetch/wait/compute spans
    "bench",      # bench.py timed-iteration step spans
    "compile",    # utils/telemetry.py instrument_jit compile spans
    "fleet",      # fleet/router.py fleet-prompt / fleet-hop spans
    "numerics",   # utils/numerics.py nonfinite-event / quarantine instants
    "faults",     # utils/faults.py fault-injected instants
    "anomaly",    # utils/anomaly.py sentinel-firing instants
    "degrade",    # utils/degrade.py degradation-rung instants
    "profiler",   # utils/tracing.hardware_trace jax.profiler bracket
)


def load_events(path: str) -> list[dict]:
    with open(path) as f:
        data = json.load(f)
    events = data.get("traceEvents", data) if isinstance(data, dict) else data
    return [e for e in events if e.get("ph") == "X"]


def percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile (the scripts/loadgen.py convention)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = max(0, min(len(s) - 1, round(q / 100.0 * (len(s) - 1))))
    return s[k]


def stream_overlap_efficiency(events: list[dict]) -> float | None:
    """Mirror of utils/tracing.stream_overlap_efficiency (drift-pinned by
    test): Σ stream-stage-compute / stream-run wall time, mean over runs."""
    runs = [e for e in events
            if e["name"] == "stream-run" and e.get("dur", 0) > 0]
    if not runs:
        return None
    comps = [e for e in events if e["name"] == "stream-stage-compute"]
    effs = []
    for r in runs:
        r0, r1 = r["ts"], r["ts"] + r["dur"]
        busy = sum(c["dur"] for c in comps
                   if c["tid"] == r["tid"] and c["ts"] >= r0
                   and c["ts"] + c["dur"] <= r1 + 1.0)
        effs.append(min(1.0, busy / r["dur"]))
    return sum(effs) / len(effs)


def lane_wait_p95_s(events: list[dict]) -> float | None:
    waits = [e["dur"] / 1e6 for e in events if e["name"] == "lane-wait"]
    return percentile(waits, 95) if waits else None


def host_gap_ms(events: list[dict]) -> float | None:
    steps: dict[int, list[dict]] = defaultdict(list)
    for e in events:
        if e["name"] == "step":
            steps[e["tid"]].append(e)
    gaps = []
    for evs in steps.values():
        evs.sort(key=lambda e: e["ts"])
        for a, b in zip(evs, evs[1:]):
            gaps.append(max(0.0, b["ts"] - (a["ts"] + a["dur"])) / 1e3)
    return sum(gaps) / len(gaps) if gaps else None


def _load_roofline():
    """utils/roofline.py loaded standalone by file path — its module level
    is stdlib-only and free of package-relative imports by contract (the
    scripts/roofline_report.py loader), so the bucket-decomposition math
    has ONE implementation instead of a hand-maintained mirror. The
    trace-aggregate functions above predate that contract and stay mirrored
    (drift-pinned by tests/test_observability.py)."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "comfyui_parallelanything_tpu", "utils", "roofline.py",
    )
    spec = importlib.util.spec_from_file_location("pa_roofline_ts", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_roofline = _load_roofline()


def attribution(events: list[dict]) -> dict | None:
    """utils/roofline.attribution_from_trace over the whole trace window,
    plus the two headline fractions (comms / host-gap — where the
    non-compute time went). Streamed windows measure compute directly and
    leave host-gap residual; async dispatch windows measure the host gaps
    and leave compute residual — see the roofline module for the bucket
    contract."""
    attr = _roofline.attribution_from_trace(events)
    if attr is None:
        return None
    fr = _roofline.attribution_fractions(attr)
    return {
        **attr,
        "comms_fraction": fr["comms_fraction"],
        "host_gap_fraction": fr["host_gap_fraction"],
    }


def chaos_counts(events: list[dict]) -> dict:
    """Chaos-tier spans (round 14): ``fault-injected`` instants from the
    injection registry (utils/faults.py) and ``degradation`` instants from
    the ladder (utils/degrade.py) — a captured trace proves what was
    injected and what gracefully degraded, by site and by rung."""
    faults = [e for e in events if e["name"] == "fault-injected"]
    rungs = [e for e in events if e["name"] == "degradation"]
    by_site: dict[str, int] = defaultdict(int)
    for e in faults:
        by_site[str(e.get("args", {}).get("site", "?"))] += 1
    by_rung: dict[str, int] = defaultdict(int)
    for e in rungs:
        by_rung[str(e.get("args", {}).get("rung", "?"))] += 1
    return {
        "faults_injected": len(faults),
        "faults_by_site": dict(sorted(by_site.items())),
        "degradations": len(rungs),
        "degradations_by_rung": dict(sorted(by_rung.items())),
    }


def numerics_counts(events: list[dict]) -> dict:
    """Numerics sentinel spans (utils/numerics.py records an instant span
    per non-finite observation / quarantine when tracing is on) — so a
    captured trace carries its own numeric-health verdict offline."""
    nonfinite = [e for e in events if e["name"] == "nonfinite-event"]
    quarantines = [e for e in events if e["name"] == "quarantine"]
    by_where: dict[str, int] = defaultdict(int)
    for e in nonfinite:
        by_where[str(e.get("args", {}).get("where", "?"))] += 1
    return {
        "nonfinite_events": len(nonfinite),
        "quarantines": len(quarantines),
        "nonfinite_by_where": dict(sorted(by_where.items())),
    }


def forensics_counts(events: list[dict]) -> dict:
    """Request-forensics span attrs (round 21): the tracer stamps every
    span that runs under an inbound traceparent with ``trace_id``, and the
    router's fleet-hop / stage-dispatch spans carry ``role`` + ``pool``
    labels.  Reported as NEW keys only — the pinned aggregate keys above
    (stream overlap, lane-wait p95, host gap) are untouched."""
    trace_ids = set()
    by_role: dict[str, int] = defaultdict(int)
    by_pool: dict[str, int] = defaultdict(int)
    by_host: dict[str, int] = defaultdict(int)
    for e in events:
        args = e.get("args", {})
        tid = args.get("trace_id")
        if tid is not None:
            trace_ids.add(str(tid))
        if args.get("role") is not None:
            by_role[str(args["role"])] += 1
        if args.get("pool") is not None:
            by_pool[str(args["pool"])] += 1
        if args.get("host") is not None:
            by_host[str(args["host"])] += 1
    return {
        "trace_ids": len(trace_ids),
        "spans_by_role": dict(sorted(by_role.items())),
        "spans_by_pool": dict(sorted(by_pool.items())),
        "spans_by_host": dict(sorted(by_host.items())),
    }


def summarize(events: list[dict]) -> dict:
    by_cat: dict[str, list[float]] = defaultdict(list)
    by_name: dict[str, list[float]] = defaultdict(list)
    for e in events:
        by_cat[e.get("cat", "?")].append(e.get("dur", 0.0))
        by_name[e["name"]].append(e.get("dur", 0.0))
    eff = stream_overlap_efficiency(events)
    p95 = lane_wait_p95_s(events)
    gap = host_gap_ms(events)
    return {
        "numerics": numerics_counts(events),
        "chaos": chaos_counts(events),
        "forensics": forensics_counts(events),
        "spans": len(events),
        "layers": {
            cat: {
                "spans": len(durs),
                "total_ms": round(sum(durs) / 1e3, 3),
                "mean_ms": round(sum(durs) / len(durs) / 1e3, 3),
                "max_ms": round(max(durs) / 1e3, 3),
            }
            for cat, durs in sorted(
                by_cat.items(), key=lambda kv: -sum(kv[1])
            )
        },
        "top_spans": {
            name: {
                "count": len(durs),
                "total_ms": round(sum(durs) / 1e3, 3),
                "p95_ms": round(percentile(durs, 95) / 1e3, 3),
            }
            for name, durs in sorted(
                by_name.items(), key=lambda kv: -sum(kv[1])
            )[:12]
        },
        "stream_overlap_efficiency": None if eff is None else round(eff, 4),
        "lane_wait_p95": None if p95 is None else round(p95, 6),
        "host_gap_ms": None if gap is None else round(gap, 4),
        # Roofline bucket decomposition of the traced window (comms and
        # host-gap fractions included — where the non-compute time went).
        "attribution": attribution(events),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", help="trace-event JSON file")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary (one JSON object)")
    ap.add_argument("--prompt-id", default=None,
                    help="restrict to one prompt's spans")
    args = ap.parse_args()
    events = load_events(args.trace)
    if args.prompt_id is not None:
        events = [e for e in events
                  if e.get("args", {}).get("prompt_id") == args.prompt_id]
    s = summarize(events)
    if args.json:
        print(json.dumps(s))
        return
    print(f"{s['spans']} spans")
    print(f"{'layer':<10} {'spans':>6} {'total ms':>10} {'mean ms':>9} "
          f"{'max ms':>9}")
    for cat, row in s["layers"].items():
        print(f"{cat:<10} {row['spans']:>6} {row['total_ms']:>10.3f} "
              f"{row['mean_ms']:>9.3f} {row['max_ms']:>9.3f}")
    print()
    print(f"{'span':<24} {'count':>6} {'total ms':>10} {'p95 ms':>9}")
    for name, row in s["top_spans"].items():
        print(f"{name:<24} {row['count']:>6} {row['total_ms']:>10.3f} "
              f"{row['p95_ms']:>9.3f}")
    print()
    print(f"stream_overlap_efficiency: {s['stream_overlap_efficiency']}")
    print(f"lane_wait_p95: {s['lane_wait_p95']}")
    print(f"host_gap_ms: {s['host_gap_ms']}")
    attr = s["attribution"]
    if attr is not None:
        print(f"attribution: compute {attr['compute_s']}s, exposed transfer "
              f"{attr['exposed_transfer_s']}s, comms {attr['comms_s']}s "
              f"({attr['comms_fraction']:.1%}), host gap "
              f"{attr['host_gap_s']}s ({attr['host_gap_fraction']:.1%}) "
              f"of {attr['wall_s']}s wall")
    n = s["numerics"]
    print(f"numerics: {n['nonfinite_events']} non-finite event(s), "
          f"{n['quarantines']} quarantine(s)"
          + (f" — by site {n['nonfinite_by_where']}"
             if n["nonfinite_by_where"] else ""))
    fx = s["forensics"]
    if fx["trace_ids"] or fx["spans_by_role"]:
        print(f"forensics: {fx['trace_ids']} trace id(s)"
              + (f", spans by role {fx['spans_by_role']}"
                 if fx["spans_by_role"] else "")
              + (f", by host {fx['spans_by_host']}"
                 if fx["spans_by_host"] else ""))
    c = s["chaos"]
    print(f"chaos: {c['faults_injected']} injected fault(s)"
          + (f" by site {c['faults_by_site']}" if c["faults_by_site"] else "")
          + f", {c['degradations']} degradation rung(s)"
          + (f" by rung {c['degradations_by_rung']}"
             if c["degradations_by_rung"] else ""))


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        pass  # `trace_summary.py t.json | head` is a normal way to use this
