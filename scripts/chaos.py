"""Chaos matrix runner: a seeded fault schedule against a live fleet, gated.

Builds an in-process fleet — primary router (durable prompt journal,
fleet/journal.py) + standby router tailing the same journal + N ``server.py``
backends — runs a BASELINE closed loop (scripts/loadgen.py, seeded prompt
schedule), then the SAME schedule as a CHAOS run while:

- the seeded fault plan fires (``utils/faults.py``: backend-http 5xx on
  POST /prompt, a slow-host stall — deterministic in ``--seed``),
- the primary ROUTER is killed mid-run (the standby detects the stale lease,
  replays every unresolved prompt from the journal through normal placement;
  clients fail over via loadgen's ``fallback_bases``),
- one BACKEND is killed mid-denoise (ordinary PR 7 failover, now
  warm-preferring).

A separate NETWORK-PARTITION leg (round 20) arms the ``network-partition``
fault site mid-run against one denoise host in BOTH directions — the
router's ``_post``/``_get`` and health polls to it raise refused-socket
errors while the host's own heartbeats silently vanish, each side staying
alive — and gates the same zero-lost + bitwise contract: the partitioned
host's in-flight prompts must fail over, and at least one failover plus
both direction's fault fires must be attributable.

Gates (exit 1 on any failure; one JSON verdict line on stdout, human table
on stderr — the bench.py/loadgen contract):

- ``prompts_lost == 0`` and every prompt completed;
- every completed latent BITWISE-equal to the fault-free baseline (the
  prompt nodes emit deterministic latents tagged by producing host — a
  replayed/failed-over prompt must deliver the identical result);
- bounded p95 inflation: chaos p95 ≤ ``--p95-factor`` × baseline p95 plus a
  takeover allowance (2 × lease TTL + the injected delays) — degradation
  must be graceful, not unbounded;
- each fired fault attributable: ``pa_fault_injected_total`` grew by the
  plan's firing count;
- a STREAM-OOM phase: a real weight-streamed model (tiny FLUX topology)
  forwards through an injected prefetch OOM — the re-carve ladder
  (``pa_degradation_total{rung="stream-recarve"}``) absorbs it and the
  output matches the unfaulted forward (the fleet phase's latents stay
  bitwise because they never cross a program rebuild; a re-carve recomposes
  XLA stages, so this phase gates allclose at the repo's bf16 tolerances).

The REAL-model bitwise replay contract (fold_in RNG) is dryrun §18's job on
the virtual mesh; this runner is the operational rehearsal CI can afford.

Requires PA_EVIDENCE_DIR (the one arming rule — chaos artifacts must never
land in the repo's real evidence); sets it to a temp dir when absent.

Usage:
    python scripts/chaos.py [--backends 2] [--clients 3] [--requests 3]
        [--seed 7] [--work-s 0.5] [--p95-factor 25] [--skip-stream] [--keep]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)


def _chaos_node(tag: str, out_dir: str):
    """Per-backend prompt node: sleeps ``work_s`` (the GIL-free device-time
    stand-in), computes a DETERMINISTIC latent from (seed, steps) — a pure
    function, so the bitwise gate isolates delivery integrity (half-runs,
    mixed replays) from numerics — and dumps it tagged with the producing
    host."""
    import numpy as np

    class ChaosDenoise:
        CATEGORY = "chaos"
        RETURN_TYPES = ("INT",)
        FUNCTION = "run"

        @classmethod
        def INPUT_TYPES(cls):
            return {"required": {"seed": ("INT", {"default": 0}),
                                 "steps": ("INT", {"default": 4}),
                                 "work_s": ("FLOAT", {"default": 0.0})}}

        def run(self, seed, steps, work_s):
            if work_s:
                time.sleep(float(work_s))
            arr = np.random.default_rng(int(seed)).standard_normal(
                (4, 8, 8)
            ).astype(np.float32)
            for _ in range(int(steps)):
                arr = np.tanh(arr * 1.1, dtype=np.float32)
            os.makedirs(out_dir, exist_ok=True)
            np.save(os.path.join(out_dir, f"{int(seed)}-{tag}.npy"), arr)
            return (int(seed),)

    return ChaosDenoise


def _graph(work_s: float):
    return {"1": {"class_type": "ChaosDenoise",
                  "inputs": {"seed": 0, "steps": 4, "work_s": float(work_s)}}}


class _Fleet:
    """Primary router (+ optional standby on the same journal) over N
    backends, all in-process."""

    def __init__(self, root: str, n_backends: int, out_dir: str,
                 journal: bool, lease_ttl_s: float = 1.0):
        from comfyui_parallelanything_tpu.fleet import (
            FleetRegistry,
            PromptJournal,
            Scoreboard,
            make_router,
        )
        from comfyui_parallelanything_tpu.server import make_server

        self.backends = []
        for i in range(n_backends):
            tag = f"chaos-host-{i}"
            srv, q = make_server(
                port=0, output_dir=os.path.join(root, tag),
                class_mappings={"ChaosDenoise": _chaos_node(tag, out_dir)},
                host_id=tag,
            )
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            self.backends.append(
                (tag, f"http://127.0.0.1:{srv.server_address[1]}", srv, q)
            )
        seeds = [(t, b) for t, b, _, _ in self.backends]
        self.journal_path = os.path.join(root, "fleet-journal.jsonl")
        mk = dict(
            backends=seeds,
            saturation_depth=1, monitor_s=0.05, max_attempts=6,
        )
        self.srv, self.router = make_router(
            port=0,
            fleet_registry=FleetRegistry(ttl_s=5.0),
            scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0,
                                  fail_after=2, timeout_s=2.0),
            journal=(PromptJournal(self.journal_path) if journal else None),
            lease_ttl_s=lease_ttl_s,
            **mk,
        )
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()
        self.base = f"http://127.0.0.1:{self.srv.server_address[1]}"
        self.standby = self.standby_srv = None
        if journal:
            self.standby_srv, self.standby = make_router(
                port=0,
                fleet_registry=FleetRegistry(ttl_s=5.0),
                scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0,
                                      fail_after=2, timeout_s=2.0),
                journal=PromptJournal(self.journal_path),
                standby=True, lease_ttl_s=lease_ttl_s,
                **mk,
            )
            threading.Thread(target=self.standby_srv.serve_forever,
                             daemon=True).start()
            self.standby_base = (
                f"http://127.0.0.1:{self.standby_srv.server_address[1]}"
            )
        t0 = time.monotonic()
        while not all(self.router.scoreboard.healthy(t) for t, *_ in seeds):
            if time.monotonic() - t0 > 60:
                raise TimeoutError("backends never turned healthy")
            time.sleep(0.02)

    def kill_router(self) -> None:
        """Crash the primary front door (HTTP gone, monitor stops, lease
        goes stale) — the standby's takeover trigger."""
        self.srv.shutdown()
        self.srv.server_close()
        self.router.shutdown()

    def kill_backend(self, idx: int) -> None:
        tag, base, srv, q = self.backends[idx]
        srv.shutdown()
        srv.server_close()
        q.interrupt()

    def stop(self) -> None:
        for srv in (self.srv, self.standby_srv):
            if srv is not None:
                try:
                    srv.shutdown()
                    srv.server_close()
                except OSError:
                    pass
        for r in (self.router, self.standby):
            if r is not None:
                r.shutdown()
        for _, _, srv, q in self.backends:
            try:
                srv.shutdown()
                srv.server_close()
            except OSError:
                pass
            q.shutdown()


def default_plan(seed: int) -> dict:
    """The seeded chaos schedule: one 5xx on a prompt dispatch (the router
    must walk on / retry, never count it lost), one slow-host stall (the
    spill/latency rehearsal), and one garbled journal record on a dispatch
    append (round 15: crash-mid-write rehearsal — the standby's fold must
    skip the damage and its takeover replay the prompt from its surviving
    submit record; garble, not truncate, so neighboring records stay
    parseable and the damage is exactly one record wide). nth values derive
    from the seed inside the registry, so two runs of one seed fire
    identically. Round 22 adds a slow-disk stall on journal dispatch
    appends (the fsync-stall rehearsal): the injected latency must land in
    ``pa_disk_append_seconds`` and the anomaly sentinel's
    ``disk_append_p95`` watch must fire ATTRIBUTED to it — the
    telemetry-plane leg of the chaos gate."""
    return {"seed": int(seed), "faults": [
        {"site": "backend-http", "match": "POST /prompt", "mode": "5xx",
         "count": 1},
        {"site": "slow-host", "mode": "stall", "delay_s": 0.5, "count": 1},
        {"site": "journal-corrupt", "match": "dispatch", "mode": "garble",
         "count": 1},
        {"site": "slow-disk", "match": "dispatch", "delay_s": 1.5,
         "count": 2},
    ]}


def _fired_total() -> float:
    from comfyui_parallelanything_tpu.utils.faults import registry as freg

    return float(sum(freg.fired().values()))


def _bitwise_check(base_dir: str, chaos_dir: str, seed: int,
                   total: int) -> tuple[int, int]:
    """(missing, mismatched) latent counts between the two runs: the
    deterministic latent per seed value must be identical for EVERY
    submitted seed, and every chaos seed must have produced one at all
    (at-least-once delivery: every dumped copy must match)."""
    import random as _random

    import numpy as np

    # ONE sequential RNG — the exact schedule loadgen submitted (a fresh
    # Random(seed) per element would repeat the first value and the gate
    # would only ever check prompt 1).
    _rng = _random.Random(seed)
    sched = [_rng.randrange(1 << 31) for _ in range(total)]
    mismatched = missing = 0
    for s in sched:
        b_files = sorted(glob.glob(os.path.join(base_dir, f"{s}-*.npy")))
        c_files = sorted(glob.glob(os.path.join(chaos_dir, f"{s}-*.npy")))
        if not b_files or not c_files:
            missing += 1
            continue
        b = np.load(b_files[0])
        for cf in c_files:
            if not (np.load(cf) == b).all():
                mismatched += 1
    return missing, mismatched


def run_fleet_chaos(**kw) -> dict:
    """The fleet phase (importable — tests/test_chaos.py drives this exact
    path). Returns the verdict dict; ``ok`` is the gate. Under
    ``PA_LOCKCHECK=1`` (ci_tier1.sh sets it for the chaos smoke) the
    lock-acquisition-order graph recorded across the whole
    router+standby+backends run must stay ACYCLIC — the verdict carries
    ``lock_cycles`` and a cycle fails the phase (a potential deadlock under
    fault injection is a chaos failure even if this run never hung).

    Round 22: the telemetry plane rides along. Wall-clock sampler cadence
    is not assertable in CI, so the phase pins PA_HISTORY_INTERVAL_S high
    (background samplers never tick mid-run) and drives the history ring +
    anomaly sentinel with EXPLICIT ticks — the injected slow-disk stall
    must fire the ``disk_append_p95`` watch ATTRIBUTED to the armed plan,
    and every firing must be attributed (an unattributed anomaly under a
    known fault plan is a telemetry failure)."""
    interval_before = os.environ.get("PA_HISTORY_INTERVAL_S")
    os.environ["PA_HISTORY_INTERVAL_S"] = "3600"  # manual ticks only
    try:
        return _fleet_chaos(**kw)
    finally:
        if interval_before is None:
            os.environ.pop("PA_HISTORY_INTERVAL_S", None)
        else:
            os.environ["PA_HISTORY_INTERVAL_S"] = interval_before


def _fleet_chaos(*, n_backends: int = 2, clients: int = 3,
                 requests: int = 3, seed: int = 7, work_s: float = 0.5,
                 p95_factor: float = 25.0, lease_ttl_s: float = 1.0,
                 root: str | None = None,
                 plan: dict | None = None) -> dict:
    from loadgen import run_load

    from comfyui_parallelanything_tpu.utils import faults

    lockcheck = None
    if os.environ.get("PA_LOCKCHECK") == "1":
        from comfyui_parallelanything_tpu.utils import lockcheck

        # Installed here when the harness (tests/conftest.py) hasn't
        # already: locks created from this point on — every per-instance
        # router/scoreboard/journal/server lock below — are tracked.
        lockcheck.install()

    root = root or tempfile.mkdtemp(prefix="pa-chaos-")
    total = clients * requests
    g = _graph(work_s)

    # -- baseline: same topology, no faults, no kills -----------------------
    os.environ.pop("PA_FAULT_PLAN", None)
    faults.reload()
    base_dir = os.path.join(root, "baseline")
    fleet = _Fleet(os.path.join(root, "b"), n_backends, base_dir,
                   journal=False)
    try:
        baseline = run_load(
            fleet.base, g, clients=clients, requests=requests, timeout=120,
            seed_key="1:inputs:seed", seed=seed,
            hosts=[b for _, b, _, _ in fleet.backends],
        )
    finally:
        fleet.stop()

    # -- chaos: seeded plan + router kill + backend kill --------------------
    os.environ["PA_FAULT_PLAN"] = json.dumps(plan or default_plan(seed))
    faults.reload()
    fired_before = _fired_total()
    from comfyui_parallelanything_tpu.utils.faults import registry as _freg

    by_site_before = dict(_freg.fired())

    # -- telemetry plane: deterministic sentinel warmup ---------------------
    # Scratch-journal appends between explicit ticks establish the
    # disk-append baseline the injected stall is judged against (the plan's
    # slow-disk spec matches "dispatch", so warm "resolve" appends never
    # fire it, and the scratch path keeps warm records out of the fleet
    # journal the standby replays).
    from comfyui_parallelanything_tpu.utils import anomaly, timeseries

    sentinel_on = timeseries.enabled() and anomaly.enabled()
    anomaly_events: list[dict] = []
    if sentinel_on:
        from comfyui_parallelanything_tpu.fleet.journal import PromptJournal

        timeseries.ring.reset()
        anomaly.sentinel.reset(seed=seed)
        timeseries.ring.mark_phase("chaos-fleet", state="begin")
        warm = PromptJournal(os.path.join(root, "warm-journal.jsonl"))
        for i in range(8):
            warm.append("resolve", f"warm-{i}")
            timeseries.ring.snapshot()
            anomaly_events += anomaly.sentinel.observe(timeseries.ring)
        warm.close()

    chaos_dir = os.path.join(root, "chaos")
    fleet = _Fleet(os.path.join(root, "c"), n_backends, chaos_dir,
                   journal=True, lease_ttl_s=lease_ttl_s)
    timers = [
        # Mid-run, not at the edges: roughly one closed-loop wave in.
        threading.Timer(work_s * 1.5, fleet.kill_router),
        threading.Timer(work_s * 2.5, fleet.kill_backend, args=(0,)),
    ]
    try:
        for t in timers:
            t.start()
        chaos = run_load(
            fleet.base, g, clients=clients, requests=requests, timeout=240,
            seed_key="1:inputs:seed", seed=seed,
            hosts=[b for _, b, _, _ in fleet.backends],
            fallback_bases=[fleet.standby_base],
        )
    finally:
        for t in timers:
            t.cancel()
        fleet.stop()
        os.environ.pop("PA_FAULT_PLAN", None)
    fired = _fired_total() - fired_before
    # Per-site DELTAS over this run (not lifetime counts — another phase in
    # the same process, e.g. the stream-OOM rehearsal, fires too), the same
    # discipline as `fired` above. reload() swaps the registry object, so
    # re-import the module-level name rather than holding a stale reference.
    from comfyui_parallelanything_tpu.utils.faults import registry as _freg2

    fired_by_site = {
        site: n - by_site_before.get(site, 0)
        for site, n in _freg2.fired().items()
        if n - by_site_before.get(site, 0) > 0
    }

    # Post-run sentinel ticks: the stall samples are in the histogram now;
    # the snapshot's window delta carries both the latency spike and the
    # pa_fault_injected_total growth the attributor reads. The phase mark
    # closes AFTER the ticks so phase attribution still sees it open.
    if sentinel_on:
        for _ in range(2):
            timeseries.ring.snapshot()
            anomaly_events += anomaly.sentinel.observe(timeseries.ring)
        timeseries.ring.mark_phase("chaos-fleet", state="end")

    # -- gates ---------------------------------------------------------------
    failures: list[str] = []
    if chaos.get("prompts_lost"):
        failures.append(f"prompts_lost={chaos['prompts_lost']} (must be 0)")
    if chaos["completed"] != total:
        failures.append(
            f"completed {chaos['completed']}/{total} (errors: "
            f"{chaos.get('errors')})"
        )
    # Bitwise survivors: the deterministic latent per seed value must be
    # identical between the baseline and chaos runs, for every submitted
    # seed — and every chaos seed must have produced one at all.
    missing, mismatched = _bitwise_check(base_dir, chaos_dir, seed, total)
    if missing:
        failures.append(f"{missing} seed(s) missing a latent dump")
    if mismatched:
        failures.append(f"{mismatched} latent(s) diverged from baseline")
    # Bounded p95 inflation: takeover costs ~lease TTL + detection sweeps;
    # anything beyond the allowance means degradation wasn't graceful.
    allowance = 2.0 * lease_ttl_s + 2.0 + work_s
    p95_bound = p95_factor * max(baseline["latency_p95_s"], 0.05) + allowance
    if chaos["latency_p95_s"] > p95_bound:
        failures.append(
            f"p95 {chaos['latency_p95_s']}s exceeds bound {p95_bound:.2f}s "
            f"(baseline {baseline['latency_p95_s']}s)"
        )
    if fired <= 0:
        failures.append("fault plan never fired (injection unproven)")
    # Telemetry-plane gates (round 22): the armed slow-disk stall must be
    # (a) counted at its site, (b) seen by the sentinel as an ATTRIBUTED
    # anomaly carrying a postmortem — and nothing may fire unattributed
    # under a known fault plan.
    planned_sites = {f["site"] for f in (plan or default_plan(seed))["faults"]}
    if "slow-disk" in planned_sites and \
            fired_by_site.get("slow-disk", 0) <= 0:
        failures.append("slow-disk never fired (injection unproven)")
    anomalies_block = None
    if sentinel_on:
        attributed = [e for e in anomaly_events if e.get("attributed")]
        unattributed = [e for e in anomaly_events
                        if not e.get("attributed")]
        if "slow-disk" in planned_sites and not attributed:
            failures.append(
                "no attributed anomaly fired (sentinel unproven — the "
                "slow-disk stall should trip disk_append_p95)"
            )
        if unattributed:
            failures.append(
                f"{len(unattributed)} unattributed anomaly firing(s): "
                + ", ".join(e["signal"] for e in unattributed)
            )
        anomalies_block = {
            "fired": len(anomaly_events),
            "attributed": len(attributed),
            "unattributed": len(unattributed),
            "signals": sorted({e["signal"] for e in anomaly_events}),
            "postmortems": [e["postmortem"] for e in anomaly_events
                            if e.get("postmortem")],
        }
    lock_cycles = None
    if lockcheck is not None:
        cycles = lockcheck.cycles()
        lock_cycles = len(cycles)
        if cycles:
            failures.append(
                "lock-order cycle(s) recorded (potential deadlock): "
                + "; ".join(" -> ".join(c) for c in cycles)
            )
    return {
        "phase": "fleet",
        "ok": not failures,
        "failures": failures,
        "lock_cycles": lock_cycles,
        "total_prompts": total,
        "prompts_lost": chaos.get("prompts_lost"),
        "completed": chaos["completed"],
        "faults_fired": fired,
        "faults_by_site": fired_by_site,
        "faults_injected_counter": chaos.get("faults_injected"),
        "anomalies": anomalies_block,
        "baseline_p95_s": baseline["latency_p95_s"],
        "chaos_p95_s": chaos["latency_p95_s"],
        "p95_bound_s": round(p95_bound, 3),
        "fleet": chaos.get("fleet"),
        "root": root,
    }


def run_partition_chaos(*, n_backends: int = 3, clients: int = 3,
                        requests: int = 3, seed: int = 11,
                        work_s: float = 0.5, p95_factor: float = 25.0,
                        root: str | None = None) -> dict:
    """The network-partition leg (round 20, importable — tests/test_chaos.py
    drives this exact path): mid-run, BOTH directions of one denoise host's
    traffic drop while each side stays alive — the ``network-partition``
    fault site cuts the router's dispatch/collect/health-poll calls to the
    victim (refused-socket OSError) and swallows the victim's own heartbeats
    — and the victim's in-flight prompts must fail over with zero lost and
    bitwise survivors. The victim runs a real ``HeartbeatClient`` beating
    ``role="denoise"`` into ``/fleet/register``, so the backend→router half
    exercises the same code path a ``server.py --role denoise`` process
    runs, and the fleet is DISAGGREGATED for the router (role pools live)."""
    from loadgen import run_load

    from comfyui_parallelanything_tpu.fleet import HeartbeatClient
    from comfyui_parallelanything_tpu.utils import faults

    root = root or tempfile.mkdtemp(prefix="pa-partition-")
    total = clients * requests
    g = _graph(work_s)

    # -- baseline: same topology, no partition ------------------------------
    os.environ.pop("PA_FAULT_PLAN", None)
    faults.reload()
    base_dir = os.path.join(root, "baseline")
    fleet = _Fleet(os.path.join(root, "b"), n_backends, base_dir,
                   journal=False)
    try:
        baseline = run_load(
            fleet.base, g, clients=clients, requests=requests, timeout=120,
            seed_key="1:inputs:seed", seed=seed,
            hosts=[b for _, b, _, _ in fleet.backends],
        )
    finally:
        fleet.stop()

    # -- partition: arm BOTH directions against host 0 mid-run --------------
    chaos_dir = os.path.join(root, "chaos")
    fleet = _Fleet(os.path.join(root, "c"), n_backends, chaos_dir,
                   journal=False)
    victim_id, victim_base = fleet.backends[0][0], fleet.backends[0][1]
    hb = HeartbeatClient(fleet.base, victim_id, victim_base,
                         interval_s=0.1, role="denoise").start()

    def arm():
        # count=None: every hit from the 1st on — a partition persists
        # until healed, unlike the one-shot faults in the default plan.
        os.environ["PA_FAULT_PLAN"] = json.dumps({"seed": int(seed), "faults": [
            {"site": "network-partition", "nth": 1, "count": None,
             "match": f"router->{victim_base}"},
            {"site": "network-partition", "nth": 1, "count": None,
             "match": f"{victim_id}->router"},
        ]})
        faults.reload()

    timer = threading.Timer(work_s * 1.5, arm)
    fired = 0.0
    try:
        timer.start()
        chaos = run_load(
            fleet.base, g, clients=clients, requests=requests, timeout=240,
            seed_key="1:inputs:seed", seed=seed,
            hosts=[b for _, b, _, _ in fleet.backends],
        )
    finally:
        timer.cancel()
        hb.stop()
        fleet.stop()
        # arm()'s reload zeroed the registry, so its lifetime total IS this
        # leg's count — read it before the disarm reload resets it again.
        fired = _fired_total()
        os.environ.pop("PA_FAULT_PLAN", None)
        faults.reload()
    beat_drops = hb._failures

    # -- gates ---------------------------------------------------------------
    failures: list[str] = []
    if chaos.get("prompts_lost"):
        failures.append(f"prompts_lost={chaos['prompts_lost']} (must be 0)")
    if chaos["completed"] != total:
        failures.append(
            f"completed {chaos['completed']}/{total} (errors: "
            f"{chaos.get('errors')})"
        )
    missing, mismatched = _bitwise_check(base_dir, chaos_dir, seed, total)
    if missing:
        failures.append(f"{missing} seed(s) missing a latent dump")
    if mismatched:
        failures.append(f"{mismatched} latent(s) diverged from baseline")
    # Detection is scoreboard polls (0.1 s cadence, fail_after 2, 2 s
    # timeout) + one dispatch walking onto the cut link — no lease TTL in
    # this leg (single router), so the allowance is poll-detection-shaped.
    allowance = 6.0 + work_s
    p95_bound = p95_factor * max(baseline["latency_p95_s"], 0.05) + allowance
    if chaos["latency_p95_s"] > p95_bound:
        failures.append(
            f"p95 {chaos['latency_p95_s']}s exceeds bound {p95_bound:.2f}s "
            f"(baseline {baseline['latency_p95_s']}s)"
        )
    if fired <= 0:
        failures.append("network-partition never fired (injection unproven)")
    if beat_drops <= 0:
        failures.append(
            "backend->router direction never cut (no heartbeat dropped)"
        )
    failovers = (chaos.get("fleet") or {}).get("failovers")
    if not failovers:
        failures.append(
            "no failover recorded — the victim's in-flight prompts were "
            "never failed over (partition landed between waves?)"
        )
    return {
        "phase": "partition",
        "ok": not failures,
        "failures": failures,
        "total_prompts": total,
        "prompts_lost": chaos.get("prompts_lost"),
        "completed": chaos["completed"],
        "victim": victim_id,
        "faults_fired": fired,
        "heartbeats_dropped": beat_drops,
        "failovers": failovers,
        "baseline_p95_s": baseline["latency_p95_s"],
        "chaos_p95_s": chaos["latency_p95_s"],
        "p95_bound_s": round(p95_bound, 3),
        "fleet": chaos.get("fleet"),
        "root": root,
    }


def run_stream_oom_chaos(*, nth: int = 2) -> dict:
    """The stream-OOM phase: a REAL weight-streamed model (tiny FLUX
    topology on CPU) forwards through an injected prefetch OOM; the
    orchestrator's re-carve ladder must absorb it — completion + allclose to
    the unfaulted forward + the ``stream-recarve`` rung counted."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from comfyui_parallelanything_tpu import (
        DeviceChain,
        ParallelConfig,
        parallelize,
    )
    from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux
    from comfyui_parallelanything_tpu.models.loader import params_nbytes
    from comfyui_parallelanything_tpu.utils import faults
    from comfyui_parallelanything_tpu.utils.metrics import registry as metrics

    cfg = FluxConfig(
        in_channels=16, hidden_size=64, num_heads=4, depth=2,
        depth_single_blocks=4, context_in_dim=32, vec_in_dim=16,
        axes_dim=(4, 6, 6), guidance_embed=False, dtype=jnp.float32,
    )
    model = build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4),
                       txt_len=16)
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, 4))
    t = jnp.linspace(900.0, 1.0, 2)
    ctx = jax.random.normal(jax.random.key(2), (2, 16, cfg.context_in_dim))
    y = jax.random.normal(jax.random.key(3), (2, cfg.vec_in_dim))
    want = model.apply(model.params, x, t, ctx, y=y)

    os.environ["PA_FAULT_PLAN"] = json.dumps({"faults": [
        {"site": "stream-prefetch-oom", "nth": int(nth), "count": 1},
    ]})
    faults.reload()
    rung0 = metrics.get("pa_degradation_total",
                        {"rung": "stream-recarve"}) or 0.0
    failures: list[str] = []
    try:
        # Budget = full param bytes → max stage 2/5 of the weights → a
        # ~3-stage carve with a strictly finer carve available (the
        # re-carve rung must have somewhere to go; a 1-segment-per-stage
        # carve would be the exhaustion case, tested elsewhere).
        pm = parallelize(
            model, DeviceChain.even(["cpu:0"]),
            ParallelConfig(weight_sharding="stream",
                           hbm_budget_bytes=params_nbytes(model.params)),
        )
        n0 = pm._get_streaming_runner().n_stages
        got = pm(x, t, ctx, y=y)
        n1 = pm._stream_runner.n_stages
        if not np.allclose(np.asarray(got), np.asarray(want),
                           rtol=2e-3, atol=1e-4):
            failures.append("re-carved streamed output diverged")
        if n1 <= n0:
            failures.append(f"no re-carve happened ({n0} → {n1} stages)")
    except Exception as e:  # noqa: BLE001 — the gate IS "it must not raise"
        failures.append(f"streamed forward died: {type(e).__name__}: {e}")
        n0 = n1 = None
    finally:
        os.environ.pop("PA_FAULT_PLAN", None)
        faults.reload()
    rung = (metrics.get("pa_degradation_total",
                        {"rung": "stream-recarve"}) or 0.0) - rung0
    if rung <= 0:
        failures.append("stream-recarve rung not counted")
    return {
        "phase": "stream-oom",
        "ok": not failures,
        "failures": failures,
        "stages_before": n0,
        "stages_after": n1,
        "recarve_rungs": rung,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backends", type=int, default=2)
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--requests", type=int, default=3,
                    help="prompts per client (closed loop)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--work-s", type=float, default=0.5)
    ap.add_argument("--p95-factor", type=float, default=25.0)
    ap.add_argument("--lease-ttl-s", type=float, default=1.0)
    ap.add_argument("--skip-stream", action="store_true",
                    help="skip the stream-OOM phase (no jax model build)")
    ap.add_argument("--skip-partition", action="store_true",
                    help="skip the network-partition leg")
    ap.add_argument("--plan", default=None,
                    help="override the fleet phase's PA_FAULT_PLAN JSON")
    args = ap.parse_args()
    if not os.environ.get("PA_EVIDENCE_DIR"):
        # The one arming rule (utils/faults.py): chaos artifacts — ledgers,
        # postmortems, journals — must never land in the repo's evidence.
        os.environ["PA_EVIDENCE_DIR"] = tempfile.mkdtemp(prefix="pa-chaos-ev-")
    phases = [run_fleet_chaos(
        n_backends=args.backends, clients=args.clients,
        requests=args.requests, seed=args.seed, work_s=args.work_s,
        p95_factor=args.p95_factor, lease_ttl_s=args.lease_ttl_s,
        plan=json.loads(args.plan) if args.plan else None,
    )]
    if not args.skip_partition:
        phases.append(run_partition_chaos(
            n_backends=max(3, args.backends), clients=args.clients,
            requests=args.requests, seed=args.seed + 4, work_s=args.work_s,
            p95_factor=args.p95_factor,
        ))
    if not args.skip_stream:
        phases.append(run_stream_oom_chaos())
    verdict = {
        "chaos": "ok" if all(p["ok"] for p in phases) else "FAILED",
        "seed": args.seed,
        "phases": phases,
    }
    for p in phases:
        sys.stderr.write(
            f"chaos[{p['phase']}]: {'ok' if p['ok'] else 'FAILED'}"
            + (f" — {'; '.join(p['failures'])}" if p["failures"] else "")
            + "\n"
        )
    print(json.dumps(verdict))
    return 0 if verdict["chaos"] == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
