"""Traffic-twin accuracy report, gate, and bank (fleet/twin.py's consumer).

Every open-loop loadgen run appends a ``kind="openloop"`` record to the perf
ledger: the seeded arrival schedule (kind/seed/rps/duration per rung — or
verbatim offsets for trace replay), the measured latency-under-load curve,
per-host service evidence, and the declared twin error band. This script
replays those records through the discrete-event twin and compares predicted
vs measured p95 — the exact audit/gate/bank trio scripts/perf_ledger.py,
numerics_audit.py, and roofline_report.py established:

- default      one line per rung of the latest openloop record per group
               (base URL): twin p95 vs measured p95, relative error, the
               capacity source (roofline / measured / mean).
- ``--check``  the TWIN GATE (wired into scripts/ci_tier1.sh after the
               roofline gate): for the latest openloop record per group,
               every rung with enough arrivals must keep
               ``|twin p95 − measured p95| / measured`` within the record's
               declared ``twin_band`` (``--band`` overrides). A ledger with
               no openloop records is SKIP, never a failure — the gate
               activates the moment open-loop evidence banks.
- ``--bank``   persist the latest comparison per group to
               ``ledger/twin_bank.json`` (``pa-twin-bank/v1``) — the banked
               predicted-vs-measured accuracy the ROADMAP autoscaling item
               builds on.

Stays jax-free: fleet/twin.py (and, inside it, utils/roofline.py) is loaded
standalone by file path — module levels stdlib-only by contract — so this
runs over a wedged tunnel or on a laptop with just the ledger.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

LEDGER_SCHEMA = "pa-perf-ledger/v1"
BANK_SCHEMA = "pa-twin-bank/v1"
BANK_FILENAME = "twin_bank.json"

# Rungs with fewer arrivals than this are statistically meaningless for a
# p95 comparison (nearest-rank p95 of 4 samples is just the max) — reported
# but never gated.
MIN_ARRIVALS = 8

DEFAULT_BAND = 0.5


def _load_std(relpath: str, alias: str):
    path = os.path.join(_REPO, "comfyui_parallelanything_tpu",
                        *relpath.split("/"))
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


twin = _load_std("fleet/twin.py", "pa_twin_report")
roofline = _load_std("utils/roofline.py", "pa_roofline_twin_report")


def _is_openloop(rec: dict) -> bool:
    return (rec.get("schema") == LEDGER_SCHEMA
            and rec.get("kind") == "openloop"
            and not rec.get("stale") and not rec.get("invalid")
            and isinstance(rec.get("openloop"), dict))


def _group_key(rec: dict) -> str:
    return str(rec.get("base") or "?")


def latest_by_group(records: list[dict]) -> dict[str, dict]:
    groups: dict[str, dict] = {}
    for rec in records:
        if _is_openloop(rec):
            groups[_group_key(rec)] = rec  # latest wins (file order)
    return groups


def _declared_band(rec: dict) -> float:
    """The record's declared twin error band — explicit None-checks, not
    truthiness: a declared band of 0 (zero tolerance) must gate at 0, not
    silently loosen to the default."""
    for band in (rec.get("twin_band"),
                 (rec.get("openloop") or {}).get("twin_band")):
        if band is not None:
            return float(band)
    return DEFAULT_BAND


def _gateable(rung: dict) -> bool:
    return (isinstance(rung.get("measured_p95_s"), (int, float))
            and rung["measured_p95_s"] > 0
            and int(rung.get("arrivals") or 0) >= MIN_ARRIVALS
            and rung.get("p95_err") is not None)


def check(records: list[dict], band_override: float | None = None,
          calib: dict | None = None) -> int:
    groups = latest_by_group(records)
    if not groups:
        print("twin_report: no openloop records in the ledger — SKIP "
              "(nothing to gate)")
        return 0
    failures = 0
    for key, rec in sorted(groups.items()):
        band = band_override if band_override is not None \
            else _declared_band(rec)
        rep = twin.replay_record(rec, calib)
        if rep is None:
            print(f"SKIP  {key}: record carries no replayable rungs/hosts")
            continue
        gated = [r for r in rep["rungs"] if _gateable(r)]
        if not gated:
            print(f"SKIP  {key}: no rung with ≥{MIN_ARRIVALS} arrivals and "
                  f"a measured p95")
            continue
        worst = max(r["p95_err"] for r in gated)
        sources = sorted({h["source"] for h in rep["hosts"]})
        if worst > band:
            failures += 1
            print(f"FAIL  {key}: twin p95 error {worst} outside the "
                  f"declared band {band} ({len(gated)} gated rung(s), "
                  f"capacity: {','.join(sources)}) — the capacity model "
                  f"disagrees with the measured queue")
        else:
            print(f"OK    {key}: twin p95 error {worst} within band {band} "
                  f"({len(gated)} gated rung(s), capacity: "
                  f"{','.join(sources)})")
    if failures:
        print(f"twin_report: {failures} failed group(s)")
        return 1
    print("twin_report: twin predictions within the declared band")
    return 0


def bank(records: list[dict], bank_file: str,
         calib: dict | None = None) -> int:
    import time

    groups = latest_by_group(records)
    if not groups:
        print("twin_report: nothing to bank (no openloop records)")
        return 1
    entries: dict[str, dict] = {}
    for key, rec in sorted(groups.items()):
        rep = twin.replay_record(rec, calib)
        if rep is None:
            continue
        gated = [r for r in rep["rungs"] if _gateable(r)]
        entries[key] = {
            "kind": rep["kind"],
            "seed": rep["seed"],
            "client_overhead_s": rep["client_overhead_s"],
            "hosts": rep["hosts"],
            "rungs": rep["rungs"],
            "p95_err_max": (
                round(max(r["p95_err"] for r in gated), 4) if gated else None
            ),
            "band": _declared_band(rec),
            "record_ts": rec.get("ts"),
        }
        print(f"BANK  {key}: p95 err max {entries[key]['p95_err_max']} "
              f"over {len(rep['rungs'])} rung(s)")
    if not entries:
        print("twin_report: nothing replayable to bank")
        return 1
    try:
        os.makedirs(os.path.dirname(bank_file) or ".", exist_ok=True)
        with open(bank_file, "w") as f:
            json.dump({"schema": BANK_SCHEMA, "ts": time.time(),
                       "groups": entries}, f, indent=1, sort_keys=True)
    except OSError as e:
        print(f"twin_report: could not write {bank_file}: {e}")
        return 1
    print(f"twin bank written to {bank_file} ({len(entries)} group(s))")
    return 0


def summarize(records: list[dict], calib: dict | None = None) -> None:
    groups = latest_by_group(records)
    total = sum(1 for rec in records if _is_openloop(rec))
    print(f"{total} openloop record(s) across {len(groups)} group(s)")
    for key, rec in sorted(groups.items()):
        rep = twin.replay_record(rec, calib)
        if rep is None:
            print(f"  {key}: not replayable (no hosts/rungs)")
            continue
        sources = sorted({h["source"] for h in rep["hosts"]})
        print(f"  {key}: kind={rep['kind']} seed={rep['seed']} "
              f"overhead={rep['client_overhead_s']}s "
              f"capacity={','.join(sources)}")
        for r in rep["rungs"]:
            print(f"    {r.get('rps_offered')} rps: twin p95 "
                  f"{r['twin_p95_s']}s vs measured {r['measured_p95_s']}s "
                  f"(err {r['p95_err']}, {r['arrivals']} arrivals)")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=None,
                    help="ledger file or directory (default: $PA_LEDGER_DIR "
                         "or <evidence dir>/ledger)")
    ap.add_argument("--calib", default=None,
                    help="roofline calibration store for the roofline "
                         "capacity tier (default: <ledger dir>/"
                         f"{roofline.CALIB_FILENAME})")
    ap.add_argument("--band", type=float, default=None,
                    help="override the records' declared twin error band")
    ap.add_argument("--check", action="store_true",
                    help="run the twin gate (exit 1 when predicted p95 "
                         "leaves the band; SKIP on an openloop-free ledger)")
    ap.add_argument("--bank", action="store_true",
                    help="persist the latest twin-vs-measured comparison "
                         "per group to the twin bank")
    args = ap.parse_args()

    from bench import evidence_dir

    ledger = (args.ledger or os.environ.get("PA_LEDGER_DIR")
              or os.path.join(evidence_dir(), "ledger"))
    if ledger.endswith(".jsonl"):
        ledger_dir = os.path.dirname(ledger) or "."
    else:
        ledger_dir = ledger
        ledger = os.path.join(ledger, "perf_ledger.jsonl")
    calib_file = args.calib or os.path.join(ledger_dir,
                                            roofline.CALIB_FILENAME)
    calib = roofline.load_calibration(calib_file)
    records = roofline.load_jsonl(ledger)
    if args.bank:
        sys.exit(bank(records, os.path.join(ledger_dir, BANK_FILENAME),
                      calib))
    if args.check:
        sys.exit(check(records, band_override=args.band, calib=calib))
    summarize(records, calib)


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        pass
