"""Numerics drift gate: golden latent fingerprints per rung, banked beside
the perf ledger.

Every fresh bench line (bench.py) carries a ``latent_fingerprint`` — a
deterministic bf16-quantized digest of the rung's final latent
(``utils/numerics.py``; invariant to occupancy, bucket width, and dp
sharding by construction) — and a ``nonfinite_events`` count. This script is
the audit over the ledger those lines append to, exactly like the perf gate
(``scripts/perf_ledger.py``) is for step time and peak HBM:

- default      one coverage line per (rung, platform) group
- ``--check``  the DRIFT GATE: for every group, compare the latest bench
               record's fingerprint against the banked golden — or, with no
               golden banked yet, against the group's own most recent prior
               record — and exit 1 on a mismatch OR on
               ``nonfinite_events > 0`` in the latest record. Groups with no
               fingerprint anywhere are SKIP, never failed (a fresh checkout
               with an empty ledger must pass CI).
- ``--bank``   bank the latest fingerprint per group as the golden
               (``<ledger>/numerics_golden.json``) — run after an INTENDED
               numeric change (new kernel, precision policy), the same
               handshake as re-banking a perf baseline.

Stale re-emits, dryrun-marked records, and ``error`` records are never
compared. The verdict is also written to ``<ledger>/numerics_gate.json``
(best-effort) — the ``numerics.fingerprint_gate`` field of ``GET /health``.
Stays jax-free (imports bench.py, whose module level is stdlib-only) so it
runs over a wedged tunnel.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

LEDGER_SCHEMA = "pa-perf-ledger/v1"
GOLDEN_FILENAME = "numerics_golden.json"
GATE_FILENAME = "numerics_gate.json"


def _load_jsonl(path: str) -> list[dict]:
    out: list[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _comparable(rec: dict) -> bool:
    """A record the gate may compare: a measured bench line (never a stale
    re-emit, dry-run, or error record) carrying a fingerprint string."""
    if rec.get("kind") != "bench" or rec.get("schema") != LEDGER_SCHEMA:
        return False
    if rec.get("stale") or rec.get("dryrun") or rec.get("invalid"):
        return False
    return isinstance(rec.get("latent_fingerprint"), str)


def _group_key(rec: dict) -> str:
    return f"{rec.get('rung') or '?'}/{rec.get('platform') or '?'}"


def _load_golden(path: str) -> dict:
    try:
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _write_gate(ledger_dir: str, verdict: dict) -> None:
    try:
        with open(os.path.join(ledger_dir, GATE_FILENAME), "w") as f:
            json.dump(verdict, f, indent=1)
    except OSError:
        pass  # best-effort: a read-only checkout must not fail the gate


def check(records: list[dict], golden: dict, *, ledger_dir: str,
          write_gate: bool = True) -> int:
    """The gate. One verdict line per group; returns the exit code and
    writes the ``numerics_gate.json`` status for ``GET /health``."""
    groups: dict[str, list[dict]] = {}
    for rec in records:
        if _comparable(rec):
            groups.setdefault(_group_key(rec), []).append(rec)
    results: dict[str, dict] = {}
    failures = 0
    if not groups:
        print("numerics_audit: no fingerprinted bench records in the ledger "
              "— OK (nothing to gate)")
    for key, recs in sorted(groups.items()):
        latest, prior = recs[-1], recs[:-1]
        fp = latest["latent_fingerprint"]
        nfe = latest.get("nonfinite_events")
        base = (golden.get(key) or {}).get("fingerprint")
        source = "golden"
        if base is None and prior:
            base = prior[-1]["latent_fingerprint"]
            source = f"ledger[{len(prior)}]"
        problems = []
        if isinstance(nfe, (int, float)) and nfe > 0:
            problems.append(f"nonfinite_events={int(nfe)}")
        if base is None:
            status = "SKIP " if not problems else "FAIL "
            print(f"{status} {key}: no golden or prior fingerprint "
                  f"(latest {fp})" + ("; " + "; ".join(problems)
                                      if problems else ""))
            results[key] = {"status": status.strip().lower(),
                            "fingerprint": fp}
            failures += bool(problems)
            continue
        if fp != base:
            problems.append(f"fingerprint drift: {fp} != {base} [{source}]")
        if problems:
            failures += 1
            print(f"DRIFT {key}: " + "; ".join(problems))
            results[key] = {"status": "drift", "fingerprint": fp,
                            "baseline": base, "source": source}
        else:
            print(f"OK    {key}: {fp} [{source}]")
            results[key] = {"status": "ok", "fingerprint": fp,
                            "source": source}
    if write_gate:
        _write_gate(ledger_dir, {
            "status": "drift" if failures else ("ok" if groups else "skip"),
            "ts": time.time(),
            "groups": results,
        })
    if failures:
        print(f"numerics_audit: {failures} drifted/poisoned group(s)")
        return 1
    print("numerics_audit: no fingerprint drift")
    return 0


def bank(records: list[dict], golden_path: str) -> int:
    """Bank the latest fingerprint per group as the golden."""
    golden = _load_golden(golden_path)
    latest: dict[str, dict] = {}
    for rec in records:
        if _comparable(rec):
            latest[_group_key(rec)] = rec
    if not latest:
        print("numerics_audit: nothing to bank (no fingerprinted bench "
              "records)")
        return 1
    for key, rec in sorted(latest.items()):
        golden[key] = {
            "fingerprint": rec["latent_fingerprint"],
            "ts": rec.get("ts"),
            "banked_ts": time.time(),
        }
        print(f"BANK  {key}: {rec['latent_fingerprint']}")
    os.makedirs(os.path.dirname(golden_path) or ".", exist_ok=True)
    with open(golden_path, "w") as f:
        json.dump(golden, f, indent=1, sort_keys=True)
    return 0


def summarize(records: list[dict], golden: dict) -> None:
    latest: dict[str, dict] = {}
    total = 0
    for rec in records:
        if _comparable(rec):
            total += 1
            latest[_group_key(rec)] = rec
    print(f"{total} fingerprinted bench record(s) across "
          f"{len(latest)} group(s); {len(golden)} golden(s) banked")
    for key, rec in sorted(latest.items()):
        g = (golden.get(key) or {}).get("fingerprint")
        mark = "=" if g == rec["latent_fingerprint"] else (
            "?" if g is None else "!")
        print(f"  {key}: {rec['latent_fingerprint']} "
              f"(nonfinite_events={rec.get('nonfinite_events')}) "
              f"golden{mark}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=None,
                    help="ledger file or directory (default: $PA_LEDGER_DIR "
                         "or <evidence dir>/ledger)")
    ap.add_argument("--golden", default=None,
                    help="golden fingerprint bank (default: "
                         f"<ledger dir>/{GOLDEN_FILENAME})")
    ap.add_argument("--check", action="store_true",
                    help="run the drift gate (exit 1 on drift or non-finite "
                         "events)")
    ap.add_argument("--bank", action="store_true",
                    help="bank the latest fingerprint per (rung, platform) "
                         "as the golden")
    args = ap.parse_args()

    from bench import evidence_dir

    ledger = (args.ledger or os.environ.get("PA_LEDGER_DIR")
              or os.path.join(evidence_dir(), "ledger"))
    if ledger.endswith(".jsonl"):
        ledger_dir = os.path.dirname(ledger) or "."
    else:  # a directory (existing or not — fresh checkouts have none yet)
        ledger_dir = ledger
        ledger = os.path.join(ledger, "perf_ledger.jsonl")
    golden_path = args.golden or os.path.join(ledger_dir, GOLDEN_FILENAME)
    records = _load_jsonl(ledger)
    if args.bank:
        sys.exit(bank(records, golden_path))
    if args.check:
        sys.exit(check(records, _load_golden(golden_path),
                       ledger_dir=ledger_dir))
    summarize(records, _load_golden(golden_path))


if __name__ == "__main__":
    main()
