"""Perf-ledger queries and the regression gate.

The ledger (``ledger/perf_ledger.jsonl``, schema ``pa-perf-ledger/v1``) holds
one JSON record per bench/dryrun/loadgen run, appended by bench.py (kinds
``bench``/``error``), ``__graft_entry__.dryrun_multichip`` (``dryrun``), and
``scripts/loadgen.py`` (``loadgen``) — see
``comfyui_parallelanything_tpu/utils/telemetry.py`` for the writer.

Modes:

- default            one summary line per ledger kind + the latest bench
                     record per (rung, platform) group
- ``--check``        the REGRESSION GATE: for every (rung, platform) group,
                     compare the group's latest bench record against its
                     baseline and exit 1 when step time regressed by more
                     than ``--step-pct`` (default 25%) or peak HBM by more
                     than ``--hbm-pct`` (default 15%). Groups with no
                     baseline are reported as SKIP, never failed — a fresh
                     checkout with an empty ledger must pass CI.

Baseline resolution per (rung, platform) group, in order:

1. the banked evidence: valid records for the same rung AND platform in
   ``BASELINE_measured.json`` (the ``bench.is_banked_tpu_record`` predicate
   for TPU-class platforms — one freshness rule, no drift; non-TPU platforms
   take any non-stale/non-invalid record). Median when several.
2. the group's own PRIOR ledger records (everything before the latest).
   Median again — a one-off fast outlier must not turn every later honest
   run into a "regression".

Stale re-emits, dryrun-marked records, and ``error`` records are never
compared in either direction. Stays jax-free (imports bench.py, whose module
level is stdlib-only) so it can run over a wedged tunnel.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from bench import _TPU_PLATFORMS, is_banked_tpu_record  # noqa: E402

LEDGER_SCHEMA = "pa-perf-ledger/v1"


def _load_jsonl(path: str) -> list[dict]:
    out: list[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _comparable(rec: dict) -> bool:
    """A bench record the gate may compare: measured (not a stale re-emit or
    a mocked dry-run), with a positive numeric step time."""
    if rec.get("kind") != "bench" or rec.get("schema") != LEDGER_SCHEMA:
        return False
    if rec.get("stale") or rec.get("dryrun") or rec.get("invalid"):
        return False
    v = rec.get("value")
    return isinstance(v, (int, float)) and v > 0


def _group_key(rec: dict) -> tuple:
    return (rec.get("rung") or rec.get("metric") or "?",
            rec.get("platform") or "?")


def _banked_baseline(rung: str, platform: str, baseline_path: str
                     ) -> tuple[float | None, float | None]:
    """(median step time, median peak HBM) of the banked evidence records for
    this rung+platform, or (None, None)."""
    vals: list[float] = []
    hbm: list[float] = []
    for rec in _load_jsonl(baseline_path):
        if rec.get("rung") != rung or rec.get("platform") != platform:
            continue
        ok = (is_banked_tpu_record(rec) and not rec.get("dryrun")
              if platform in _TPU_PLATFORMS
              else not (rec.get("stale") or rec.get("invalid")
                        or rec.get("dryrun")))
        if not ok:
            continue
        v = rec.get("value")
        if isinstance(v, (int, float)) and v > 0:
            vals.append(float(v))
        p = rec.get("peak_hbm_bytes")
        if isinstance(p, (int, float)) and p > 0:
            hbm.append(float(p))
    return (statistics.median(vals) if vals else None,
            statistics.median(hbm) if hbm else None)


def _prior_baseline(prior: list[dict]) -> tuple[float | None, float | None]:
    vals = [float(r["value"]) for r in prior]
    hbm = [float(r["peak_hbm_bytes"]) for r in prior
           if isinstance(r.get("peak_hbm_bytes"), (int, float))
           and r["peak_hbm_bytes"] > 0]
    return (statistics.median(vals) if vals else None,
            statistics.median(hbm) if hbm else None)


def check(records: list[dict], baseline_path: str, step_pct: float,
          hbm_pct: float) -> int:
    """The gate. Prints one verdict line per group; returns the exit code."""
    groups: dict[tuple, list[dict]] = {}
    for rec in records:
        if _comparable(rec):
            groups.setdefault(_group_key(rec), []).append(rec)
    if not groups:
        print("perf_ledger: no comparable bench records in the ledger — OK "
              "(nothing to gate)")
        return 0
    failures = 0
    for (rung, platform), recs in sorted(groups.items()):
        latest, prior = recs[-1], recs[:-1]
        base_v, base_hbm = _banked_baseline(rung, platform, baseline_path)
        prior_v, prior_hbm = _prior_baseline(prior)
        source = "banked"
        if base_v is None:
            base_v = prior_v
            source = f"ledger[{len(prior)}]"
        if base_hbm is None:
            # Resolved independently of the step-time source: records banked
            # before round 9 carry no peak_hbm_bytes, and the HBM half of the
            # gate must not go inert just because a step-time baseline exists.
            base_hbm = prior_hbm
        if base_v is None:
            print(f"SKIP  {rung}/{platform}: no baseline "
                  f"(latest {latest['value']} s/it)")
            continue
        v = float(latest["value"])
        ratio = v / base_v
        verdict = []
        if ratio > 1.0 + step_pct / 100.0:
            verdict.append(
                f"step time {v:.4g} s/it vs baseline {base_v:.4g} "
                f"({ratio:.2f}x > +{step_pct:g}%)"
            )
        p = latest.get("peak_hbm_bytes")
        if (base_hbm and isinstance(p, (int, float)) and p > 0
                and p / base_hbm > 1.0 + hbm_pct / 100.0):
            verdict.append(
                f"peak HBM {p / 2**30:.2f} GiB vs baseline "
                f"{base_hbm / 2**30:.2f} GiB "
                f"({p / base_hbm:.2f}x > +{hbm_pct:g}%)"
            )
        if verdict:
            failures += 1
            print(f"REGRESSION  {rung}/{platform} [{source}]: "
                  + "; ".join(verdict))
        else:
            print(f"OK    {rung}/{platform} [{source}]: {v:.4g} s/it "
                  f"({ratio:.2f}x baseline)")
    if failures:
        print(f"perf_ledger: {failures} regressed group(s)")
        return 1
    print("perf_ledger: no regressions")
    return 0


def summarize(records: list[dict]) -> None:
    kinds: dict[str, int] = {}
    for rec in records:
        kinds[rec.get("kind", "?")] = kinds.get(rec.get("kind", "?"), 0) + 1
    print(f"{len(records)} ledger record(s): "
          + (", ".join(f"{k}={n}" for k, n in sorted(kinds.items()))
             or "none"))
    latest: dict[tuple, dict] = {}
    for rec in records:
        if _comparable(rec):
            latest[_group_key(rec)] = rec
    for (rung, platform), rec in sorted(latest.items()):
        extras = []
        if rec.get("compile_time_s") is not None:
            extras.append(f"compile {rec['compile_time_s']}s "
                          f"(hits {rec.get('compile_cache_hits')}, "
                          f"misses {rec.get('compile_cache_misses')})")
        if isinstance(rec.get("peak_hbm_bytes"), (int, float)):
            extras.append(f"peak {rec['peak_hbm_bytes'] / 2**30:.2f} GiB")
        print(f"  {rung}/{platform}: {rec.get('value')} {rec.get('unit', '')}"
              + (" — " + ", ".join(extras) if extras else ""))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=None,
                    help="ledger file or directory (default: $PA_LEDGER_DIR "
                         "or <evidence dir>/ledger)")
    ap.add_argument("--baseline", default=None,
                    help="banked evidence file (default: <evidence dir>/"
                         "BASELINE_measured.json)")
    ap.add_argument("--check", action="store_true",
                    help="run the regression gate (exit 1 on regression)")
    ap.add_argument("--step-pct", type=float, default=25.0,
                    help="max tolerated step-time growth vs baseline (%%)")
    ap.add_argument("--hbm-pct", type=float, default=15.0,
                    help="max tolerated peak-HBM growth vs baseline (%%)")
    args = ap.parse_args()

    from bench import evidence_dir

    ledger = (args.ledger or os.environ.get("PA_LEDGER_DIR")
              or os.path.join(evidence_dir(), "ledger"))
    if os.path.isdir(ledger):
        ledger = os.path.join(ledger, "perf_ledger.jsonl")
    baseline = args.baseline or os.path.join(
        evidence_dir(), "BASELINE_measured.json"
    )
    records = _load_jsonl(ledger)
    if args.check:
        sys.exit(check(records, baseline, args.step_pct, args.hbm_pct))
    summarize(records)


if __name__ == "__main__":
    main()
