"""Roofline attribution report, schema gate, and calibration bank.

The measured side of every run lives in the perf ledger
(``ledger/perf_ledger.jsonl``); the predicted side rides the same records as
``predicted_step_s`` / ``predicted_step_raw_s`` / ``roofline_ratio`` /
``attribution`` / ``roofline_programs`` (bench.py + dryrun, written via
``utils/roofline.py``). This script is the offline consumer — the exact
audit/gate/bank trio scripts/perf_ledger.py and scripts/numerics_audit.py
established:

- default      one line per (rung, platform) group: predicted vs actual,
               ratio, attribution fractions, FLOPs source — plus the
               calibration store's current key count.
- ``--check``  the SCHEMA GATE (wired into scripts/ci_tier1.sh after the
               perf and numerics gates): for the latest roofline-carrying
               record per group, ``roofline_ratio`` must sit in (0, 1.2]
               (a prediction more than 1.2x the measured time means the
               model or its calibration is lying), every attribution bucket
               must be non-negative, and the buckets must sum to within 10%
               of the recorded wall. Records without roofline fields (the
               pre-round-13 history) are skipped; an empty/unroofed ledger
               is SKIP, never a failure.
- ``--bank``   fit per-(program, platform, shape-bucket) calibration scales
               from the FULL ledger history (scale = conservative p25 of
               actual / predicted_raw — always against the raw prediction
               so re-banking converges, and below-median so an honest
               speedup doesn't trip the fixed (0, 1.2] band) and persist to
               ``ledger/roofline_calib.json``. Run after banking new
               hardware evidence — the next run's predictions are then
               self-corrected.

Stays jax-free: ``utils/roofline.py`` is loaded standalone by file path (its
module level is stdlib-only and free of package-relative imports by
contract), so this runs over a wedged tunnel or on a laptop with just the
ledger.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

LEDGER_SCHEMA = "pa-perf-ledger/v1"


def _load_roofline():
    """utils/roofline.py loaded standalone — no package import, no jax."""
    path = os.path.join(_REPO, "comfyui_parallelanything_tpu", "utils",
                        "roofline.py")
    spec = importlib.util.spec_from_file_location("pa_roofline", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


roofline = _load_roofline()

ATTR_BUCKETS = ("compute_s", "exposed_transfer_s", "comms_s", "host_gap_s")


def _carries_roofline(rec: dict) -> bool:
    """A record this gate may judge: a measured bench/dryrun line (never a
    stale re-emit or error record) that actually carries a roofline ratio —
    the pre-roofline history and null-filled stale lines are out of scope."""
    if rec.get("schema") != LEDGER_SCHEMA:
        return False
    if rec.get("kind") not in ("bench", "dryrun"):
        return False
    if rec.get("stale") or rec.get("invalid"):
        return False
    return isinstance(rec.get("roofline_ratio"), (int, float))


def _group_key(rec: dict) -> str:
    return (f"{rec.get('rung') or rec.get('metric') or '?'}/"
            f"{rec.get('platform') or '?'}")


def _check_attribution(attr) -> list[str]:
    """Bucket sanity: non-negative, and Σ buckets within 10% of the wall."""
    problems: list[str] = []
    if attr is None:
        return problems  # an untraced run legitimately carries null
    if not isinstance(attr, dict):
        return [f"attribution is not an object: {attr!r}"]
    for b in ATTR_BUCKETS:
        v = attr.get(b)
        if not isinstance(v, (int, float)) or v < 0:
            problems.append(f"attribution bucket {b} not non-negative: {v!r}")
    wall = attr.get("wall_s")
    if isinstance(wall, (int, float)) and wall > 0:
        total = sum(
            attr.get(b) for b in ATTR_BUCKETS
            if isinstance(attr.get(b), (int, float))
        )
        if not 0.9 * wall <= total <= 1.1 * wall:
            problems.append(
                f"attribution buckets sum {total:.4g}s vs wall "
                f"{wall:.4g}s (outside the 10% band)"
            )
    return problems


def check(records: list[dict]) -> int:
    """The gate: latest roofline-carrying record per group; exit 1 on any
    out-of-band ratio or malformed attribution."""
    groups: dict[str, dict] = {}
    for rec in records:
        if _carries_roofline(rec):
            groups[_group_key(rec)] = rec  # latest wins (file order)
    if not groups:
        print("roofline_report: no roofline-carrying records in the ledger "
              "— SKIP (nothing to gate)")
        return 0
    failures = 0
    for key, rec in sorted(groups.items()):
        ratio = rec["roofline_ratio"]
        problems = []
        if not 0.0 < ratio <= 1.2:
            problems.append(
                f"roofline_ratio {ratio} outside (0, 1.2] — the analytic "
                "model (or its calibration) disagrees with the clock"
            )
        problems += _check_attribution(rec.get("attribution"))
        if problems:
            failures += 1
            print(f"FAIL  {key}: " + "; ".join(problems))
        else:
            print(f"OK    {key}: ratio {ratio} "
                  f"(predicted {rec.get('predicted_step_s')}s, "
                  f"measured {rec.get('value')}{rec.get('unit', '')})")
    if failures:
        print(f"roofline_report: {failures} failed group(s)")
        return 1
    print("roofline_report: roofline schema sane")
    return 0


def bank(records: list[dict], calib_file: str) -> int:
    """Fit + persist the calibration store from the full ledger history."""
    scales = roofline.fit_calibration(records)
    if not scales:
        print("roofline_report: nothing to bank (no records carry both a "
              "raw prediction and a measurement)")
        return 1
    path = roofline.save_calibration(scales, calib_file)
    if path is None:
        print(f"roofline_report: could not write {calib_file}")
        return 1
    for key, entry in sorted(scales.items()):
        print(f"BANK  {key}: scale {entry['scale']} (n={entry['n']})")
    print(f"calibration written to {path} ({len(scales)} key(s))")
    return 0


def summarize(records: list[dict], calib_file: str) -> None:
    latest: dict[str, dict] = {}
    total = 0
    for rec in records:
        if _carries_roofline(rec):
            total += 1
            latest[_group_key(rec)] = rec
    calib = roofline.load_calibration(calib_file)
    print(f"{total} roofline-carrying record(s) across {len(latest)} "
          f"group(s); {len(calib)} calibration key(s) banked")
    for key, rec in sorted(latest.items()):
        fr = roofline.attribution_fractions(rec.get("attribution"))
        attr_txt = (
            "untraced" if fr is None else
            f"compute {fr['compute_fraction']:.0%} / transfer "
            f"{fr['exposed_transfer_fraction']:.0%} / comms "
            f"{fr['comms_fraction']:.0%} / host-gap "
            f"{fr['host_gap_fraction']:.0%}"
        )
        progs = rec.get("roofline_programs")
        print(f"  {key}: predicted {rec.get('predicted_step_s')}s vs "
              f"measured {rec.get('value')} (ratio "
              f"{rec.get('roofline_ratio')}, flops_source "
              f"{rec.get('flops_source')}); {attr_txt}"
              + (f"; {len(progs)} program row(s)"
                 if isinstance(progs, dict) else ""))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=None,
                    help="ledger file or directory (default: $PA_LEDGER_DIR "
                         "or <evidence dir>/ledger)")
    ap.add_argument("--calib", default=None,
                    help="calibration store (default: <ledger dir>/"
                         f"{roofline.CALIB_FILENAME})")
    ap.add_argument("--check", action="store_true",
                    help="run the schema gate (exit 1 on an out-of-band "
                         "ratio or malformed attribution)")
    ap.add_argument("--bank", action="store_true",
                    help="fit calibration scales from ledger history and "
                         "persist them")
    args = ap.parse_args()

    from bench import evidence_dir

    ledger = (args.ledger or os.environ.get("PA_LEDGER_DIR")
              or os.path.join(evidence_dir(), "ledger"))
    if ledger.endswith(".jsonl"):
        ledger_dir = os.path.dirname(ledger) or "."
    else:
        ledger_dir = ledger
        ledger = os.path.join(ledger, "perf_ledger.jsonl")
    calib_file = args.calib or os.path.join(ledger_dir,
                                            roofline.CALIB_FILENAME)
    records = roofline.load_jsonl(ledger)
    if args.bank:
        sys.exit(bank(records, calib_file))
    if args.check:
        sys.exit(check(records))
    summarize(records, calib_file)


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        pass
