"""Per-op-class MFU budget for a bench rung (VERDICT r4 next-2).

The round-3 hardware table shows sd15_16 at 8.6% MFU while sdxl_8 hits 40% on
the same chip — a 4.7× gap that needs a *budget* (where do the 91% of cycles
go?) before a live window can fix it. This script produces that budget WITHOUT
hardware: it traces the rung's denoise-step jaxpr, walks every equation
(recursing into pjit/closed-call subjaxprs), and buckets exact FLOPs and
memory traffic by op class:

- ``conv``       — conv_general_dilated (the UNet trunk)
- ``matmul``     — dot_general (attention projections, transformer MLPs,
                   attention score/value products)
- ``attention``  — the dot_generals of attention score/value products
                   (contraction or output dim is a sequence length from this
                   trace) — split out because lane-padding waste lives here
- ``elementwise`` — everything else, costed by bytes touched (norms,
                   activations, softmax, residual adds)

Roofline projection per class (v5e-1: 197 bf16 TFLOP/s, 819 GB/s HBM):
``t_class = max(flops / peak_flops, bytes / hbm_bw)``. The MXU-waste model
additionally reports matmul time at the PADDED contraction width (lane
granularity 128): a 40-wide head dim costs the MXU the same as 128 — the
padded/unpadded ratio is the ceiling a lane-respecting kernel can claw back.

Output: a table on stdout + ``MFU_BUDGET.json`` next to the other evidence
artifacts. Run for any rung: ``BENCH_CONFIG=sd15_16 python scripts/mfu_budget.py``.
CPU-safe (pure tracing; nothing executes).
"""

from __future__ import annotations

import json
import math
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

PEAK_FLOPS = 197e12  # v5e bf16
HBM_BW = 819e9       # v5e HBM bytes/s
LANE = 128           # MXU lane granularity


def _nbytes(aval) -> int:
    return math.prod(aval.shape) * aval.dtype.itemsize if aval.shape else aval.dtype.itemsize


def _dot_flops(eqn):
    """Exact dot_general FLOPs (2·M·N·K over batch dims) + the lane-padded
    variant (contraction and output dims rounded up to LANE)."""
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    k = math.prod(lhs.shape[d] for d in lc)
    b = math.prod(lhs.shape[d] for d in lb)
    m = math.prod(
        lhs.shape[d] for d in range(len(lhs.shape)) if d not in (*lc, *lb)
    )
    n = math.prod(
        rhs.shape[d] for d in range(len(rhs.shape)) if d not in (*rc, *rb)
    )
    pad = lambda v: -(-v // LANE) * LANE  # noqa: E731
    return 2 * b * m * n * k, 2 * b * pad(m) * pad(n) * pad(k), (m, n, k, b)


def _conv_flops(eqn):
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval  # kernel (spatial..., in/feature, out) per dnums
    # 2 · out_elements · (kernel elements per output) — feature_group_count
    # divides the per-output kernel work.
    groups = eqn.params.get("feature_group_count", 1)
    kernel_per_out = math.prod(rhs.shape[:-1]) // max(groups, 1)
    flops = 2 * math.prod(out.shape) * kernel_per_out
    return flops, flops  # convs lower through MXU-shaped patches; no extra pad model


def _subjaxprs(eqn):
    """Inner jaxprs of one equation (pjit/scan/cond/custom-call params)."""
    from jax.extend import core as jex_core

    closed = getattr(jex_core, "ClosedJaxpr", None)
    bare = getattr(jex_core, "Jaxpr", None)
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if closed is not None and isinstance(x, closed):
                yield x.jaxpr
            elif bare is not None and isinstance(x, bare):
                yield x


def walk(jaxpr, acc, seq_lens):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        for sub in _subjaxprs(eqn):  # recurse into pjit/scan/cond
            walk(sub, acc, seq_lens)
        if name == "dot_general":
            f, fpad, (m, n, k, b) = _dot_flops(eqn)
            cls = "matmul"
            # Attention score/value products: QK^T contracts the head dim
            # (k ≤ 256) against a full sequence (m or n ∈ seq_lens — the
            # chunked path keeps full length only on the K side); PV
            # contracts the sequence itself (k ∈ seq_lens). This is where
            # 40/80/160-wide-head lane padding concentrates.
            if (k in seq_lens) or (
                (m in seq_lens or n in seq_lens) and k <= 256
            ):
                cls = "attention"
            acc[cls]["flops"] += f
            acc[cls]["flops_padded"] += fpad
            acc[cls]["bytes"] += sum(_nbytes(v.aval) for v in eqn.invars)
            acc[cls]["bytes"] += sum(_nbytes(v.aval) for v in eqn.outvars)
            acc[cls]["count"] += 1
        elif name == "conv_general_dilated":
            f, fpad = _conv_flops(eqn)
            acc["conv"]["flops"] += f
            acc["conv"]["flops_padded"] += fpad
            acc["conv"]["bytes"] += sum(_nbytes(v.aval) for v in eqn.invars)
            acc["conv"]["bytes"] += sum(_nbytes(v.aval) for v in eqn.outvars)
            acc["conv"]["count"] += 1
        elif not eqn.primitive.multiple_results or name in ("scan", "while"):
            byts = sum(_nbytes(v.aval) for v in eqn.invars if hasattr(v, "aval"))
            byts += sum(_nbytes(v.aval) for v in eqn.outvars)
            acc["elementwise"]["flops"] += math.prod(
                eqn.outvars[0].aval.shape
            ) if eqn.outvars and eqn.outvars[0].aval.shape else 0
            acc["elementwise"]["bytes"] += byts
            acc["elementwise"]["count"] += 1
            acc.setdefault("_by_prim", {}).setdefault(name, [0, 0])
            acc["_by_prim"][name][0] += 1
            acc["_by_prim"][name][1] += byts


def analytic_flops(apply, params, x, t, ctx, kwargs=None):
    """Total model FLOPs of ONE forward step from the exact jaxpr walk —
    bench.py's fallback when XLA HLO cost analysis returns nothing (VERDICT
    r5 next-6: zimage_21_int8 banked ``mfu: null``; observed on the
    QuantTensor int8 rungs). Sums every op class; elementwise FLOPs are the
    output-element count, a rounding error next to the matmuls. Pure tracing —
    nothing executes, CPU-safe."""
    import jax as _jax

    kw = dict(kwargs or {})
    jaxpr = _jax.make_jaxpr(
        lambda p, x_, t_, c_: apply(p, x_, t_, c_, **kw)
    )(params, x, t, ctx)
    acc = {
        c: {"flops": 0, "flops_padded": 0, "bytes": 0, "count": 0}
        for c in ("conv", "matmul", "attention", "elementwise")
    }
    walk(jaxpr.jaxpr, acc, set())
    acc.pop("_by_prim", None)
    total = float(sum(c["flops"] for c in acc.values()))
    return total if total > 0 else None


def main():
    global jax
    import jax
    import jax.numpy as jnp

    import bench

    rung = os.environ.get("BENCH_CONFIG", "sd15_16")
    model, batch, lat_shape, ctx_len, ctx_dim, kwargs, workload, *mb = (
        bench._RUNGS[rung](jnp, jax.random.key(0))
    )
    x = jnp.zeros(lat_shape, jnp.bfloat16)
    t = jnp.zeros((batch,), jnp.float32)
    ctx = jnp.zeros((batch, ctx_len, ctx_dim), jnp.bfloat16)

    jaxpr = jax.make_jaxpr(
        lambda p, x, t, c: model.apply(p, x, t, c, **kwargs)
    )(model.params, x, t, ctx)

    # Sequence lengths that can appear as attention S×S outputs: every
    # spatial-token count at the UNet/DiT resolutions in this trace.
    side = lat_shape[1]
    seq_lens = {ctx_len}
    for s in range(8):
        if side >> s:
            seq_lens.add((side >> s) * (lat_shape[2] >> s))

    acc = {
        c: {"flops": 0, "flops_padded": 0, "bytes": 0, "count": 0}
        for c in ("conv", "matmul", "attention", "elementwise")
    }
    walk(jaxpr.jaxpr, acc, seq_lens)
    by_prim = acc.pop("_by_prim", {})

    total_flops = sum(c["flops"] for c in acc.values())
    rows, total_ms = [], 0.0
    for cls, c in acc.items():
        t_flops = c["flops"] / PEAK_FLOPS
        t_pad = c["flops_padded"] / PEAK_FLOPS
        t_mem = c["bytes"] / HBM_BW
        t_cls = max(t_pad, t_mem)
        total_ms += t_cls * 1e3
        rows.append({
            "class": cls, "count": c["count"], "gflops": c["flops"] / 1e9,
            "gflops_padded": c["flops_padded"] / 1e9,
            "gbytes": c["bytes"] / 1e9,
            "ms_compute": t_flops * 1e3, "ms_padded": t_pad * 1e3,
            "ms_memory": t_mem * 1e3, "ms_roofline": t_cls * 1e3,
            "bound": "memory" if t_mem > t_pad else "compute",
        })
    out = {
        "rung": rung, "workload": workload, "batch": batch,
        "total_model_gflops": total_flops / 1e9,
        "ideal_s_it": total_flops / PEAK_FLOPS,
        "roofline_s_it": total_ms / 1e3,
        "roofline_mfu": (total_flops / PEAK_FLOPS) / (total_ms / 1e3)
        if total_ms else None,
        "classes": rows,
        "peak_flops": PEAK_FLOPS, "hbm_bw": HBM_BW,
    }
    path = os.path.join(bench.evidence_dir(), "MFU_BUDGET.json")
    existing = []
    if os.path.exists(path):
        existing = json.load(open(path))
        if not isinstance(existing, list):
            existing = [existing]
    existing = [e for e in existing if e.get("rung") != rung] + [out]
    json.dump(existing, open(path, "w"), indent=1)

    hdr = (f"{'class':18} {'n':>5} {'GFLOP':>10} {'GFLOP(pad)':>11} "
           f"{'GB':>8} {'ms@peak':>8} {'ms(pad)':>8} {'ms(mem)':>8} "
           f"{'roofline':>9} bound")
    print(hdr)
    for r in rows:
        print(f"{r['class']:18} {r['count']:>5} {r['gflops']:>10.1f} "
              f"{r['gflops_padded']:>11.1f} {r['gbytes']:>8.2f} "
              f"{r['ms_compute']:>8.2f} {r['ms_padded']:>8.2f} "
              f"{r['ms_memory']:>8.2f} {r['ms_roofline']:>9.2f} {r['bound']}")
    top = sorted(by_prim.items(), key=lambda kv: -kv[1][1])[:8]
    out["elementwise_top"] = [
        {"prim": k, "count": v[0], "gbytes": v[1] / 1e9} for k, v in top
    ]
    print("\nelementwise top contributors (UNFUSED bytes — XLA fuses most;"
          " ranking, not prediction):")
    for k, v in top:
        print(f"  {k:28} n={v[0]:>5}  {v[1]/1e9:>8.2f} GB")
    print(f"\nrung={rung}  model={total_flops/1e12:.2f} TFLOP/step  "
          f"ideal={out['ideal_s_it']*1e3:.1f} ms/it  "
          f"unfused-roofline={total_ms:.1f} ms/it  "
          f"unfused-roofline-MFU={out['roofline_mfu']:.1%}")
    print(f"budget written to {path}")


if __name__ == "__main__":
    main()
