"""Per-op-class MFU budget for a bench rung (VERDICT r4 next-2).

The round-3 hardware table shows sd15_16 at 8.6% MFU while sdxl_8 hits 40% on
the same chip — a 4.7× gap that needs a *budget* (where do the 91% of cycles
go?) before a live window can fix it. This script produces that budget WITHOUT
hardware: it traces the rung's denoise-step jaxpr, walks every equation
(recursing into pjit/closed-call subjaxprs), and buckets exact FLOPs and
memory traffic by op class:

- ``conv``       — conv_general_dilated (the UNet trunk)
- ``matmul``     — dot_general (attention projections, transformer MLPs,
                   attention score/value products)
- ``attention``  — the dot_generals of attention score/value products
                   (contraction or output dim is a sequence length from this
                   trace) — split out because lane-padding waste lives here
- ``elementwise`` — everything else, costed by bytes touched (norms,
                   activations, softmax, residual adds)

Roofline projection per class (v5e-1: 197 bf16 TFLOP/s, 819 GB/s HBM):
``t_class = max(flops / peak_flops, bytes / hbm_bw)``. The MXU-waste model
additionally reports matmul time at the PADDED contraction width (lane
granularity 128): a 40-wide head dim costs the MXU the same as 128 — the
padded/unpadded ratio is the ceiling a lane-respecting kernel can claw back.

Output: a table on stdout + ``MFU_BUDGET.json`` next to the other evidence
artifacts. Run for any rung: ``BENCH_CONFIG=sd15_16 python scripts/mfu_budget.py``.
CPU-safe (pure tracing; nothing executes).
"""

from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The jaxpr walk lives in utils/roofline.py now (ONE FLOPs counter shared by
# this budget, bench.py's step-cost accessor, and the roofline layer — the
# two sources can no longer silently disagree); this script keeps the
# per-op-class presentation over it. Re-exported names (walk/analytic_flops)
# keep the historical entry points working. Loaded STANDALONE by file path
# (the scripts/roofline_report.py pattern): importing through the package
# `__init__` chain pulls jax at module level, which wedges this script's
# startup whenever the TPU tunnel is down — the standalone-contract drift
# palint's pass now fails CI on.


def _load_roofline():
    import importlib.util

    path = os.path.join(_REPO, "comfyui_parallelanything_tpu", "utils",
                        "roofline.py")
    spec = importlib.util.spec_from_file_location("pa_roofline_mfu", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_roofline = _load_roofline()
analytic_flops = _roofline.analytic_flops  # re-export (bench's fallback)
empty_acc = _roofline.empty_acc
walk = _roofline.walk_jaxpr

PEAK_FLOPS = 197e12  # v5e bf16
HBM_BW = 819e9       # v5e HBM bytes/s
# (the MXU 128-lane padding model lives with the walk in utils/roofline.py)


def main():
    global jax
    import jax
    import jax.numpy as jnp

    import bench

    rung = os.environ.get("BENCH_CONFIG", "sd15_16")
    model, batch, lat_shape, ctx_len, ctx_dim, kwargs, workload, *mb = (
        bench._RUNGS[rung](jnp, jax.random.key(0))
    )
    x = jnp.zeros(lat_shape, jnp.bfloat16)
    t = jnp.zeros((batch,), jnp.float32)
    ctx = jnp.zeros((batch, ctx_len, ctx_dim), jnp.bfloat16)

    jaxpr = jax.make_jaxpr(
        lambda p, x, t, c: model.apply(p, x, t, c, **kwargs)
    )(model.params, x, t, ctx)

    # Sequence lengths that can appear as attention S×S outputs: every
    # spatial-token count at the UNet/DiT resolutions in this trace.
    side = lat_shape[1]
    seq_lens = {ctx_len}
    for s in range(8):
        if side >> s:
            seq_lens.add((side >> s) * (lat_shape[2] >> s))

    acc = empty_acc()
    walk(jaxpr.jaxpr, acc, seq_lens)
    by_prim = acc.pop("_by_prim", {})

    total_flops = sum(c["flops"] for c in acc.values())
    rows, total_ms = [], 0.0
    for cls, c in acc.items():
        t_flops = c["flops"] / PEAK_FLOPS
        t_pad = c["flops_padded"] / PEAK_FLOPS
        t_mem = c["bytes"] / HBM_BW
        t_cls = max(t_pad, t_mem)
        total_ms += t_cls * 1e3
        rows.append({
            "class": cls, "count": c["count"], "gflops": c["flops"] / 1e9,
            "gflops_padded": c["flops_padded"] / 1e9,
            "gbytes": c["bytes"] / 1e9,
            "ms_compute": t_flops * 1e3, "ms_padded": t_pad * 1e3,
            "ms_memory": t_mem * 1e3, "ms_roofline": t_cls * 1e3,
            "bound": "memory" if t_mem > t_pad else "compute",
        })
    out = {
        "rung": rung, "workload": workload, "batch": batch,
        "total_model_gflops": total_flops / 1e9,
        "ideal_s_it": total_flops / PEAK_FLOPS,
        "roofline_s_it": total_ms / 1e3,
        "roofline_mfu": (total_flops / PEAK_FLOPS) / (total_ms / 1e3)
        if total_ms else None,
        "classes": rows,
        "peak_flops": PEAK_FLOPS, "hbm_bw": HBM_BW,
    }
    path = os.path.join(bench.evidence_dir(), "MFU_BUDGET.json")
    existing = []
    if os.path.exists(path):
        existing = json.load(open(path))
        if not isinstance(existing, list):
            existing = [existing]
    existing = [e for e in existing if e.get("rung") != rung] + [out]
    json.dump(existing, open(path, "w"), indent=1)

    hdr = (f"{'class':18} {'n':>5} {'GFLOP':>10} {'GFLOP(pad)':>11} "
           f"{'GB':>8} {'ms@peak':>8} {'ms(pad)':>8} {'ms(mem)':>8} "
           f"{'roofline':>9} bound")
    print(hdr)
    for r in rows:
        print(f"{r['class']:18} {r['count']:>5} {r['gflops']:>10.1f} "
              f"{r['gflops_padded']:>11.1f} {r['gbytes']:>8.2f} "
              f"{r['ms_compute']:>8.2f} {r['ms_padded']:>8.2f} "
              f"{r['ms_memory']:>8.2f} {r['ms_roofline']:>9.2f} {r['bound']}")
    top = sorted(by_prim.items(), key=lambda kv: -kv[1][1])[:8]
    out["elementwise_top"] = [
        {"prim": k, "count": v[0], "gbytes": v[1] / 1e9} for k, v in top
    ]
    print("\nelementwise top contributors (UNFUSED bytes — XLA fuses most;"
          " ranking, not prediction):")
    for k, v in top:
        print(f"  {k:28} n={v[0]:>5}  {v[1]/1e9:>8.2f} GB")
    print(f"\nrung={rung}  model={total_flops/1e12:.2f} TFLOP/step  "
          f"ideal={out['ideal_s_it']*1e3:.1f} ms/it  "
          f"unfused-roofline={total_ms:.1f} ms/it  "
          f"unfused-roofline-MFU={out['roofline_mfu']:.1%}")
    print(f"budget written to {path}")


if __name__ == "__main__":
    main()
