"""observability: no bare print()/time.time() in the package.

Migrated from ``tests/test_telemetry.py::TestObservabilityLint`` (round 9)
so there is ONE lint engine: the package's output vocabulary is spans,
logs, and metrics (``utils/{tracing,logging,metrics}.py`` — the PARITY
print-site mapping), and its duration clocks are monotonic
(``StepTimer``/``time.monotonic``/``perf_counter``). A bare ``print()`` is
invisible to every collector; an ad-hoc ``time.time()`` difference breaks
under clock steps.

The old CENTRAL allowlists (path-suffix + marker tuples in the test file)
are now per-line pragmas next to the code they excuse —
``# palint: allow[observability] <why>`` — so the justification lives
in-line, and the engine's stale-pragma check replaces
``test_allowlist_entries_still_exist``. Legitimate sites: CLI banners
(server/router/host ``__main__``), and wall-clock EPOCH STAMPS on
persisted/advertised records (ledger ts, journal ts — where wall-clock is
the one clock two processes share).

scripts/, bench.py and tests/ stay exempt (CLI surfaces by design).
"""

from __future__ import annotations

import re

NAME = "observability"
DOC = "no bare print()/time.time() in the package (spans/logs/metrics only)"

_PRINT_RE = re.compile(r"^\s*print\(")
_TIME_RE = re.compile(r"\btime\.time\(")


def run(ctx) -> list[dict]:
    findings: list[dict] = []
    for f in ctx.package_files():
        for i, line in enumerate(f.lines, 1):
            comment = f.comments.get(i)
            if comment:
                cut = line.rfind(comment)
                if cut >= 0:  # match against code only, not the comment
                    line = line[:cut]
            if _PRINT_RE.match(line):
                findings.append({
                    "path": f.rel, "line": i, "code": "bare-print",
                    "message": "bare print() in the package — use "
                               "utils/logging (or justify with a pragma: "
                               "CLI banners only)",
                })
            if _TIME_RE.search(line) and not line.lstrip().startswith("#"):
                findings.append({
                    "path": f.rel, "line": i, "code": "ad-hoc-time",
                    "message": "time.time() in the package — durations use "
                               "monotonic clocks (StepTimer/tracing); "
                               "wall-clock epoch STAMPS justify a pragma",
                })
    return findings
