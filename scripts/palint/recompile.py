"""recompile-hazard: jit cache-key stability at the program-cache sites.

Every compiled program in this stack is cached under a stable name
(``utils/telemetry.instrument_jit``) and a hashable cache key — PR 10
dropped the sampler from the serving bucket key precisely so traffic mix
can't recompile, and the roofline/compile registries key per-program
accounting off those names. Three ways that quietly rots:

- **dynamic program names** at ``instrument_jit`` sites: an f-string /
  ``%``-format / ``.format()``/concat name mints a new program identity per
  value — unbounded registry cardinality and per-value compile accounting.
  The two legitimate sites (stage-carve names — the stage span IS part of
  program identity, bounded by the carve count) carry justified pragmas.
- **unhashable static args**: a parameter declared in ``static_argnums`` /
  ``static_argnames`` whose default (or call-site value, same module) is a
  list/dict/set raises at trace time — or, for arrays smuggled through
  ``static_argnames``, recompiles every call.
- **mutable default kwargs** in the modules that build jit cache keys
  (sampling/, parallel/, serving/, models/api.py, utils/telemetry.py): a
  shared default dict flowing into a cache key makes the key aliasable and
  order-dependent. (The package is currently clean — this keeps it so.)
"""

from __future__ import annotations

import ast

NAME = "recompile-hazard"
DOC = "jit cache keys: stable names, hashable statics, no mutable defaults"

# Files whose functions feed jit cache keys (program caches, bucket keys,
# loop-program keys): mutable defaults are flagged here.
CACHE_KEY_DIRS = (
    "comfyui_parallelanything_tpu/sampling/",
    "comfyui_parallelanything_tpu/parallel/",
    "comfyui_parallelanything_tpu/serving/",
    "comfyui_parallelanything_tpu/models/api.py",
    "comfyui_parallelanything_tpu/utils/telemetry.py",
)

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "defaultdict",
                  "deque", "OrderedDict", "Counter"}


def _is_mutable(node) -> bool:
    if isinstance(node, _MUTABLE_LITERALS):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        return name in _MUTABLE_CTORS
    return False


def _is_jit_call(node: ast.Call) -> str | None:
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "instrument_jit":
        return "instrument_jit"
    if isinstance(fn, ast.Attribute) and fn.attr == "instrument_jit":
        return "instrument_jit"
    if isinstance(fn, ast.Attribute) and fn.attr == "jit" and \
            isinstance(fn.value, ast.Name) and fn.value.id == "jax":
        return "jax.jit"
    return None


def _dynamic_string(node) -> bool:
    """True when the expression builds a string at runtime (f-string,
    %-format, .format(), +-concat of non-constants)."""
    if isinstance(node, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in node.values)
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Mod, ast.Add)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "format":
        return True
    return False


def _static_param_names(node: ast.Call, fn_def) -> list[str]:
    """Parameter names declared static by this jit call, resolvable against
    ``fn_def`` (the wrapped function's def in the same module) or directly
    from static_argnames literals."""
    names: list[str] = []
    for kw in node.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.append(el.value)
        elif kw.arg == "static_argnums" and fn_def is not None:
            idxs = [el.value for el in ast.walk(kw.value)
                    if isinstance(el, ast.Constant)
                    and isinstance(el.value, int)]
            params = [a.arg for a in fn_def.args.args]
            for i in idxs:
                if 0 <= i < len(params):
                    names.append(params[i])
    return names


def run(ctx) -> list[dict]:
    findings: list[dict] = []
    for f in ctx.files:
        if f.tree is None:
            continue
        in_pkg = f.rel.startswith("comfyui_parallelanything_tpu/") or \
            f.rel == "bench.py"
        if not in_pkg:
            continue
        # function defs by name (module-wide), for static-arg resolution
        # and mutable-default checks.
        all_defs: list = []
        defs: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                all_defs.append(node)
                defs.setdefault(node.name, node)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _is_jit_call(node)
            if kind is None:
                continue
            # (a) dynamic program name: instrument_jit(fn, <name>).
            if kind == "instrument_jit" and len(node.args) >= 2 and \
                    _dynamic_string(node.args[1]):
                findings.append({
                    "path": f.rel, "line": node.lineno,
                    "code": "dynamic-program-name",
                    "message": "program name built at runtime mints a new "
                               "program identity per value — unbounded "
                               "compile/roofline registry cardinality; use "
                               "a stable literal name",
                })
            # (b) unhashable statics: resolve the wrapped fn's def and
            # check declared-static params for mutable defaults.
            wrapped = None
            if node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Name):
                    wrapped = defs.get(a0.id)
            statics = _static_param_names(node, wrapped)
            if wrapped is not None and statics:
                args = wrapped.args
                pos = args.posonlyargs + args.args
                defaults = [None] * (len(pos) - len(args.defaults)) + \
                    list(args.defaults)
                kw = dict(zip([a.arg for a in args.kwonlyargs],
                              args.kw_defaults))
                for p, d in list(zip([a.arg for a in pos], defaults)) + \
                        list(kw.items()):
                    if p in statics and d is not None and _is_mutable(d):
                        findings.append({
                            "path": f.rel, "line": d.lineno,
                            "code": "unhashable-static",
                            "message": f"param `{p}` is declared static but "
                                       f"defaults to an unhashable mutable "
                                       f"— trace-time TypeError (or a "
                                       f"per-call recompile)",
                        })
        # (c) mutable default kwargs in cache-key-feeding modules.
        if any(f.rel.startswith(d) or f.rel == d for d in CACHE_KEY_DIRS):
            for fn_def in all_defs:
                args = fn_def.args
                for d in list(args.defaults) + \
                        [x for x in args.kw_defaults if x is not None]:
                    if _is_mutable(d):
                        findings.append({
                            "path": f.rel, "line": d.lineno,
                            "code": "mutable-default",
                            "message": f"mutable default in `{fn_def.name}` "
                                       f"— a shared instance flowing into a "
                                       f"jit cache key aliases across calls",
                        })
    return findings
