"""host-sync: no device sync/transfer inside timed loops or hot step paths.

The PR 3 discipline, now enforced: a ``block_until_ready`` / device→host
transfer inside a TIMED region books transfer time as compute (the exact
lie ``stream-prefetch-wait`` exists to prevent — "exposed transfer is
booked as wait, never compute"), and one inside a per-step hot path adds a
host round-trip to every sampler step. Two scopes:

1. **timed loops** (detected): a function that stamps
   ``t = time.perf_counter()`` and later computes ``time.perf_counter() -
   t`` brackets a timed window; any banned sync inside a ``for``/``while``
   loop within that window is flagged. (Syncs between the stamps but
   outside a loop are the closing boundary — ``StepTimer``'s honest-timing
   block — and are the loop-free pattern the repo's timers use.)

2. **hot step paths** (declared, :data:`HOT_PATHS`): the per-step compiled
   dispatch paths. EVERY banned sync there is flagged — the legitimate
   boundary syncs (the serving dispatch's completion block, streaming's
   backpressure and trace-mode prefetch-wait blocks) carry
   ``# palint: allow[host-sync]`` pragmas whose justifications ARE the
   discipline, reviewed in place; a new sync shows up as a finding.

Banned: ``block_until_ready``, ``jax.device_get``, ``np.asarray``,
``force_ready``, ``.item()``, and ``float(x[...])``/``float(f(...))``
(a float() on a subscript/call result is how device scalars leak to host
mid-loop; ``float(name)`` on a host scalar is not flagged).
"""

from __future__ import annotations

import ast

NAME = "host-sync"
DOC = "no host sync/transfer in timed loops or compiled-step hot paths"

# (path suffix, flattened qualname suffix) — the per-step hot paths. The
# bench timed loop itself is covered by scope 1 (chained_time) plus the
# `step` closure here.
HOT_PATHS = (
    ("comfyui_parallelanything_tpu/serving/bucket.py", "StepBucket.dispatch"),
    ("comfyui_parallelanything_tpu/serving/decode.py",
     "DecodeQueue._dispatch"),
    ("comfyui_parallelanything_tpu/parallel/streaming.py",
     "StreamingRunner.__call__"),
    ("bench.py", "step"),
)

_SYNC_ATTRS = {"block_until_ready", "device_get", "item"}
_SYNC_NAMES = {"force_ready"}


def _banned_call(node: ast.Call) -> str | None:
    """The banned-construct label for this call, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in _SYNC_ATTRS:
            return f".{fn.attr}()"
        # numpy's asarray is a device→host transfer; jnp.asarray is the
        # opposite direction (host→device staging) and stays legal.
        if fn.attr == "asarray" and isinstance(fn.value, ast.Name) and \
                fn.value.id in ("np", "numpy", "_np", "onp"):
            return f"{fn.value.id}.asarray()"
    elif isinstance(fn, ast.Name):
        if fn.id in _SYNC_NAMES:
            return f"{fn.id}()"
        if fn.id == "float" and node.args and isinstance(
                node.args[0], (ast.Subscript, ast.Call)):
            return "float(<device value>)"
    return None


def _functions(tree):
    """Yield (flattened qualname, node) for every function, including
    closures (qualname drops the `<locals>` hops: `Outer.inner`)."""
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)
    yield from walk(tree, "")


def _timed_window(fn_node):
    """(start_line, end_line) of the perf_counter()-bracketed region in
    this function's own body (nested defs excluded), or None."""
    def is_pc_call(node):
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "perf_counter")

    starts: dict[str, int] = {}
    end_by_name: dict[str, int] = {}
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and is_pc_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    starts.setdefault(t.id, node.lineno)
        elif (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
              and is_pc_call(node.left)
              and isinstance(node.right, ast.Name)):
            end_by_name[node.right.id] = max(
                end_by_name.get(node.right.id, 0), node.lineno)
    windows = [(starts[n], end_by_name[n]) for n in starts
               if n in end_by_name and end_by_name[n] > starts[n]]
    if not windows:
        return None
    return min(w[0] for w in windows), max(w[1] for w in windows)


def _loop_lines(fn_node, lo: int, hi: int) -> set[int]:
    """Lines inside for/while loops that start within [lo, hi] in this
    function (nested functions included — a closure dispatched per
    iteration is still the loop body)."""
    lines: set[int] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.For, ast.While)) and lo <= node.lineno <= hi:
            for sub in ast.walk(node):
                ln = getattr(sub, "lineno", None)
                if ln is not None:
                    lines.add(ln)
    return lines


def run(ctx) -> list[dict]:
    findings: list[dict] = []
    seen: set[tuple] = set()

    def add(f, node, label, why):
        key = (f.rel, node.lineno, label)
        if key in seen:
            return
        seen.add(key)
        findings.append({
            "path": f.rel, "line": node.lineno, "code": "sync-in-hot-path",
            "message": f"{label} {why} — the PR 3 discipline: exposed "
                       f"transfer is booked as wait, never compute",
        })

    for f in ctx.files:
        if f.tree is None or f.rel.startswith("scripts/"):
            continue
        hot_names = tuple(q for suffix, q in HOT_PATHS
                          if f.rel.endswith(suffix))
        for qual, fn_node in _functions(f.tree):
            is_hot = any(qual == q or qual.endswith("." + q)
                         for q in hot_names)
            window = _timed_window(fn_node)
            if not is_hot and window is None:
                continue
            loop_lines = (_loop_lines(fn_node, *window)
                          if window is not None else set())
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Call):
                    continue
                label = _banned_call(node)
                if label is None:
                    continue
                if is_hot:
                    add(f, node, label,
                        f"in hot step path `{qual}`")
                elif node.lineno in loop_lines:
                    add(f, node, label,
                        f"inside a loop in `{qual}`'s timed "
                        f"perf_counter window")
    return findings
