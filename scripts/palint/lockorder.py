"""lock-discipline: guarded-by annotations on the threaded tier, enforced.

The fleet/serving/utils tier runs ~20 locks across server threads, prompt
workers, monitor sweeps, heartbeats, and the serving dispatcher. The
discipline this pass enforces (the static half — ``utils/lockcheck.py``'s
runtime acquisition-order graph is the dynamic half, and the two
cross-check each other):

1. **inventory is explicit**: in any class whose ``__init__`` constructs a
   ``threading.Lock``/``RLock``, every mutable-container attribute assigned
   in ``__init__`` must be annotated — ``# guarded-by: <lock>`` when the
   lock protects it, or ``# unguarded: <reason>`` when it is deliberately
   free (single-writer, pre-thread-start, atomic by the GIL…). An
   unannotated shared container is the finding: nobody can review locking
   they can't see.
2. **guarded writes hold the lock**: a write to a ``guarded-by: L``
   attribute outside ``__init__`` must sit lexically inside ``with
   self.L:`` (or ``with L:`` for module-level locks), or in a method whose
   ``def`` line carries ``# palint: holds L`` (documents "caller holds
   it" — the RLock pattern). Writes are assignments, augmented assigns,
   ``del``, subscript stores, and the mutator calls (``append``/``pop``/
   ``update``/…). Reads are not checked (the tier reads stale-tolerant
   snapshots by design).

Module-level locks follow the same shape: ``NAME = threading.Lock()`` plus
``# guarded-by: NAME`` on the globals it protects.

Scope: the threaded tier only (fleet/, serving/, utils/, server.py,
host.py) — the model zoo is functional and thread-free by construction.
"""

from __future__ import annotations

import ast

NAME = "lock-discipline"
DOC = "guarded-by annotations present and writes hold the declared lock"

SCOPE_PREFIXES = (
    "comfyui_parallelanything_tpu/fleet/",
    "comfyui_parallelanything_tpu/serving/",
    "comfyui_parallelanything_tpu/utils/",
    "comfyui_parallelanything_tpu/server.py",
    "comfyui_parallelanything_tpu/host.py",
)

_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "popleft", "appendleft", "remove", "discard", "clear",
}
_CONTAINER_CTORS = {"dict", "list", "set", "defaultdict", "deque",
                    "OrderedDict", "Counter"}


def _is_lock_ctor(node) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("Lock", "RLock")
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("threading", "_threading"))


def _is_container(node) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        return name in _CONTAINER_CTORS
    return False


def _self_attr(node) -> str | None:
    """'attr' when node is `self.attr`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _write_targets(node):
    """Yield (kind, target-expr) for the writes this statement performs."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            yield "assign", t
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if getattr(node, "value", None) is not None or \
                isinstance(node, ast.AugAssign):
            yield "assign", node.target
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            yield "del", t


def _with_lock_names(with_node: ast.With) -> set[str]:
    """Lock names this `with` acquires: `self.X` → 'X', bare `X` → 'X'."""
    names: set[str] = set()
    for item in with_node.items:
        expr = item.context_expr
        # `with self._lock:` / `with _batch_lock:` / `with lock.acquire…`
        a = _self_attr(expr)
        if a:
            names.add(a)
        elif isinstance(expr, ast.Name):
            names.add(expr.id)
        elif isinstance(expr, ast.Call):
            a = _self_attr(expr.func)
            if a:
                names.add(a)
            elif isinstance(expr.func, ast.Name):
                names.add(expr.func.id)
    return names


class _ClassInfo:
    def __init__(self, node):
        self.node = node
        self.locks: set[str] = set()          # lock attr names
        self.guarded: dict[str, str] = {}     # attr -> lock name
        self.annotated: set[str] = set()      # attrs with any annotation
        self.container_attrs: dict[str, int] = {}  # attr -> init line
        # `self._cond = threading.Condition(self._lock)` — entering the
        # condition IS holding the lock it wraps: alias name -> lock name.
        self.aliases: dict[str, str] = {}


def _analyze_class(sf, cls) -> _ClassInfo | None:
    info = _ClassInfo(cls)
    init = next((n for n in cls.body
                 if isinstance(n, ast.FunctionDef) and n.name == "__init__"),
                None)
    if init is None:
        return None
    for node in ast.walk(init):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1) or \
                (isinstance(node, ast.AnnAssign)
                 and node.value is not None):
            target = node.targets[0] if isinstance(node, ast.Assign) \
                else node.target
            attr = _self_attr(target)
            if attr is None:
                continue
            if _is_lock_ctor(node.value):
                info.locks.add(attr)
                continue
            if isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr == "Condition" and \
                    node.value.args:
                wrapped = _self_attr(node.value.args[0])
                if wrapped:
                    info.aliases[attr] = wrapped
                    continue
            guard = sf.near(sf.guards, node.lineno)
            unguard = sf.near(sf.unguarded, node.lineno) is not None
            if guard:
                info.guarded[attr] = guard
                info.annotated.add(attr)
            elif unguard:
                info.annotated.add(attr)
            if _is_container(node.value):
                info.container_attrs.setdefault(attr, node.lineno)
    if not info.locks:
        return None
    return info


def _check_method_writes(sf, info, method, findings, *,
                         module_guards=None):
    """Flag writes to guarded attrs outside the declared lock's `with`."""
    holds = sf.near(sf.holds, method.lineno)

    aliases = info.aliases if info else {}

    def covered(node, lock_name) -> bool:
        if holds == lock_name:
            return True
        for w in with_stack_of.get(id(node), ()):  # lexical With ancestry
            if lock_name in w:
                return True
            if any(aliases.get(n) == lock_name for n in w):
                return True
        return False

    # Build the lexical with-ancestry map for this method.
    with_stack_of: dict[int, tuple] = {}

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            s = stack
            if isinstance(child, ast.With):
                s = stack + (_with_lock_names(child),)
            with_stack_of[id(child)] = s
            walk(child, s)

    walk(method, ())

    guarded = dict(info.guarded) if info else {}
    mod_guarded = module_guards or {}

    for node in ast.walk(method):
        checks = []  # (lock, attr-desc, line)
        for kind, tgt in _write_targets(node):
            base = tgt
            if isinstance(tgt, ast.Subscript):
                base = tgt.value
            attr = _self_attr(base)
            if attr and attr in guarded:
                checks.append((guarded[attr], f"self.{attr}", node))
            elif isinstance(base, ast.Name) and base.id in mod_guarded:
                checks.append((mod_guarded[base.id], base.id, node))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            base = node.func.value
            attr = _self_attr(base)
            if attr and attr in guarded:
                checks.append((guarded[attr],
                               f"self.{attr}.{node.func.attr}()", node))
            elif isinstance(base, ast.Name) and base.id in mod_guarded:
                checks.append((mod_guarded[base.id],
                               f"{base.id}.{node.func.attr}()", node))
        for lock, desc, n in checks:
            if not covered(n, lock):
                findings.append({
                    "path": sf.rel, "line": n.lineno,
                    "code": "unguarded-write",
                    "message": f"write to {desc} (guarded-by: {lock}) "
                               f"outside `with {lock}:` — annotate the "
                               f"method `# palint: holds {lock}` if the "
                               f"caller holds it, or take the lock",
                })


def run(ctx) -> list[dict]:
    findings: list[dict] = []
    for sf in ctx.files:
        if sf.tree is None or not any(
                sf.rel.startswith(p) or sf.rel == p
                for p in SCOPE_PREFIXES):
            continue
        # Module-level locks + guarded globals.
        module_locks: set[str] = set()
        module_guards: dict[str, str] = {}
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if _is_lock_ctor(node.value):
                    module_locks.add(name)
                else:
                    guard = sf.near(sf.guards, node.lineno)
                    if guard:
                        module_guards[name] = guard
        # Classes with locks: inventory + write checks.
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = _analyze_class(sf, node)
            if info is None:
                continue
            for attr, line in sorted(info.container_attrs.items()):
                if attr not in info.annotated and attr not in info.locks:
                    findings.append({
                        "path": sf.rel, "line": line,
                        "code": "unannotated-shared-attr",
                        "message": f"`self.{attr}` is a mutable container "
                                   f"in a lock-owning class with no "
                                   f"`# guarded-by: <lock>` / `# unguarded: "
                                   f"<reason>` annotation — locking must be "
                                   f"reviewable",
                    })
            for meth in node.body:
                if isinstance(meth, ast.FunctionDef) and \
                        meth.name != "__init__":
                    _check_method_writes(sf, info, meth, findings,
                                         module_guards=module_guards)
        # Module-level guarded globals written by module functions.
        if module_guards:
            for node in sf.tree.body:
                if isinstance(node, ast.FunctionDef):
                    _check_method_writes(sf, None, node, findings,
                                         module_guards=module_guards)
    return findings
