"""palint — the repo-native static-analysis engine (stdlib-only, jax-free).

Ten rounds of growth accumulated load-bearing conventions that nothing
machine-checked: standalone-loadable stdlib-only modules (the gate scripts
must run over a wedged TPU tunnel), host-sync discipline in timed and
compiled hot paths (PR 3: "exposed transfer is booked as wait, never
compute"), jit cache-key stability, registry-backed vocabularies (metric
families, fault sites, span categories, env vars, the bench late-schema),
and a thread-heavy fleet/serving tier whose deadlock-freedom was proven
only by luck. This package is the ONE lint engine for all of them — the
reference has zero correctness tooling (SURVEY §4/§5.2: defensive
try/except and print-and-continue), so every pass here is a capability the
reference cannot express.

Engine contract:

- **passes** are sibling modules loaded by file path (no package-relative
  imports — the engine itself honors the standalone contract it enforces).
  Each exposes ``NAME``, ``DOC`` and ``run(ctx) -> list[dict]`` where a
  finding dict is ``{"path", "line", "code", "message"}``.
- **one Finding schema** (:class:`Finding`): pass name, repo-relative path,
  1-based line, a stable kebab-case code, and a human message. ``--check``
  exits nonzero iff any finding survives the pragmas.
- **pragmas** (per-line allowlist, justified in-line — the review speed
  bump the old test_telemetry allowlists created, now next to the code):

  - ``# palint: allow[<pass>] <justification>`` on the flagged line or the
    line above suppresses that pass's findings there. An EMPTY
    justification is itself a finding (``unjustified-pragma``), and a
    pragma that suppresses nothing is a finding (``stale-pragma``) — the
    staleness discipline the old allowlist test enforced centrally.
  - ``# guarded-by: <lock>`` / ``# unguarded: <reason>`` annotate shared
    attributes for the lock-discipline pass.
  - ``# palint: holds <lock>`` on a ``def`` line documents that the method
    is only called with ``<lock>`` already held.

- **JSON report** (``pa-palint/v1``) into ``ledger/palint.json``
  (``PA_LEDGER_DIR`` redirects, the perf-ledger rule).

The runtime companion is ``utils/lockcheck.py`` (PA_LOCKCHECK=1): the
static ``guarded-by`` annotations and the dynamic lock-acquisition-order
graph cross-check each other.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import sys
import tokenize

SCHEMA = "pa-palint/v1"
PKG_DIR = "comfyui_parallelanything_tpu"

# Pass modules, in report order. Loaded by file path from this directory —
# see _load_passes (no relative imports: the engine obeys the
# standalone-contract pass it ships).
PASS_FILES = (
    "standalone.py",
    "hostsync.py",
    "recompile.py",
    "registries.py",
    "lockorder.py",
    "observability.py",
)

# Applied to COMMENT tokens only (tokenize above), so no '#' anchor: the
# markers may trail an existing comment ("# socket map — guarded-by: _lock").
_ALLOW_RE = re.compile(
    r"palint:\s*allow\[([a-z0-9_,-]+)\]\s*(.*?)\s*$"
)
_GUARD_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")
_UNGUARD_RE = re.compile(r"\bunguarded:\s*(\S.*)?$")
_HOLDS_RE = re.compile(r"palint:\s*holds\s+([A-Za-z_][A-Za-z0-9_.]*)")


class Finding:
    """The one finding schema every pass reports through."""

    __slots__ = ("pass_name", "path", "line", "code", "message")

    def __init__(self, pass_name: str, path: str, line: int, code: str,
                 message: str):
        self.pass_name = pass_name
        self.path = path
        self.line = int(line)
        self.code = code
        self.message = message

    def to_dict(self) -> dict:
        return {"pass": self.pass_name, "path": self.path, "line": self.line,
                "code": self.code, "message": self.message}

    def __str__(self) -> str:  # the human line: clickable path:line
        return (f"{self.path}:{self.line}: [{self.pass_name}/{self.code}] "
                f"{self.message}")


class Pragma:
    __slots__ = ("line", "passes", "reason", "used")

    def __init__(self, line: int, passes: tuple[str, ...], reason: str):
        self.line = line
        self.passes = passes
        self.reason = reason
        self.used = False


class SourceFile:
    """One parsed repo file: text, AST, comments, and palint pragmas."""

    def __init__(self, root: str, rel: str):
        self.root = root
        self.rel = rel
        self.path = os.path.join(root, rel)
        with open(self.path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.syntax_error: str | None = None
        try:
            self.tree: ast.Module | None = ast.parse(self.text)
        except SyntaxError as e:  # surfaced as a finding by lint()
            self.tree = None
            self.syntax_error = f"line {e.lineno}: {e.msg}"
        # line -> comment text (inline and full-line), via tokenize so
        # strings containing '#' can't fake a pragma.
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            pass
        self.pragmas: dict[int, Pragma] = {}
        self.guards: dict[int, str] = {}      # line -> lock name
        self.unguarded: dict[int, str] = {}   # line -> reason ("" = missing)
        self.holds: dict[int, str] = {}       # line -> lock name
        for line, text in self.comments.items():
            m = _ALLOW_RE.search(text)
            if m:
                passes = tuple(p.strip() for p in m.group(1).split(","))
                self.pragmas[line] = Pragma(line, passes, m.group(2).strip())
            m = _GUARD_RE.search(text)
            if m:
                self.guards[line] = m.group(1)
            m = _UNGUARD_RE.search(text)
            if m:
                self.unguarded[line] = (m.group(1) or "").strip()
            m = _HOLDS_RE.search(text)
            if m:
                self.holds[line] = m.group(1)

    def near(self, table: dict, line: int):
        """``table[line]`` (an annotation on the line itself), or the
        nearest entry in the contiguous comment block immediately above —
        the shared lookup rule for guards/unguarded/holds annotations."""
        if line in table:
            return table[line]
        ln = line - 1
        while ln > 0 and ln in self.comments and \
                self.lines[ln - 1].lstrip().startswith("#"):
            if ln in table:
                return table[ln]
            ln -= 1
        return None

    def allow_for(self, line: int, pass_name: str) -> Pragma | None:
        """The pragma covering ``line`` for ``pass_name``: on the line
        itself, or anywhere in the contiguous comment block immediately
        above it (multi-line justifications are encouraged)."""
        p = self.pragmas.get(line)
        if p is not None and pass_name in p.passes:
            return p
        ln = line - 1
        while ln > 0 and ln in self.comments and \
                self.lines[ln - 1].lstrip().startswith("#"):
            p = self.pragmas.get(ln)
            if p is not None and pass_name in p.passes:
                return p
            ln -= 1
        return None


class Ctx:
    """What a pass sees: the repo root and the parsed file set."""

    def __init__(self, root: str, files: list[SourceFile]):
        self.root = root
        self.files = files
        self._by_rel = {f.rel: f for f in files}

    def file(self, rel: str) -> SourceFile | None:
        return self._by_rel.get(rel)

    def package_files(self) -> list[SourceFile]:
        return [f for f in self.files if f.rel.startswith(PKG_DIR + "/")]


def collect_rels(root: str) -> list[str]:
    """The linted file set: the package, bench.py, and scripts/ (incl. this
    engine). tests/ and __graft_entry__.py are out of scope — fixtures and
    the driver harness would drown the signal."""
    rels: list[str] = []
    for base in (PKG_DIR, "scripts"):
        top = os.path.join(root, base)
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rels.append(os.path.relpath(
                        os.path.join(dirpath, fn), root))
    if os.path.exists(os.path.join(root, "bench.py")):
        rels.append("bench.py")
    return sorted(rels)


def _load_passes():
    import importlib.util

    here = os.path.dirname(os.path.abspath(__file__))
    mods = []
    for fn in PASS_FILES:
        path = os.path.join(here, fn)
        spec = importlib.util.spec_from_file_location(
            f"pa_palint_{fn[:-3]}", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mods.append(mod)
    return mods


def lint(root: str, rels: list[str] | None = None):
    """Run every pass over the repo at ``root``. Returns
    ``(findings, report_dict)`` with pragmas applied (suppressed findings
    dropped; stale/unjustified pragmas surfaced as findings)."""
    if rels is None:
        rels = collect_rels(root)
    files = [SourceFile(root, rel) for rel in rels]
    ctx = Ctx(root, files)
    findings: list[Finding] = []
    for f in files:
        if f.syntax_error:
            findings.append(Finding("engine", f.rel, 0, "syntax-error",
                                    f.syntax_error))
    counts: dict[str, int] = {}
    for mod in _load_passes():
        name = mod.NAME
        raw = mod.run(ctx)
        kept = 0
        for d in raw:
            sf = ctx.file(d["path"])
            pragma = sf.allow_for(d["line"], name) if sf else None
            if pragma is not None:
                pragma.used = True
                continue
            kept += 1
            findings.append(Finding(name, d["path"], d["line"], d["code"],
                                    d["message"]))
        counts[name] = kept
    # Pragma hygiene — the staleness check the old central allowlist test
    # did (`test_allowlist_entries_still_exist`), now per-pragma: one that
    # suppresses nothing must be removed with the site it covered, and one
    # without a justification is not an allowlist entry, it's a mute button.
    for f in files:
        for pragma in f.pragmas.values():
            if not pragma.reason:
                findings.append(Finding(
                    "engine", f.rel, pragma.line, "unjustified-pragma",
                    "palint allow pragma without an in-line justification"))
            elif not pragma.used:
                findings.append(Finding(
                    "engine", f.rel, pragma.line, "stale-pragma",
                    f"pragma allow[{','.join(pragma.passes)}] suppresses "
                    f"nothing — remove it with the site it covered"))
        # `# unguarded:` with no reason would silence the lock-discipline
        # inventory check unjustified — same mute-button rule as pragmas.
        for line, reason in sorted(f.unguarded.items()):
            if not reason:
                findings.append(Finding(
                    "engine", f.rel, line, "unjustified-annotation",
                    "`# unguarded:` without a reason — the form is "
                    "`# unguarded: <why this attr is deliberately lock-"
                    "free>`"))
    findings.sort(key=lambda x: (x.path, x.line, x.pass_name, x.code))
    # No timestamp: the report is committed (ledger/palint.json) and every
    # --check run rewrites it — deterministic bytes on an unchanged tree
    # keep the gate from churning the working copy.
    report = {
        "schema": SCHEMA,
        "root": os.path.abspath(root),
        "files_scanned": len(files),
        "counts": counts,
        "findings": [x.to_dict() for x in findings],
        "ok": not findings,
    }
    return findings, report


def report_path(root: str) -> str:
    led = os.environ.get("PA_LEDGER_DIR") or os.path.join(root, "ledger")
    return os.path.join(led, "palint.json")


def write_report(root: str, report: dict) -> str:
    path = report_path(root)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    return path


def env_table(root: str) -> str:
    """The generated ``PA_*`` env-var reference (markdown): the variable
    INVENTORY is the registry-consistency pass's own code scan (names
    cannot drift — the pass gates both directions), while the Purpose
    column is hand-written prose PRESERVED from the existing README table
    on regeneration; a variable the README has never described gets a TODO
    row naming its read sites. Regenerating is therefore always safe:
    ``python scripts/palint.py --env-table`` reproduces the committed
    table verbatim until the code's inventory changes."""
    rels = collect_rels(root)
    files = [SourceFile(root, rel) for rel in rels]
    ctx = Ctx(root, files)
    for mod in _load_passes():
        if mod.NAME == "registry-consistency":
            inv = mod.env_inventory(ctx)
            break
    else:  # pragma: no cover - PASS_FILES always includes registries
        raise RuntimeError("registry-consistency pass not found")
    purposes: dict[str, str] = {}
    try:
        with open(os.path.join(root, "README.md"), encoding="utf-8") as fh:
            for m in re.finditer(
                    r"^\|\s*`(PA_[A-Z0-9_]+)`\s*\|\s*(.*?)\s*\|\s*$",
                    fh.read(), re.MULTILINE):
                purposes[m.group(1)] = m.group(2)
    except OSError:
        pass
    lines = ["| Variable | Purpose |", "|---|---|"]
    for name in sorted(inv):
        purpose = purposes.get(name)
        if not purpose:
            where = sorted({rel.split("/")[-1] for rel in inv[name]})
            shown = ", ".join(where[:4]) + (", …" if len(where) > 4 else "")
            purpose = f"TODO: describe (read in {shown})"
        lines.append(f"| `{name}` | {purpose} |")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="palint.py",
        description="repo-native static analysis (see scripts/palint/)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any finding survives the pragmas "
                         "(the ci_tier1.sh gate)")
    ap.add_argument("--json", action="store_true",
                    help="print the full JSON report instead of text")
    ap.add_argument("--env-table", action="store_true",
                    help="print the generated PA_* env-var markdown table "
                         "(the README reference is this output)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the checkout containing this "
                         "script)")
    args = ap.parse_args(argv)
    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if args.env_table:
        sys.stdout.write(env_table(root) + "\n")
        return 0
    findings, report = lint(root)
    path = write_report(root, report)
    if args.json:
        sys.stdout.write(json.dumps(report) + "\n")
    else:
        for f in findings:
            sys.stdout.write(str(f) + "\n")
        sys.stdout.write(
            f"palint: {len(findings)} finding(s) over "
            f"{report['files_scanned']} files — report {path}\n")
    if args.check and findings:
        return 1
    return 0
