"""standalone-contract: stdlib-only module level, no package-relative imports.

The gate scripts (perf_ledger, numerics_audit, roofline_report,
twin_report, trace_summary, palint itself) must run over a wedged TPU
tunnel or on a laptop holding just the ledger — the tunnel plugin wedges
``import jax`` in every process when it is down (CLAUDE.md). That only
works because the modules they load keep their MODULE LEVEL stdlib-only
and free of package-relative imports: ``utils/roofline.py`` established
the contract (scripts/roofline_report.py path-loads it), ``utils/slo.py``,
``utils/retry.py``, ``utils/faults.py``, ``utils/lockcheck.py`` and
``fleet/twin.py`` adopted it, and ``bench.py``'s module level is the
reason scripts/perf_ledger.py can ``import bench`` jax-free.

This pass machine-checks the contract for those modules plus ALL of
``scripts/``:

- module-level ``import``/``from`` must resolve to the stdlib or to
  ``bench`` (itself a checked standalone module);
- package-relative imports (``from . import x`` / ``from ..utils import``)
  are banned at module level for the declared-standalone package modules
  (a path-loaded module has no package to be relative to);
- function-level imports are exempt — that IS the graceful-degradation
  pattern the contract prescribes.

TPU-side scripts (bench_kernels, measure_tpu, …) already keep jax behind
function level, so the whole directory holds the contract uniformly.
"""

from __future__ import annotations

import ast
import sys

NAME = "standalone-contract"
DOC = "standalone-loadable modules: stdlib-only module level"

# Package modules that DECLARE the standalone contract (each one's
# docstring says so; scripts load them by file path). scripts/ and
# bench.py are added wholesale by run().
DECLARED = (
    "comfyui_parallelanything_tpu/utils/roofline.py",
    "comfyui_parallelanything_tpu/utils/slo.py",
    "comfyui_parallelanything_tpu/utils/retry.py",
    "comfyui_parallelanything_tpu/utils/faults.py",
    "comfyui_parallelanything_tpu/utils/lockcheck.py",
    "comfyui_parallelanything_tpu/utils/timeseries.py",
    "comfyui_parallelanything_tpu/utils/anomaly.py",
    "comfyui_parallelanything_tpu/fleet/twin.py",
)

# Non-stdlib module-level imports that are still standalone-safe: bench.py
# keeps its own module level jax-free (checked by this pass), which is what
# lets scripts/perf_ledger.py et al. `import bench` over a wedged tunnel.
ALLOWED_LOCAL = {"bench"}


def _stdlib() -> frozenset:
    names = getattr(sys, "stdlib_module_names", None)
    if names:  # 3.10+
        return frozenset(names) | {"__future__"}
    return frozenset({"__future__"})  # pragma: no cover - 3.10 floor


def run(ctx) -> list[dict]:
    stdlib = _stdlib()
    findings: list[dict] = []
    targets = [f for f in ctx.files
               if f.rel in DECLARED
               or f.rel == "bench.py"
               or f.rel.startswith("scripts/")]
    for f in targets:
        if f.tree is None:
            continue
        for node in _module_level_imports(f.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    top = alias.name.split(".")[0]
                    if top not in stdlib and top not in ALLOWED_LOCAL:
                        findings.append({
                            "path": f.rel, "line": node.lineno,
                            "code": "nonstd-import",
                            "message": (
                                f"module-level `import {alias.name}` breaks "
                                f"the standalone contract (stdlib-only — "
                                f"move under function level or path-load)"),
                        })
            elif isinstance(node, ast.ImportFrom):
                if node.level and node.level > 0:
                    findings.append({
                        "path": f.rel, "line": node.lineno,
                        "code": "relative-import",
                        "message": (
                            "module-level package-relative import — a "
                            "path-loaded standalone module has no package "
                            "to be relative to"),
                    })
                    continue
                top = (node.module or "").split(".")[0]
                if top and top not in stdlib and top not in ALLOWED_LOCAL:
                    findings.append({
                        "path": f.rel, "line": node.lineno,
                        "code": "nonstd-import",
                        "message": (
                            f"module-level `from {node.module} import …` "
                            f"breaks the standalone contract (pulls the "
                            f"package __init__ chain — path-load the module "
                            f"instead, the scripts/roofline_report.py "
                            f"pattern)"),
                    })
    return findings


def _module_level_imports(tree: ast.Module):
    """Imports in the module body, including inside top-level `if`/`try`
    blocks (those still execute at import time) — but NOT inside function
    or class bodies."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, (ast.If, ast.Try, ast.With)):
            for field in ("body", "orelse", "finalbody", "handlers"):
                for child in getattr(node, field, []) or []:
                    if isinstance(child, ast.ExceptHandler):
                        stack.extend(child.body)
                    else:
                        stack.append(child)
