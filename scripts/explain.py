#!/usr/bin/env python3
"""Price every wall-second of one prompt: the request-forensics CLI.

``explain.py <prompt_id> --base http://router:8187`` fetches the stitched
cross-host timeline (``GET /fleet/trace?prompt_id=`` — every host the prompt
touched, one trace_id, clock-aligned tracks; see fleet/router.py
``stitch_trace``) and reconstructs where the client-observed wall went,
priced with the roofline bucket vocabulary (utils/roofline.py):

- ``compute``          — device/program execution (workflow-node span union)
- ``exposed_transfer`` — weight-streaming prefetch the overlap didn't hide
- ``comms``            — cross-host hops: dispatch POSTs, stage hand-offs,
                         remote handle/cond fetches
- ``queue_wait``       — admission + lane-seat waits (every ``*-wait`` span)
- ``host_gap``         — the residual: wall time no span accounts for
                         (scheduler gaps, history polling, HTTP overhead)

Bucket precedence is queue > transfer > comms > compute (a lane-wait inside
a workflow-node span is queue time, not compute), and ``host_gap`` is the
residual against the wall — so the buckets are non-negative and sum to the
wall BY CONSTRUCTION whenever the wall covers the trace window. The
``--check`` gate (CI: scripts/ci_tier1.sh) enforces the conservation rule:
every bucket >= 0 and |sum - wall| <= 10% of wall (BASELINE.md forensics
protocol).

Stdlib-only and jax-free (the scripts/ standalone contract — same as
trace_summary.py): runs anywhere the trace JSON can be carried.

The reference answers "why was prompt X slow" with per-thread progress
prints read off a terminal (any_device_parallel.py progress lines); this
CLI answers it from one stitched document covering every host.

Usage:
  explain.py <prompt_id> [--base URL]      # fetch + explain one prompt
  explain.py --trace-file doc.json         # explain an already-saved stitch
  explain.py ... --wall-s 3.2              # price against the CLIENT wall
  explain.py ... --check [--min-hosts 3]   # CI gate (exit 1 on violation)
  explain.py ... --json                    # machine-readable report
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

SCHEMA = "pa-explain/v1"

# Bucket classification by span name, applied in precedence order (first
# match wins): queue > exposed_transfer > comms > compute. Substring rules
# keep the map robust to per-subsystem naming (lane-wait, admission-wait,
# decode-wait... are all queue).
QUEUE_SUFFIX = "-wait"
TRANSFER_MARKS = ("prefetch", "transfer", "h2d", "d2h")
COMMS_NAMES = ("fleet-hop", "stage-dispatch")
COMMS_MARKS = ("fetch", "comms", "collective", "all-gather", "all-reduce")
COMPUTE_NAMES = ("workflow-node",)
BUCKETS = ("compute", "exposed_transfer", "comms", "queue_wait", "host_gap")


def classify(name: str) -> str | None:
    n = str(name)
    if n.endswith(QUEUE_SUFFIX):
        return "queue_wait"
    if any(m in n for m in TRANSFER_MARKS):
        return "exposed_transfer"
    if n in COMMS_NAMES or any(m in n for m in COMMS_MARKS):
        return "comms"
    if n in COMPUTE_NAMES:
        return "compute"
    return None


# -- interval algebra (seconds) ----------------------------------------------


def _merge(ivals):
    """Union of [s, e) intervals as a sorted disjoint list."""
    out = []
    for s, e in sorted(i for i in ivals if i[1] > i[0]):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def _subtract(ivals, cuts):
    """``ivals`` minus ``cuts`` (both disjoint sorted)."""
    out = []
    for s, e in ivals:
        cur = s
        for cs, ce in cuts:
            if ce <= cur or cs >= e:
                continue
            if cs > cur:
                out.append([cur, cs])
            cur = max(cur, ce)
            if cur >= e:
                break
        if cur < e:
            out.append([cur, e])
    return out


def _total(ivals) -> float:
    return sum(e - s for s, e in ivals)


# -- the explanation ---------------------------------------------------------


def _x_events(doc):
    return [e for e in doc.get("traceEvents", ()) if e.get("ph") == "X"]


def _span_interval(e):
    s = e.get("ts", 0.0) / 1e6
    return [s, s + max(0.0, e.get("dur", 0.0)) / 1e6]


def _bucketize(events):
    """The five-bucket pricing of one event set against its own window.
    Returns (window_s, by_bucket_intervals) — ``host_gap`` is priced by the
    caller against whichever wall it answers for."""
    pools = {"queue_wait": [], "exposed_transfer": [], "comms": [],
             "compute": []}
    for e in events:
        b = classify(e.get("name", ""))
        if b is not None:
            pools[b].append(_span_interval(e))
    covered = []
    out = {}
    # Precedence by subtraction: a second already priced as queue is never
    # double-billed as compute.
    for b in ("queue_wait", "exposed_transfer", "comms", "compute"):
        u = _subtract(_merge(pools[b]), covered)
        out[b] = u
        covered = _merge(covered + u)
    return out


def explain_doc(doc: dict, wall_s: float | None = None) -> dict:
    """Turn one stitched fleet trace (``pa-fleet-trace/v1``) into the priced
    forensics report. ``wall_s`` is the CLIENT-observed end-to-end latency
    when the caller has it; absent, the router's ``fleet-prompt`` span
    (submit -> entry collected) stands in, then the raw trace extent."""
    xs = _x_events(doc)
    if not xs:
        return {"schema": SCHEMA, "error": "trace holds no spans",
                "trace_id": doc.get("trace_id")}
    t0 = min(e.get("ts", 0.0) for e in xs) / 1e6
    t1 = max(e.get("ts", 0.0) + e.get("dur", 0.0) for e in xs) / 1e6
    window_s = max(0.0, t1 - t0)
    fleet_prompt = next((e for e in xs if e.get("name") == "fleet-prompt"),
                        None)
    if wall_s is None and fleet_prompt is not None:
        wall_s = fleet_prompt.get("dur", 0.0) / 1e6
    if wall_s is None:
        wall_s = window_s

    pools = _bucketize(xs)
    buckets = {b: round(_total(u), 6) for b, u in pools.items()}
    accounted = sum(buckets.values())
    buckets["host_gap"] = round(max(0.0, wall_s - accounted), 6)
    total = sum(buckets.values())
    rel_err = abs(total - wall_s) / wall_s if wall_s > 0 else 0.0
    dominant = max(BUCKETS, key=lambda b: buckets[b])

    # Per-stage rows: one per backend prompt span (a mid-stage failover
    # shows the same stage twice, on two hosts — both priced).
    stages = []
    for e in xs:
        if e.get("name") != "prompt":
            continue
        args = e.get("args") or {}
        lo, hi = _span_interval(e)
        inside = [x for x in xs
                  if x.get("pid") == e.get("pid")
                  and _span_interval(x)[0] >= lo - 1e-6
                  and _span_interval(x)[1] <= hi + 1e-6]
        sp = _bucketize(inside)
        row = {
            "host": args.get("host_id") or args.get("host"),
            "role": args.get("role"),
            "stage": args.get("stage"),
            "start_s": round(lo - t0, 6),
            "wall_s": round(hi - lo, 6),
        }
        for b in ("compute", "exposed_transfer", "comms", "queue_wait"):
            row[b + "_s"] = round(_total(sp[b]), 6)
        row["host_gap_s"] = round(
            max(0.0, row["wall_s"] - sum(
                row[b + "_s"]
                for b in ("compute", "exposed_transfer", "comms",
                          "queue_wait"))), 6)
        stages.append(row)
    stages.sort(key=lambda r: r["start_s"])

    # The cross-host critical path: stage executions in time order with the
    # inter-stage gaps (dispatch + collect + hand-off) called out — the gap
    # seconds are where the router/journal story (instant events) points.
    path = []
    cursor = t0
    for row in stages:
        gap = row["start_s"] - (cursor - t0)
        if gap > 1e-6:
            path.append({"kind": "gap", "wall_s": round(gap, 6)})
        path.append({"kind": "stage", **{k: row[k] for k in
                                         ("host", "role", "stage", "wall_s")}})
        cursor = max(cursor, t0 + row["start_s"] + row["wall_s"])
    tail = t1 - cursor
    if tail > 1e-6:
        path.append({"kind": "gap", "wall_s": round(tail, 6)})

    trace_ids = {str((e.get("args") or {}).get("trace_id"))
                 for e in xs if (e.get("args") or {}).get("trace_id")}
    hosts = doc.get("hosts") or []
    journal = sorted({e.get("name") for e in doc.get("traceEvents", ())
                      if e.get("ph") == "i"})

    report = {
        "schema": SCHEMA,
        "trace_id": doc.get("trace_id"),
        "trace_ids_seen": sorted(trace_ids),
        "hosts": hosts,
        "host_tracks": sum(1 for h in hosts if h.get("role") != "router"),
        "fetch_ok": [h.get("host") for h in hosts if h.get("ok")],
        "fetch_failed": [h.get("host") for h in hosts if not h.get("ok")],
        "spans": len(xs),
        "journal_events": journal,
        "wall_s": round(wall_s, 6),
        "trace_window_s": round(window_s, 6),
        "buckets_s": buckets,
        "bucket_fractions": {
            b: round(v / wall_s, 4) if wall_s > 0 else 0.0
            for b, v in buckets.items()
        },
        "dominant_bucket": dominant,
        "conservation": {
            "sum_s": round(total, 6),
            "wall_s": round(wall_s, 6),
            "rel_err": round(rel_err, 4),
        },
        "stages": stages,
        "critical_path": path,
    }
    # SLO stage deltas when objectives are declared (same env contract as
    # utils/slo.py, parsed stdlib-side): how far the wall sits from each
    # latency objective's threshold.
    objectives = _objectives_from_env()
    if objectives:
        report["slo"] = [
            {"objective": name, "threshold_s": thr,
             "delta_s": round(wall_s - thr, 6),
             "met": wall_s <= thr}
            for name, thr in objectives
        ]
    return report


def _objectives_from_env() -> list:
    """(name, threshold_s) pairs from PA_SLO_OBJECTIVES (the utils/slo.py
    JSON contract), without importing the package (jax-free)."""
    raw = os.environ.get("PA_SLO_OBJECTIVES")
    if not raw:
        return []
    try:
        objs = json.loads(raw)
        return [(str(o["name"]), float(o["threshold_s"]))
                for o in objs if "name" in o and "threshold_s" in o]
    except (ValueError, TypeError, KeyError):
        return []


def check(report: dict, *, tolerance: float = 0.10,
          min_hosts: int = 1) -> list:
    """The conservation gate: every violated rule as a message (empty =
    pass). CI runs this on the fleet smoke's slowest prompt."""
    errs = []
    if report.get("error"):
        return [f"no explanation: {report['error']}"]
    if report.get("host_tracks", 0) < min_hosts:
        errs.append(
            f"stitched timeline covers {report.get('host_tracks', 0)} host "
            f"track(s), need >= {min_hosts}"
        )
    if len(report.get("trace_ids_seen") or ()) > 1:
        errs.append(
            f"spans carry {len(report['trace_ids_seen'])} trace_ids, "
            f"expected one lineage: {report['trace_ids_seen']}"
        )
    for b, v in (report.get("buckets_s") or {}).items():
        if v < 0:
            errs.append(f"bucket {b} is negative ({v}s)")
    cons = report.get("conservation") or {}
    if cons.get("rel_err", 1.0) > tolerance:
        errs.append(
            f"buckets sum to {cons.get('sum_s')}s vs wall "
            f"{cons.get('wall_s')}s — rel err {cons.get('rel_err')} > "
            f"{tolerance} (the 10% conservation rule)"
        )
    return errs


def _fetch(base: str, prompt_id: str, timeout: float = 30.0) -> dict:
    url = f"{base.rstrip('/')}/fleet/trace?prompt_id={prompt_id}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _render(report: dict) -> str:
    if report.get("error"):
        return f"explain: {report['error']}"
    lines = [
        f"prompt {report['trace_id']} — wall {report['wall_s']:.3f}s over "
        f"{report['host_tracks']} host track(s), {report['spans']} spans",
    ]
    if report.get("fetch_failed"):
        lines.append(f"  (missing hops: {', '.join(map(str, report['fetch_failed']))})")
    w = report["wall_s"] or 1.0
    for b in BUCKETS:
        v = report["buckets_s"].get(b, 0.0)
        bar = "#" * int(round(40 * v / w))
        flag = "  <= dominant" if b == report["dominant_bucket"] else ""
        lines.append(f"  {b:<17} {v:>8.3f}s {v / w:>6.1%} {bar}{flag}")
    cons = report["conservation"]
    lines.append(
        f"  conservation: buckets sum {cons['sum_s']:.3f}s vs wall "
        f"{cons['wall_s']:.3f}s (rel err {cons['rel_err']:.1%})"
    )
    if report.get("stages"):
        lines.append("  critical path:")
        for seg in report["critical_path"]:
            if seg["kind"] == "gap":
                lines.append(f"    .. {seg['wall_s']:.3f}s hand-off/queue gap")
            else:
                lines.append(
                    f"    [{seg.get('role') or '-'}] {seg.get('host')}: "
                    f"{seg['wall_s']:.3f}s"
                )
    for o in report.get("slo") or ():
        verdict = "met" if o["met"] else "MISSED"
        lines.append(
            f"  slo {o['objective']}: {verdict} "
            f"(delta {o['delta_s']:+.3f}s vs {o['threshold_s']}s)"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("prompt_id", nargs="?", help="router-scoped prompt id")
    ap.add_argument("--base", default="http://127.0.0.1:8187",
                    help="fleet router base URL")
    ap.add_argument("--trace-file", help="explain a saved stitched trace "
                    "instead of fetching")
    ap.add_argument("--wall-s", type=float, default=None,
                    help="client-observed wall to price against")
    ap.add_argument("--check", action="store_true",
                    help="gate mode: exit 1 unless buckets are non-negative "
                         "and conserve the wall within --tolerance")
    ap.add_argument("--tolerance", type=float, default=0.10)
    ap.add_argument("--min-hosts", type=int, default=1,
                    help="--check: minimum stitched host tracks")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.trace_file:
        with open(args.trace_file) as f:
            doc = json.load(f)
        # The CI forensics dump wraps the stitched doc with the
        # client-observed wall it was measured against
        # (tests/test_roles.py::TestRequestForensics writes it under
        # PA_FORENSICS_DUMP) — unwrap, and let the recorded wall stand in
        # unless --wall-s overrides.
        if isinstance(doc, dict) and isinstance(doc.get("doc"), dict):
            if args.wall_s is None and doc.get("wall_s") is not None:
                args.wall_s = float(doc["wall_s"])
            doc = doc["doc"]
    elif args.prompt_id:
        try:
            doc = _fetch(args.base, args.prompt_id)
        except OSError as e:
            print(f"explain: cannot fetch stitched trace: {e}",
                  file=sys.stderr)
            return 2
    else:
        ap.error("need a prompt_id (or --trace-file)")

    report = explain_doc(doc, wall_s=args.wall_s)
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(_render(report))
    if args.check:
        errs = check(report, tolerance=args.tolerance,
                     min_hosts=args.min_hosts)
        for e in errs:
            print(f"explain --check: {e}", file=sys.stderr)
        return 1 if errs else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
