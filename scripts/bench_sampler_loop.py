"""Eager vs whole-loop-compiled sampler benchmark (VERDICT r2 item 2 evidence).

Quantifies what ``run_sampler(compile_loop=True)`` buys on real hardware: the
eager path re-enters the jitted forward from Python every denoise step (the
reference's hot-loop shape, any_device_parallel.py:1287), paying per-step
dispatch and a fresh latent allocation; the compiled path runs the whole loop
as one lax.scan XLA program with the latent donated.

    python scripts/bench_sampler_loop.py          # default: sd15-class, 20 steps
    BENCH_STEPS=30 python scripts/bench_sampler_loop.py

Appends JSON lines to SAMPLER_LOOP_BENCH.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.models import build_unet, sd15_config
    from comfyui_parallelanything_tpu.sampling.runner import run_sampler
    from comfyui_parallelanything_tpu.utils import enable_compilation_cache

    from bench import _TPU_PLATFORMS, evidence_dir

    enable_compilation_cache()
    dev = jax.devices()[0]
    on_tpu = dev.platform in _TPU_PLATFORMS
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    if os.environ.get("PA_BENCH_TINY") == "1":
        on_tpu = False  # dry-run: record flows as TPU, workload stays smoke-size
    if on_tpu:
        batch, latent, ctx_len = 8, 64, 77   # 512² SD1.5-class
        cfg = sd15_config(dtype=jnp.bfloat16)
    else:
        batch, latent, ctx_len = 4, 16, 24   # CPU smoke
        cfg = sd15_config(
            model_channels=64, channel_mult=(1, 2), transformer_depth=(1, 1),
            attention_levels=(0, 1), context_dim=64, num_heads=4, norm_groups=16,
            dtype=jnp.float32,
        )
    model = build_unet(cfg, jax.random.key(0), sample_shape=(1, latent, latent, 4))
    noise = jax.random.normal(jax.random.key(1), (batch, latent, latent, 4))
    ctx = jax.random.normal(jax.random.key(2), (batch, ctx_len, cfg.context_dim))

    rec = {
        "workload": f"sd15-class b={batch} {latent * 8}px {steps} steps dpmpp_2m",
        "platform": dev.platform, "device_kind": dev.device_kind,
        "steps": steps, "ts": time.time(),
    }
    # Tunnel-proof timing: each run feeds its output back as the next run's
    # noise (see utils/metrics.chained_time for why per-call
    # block_until_ready is untrustworthy through the axon tunnel). Values may
    # blow up over chained runs with random weights; TPU arithmetic is
    # value-independent, so timing is unaffected.
    from comfyui_parallelanything_tpu.utils.metrics import chained_time

    iters = 3
    for key, flag in (("eager_s", False), ("compiled_s", True)):
        sec, _ = chained_time(
            lambda v, _flag=flag: run_sampler(
                model, v, ctx, sampler="dpmpp_2m", steps=steps,
                compile_loop=_flag,
            ).astype(noise.dtype),
            noise, iters,
        )
        rec[key] = round(sec, 4)
    rec["compiled_speedup"] = round(rec["eager_s"] / rec["compiled_s"], 3)
    print(json.dumps(rec))
    with open(os.path.join(evidence_dir(), "SAMPLER_LOOP_BENCH.json"), "a") as f:
        f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
