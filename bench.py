"""Benchmark entry point — prints ONE JSON line for the driver.

Workloads follow the BASELINE.md ladder; select with BENCH_CONFIG (default picks by
platform):

- ``sd15_16``  — SD1.5-class UNet, bf16, batch=16, 1024² pixels (128² latents). The
  BASELINE headline shape ("sec/it at batch=16 1024²").
- ``sdxl_8``   — SDXL-class UNet, bf16, batch=8, 1024².
- ``zimage_21``— Z_Image-class MMDiT, batch=21, 1024² — the reference's own benchmark
  run (/root/reference/README.md:46-60: 26.00 s/it on one RTX 3090, 12.91 s/it on
  two GPUs). Large: needs most of a v5e chip's HBM.
- ``smoke``    — reduced-width SD1.5 topology on CPU (no TPU attached).

``vs_baseline`` divides the reference's published single-GPU 26.00 s/it by our s/it —
>1 means faster than the reference's single-GPU row. Workloads are not identical
(different model families per rung); the "workload" field records exactly what ran.
"""

import json
import os
import sys
import time


def _build(config_name):
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.models import (
        build_flux,
        build_unet,
        sd15_config,
        sdxl_config,
        z_image_turbo_config,
    )

    rng = jax.random.key(0)
    if config_name == "sd15_16":
        batch, latent, ctx_len = 16, 128, 77
        cfg = sd15_config(dtype=jnp.bfloat16)
        model = build_unet(cfg, rng, sample_shape=(1, latent, latent, 4))
        x_ch, ctx_dim = 4, cfg.context_dim
        kwargs = {}
        workload = "SD1.5 UNet bf16 batch=16 1024x1024"
    elif config_name == "sdxl_8":
        batch, latent, ctx_len = 8, 128, 77
        cfg = sdxl_config(dtype=jnp.bfloat16)
        model = build_unet(cfg, rng, sample_shape=(1, latent, latent, 4))
        x_ch, ctx_dim = 4, cfg.context_dim
        kwargs = {"y": jnp.zeros((batch, cfg.adm_in_channels), jnp.float32)}
        workload = "SDXL UNet bf16 batch=8 1024x1024"
    elif config_name == "zimage_21":
        batch, latent, ctx_len = 21, 128, 128
        cfg = z_image_turbo_config(dtype=jnp.bfloat16)
        model = build_flux(
            cfg, rng, sample_shape=(1, 16, 16, 16), txt_len=ctx_len
        )
        x_ch, ctx_dim = 16, cfg.context_in_dim
        kwargs = {}
        workload = "Z_Image-class MMDiT bf16 batch=21 1024x1024 (README repro shape)"
    elif config_name == "smoke":
        batch, latent, ctx_len = 8, 32, 24
        cfg = sd15_config(
            model_channels=64,
            channel_mult=(1, 2, 4),
            transformer_depth=(1, 1, 1),
            context_dim=256,
            dtype=jnp.bfloat16,
        )
        model = build_unet(cfg, rng, sample_shape=(1, latent, latent, 4))
        x_ch, ctx_dim = 4, cfg.context_dim
        kwargs = {}
        workload = "SD1.5-topology smoke batch=8 256x256"
    else:
        raise ValueError(f"unknown BENCH_CONFIG {config_name!r}")
    return model, batch, latent, x_ch, ctx_len, ctx_dim, kwargs, workload


def main() -> None:
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu import DeviceChain, parallelize

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    config_name = os.environ.get(
        "BENCH_CONFIG", "sd15_16" if platform == "tpu" else "smoke"
    )

    model, batch, latent, x_ch, ctx_len, ctx_dim, kwargs, workload = _build(config_name)

    chain = DeviceChain.even([f"{platform}:{d.id}" for d in jax.devices()])
    pm = parallelize(model, chain)

    kx, kc = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, (batch, latent, latent, x_ch), jnp.float32)
    t = jnp.linspace(999.0, 1.0, batch)
    ctx = jax.random.normal(kc, (batch, ctx_len, ctx_dim), jnp.float32)

    # Warmup/compile, then timed denoise-step iterations.
    out = pm(x, t, ctx, **kwargs)
    jax.block_until_ready(out)
    iters = 10 if platform == "tpu" else 2  # CPU runs are smoke-only
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pm(x, t, ctx, **kwargs)
    jax.block_until_ready(out)
    sec_it = (time.perf_counter() - t0) / iters

    ref_single_gpu = 26.00  # /root/reference/README.md:54-56
    print(
        json.dumps(
            {
                "metric": f"sec/it denoise step [{config_name}]",
                "value": round(sec_it, 4),
                "unit": "s/it",
                "vs_baseline": round(ref_single_gpu / sec_it, 2),
                "workload": f"{workload} ({platform} x{n_dev})",
                "images_per_sec": round(batch / sec_it, 3),
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver needs a line either way
        print(json.dumps({"metric": "error", "value": 0, "unit": "", "vs_baseline": 0, "error": str(e)[:300]}))
        sys.exit(1)
