"""Benchmark entry point — prints ONE JSON line for the driver.

Workloads follow the BASELINE.md ladder; select with BENCH_CONFIG (default picks by
platform):

- ``sd15_16``  — SD1.5-class UNet, bf16, batch=16, 1024² pixels (128² latents). The
  BASELINE headline shape ("sec/it at batch=16 1024²").
- ``sdxl_8``   — SDXL-class UNet, bf16, batch=8, 1024².
- ``zimage_21``— Z_Image-class MMDiT, batch=21, 1024² — the reference's own benchmark
  run (/root/reference/README.md:46-60: 26.00 s/it on one RTX 3090, 12.91 s/it on
  two GPUs). Large: needs most of a v5e chip's HBM.
- ``flux_16``  — FLUX-class MMDiT, batch=16, 1024² (the BASELINE.json north-star
  shape). Full flux-dev (12B) needs FSDP over a v5e-8 pod slice; on a single chip
  this rung runs the dev *topology* at reduced depth so the shape (4096 img tokens
  of joint attention, bf16, pallas flash path) is what's measured.
- ``wan_video``— WAN-class video DiT, 16 frames 480p-latent batch=1 (sequence-
  dominant workload; temporal tokens ≈ video "batch").
- ``smoke``    — reduced-width SD1.5 topology on CPU (no TPU attached).

``vs_baseline`` divides the reference's published single-GPU 26.00 s/it by our s/it —
>1 means faster than the reference's single-GPU row. Workloads are not identical
(different model families per rung); the "workload" field records exactly what ran.
"""

import json
import os
import sys
import time


def _build(config_name):
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.models import (
        build_flux,
        build_unet,
        sd15_config,
        sdxl_config,
        z_image_turbo_config,
    )

    rng = jax.random.key(0)
    if config_name == "sd15_16":
        batch, latent, ctx_len = 16, 128, 77
        cfg = sd15_config(dtype=jnp.bfloat16)
        model = build_unet(cfg, rng, sample_shape=(1, latent, latent, 4))
        x_shape, ctx_dim = (batch, latent, latent, 4), cfg.context_dim
        kwargs = {}
        workload = "SD1.5 UNet bf16 batch=16 1024x1024"
    elif config_name == "sdxl_8":
        batch, latent, ctx_len = 8, 128, 77
        cfg = sdxl_config(dtype=jnp.bfloat16)
        model = build_unet(cfg, rng, sample_shape=(1, latent, latent, 4))
        x_shape, ctx_dim = (batch, latent, latent, 4), cfg.context_dim
        kwargs = {"y": jnp.zeros((batch, cfg.adm_in_channels), jnp.float32)}
        workload = "SDXL UNet bf16 batch=8 1024x1024"
    elif config_name == "zimage_21":
        batch, latent, ctx_len = 21, 128, 128
        cfg = z_image_turbo_config(dtype=jnp.bfloat16)
        model = build_flux(
            cfg, rng, sample_shape=(1, 16, 16, 16), txt_len=ctx_len
        )
        x_shape, ctx_dim = (batch, latent, latent, 16), cfg.context_in_dim
        kwargs = {}
        workload = "Z_Image-class MMDiT bf16 batch=21 1024x1024 (README repro shape)"
    elif config_name == "flux_16":
        from comfyui_parallelanything_tpu.models import flux_dev_config

        batch, latent, ctx_len = 16, 128, 512
        # Dev topology (double+single blocks, guidance embed, 24 heads x 128) at
        # depth that fits one v5e chip; full 19/38-depth dev runs FSDP multi-chip.
        cfg = flux_dev_config(depth=4, depth_single_blocks=8, dtype=jnp.bfloat16)
        model = build_flux(cfg, rng, sample_shape=(1, 32, 32, 16), txt_len=ctx_len)
        x_shape, ctx_dim = (batch, latent, latent, 16), cfg.context_in_dim
        kwargs = {
            "y": jnp.zeros((batch, cfg.vec_in_dim), jnp.float32),
            "guidance": jnp.full((batch,), 3.5, jnp.float32),
        }
        workload = "FLUX-class MMDiT bf16 batch=16 1024x1024 (reduced depth 4/8)"
    elif config_name == "wan_video":
        from comfyui_parallelanything_tpu.models import build_wan, wan_1_3b_config

        batch, ctx_len = 1, 128
        cfg = wan_1_3b_config(depth=8, dtype=jnp.bfloat16)
        frames, lat_h, lat_w = 16, 30, 52  # ~480p latent video, 16 frames
        model = build_wan(
            cfg, rng, sample_shape=(1, frames, lat_h, lat_w, cfg.in_channels),
            txt_len=ctx_len,
        )
        x_shape = (batch, frames, lat_h, lat_w, cfg.in_channels)
        ctx_dim = cfg.text_dim
        kwargs = {}
        workload = f"WAN-class video DiT bf16 {frames}f {lat_h}x{lat_w} latents"
    elif config_name == "smoke":
        batch, latent, ctx_len = 8, 32, 24
        cfg = sd15_config(
            model_channels=64,
            channel_mult=(1, 2, 4),
            transformer_depth=(1, 1, 1),
            context_dim=256,
            dtype=jnp.bfloat16,
        )
        model = build_unet(cfg, rng, sample_shape=(1, latent, latent, 4))
        x_shape, ctx_dim = (batch, latent, latent, 4), cfg.context_dim
        kwargs = {}
        workload = "SD1.5-topology smoke batch=8 256x256"
    else:
        raise ValueError(f"unknown BENCH_CONFIG {config_name!r}")
    return model, batch, x_shape, ctx_len, ctx_dim, kwargs, workload


def main() -> None:
    import jax
    import jax.numpy as jnp

    # Persistent XLA compilation cache: repeat driver runs skip the 20-40s
    # first-compile (cache dir is repo-local; harmless on first run).
    try:
        jax.config.update("jax_compilation_cache_dir", 
                          os.path.join(os.path.dirname(__file__), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass

    from comfyui_parallelanything_tpu import DeviceChain, parallelize

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    config_name = os.environ.get(
        "BENCH_CONFIG", "sd15_16" if platform == "tpu" else "smoke"
    )

    model, batch, x_shape, ctx_len, ctx_dim, kwargs, workload = _build(config_name)

    chain = DeviceChain.even([f"{platform}:{d.id}" for d in jax.devices()])
    pm = parallelize(model, chain)

    kx, kc = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, x_shape, jnp.float32)
    t = jnp.linspace(999.0, 1.0, batch)
    ctx = jax.random.normal(kc, (batch, ctx_len, ctx_dim), jnp.float32)

    # Warmup/compile, then timed denoise-step iterations.
    out = pm(x, t, ctx, **kwargs)
    jax.block_until_ready(out)
    iters = 10 if platform == "tpu" else 2  # CPU runs are smoke-only
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pm(x, t, ctx, **kwargs)
    jax.block_until_ready(out)
    sec_it = (time.perf_counter() - t0) / iters

    ref_single_gpu = 26.00  # /root/reference/README.md:54-56
    print(
        json.dumps(
            {
                "metric": f"sec/it denoise step [{config_name}]",
                "value": round(sec_it, 4),
                "unit": "s/it",
                "vs_baseline": round(ref_single_gpu / sec_it, 2),
                "workload": f"{workload} ({platform} x{n_dev})",
                "images_per_sec": round(batch / sec_it, 3),
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver needs a line either way
        print(json.dumps({"metric": "error", "value": 0, "unit": "", "vs_baseline": 0, "error": str(e)[:300]}))
        sys.exit(1)
