"""Benchmark entry point — prints ONE JSON line for the driver.

Two-process design (round-2 hardening): the outer process never imports jax, so a
wedged axon TPU tunnel cannot take the whole benchmark down. It probes TPU
availability in a bounded subprocess (2 attempts), runs the real benchmark in a
child with the inherited TPU env, and on any failure falls back to an honest
CPU-smoke run in a sanitized env (``JAX_PLATFORMS=cpu``, tunnel vars dropped) —
the JSON line then carries ``platform: "cpu"`` so it can never masquerade as a
TPU number.

Workloads follow the BASELINE.md ladder; select with BENCH_CONFIG (default picks by
platform):

- ``sd15_16``  — SD1.5-class UNet, bf16, batch=16, 1024² pixels (128² latents). The
  BASELINE headline shape ("sec/it at batch=16 1024²").
- ``sdxl_8``   — SDXL-class UNet, bf16, batch=8, 1024².
- ``zimage_21``— Z_Image-class MMDiT, batch=21, 1024² — the reference's own benchmark
  run (/root/reference/README.md:46-60: 26.00 s/it on one RTX 3090, 12.91 s/it on
  two GPUs). Z_Image's exact architecture is not public; this rung runs a
  flux-class proxy (models/flux.py z_image_turbo_config) at matching scale.
- ``flux_16``  — FLUX-class MMDiT, batch=16, 1024² (the BASELINE.json north-star
  shape). Full flux-dev (12B) needs FSDP over a v5e-8 pod slice; on a single chip
  this rung runs the dev *topology* at reduced depth so the shape (4096 img tokens
  of joint attention, bf16, pallas flash path) is what's measured.
- ``flux_16_int8`` — FULL 19/38 flux-dev topology with int8-stored weights
  (fits one v5e chip): the measured replacement for flux_16's analytic
  full-depth extrapolation.
- ``flux_stream`` — FULL 19/38 flux-dev, int8, WEIGHT-STREAMED on one chip
  (parallel/streaming.py): host-pinned params double-buffered through HBM —
  the rung for chips whose usable HBM is below even the int8 replica (the
  round-5 finding that left the flagship blank). PA_STREAM_HBM_BUDGET
  overrides the carve budget (bytes).
- ``wan_video``— WAN-class video DiT, 16 frames 480p-latent batch=1 (sequence-
  dominant workload; temporal tokens ≈ video "batch").
- ``hybrid_sd15`` — SD1.5-class UNet, batch=8, 512², on a heterogeneous
  tpu:0(70%)+cpu(30%) chain: the two-platform weighted host-scatter path
  (SURVEY §7 hard part 1) measured on real hardware.
- ``smoke``    — reduced-width SD1.5 topology on CPU (no TPU attached).

``vs_baseline`` is the reference's published single-GPU 26.00 s/it divided by our
s/it — emitted ONLY on the like-for-like ``zimage_21`` rung; every other rung
reports ``null`` (dividing the Z_Image baseline by a different workload's s/it is
cross-workload noise, not a speedup). ``mfu`` is analytic model FLOPs/step (XLA HLO
cost analysis) / s/it / aggregate chip peak bf16 FLOP/s.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.abspath(__file__))

# The tunneled TPU registers as the experimental 'axon' PJRT platform; treat it as
# TPU everywhere (round-1 failure mode: == "tpu" comparisons diverted real-TPU runs
# to the CPU-smoke path).
#
# PA_FAKE_TPU_PLATFORM extends the tuple for the watchdog DRY-RUN only (the
# round-3 lesson: the measurement pipeline's first real execution was on the
# one live tunnel window, and three infrastructure bugs ate it). The guard
# below makes the fake platform unusable against the real evidence files:
# every record it produces lands in PA_EVIDENCE_DIR and carries "dryrun".
_FAKE_TPU = os.environ.get("PA_FAKE_TPU_PLATFORM")
_TINY = os.environ.get("PA_BENCH_TINY") == "1"
_FAIL_INJECT = os.environ.get("PA_FAIL_INJECT") or os.environ.get(
    "PA_FAULT_PLAN")
if (_FAKE_TPU or _TINY or _FAIL_INJECT) and not os.environ.get(
        "PA_EVIDENCE_DIR"):
    raise RuntimeError(
        "PA_FAKE_TPU_PLATFORM / PA_BENCH_TINY / PA_FAIL_INJECT / "
        "PA_FAULT_PLAN require PA_EVIDENCE_DIR: a faked platform, "
        "tiny-workload, or injected-failure run must never write into the "
        "repo's real evidence artifacts (the perf ledger and postmortem "
        "bundles follow the evidence dir; utils/faults.py enforces the same "
        "arming rule in-process)"
    )
_TPU_PLATFORMS = ("tpu", "axon") + ((_FAKE_TPU,) if _FAKE_TPU else ())


def is_banked_tpu_record(rec: dict) -> bool:
    """The ONE freshness predicate for rung evidence, shared by every consumer
    (the fallbacks below and scripts/tpu_watchdog.py): a genuine measurement —
    not marked invalid, not a stale re-emit — from a TPU-class platform. The
    ``dryrun`` marker is deliberately NOT filtered here: mocked records are
    confined to their own PA_EVIDENCE_DIR, where the watchdog dry-run
    legitimately treats them as banked."""
    return (
        not rec.get("invalid")
        and not rec.get("stale")
        and rec.get("platform") in _TPU_PLATFORMS
    )


def evidence_dir() -> str:
    """Root for the append-only evidence artifacts (BASELINE_measured.json,
    KERNEL_BENCH.json, SAMPLER_LOOP_BENCH.json, BASELINE.md). The watchdog
    dry-run points this at a temp dir so a mocked run can never pollute the
    real record."""
    return os.environ.get("PA_EVIDENCE_DIR") or _REPO


def _ledger_append(record: dict, kind: str) -> None:
    """Outer-process perf-ledger append. Stdlib twin of
    ``comfyui_parallelanything_tpu.utils.telemetry.append_ledger_record`` —
    the outer process must never import the package (its ``__init__`` pulls
    jax, which a wedged axon tunnel hangs), so the schema stamp lives in both
    places on purpose; ``scripts/perf_ledger.py`` validates the shared
    ``schema`` field either way. Best-effort: a full disk must not cost the
    driver its one JSON line."""
    import time

    ledger = os.environ.get("PA_LEDGER_DIR") or os.path.join(
        evidence_dir(), "ledger"
    )
    rec = dict(record)
    rec["schema"] = "pa-perf-ledger/v1"
    rec["kind"] = kind
    rec.setdefault("ts", time.time())
    rec.setdefault("pid", os.getpid())
    try:
        os.makedirs(ledger, exist_ok=True)
        with open(os.path.join(ledger, "perf_ledger.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass

# Peak dense bf16 FLOP/s per chip, by device_kind substring (public spec sheets).
_PEAK_BF16 = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
]

_REF_SINGLE_GPU_S_IT = 26.00  # /root/reference/README.md:54-56 (Z_Image batch=21)

# Pinned timing protocol (VERDICT r5 next-7: the smoke rung drifted
# 4.87→5.71 s/it across rounds 3→5 with nothing to attribute it to). These
# are part of the evidence schema now — every JSON line records them plus the
# 1-minute load average, so a drifted number is auditable against host load.
TPU_BENCH_ITERS = 10
SMOKE_BENCH_ITERS = 5
BENCH_WARMUP_STEPS = 2


def _loadavg_1m():
    """1-minute load average, or None on platforms without getloadavg."""
    try:
        return round(os.getloadavg()[0], 2)
    except (AttributeError, OSError):
        return None


def _bf16_build(build_fn, cfg, **build_kw):
    """Build a model with bf16-STORED weights synthesized host-side from
    abstract shapes — no f32 pytree is ever materialized on any device.

    Two bugs this kills at once: (a) flax ``init`` stores params at the default
    ``param_dtype`` f32, so the "bf16" rung labels were silently benching f32
    weight storage (2x the HBM reads on every matmul — the usual TPU
    bottleneck); (b) the z-image proxy is 5.77B params = 21.5 GiB at f32, an
    init-time OOM on a 16 GiB v5e chip, while its bf16 inference layout
    (10.8 GiB) fits. Weights are zeros: matmul/attention timing is
    value-independent, the same argument as ``_synth_int8_params``."""
    import jax
    import jax.numpy as jnp

    sds = jax.eval_shape(
        lambda key: build_fn(cfg, rng=key, **build_kw).params, jax.random.key(0)
    )
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        params = jax.tree.map(
            lambda l: jnp.zeros(l.shape, jnp.bfloat16)
            if l.dtype == jnp.float32 else jnp.zeros(l.shape, l.dtype),
            sds,
        )
    return build_fn(cfg, params=params, **build_kw)


def _rung_sd15_16(jnp, rng):
    from comfyui_parallelanything_tpu.models import build_unet, sd15_config

    batch, latent, ctx_len = 16, 128, 77
    cfg = sd15_config(dtype=jnp.bfloat16)
    model = _bf16_build(build_unet, cfg, sample_shape=(1, latent, latent, 4))
    return (model, batch, (batch, latent, latent, 4), ctx_len, cfg.context_dim,
            {}, "SD1.5 UNet bf16 batch=16 1024x1024")


def _rung_sdxl_8(jnp, rng):
    from comfyui_parallelanything_tpu.models import build_unet, sdxl_config

    batch, latent, ctx_len = 8, 128, 77
    cfg = sdxl_config(dtype=jnp.bfloat16)
    model = _bf16_build(build_unet, cfg, sample_shape=(1, latent, latent, 4))
    kwargs = {"y": jnp.zeros((batch, cfg.adm_in_channels), jnp.float32)}
    return (model, batch, (batch, latent, latent, 4), ctx_len, cfg.context_dim,
            kwargs, "SDXL UNet bf16 batch=8 1024x1024")


def _rung_zimage_21(jnp, rng):
    from comfyui_parallelanything_tpu.models import build_flux, z_image_turbo_config

    batch, latent, ctx_len = 21, 128, 128
    cfg = z_image_turbo_config(dtype=jnp.bfloat16)
    model = _bf16_build(
        build_flux, cfg, sample_shape=(1, 16, 16, 16), txt_len=ctx_len
    )
    # 3 sequential microbatches of 7: 10.8 GiB bf16 weights + full-batch-21
    # activations OOM'd a 16 GiB v5e (evidence: zimage_21 fallback_stderr in
    # BASELINE_measured.json); 21 images per iteration either way.
    return (model, batch, (batch, latent, latent, 16), ctx_len, cfg.context_in_dim,
            {}, "Z_Image-scale MMDiT bf16 batch=21 (3x7 microbatch) 1024x1024 "
                "(flux-class proxy; README repro shape)", 3)


def _int8_synth_model(jnp, cfg, sample_shape, txt_len, name):
    """Flux-family model with int8-SYNTHESIZED weights (zeros; matmul timing
    is value-independent) built from abstract shapes — no high-precision
    pytree is ever materialized. Dequantize happens inside jit: int8 HBM
    reads, on-chip widening (models/quantize.py). Shared by the int8 rungs.
    Carries the staged pipeline spec (stage closures rebound through the same
    dequantize wrapper, the models/quantize.quantize_model pattern) so the
    weight-streaming rung can carve it."""
    import dataclasses as _dc

    from comfyui_parallelanything_tpu.models import flux_abstract_params
    from comfyui_parallelanything_tpu.models.api import DiffusionModel
    from comfyui_parallelanything_tpu.models.flux import (
        FluxModel,
        _flux_pipeline_spec,
    )
    from comfyui_parallelanything_tpu.models.quantize import dequantize_params

    sds = flux_abstract_params(cfg, sample_shape=sample_shape, txt_len=txt_len)
    params = _synth_int8_params(sds)
    module = FluxModel(cfg)

    def apply(p, x, t, context=None, **kw):
        return module.apply(
            {"params": dequantize_params(p, jnp.bfloat16)}, x, t, context, **kw
        )

    def wrap_stage(fn):
        def wrapped(p, *a, **k):
            return fn(dequantize_params(p, jnp.bfloat16), *a, **k)

        return wrapped

    spec = _flux_pipeline_spec(module, cfg)
    spec = _dc.replace(
        spec,
        prepare=wrap_stage(spec.prepare),
        segments=tuple(
            _dc.replace(seg, fn=wrap_stage(seg.fn)) for seg in spec.segments
        ),
        finalize=wrap_stage(spec.finalize),
    )
    return DiffusionModel(
        apply=apply, params=params, name=name, config=cfg, pipeline_spec=spec
    )


def _rung_zimage_21_int8(jnp, rng):
    """The README-repro shape (batch=21, 1024²) with int8-STORED weights —
    the fallback headline when the bf16 rung cannot fit the tunnel chip's
    usable HBM (observed this round: zimage_21 hit RESOURCE_EXHAUSTED at
    runtime even fully sequential, batch-1 microbatches — weights + overhead
    alone exceed the chip; see HBM_PROBE.json). Same proxy topology, same 21
    images per iteration; weights dequantize to bf16 inside jit, so compute
    is still bf16 and the workload label carries the weight-precision caveat
    for the vs_baseline claim."""
    from comfyui_parallelanything_tpu.models import z_image_turbo_config

    batch, latent, ctx_len = 21, 128, 128
    cfg = z_image_turbo_config(dtype=jnp.bfloat16)
    model = _int8_synth_model(
        jnp, cfg, sample_shape=(1, 16, 16, 16), txt_len=ctx_len,
        name="zimage-int8",
    )
    return (model, batch, (batch, latent, latent, 16), ctx_len,
            cfg.context_in_dim, {},
            "Z_Image-scale MMDiT int8 weights/bf16 compute batch=21 "
            "(3x7 microbatch) 1024x1024 (flux-class proxy; README repro "
            "shape; NOT weight-precision like-for-like)", 3)


def _rung_flux_16(jnp, rng):
    from comfyui_parallelanything_tpu.models import build_flux, flux_dev_config

    batch, latent, ctx_len = 16, 128, 512
    # Dev topology (double+single blocks, guidance embed, 24 heads x 128) at
    # depth that fits one v5e chip; full 19/38-depth dev runs FSDP multi-chip.
    cfg = flux_dev_config(depth=4, depth_single_blocks=8, dtype=jnp.bfloat16)
    model = _bf16_build(
        build_flux, cfg, sample_shape=(1, 32, 32, 16), txt_len=ctx_len
    )
    kwargs = {
        "y": jnp.zeros((batch, cfg.vec_in_dim), jnp.float32),
        "guidance": jnp.full((batch,), 3.5, jnp.float32),
    }
    return (model, batch, (batch, latent, latent, 16), ctx_len, cfg.context_in_dim,
            kwargs, "FLUX-class MMDiT bf16 batch=16 1024x1024 (reduced depth 4/8)")


def _synth_int8_params(sds, min_size: int = 2**16):
    """Materialize a quantized parameter pytree directly from abstract shapes,
    on host CPU: large >=2-D leaves become ``QuantTensor(int8 zeros, const
    scale)`` (the same min-size/channel-axis rule as quantize_params), small
    leaves bf16 zeros. Matmul timing is value-independent, so zeros measure the
    same compute as real weights — and a 12B high-precision pytree is never
    materialized anywhere."""
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.models.quantize import (
        QuantTensor,
        int8_eligible,
    )

    cpu = jax.devices("cpu")[0]

    def synth(leaf):
        shape = tuple(leaf.shape)
        with jax.default_device(cpu):
            if int8_eligible(shape, min_size):
                scale_shape = tuple(1 for _ in shape[:-1]) + (shape[-1],)
                return QuantTensor(
                    q=jnp.zeros(shape, jnp.int8),
                    scale=jnp.full(scale_shape, 1e-2, jnp.float32),
                )
            return jnp.zeros(shape, jnp.bfloat16)

    return jax.tree.map(synth, sds)


def _rung_flux_16_int8(jnp, rng):
    """FULL 19/38 flux-dev topology, int8-stored weights — the measured
    replacement for flux_16's analytic depth bridge (VERDICT r2 item 3): a
    ~12 GB int8 replica fits a 16 GB v5e chip, so full-depth s/it is a real
    measurement, not a FLOP-ratio extrapolation. Weights are synthesized
    directly as int8 (zeros; matmul timing is value-independent) from abstract
    shapes — a 12B f32/bf16 pytree is never materialized anywhere. Dequantize
    happens inside jit: int8 HBM reads, on-chip widening (models/quantize.py).
    """
    from comfyui_parallelanything_tpu.models import flux_dev_config

    batch, latent, ctx_len = 16, 128, 512
    cfg = flux_dev_config(dtype=jnp.bfloat16)
    model = _int8_synth_model(
        jnp, cfg, sample_shape=(1, 32, 32, 16), txt_len=ctx_len,
        name="flux-dev-int8",
    )
    kwargs = {
        "y": jnp.zeros((batch, cfg.vec_in_dim), jnp.float32),
        "guidance": jnp.full((batch,), 3.5, jnp.float32),
    }
    # 4 sequential microbatches of 4: ~12 GiB int8 weights + dequant temps +
    # full-batch-16 activations OOM'd the 16 GiB chip (evidence: flux_16_int8
    # fallback_stderr in BASELINE_measured.json); 16 images per iteration
    # either way, and 4x4608 token-rows per matmul still fills the MXU.
    return (model, batch, (batch, latent, latent, 16), ctx_len, cfg.context_in_dim,
            kwargs, "FLUX-dev MMDiT FULL depth 19/38, int8 weights, batch=16 "
                    "(4x4 microbatch) 1024x1024 (measured full depth, single chip)",
            4)


def _rung_flux_stream(jnp, rng):
    """FULL 19/38 flux-dev topology, int8 weights, STREAMED through one chip —
    the north-star shape (batch=16 @1024²) as a measurement instead of a
    blank: ~12 GiB of int8 weights exceed the chip's usable HBM (<10.8 GiB,
    round-5 HBM finding), so no resident placement can ever run it
    single-chip. The weight-streaming executor (parallel/streaming.py) keeps
    params host-pinned and double-buffers per-stage sub-pytrees through HBM —
    int8 on the wire (half the bf16 transfer bytes), dequantized on-chip
    inside each stage program. run_inner routes this rung through
    ``ParallelConfig(weight_sharding="stream")`` on the lead chip."""
    from comfyui_parallelanything_tpu.models import flux_dev_config

    batch, latent, ctx_len = 16, 128, 512
    cfg = flux_dev_config(dtype=jnp.bfloat16)
    model = _int8_synth_model(
        jnp, cfg, sample_shape=(1, 32, 32, 16), txt_len=ctx_len,
        name="flux-dev-int8-stream",
    )
    kwargs = {
        "y": jnp.zeros((batch, cfg.vec_in_dim), jnp.float32),
        "guidance": jnp.full((batch,), 3.5, jnp.float32),
    }
    # 4 sequential microbatches of 4 (the flux_16_int8 activation-peak
    # lesson); the streamed schedule re-runs per chunk, so transfer overlap
    # is measured under the same per-iteration image count as the resident
    # rungs.
    return (model, batch, (batch, latent, latent, 16), ctx_len,
            cfg.context_in_dim, kwargs,
            "FLUX-dev MMDiT FULL depth 19/38, int8 weights STREAMED "
            "(host-pinned, double-buffered), batch=16 (4x4 microbatch) "
            "1024x1024 (single chip; weights exceed HBM)", 4)


def _rung_wan_video(jnp, rng):
    from comfyui_parallelanything_tpu.models import build_wan, wan_1_3b_config

    batch, ctx_len = 1, 128
    cfg = wan_1_3b_config(depth=8, dtype=jnp.bfloat16)
    frames, lat_h, lat_w = 16, 30, 52  # ~480p latent video, 16 frames
    model = _bf16_build(
        build_wan, cfg, sample_shape=(1, frames, lat_h, lat_w, cfg.in_channels),
        txt_len=ctx_len,
    )
    return (model, batch, (batch, frames, lat_h, lat_w, cfg.in_channels), ctx_len,
            cfg.text_dim, {},
            f"WAN-class video DiT bf16 {frames}f {lat_h}x{lat_w} latents")


def _rung_hybrid_sd15(jnp, rng):
    """Heterogeneous tpu:0 + cpu weighted chain (SURVEY §7 hard part 1) on real
    hardware: the one rung that exercises the two-program host-scatter path
    (orchestrator._data_parallel multi-group branch) off the virtual mesh. The
    TPU carries 70%, the host CPU 30% — the reference's CPU+GPU hybrid chain
    configuration (README.md:133-134) in TPU terms. Small model + 512² so the
    CPU side cannot wedge a window."""
    from comfyui_parallelanything_tpu.models import build_unet, sd15_config

    batch, latent, ctx_len = 8, 64, 77
    cfg = sd15_config(dtype=jnp.bfloat16)
    model = _bf16_build(build_unet, cfg, sample_shape=(1, latent, latent, 4))
    return (model, batch, (batch, latent, latent, 4), ctx_len, cfg.context_dim,
            {}, "SD1.5 UNet bf16 batch=8 512x512 hybrid tpu:0(70)+cpu(30)")


def _rung_smoke(jnp, rng):
    from comfyui_parallelanything_tpu.models import build_unet, sd15_config

    batch, latent, ctx_len = 8, 32, 24
    cfg = sd15_config(
        model_channels=64,
        channel_mult=(1, 2, 4),
        transformer_depth=(1, 1, 1),
        context_dim=256,
        dtype=jnp.bfloat16,
    )
    model = build_unet(cfg, rng, sample_shape=(1, latent, latent, 4))
    return (model, batch, (batch, latent, latent, 4), ctx_len, cfg.context_dim,
            {}, "SD1.5-topology smoke batch=8 256x256")


# Single source of truth for rung names: the outer process validates BENCH_CONFIG
# against this dict, the inner dispatches through it — they cannot drift.
_RUNGS = {
    "sd15_16": _rung_sd15_16,
    "sdxl_8": _rung_sdxl_8,
    "zimage_21": _rung_zimage_21,
    "zimage_21_int8": _rung_zimage_21_int8,
    "flux_16": _rung_flux_16,
    "flux_16_int8": _rung_flux_16_int8,
    "flux_stream": _rung_flux_stream,
    "wan_video": _rung_wan_video,
    "hybrid_sd15": _rung_hybrid_sd15,
    "smoke": _rung_smoke,
}
_KNOWN_CONFIGS = tuple(_RUNGS)


def _build(config_name):
    import jax
    import jax.numpy as jnp

    if config_name not in _RUNGS:
        raise ValueError(f"unknown BENCH_CONFIG {config_name!r}")
    if os.environ.get("PA_BENCH_TINY") == "1" and config_name != "smoke":
        # Watchdog dry-run: every rung runs the smoke-size model (the control
        # flow under test is probe→bench→record, not the workload), with a
        # 2-way microbatch so the sequential-chunk path is exercised too.
        built = _rung_smoke(jnp, jax.random.key(0))
        label = f"TINY-DRYRUN[{config_name}] {built[6]}"
        return built[:6] + (label, 2)
    return _RUNGS[config_name](jnp, jax.random.key(0))


def _cost_flops(lowered):
    """FLOPs from a Lowered's XLA HLO cost analysis, or None if unavailable."""
    cost = lowered.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    flops = (cost or {}).get("flops")
    return float(flops) if flops and flops > 0 else None


def _step_cost(model, x, t, ctx, kwargs):
    """Analytic model FLOPs + bytes for one denoise step via the ONE shared
    accessor (``utils/roofline.step_cost``): XLA HLO cost analysis of a CPU
    lowering (the axon tunnel's PJRT client implements no cost analysis, and
    dot/conv counts are backend-independent) with the exact jaxpr walk as
    fallback and cross-check — the unification that keeps ``mfu`` and
    ``roofline_ratio`` counting the same step (the record carries
    ``flops_source`` and the hlo/jaxpr discrepancy ratio when both
    resolved). Returns the accessor's dict; every field None on failure."""
    try:
        from comfyui_parallelanything_tpu.utils import roofline

        return roofline.step_cost(
            model.apply, model.params, x, t, ctx, kwargs
        )
    except Exception:
        return {"flops": None, "bytes_accessed": None, "flops_hlo": None,
                "flops_jaxpr": None, "flops_source": None,
                "flops_discrepancy_ratio": None}


def _full_flux_flops(batch, latent, ctx_len):
    """Analytic FLOPs/step of the FULL 19/38-depth flux-dev at this rung's
    shapes, from abstract (never-materialized) params — the analytic bridge from
    the reduced-depth flux_16 measurement to the full model the BASELINE
    north-star is defined on."""
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.models import flux_abstract_params, flux_dev_config
    from comfyui_parallelanything_tpu.models.flux import FluxModel

    try:
        cfg = flux_dev_config(dtype=jnp.bfloat16)
        module = FluxModel(cfg)
        sds = flux_abstract_params(cfg, sample_shape=(1, 32, 32, 16), txt_len=ctx_len)
        args = (
            jax.ShapeDtypeStruct((batch, latent, latent, 16), jnp.float32),
            jax.ShapeDtypeStruct((batch,), jnp.float32),
            jax.ShapeDtypeStruct((batch, ctx_len, cfg.context_in_dim), jnp.float32),
            jax.ShapeDtypeStruct((batch, cfg.vec_in_dim), jnp.float32),
            jax.ShapeDtypeStruct((batch,), jnp.float32),
        )
        return _cost_flops(
            jax.jit(
                lambda p, x, t, c, y, g: module.apply(
                    {"params": p}, x, t, c, y=y, guidance=g
                )
            ).lower(sds, *args)
        )
    except Exception:
        return None


def _peak_bf16(device_kind):
    """Peak bf16 FLOP/s for a chip; falls back to the PALLAS_AXON_TPU_GEN env var
    when the tunneled device_kind string doesn't name the generation."""
    for kind in (device_kind.lower(), os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()):
        for key, peak in _PEAK_BF16:
            if key in kind:
                return peak
    return None


def _default_tpu_rung() -> str:
    """Default rung for a bare ``python bench.py`` on TPU (the driver's
    end-of-round run): the README-repro headline ``zimage_21`` — the one rung
    whose ``vs_baseline`` compares like-for-like against the reference's
    26.00 s/it — but only once the watchdog has proven it banks (a valid
    ``platform: tpu|axon`` line in BASELINE_measured.json). Second choice:
    the int8-weight variant of the same shape (banked the same way; its
    label carries the weight-precision caveat). Otherwise the reliable
    ``sd15_16``, so an unproven heavyweight can never cost the driver a
    wedged 30-minute child."""
    banked = set()
    try:
        with open(os.path.join(evidence_dir(), "BASELINE_measured.json")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if is_banked_tpu_record(rec):
                    banked.add(rec.get("rung"))
    except OSError:
        pass
    for rung in ("zimage_21", "zimage_21_int8"):
        if rung in banked:
            return rung
    return "sd15_16"


def _stale_tpu_record(requested):
    """The most recent banked VALID TPU record from BASELINE_measured.json
    (preferring the requested rung's own records), or None when no TPU
    evidence has ever banked. The wedged-tunnel fallback re-emits it with
    ``"stale": true`` instead of a meaningless CPU smoke (VERDICT r5 weak-1:
    three of five round snapshots were smoke while real TPU evidence sat in
    the measured file)."""
    best = best_any = None
    try:
        with open(os.path.join(evidence_dir(), "BASELINE_measured.json")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not is_banked_tpu_record(rec) or rec.get("dryrun"):
                    # dryrun additionally excluded: a mocked record must never
                    # re-emit as (stale) TPU evidence.
                    continue
                ts = rec.get("ts") or 0
                if best_any is None or ts >= (best_any.get("ts") or 0):
                    best_any = rec
                if requested and rec.get("rung") == requested:
                    if best is None or ts >= (best.get("ts") or 0):
                        best = rec
    except OSError:
        return None
    return best or best_any


def _plan_summary(pm):
    """Compact plan view for the JSON line (None when the planner is off,
    the chain was ineligible, or the summary layer fails — the one line
    outranks its plan field)."""
    try:
        from comfyui_parallelanything_tpu.parallel import planner

        return planner.plan_summary(getattr(pm, "plan", None))
    except Exception:
        return None


def _make_step(pm, batch, n_chunks, t, ctx, kwargs):
    """One denoise-step callable mapping latents -> latents (the shape
    ``chained_time`` chains). ``n_chunks > 1`` runs the batch as that many
    sequential microbatches and concatenates — identical images-per-iteration,
    activation peak divided by ``n_chunks`` (how a 16 GiB chip runs a batch
    sized for the reference's 24 GiB GPU). ``batch`` must divide evenly."""
    import jax.numpy as jnp

    if n_chunks == 1:
        return lambda v: pm(v, t, ctx, **kwargs)
    if batch % n_chunks:
        raise ValueError(f"batch {batch} not divisible by n_chunks {n_chunks}")

    def _slice_batch(a, sl):
        return a[sl] if hasattr(a, "shape") and a.shape[:1] == (batch,) else a

    def step(v):
        size = batch // n_chunks
        outs = []
        for i in range(n_chunks):
            sl = slice(i * size, (i + 1) * size)
            kw = {k: _slice_batch(a, sl) for k, a in kwargs.items()}
            outs.append(pm(v[sl], t[sl], ctx[sl], **kw))
        return jnp.concatenate(outs, axis=0)

    return step


def run_inner() -> None:
    """The measured benchmark, wrapped by the flight recorder: on ANY failure
    a postmortem bundle (trace rings, metrics, per-device memory, recent
    logs — utils/telemetry.py) is dumped and its path surfaced on stderr as
    ``POSTMORTEM_BUNDLE=<path>`` for the outer process / watchdog to attach
    to the failure record; the exception then propagates so the outer
    fallback ladder (stale re-emit → CPU smoke) behaves exactly as before."""
    try:
        _run_inner()
    except BaseException as e:
        if isinstance(e, SystemExit) and not e.code:
            raise
        try:
            from comfyui_parallelanything_tpu.utils import telemetry

            tag = os.environ.get("BENCH_CONFIG", "default")
            path = telemetry.write_postmortem(f"bench-{tag}", error=e)
            if path:
                sys.stderr.write(f"POSTMORTEM_BUNDLE={path}\n")
        except Exception:
            pass
        raise


def _run_inner() -> None:
    import jax
    import jax.numpy as jnp

    # Persistent XLA compilation cache: repeat driver runs skip the 20-40s
    # first-compile (cache dir is repo-local; harmless on first run). The
    # enable also installs the compile-event watchers; install them
    # explicitly too so compile accounting survives a cache-enable failure.
    from comfyui_parallelanything_tpu.utils import telemetry

    telemetry.watch_compiles()
    telemetry.watermark.reset()
    try:
        from comfyui_parallelanything_tpu.utils import enable_compilation_cache

        enable_compilation_cache(os.path.join(_REPO, ".jax_cache"))
    except Exception:
        pass

    from comfyui_parallelanything_tpu import (
        DeviceChain,
        ParallelConfig,
        parallelize,
    )

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    is_tpu = platform in _TPU_PLATFORMS
    config_name = os.environ.get(
        "BENCH_CONFIG", _default_tpu_rung() if is_tpu else "smoke"
    )

    built = _build(config_name)
    model, batch, x_shape, ctx_len, ctx_dim, kwargs, workload = built[:7]
    # Optional 8th element: sequential microbatch count. The big single-chip
    # rungs OOM at full batch (bf16 weights 10.8-12 GiB + the fused
    # single-block projection's (B, 4224, 21504) activation on a 16 GiB v5e);
    # splitting the batch into N sequential chunks divides the activation peak
    # by N while keeping the workload identical — the same B images per
    # iteration, exactly how a 16 GiB chip should run a batch sized for the
    # reference's 24 GiB RTX 3090.
    n_chunks = built[7] if len(built) > 7 else 1
    # BENCH_MICROBATCH: the watchdog's OOM-recovery knob — re-run a rung with a
    # deeper sequential split in the SAME window instead of waiting a round for
    # a code change (VERDICT r3 next-1: "microbatch deeper (7x3, 8x2)"). Values
    # that don't divide the batch round up to the next divisor.
    override = os.environ.get("BENCH_MICROBATCH")
    if override:
        want = max(int(override), n_chunks)
        # Next divisor of batch at or above the request; an over-deep request
        # clamps to fully-sequential (batch chunks of 1) instead of crashing.
        n_chunks = next(
            (c for c in range(want, batch + 1) if batch % c == 0), batch
        )

    kx, kc = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, x_shape, jnp.float32)
    t = jnp.linspace(999.0, 1.0, batch)
    ctx = jax.random.normal(kc, (batch, ctx_len, ctx_dim), jnp.float32)

    # Analytic step cost BEFORE the wrap (it was always computed for MFU —
    # now it doubles as the planner's hints): the auto-parallel planner
    # (parallel/planner.py) scores candidate plans against the rung's real
    # per-dispatch FLOPs/bytes instead of a weights-derived estimate.
    cost = _step_cost(model, x, t, ctx, kwargs)
    plan_hints = {
        "rung": config_name,
        "flops": (cost["flops"] / n_chunks) if cost["flops"] else None,
        "bytes_accessed": (
            cost["bytes_accessed"] / n_chunks
            if cost["bytes_accessed"] else None
        ),
        "batch": batch // n_chunks,
    }

    if config_name == "flux_stream":
        # Weight-streaming rung: ONE chip, params host-pinned, stages
        # double-buffered (parallel/streaming.py). The explicit stream mode
        # pins the rung's meaning (the weights-don't-fit auto-routing would
        # pick it anyway on a chip whose budget the pytree exceeds) while
        # the planner still searches the stage-CARVE axis within it;
        # PA_STREAM_HBM_BUDGET overrides the carve budget — the off-hardware
        # rehearsal forces multi-stage carving on a tiny model with it.
        chain = DeviceChain.even([f"{platform}:{jax.devices()[0].id}"])
        budget = os.environ.get("PA_STREAM_HBM_BUDGET")
        pm = parallelize(
            model, chain,
            ParallelConfig(
                weight_sharding="stream",
                hbm_budget_bytes=int(budget) if budget else None,
            ),
            plan_hints=plan_hints,
        )
    elif config_name == "hybrid_sd15" and is_tpu and platform != "cpu":
        # The heterogeneous rung: lead TPU chip at 70%, host CPU at 30% — a
        # two-platform chain, so parallelize builds two SPMD groups and the
        # weighted host scatter (SURVEY §7 hard part 1) actually runs.
        chain = DeviceChain.from_pairs(
            [(f"{platform}:{jax.devices()[0].id}", 70.0), ("cpu", 30.0)]
        )
        pm = parallelize(model, chain, plan_hints=plan_hints)
    else:
        chain = DeviceChain.even([f"{platform}:{d.id}" for d in jax.devices()])
        pm = parallelize(model, chain, plan_hints=plan_hints)

    step = _make_step(pm, batch, n_chunks, t, ctx, kwargs)

    # Span tracing (round 8, utils/tracing.py): every benchmarked iteration
    # runs traced — per-span cost is ~µs against multi-second denoise steps —
    # so every JSON line carries the trace-derived aggregates
    # (stream_overlap_efficiency / lane_wait_p95 / host_gap_ms) and
    # PA_TRACE_OUT (the --trace-out flag) can dump the full Perfetto
    # timeline without a second run.
    from comfyui_parallelanything_tpu.utils import tracing

    tracing.enable()
    # Numerics sentinel (round 11, utils/numerics.py): OPT-IN for bench runs
    # (PA_NUMERICS=1) — with the flag on, the streaming rung's per-stage
    # finite checks run inside the timed iterations, which would shift
    # sec/it against pre-sentinel ledger baselines. Default-off keeps the
    # pinned timing protocol untouched; the fingerprint and final-output
    # stats below are flag-independent (computed after the loop), so every
    # line still carries latent_fingerprint/nonfinite_events either way.
    from comfyui_parallelanything_tpu.utils import numerics

    if os.environ.get("PA_NUMERICS", "") not in ("", "0", "false"):
        numerics.enable()
    numerics.sentinel.reset()
    inner_step = step
    # Fault injection (round 14, utils/faults.py — the unified registry
    # absorbing this file's old ad-hoc parser): a deterministic mid-run
    # failure (``mid-step-crash`` site) so the postmortem/forensics path is
    # rehearsed off-hardware — the round-3 lesson applied to the flight
    # recorder itself. The legacy ``PA_FAIL_INJECT=oom`` alias fires from
    # step 3 on (the historical contract: the bundle holds real warmup
    # spans/samples); ``PA_FAULT_PLAN`` schedules arbitrary steps.
    # ``nan:<lane>`` values parse to the ``lane-nan`` site (the serving
    # quarantine rehearsal) and never fire here. Arming requires the
    # PA_EVIDENCE_DIR redirect — enforced at module load above AND by the
    # registry's own rule.
    from comfyui_parallelanything_tpu.utils import faults

    _step_no = [0]

    def step(v):
        _step_no[0] += 1
        _act = faults.check("mid-step-crash", key=f"{config_name}:{_step_no[0]}")
        if _act is not None:
            raise faults.oom_error(_act)
        with tracing.span("step", cat="bench", rung=config_name):
            out = inner_step(v)
        # HBM watermark sampling during WARMUP steps only: memory_stats() is
        # a host call (and the fallback walks live arrays), so sampling
        # inside the timed loop would inflate sec/it against baselines
        # banked before round 9 — the exact protocol drift the pinned
        # iteration counts exist to prevent. Warmup runs the identical
        # program, so the peak it observes is the steady-state peak; one
        # more sample lands after the timed loop below.
        if _step_no[0] <= BENCH_WARMUP_STEPS:
            telemetry.watermark.sample()
        return out

    # Warmup/compile + timed denoise-step iterations, tunnel-proof: the axon
    # plugin's block_until_ready returned in 2.8 ms for a 43-TFLOP step (~80x
    # the chip's peak), so chained_time chains each iteration's output into
    # the next input and closes with a host readback (utils/metrics.py).
    # The protocol is PINNED and recorded in the JSON line (iteration count +
    # warmup steps, VERDICT r5 next-7): the 4.87→5.71 s/it smoke drift across
    # rounds could not be attributed between protocol change and host load —
    # now the protocol is a constant and the load average is in the record.
    from comfyui_parallelanything_tpu.utils.metrics import chained_time

    iters = TPU_BENCH_ITERS if is_tpu else SMOKE_BENCH_ITERS
    if os.environ.get("PA_BENCH_TINY") == "1":
        iters = 3  # dry-run: control flow under test, not timing fidelity
    sec_it, final_out = chained_time(step, x, iters, warmup=BENCH_WARMUP_STEPS)
    # Post-loop watermark sample (the warmup-phase samples above kept the
    # host call out of the timed iterations): on real devices memory_stats'
    # running peak covers the timed steps too.
    telemetry.watermark.sample()

    # Numerics audit fields (utils/numerics.py), computed post-loop on the
    # chained final output: the latent fingerprint (bf16-quantized digest —
    # deterministic per rung, what scripts/numerics_audit.py --check diffs
    # against its golden bank) and the run's non-finite event count (sentinel
    # events — e.g. a streamed stage gone bad — plus a poisoned final
    # output). Best-effort: the one JSON line outranks its audit fields.
    latent_fingerprint = None
    try:
        import numpy as _np

        fstats = numerics.stats_to_dict(
            _np.asarray(numerics.array_stats(final_out))
        )
        if fstats["nonfinite"]:
            numerics.sentinel.record_event(
                "bench-final", rung=config_name, **fstats
            )
        latent_fingerprint = numerics.latent_fingerprint(final_out)
    except Exception:
        pass
    nonfinite_events = numerics.sentinel.event_count

    trace_events = tracing.export()
    trace_aggs = tracing.trace_aggregates(trace_events)
    trace_out = os.environ.get("PA_TRACE_OUT")
    if trace_out:
        with open(trace_out, "w") as f:
            json.dump(trace_events, f)
        sys.stderr.write(f"bench: trace written to {trace_out}\n")

    # MFU: analytic step FLOPs / time / aggregate peak. TPU only (CPU peak is
    # not meaningful for MXU utilization). ``cost`` was computed before the
    # wrap (it seeded the planner's hints).
    mfu = None
    flops = cost["flops"]
    peak = _peak_bf16(jax.devices()[0].device_kind) if is_tpu else None
    if flops and peak:
        mfu = round(flops / sec_it / (peak * n_dev), 4)

    # Roofline attribution (utils/roofline.py, this round): the calibrated
    # analytic prediction for this rung's step — max(compute, memory) over
    # the platform roofline, scaled by the banked (rung, platform,
    # shape-bucket) calibration when one exists — the predicted_step_s /
    # roofline_ratio pair every line carries, plus the measured-side bucket
    # decomposition of the timed window from the trace spans. DP forwards
    # run collective-free, so the bench prediction carries no comms term;
    # the per-program registry rows (ledger only) price their own meshes.
    predicted_step_s = predicted_step_raw_s = roofline_ratio = None
    attribution = None
    try:
        from comfyui_parallelanything_tpu.utils import roofline

        if flops and roofline.enabled():
            spec = roofline.platform_spec(
                jax.devices()[0].device_kind, platform
            )
            pred = roofline.predict_time_s(
                flops, cost["bytes_accessed"], spec, n_devices=n_dev
            )
            scale = roofline.calibration_scale(
                roofline.load_calibration(), f"rung:{config_name}",
                platform, roofline.shape_bucket(flops),
            )
            predicted_step_raw_s = round(pred["predicted_s"], 6)
            predicted_step_s = round(pred["predicted_s"] * scale, 6)
            if sec_it > 0:
                roofline_ratio = round(predicted_step_s / sec_it, 4)
        if roofline.enabled():
            attribution = roofline.attribution_from_trace(
                trace_events, wall_s=sec_it * iters, last_steps=iters
            )
    except Exception:
        pass

    # vs_baseline only on the README-repro-shaped rungs; anything else would
    # divide the Z_Image baseline by a different workload's s/it. The int8
    # variant's workload label carries the weight-precision caveat the claim
    # must keep.
    vs_baseline = (
        round(_REF_SINGLE_GPU_S_IT / sec_it, 2)
        if config_name in ("zimage_21", "zimage_21_int8") else None
    )

    from comfyui_parallelanything_tpu.ops.attention import (
        chunk_config,
        get_attention_backend,
        resolved_backends,
    )

    _comp = telemetry.compile_snapshot()
    record = {
        "metric": f"sec/it denoise step [{config_name}]",
        "value": round(sec_it, 4),
        "unit": "s/it",
        "vs_baseline": vs_baseline,
        "platform": platform,
        "n_devices": n_dev,
        "mfu": mfu,
        "model_flops_per_step": flops,
        "workload": f"{workload} ({platform} x{n_dev})",
        "microbatch_chunks": n_chunks,
        "images_per_sec": round(batch / sec_it, 3),
        # Pinned protocol + host-load context (the smoke-drift audit trail).
        "bench_iters": iters,
        "warmup_steps": BENCH_WARMUP_STEPS,
        "loadavg_1m": _loadavg_1m(),
        # Trace-derived aggregates (utils/tracing.py): stream compute
        # occupancy of the streamed-run wall clock (null off the stream
        # rung), serving lane-wait p95 (null without serving traffic), and
        # the mean host gap between step spans — where host scheduling
        # overhead shows up before any device profile is opened.
        **trace_aggs,
        # Resource accounting (utils/telemetry.py, round 9): where the
        # compiles and the bytes went. compile_time_s is total in-process
        # XLA backend-compile wall time; hits/misses are the persistent
        # compilation cache's (a warm .jax_cache turns the 20-40s
        # first-compile into hits); peak_hbm_bytes is the per-iteration
        # watermark (deterministic pseudo-accounting off-hardware).
        "compile_time_s": _comp["compile_time_s"],
        "compile_cache_hits": _comp["cache_hits"],
        "compile_cache_misses": _comp["cache_misses"],
        "peak_hbm_bytes": telemetry.watermark.peak_bytes or None,
        # Numerics audit (utils/numerics.py): the rung's deterministic
        # latent fingerprint (drift-gated by scripts/numerics_audit.py) and
        # non-finite events observed this run (0 on a healthy rung).
        "latent_fingerprint": latent_fingerprint,
        "nonfinite_events": nonfinite_events,
        # Which attention path(s) actually served the run, resolved at trace
        # time ("pallas", "xla", or "pallas+xla" when different shapes picked
        # differently) — so the evidence never hides an XLA fallback behind an
        # "auto" setting. Falls back to the configured setting if the model
        # has no attention at all.
        "attention_backend": "+".join(resolved_backends()) or get_attention_backend(),
        # Which chunked-attention configuration served the run (the sd15_16
        # MFU-budget sweep dimension): threshold elems + softmax dtype.
        "attn_chunk": chunk_config(),
        # Roofline attribution (utils/roofline.py): the calibrated analytic
        # step prediction, its ratio against the measured step (sane band
        # (0, 1.2] — gated by scripts/roofline_report.py --check), the raw
        # (uncalibrated) prediction the calibration fit reads back, the
        # measured-side compute/exposed-transfer/host-gap/comms bucket
        # decomposition of the timed window, and which FLOPs source priced
        # it (hlo vs jaxpr, + their discrepancy ratio when both resolved).
        "predicted_step_s": predicted_step_s,
        "predicted_step_raw_s": predicted_step_raw_s,
        "roofline_ratio": roofline_ratio,
        "attribution": attribution,
        "flops_source": cost["flops_source"],
        "flops_discrepancy_ratio": cost["flops_discrepancy_ratio"],
        # Auto-parallel planner (parallel/planner.py): the plan this rung's
        # wrap routed through — chosen candidate, shadow hand-plan score,
        # divergence — null with PA_PLANNER=0 or on ineligible chains
        # (hybrid multi-group).
        "plan": _plan_summary(pm),
    }
    if _FAKE_TPU or _TINY:
        record["dryrun"] = True
    if config_name == "flux_16" and flops:
        # Analytic bridge to the full 19/38-depth model (compute-bound regime:
        # time scales with matmul FLOPs at fixed shapes/arithmetic class).
        full = _full_flux_flops(batch, x_shape[1], ctx_len)
        if full:
            record["full_model_flops_per_step"] = full
            record["extrapolated_full_depth_s_it"] = round(sec_it * full / flops, 4)
    # Perf-ledger record (utils/telemetry.py): the regression gate's input —
    # one schema-versioned line per measured run, rung-stamped. The ledger
    # twin additionally carries the per-program roofline rows (predictions
    # for every instrumented program this run compiled — the calibration
    # fit's program-level input), which stay off the stdout line to keep
    # the driver contract lean.
    # kind="plan" ledger record (parallel/planner.py + scripts/plan_report.py
    # --check): the decision with its measured actual — predicted-vs-actual
    # error banked per rung, and the raw prediction fit_calibration reads
    # back so the planner sharpens per platform. Appended BEFORE the bench
    # record so the ledger's last line stays the bench record (the
    # rehearsal tests' contract).
    try:
        plan_decision = getattr(pm, "plan", None)
        if plan_decision is not None:
            from comfyui_parallelanything_tpu.parallel import planner

            plan_ledger = planner.ledger_record(
                plan_decision, actual_s=sec_it / n_chunks
            )
            if _FAKE_TPU or _TINY:
                plan_ledger["dryrun"] = True
            telemetry.append_ledger_record(plan_ledger, "plan")
    except Exception:
        pass

    ledger_rec = {**record, "rung": config_name}
    try:
        from comfyui_parallelanything_tpu.utils import roofline

        prog_rows = roofline.program_rows_for_ledger()
        if (prog_rows and "parallel-apply" in prog_rows
                and config_name != "flux_stream"):
            # Program-level measured_s — what the calibration fit pairs
            # against predicted_raw_s per program. The resident rungs'
            # timed step is exactly n_chunks sequential dispatches of the
            # DP step program, so per-dispatch wall is its honest measured
            # cost. The streamed rung's step runs the stage programs
            # instead (stage-index→program joins await the planner item).
            prog_rows["parallel-apply"]["measured_s"] = round(
                sec_it / n_chunks, 6
            )
        ledger_rec["roofline_programs"] = prog_rows
    except Exception:
        pass
    telemetry.append_ledger_record(ledger_rec, "bench")
    print(json.dumps(record))


def _cpu_env():
    """Sanitized CPU env — the shared tests/conftest.py recipe, via the graft
    entry's helper so the sanitization logic lives in one place."""
    from __graft_entry__ import _sanitized_cpu_env

    return _sanitized_cpu_env(1)


def _postmortem_path(stderr: str) -> str | None:
    """The inner child's ``POSTMORTEM_BUNDLE=<path>`` marker, if it dumped
    one before dying (run_inner's flight-recorder wrapper)."""
    import re

    m = None
    for m in re.finditer(r"POSTMORTEM_BUNDLE=(\S+)", stderr or ""):
        pass  # last marker wins (retries can dump more than one)
    return m.group(1) if m else None


def _run_child(env, config, timeout):
    """Run the inner benchmark in a subprocess.

    Returns ``(json_line_or_None, stderr_tail, postmortem_path_or_None)`` —
    the stderr tail is preserved so a failed child's traceback survives into
    the round's artifacts, and the postmortem marker is extracted BEFORE the
    tail truncation (the traceback printed after it can exceed the tail)."""
    env = dict(env)
    if config is not None:
        env["BENCH_CONFIG"] = config
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner"],
            env=env, cwd=_REPO, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        # A child can print its metric line and then hang in plugin teardown
        # (the axon wedge) — salvage stdout before declaring the run lost.
        from __graft_entry__ import _salvage_output

        stdout, stderr = _salvage_output(e)
        tail = (f"inner benchmark timed out after {timeout}s; "
                f"stderr tail:\n{stderr.strip()[-2000:]}")
        return _last_json_line(stdout), tail, _postmortem_path(stderr)
    return (_last_json_line(proc.stdout), proc.stderr.strip()[-2000:],
            _postmortem_path(proc.stderr))


def _last_json_line(stdout):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in parsed:
                return line
    return None


def _tpu_probe(timeout=120, attempts=2):
    """Bounded check that the TPU backend actually initializes. A wedged axon
    tunnel hangs `import jax`, so this must run (and die) in a subprocess.

    Returns ``(ok, reason)`` — the probe child's stderr tail survives into the
    fallback note so a tunnel-flap diagnostic reaches the round's artifacts."""
    code = (
        "import jax, sys; d = jax.devices(); "
        f"sys.exit(0 if d and d[0].platform in {_TPU_PLATFORMS!r} else 3)"
    )
    reason = ""
    for _ in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                env=dict(os.environ), capture_output=True, text=True,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            reason = f"probe timed out after {timeout}s (wedged tunnel?)"
            continue  # worth one more attempt
        if proc.returncode == 0:
            return True, ""
        reason = f"probe rc={proc.returncode}: {proc.stderr.strip()[-500:]}"
        if proc.returncode == 3:
            return False, reason  # jax imported fine; definitively not TPU
        # other nonzero rc: backend init crashed (tunnel flap) — retry once
    return False, reason


# Fields added to the line schema after records were first banked: a stale
# re-emit (or error line) must carry them as nulls, never omit them — the
# schema stays uniform for every consumer.
_LATE_SCHEMA_FIELDS = (
    "stream_overlap_efficiency", "lane_wait_p95", "host_gap_ms",
    "compile_time_s", "compile_cache_hits", "compile_cache_misses",
    "peak_hbm_bytes", "latent_fingerprint", "nonfinite_events",
    # Roofline attribution (round 13): prediction, ratio, measured-side
    # bucket breakdown, and the FLOPs-source audit fields.
    "predicted_step_s", "predicted_step_raw_s", "roofline_ratio",
    "attribution", "flops_source", "flops_discrepancy_ratio",
    # Auto-parallel planner (round 18): the plan the wrap routed through.
    "plan",
)


def _error_line(error, metric="error", postmortem=None):
    """The one failure-path JSON schema — every error exit goes through here so
    the driver always sees a consistent field set (including the trace-derived
    aggregate and resource-accounting fields every bench line now carries,
    null here). ``postmortem`` is the failure bundle's path when the inner
    child managed to dump one."""
    rec = {
        "metric": metric, "value": 0, "unit": "", "vs_baseline": None,
        "platform": "none", "n_devices": 0, "error": error[:300],
        "loadavg_1m": _loadavg_1m(),
    }
    for field in _LATE_SCHEMA_FIELDS:
        rec[field] = None
    if postmortem:
        rec["postmortem"] = postmortem
    return json.dumps(rec)


def _pop_trace_out_flag() -> None:
    """Honor ``--trace-out PATH`` (and ``--trace-out=PATH``) by exporting
    PA_TRACE_OUT for the inner child (both spellings also work set directly
    in the environment). Parsed by hand: bench.py's only other argv surface
    is the ``--inner`` sentinel, and argparse would reject it."""
    argv = sys.argv
    for i, a in enumerate(list(argv)):
        if a == "--trace-out" and i + 1 < len(argv):
            os.environ["PA_TRACE_OUT"] = os.path.abspath(argv[i + 1])
            del argv[i:i + 2]
            return
        if a.startswith("--trace-out="):
            os.environ["PA_TRACE_OUT"] = os.path.abspath(a.split("=", 1)[1])
            del argv[i]
            return


def main() -> None:
    _pop_trace_out_flag()
    if "--inner" in sys.argv:
        run_inner()
        return
    try:
        _orchestrate()
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — the driver contract is one JSON line, always
        print(_error_line(str(e)))
        sys.exit(1)


def _orchestrate() -> None:
    requested = os.environ.get("BENCH_CONFIG")
    if requested is not None and requested not in _KNOWN_CONFIGS:
        # Misconfiguration must surface as an error, not a plausible smoke line.
        print(_error_line(
            f"unknown BENCH_CONFIG {requested!r}; known: {list(_KNOWN_CONFIGS)}"
        ))
        sys.exit(1)

    # smoke is by definition the no-TPU rung — skip the (up to 2×120s) probe.
    fallback_cause = "no TPU available"
    postmortem = None
    if os.environ.get("BENCH_FORCE_CPU") != "1" and requested != "smoke":
        tpu_ok, probe_reason = _tpu_probe()
        if tpu_ok:
            line, err, postmortem = _run_child(
                dict(os.environ), requested, timeout=1800
            )
            if line is not None:
                print(line)
                return
            fallback_cause = "TPU benchmark child failed after successful probe"
            sys.stderr.write(
                f"bench: {fallback_cause}; falling back to CPU smoke. "
                f"Inner stderr tail:\n{err}\n"
            )
            # The failed attempt is ledger history (kind=error — the
            # regression gate never compares it) with its forensics pointer.
            _ledger_append({
                "rung": requested, "error": fallback_cause,
                "stderr_tail": err[-500:], "postmortem": postmortem,
                "loadavg_1m": _loadavg_1m(),
            }, "error")
        elif probe_reason:
            fallback_cause = f"TPU probe failed: {probe_reason[:200]}"
            sys.stderr.write(f"bench: TPU probe failed — {probe_reason}\n")

        # Stale-evidence fallback (VERDICT r5 weak-1/next-4): a wedged tunnel
        # must not turn the round's official line into a CPU smoke when real
        # TPU evidence is banked — re-emit the most recent valid banked TPU
        # record, explicitly marked stale with its capture timestamp. Still
        # exactly one JSON line.
        stale = _stale_tpu_record(requested)
        if stale is not None:
            out = dict(stale)
            out["stale"] = True
            out["stale_reason"] = fallback_cause
            out["captured_ts"] = out.get("ts")
            out["loadavg_1m"] = _loadavg_1m()  # load NOW, not at capture
            # Records banked before rounds 8/9 predate the trace-derived
            # aggregates and the resource-accounting fields; the schema
            # stays uniform (nulls, never absent).
            for field in _LATE_SCHEMA_FIELDS:
                out.setdefault(field, None)
            if postmortem:
                # The FAILED fresh attempt's forensics ride the stale line —
                # the whole point of the bundle is diagnosing why the rung
                # needed the fallback.
                out["postmortem"] = postmortem
            sys.stderr.write(
                f"bench: emitting stale banked TPU record for rung "
                f"{out.get('rung')!r} (captured ts {out.get('ts')}) — "
                f"{fallback_cause}\n"
            )
            print(json.dumps(out))
            return

    # Honest CPU fallback — platform field in the JSON marks it as such
    # (reached only when NO TPU evidence has ever banked). Always the smoke
    # rung: the real rungs are TPU-sized and would hang a CPU run.
    if requested not in (None, "smoke"):
        sys.stderr.write(
            f"bench: substituting CPU smoke rung for requested {requested!r} "
            f"({fallback_cause})\n"
        )
    line, err, cpu_postmortem = _run_child(_cpu_env(), "smoke", timeout=900)
    if line is not None:
        if postmortem:
            # A TPU attempt failed (and dumped forensics) before this smoke
            # substitution — its bundle path must ride the line we actually
            # emit, like the stale and error paths, or the most common
            # failure shape (TPU OOM → smoke fallback) loses its postmortem.
            try:
                out = json.loads(line)
                out["postmortem"] = postmortem
                line = json.dumps(out)
            except json.JSONDecodeError:
                pass
        print(line)
        return

    # Last resort: still exactly one parseable line, honestly labeled, with
    # the forensics pointer (the most recent bundle any child dumped).
    postmortem = cpu_postmortem or postmortem
    sys.stderr.write(f"bench: CPU fallback also failed. Inner stderr tail:\n{err}\n")
    _ledger_append({
        "rung": requested or "smoke",
        "error": "both TPU and CPU benchmark subprocesses failed",
        "stderr_tail": err[-500:], "postmortem": postmortem,
        "loadavg_1m": _loadavg_1m(),
    }, "error")
    print(_error_line(
        "both TPU and CPU benchmark subprocesses failed; last stderr: " + err[-200:],
        metric="sec/it denoise step [unavailable]",
        postmortem=postmortem,
    ))
    sys.exit(1)


if __name__ == "__main__":
    main()
