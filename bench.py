"""Benchmark entry point — prints ONE JSON line for the driver.

Workload (round 1): SD1.5-class UNet, bf16, batch=16, 512x512 pixels (64x64 latents),
denoise-step forward with batched CFG folded in — the closest runnable analogue of the
reference's headline measurement (s/it read off the sampler; /root/reference/README.md:46-60,
26.00 s/it single-GPU at batch=21 1024^2 on an RTX 3090). The ladder's 1024^2 FLUX
config takes over as the flagship once the MMDiT lands.

``vs_baseline`` is the reference's published single-GPU sec/it divided by ours —
>1 means faster than the reference's single-GPU row. The workloads are not yet
identical (SD1.5 @512^2 vs Z_Image @1024^2); the "workload" field says exactly what ran.
"""

import json
import sys
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu import DeviceChain, parallelize
    from comfyui_parallelanything_tpu.models import build_unet, sd15_config

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())

    if platform == "tpu":
        batch, latent = 16, 64
        cfg = sd15_config(dtype=jnp.bfloat16)
        workload = f"SD1.5 UNet bf16 batch={batch} 512x512"
    else:
        # Off-TPU smoke: same topology, reduced widths, so the bench path stays
        # executable on the CPU mesh without a TPU attached.
        batch, latent = 8, 32
        cfg = sd15_config(
            model_channels=64,
            channel_mult=(1, 2, 4),
            transformer_depth=(1, 1, 1),
            context_dim=256,
            dtype=jnp.bfloat16,
        )
        workload = f"SD1.5-topology smoke batch={batch} 256x256"
    model = build_unet(
        cfg, jax.random.key(0), sample_shape=(1, latent, latent, 4), name="sd15"
    )

    chain = DeviceChain.even(
        [f"{platform}:{d.id}" for d in jax.devices()][: max(1, n_dev)]
    )
    pm = parallelize(model, chain)

    rng = jax.random.key(1)
    kx, kc = jax.random.split(rng)
    x = jax.random.normal(kx, (batch, latent, latent, 4), jnp.float32)
    t = jnp.linspace(999.0, 1.0, batch)
    ctx = jax.random.normal(kc, (batch, 77, cfg.context_dim), jnp.float32)

    # Warmup/compile, then timed denoise-step iterations.
    out = pm(x, t, ctx)
    jax.block_until_ready(out)
    iters = 10 if platform == "tpu" else 2  # CPU runs are smoke-only
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pm(x, t, ctx)
    jax.block_until_ready(out)
    sec_it = (time.perf_counter() - t0) / iters

    ref_single_gpu = 26.00  # /root/reference/README.md:54-56
    print(
        json.dumps(
            {
                "metric": "sec/it SD1.5-UNet denoise step",
                "value": round(sec_it, 4),
                "unit": "s/it",
                "vs_baseline": round(ref_single_gpu / sec_it, 2),
                "workload": f"{workload} ({platform} x{n_dev})",
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver needs a line either way
        print(json.dumps({"metric": "error", "value": 0, "unit": "", "vs_baseline": 0, "error": str(e)[:300]}))
        sys.exit(1)
