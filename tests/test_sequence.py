"""Sequence/context parallelism: ring + Ulysses attention must match single-device
attention over the full sequence (first-class here; absent in the reference —
SURVEY §5.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.ops.attention import _xla_attention
from comfyui_parallelanything_tpu.parallel.mesh import AXIS_SEQ, build_mesh
from comfyui_parallelanything_tpu.parallel.sequence import sequence_parallel_attention


@pytest.fixture(scope="module")
def seq_mesh(cpu_devices):
    return build_mesh(cpu_devices[:4], {AXIS_SEQ: 4})


def _qkv(B=2, S=32, H=4, D=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


class TestRingAttention:
    def test_matches_full_attention(self, seq_mesh):
        q, k, v = _qkv()
        scale = q.shape[-1] ** -0.5
        want = _xla_attention(q, k, v, scale)
        got = sequence_parallel_attention(q, k, v, seq_mesh, method="ring")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_output_sharded_on_seq(self, seq_mesh):
        q, k, v = _qkv()
        got = sequence_parallel_attention(q, k, v, seq_mesh, method="ring")
        assert len(got.sharding.device_set) == 4

    def test_rejects_indivisible_seq(self, seq_mesh):
        q, k, v = _qkv(S=30)
        with pytest.raises(ValueError, match="not divisible"):
            sequence_parallel_attention(q, k, v, seq_mesh, method="ring")


class TestUlyssesAttention:
    def test_matches_full_attention(self, seq_mesh):
        q, k, v = _qkv()
        scale = q.shape[-1] ** -0.5
        want = _xla_attention(q, k, v, scale)
        got = sequence_parallel_attention(q, k, v, seq_mesh, method="ulysses")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_rejects_indivisible_heads(self, seq_mesh):
        q, k, v = _qkv(H=3, S=32)
        with pytest.raises(ValueError, match="divisible"):
            sequence_parallel_attention(q, k, v, seq_mesh, method="ulysses")


class TestLongSequence:
    def test_ring_eight_way(self, cpu_devices):
        mesh = build_mesh(cpu_devices, {AXIS_SEQ: 8})
        q, k, v = _qkv(B=1, S=128, H=2, D=8, seed=5)
        want = _xla_attention(q, k, v, q.shape[-1] ** -0.5)
        got = sequence_parallel_attention(q, k, v, mesh, method="ring")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )


class TestSequenceParallelContext:
    """The model-level integration: any model's attention routes over the seq mesh
    inside the ``sequence_parallel`` context, matching the unsharded forward."""

    def test_flux_forward_matches(self, seq_mesh):
        from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux
        from comfyui_parallelanything_tpu.ops.attention import sequence_parallel

        cfg = FluxConfig(
            in_channels=16, hidden_size=64, num_heads=4, depth=1,
            depth_single_blocks=1, context_in_dim=32, vec_in_dim=16,
            axes_dim=(4, 6, 6), guidance_embed=False, dtype=jnp.float32,
        )
        # 16 txt + 64 img tokens = 80 — not divisible by 4? use 16+16=32.
        model = build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=16)
        x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (1, 16, 32), jnp.float32)
        y = jax.random.normal(jax.random.key(3), (1, 16), jnp.float32)
        t = jnp.array([0.5])
        want = model(x, t, ctx, y=y)
        with sequence_parallel(seq_mesh, method="ring"):
            got = model.apply(model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_wan_forward_matches_ulysses(self, seq_mesh):
        from comfyui_parallelanything_tpu.models.wan import WanConfig, build_wan
        from comfyui_parallelanything_tpu.ops.attention import sequence_parallel

        cfg = WanConfig(
            in_channels=4, out_channels=4, hidden_size=48, ffn_dim=96,
            num_heads=4, depth=1, text_dim=32, freq_dim=32, dtype=jnp.float32,
        )
        model = build_wan(cfg, jax.random.key(0), sample_shape=(1, 2, 8, 8, 4), txt_len=8)
        x = jax.random.normal(jax.random.key(1), (1, 2, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (1, 8, 32), jnp.float32)
        t = jnp.array([0.5])
        want = model(x, t, ctx)
        with sequence_parallel(seq_mesh, method="ulysses"):
            got = model.apply(model.params, x, t, ctx)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_jit_cache_not_baked_across_contexts(self, seq_mesh):
        # A model first traced OUTSIDE the context must not silently reuse that
        # program INSIDE it (and vice versa): the ctx is part of the jit cache key.
        from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux
        from comfyui_parallelanything_tpu.ops.attention import sequence_parallel

        cfg = FluxConfig(
            in_channels=16, hidden_size=32, num_heads=4, depth=1,
            depth_single_blocks=1, context_in_dim=16, vec_in_dim=8,
            axes_dim=(4, 2, 2), guidance_embed=False, dtype=jnp.float32,
        )
        model = build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=16)
        x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (1, 16, 16), jnp.float32)
        t = jnp.array([0.5])
        outside = model(x, t, ctx)  # traced without seq routing
        with sequence_parallel(seq_mesh, method="ring"):
            inside = model(x, t, ctx)  # same shapes — must re-trace with routing
            assert len(inside.sharding.device_set) == 4 or np.allclose(
                np.asarray(inside), np.asarray(outside), atol=1e-4
            )
        np.testing.assert_allclose(
            np.asarray(inside), np.asarray(outside), rtol=1e-4, atol=1e-4
        )
        # Distinct compiled entries per context:
        assert len(model._jit_cache) == 2

    def test_context_restores(self, seq_mesh):
        from comfyui_parallelanything_tpu.ops.attention import (
            _SEQ_CTX,
            sequence_parallel,
        )

        with sequence_parallel(seq_mesh):
            assert getattr(_SEQ_CTX, "cfg", None) is not None
        assert getattr(_SEQ_CTX, "cfg", None) is None
