"""Sequence/context parallelism: ring + Ulysses attention must match single-device
attention over the full sequence (first-class here; absent in the reference —
SURVEY §5.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.ops.attention import _xla_attention
from comfyui_parallelanything_tpu.parallel.mesh import AXIS_SEQ, build_mesh
from comfyui_parallelanything_tpu.parallel.sequence import sequence_parallel_attention


@pytest.fixture(scope="module")
def seq_mesh(cpu_devices):
    return build_mesh(cpu_devices[:4], {AXIS_SEQ: 4})


def _qkv(B=2, S=32, H=4, D=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


class TestRingAttention:
    def test_matches_full_attention(self, seq_mesh):
        q, k, v = _qkv()
        scale = q.shape[-1] ** -0.5
        want = _xla_attention(q, k, v, scale)
        got = sequence_parallel_attention(q, k, v, seq_mesh, method="ring")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_output_sharded_on_seq(self, seq_mesh):
        q, k, v = _qkv()
        got = sequence_parallel_attention(q, k, v, seq_mesh, method="ring")
        assert len(got.sharding.device_set) == 4

    def test_rejects_indivisible_seq(self, seq_mesh):
        q, k, v = _qkv(S=30)
        with pytest.raises(ValueError, match="not divisible"):
            sequence_parallel_attention(q, k, v, seq_mesh, method="ring")


class TestUlyssesAttention:
    def test_matches_full_attention(self, seq_mesh):
        q, k, v = _qkv()
        scale = q.shape[-1] ** -0.5
        want = _xla_attention(q, k, v, scale)
        got = sequence_parallel_attention(q, k, v, seq_mesh, method="ulysses")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_rejects_indivisible_heads(self, seq_mesh):
        q, k, v = _qkv(H=3, S=32)
        with pytest.raises(ValueError, match="divisible"):
            sequence_parallel_attention(q, k, v, seq_mesh, method="ulysses")


class TestLongSequence:
    def test_ring_eight_way(self, cpu_devices):
        mesh = build_mesh(cpu_devices, {AXIS_SEQ: 8})
        q, k, v = _qkv(B=1, S=128, H=2, D=8, seed=5)
        want = _xla_attention(q, k, v, q.shape[-1] ** -0.5)
        got = sequence_parallel_attention(q, k, v, mesh, method="ring")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )
