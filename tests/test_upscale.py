"""ESRGAN-family upscaler (models/upscale.py): config sniffing, both public
checkpoint layouts round-tripped by inverse synthesis, tiled-vs-whole
equivalence, and the stock UpscaleModelLoader/ImageUpscaleWithModel shims in
a workflow."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from comfyui_parallelanything_tpu.models import (
    UpscaleConfig,
    build_upscaler,
    load_upscale_checkpoint,
    upscale_image,
)
from comfyui_parallelanything_tpu.models.upscale import (
    _normalize_esrgan_keys,
    convert_upscale_checkpoint,
    sniff_upscale_config,
)

TINY = UpscaleConfig(nf=8, nb=2, gc=4, scale=4, dtype=jnp.float32)


@pytest.fixture(scope="module")
def tiny_upscaler():
    return build_upscaler(TINY, jax.random.key(0))


def _modern_sd(cfg: UpscaleConfig, params) -> dict:
    """Inverse-synthesize the modern RRDBNet layout from our params."""
    sd: dict = {}

    def put(key, p):
        sd[f"{key}.weight"] = np.asarray(p["kernel"]).transpose(3, 2, 0, 1)
        if "bias" in p:
            sd[f"{key}.bias"] = np.asarray(p["bias"])

    for k in ("conv_first", "conv_body", "conv_up1", "conv_up2",
              "conv_hr", "conv_last"):
        put(k, params[k])
    for i in range(cfg.nb):
        for k in range(1, 4):
            for j in range(1, 6):
                put(f"body.{i}.rdb{k}.conv{j}",
                    params[f"body_{i}"][f"rdb{k}"][f"conv{j}"])
    return sd


def _legacy_sd(cfg: UpscaleConfig, params) -> dict:
    """The old ESRGAN sequential naming for the same weights."""
    modern = _modern_sd(cfg, params)
    import re

    out = {}
    head = {"conv_first": "model.0", "conv_up1": "model.3",
            "conv_up2": "model.6", "conv_hr": "model.8",
            "conv_last": "model.10"}
    for k, v in modern.items():
        m = re.match(r"body\.(\d+)\.rdb(\d)\.conv(\d)\.(weight|bias)", k)
        if m:
            i, r, c, wb = m.groups()
            out[f"model.1.sub.{i}.RDB{r}.conv{c}.0.{wb}"] = v
            continue
        if k.startswith("conv_body."):
            out[f"model.1.sub.{cfg.nb}.{k.split('.', 1)[1]}"] = v
            continue
        stem, wb = k.rsplit(".", 1)
        out[f"{head[stem]}.{wb}"] = v
    return out


class TestConversion:
    def test_modern_layout_round_trip(self, tiny_upscaler):
        sd = _modern_sd(TINY, tiny_upscaler.params)
        cfg = sniff_upscale_config(sd)
        assert (cfg.nf, cfg.nb, cfg.gc, cfg.scale) == (8, 2, 4, 4)
        params, _ = convert_upscale_checkpoint(sd)
        x = jax.random.uniform(jax.random.key(1), (1, 12, 10, 3))
        np.testing.assert_allclose(
            np.asarray(build_upscaler(cfg, params=params)(x)),
            np.asarray(tiny_upscaler(x)), rtol=1e-6, atol=1e-6,
        )

    def test_legacy_layout_converts_identically(self, tiny_upscaler):
        legacy = _legacy_sd(TINY, tiny_upscaler.params)
        norm = _normalize_esrgan_keys(legacy)
        assert sorted(norm) == sorted(_modern_sd(TINY, tiny_upscaler.params))
        params, cfg = convert_upscale_checkpoint(legacy)
        x = jax.random.uniform(jax.random.key(1), (1, 12, 10, 3))
        np.testing.assert_allclose(
            np.asarray(build_upscaler(cfg, params=params)(x)),
            np.asarray(tiny_upscaler(x)), rtol=1e-6, atol=1e-6,
        )

    def test_pixel_unshuffle_matches_torch_channel_order(self):
        # RealESRGAN x2/x1 conv_first weights were trained against
        # torch.pixel_unshuffle's C-major depth order — pin ours to it.
        torch = pytest.importorskip("torch")

        from comfyui_parallelanything_tpu.models.upscale import _pixel_unshuffle

        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 8, 6, 3)).astype(np.float32)
        ours = np.asarray(_pixel_unshuffle(jnp.asarray(x), 2))
        want = (
            torch.nn.functional.pixel_unshuffle(
                torch.from_numpy(x).permute(0, 3, 1, 2), 2
            ).permute(0, 2, 3, 1).numpy()
        )
        np.testing.assert_allclose(ours, want, rtol=0, atol=0)

    def test_legacy_non_x4_layout_rejected_clearly(self, tiny_upscaler):
        legacy = _legacy_sd(TINY, tiny_upscaler.params)
        # Simulate an x2 legacy head (different sequential indices).
        legacy["model.4.weight"] = legacy.pop("model.10.weight")
        legacy["model.4.bias"] = legacy.pop("model.10.bias")
        with pytest.raises(ValueError, match="x4 sequential layout"):
            convert_upscale_checkpoint(legacy)

    def test_scale2_pixel_unshuffle_shapes(self):
        cfg = UpscaleConfig(nf=8, nb=1, gc=4, scale=2, in_channels=3,
                            dtype=jnp.float32)
        model = build_upscaler(cfg, jax.random.key(2))
        out = model(jnp.zeros((1, 16, 12, 3)))
        assert out.shape == (1, 32, 24, 3)
        # Sniffing reads the shuffle factor off conv_first's input width (12).
        sd = {  # minimal keys the sniffer touches
            "conv_first.weight": np.zeros((8, 12, 3, 3), np.float32),
            "conv_last.weight": np.zeros((3, 8, 3, 3), np.float32),
            "body.0.rdb1.conv1.weight": np.zeros((4, 8, 3, 3), np.float32),
        }
        got = sniff_upscale_config(sd)
        assert got.scale == 2 and got.in_channels == 3

    def test_sniff_rejects_unrecognized_input_width(self):
        # A 4-channel x4 variant (conv_first in width 8 after unshuffle-2)
        # must raise descriptively, not sniff as in_channels=1 with a wrong
        # shuffle factor and build a silently wrong topology.
        sd = {
            "conv_first.weight": np.zeros((8, 8, 3, 3), np.float32),
            "conv_last.weight": np.zeros((3, 8, 3, 3), np.float32),
            "body.0.rdb1.conv1.weight": np.zeros((4, 8, 3, 3), np.float32),
        }
        with pytest.raises(ValueError, match="conv_first input width 8"):
            sniff_upscale_config(sd)


class TestUpscaleImage:
    def test_output_scale_and_range(self, tiny_upscaler):
        x = jax.random.uniform(jax.random.key(3), (2, 12, 10, 3))
        out = upscale_image(tiny_upscaler, x)
        assert out.shape == (2, 48, 40, 3)
        arr = np.asarray(out)
        assert arr.min() >= 0.0 and arr.max() <= 1.0

    def test_tiled_approximates_whole(self, tiny_upscaler):
        # Tiling is the host's approximation too: tile borders see the conv
        # zero-padding instead of real context, so seams differ slightly —
        # the blend must keep the output CLOSE in aggregate and the weight
        # normalization must leave no holes or hot spots.
        x = jax.random.uniform(jax.random.key(4), (1, 40, 36, 3))
        whole = np.asarray(upscale_image(tiny_upscaler, x, tile=512))
        tiled = np.asarray(upscale_image(tiny_upscaler, x, tile=32, overlap=8))
        assert tiled.shape == whole.shape
        assert np.isfinite(tiled).all()
        assert np.mean(np.abs(tiled - whole)) < 0.02
        # Interior far from any seam is exact (receptive field inside tile).
        np.testing.assert_allclose(tiled[:, 64:80, 60:76], whole[:, 64:80, 60:76],
                                   rtol=1e-4, atol=1e-4)


class TestStockShims:
    def test_stock_upscale_workflow_runs(self, tmp_path, monkeypatch):
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.host import run_workflow

        up = build_upscaler(TINY, jax.random.key(0))
        up_dir = tmp_path / "models" / "upscale_models"
        up_dir.mkdir(parents=True)
        save_file(
            {k: np.ascontiguousarray(v)
             for k, v in _modern_sd(TINY, up.params).items()},
            str(up_dir / "tiny_x4.safetensors"),
        )
        monkeypatch.setenv("PA_MODELS_DIR", str(tmp_path / "models"))

        from PIL import Image

        in_dir = tmp_path / "input"
        in_dir.mkdir()
        Image.fromarray(
            (np.random.default_rng(0).uniform(size=(12, 12, 3)) * 255)
            .astype(np.uint8)
        ).save(in_dir / "src.png")
        monkeypatch.setenv("PA_INPUT_DIR", str(in_dir))

        out = run_workflow({
            "1": {"class_type": "LoadImage", "inputs": {"image": "src.png"}},
            "2": {"class_type": "UpscaleModelLoader",
                  "inputs": {"model_name": "tiny_x4.safetensors"}},
            "3": {"class_type": "ImageUpscaleWithModel",
                  "inputs": {"upscale_model": ["2", 0], "image": ["1", 0]}},
        })
        img = np.asarray(out["3"][0])
        assert img.shape[1:3] == (48, 48)
        assert np.isfinite(img).all()
