"""Native orbax save/restore round-trip (SURVEY §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

from comfyui_parallelanything_tpu.models.checkpoint import load_params, save_params


class TestSaveRestore:
    def test_roundtrip(self, tmp_path):
        params = {
            "layer": {"kernel": jnp.arange(12, dtype=jnp.float32).reshape(3, 4)},
            "bias": jnp.ones((4,), jnp.float32),
        }
        path = tmp_path / "ckpt"
        save_params(path, params)
        restored = load_params(path)
        np.testing.assert_array_equal(
            np.asarray(restored["layer"]["kernel"]), np.asarray(params["layer"]["kernel"])
        )
        np.testing.assert_array_equal(
            np.asarray(restored["bias"]), np.asarray(params["bias"])
        )

    def test_restore_into_target_structure(self, tmp_path):
        params = {"w": jnp.full((8, 8), 3.0)}
        path = tmp_path / "ckpt2"
        save_params(path, params)
        like = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params
        )
        restored = load_params(path, like)
        assert restored["w"].shape == (8, 8)
        assert float(restored["w"][0, 0]) == 3.0
