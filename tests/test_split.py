"""Unit tests for the pure split arithmetic (SURVEY §4: the logic the reference never
tested — weight normalization 1019-1027, split sizes 1317-1322 & 737-766, kwargs
splitting 1252-1267, result concat 1269-1285)."""

import numpy as np
import pytest

from comfyui_parallelanything_tpu.parallel.split import (
    batch_size_of,
    blend_memory_weights,
    blend_speed_weights,
    block_ranges,
    concat_results,
    largest_remainder_split,
    normalize_weights,
    split_kwargs,
    split_tree,
    weighted_batch_split,
)


class TestNormalizeWeights:
    def test_basic(self):
        assert normalize_weights([50, 50]) == (0.5, 0.5)
        w = normalize_weights([40, 40, 15, 5])  # README's 4-GPU example split
        assert w is not None
        assert abs(sum(w) - 1.0) < 1e-12
        assert w[0] == pytest.approx(0.4)

    def test_sum_zero_aborts(self):
        # Reference aborts the whole setup when sum <= 0 (1019-1027).
        assert normalize_weights([0, 0]) is None
        assert normalize_weights([]) is None
        assert normalize_weights([-5, 5]) is None

    def test_unnormalized_percentages(self):
        w = normalize_weights([1, 3])
        assert w == (0.25, 0.75)


class TestLargestRemainderSplit:
    def test_sums_exactly(self):
        for batch in [1, 2, 7, 16, 21, 100]:
            for weights in [(0.5, 0.5), (0.4, 0.4, 0.15, 0.05), (0.9, 0.05, 0.05)]:
                sizes = largest_remainder_split(batch, weights)
                assert sum(sizes) == batch
                assert all(s >= 0 for s in sizes)

    def test_many_small_weights_no_overflow(self):
        # The reference's max(1, int(b*w)) overflows here: 8 devices at 12.5% on
        # batch 4 would produce 8 chunks of 1 = 8 > 4. We must sum to 4 exactly.
        sizes = largest_remainder_split(4, [1 / 8] * 8)
        assert sum(sizes) == 4
        assert sorted(sizes) == [0, 0, 0, 0, 1, 1, 1, 1]

    def test_zero_total(self):
        assert largest_remainder_split(0, [0.5, 0.5]) == (0, 0)

    def test_even(self):
        assert largest_remainder_split(16, [0.5, 0.5]) == (8, 8)
        assert largest_remainder_split(21, [0.5, 0.5]) == (11, 10)  # tie → earlier link

    def test_degenerate_weights_even_split(self):
        assert largest_remainder_split(8, [0.0, 0.0]) == (4, 4)

    def test_weighted_batch_split_alias(self):
        assert weighted_batch_split(10, [0.7, 0.3]) == (7, 3)


class TestBlendMemoryWeights:
    def test_blend_formula(self):
        # Parity: 0.7*user + 0.3*mem_share, renormalized (753-762).
        w = blend_memory_weights([0.5, 0.5], [100, 300])
        expected = np.array([0.7 * 0.5 + 0.3 * 0.25, 0.7 * 0.5 + 0.3 * 0.75])
        expected /= expected.sum()
        np.testing.assert_allclose(w, expected, rtol=1e-12)

    def test_no_memory_info_falls_back_to_user(self):
        # CPU-only chain: free bytes all 0 → pure user weights (738-739).
        assert blend_memory_weights([0.6, 0.4], [0, 0]) == (0.6, 0.4)

    def test_length_mismatch(self):
        with pytest.raises(ValueError):
            blend_memory_weights([0.5], [1, 2])


class TestBlendSpeedWeights:
    def test_blend_formula(self):
        # The memory blend's twin: 0.7*user + 0.3*inverse-time share.
        w = blend_speed_weights([0.5, 0.5], [1.0, 3.0])
        inv = np.array([1.0, 1.0 / 3.0])
        expected = 0.7 * np.array([0.5, 0.5]) + 0.3 * inv / inv.sum()
        expected /= expected.sum()
        np.testing.assert_allclose(w, expected, rtol=1e-12)
        assert w[0] > 0.5 > w[1]  # the faster device gains share

    def test_fast_tpu_slow_cpu_spec_pair_shifts_toward_speed(self):
        # Acceptance (ROADMAP speed-aware hybrid blending): a v6-vs-CPU
        # platform-spec pair moves a 50/50 user split decisively toward the
        # TPU — the split reflects SPEED, not VRAM.
        from comfyui_parallelanything_tpu.utils import roofline

        t_tpu = roofline.nominal_step_time_s("TPU v6 lite", "tpu")
        t_cpu = roofline.nominal_step_time_s("", "cpu")
        assert t_tpu < t_cpu / 10  # the specs really are an order apart
        w = blend_speed_weights([0.5, 0.5], [t_tpu, t_cpu])
        # alpha=0.7 bounds the shift at 0.7*user + 0.3*1: the TPU lands
        # near the 0.65 cap, the CPU near the 0.35 floor.
        assert w[0] > 0.6 > 0.4 > w[1]
        # VRAM-only blending cannot see this: equal free bytes leave 50/50.
        assert blend_memory_weights([0.5, 0.5], [100, 100]) == \
            pytest.approx((0.5, 0.5))

    def test_homogeneous_chain_is_a_no_op(self):
        # Equal specs → equal times → user weights untouched (even SPMD
        # sharding and explicit user splits on same-platform meshes are
        # never perturbed).
        assert blend_speed_weights([0.6, 0.4], [2.0, 2.0]) == (0.6, 0.4)

    def test_unknown_spec_falls_back_to_user(self):
        assert blend_speed_weights([0.6, 0.4], [0.0, 1.0]) == (0.6, 0.4)

    def test_length_mismatch(self):
        with pytest.raises(ValueError):
            blend_speed_weights([0.5], [1.0, 2.0])


class TestBlockRanges:
    def test_contiguous_cover(self):
        ranges = block_ranges(19, [0.4, 0.4, 0.2])
        assert ranges[0][0] == 0
        assert ranges[-1][1] == 19
        for (a, b), (c, d) in zip(ranges, ranges[1:]):
            assert b == c
        assert sum(b - a for a, b in ranges) == 19

    def test_proportionality(self):
        ranges = block_ranges(10, [0.5, 0.5])
        assert ranges == ((0, 5), (5, 10))

    def test_zero_weight_stage_empty(self):
        ranges = block_ranges(4, [1.0, 0.0])
        assert ranges == ((0, 4), (4, 4))


class TestBatchSizeOf:
    def test_array(self):
        assert batch_size_of(np.zeros((5, 3))) == 5

    def test_container(self):
        # First tensor inside a list/tuple (1213-1218).
        assert batch_size_of(["meta", np.zeros((7, 2))]) == 7

    def test_scalar_fallback(self):
        assert batch_size_of(3.0) == 1
        assert batch_size_of(np.float32(1.0)) == 1


class TestSplitTree:
    def test_array_split(self):
        chunks = split_tree(np.arange(10).reshape(10, 1), [7, 3])
        assert chunks[0].shape == (7, 1)
        assert chunks[1].shape == (3, 1)
        np.testing.assert_array_equal(np.concatenate(chunks), np.arange(10).reshape(10, 1))

    def test_container_elementwise_and_replication(self):
        x = [np.zeros((4, 2)), "label"]
        chunks = split_tree(x, [2, 2])
        assert chunks[0][0].shape == (2, 2)
        assert chunks[0][1] == "label" and chunks[1][1] == "label"

    def test_non_matching_array_replicated(self):
        # An array whose dim0 != sum(sizes) is treated as non-batch and replicated.
        x = np.zeros((3, 2))
        chunks = split_tree(x, [2, 2])
        assert chunks[0].shape == (3, 2) and chunks[1].shape == (3, 2)


class TestSplitKwargs:
    def test_split_iff_dim0_matches_batch(self):
        # Parity rule (1252-1267): split only arrays with dim0 == batch.
        kwargs = {
            "y": np.zeros((8, 4)),       # split
            "guidance": np.zeros((3,)),  # broadcast (dim0 != batch)
            "flag": True,                # broadcast (non-array)
        }
        out = split_kwargs(kwargs, batch=8, sizes=[5, 3])
        assert out[0]["y"].shape == (5, 4)
        assert out[1]["y"].shape == (3, 4)
        assert out[0]["guidance"].shape == (3,)
        assert out[1]["flag"] is True


class TestConcatResults:
    def test_arrays(self):
        out = concat_results([np.ones((2, 3)), np.zeros((1, 3))])
        assert out.shape == (3, 3)

    def test_tuple_outputs_elementwise(self):
        # Parity: tuple-of-tensors outputs concat element-wise (1276-1282).
        a = (np.ones((2, 1)), np.ones((2, 2)))
        b = (np.zeros((1, 1)), np.zeros((1, 2)))
        out = concat_results([a, b])
        assert isinstance(out, tuple)
        assert out[0].shape == (3, 1) and out[1].shape == (3, 2)

    def test_non_array_passthrough_from_chunk0(self):
        assert concat_results(["first", "second"]) == "first"

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            concat_results([])
