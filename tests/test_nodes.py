"""Node-protocol layer: the reference's L4 surface re-exposed (SURVEY §2a).

Covers the chain-building semantics the reference leaves untested (SURVEY §4):
copy-then-append, pct<=0 drops, wire-format keys, and orchestrator routing through
the node entry point."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import nodes
from comfyui_parallelanything_tpu.models import build_unet, sd15_config
from comfyui_parallelanything_tpu.nodes import (
    NODE_CLASS_MAPPINGS,
    NODE_DISPLAY_NAME_MAPPINGS,
    ParallelAnything,
    ParallelDevice,
    ParallelDeviceList,
    chain_from_wire,
    chain_to_wire,
)
from comfyui_parallelanything_tpu.parallel.chain import DeviceChain
from comfyui_parallelanything_tpu.parallel.orchestrator import ParallelModel


class TestNodeProtocol:
    def test_mappings_complete(self):
        # Reference-parity nodes (SURVEY §2a) must all be present; host-layer
        # additions (TPU* nodes, covered in test_host_nodes.py) ride alongside.
        assert {
            "ParallelAnything",
            "ParallelAnythingAdvanced",
            "ParallelDevice",
            "ParallelDeviceList",
        } <= set(NODE_CLASS_MAPPINGS)
        assert set(NODE_DISPLAY_NAME_MAPPINGS) == set(NODE_CLASS_MAPPINGS)

    def test_declarative_contract(self):
        # Every node carries the full declarative protocol the host introspects
        # (INPUT_TYPES/RETURN_TYPES/FUNCTION/CATEGORY, reference 788-817, 867-870,
        # 912-915).
        for cls in NODE_CLASS_MAPPINGS.values():
            assert callable(cls.INPUT_TYPES)
            assert isinstance(cls.RETURN_TYPES, tuple)
            assert isinstance(cls.FUNCTION, str)
            assert hasattr(cls, cls.FUNCTION)
            assert cls.CATEGORY

    def test_seed_key_accepts_full_stock_64bit_range(self):
        # Stock seed widgets randomize over [0, 2**64); jax.random.key takes
        # signed int64 (ADVICE r3). seed_key must fold, deterministically.
        import jax

        from comfyui_parallelanything_tpu.nodes import SEED_MAX, seed_key

        assert SEED_MAX == 2**64 - 1
        for s in (0, 7, 2**63 - 1, 2**63, SEED_MAX):
            seed_key(s)  # must not raise
        same = jax.random.key_data(seed_key(2**63 + 5))
        folded = jax.random.key_data(jax.random.key(5))
        assert (same == folded).all()

    def test_device_dropdown_always_has_cpu(self):
        devs = ParallelDevice.get_available_devices()
        assert "cpu" in devs
        inputs = ParallelDevice.INPUT_TYPES()
        assert inputs["required"]["device_id"][0] == devs


class TestParallelDevice:
    def test_append_and_copy(self):
        node = ParallelDevice()
        (chain1,) = node.add_device("cpu", 60.0)
        (chain2,) = node.add_device("cpu:1", 40.0, previous_devices=chain1)
        # Upstream list untouched (parity: copy at 821-824).
        assert len(chain1) == 1 and len(chain2) == 2
        assert chain2[0]["device"] == "cpu"
        assert chain2[1] == {"device": "cpu:1", "percentage": 40.0, "weight": 0.4}


class TestParallelDeviceList:
    def test_zero_pct_slots_dropped(self):
        node = ParallelDeviceList()
        (chain,) = node.create_list(
            device_1="cpu", percentage_1=70.0,
            device_2="cpu:1", percentage_2=30.0,
            device_3="cpu:2", percentage_3=0.0,
            device_4="cpu:3", percentage_4=-5.0,
        )
        assert [e["device"] for e in chain] == ["cpu", "cpu:1"]

    def test_four_slots_declared(self):
        req = ParallelDeviceList.INPUT_TYPES()["required"]
        assert {f"device_{i}" for i in range(1, 5)} <= set(req)
        assert {f"percentage_{i}" for i in range(1, 5)} <= set(req)


class TestWireFormat:
    def test_roundtrip(self):
        chain = DeviceChain.from_pairs([("cpu", 70.0), ("cpu:1", 30.0)])
        wire = chain_to_wire(chain)
        assert wire[0]["weight"] == 0.7  # dead-data key kept for wire parity
        back = chain_from_wire(wire)
        assert back.devices == chain.devices
        assert back.percentages == chain.percentages

    def test_from_wire_drops_nonpositive(self):
        back = chain_from_wire(
            [{"device": "cpu", "percentage": 0.0}, {"device": "cpu:1", "percentage": 5.0}]
        )
        assert back.devices == ("cpu:1",)


class TestParallelAnythingNode:
    def test_setup_wraps_model(self):
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        node = ParallelAnything()
        dev_node = ParallelDevice()
        (chain,) = dev_node.add_device("cpu", 50.0)
        (chain,) = dev_node.add_device("cpu:1", 50.0, previous_devices=chain)
        (wrapped,) = node.setup_parallel(model, chain)
        assert isinstance(wrapped, ParallelModel)
        assert wrapped.n_devices == 2

        x = jax.random.normal(jax.random.key(1), (4, 16, 16, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (4, 12, 64), jnp.float32)
        out = wrapped(x, jnp.ones((4,)), ctx)
        assert out.shape == (4, 16, 16, 4)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_ksampler_compile_loop_widget(self):
        # The node-level opt-in for whole-loop compilation must produce the
        # same latent as the eager path.
        from comfyui_parallelanything_tpu.nodes import TPUEmptyLatent, TPUKSampler

        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        # Guard against a vacuous eager-vs-eager comparison: the model must
        # actually be single-program traceable for the compiled path to run.
        from comfyui_parallelanything_tpu.sampling.compiled import trace_spec_of

        assert trace_spec_of(model) is not None
        (latent,) = TPUEmptyLatent().generate(width=64, height=64, batch_size=2)
        cond = {"context": jax.random.normal(jax.random.key(3), (1, 6, 64))}
        node = TPUKSampler()
        outs = {}
        for flag in (False, True):
            (out,) = node.sample(
                model, cond, latent, seed=5, steps=2, cfg=1.0,
                sampler_name="euler", scheduler="karras", compile_loop=flag,
            )
            outs[flag] = np.asarray(out["samples"])
        np.testing.assert_allclose(outs[False], outs[True], rtol=2e-4, atol=2e-5)

    def test_advanced_node_wires_tp(self):
        from comfyui_parallelanything_tpu.nodes import ParallelAnythingAdvanced

        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        node = ParallelAnythingAdvanced()
        chain = [
            {"device": f"cpu:{i}", "percentage": 25.0, "weight": 0.25} for i in range(4)
        ]
        # Invoke through the node protocol (FUNCTION attr), exactly as the host
        # graph executor does — the advanced widgets flow through **config_extra.
        (wrapped,) = getattr(node, node.FUNCTION)(model, chain, tensor_parallel=2)
        assert isinstance(wrapped, ParallelModel)
        assert wrapped._groups[0].mesh.shape == {"data": 2, "model": 2}

    def test_advanced_node_microbatch_and_reactivate_widgets(self):
        from comfyui_parallelanything_tpu.nodes import ParallelAnythingAdvanced

        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        node = ParallelAnythingAdvanced()
        spec = node.INPUT_TYPES()
        assert "pipeline_microbatches" in spec["optional"]
        assert "reactivate_after" in spec["optional"]
        chain = [
            {"device": f"cpu:{i}", "percentage": 50.0, "weight": 0.5}
            for i in range(2)
        ]
        (wrapped,) = getattr(node, node.FUNCTION)(
            model, chain, pipeline_microbatches=2, reactivate_after=0
        )
        assert wrapped.config.pipeline_microbatches == 2
        assert wrapped.config.reactivate_after is None  # 0 widget -> off
        (wrapped2,) = getattr(node, node.FUNCTION)(
            model, chain, reactivate_after=5
        )
        assert wrapped2.config.reactivate_after == 5

    def test_save_load_image_roundtrip(self, tmp_path):
        # The terminal/entry nodes of exported workflows: save a batch as
        # numbered PNGs, load one back within 8-bit quantization error.
        from comfyui_parallelanything_tpu.nodes import TPULoadImage, TPUSaveImage

        imgs = jnp.asarray(
            np.random.default_rng(0).uniform(0, 1, size=(2, 16, 16, 3)),
            jnp.float32,
        )
        (paths,) = TPUSaveImage().save(
            imgs, filename_prefix="t", output_dir=str(tmp_path)
        )
        assert len(paths) == 2 and all(p.endswith(".png") for p in paths)
        # Re-run continues numbering instead of overwriting.
        (paths2,) = TPUSaveImage().save(
            imgs, filename_prefix="t", output_dir=str(tmp_path)
        )
        assert set(paths).isdisjoint(paths2)
        image, mask = TPULoadImage().load(paths[0])
        assert image.shape == (1, 16, 16, 3)
        np.testing.assert_allclose(
            np.asarray(image[0]), np.asarray(imgs[0]), atol=1.0 / 255.0 + 1e-6
        )
        assert mask.shape == (1, 16, 16) and float(mask.max()) == 0.0

    def test_save_image_counter_survives_gaps(self, tmp_path):
        # Deleting an early file must not shift numbering onto survivors.
        import os

        from comfyui_parallelanything_tpu.nodes import TPUSaveImage

        img = jnp.ones((1, 4, 4, 3), jnp.float32)
        (p1,) = TPUSaveImage().save(img, "t", str(tmp_path))[0]
        ((p2,),) = TPUSaveImage().save(img, "t", str(tmp_path))
        os.remove(p1)  # leave a gap at index 0
        ((p3,),) = TPUSaveImage().save(img, "t", str(tmp_path))
        assert p3 != p2 and os.path.exists(p2)  # survivor untouched

    def test_save_image_subfolder_prefix(self, tmp_path):
        # Host SaveImage semantics: the prefix may carry a subfolder.
        import os

        from comfyui_parallelanything_tpu.nodes import TPUSaveImage

        img = jnp.ones((1, 4, 4, 3), jnp.float32)
        ((p1,),) = TPUSaveImage().save(img, "run1/img", str(tmp_path))
        ((p2,),) = TPUSaveImage().save(img, "run1/img", str(tmp_path))
        assert os.path.dirname(p1) == str(tmp_path / "run1")
        assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)

    def test_save_video_frames(self, tmp_path):
        # WAN decode emits (B, F, H, W, 3) video floats — every frame saves as
        # its own numbered PNG, in clip/frame order.
        from comfyui_parallelanything_tpu.nodes import TPUSaveImage

        vid = jnp.ones((1, 3, 8, 8, 3)) * 0.5
        (paths,) = TPUSaveImage().save(vid, "v", str(tmp_path))
        assert len(paths) == 3
        import os

        assert all(os.path.exists(p) for p in paths)

    def test_save_image_embeds_metadata(self, tmp_path):
        from PIL import Image

        from comfyui_parallelanything_tpu.nodes import TPUSaveImage

        img = jnp.ones((1, 4, 4, 3), jnp.float32)
        ((p,),) = TPUSaveImage().save(
            img, "m", str(tmp_path), metadata="prompt: a lighthouse"
        )
        assert Image.open(p).text["parameters"] == "prompt: a lighthouse"

    def test_image_scale(self):
        from comfyui_parallelanything_tpu.nodes import TPUImageScale

        img = jnp.linspace(0, 1, 2 * 8 * 8 * 3).reshape(2, 8, 8, 3)
        (out,) = TPUImageScale().scale(img, width=16, height=12)
        assert out.shape == (2, 12, 16, 3)
        assert float(out.min()) >= 0.0 and float(out.max()) <= 1.0
        # Nearest on an integer upscale preserves exact values.
        (nn,) = TPUImageScale().scale(img, width=16, height=16, method="nearest")
        np.testing.assert_array_equal(np.asarray(nn[:, ::2, ::2]), np.asarray(img))
        with pytest.raises(ValueError, match="method"):
            TPUImageScale().scale(img, width=8, height=8, method="cubic")

    def test_save_image_rejects_escaping_prefix(self, tmp_path):
        from comfyui_parallelanything_tpu.nodes import TPUSaveImage

        img = jnp.ones((1, 4, 4, 3), jnp.float32)
        for bad in ("../esc/img", "/tmp/abs/img"):
            with pytest.raises(ValueError, match="outside"):
                TPUSaveImage().save(img, bad, str(tmp_path))

    def test_load_image_alpha_becomes_mask(self, tmp_path):
        from PIL import Image

        from comfyui_parallelanything_tpu.nodes import TPULoadImage

        rgba = np.zeros((8, 8, 4), np.uint8)
        rgba[..., :3] = 128
        rgba[..., 3] = 255
        rgba[:4, :, 3] = 0  # top half transparent -> mask 1
        p = tmp_path / "a.png"
        Image.fromarray(rgba, "RGBA").save(p)
        image, mask = TPULoadImage().load(str(p))
        assert float(mask[0, :4].min()) == 1.0
        assert float(mask[0, 4:].max()) == 0.0

    def test_unusable_chain_returns_model_unchanged(self):
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        node = ParallelAnything()
        (result,) = node.setup_parallel(model, [])
        assert result is model
