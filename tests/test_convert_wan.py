"""WAN converter: official-layout round-trip + same-program forward substitution.

Strategy mirrors test_convert.py: synthesize an official-layout state dict by
inverting the converter's transforms from freshly-initialized params, convert it
back, require bitwise identity, and run both param sets through one jitted
forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models.convert_wan import convert_wan_checkpoint
from comfyui_parallelanything_tpu.models.loader import load_wan_checkpoint
from comfyui_parallelanything_tpu.models.wan import WanConfig, build_wan

TINY = WanConfig(
    in_channels=4,
    out_channels=4,
    hidden_size=48,
    ffn_dim=96,
    num_heads=4,
    depth=2,
    text_dim=32,
    freq_dim=16,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny_wan():
    return build_wan(TINY, jax.random.key(0), sample_shape=(1, 2, 4, 4, 4), txt_len=6)


def _inv_dense(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["kernel"]).T
    if "bias" in p:
        sd[f"{key}.bias"] = np.asarray(p["bias"])


def _official_layout_sd(cfg: WanConfig, params) -> dict:
    sd: dict = {}
    pt, ph, pw = cfg.patch_size
    k = np.asarray(params["patch_embedding"]["kernel"])  # (pt·ph·pw·C, O)
    sd["patch_embedding.weight"] = (
        k.reshape(pt, ph, pw, cfg.in_channels, -1).transpose(4, 3, 0, 1, 2)
    )
    sd["patch_embedding.bias"] = np.asarray(params["patch_embedding"]["bias"])
    _inv_dense(params["text_in"], "text_embedding.0", sd)
    _inv_dense(params["text_hidden"], "text_embedding.2", sd)
    _inv_dense(params["time_in"], "time_embedding.0", sd)
    _inv_dense(params["time_hidden"], "time_embedding.2", sd)
    _inv_dense(params["time_projection"], "time_projection.1", sd)
    _inv_dense(params["head_proj"], "head.head", sd)
    sd["head.modulation"] = np.asarray(params["head_modulation"]["bias"])
    for i in range(cfg.depth):
        blk = params[f"blocks_{i}"]
        t = f"blocks.{i}"
        for ours, theirs in (("self", "self_attn"), ("cross", "cross_attn")):
            for proj in "qkvo":
                _inv_dense(blk[f"{ours}_{proj}"], f"{t}.{theirs}.{proj}", sd)
            for nrm in "qk":
                sd[f"{t}.{theirs}.norm_{nrm}.weight"] = np.asarray(
                    blk[f"{ours}_{nrm}_norm"]["scale"]
                )
        sd[f"{t}.norm3.weight"] = np.asarray(blk["norm3"]["scale"])
        sd[f"{t}.norm3.bias"] = np.asarray(blk["norm3"]["bias"])
        _inv_dense(blk["ffn_in"], f"{t}.ffn.0", sd)
        _inv_dense(blk["ffn_out"], f"{t}.ffn.2", sd)
        sd[f"{t}.modulation"] = np.asarray(blk["modulation"])
    return sd


class TestWanRoundTrip:
    def test_bitwise_roundtrip(self, tiny_wan):
        sd = _official_layout_sd(TINY, tiny_wan.params)
        got = convert_wan_checkpoint(sd, TINY)
        fg = dict(flatten_tree(got))
        fw = dict(flatten_tree(tiny_wan.params))
        assert sorted(fg) == sorted(fw)
        for k in fw:
            np.testing.assert_array_equal(fg[k], fw[k], err_msg=str(k))

    def test_converted_params_run_forward(self, tiny_wan):
        sd = _official_layout_sd(TINY, tiny_wan.params)
        params = convert_wan_checkpoint(sd, TINY)
        x = jax.random.normal(jax.random.key(1), (1, 2, 4, 4, 4), jnp.float32)
        t = jnp.array([0.5])
        ctx = jax.random.normal(jax.random.key(2), (1, 6, 32), jnp.float32)
        f = jax.jit(tiny_wan.apply)
        want = f(tiny_wan.params, x, t, ctx)
        got = f(params, x, t, ctx)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_loader_default_path(self, tiny_wan):
        sd = _official_layout_sd(TINY, tiny_wan.params)
        model = load_wan_checkpoint(sd, TINY)
        x = jnp.zeros((1, 2, 4, 4, 4), jnp.float32)
        ctx = jnp.zeros((1, 6, 32), jnp.float32)
        out = model.apply(model.params, x, jnp.array([0.1]), ctx)
        assert out.shape == (1, 2, 4, 4, 4)

    def test_i2v_branch_keys_ignored(self, tiny_wan):
        sd = _official_layout_sd(TINY, tiny_wan.params)
        sd["img_emb.proj.0.weight"] = np.zeros((8, 8), np.float32)
        got = convert_wan_checkpoint(sd, TINY)  # no error, branch ignored
        assert "img_emb" not in got
