"""WAN converter: official-layout round-trip + same-program forward substitution.

Strategy mirrors test_convert.py: synthesize an official-layout state dict by
inverting the converter's transforms from freshly-initialized params, convert it
back, require bitwise identity, and run both param sets through one jitted
forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models.convert_wan import convert_wan_checkpoint
from comfyui_parallelanything_tpu.models.loader import load_wan_checkpoint
from comfyui_parallelanything_tpu.models.wan import WanConfig, build_wan

TINY = WanConfig(
    in_channels=4,
    out_channels=4,
    hidden_size=48,
    ffn_dim=96,
    num_heads=4,
    depth=2,
    text_dim=32,
    freq_dim=16,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny_wan():
    return build_wan(TINY, jax.random.key(0), sample_shape=(1, 2, 4, 4, 4), txt_len=6)


def _inv_dense(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["kernel"]).T
    if "bias" in p:
        sd[f"{key}.bias"] = np.asarray(p["bias"])


def _inv_ln(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["scale"])
    sd[f"{key}.bias"] = np.asarray(p["bias"])


def _official_layout_sd(cfg: WanConfig, params) -> dict:
    sd: dict = {}
    if cfg.img_dim is not None:
        _inv_ln(params["img_ln_in"], "img_emb.proj.0", sd)
        _inv_dense(params["img_in"], "img_emb.proj.1", sd)
        _inv_dense(params["img_hidden"], "img_emb.proj.3", sd)
        _inv_ln(params["img_ln_out"], "img_emb.proj.4", sd)
    pt, ph, pw = cfg.patch_size
    k = np.asarray(params["patch_embedding"]["kernel"])  # (pt·ph·pw·C, O)
    sd["patch_embedding.weight"] = (
        k.reshape(pt, ph, pw, cfg.in_channels, -1).transpose(4, 3, 0, 1, 2)
    )
    sd["patch_embedding.bias"] = np.asarray(params["patch_embedding"]["bias"])
    _inv_dense(params["text_in"], "text_embedding.0", sd)
    _inv_dense(params["text_hidden"], "text_embedding.2", sd)
    _inv_dense(params["time_in"], "time_embedding.0", sd)
    _inv_dense(params["time_hidden"], "time_embedding.2", sd)
    _inv_dense(params["time_projection"], "time_projection.1", sd)
    _inv_dense(params["head_proj"], "head.head", sd)
    sd["head.modulation"] = np.asarray(params["head_modulation"]["bias"])
    for i in range(cfg.depth):
        blk = params[f"blocks_{i}"]
        t = f"blocks.{i}"
        for ours, theirs in (("self", "self_attn"), ("cross", "cross_attn")):
            for proj in "qkvo":
                _inv_dense(blk[f"{ours}_{proj}"], f"{t}.{theirs}.{proj}", sd)
            for nrm in "qk":
                sd[f"{t}.{theirs}.norm_{nrm}.weight"] = np.asarray(
                    blk[f"{ours}_{nrm}_norm"]["scale"]
                )
        sd[f"{t}.norm3.weight"] = np.asarray(blk["norm3"]["scale"])
        sd[f"{t}.norm3.bias"] = np.asarray(blk["norm3"]["bias"])
        _inv_dense(blk["ffn_in"], f"{t}.ffn.0", sd)
        _inv_dense(blk["ffn_out"], f"{t}.ffn.2", sd)
        sd[f"{t}.modulation"] = np.asarray(blk["modulation"])
        if cfg.img_dim is not None:
            _inv_dense(blk["cross_k_img"], f"{t}.cross_attn.k_img", sd)
            _inv_dense(blk["cross_v_img"], f"{t}.cross_attn.v_img", sd)
            sd[f"{t}.cross_attn.norm_k_img.weight"] = np.asarray(
                blk["cross_k_img_norm"]["scale"]
            )
    return sd


class TestWanRoundTrip:
    def test_bitwise_roundtrip(self, tiny_wan):
        sd = _official_layout_sd(TINY, tiny_wan.params)
        got = convert_wan_checkpoint(sd, TINY)
        fg = dict(flatten_tree(got))
        fw = dict(flatten_tree(tiny_wan.params))
        assert sorted(fg) == sorted(fw)
        for k in fw:
            np.testing.assert_array_equal(fg[k], fw[k], err_msg=str(k))

    def test_converted_params_run_forward(self, tiny_wan):
        sd = _official_layout_sd(TINY, tiny_wan.params)
        params = convert_wan_checkpoint(sd, TINY)
        x = jax.random.normal(jax.random.key(1), (1, 2, 4, 4, 4), jnp.float32)
        t = jnp.array([0.5])
        ctx = jax.random.normal(jax.random.key(2), (1, 6, 32), jnp.float32)
        f = jax.jit(tiny_wan.apply)
        want = f(tiny_wan.params, x, t, ctx)
        got = f(params, x, t, ctx)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_loader_default_path(self, tiny_wan):
        sd = _official_layout_sd(TINY, tiny_wan.params)
        model = load_wan_checkpoint(sd, TINY)
        x = jnp.zeros((1, 2, 4, 4, 4), jnp.float32)
        ctx = jnp.zeros((1, 6, 32), jnp.float32)
        out = model.apply(model.params, x, jnp.array([0.1]), ctx)
        assert out.shape == (1, 2, 4, 4, 4)

    def test_i2v_branch_keys_ignored(self, tiny_wan):
        sd = _official_layout_sd(TINY, tiny_wan.params)
        sd["img_emb.proj.0.weight"] = np.zeros((8, 8), np.float32)
        got = convert_wan_checkpoint(sd, TINY)  # no error, branch ignored
        assert "img_emb" not in got


TINY_I2V = WanConfig(
    in_channels=9,  # 4 latent + 4 mask + 1-ch cond stand-in (shape-only tiny)
    out_channels=4,
    hidden_size=48,
    ffn_dim=96,
    num_heads=4,
    depth=2,
    text_dim=32,
    freq_dim=16,
    img_dim=24,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny_wan_i2v():
    return build_wan(
        TINY_I2V, jax.random.key(3), sample_shape=(1, 2, 4, 4, 9), txt_len=6
    )


class TestWanI2VClipBranch:
    """WAN2.1-style i2v: img_emb MLPProj + per-block k_img/v_img heads
    (reference tested-model set includes WAN i2v, /root/reference/README.md:5)."""

    def _fea(self, b=1):
        return jax.random.normal(
            jax.random.key(9), (b, 5, TINY_I2V.img_dim), jnp.float32
        )

    def test_bitwise_roundtrip_with_img_branch(self, tiny_wan_i2v):
        sd = _official_layout_sd(TINY_I2V, tiny_wan_i2v.params)
        assert "img_emb.proj.1.weight" in sd
        assert "blocks.0.cross_attn.k_img.weight" in sd
        got = convert_wan_checkpoint(sd, TINY_I2V)
        fg = dict(flatten_tree(got))
        fw = dict(flatten_tree(tiny_wan_i2v.params))
        assert sorted(fg) == sorted(fw)
        for k in fw:
            np.testing.assert_array_equal(fg[k], fw[k], err_msg=str(k))

    def test_clip_fea_changes_output(self, tiny_wan_i2v):
        x = jax.random.normal(jax.random.key(1), (1, 2, 4, 4, 9), jnp.float32)
        t = jnp.array([0.5])
        ctx = jax.random.normal(jax.random.key(2), (1, 6, 32), jnp.float32)
        m = tiny_wan_i2v
        base = np.asarray(m.apply(m.params, x, t, ctx))
        with_img = np.asarray(
            m.apply(m.params, x, t, ctx, clip_fea=self._fea())
        )
        assert base.shape == with_img.shape == (1, 2, 4, 4, 4)
        assert np.abs(base - with_img).max() > 1e-6

    def test_golden_converted_forward_matches(self, tiny_wan_i2v):
        sd = _official_layout_sd(TINY_I2V, tiny_wan_i2v.params)
        params = convert_wan_checkpoint(sd, TINY_I2V)
        x = jax.random.normal(jax.random.key(4), (1, 2, 4, 4, 9), jnp.float32)
        t = jnp.array([0.3])
        ctx = jax.random.normal(jax.random.key(5), (1, 6, 32), jnp.float32)
        f = jax.jit(tiny_wan_i2v.apply)
        want = f(tiny_wan_i2v.params, x, t, ctx, clip_fea=self._fea())
        got = f(params, x, t, ctx, clip_fea=self._fea())
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_clip_fea_on_t2v_config_raises(self, tiny_wan):
        x = jnp.zeros((1, 2, 4, 4, 4), jnp.float32)
        ctx = jnp.zeros((1, 6, 32), jnp.float32)
        with pytest.raises(ValueError, match="img_dim"):
            tiny_wan.apply(
                tiny_wan.params, x, jnp.array([0.1]), ctx,
                clip_fea=jnp.zeros((1, 5, 24)),
            )

    def test_apply_i2v_conditioning_composes(self, tiny_wan_i2v):
        from comfyui_parallelanything_tpu.models.wan import (
            apply_i2v_conditioning,
        )

        cond = jax.random.normal(jax.random.key(6), (1, 2, 4, 4, 5))
        fea = self._fea()
        composed = apply_i2v_conditioning(tiny_wan_i2v, cond, fea)
        x = jax.random.normal(jax.random.key(7), (1, 2, 4, 4, 4), jnp.float32)
        t = jnp.array([0.5])
        ctx = jax.random.normal(jax.random.key(8), (1, 6, 32), jnp.float32)
        got = composed.apply(composed.params, x, t, ctx)
        want = tiny_wan_i2v.apply(
            tiny_wan_i2v.params,
            jnp.concatenate([x, cond.astype(x.dtype)], axis=-1),
            t, ctx, clip_fea=fea,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # CFG's doubled batch tiles both conditioning tensors.
        x2 = jnp.concatenate([x, x], axis=0)
        got2 = composed.apply(composed.params, x2, jnp.array([0.5, 0.5]),
                              jnp.concatenate([ctx, ctx], axis=0))
        np.testing.assert_allclose(
            np.asarray(got2[0]), np.asarray(got2[1]), atol=1e-5
        )


class TestI2VConditioningConfigAware:
    """apply_i2v_conditioning's host WAN21.concat_cond semantics (review
    fixes): zero-fill when no start-image cond, ignore on t2v checkpoints,
    reject mismatched widths at compose time."""

    def test_missing_cond_zero_fills(self, tiny_wan_i2v):
        from comfyui_parallelanything_tpu.models.wan import (
            apply_i2v_conditioning,
        )

        fea = jax.random.normal(jax.random.key(9), (1, 5, 24), jnp.float32)
        composed = apply_i2v_conditioning(tiny_wan_i2v, cond=None,
                                          clip_fea=fea)
        x = jax.random.normal(jax.random.key(1), (1, 2, 4, 4, 4), jnp.float32)
        t = jnp.array([0.5])
        ctx = jnp.zeros((1, 6, 32))
        got = composed.apply(composed.params, x, t, ctx)
        want = tiny_wan_i2v.apply(
            tiny_wan_i2v.params,
            jnp.concatenate([x, jnp.zeros((1, 2, 4, 4, 5))], axis=-1),
            t, ctx, clip_fea=fea,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_t2v_checkpoint_ignores_tag(self, tiny_wan):
        from comfyui_parallelanything_tpu.models.wan import (
            apply_i2v_conditioning,
        )

        composed = apply_i2v_conditioning(
            tiny_wan, cond=jnp.zeros((1, 2, 4, 4, 5))
        )
        assert composed is tiny_wan  # stock: no concat slots → no-op

    def test_wrong_width_cond_rejected(self, tiny_wan_i2v):
        from comfyui_parallelanything_tpu.models.wan import (
            apply_i2v_conditioning,
        )

        with pytest.raises(ValueError, match="concatenates 5"):
            apply_i2v_conditioning(
                tiny_wan_i2v, cond=jnp.zeros((1, 2, 4, 4, 9))
            )
