"""palint (round 16): the repo-native static-analysis suite + lockcheck.

- each of the six passes fires on a positive fixture and stays quiet on
  the matching negative (standalone-contract, host-sync, recompile-hazard,
  registry-consistency, lock-discipline, observability);
- the pragma engine: `# palint: allow[pass] why` suppresses, an
  unjustified pragma is a finding, a stale pragma is a finding;
- the JSON report schema (`pa-palint/v1`) and the `--check` CLI gate on
  the REAL repo (green — every surviving convention violation is fixed or
  justified in-line);
- utils/lockcheck.py: a deliberate A→B / B→A acquisition cycle is
  detected (and a 3-lock transitive one), a clean consistent ordering is
  not, install() wraps repo-created locks only, uninstall() restores.

The engine is loaded by file path (its own standalone contract — no jax,
no package import), so this file runs even when the package can't import.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import threading
import _thread
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


def _load_engine():
    pkg_dir = REPO / "scripts" / "palint"
    spec = importlib.util.spec_from_file_location(
        "pa_palint_test", str(pkg_dir / "__init__.py"),
        submodule_search_locations=[str(pkg_dir)])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["pa_palint_test"] = mod
    spec.loader.exec_module(mod)
    return mod


engine = _load_engine()


def _load_lockcheck():
    path = REPO / "comfyui_parallelanything_tpu" / "utils" / "lockcheck.py"
    spec = importlib.util.spec_from_file_location(
        "pa_lockcheck_test", str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mini_repo(tmp_path: Path, files: dict[str, str]) -> Path:
    """A throwaway repo skeleton; keys are repo-relative paths."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return tmp_path


def _codes(findings, pass_name=None):
    return [f.code for f in findings
            if pass_name is None or f.pass_name == pass_name]


def lint(root: Path):
    findings, report = engine.lint(str(root))
    return findings, report


PKG = "comfyui_parallelanything_tpu"


# ---------------------------------------------------------------------------
# standalone-contract
# ---------------------------------------------------------------------------

class TestStandaloneContract:
    def test_module_level_jax_import_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {
            f"{PKG}/utils/roofline.py": "import json\nimport jax\n",
        })
        findings, _ = lint(root)
        codes = _codes(findings, "standalone-contract")
        assert codes == ["nonstd-import"]

    def test_relative_import_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {
            f"{PKG}/fleet/twin.py": "from ..utils import retry\n",
        })
        findings, _ = lint(root)
        assert _codes(findings, "standalone-contract") == ["relative-import"]

    def test_script_package_import_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {
            "scripts/myreport.py":
                f"from {PKG}.utils.roofline import walk_jaxpr\n",
        })
        findings, _ = lint(root)
        assert _codes(findings, "standalone-contract") == ["nonstd-import"]

    def test_clean_patterns_pass(self, tmp_path):
        root = _mini_repo(tmp_path, {
            # stdlib + function-level jax + `import bench`: all legal.
            f"{PKG}/utils/slo.py":
                "import json\nimport os\n\n"
                "def f():\n    import jax\n    return jax\n",
            "scripts/gate.py": "import bench\nimport argparse\n",
            "bench.py": "import json\n",
            # non-declared package modules may import anything.
            f"{PKG}/models/unet.py": "import jax\n",
        })
        findings, _ = lint(root)
        assert _codes(findings, "standalone-contract") == []

    def test_import_under_module_level_try_still_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {
            f"{PKG}/utils/retry.py":
                "try:\n    import numpy\nexcept ImportError:\n"
                "    numpy = None\n",
        })
        findings, _ = lint(root)
        assert _codes(findings, "standalone-contract") == ["nonstd-import"]


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

_TIMED_LOOP_BAD = """\
import time

def run(step, x, iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        x = step(x)
        x.block_until_ready()
    return (time.perf_counter() - t0) / iters
"""

_TIMED_LOOP_OK = """\
import time

def run(step, x, iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        x = step(x)
    force_ready(x)
    return (time.perf_counter() - t0) / iters
"""


class TestHostSync:
    def test_sync_inside_timed_loop_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/utils/metrics.py":
                                     _TIMED_LOOP_BAD})
        findings, _ = lint(root)
        assert "sync-in-hot-path" in _codes(findings, "host-sync")

    def test_boundary_sync_outside_loop_ok(self, tmp_path):
        # The closing force_ready sits between the stamps but outside the
        # loop — the StepTimer/chained_time honest-timing pattern.
        root = _mini_repo(tmp_path, {f"{PKG}/utils/metrics.py":
                                     _TIMED_LOOP_OK})
        findings, _ = lint(root)
        assert _codes(findings, "host-sync") == []

    def test_hot_path_transfer_flagged_and_jnp_asarray_ok(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/serving/bucket.py": (
            "import numpy as np\nimport jax.numpy as jnp\n\n"
            "class StepBucket:\n"
            "    def dispatch(self):\n"
            "        dev = jnp.asarray([1.0])\n"      # host→device: legal
            "        host = np.asarray(dev)\n"        # device→host: flagged
            "        return float(host[0])\n"         # float(subscript): flagged
        )})
        findings, _ = lint(root)
        codes = _codes(findings, "host-sync")
        assert codes.count("sync-in-hot-path") == 2

    def test_pragma_allows_boundary_block(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/serving/bucket.py": (
            "class StepBucket:\n"
            "    def dispatch(self, jax, x):\n"
            "        # palint: allow[host-sync] completion boundary\n"
            "        jax.block_until_ready(x)\n"
        )})
        findings, _ = lint(root)
        assert _codes(findings, "host-sync") == []
        # and the pragma is counted as used, not stale
        assert "stale-pragma" not in _codes(findings)


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------

class TestRecompileHazard:
    def test_dynamic_program_name_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/sampling/loops.py": (
            "def build(fn, n):\n"
            "    return instrument_jit(fn, f'loop:{n}')\n"
        )})
        findings, _ = lint(root)
        assert _codes(findings, "recompile-hazard") == [
            "dynamic-program-name"]

    def test_unhashable_static_and_mutable_default_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/sampling/loops.py": (
            "import jax\n\n"
            "def step(x, opts={}):\n"
            "    return x\n\n"
            "prog = jax.jit(step, static_argnames=('opts',))\n"
        )})
        findings, _ = lint(root)
        codes = _codes(findings, "recompile-hazard")
        assert "unhashable-static" in codes
        assert "mutable-default" in codes

    def test_static_argnums_resolution(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/parallel/stage.py": (
            "import jax\n\n"
            "def step(x, shape=[1, 2]):\n"
            "    return x\n\n"
            "prog = jax.jit(step, static_argnums=[1])\n"
        )})
        findings, _ = lint(root)
        assert "unhashable-static" in _codes(findings, "recompile-hazard")

    def test_stable_literal_name_ok(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/sampling/loops.py": (
            "def build(fn):\n"
            "    return instrument_jit(fn, 'loop:k', static_argnames=('n',))\n"
        )})
        findings, _ = lint(root)
        assert _codes(findings, "recompile-hazard") == []


# ---------------------------------------------------------------------------
# registry-consistency
# ---------------------------------------------------------------------------

class TestRegistryConsistency:
    def test_metric_family_check(self, tmp_path):
        root = _mini_repo(tmp_path, {
            f"{PKG}/utils/metrics.py":
                '"""Families: ``pa_good_*`` (x).\n"""\n',
            f"{PKG}/serving/bucket.py": (
                "def f(registry):\n"
                "    registry.counter('pa_good_x_total')\n"
                "    registry.gauge('pa_bad_thing', 1.0)\n"
            ),
        })
        findings, _ = lint(root)
        bad = [f for f in findings if f.code == "undocumented-metric"]
        assert len(bad) == 1 and "pa_bad_thing" in bad[0].message

    def test_env_table_both_directions(self, tmp_path):
        root = _mini_repo(tmp_path, {
            "README.md": "| `PA_DOCUMENTED` | x |\n| `PA_GHOST` | y |\n",
            f"{PKG}/server.py": (
                "import os\n"
                "A = os.environ.get('PA_DOCUMENTED')\n"
                "B = os.environ.get('PA_UNDOCUMENTED')\n"
            ),
        })
        findings, _ = lint(root)
        codes = _codes(findings, "registry-consistency")
        assert codes.count("undocumented-env") == 1
        assert codes.count("stale-env-doc") == 1

    def test_fault_sites_both_directions(self, tmp_path):
        root = _mini_repo(tmp_path, {
            f"{PKG}/utils/faults.py":
                "FAULT_SITES = {'real-site': 'x', 'dead-site': 'y'}\n",
            f"{PKG}/parallel/streaming.py": (
                "def f(faults):\n"
                "    faults.check('real-site', key='k')\n"
                "    faults.check('typo-site', key='k')\n"
            ),
        })
        findings, _ = lint(root)
        codes = _codes(findings, "registry-consistency")
        assert codes.count("unknown-fault-site") == 1
        assert codes.count("unfired-fault-site") == 1

    def test_span_category_vocabulary(self, tmp_path):
        root = _mini_repo(tmp_path, {
            "scripts/trace_summary.py":
                "SPAN_CATEGORIES = ('stream', 'ghost')\n",
            f"{PKG}/utils/tracing.py": (
                "def f(tracing):\n"
                "    tracing.record('x', 0, 1, cat='stream')\n"
                "    tracing.record('y', 0, 1, cat='mystery')\n"
            ),
        })
        findings, _ = lint(root)
        codes = _codes(findings, "registry-consistency")
        assert codes.count("unknown-span-category") == 1
        assert codes.count("stale-span-category") == 1

    def test_late_schema_drift(self, tmp_path):
        root = _mini_repo(tmp_path, {
            "bench.py": (
                "_LATE_SCHEMA_FIELDS = ('emitted_field', 'phantom_field')\n"
                "rec = {}\n"
                "rec['emitted_field'] = 1\n"
            ),
        })
        findings, _ = lint(root)
        drift = [f for f in findings if f.code == "late-schema-drift"]
        assert len(drift) == 1 and "phantom_field" in drift[0].message


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

_LOCKED_CLASS = """\
import threading


class Table:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {{}}{ann}

    def put(self, k, v):
{body}
"""


class TestLockDiscipline:
    def test_unannotated_container_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/fleet/table.py":
                          _LOCKED_CLASS.format(
                              ann="",
                              body="        with self._lock:\n"
                                   "            self._rows[k] = v\n")})
        findings, _ = lint(root)
        assert _codes(findings, "lock-discipline") == [
            "unannotated-shared-attr"]

    def test_guarded_write_outside_lock_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/fleet/table.py":
                          _LOCKED_CLASS.format(
                              ann="  # guarded-by: _lock",
                              body="        self._rows[k] = v\n")})
        findings, _ = lint(root)
        assert _codes(findings, "lock-discipline") == ["unguarded-write"]

    def test_guarded_write_under_lock_ok(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/fleet/table.py":
                          _LOCKED_CLASS.format(
                              ann="  # guarded-by: _lock",
                              body="        with self._lock:\n"
                                   "            self._rows[k] = v\n")})
        findings, _ = lint(root)
        assert _codes(findings, "lock-discipline") == []

    def test_holds_annotation_and_mutator_calls(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/serving/table.py": (
            "import threading\n\n\n"
            "class Table:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._rows = {}  # guarded-by: _lock\n\n"
            "    def _put(self, k, v):  # palint: holds _lock\n"
            "        self._rows.update({k: v})\n\n"
            "    def drop(self, k):\n"
            "        self._rows.pop(k, None)\n"
        )})
        findings, _ = lint(root)
        # update() under holds is fine; pop() outside any lock is not.
        assert _codes(findings, "lock-discipline") == ["unguarded-write"]

    def test_condition_alias_covers_lock(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/serving/table.py": (
            "import threading\n\n\n"
            "class Table:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "        self._cond = threading.Condition(self._lock)\n"
            "        self._rows = {}  # guarded-by: _lock\n\n"
            "    def put(self, k, v):\n"
            "        with self._cond:\n"
            "            self._rows[k] = v\n"
        )})
        findings, _ = lint(root)
        assert _codes(findings, "lock-discipline") == []

    def test_unguarded_reason_accepted(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/fleet/table.py":
                          _LOCKED_CLASS.format(
                              ann="  # unguarded: write-once pre-thread",
                              body="        self.other = v\n")})
        findings, _ = lint(root)
        assert _codes(findings, "lock-discipline") == []

    def test_unguarded_empty_reason_flagged(self, tmp_path):
        # `# unguarded:` with no reason would be a mute button — the engine
        # rejects it the way it rejects unjustified allow-pragmas.
        root = _mini_repo(tmp_path, {f"{PKG}/fleet/table.py":
                          _LOCKED_CLASS.format(
                              ann="  # unguarded:",
                              body="        self.other = v\n")})
        findings, _ = lint(root)
        assert "unjustified-annotation" in _codes(findings, "engine")

    def test_module_level_lock_and_global(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/serving/mod.py": (
            "import threading\n\n"
            "_batch_lock = threading.Lock()\n"
            "_counts = {}  # guarded-by: _batch_lock\n\n\n"
            "def good(k):\n"
            "    with _batch_lock:\n"
            "        _counts[k] = _counts.get(k, 0) + 1\n\n\n"
            "def bad(k):\n"
            "    _counts[k] = 0\n"
        )})
        findings, _ = lint(root)
        assert _codes(findings, "lock-discipline") == ["unguarded-write"]


# ---------------------------------------------------------------------------
# observability + pragma engine
# ---------------------------------------------------------------------------

class TestObservabilityAndPragmas:
    def test_print_and_time_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/utils/thing.py": (
            "import time\n\n"
            "def f():\n"
            "    print('hello')\n"
            "    return time.time()\n"
        )})
        findings, _ = lint(root)
        codes = _codes(findings, "observability")
        assert sorted(codes) == ["ad-hoc-time", "bare-print"]

    def test_scripts_exempt(self, tmp_path):
        root = _mini_repo(tmp_path, {"scripts/cli.py":
                                     "import time\nprint(time.time())\n"})
        findings, _ = lint(root)
        assert _codes(findings, "observability") == []

    def test_pragma_suppresses(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/utils/thing.py": (
            "def f():\n"
            "    # palint: allow[observability] CLI banner\n"
            "    print('hello')\n"
        )})
        findings, _ = lint(root)
        assert findings == []

    def test_unjustified_pragma_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/utils/thing.py": (
            "def f():\n"
            "    # palint: allow[observability]\n"
            "    print('hello')\n"
        )})
        findings, _ = lint(root)
        assert _codes(findings) == ["unjustified-pragma"]

    def test_stale_pragma_flagged(self, tmp_path):
        root = _mini_repo(tmp_path, {f"{PKG}/utils/thing.py": (
            "def f():\n"
            "    # palint: allow[observability] nothing here anymore\n"
            "    return 1\n"
        )})
        findings, _ = lint(root)
        assert _codes(findings) == ["stale-pragma"]


# ---------------------------------------------------------------------------
# report schema + the real repo gate (CLI, subprocess)
# ---------------------------------------------------------------------------

class TestReportAndRepoGate:
    def test_check_green_on_repo_and_report_schema(self, tmp_path):
        env = dict(os.environ, PA_LEDGER_DIR=str(tmp_path))
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "palint.py"),
             "--check", "--json"],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert proc.returncode == 0, (
            f"palint --check failed on the repo:\n{proc.stdout}\n"
            f"{proc.stderr}"
        )
        report = json.loads(proc.stdout)
        assert report["schema"] == "pa-palint/v1"
        assert report["ok"] is True and report["findings"] == []
        assert set(report["counts"]) == {
            "standalone-contract", "host-sync", "recompile-hazard",
            "registry-consistency", "lock-discipline", "observability",
        }
        assert report["files_scanned"] > 50
        # the ledger report landed under the redirect
        on_disk = json.loads((tmp_path / "palint.json").read_text())
        assert on_disk["schema"] == "pa-palint/v1"

    def test_check_exits_nonzero_on_violation(self, tmp_path):
        root = _mini_repo(tmp_path, {
            f"{PKG}/utils/thing.py": "print('x')\n",
            "scripts/.keep.py": "",
        })
        findings, report = lint(root)
        assert findings and report["ok"] is False

    def test_env_table_contains_inventory(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "palint.py"),
             "--env-table"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0
        assert "| `PA_LOCKCHECK` |" in proc.stdout
        assert "| `PA_FAULT_PLAN` |" in proc.stdout

    def test_env_table_preserves_readme_purposes(self, tmp_path):
        # The inventory comes from the code; the Purpose prose is preserved
        # from the committed README on regeneration, and a var the README
        # has never described gets a TODO row naming its read sites — so
        # "regenerate after adding a variable" never destroys the docs.
        root = _mini_repo(tmp_path, {
            f"{PKG}/utils/thing.py": (
                "import os\n\n"
                "A = os.environ.get('PA_OLD_VAR')\n"
                "B = os.environ.get('PA_NEW_VAR')\n"),
            "README.md": (
                "| Variable | Purpose |\n|---|---|\n"
                "| `PA_OLD_VAR` | the documented purpose |\n"),
        })
        table = engine.env_table(str(root))
        assert "| `PA_OLD_VAR` | the documented purpose |" in table
        assert "| `PA_NEW_VAR` | TODO: describe (read in thing.py) |" \
            in table

    def test_env_table_reproduces_committed_readme_table(self):
        # The README's committed table IS the generator's output today —
        # the drift gate the README documents.
        table = engine.env_table(str(REPO))
        readme = (REPO / "README.md").read_text()
        for row in table.splitlines()[2:]:
            assert row in readme, f"README env table drifted: {row}"
        assert "TODO: describe" not in table

    def test_engine_is_jax_free(self):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        code = (
            "import runpy, sys\n"
            "sys.argv = ['palint.py', '--env-table']\n"
            "try:\n"
            f"    runpy.run_path(r'{REPO}/scripts/palint.py',"
            " run_name='__main__')\n"
            "except SystemExit as e:\n"
            "    assert (e.code or 0) == 0, e.code\n"
            "assert 'jax' not in sys.modules, 'palint pulled jax'\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# utils/lockcheck.py — the runtime half
# ---------------------------------------------------------------------------

class TestLockcheck:
    def test_ab_ba_cycle_detected(self):
        lc = _load_lockcheck()
        A = lc.TrackedLock(_thread.allocate_lock(), "site:A", "Lock")
        B = lc.TrackedLock(_thread.allocate_lock(), "site:B", "Lock")

        def order_ab():
            with A:
                with B:
                    pass

        def order_ba():
            with B:
                with A:
                    pass

        # Two code paths with opposite orders, exercised from two threads
        # run to completion sequentially — no real deadlock ever fires, and
        # the graph still convicts the ORDER.
        for fn in (order_ab, order_ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        cyc = lc.cycles()
        assert len(cyc) == 1
        assert set(cyc[0]) == {"site:A", "site:B"}
        assert lc.report()["ok"] is False

    def test_clean_ordering_not_flagged(self):
        lc = _load_lockcheck()
        A = lc.TrackedLock(_thread.allocate_lock(), "site:A", "Lock")
        B = lc.TrackedLock(_thread.allocate_lock(), "site:B", "Lock")
        for _ in range(3):
            with A:
                with B:
                    pass
        assert lc.cycles() == []
        assert lc.report()["ok"] is True
        assert lc.edges() and lc.edges()[0]["count"] == 3

    def test_edge_attribution_names_acquiring_site(self):
        lc = _load_lockcheck()
        A = lc.TrackedLock(_thread.allocate_lock(), "site:A", "Lock")
        B = lc.TrackedLock(_thread.allocate_lock(), "site:B", "Lock")
        with A:
            with B:
                pass
        (edge,) = lc.edges()
        # The forensic `at` must name the ACQUIRING frame (this file), not
        # lockcheck's own __enter__/acquire plumbing — with-statements add
        # two lockcheck frames that a fixed _getframe depth would land on.
        assert edge["at"].startswith("test_palint.py:"), edge
        lc = _load_lockcheck()
        locks = {s: lc.TrackedLock(_thread.allocate_lock(), f"site:{s}",
                                   "Lock") for s in "ABC"}
        for first, second in (("A", "B"), ("B", "C"), ("C", "A")):
            with locks[first]:
                with locks[second]:
                    pass
        cyc = lc.cycles()
        assert len(cyc) == 1 and set(cyc[0]) == {
            "site:A", "site:B", "site:C"}

    def test_rlock_reentry_is_not_an_edge(self):
        lc = _load_lockcheck()
        R = lc.TrackedLock(_thread.allocate_lock(), "site:R", "RLock")
        # simulate reentrancy bookkeeping: same object acquired nested
        held = [R, R]
        with lc._graph_mutex:
            pass  # no edge was recorded for a self-pair
        A = lc.TrackedLock(_thread.allocate_lock(), "site:R", "Lock")
        B = lc.TrackedLock(_thread.allocate_lock(), "site:R", "Lock")
        with A:
            with B:  # distinct objects, SAME creation site: not an edge
                pass
        assert lc.edges() == [] and held

    def test_install_tracks_repo_locks_and_uninstall_restores(self):
        lc = _load_lockcheck()
        prev_lock, prev_rlock = threading.Lock, threading.RLock
        lc.install()
        try:
            tracked = threading.Lock()   # created HERE (tests/ = in-repo)
            assert type(tracked).__name__ == "TrackedLock"
            assert tracked.site.startswith("tests/test_palint.py")
            with tracked:
                assert tracked.locked()
            r = threading.RLock()
            with r:
                with r:  # reentrancy must hold through the proxy
                    pass
            cond = threading.Condition(threading.RLock())
            with cond:
                pass
        finally:
            lc.uninstall()
        assert threading.Lock is prev_lock
        assert threading.RLock is prev_rlock
