"""AutoencoderKL: shapes, converter round-trip, tiled decode, loader sniffing.

Same strategy as test_convert.py: synthesize an ldm-layout state dict by inverting
the converter's layout transforms from freshly-initialized params, convert it back,
and require a bitwise round-trip (the converter only relays/transposes weights)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models.convert_vae import (
    convert_vae_checkpoint,
    strip_vae_prefix,
)
from comfyui_parallelanything_tpu.models.loader import load_vae_checkpoint
from comfyui_parallelanything_tpu.models.vae import (
    VAEConfig,
    build_vae,
    flux_vae_config,
    sd_vae_config,
    sdxl_vae_config,
)

TINY = VAEConfig(
    z_channels=4,
    base_channels=32,
    channel_mult=(1, 2),
    num_res_blocks=1,
    norm_groups=8,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny_vae():
    return build_vae(TINY, jax.random.key(0), sample_hw=16)


def _inv_conv(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["kernel"]).transpose(3, 2, 0, 1)
    if "bias" in p:
        sd[f"{key}.bias"] = np.asarray(p["bias"])


def _inv_norm(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["scale"])
    sd[f"{key}.bias"] = np.asarray(p["bias"])


def _inv_res(p, t, sd):
    _inv_norm(p["norm1"], f"{t}.norm1", sd)
    _inv_conv(p["conv1"], f"{t}.conv1", sd)
    _inv_norm(p["norm2"], f"{t}.norm2", sd)
    _inv_conv(p["conv2"], f"{t}.conv2", sd)
    if "nin_shortcut" in p:
        _inv_conv(p["nin_shortcut"], f"{t}.nin_shortcut", sd)


def _inv_attn(p, t, sd):
    _inv_norm(p["norm"], f"{t}.norm", sd)
    for k in ("q", "k", "v", "proj_out"):
        _inv_conv(p[k], f"{t}.{k}", sd)


def _ldm_layout_sd(cfg: VAEConfig, params) -> dict:
    """Params → ldm checkpoint layout (the converter's inverse)."""
    sd: dict = {}
    enc, dec = params["encoder"], params["decoder"]
    _inv_conv(enc["conv_in"], "encoder.conv_in", sd)
    _inv_res(enc["mid_block_1"], "encoder.mid.block_1", sd)
    _inv_attn(enc["mid_attn_1"], "encoder.mid.attn_1", sd)
    _inv_res(enc["mid_block_2"], "encoder.mid.block_2", sd)
    _inv_norm(enc["norm_out"], "encoder.norm_out", sd)
    _inv_conv(enc["conv_out"], "encoder.conv_out", sd)
    for lvl in range(len(cfg.channel_mult)):
        for i in range(cfg.num_res_blocks):
            _inv_res(enc[f"down_{lvl}_block_{i}"], f"encoder.down.{lvl}.block.{i}", sd)
        if lvl != len(cfg.channel_mult) - 1:
            _inv_conv(
                enc[f"down_{lvl}_downsample"]["conv"],
                f"encoder.down.{lvl}.downsample.conv",
                sd,
            )
    _inv_conv(dec["conv_in"], "decoder.conv_in", sd)
    _inv_res(dec["mid_block_1"], "decoder.mid.block_1", sd)
    _inv_attn(dec["mid_attn_1"], "decoder.mid.attn_1", sd)
    _inv_res(dec["mid_block_2"], "decoder.mid.block_2", sd)
    _inv_norm(dec["norm_out"], "decoder.norm_out", sd)
    _inv_conv(dec["conv_out"], "decoder.conv_out", sd)
    for lvl in range(len(cfg.channel_mult)):
        for i in range(cfg.num_res_blocks + 1):
            _inv_res(dec[f"up_{lvl}_block_{i}"], f"decoder.up.{lvl}.block.{i}", sd)
        if lvl != 0:
            _inv_conv(
                dec[f"up_{lvl}_upsample"]["conv"],
                f"decoder.up.{lvl}.upsample.conv",
                sd,
            )
    if cfg.use_quant_conv:
        _inv_conv(params["quant_conv"], "quant_conv", sd)
        _inv_conv(params["post_quant_conv"], "post_quant_conv", sd)
    return sd



class TestShapes:
    def test_encode_decode_shapes(self, tiny_vae):
        f = tiny_vae.spatial_factor
        assert f == 2
        x = jax.random.normal(jax.random.key(1), (2, 16, 16, 3), jnp.float32)
        z = tiny_vae.encode(x)
        assert z.shape == (2, 16 // f, 16 // f, TINY.z_channels)
        img = tiny_vae.decode(z)
        assert img.shape == x.shape

    def test_encode_sampling_differs_from_mean(self, tiny_vae):
        x = jax.random.normal(jax.random.key(1), (1, 16, 16, 3), jnp.float32)
        z_mean = tiny_vae.encode(x)
        z_smp = tiny_vae.encode(x, rng=jax.random.key(2))
        assert not np.allclose(np.asarray(z_mean), np.asarray(z_smp))

    def test_family_config_constants(self):
        assert sd_vae_config().scaling_factor == pytest.approx(0.18215)
        assert sdxl_vae_config().scaling_factor == pytest.approx(0.13025)
        assert flux_vae_config().z_channels == 16
        assert not flux_vae_config().use_quant_conv

    def test_scale_shift_applied_against_closed_form(self, tiny_vae):
        """Independent check of the latent conventions (a swapped inversion order in
        decode would cancel out in any encode→decode round-trip test):

        - encode (no rng) must equal (posterior_mean - shift) * scale exactly;
        - decode under (scale, shift) must equal the identity-convention decode of
          z / scale + shift, with weights held fixed.
        """
        import dataclasses

        from comfyui_parallelanything_tpu.models.vae import VAE, AutoencoderKL

        cfg = dataclasses.replace(TINY, scaling_factor=0.37, shift_factor=0.21)
        vae = VAE(cfg=cfg, params=tiny_vae.params)
        ident = VAE(
            cfg=dataclasses.replace(cfg, scaling_factor=1.0, shift_factor=0.0),
            params=tiny_vae.params,
        )
        x = jax.random.normal(jax.random.key(8), (1, 16, 16, 3), jnp.float32)
        module = AutoencoderKL(cfg)
        mean, _ = module.apply(
            {"params": vae.params}, x, method=AutoencoderKL.moments
        )
        np.testing.assert_allclose(
            np.asarray(vae.encode(x)),
            (np.asarray(mean) - cfg.shift_factor) * cfg.scaling_factor,
            rtol=1e-6,
            atol=1e-6,
        )
        z = jax.random.normal(jax.random.key(9), (1, 8, 8, 4), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(vae.decode(z)),
            np.asarray(ident.decode(z / cfg.scaling_factor + cfg.shift_factor)),
            rtol=1e-5,
            atol=1e-5,
        )


class TestConverterRoundTrip:
    def test_bitwise_roundtrip(self, tiny_vae):
        sd = _ldm_layout_sd(TINY, tiny_vae.params)
        got = convert_vae_checkpoint(sd, TINY)
        flat_got = dict(flatten_tree(got))
        flat_want = dict(flatten_tree(tiny_vae.params))
        assert sorted(flat_got) == sorted(flat_want)
        for k in flat_want:
            np.testing.assert_array_equal(flat_got[k], flat_want[k], err_msg=str(k))

    def test_rank2_attention_projections(self, tiny_vae):
        # diffusers-style exports store attn q/k/v/proj_out as rank-2 linears.
        sd = _ldm_layout_sd(TINY, tiny_vae.params)
        for t in ("encoder.mid.attn_1", "decoder.mid.attn_1"):
            for k in ("q", "k", "v", "proj_out"):
                w = sd[f"{t}.{k}.weight"]
                sd[f"{t}.{k}.weight"] = w[:, :, 0, 0]
        got = convert_vae_checkpoint(sd, TINY)
        np.testing.assert_array_equal(
            np.asarray(got["encoder"]["mid_attn_1"]["q"]["kernel"]),
            np.asarray(tiny_vae.params["encoder"]["mid_attn_1"]["q"]["kernel"]),
        )

    def test_prefix_stripping(self, tiny_vae):
        sd = _ldm_layout_sd(TINY, tiny_vae.params)
        prefixed = {f"first_stage_model.{k}": v for k, v in sd.items()}
        # Combined checkpoints carry non-VAE keys too — they must be ignored.
        prefixed["model.diffusion_model.out.0.weight"] = np.zeros(4, np.float32)
        assert sorted(strip_vae_prefix(prefixed)) == sorted(sd)

    def test_unconsumed_keys_rejected(self, tiny_vae):
        sd = _ldm_layout_sd(TINY, tiny_vae.params)
        sd["encoder.down.7.block.0.conv1.weight"] = np.zeros((4, 4, 3, 3), np.float32)
        with pytest.raises(ValueError, match="unconverted"):
            convert_vae_checkpoint(sd, TINY)

    def test_in_range_attn_variant_rejected(self, tiny_vae):
        # kl-f16-style layouts carry encoder.down.{l}.attn.{i}.* — indices are
        # in-range, so only consumed-key tracking catches the mismatch.
        sd = _ldm_layout_sd(TINY, tiny_vae.params)
        sd["encoder.down.0.attn.0.q.weight"] = np.zeros((32, 32, 1, 1), np.float32)
        with pytest.raises(ValueError, match="unconverted"):
            convert_vae_checkpoint(sd, TINY)


class TestTiledDecode:
    def test_matches_full_decode_in_interior(self, tiny_vae):
        z = jax.random.normal(jax.random.key(3), (1, 24, 24, 4), jnp.float32)
        full = np.asarray(tiny_vae.decode(z), np.float32)
        tiled = np.asarray(tiny_vae.decode_tiled(z, tile=16, overlap=8), np.float32)
        assert tiled.shape == full.shape
        # Conv receptive fields cross tile edges, so exact equality only holds
        # away from seams — and at this toy geometry (16-px tiles, 8-px
        # overlap, a decoder receptive field spanning most of a tile) the seam
        # halo covers nearly every pixel, leaving a deterministic ~5% mean
        # deviation. Bound it relative to the signal scale so the check
        # survives decoder-depth tweaks while still catching a broken blend
        # (an unblended hard seam is several times this).
        assert np.mean(np.abs(tiled - full)) < 0.1 * np.mean(np.abs(full))

    def test_non_square_and_single_axis_tiling(self, tiny_vae):
        z = jax.random.normal(jax.random.key(4), (1, 8, 40, 4), jnp.float32)
        out = tiny_vae.decode_tiled(z, tile=16, overlap=4)
        assert out.shape == (1, 16, 80, 3)

    def test_small_latent_short_circuits(self, tiny_vae):
        z = jax.random.normal(jax.random.key(5), (1, 8, 8, 4), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(tiny_vae.decode_tiled(z, tile=16)),
            np.asarray(tiny_vae.decode(z)),
            rtol=1e-6,
            atol=1e-6,
        )

    def test_invalid_overlap_rejected(self, tiny_vae):
        z = jnp.zeros((1, 40, 40, 4), jnp.float32)
        with pytest.raises(ValueError, match="overlap"):
            tiny_vae.decode_tiled(z, tile=16, overlap=16)

    def test_zero_overlap_valid(self, tiny_vae):
        z = jax.random.normal(jax.random.key(6), (1, 24, 24, 4), jnp.float32)
        out = tiny_vae.decode_tiled(z, tile=16, overlap=0)
        assert out.shape == (1, 48, 48, 3)
        assert np.isfinite(np.asarray(out)).all()


class TestLoader:
    def test_load_from_state_dict_with_sniffed_config(self, tiny_vae):
        sd = _ldm_layout_sd(TINY, tiny_vae.params)
        # Sniffing picks sd_vae_config for 4-channel latents; TINY differs from the
        # full-size config, so pass cfg explicitly and check the sniff separately.
        vae = load_vae_checkpoint(sd, cfg=TINY)
        x = jax.random.normal(jax.random.key(7), (1, 16, 16, 3), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(vae.decode(vae.encode(x))),
            np.asarray(tiny_vae.decode(tiny_vae.encode(x))),
            rtol=1e-6,
            atol=1e-6,
        )

    def test_sniff_flux_vs_sd(self):
        from comfyui_parallelanything_tpu.models.loader import sniff_vae_config

        sd4 = {"decoder.conv_in.weight": np.zeros((64, 4, 3, 3), np.float32)}
        sd16 = {"decoder.conv_in.weight": np.zeros((64, 16, 3, 3), np.float32)}
        assert sniff_vae_config(sd4).z_channels == 4
        assert sniff_vae_config(sd4).use_quant_conv
        assert sniff_vae_config(sd16).z_channels == 16
        assert not sniff_vae_config(sd16).use_quant_conv
        # Prefixed (full ComfyUI checkpoint) layout sniffs too.
        pre = {"first_stage_model.decoder.conv_in.weight": sd16[
            "decoder.conv_in.weight"
        ]}
        assert sniff_vae_config(pre).z_channels == 16
        with pytest.raises(KeyError, match="AutoencoderKL"):
            sniff_vae_config({"not_a_vae.weight": np.zeros(1, np.float32)})


class TestTiledEncode:
    def test_matches_full_encode(self, tiny_vae):
        x = jax.random.uniform(jax.random.key(7), (1, 80, 80, 3)) * 2 - 1
        full = np.asarray(tiny_vae.encode(x), np.float32)
        tiled = np.asarray(tiny_vae.encode_tiled(x, tile=48, overlap=16), np.float32)
        assert tiled.shape == full.shape
        assert np.mean(np.abs(tiled - full)) < 2e-2

    def test_small_input_short_circuits(self, tiny_vae):
        x = jax.random.uniform(jax.random.key(8), (1, 16, 16, 3))
        np.testing.assert_array_equal(
            np.asarray(tiny_vae.encode_tiled(x, tile=32)),
            np.asarray(tiny_vae.encode(x)),
        )

    def test_unaligned_tile_rejected(self, tiny_vae):
        with pytest.raises(ValueError, match="multiples"):
            tiny_vae.encode_tiled(jnp.zeros((1, 64, 64, 3)), tile=31, overlap=8)

    def test_encode_maybe_tiled_aligns_overlap(self, tiny_vae):
        """Any factor-aligned tile size works — the helper floors the derived
        overlap to the VAE's alignment."""
        from comfyui_parallelanything_tpu.models.vae import encode_maybe_tiled

        x = jax.random.uniform(jax.random.key(9), (1, 72, 72, 3))
        out = encode_maybe_tiled(tiny_vae, x, 52)  # 52//4=13 → floored to 12
        assert out.shape == (1, 36, 36, 4)
