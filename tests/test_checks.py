"""Numeric assertion utility (checkify NaN/Inf guard — SURVEY §5.2's TPU plan)."""

import jax
import jax.numpy as jnp
import pytest

from comfyui_parallelanything_tpu.utils.checks import checked


class TestChecked:
    def test_clean_passthrough(self):
        fn = checked(lambda x: x * 2.0, "double")
        out = fn(jnp.ones((3,)))
        assert jnp.allclose(out, 2.0)

    def test_nan_raises(self):
        fn = checked(lambda x: x / 0.0 * 0.0, "nanmaker")  # 0/0 → NaN
        with pytest.raises(Exception, match="NaN/Inf"):
            fn(jnp.zeros((3,)))

    def test_inf_raises(self):
        fn = checked(lambda x: 1.0 / x, "infmaker")
        with pytest.raises(Exception, match="NaN/Inf"):
            fn(jnp.zeros((3,)))

    def test_pytree_outputs(self):
        fn = checked(lambda x: {"a": x, "b": (x + 1, x - 1)}, "tree")
        out = fn(jnp.ones((2,)))
        assert set(out) == {"a", "b"}

    def test_under_jit(self):
        fn = checked(jax.jit(lambda x: x * jnp.inf * 0.0), "jitted")
        with pytest.raises(Exception, match="NaN/Inf"):
            fn(jnp.ones((2,)))
