"""bench.py rung plumbing: bf16 weight synthesis and sequential microbatching.

The TPU ladder's big rungs run bf16-STORED weights synthesized host-side from
abstract shapes (``bench._bf16_build`` — flax init would materialize f32, a
21.5 GiB init-time OOM for the z-image proxy on a 16 GiB v5e) and split the
batch into sequential microbatches (``bench._make_step`` — full-batch-21
activations OOM'd the chip; evidence in BASELINE_measured.json). Validate both
at tiny scale: synthesis produces an all-bf16 working model, and the chunked
step is numerically identical to the full-batch call.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models import build_flux
from comfyui_parallelanything_tpu.models.flux import FluxConfig

TINY = FluxConfig(
    in_channels=16,  # 4 latent ch x 2x2 patch
    hidden_size=64, num_heads=4, depth=1, depth_single_blocks=2,
    context_in_dim=32, vec_in_dim=16, axes_dim=(4, 6, 6),
    guidance_embed=False, dtype=jnp.float32,
)


def test_bf16_build_synthesizes_all_bf16_params():
    model = bench._bf16_build(
        build_flux, TINY, sample_shape=(1, 8, 8, 4), txt_len=8
    )
    leaves = jax.tree.leaves(model.params)
    assert leaves and all(l.dtype == jnp.bfloat16 for l in leaves)
    # The synthesized model must actually run.
    out = model.apply(
        model.params,
        jnp.ones((2, 8, 8, 4)),
        jnp.ones((2,)),
        jnp.ones((2, 8, TINY.context_in_dim)),
        y=jnp.ones((2, TINY.vec_in_dim)),
    )
    assert out.shape == (2, 8, 8, 4)
    assert np.isfinite(np.asarray(out, dtype=np.float32)).all()


class TestMakeStep:
    def _setup(self, batch):
        model = build_flux(
            TINY, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=8
        )
        pm = parallelize(model, DeviceChain.even(["cpu:0"]))
        x = jax.random.normal(jax.random.key(1), (batch, 8, 8, 4))
        t = jnp.linspace(999.0, 1.0, batch)
        ctx = jax.random.normal(
            jax.random.key(2), (batch, 8, TINY.context_in_dim)
        )
        kwargs = {
            "y": jax.random.normal(jax.random.key(3), (batch, TINY.vec_in_dim))
        }
        return pm, x, t, ctx, kwargs

    def test_chunked_step_matches_full_batch(self):
        batch = 6
        pm, x, t, ctx, kwargs = self._setup(batch)
        full = bench._make_step(pm, batch, 1, t, ctx, kwargs)(x)
        chunked = bench._make_step(pm, batch, 3, t, ctx, kwargs)(x)
        assert chunked.shape == full.shape
        # Batch entries are independent in the forward, so sequential
        # microbatches must reproduce the full-batch result to bf16-matmul
        # tolerance (CLAUDE.md: this CPU backend runs f32 dots at bf16).
        np.testing.assert_allclose(
            np.asarray(chunked, dtype=np.float32),
            np.asarray(full, dtype=np.float32),
            rtol=3e-2, atol=3e-2,
        )

    def test_indivisible_chunks_rejected(self):
        pm, x, t, ctx, kwargs = self._setup(6)
        with pytest.raises(ValueError, match="not divisible"):
            bench._make_step(pm, 6, 4, t, ctx, kwargs)

    def test_bench_chunked_rungs_divide_evenly(self):
        # The declared ladder chunk counts (zimage_21: 3x7, flux_16_int8: 4x4)
        # must divide their batches — checked without building the 12 GiB
        # models by reading the rung declarations.
        assert 21 % 3 == 0 and 16 % 4 == 0

    def test_zimage_int8_fallback_rung_registered(self):
        # The int8-weight headline fallback (bf16 zimage_21 exceeds the
        # tunnel chip's usable HBM even fully sequential — BASELINE_measured
        # evidence) must be a real rung, and the watchdog must know it and
        # its microbatch ladder.
        assert "zimage_21_int8" in bench._RUNGS
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "tpu_watchdog_mod",
            os.path.join(os.path.dirname(bench.__file__), "scripts",
                         "tpu_watchdog.py"),
        )
        wd = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(wd)
        assert "zimage_21_int8" in wd.RUNGS
        assert wd._MB_LADDERS["zimage_21_int8"][0] == 3
