"""bench.py rung plumbing: bf16 weight synthesis and sequential microbatching.

The TPU ladder's big rungs run bf16-STORED weights synthesized host-side from
abstract shapes (``bench._bf16_build`` — flax init would materialize f32, a
21.5 GiB init-time OOM for the z-image proxy on a 16 GiB v5e) and split the
batch into sequential microbatches (``bench._make_step`` — full-batch-21
activations OOM'd the chip; evidence in BASELINE_measured.json). Validate both
at tiny scale: synthesis produces an all-bf16 working model, and the chunked
step is numerically identical to the full-batch call.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models import build_flux
from comfyui_parallelanything_tpu.models.flux import FluxConfig

TINY = FluxConfig(
    in_channels=16,  # 4 latent ch x 2x2 patch
    hidden_size=64, num_heads=4, depth=1, depth_single_blocks=2,
    context_in_dim=32, vec_in_dim=16, axes_dim=(4, 6, 6),
    guidance_embed=False, dtype=jnp.float32,
)


def test_bf16_build_synthesizes_all_bf16_params():
    model = bench._bf16_build(
        build_flux, TINY, sample_shape=(1, 8, 8, 4), txt_len=8
    )
    leaves = jax.tree.leaves(model.params)
    assert leaves and all(l.dtype == jnp.bfloat16 for l in leaves)
    # The synthesized model must actually run.
    out = model.apply(
        model.params,
        jnp.ones((2, 8, 8, 4)),
        jnp.ones((2,)),
        jnp.ones((2, 8, TINY.context_in_dim)),
        y=jnp.ones((2, TINY.vec_in_dim)),
    )
    assert out.shape == (2, 8, 8, 4)
    assert np.isfinite(np.asarray(out, dtype=np.float32)).all()


class TestMakeStep:
    def _setup(self, batch):
        model = build_flux(
            TINY, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=8
        )
        pm = parallelize(model, DeviceChain.even(["cpu:0"]))
        x = jax.random.normal(jax.random.key(1), (batch, 8, 8, 4))
        t = jnp.linspace(999.0, 1.0, batch)
        ctx = jax.random.normal(
            jax.random.key(2), (batch, 8, TINY.context_in_dim)
        )
        kwargs = {
            "y": jax.random.normal(jax.random.key(3), (batch, TINY.vec_in_dim))
        }
        return pm, x, t, ctx, kwargs

    def test_chunked_step_matches_full_batch(self):
        batch = 6
        pm, x, t, ctx, kwargs = self._setup(batch)
        full = bench._make_step(pm, batch, 1, t, ctx, kwargs)(x)
        chunked = bench._make_step(pm, batch, 3, t, ctx, kwargs)(x)
        assert chunked.shape == full.shape
        # Batch entries are independent in the forward, so sequential
        # microbatches must reproduce the full-batch result to bf16-matmul
        # tolerance (CLAUDE.md: this CPU backend runs f32 dots at bf16).
        np.testing.assert_allclose(
            np.asarray(chunked, dtype=np.float32),
            np.asarray(full, dtype=np.float32),
            rtol=3e-2, atol=3e-2,
        )

    def test_indivisible_chunks_rejected(self):
        pm, x, t, ctx, kwargs = self._setup(6)
        with pytest.raises(ValueError, match="not divisible"):
            bench._make_step(pm, 6, 4, t, ctx, kwargs)

    def test_bench_chunked_rungs_divide_evenly(self):
        # The declared ladder chunk counts (zimage_21: 3x7, flux_16_int8: 4x4)
        # must divide their batches — checked without building the 12 GiB
        # models by reading the rung declarations.
        assert 21 % 3 == 0 and 16 % 4 == 0

    def test_flux_stream_rung_registered(self):
        # The weight-streaming flagship rung (weights exceed usable HBM —
        # the round-5 finding that left the north-star blank) must be a real
        # rung the watchdog knows.
        assert "flux_stream" in bench._RUNGS
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "tpu_watchdog_mod2",
            os.path.join(os.path.dirname(bench.__file__), "scripts",
                         "tpu_watchdog.py"),
        )
        wd = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(wd)
        assert "flux_stream" in wd.RUNGS

    def test_zimage_int8_fallback_rung_registered(self):
        # The int8-weight headline fallback (bf16 zimage_21 exceeds the
        # tunnel chip's usable HBM even fully sequential — BASELINE_measured
        # evidence) must be a real rung, and the watchdog must know it and
        # its microbatch ladder.
        assert "zimage_21_int8" in bench._RUNGS
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "tpu_watchdog_mod",
            os.path.join(os.path.dirname(bench.__file__), "scripts",
                         "tpu_watchdog.py"),
        )
        wd = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(wd)
        assert "zimage_21_int8" in wd.RUNGS
        assert wd._MB_LADDERS["zimage_21_int8"][0] == 3


def test_flux_stream_rung_rehearsed_off_hardware(tmp_path):
    """The flux_stream run path end to end in a subprocess — tiny workload,
    fake evidence dir, small stream budget so the carve produces real stages
    (the round-3 lesson: never let a code path execute first on an unattended
    live tunnel). Must emit exactly one JSON line with the streaming rung's
    label, the microbatched step, and non-null FLOPs wiring."""
    import json
    import os
    import re
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    ).strip()
    env["PA_BENCH_TINY"] = "1"
    env["PA_EVIDENCE_DIR"] = str(tmp_path)
    # Hermetic compile cache: never touch (or depend on) the machine-global
    # ~/.cache dir, and pin the min-compile-time write threshold to 0 so the
    # cold cache records a miss for every tiny program regardless of host
    # speed — the hit/miss assertion below needs at least one event.
    env["PA_TPU_COMPILE_CACHE"] = str(tmp_path / "xla-cache")
    env["PA_COMPILE_CACHE_MIN_S"] = "0"
    env["PA_STREAM_HBM_BUDGET"] = "400000"  # tiny → forces a multi-stage carve
    env["BENCH_CONFIG"] = "flux_stream"
    repo = os.path.dirname(bench.__file__)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--inner"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "sec/it denoise step [flux_stream]"
    assert rec["model_flops_per_step"], "MFU wiring must be non-null"
    assert rec["microbatch_chunks"] == 2  # tiny rungs declare 2 chunks
    assert rec["dryrun"] is True
    # The streaming executor actually served the run (stderr carries the
    # placement log with the stage count).
    assert "weight streaming enabled" in proc.stderr
    # Resource accounting (round 9, utils/telemetry.py): every fresh line
    # carries compile + HBM accounting, and the run appended a ledger record.
    assert rec["compile_time_s"] > 0
    assert rec["compile_cache_hits"] + rec["compile_cache_misses"] > 0
    assert rec["peak_hbm_bytes"] > 0
    ledger = os.path.join(str(tmp_path), "ledger", "perf_ledger.jsonl")
    assert os.path.exists(ledger)
    lrec = json.loads(open(ledger).read().strip().splitlines()[-1])
    assert lrec["kind"] == "bench" and lrec["rung"] == "flux_stream"
    assert lrec["schema"] == "pa-perf-ledger/v1"


class TestStaleRecordFallback:
    """bench.py's wedged-tunnel fallback (VERDICT r5 weak-1/next-4): when no
    fresh TPU run is possible, the most recent banked TPU record re-emits
    with ``"stale": true`` + its capture timestamp instead of a meaningless
    CPU smoke — still exactly one JSON line."""

    def _seed(self, tmp_path, records):
        import json
        import os

        path = os.path.join(str(tmp_path), "BASELINE_measured.json")
        with open(path, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        return path

    def test_stale_record_selection(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path))
        self._seed(tmp_path, [
            {"rung": "sd15_16", "platform": "tpu", "value": 2.6, "ts": 10.0},
            {"rung": "sd15_16", "platform": "tpu", "value": 2.5, "ts": 20.0},
            {"rung": "sdxl_8", "platform": "tpu", "value": 0.6, "ts": 30.0},
            # Never eligible: invalid, dryrun, already-stale, CPU records.
            {"rung": "sd15_16", "platform": "tpu", "value": 0.1, "ts": 40.0,
             "invalid": "timing artifact"},
            {"rung": "zimage_21", "platform": "tpu", "value": 1.0, "ts": 50.0,
             "dryrun": True},
            {"rung": "sd15_16", "platform": "tpu", "value": 9.9, "ts": 60.0,
             "stale": True},
            {"rung": "smoke", "platform": "cpu", "value": 5.0, "ts": 70.0},
        ])
        # Requested rung wins over globally-newer other-rung records.
        rec = bench._stale_tpu_record("sd15_16")
        assert rec["value"] == 2.5 and rec["ts"] == 20.0
        # No record for the requested rung → most recent valid TPU record.
        rec = bench._stale_tpu_record("wan_video")
        assert rec["rung"] == "sdxl_8"
        # Nothing banked at all → None (the CPU smoke remains the fallback).
        monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path / "empty"))
        assert bench._stale_tpu_record("sd15_16") is None

    def test_orchestrate_emits_stale_line_when_probe_fails(self, tmp_path):
        """Full outer bench.py run in a CPU-only env: the probe reports
        not-TPU, and the banked record re-emits as ONE stale JSON line —
        without ever building a model (fast)."""
        import json
        import os
        import re
        import subprocess
        import sys

        self._seed(tmp_path, [
            {"metric": "sec/it denoise step [sd15_16]", "rung": "sd15_16",
             "platform": "tpu", "value": 2.57, "unit": "s/it", "ts": 123.0},
        ])
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            env.get("XLA_FLAGS", ""),
        ).strip()
        env["PA_EVIDENCE_DIR"] = str(tmp_path)
        env["BENCH_CONFIG"] = "sd15_16"
        repo = os.path.dirname(bench.__file__)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py")],
            env=env, cwd=repo, capture_output=True, text=True, timeout=420,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
        assert len(lines) == 1, f"exactly one JSON line required: {lines}"
        rec = json.loads(lines[0])
        assert rec["stale"] is True
        assert rec["platform"] == "tpu" and rec["value"] == 2.57
        assert rec["captured_ts"] == 123.0
        assert "stale_reason" in rec
        # A record banked before round 9 predates the resource-accounting
        # fields: the stale re-emit carries them as nulls, never absent.
        for field in ("compile_time_s", "compile_cache_hits",
                      "compile_cache_misses", "peak_hbm_bytes"):
            assert field in rec and rec[field] is None
