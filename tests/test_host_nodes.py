"""Host-layer nodes: the ComfyUI core-graph equivalents this framework supplies
standalone (the reference relies on its host for all of these — SURVEY §2g).

The headline test wires the full workflow node-for-node:
TextEncode ×2 → ParallelAnything(model) → KSampler → VAEDecode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models import (
    CLIPTextConfig,
    VAEConfig,
    build_clip_text,
    build_unet,
    build_vae,
    sd15_config,
)
from comfyui_parallelanything_tpu.nodes import (
    NODE_CLASS_MAPPINGS,
    NODE_DISPLAY_NAME_MAPPINGS,
    ParallelAnything,
    ParallelDevice,
    TPUConditioningCombine,
    TPUEmptyLatent,
    TPUKSampler,
    TPUTextEncode,
    TPUVAEDecode,
)

from test_tokenizer import _tiny_tokenizer


@pytest.fixture(scope="module")
def graph_parts():
    tok = _tiny_tokenizer()
    ccfg = CLIPTextConfig(
        vocab_size=64, hidden_size=48, num_layers=2, num_heads=4, max_len=8,
        eos_id=tok.eos_id, dtype=jnp.float32,
    )
    ucfg = sd15_config(
        model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
        attention_levels=(0, 1), context_dim=48, num_heads=4, norm_groups=8,
        dtype=jnp.float32,
    )
    vcfg = VAEConfig(
        z_channels=4, base_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        norm_groups=8, dtype=jnp.float32,
    )
    clip_wire = {
        "encoder": build_clip_text(ccfg, jax.random.key(0)),
        "tokenizer": tok,
        "type": "clip-l",
    }
    model = build_unet(ucfg, jax.random.key(1), sample_shape=(1, 8, 8, 4))
    vae = build_vae(vcfg, jax.random.key(2), sample_hw=16)
    return clip_wire, model, vae


class TestConditioningCombine:
    def test_sdxl_mode_assembles_2048_context_and_2816_pooled(self):
        a = {"context": jnp.zeros((1, 8, 768)), "penultimate": jnp.zeros((1, 8, 768)),
             "pooled": jnp.zeros((1, 768))}
        b = {"context": jnp.zeros((1, 8, 1280)), "penultimate": jnp.zeros((1, 8, 1280)),
             "pooled": jnp.zeros((1, 1280))}
        (cond,) = TPUConditioningCombine().combine(a, b, "sdxl", width=1024, height=1024)
        assert cond["context"].shape == (1, 8, 2048)
        assert cond["pooled"].shape == (1, 2816)

    def test_flux_mode_merges_t5_context_with_clip_pooled(self):
        t5 = {"context": jnp.zeros((1, 32, 64)), "pooled": None}
        clip = {"context": jnp.zeros((1, 8, 48)), "pooled": jnp.zeros((1, 16))}
        (cond,) = TPUConditioningCombine().combine(t5, clip, "flux")
        assert cond["context"].shape == (1, 32, 64)
        assert cond["pooled"].shape == (1, 16)

    def test_missing_towers_rejected(self):
        t5 = {"context": jnp.zeros((1, 32, 64)), "pooled": None}
        with pytest.raises(ValueError, match="flux mode"):
            TPUConditioningCombine().combine(t5, t5, "flux")
        with pytest.raises(ValueError, match="sdxl mode"):
            TPUConditioningCombine().combine(t5, t5, "sdxl")


class TestRegistration:
    def test_all_nodes_registered_with_display_names(self):
        assert set(NODE_CLASS_MAPPINGS) == set(NODE_DISPLAY_NAME_MAPPINGS)
        for name, cls in NODE_CLASS_MAPPINGS.items():
            assert hasattr(cls, "INPUT_TYPES") and hasattr(cls, "FUNCTION"), name
            assert hasattr(cls, "RETURN_TYPES"), name
            # FUNCTION names a real method (the host calls it via getattr).
            assert callable(getattr(cls, cls.FUNCTION, None)), name

    def test_host_nodes_present(self):
        for key in ("TPUCheckpointLoader", "TPUCLIPLoader", "TPUTextEncode",
                    "TPUEmptyLatent", "TPUKSampler", "TPUVAEDecode"):
            assert key in NODE_CLASS_MAPPINGS


class TestFullNodeGraph:
    def test_workflow_text_to_image(self, graph_parts):
        clip_wire, model, vae = graph_parts

        # CLIPTextEncode x2 (positive / negative)
        (positive,) = TPUTextEncode().encode(clip_wire, "hello world")
        (negative,) = TPUTextEncode().encode(clip_wire, "world")
        assert positive["context"].shape == (1, 8, 48)

        # ParallelDevice -> ParallelAnything (the reference's own node path)
        (chain,) = ParallelDevice().add_device("cpu:0", 50.0)
        (chain,) = ParallelDevice().add_device("cpu:1", 50.0, chain)
        (pmodel,) = ParallelAnything().setup_parallel(model, chain)

        # EmptyLatent -> KSampler -> VAEDecode
        (latent,) = TPUEmptyLatent().generate(width=16, height=16, batch_size=1)
        assert latent["samples"].shape == (1, 2, 2, 4)
        (latent,) = TPUEmptyLatent().generate(width=128, height=128, batch_size=2)
        (sampled,) = TPUKSampler().sample(
            pmodel, positive, latent, seed=3, steps=2, cfg=4.0,
            sampler_name="dpmpp_2m", negative=negative,
        )
        assert sampled["samples"].shape == latent["samples"].shape
        (image,) = TPUVAEDecode().decode(vae, sampled)
        a = np.asarray(image)
        assert a.shape == (2, 32, 32, 3)
        assert np.isfinite(a).all() and a.min() >= 0.0 and a.max() <= 1.0

    def test_clip_skip_selects_layer(self, graph_parts):
        # Host CLIPSetLastLayer semantics: 1 = final layer, 2 = penultimate,
        # 0 = model default.
        clip_wire, _, _ = graph_parts
        (default,) = TPUTextEncode().encode(clip_wire, "hello")
        (final,) = TPUTextEncode().encode(clip_wire, "hello", clip_skip=1)
        (pen,) = TPUTextEncode().encode(clip_wire, "hello", clip_skip=2)
        np.testing.assert_array_equal(
            np.asarray(final["context"]), np.asarray(default["context"])
        )  # CLIP-L default == final layer
        np.testing.assert_array_equal(
            np.asarray(pen["context"]), np.asarray(default["penultimate"])
        )
        assert not np.allclose(
            np.asarray(final["context"]), np.asarray(pen["context"])
        )

    def test_ksampler_ddim_and_no_negative(self, graph_parts):
        clip_wire, model, _ = graph_parts
        (positive,) = TPUTextEncode().encode(clip_wire, "hello")
        (latent,) = TPUEmptyLatent().generate(width=64, height=64, batch_size=1)
        (out,) = TPUKSampler().sample(
            model, positive, latent, seed=0, steps=1, cfg=1.0, sampler_name="ddim",
        )
        assert out["samples"].shape == (1, 8, 8, 4)

    def test_vae_decode_tiled_path(self, graph_parts):
        _, _, vae = graph_parts
        latent = {"samples": jax.random.normal(jax.random.key(5), (1, 24, 24, 4))}
        (img,) = TPUVAEDecode().decode(vae, latent, tile_size=16)
        assert np.asarray(img).shape == (1, 48, 48, 3)

    def test_conditioning_batch_must_divide(self, graph_parts):
        clip_wire, model, _ = graph_parts
        (pos,) = TPUTextEncode().encode(clip_wire, "hello")
        pos = {**pos, "context": jnp.concatenate([pos["context"]] * 2)}
        (latent,) = TPUEmptyLatent().generate(width=64, height=64, batch_size=3)
        with pytest.raises(ValueError, match="does not divide"):
            TPUKSampler().sample(
                model, pos, latent, seed=0, steps=1, cfg=1.0, sampler_name="euler"
            )

    def test_seed_determinism(self, graph_parts):
        clip_wire, model, _ = graph_parts
        (positive,) = TPUTextEncode().encode(clip_wire, "hello")
        (latent,) = TPUEmptyLatent().generate(width=64, height=64, batch_size=1)
        kw = dict(seed=7, steps=1, cfg=1.0, sampler_name="euler")
        (a,) = TPUKSampler().sample(model, positive, latent, **kw)
        (b,) = TPUKSampler().sample(model, positive, latent, **kw)
        np.testing.assert_array_equal(np.asarray(a["samples"]), np.asarray(b["samples"]))


class TestCustomSamplingGraph:
    """The host's custom-sampling node family (RandomNoise / KSamplerSelect /
    BasicScheduler / guiders / SamplerCustomAdvanced) — the graph exported
    FLUX workflows use instead of the one-box KSampler."""

    def test_wire_objects(self, graph_parts):
        from comfyui_parallelanything_tpu.nodes import (
            TPUBasicGuider,
            TPUBasicScheduler,
            TPUCFGGuider,
            TPUFluxGuidance,
            TPUKSamplerSelect,
            TPURandomNoise,
        )

        clip_wire, model, _ = graph_parts
        (noise,) = TPURandomNoise().get_noise(42)
        assert noise == {"seed": 42}
        (samp,) = TPUKSamplerSelect().get_sampler("euler")
        assert samp == {"sampler": "euler"}
        (sig,) = TPUBasicScheduler().get_sigmas(model, "normal", 6, 1.0)
        s = np.asarray(sig)
        assert len(s) == 7 and (np.diff(s) < 0).all() and s[-1] == 0.0
        # denoise < 1 truncates to the last steps+1 of a longer ladder.
        (sig_d,) = TPUBasicScheduler().get_sigmas(model, "normal", 6, 0.5)
        assert len(np.asarray(sig_d)) == 7
        assert float(np.asarray(sig_d)[0]) < float(s[0])

        (cond,) = TPUTextEncode().encode(clip_wire, "hello")
        (tagged,) = TPUFluxGuidance().append(cond, 4.0)
        assert tagged["guidance"] == 4.0 and "context" in tagged
        (g1,) = TPUBasicGuider().get_guider(model, cond)
        assert g1["cfg"] == 1.0 and g1["negative"] is None
        (g2,) = TPUCFGGuider().get_guider(model, cond, cond, 6.0)
        assert g2["cfg"] == 6.0 and g2["negative"] is not None

    def test_full_custom_graph_matches_ksampler(self, graph_parts):
        # SamplerCustomAdvanced with BasicScheduler sigmas must reproduce the
        # one-box KSampler run with the same seed/scheduler/steps.
        from comfyui_parallelanything_tpu.nodes import (
            TPUBasicScheduler,
            TPUCFGGuider,
            TPUKSamplerSelect,
            TPURandomNoise,
            TPUSamplerCustomAdvanced,
        )

        clip_wire, model, _ = graph_parts
        (pos,) = TPUTextEncode().encode(clip_wire, "hello world")
        (neg,) = TPUTextEncode().encode(clip_wire, "world")
        (latent,) = TPUEmptyLatent().generate(width=64, height=64, batch_size=2)

        (noise,) = TPURandomNoise().get_noise(9)
        (samp,) = TPUKSamplerSelect().get_sampler("dpmpp_2m")
        (sig,) = TPUBasicScheduler().get_sigmas(model, "karras", 3, 1.0)
        (guider,) = TPUCFGGuider().get_guider(model, pos, neg, 4.0)
        out, den = TPUSamplerCustomAdvanced().sample(noise, guider, samp, sig, latent)
        np.testing.assert_array_equal(
            np.asarray(out["samples"]), np.asarray(den["samples"])
        )
        (ref,) = TPUKSampler().sample(
            model, pos, latent, seed=9, steps=3, cfg=4.0,
            sampler_name="dpmpp_2m", negative=neg, scheduler="karras",
        )
        np.testing.assert_allclose(
            np.asarray(out["samples"]), np.asarray(ref["samples"]),
            rtol=1e-5, atol=1e-5,
        )

    def test_img2img_via_truncated_sigmas(self, graph_parts):
        # A non-zero latent + truncated ladder is img2img by construction
        # (host noise_scaling semantics) — output should stay nearer the init
        # than a full-strength run does.
        from comfyui_parallelanything_tpu.nodes import (
            TPUBasicGuider,
            TPUBasicScheduler,
            TPUKSamplerSelect,
            TPURandomNoise,
            TPUSamplerCustomAdvanced,
        )

        clip_wire, model, _ = graph_parts
        (pos,) = TPUTextEncode().encode(clip_wire, "hello")
        init = {"samples": jnp.full((1, 8, 8, 4), 2.0)}
        (noise,) = TPURandomNoise().get_noise(1)
        (samp,) = TPUKSamplerSelect().get_sampler("euler")
        (guider,) = TPUBasicGuider().get_guider(model, pos)
        (sig_full,) = TPUBasicScheduler().get_sigmas(model, "normal", 4, 1.0)
        (sig_trunc,) = TPUBasicScheduler().get_sigmas(model, "normal", 4, 0.3)
        full, _ = TPUSamplerCustomAdvanced().sample(noise, guider, samp, sig_full, init)
        weak, _ = TPUSamplerCustomAdvanced().sample(noise, guider, samp, sig_trunc, init)
        d_full = float(jnp.abs(full["samples"] - init["samples"]).mean())
        d_weak = float(jnp.abs(weak["samples"] - init["samples"]).mean())
        assert d_weak < d_full


class TestSplitSigmaStages:
    """SplitSigmas + DisableNoise: two-stage sampling must reproduce the
    unsplit run EXACTLY for deterministic samplers — eps via identity
    noise_scaling continuation, flow via the host's inverse_noise_scaling
    round-trip on the partial output."""

    def _stages(self, model, pos, latent, sigmas, split_at):
        from comfyui_parallelanything_tpu.nodes import (
            TPUBasicGuider,
            TPUDisableNoise,
            TPUKSamplerSelect,
            TPURandomNoise,
            TPUSamplerCustomAdvanced,
            TPUSplitSigmas,
        )

        (guider,) = TPUBasicGuider().get_guider(model, pos)
        (samp,) = TPUKSamplerSelect().get_sampler("euler")
        (noise,) = TPURandomNoise().get_noise(5)
        (no_noise,) = TPUDisableNoise().get_noise()
        full, _ = TPUSamplerCustomAdvanced().sample(noise, guider, samp,
                                                    sigmas, latent)
        high, low = TPUSplitSigmas().split(sigmas, split_at)
        mid, _ = TPUSamplerCustomAdvanced().sample(noise, guider, samp,
                                                   high, latent)
        out, _ = TPUSamplerCustomAdvanced().sample(no_noise, guider, samp,
                                                   low, mid)
        return full, out

    def test_eps_two_stage_equals_full(self, graph_parts):
        from comfyui_parallelanything_tpu.nodes import TPUBasicScheduler

        clip_wire, model, _ = graph_parts
        (pos,) = TPUTextEncode().encode(clip_wire, "hello")
        (latent,) = TPUEmptyLatent().generate(width=64, height=64, batch_size=1)
        (sig,) = TPUBasicScheduler().get_sigmas(model, "normal", 4, 1.0)
        full, out = self._stages(model, pos, latent, sig, 2)
        np.testing.assert_allclose(
            np.asarray(full["samples"]), np.asarray(out["samples"]),
            rtol=1e-5, atol=1e-6,
        )

    def test_flow_two_stage_equals_full(self):
        from comfyui_parallelanything_tpu.models import build_flux, flux_dev_config
        from comfyui_parallelanything_tpu.nodes import TPUBasicScheduler

        cfg = flux_dev_config(depth=1, depth_single_blocks=1, hidden_size=128,
                              num_heads=1, context_in_dim=32, vec_in_dim=16,
                              dtype=jnp.float32)
        model = build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 16),
                           txt_len=6)
        pos = {"context": jax.random.normal(jax.random.key(3), (1, 6, 32)),
               "pooled": jnp.zeros((1, 16))}
        latent = {"samples": jnp.zeros((1, 8, 8, 16))}
        (sig,) = TPUBasicScheduler().get_sigmas(model, "normal", 4, 1.0)
        full, out = self._stages(model, pos, latent, sig, 2)
        np.testing.assert_allclose(
            np.asarray(full["samples"]), np.asarray(out["samples"]),
            rtol=1e-4, atol=1e-5,
        )

    def test_flip_sigmas(self):
        from comfyui_parallelanything_tpu.nodes import TPUFlipSigmas

        sig = jnp.asarray([1.0, 0.5, 0.2, 0.0])
        (flipped,) = TPUFlipSigmas().flip(sig)
        f = np.asarray(flipped)
        assert f[0] == pytest.approx(1e-4)  # zero start bumped
        np.testing.assert_allclose(f[1:], [0.2, 0.5, 1.0])

    def test_flip_preserves_small_nonzero_start(self):
        from comfyui_parallelanything_tpu.nodes import TPUFlipSigmas

        sig = jnp.asarray([1.0, 0.5, 5e-5])
        (flipped,) = TPUFlipSigmas().flip(sig)
        assert np.asarray(flipped)[0] == pytest.approx(5e-5)

    def test_flow_partial_run_to_sigma_one_rejected(self):
        # A flow ladder ending AT 1.0 (pure noise) has no inverse noise
        # scaling; the node rejects instead of emitting inf like the host.
        from comfyui_parallelanything_tpu.models import build_flux, flux_dev_config
        from comfyui_parallelanything_tpu.nodes import (
            TPUBasicGuider,
            TPUKSamplerSelect,
            TPURandomNoise,
            TPUSamplerCustomAdvanced,
        )

        cfg = flux_dev_config(depth=1, depth_single_blocks=1, hidden_size=128,
                              num_heads=1, context_in_dim=32, vec_in_dim=16,
                              dtype=jnp.float32)
        model = build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 16),
                           txt_len=6)
        pos = {"context": jax.random.normal(jax.random.key(3), (1, 6, 32)),
               "pooled": jnp.zeros((1, 16))}
        latent = {"samples": jnp.zeros((1, 8, 8, 16))}
        (guider,) = TPUBasicGuider().get_guider(model, pos)
        (samp,) = TPUKSamplerSelect().get_sampler("euler")
        (noise,) = TPURandomNoise().get_noise(1)
        bad = jnp.asarray([1.0, 1.0])  # degenerate: ends at pure noise
        with pytest.raises(ValueError, match="pure noise"):
            TPUSamplerCustomAdvanced().sample(noise, guider, samp, bad, latent)
