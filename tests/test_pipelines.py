"""End-to-end pipelines: prompt → image on tiny models over the 8-device mesh.

Exercises the whole standalone stack the reference delegates to its host app —
tokenize, text-encode, per-step parallel denoise, VAE decode — including shape,
determinism, CFG batching, and sampler dispatch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import comfyui_parallelanything_tpu as pa
from comfyui_parallelanything_tpu.models import (
    CLIPTextConfig,
    T5Config,
    VAEConfig,
    build_clip_text,
    build_flux,
    build_t5_encoder,
    build_unet,
    build_vae,
    sd15_config,
)
from comfyui_parallelanything_tpu.models.flux import FluxConfig
from comfyui_parallelanything_tpu.pipelines import FluxPipeline, StableDiffusionPipeline

from test_tokenizer import _tiny_tokenizer


@pytest.fixture(scope="module")
def sd_pipe():
    tok = _tiny_tokenizer()
    ccfg = CLIPTextConfig(
        vocab_size=64, hidden_size=48, num_layers=2, num_heads=4, max_len=8,
        eos_id=tok.eos_id, dtype=jnp.float32,
    )
    ucfg = sd15_config(
        model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
        attention_levels=(0, 1), context_dim=48, num_heads=4, norm_groups=8,
        dtype=jnp.float32,
    )
    vcfg = VAEConfig(
        z_channels=4, base_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        norm_groups=8, dtype=jnp.float32,
    )
    return StableDiffusionPipeline(
        unet=build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4)),
        vae=build_vae(vcfg, jax.random.key(1), sample_hw=16),
        clip=build_clip_text(ccfg, jax.random.key(2)),
        tokenizer=tok,
    )


class TestStableDiffusionPipeline:
    def test_prompt_to_image_shape_and_range(self, sd_pipe):
        img = sd_pipe("hello world", steps=2, cfg_scale=1.0, height=16, width=16)
        assert img.shape == (1, 16, 16, 3)
        a = np.asarray(img)
        assert np.isfinite(a).all() and a.min() >= 0.0 and a.max() <= 1.0

    def test_deterministic_given_rng(self, sd_pipe):
        kw = dict(steps=2, cfg_scale=1.0, height=16, width=16, rng=jax.random.key(7))
        np.testing.assert_array_equal(
            np.asarray(sd_pipe("hello", **kw)), np.asarray(sd_pipe("hello", **kw))
        )

    def test_scheduler_menu_reaches_pipeline(self, sd_pipe):
        # The Python pipeline API exposes the same scheduler menu as the node
        # graph (shared run_sampler dispatch — they must not drift apart).
        kw = dict(steps=2, cfg_scale=1.0, height=16, width=16, rng=jax.random.key(7))
        base = np.asarray(sd_pipe("hello", scheduler="karras", **kw))
        sgm = np.asarray(sd_pipe("hello", scheduler="sgm_uniform", **kw))
        assert np.isfinite(sgm).all()
        assert not np.allclose(base, sgm)  # different sigma spacing, different image

    def test_cfg_changes_output(self, sd_pipe):
        kw = dict(steps=2, height=16, width=16, rng=jax.random.key(7))
        base = np.asarray(sd_pipe("hello", cfg_scale=1.0, **kw))
        cfg = np.asarray(
            sd_pipe("hello", negative_prompt="world", cfg_scale=5.0, **kw)
        )
        assert not np.allclose(base, cfg)

    @pytest.mark.parametrize("sampler", ["ddim", "euler", "dpmpp_2m", "heun"])
    def test_sampler_dispatch(self, sd_pipe, sampler):
        img = sd_pipe(
            "hello", steps=2, cfg_scale=1.0, height=16, width=16, sampler=sampler
        )
        assert img.shape == (1, 16, 16, 3)

    def test_euler_ancestral_uses_rng(self, sd_pipe):
        img = sd_pipe(
            "hello", steps=2, cfg_scale=1.0, height=16, width=16,
            sampler="euler_ancestral",
        )
        assert np.isfinite(np.asarray(img)).all()

    def test_unknown_sampler_rejected(self, sd_pipe):
        with pytest.raises(ValueError, match="unknown sampler"):
            sd_pipe("hello", sampler="nope", height=16, width=16)

    def test_bad_resolution_rejected(self, sd_pipe):
        with pytest.raises(ValueError, match="multiples"):
            sd_pipe("hello", height=15, width=16)

    def test_parallelized_unet_matches_single(self, sd_pipe):
        """The same pipeline with the UNet wrapped by parallelize must produce the
        same images — the parallel scheduler is transparency-tested end to end."""
        chain = pa.DeviceChain.even([f"cpu:{i}" for i in range(4)])
        punet = pa.parallelize(sd_pipe.unet, chain)
        ppipe = StableDiffusionPipeline(
            unet=punet, vae=sd_pipe.vae, clip=sd_pipe.clip, tokenizer=sd_pipe.tokenizer
        )
        kw = dict(
            steps=2, cfg_scale=3.0, negative_prompt="world",
            height=16, width=16, rng=jax.random.key(3),
        )
        want = np.asarray(sd_pipe(["hello", "world"], **kw))
        got = np.asarray(ppipe(["hello", "world"], **kw))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


class TestCFGKwargDoubling:
    def test_uncond_variant_rides_second_half(self):
        from comfyui_parallelanything_tpu.sampling.cfg import double_kwargs

        y = jnp.arange(4.0).reshape(2, 2)
        uy = -y
        out = double_kwargs({"y": y, "flag": 3}, {"y": uy}, batch=2)
        np.testing.assert_array_equal(
            np.asarray(out["y"]), np.concatenate([np.asarray(y), np.asarray(uy)])
        )
        assert out["flag"] == 3

    def test_missing_uncond_duplicates_cond(self):
        from comfyui_parallelanything_tpu.sampling.cfg import double_kwargs

        y = jnp.ones((2, 3))
        out = double_kwargs({"y": y}, None, batch=2)
        assert out["y"].shape == (4, 3)


class TestSDXLStylePipeline:
    def test_negative_pooled_feeds_uncond_half(self, sd_pipe):
        """SDXL semantics: the uncond half of the CFG batch must be conditioned on
        the NEGATIVE prompt's pooled vector. Checked via a recording model."""
        tok = _tiny_tokenizer()
        ccfg = CLIPTextConfig(
            vocab_size=64, hidden_size=48, num_layers=2, num_heads=4, max_len=8,
            eos_id=tok.eos_id, dtype=jnp.float32,
        )
        gcfg = CLIPTextConfig(
            vocab_size=64, hidden_size=48, num_layers=2, num_heads=4, max_len=8,
            eos_id=tok.eos_id, projection_dim=16, act="gelu", dtype=jnp.float32,
        )
        clip_l = build_clip_text(ccfg, jax.random.key(0))
        clip_g = build_clip_text(gcfg, jax.random.key(1))
        seen = {}

        def recording_unet(x, t, context, y=None, **kw):
            seen["y"] = y
            return jnp.zeros_like(x)

        pipe = StableDiffusionPipeline(
            unet=recording_unet, vae=sd_pipe.vae, clip=clip_l, tokenizer=tok,
            clip_g=clip_g,
        )
        pipe("hello", negative_prompt="world", steps=1, cfg_scale=5.0,
             height=16, width=16, sampler="ddim")
        y = np.asarray(seen["y"])
        assert y.shape[0] == 2  # cond ‖ uncond
        # Different prompts → different pooled halves (the old bug duplicated cond).
        assert not np.allclose(y[0], y[1])

    def test_negative_list_length_validated(self, sd_pipe):
        with pytest.raises(ValueError, match="negative_prompt"):
            sd_pipe(["a", "b"], negative_prompt=["n"], cfg_scale=5.0,
                    height=16, width=16)


class TestFluxPipeline:
    @pytest.fixture(scope="class")
    def flux_pipe(self):
        tok = _tiny_tokenizer()
        ccfg = CLIPTextConfig(
            vocab_size=64, hidden_size=48, num_layers=2, num_heads=4, max_len=8,
            eos_id=tok.eos_id, projection_dim=16, dtype=jnp.float32,
        )
        t5cfg = T5Config(
            vocab_size=64, d_model=32, num_layers=2, num_heads=4, d_kv=8, d_ff=64,
            dtype=jnp.float32,
        )
        # in_channels = vae z (16) x patch 2x2 = 64 (patchified token dim).
        fcfg = FluxConfig(
            in_channels=64, hidden_size=32, num_heads=2, depth=1,
            depth_single_blocks=1, context_in_dim=32, vec_in_dim=16,
            axes_dim=(4, 6, 6), guidance_embed=True, dtype=jnp.float32,
        )
        vcfg = VAEConfig(
            z_channels=16, base_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            norm_groups=8, use_quant_conv=False, dtype=jnp.float32,
        )
        return FluxPipeline(
            dit=build_flux(fcfg, jax.random.key(0), sample_shape=(1, 8, 8, 16), txt_len=8),
            vae=build_vae(vcfg, jax.random.key(1), sample_hw=16),
            clip=build_clip_text(ccfg, jax.random.key(2)),
            t5=build_t5_encoder(t5cfg, jax.random.key(3)),
            tokenizer=tok,
            t5_tokenizer=tok,
        )

    def test_prompt_to_image(self, flux_pipe):
        img = flux_pipe("hello world", steps=2, guidance=3.5, height=16, width=16)
        assert img.shape == (1, 16, 16, 3)
        assert np.isfinite(np.asarray(img)).all()

    def test_schnell_style_no_guidance(self, flux_pipe):
        img = flux_pipe("hello", steps=1, guidance=None, height=16, width=16)
        assert img.shape == (1, 16, 16, 3)

    def test_resolution_must_divide_vae_times_patch(self, flux_pipe):
        # unit = vae factor (2 for the tiny config) x patch 2 = 4
        with pytest.raises(ValueError, match="multiples"):
            flux_pipe("hello", steps=1, height=14, width=16)

    def test_true_cfg_with_negative(self, flux_pipe):
        img = flux_pipe(
            "hello", negative_prompt="world", cfg_scale=3.0, steps=1,
            guidance=None, height=16, width=16,
        )
        assert img.shape == (1, 16, 16, 3)
        assert np.isfinite(np.asarray(img)).all()
