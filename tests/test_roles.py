"""Disaggregated role pools (ROADMAP "role disaggregation"): stage carving
(host.carve_stages), pool plumbing (fleet/roles.py), the router's stage-aware
dispatch over a live encode / denoise / decode fleet, and the fixed-host-count
throughput comparison the role-pool CI smoke gates.

Reference behavior: every worker thread runs the WHOLE sampler — encode,
denoise, and decode execute on whatever device the thread was pinned to
(any_device_parallel.py:817-905) — so stages, pools, and hand-off handles are
all this port's addition and everything here asserts against fleet/roles.py's
own contracts.

The toy stage nodes model the one physical effect disaggregation exploits: a
host's HBM holds ONE stage's program + weights at a time (warm-LRU-of-1), so
running a different node class than the last run pays ``setup_s`` again.
Homogeneous hosts pay ~3 switches per prompt; role hosts pay one setup ever.
"""

import hashlib
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from comfyui_parallelanything_tpu.fleet import (
    FleetRegistry,
    PromptJournal,
    Scoreboard,
    StageStore,
    make_router,
    normalize_role,
    suggest_pool_split,
)
from comfyui_parallelanything_tpu.fleet import roles as fleet_roles
from comfyui_parallelanything_tpu.host import carve_stages
from comfyui_parallelanything_tpu.server import make_server
from comfyui_parallelanything_tpu.utils import tracing
from comfyui_parallelanything_tpu.utils.metrics import registry

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


# ---------------------------------------------------------------------------
# toy stage nodes
# ---------------------------------------------------------------------------


def _stage_nodes(tag: str, out_dir: str, setup_s: float = 0.0):
    """Per-backend stage node classes (the per-backend factory pattern
    scripts/chaos.py uses: tag + out_dir baked into the closure). Class
    names contain the carve substrings ("TextEncode" / "Sampler" /
    "Decode", host._intrinsic_stage) so carve_stages ranks them and the
    SLO stage histograms classify them.

    ``setup_s`` is the class-switch cost: the backend pays it whenever it
    runs a different node class than its LAST run (a warm-LRU-of-1 of
    program + weights in HBM) — the cost role pools amortize away."""
    state = {"warm": None}

    def _charge(name):
        if setup_s and state["warm"] != name:
            time.sleep(setup_s)
        state["warm"] = name

    class ToyTextEncode:
        CATEGORY = "roles-test"
        RETURN_TYPES = ("COND",)
        FUNCTION = "run"

        @classmethod
        def INPUT_TYPES(cls):
            return {"required": {"text": ("STRING", {"default": ""}),
                                 "work_s": ("FLOAT", {"default": 0.0})}}

        def run(self, text, work_s):
            _charge("encode")
            time.sleep(float(work_s))
            digest = hashlib.md5(str(text).encode()).digest()
            cond = np.frombuffer(digest, np.uint8).astype(np.float32)
            return (cond,)

    class ToySampler:
        CATEGORY = "roles-test"
        RETURN_TYPES = ("LATENT",)
        FUNCTION = "run"

        @classmethod
        def INPUT_TYPES(cls):
            return {"required": {"cond": ("COND",),
                                 "seed": ("INT", {"default": 0}),
                                 "work_s": ("FLOAT", {"default": 0.0})}}

        def run(self, cond, seed, work_s):
            _charge("denoise")
            time.sleep(float(work_s))
            rng = np.random.default_rng(int(seed))
            latent = np.tanh(
                rng.standard_normal(16).astype(np.float32)
                + np.asarray(cond, dtype=np.float32) / 255.0
            )
            return (latent.astype(np.float32),)

    class ToyDecode:
        CATEGORY = "roles-test"
        RETURN_TYPES = ("INT",)
        FUNCTION = "run"

        @classmethod
        def INPUT_TYPES(cls):
            return {"required": {"latent": ("LATENT",),
                                 "seed": ("INT", {"default": 0}),
                                 "work_s": ("FLOAT", {"default": 0.0})}}

        def run(self, latent, seed, work_s):
            _charge("decode")
            time.sleep(float(work_s))
            arr = np.asarray(latent, dtype=np.float32)
            os.makedirs(out_dir, exist_ok=True)
            np.save(os.path.join(out_dir, f"{int(seed)}-{tag}.npy"), arr)
            return (int(abs(float(arr.sum())) * 1e6) & 0x7FFFFFFF,)

    return {"ToyTextEncode": ToyTextEncode, "ToySampler": ToySampler,
            "ToyDecode": ToyDecode}


def _sgraph(seed, text="a castle", enc_s=0.0, den_s=0.0, dec_s=0.0):
    """The canonical 3-stage workflow: TextEncode → Sampler → Decode."""
    return {
        "1": {"class_type": "ToyTextEncode",
              "inputs": {"text": str(text), "work_s": enc_s}},
        "2": {"class_type": "ToySampler",
              "inputs": {"cond": ["1", 0], "seed": int(seed),
                         "work_s": den_s}},
        "3": {"class_type": "ToyDecode",
              "inputs": {"latent": ["2", 0], "seed": int(seed),
                         "work_s": dec_s}},
    }


# ---------------------------------------------------------------------------
# HTTP helpers (test_fleet.py's, duplicated to keep this module standalone)
# ---------------------------------------------------------------------------


def _get(base, path, timeout=15):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _post(base, path, payload=None, timeout=15):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _wait(pred, timeout=20, interval=0.02, what="condition"):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return
        time.sleep(interval)
    raise TimeoutError(f"never saw: {what}")


def _wait_entry(base, pid, timeout=30):
    out = {}

    def have():
        hist = _get(base, f"/history/{pid}")
        if pid in hist:
            out["entry"] = hist[pid]
            return True
        return False

    _wait(have, timeout=timeout, what=f"history entry for {pid}")
    return out["entry"]


class _RoleBackend:
    """One in-process backend with a declared role and its own latent dump
    dir (the bitwise witness ToyDecode writes)."""

    def __init__(self, tmp_path, host_id, role="all", setup_s=0.0):
        self.out_dir = str(tmp_path / f"latents-{host_id}")
        self.srv, self.q = make_server(
            port=0, output_dir=str(tmp_path / host_id),
            class_mappings=_stage_nodes(host_id, self.out_dir, setup_s),
            host_id=host_id, role=role,
        )
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()
        self.base = f"http://127.0.0.1:{self.srv.server_address[1]}"
        self.host_id = host_id
        self.alive = True

    def kill(self):
        self.srv.shutdown()
        self.srv.server_close()
        self.q.interrupt()
        self.alive = False

    def stop(self):
        if self.alive:
            self.srv.shutdown()
            self.srv.server_close()
        self.q.shutdown()


def _mk_fleet(tmp_path, specs, setup_s=0.0, **router_kw):
    """(base, srv, router, backends) over ``specs = [(host_id, role), ...]``
    static seeds; waits for every backend healthy (and for role visibility
    when any spec declares one — roles ride the scoreboard's health poll
    for static seeds)."""
    backends = [_RoleBackend(tmp_path, hid, role, setup_s)
                for hid, role in specs]
    kw = dict(
        fleet_registry=FleetRegistry(ttl_s=5.0),
        scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0, fail_after=2,
                              timeout_s=2.0),
        saturation_depth=2, monitor_s=0.05, max_attempts=4,
    )
    kw.update(router_kw)
    srv, router = make_router(
        port=0, backends=[(b.host_id, b.base) for b in backends], **kw)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    _wait(lambda: all(router.scoreboard.healthy(b.host_id) for b in backends),
          what="backends healthy")
    if any(role != "all" for _, role in specs):
        _wait(lambda: router.roles.disaggregated(),
              what="declared roles visible to the router")
    return base, srv, router, backends


def _stop_fleet(srv, router, backends):
    srv.shutdown()
    srv.server_close()
    router.shutdown()
    for b in backends:
        b.stop()


# ---------------------------------------------------------------------------
# carve_stages
# ---------------------------------------------------------------------------


class TestCarveStages:
    def test_three_stage_carve(self):
        plan = carve_stages(_sgraph(1))
        assert plan is not None
        names = [s["stage"] for s in plan["stages"]]
        assert names == ["encode", "denoise", "decode"]
        enc, den, dec = plan["stages"]
        assert enc["nodes"] == ["1"]
        assert enc["needs"] == [] and enc["exports"] == ["1"]
        assert den["nodes"] == ["2"]
        assert den["needs"] == ["1"] and den["exports"] == ["2"]
        assert dec["nodes"] == ["3"]
        assert dec["needs"] == ["2"] and dec["exports"] == []
        # Each stage graph is the FULL upstream closure — a host holding no
        # handles recomputes the prefix locally, never errors.
        assert set(enc["graph"]) == {"1"}
        assert set(den["graph"]) == {"1", "2"}
        assert set(dec["graph"]) == {"1", "2", "3"}

    def test_neutral_node_inherits_max_ancestor_rank(self):
        g = _sgraph(2)
        # A save-ish neutral class after decode is decode work...
        g["4"] = {"class_type": "ToySave", "inputs": {"x": ["3", 0]}}
        plan = carve_stages(g)
        dec = plan["stages"][2]
        assert set(dec["nodes"]) == {"3", "4"}
        # ... and decode still only needs the denoise boundary handle.
        assert dec["needs"] == ["2"]

    def test_free_loader_rides_dependent_closures(self):
        g = _sgraph(3)
        # A loader with no ranked ancestor is FREE: it joins the closure of
        # every stage that (transitively) consumes it, members unchanged.
        g["0"] = {"class_type": "ToyLoader", "inputs": {}}
        g["2"]["inputs"]["model"] = ["0", 0]
        plan = carve_stages(g)
        enc, den, dec = plan["stages"]
        assert "0" not in enc["graph"]          # encode never consumes it
        assert "0" in den["graph"] and "0" in dec["graph"]
        for st in plan["stages"]:
            assert "0" not in st["nodes"]       # free, not a member
            assert "0" not in st["needs"]       # no handle for unranked ids

    def test_fewer_than_two_intrinsic_stages_no_carve(self):
        assert carve_stages({"1": {"class_type": "SleepWork",
                                   "inputs": {}}}) is None
        only_sampler = {"1": {"class_type": "ToySampler",
                              "inputs": {"seed": 1}}}
        assert carve_stages(only_sampler) is None

    def test_cycle_no_carve(self):
        g = {
            "1": {"class_type": "ToyTextEncode", "inputs": {"text": "x"}},
            "2": {"class_type": "ToySampler",
                  "inputs": {"cond": ["1", 0], "latent": ["3", 0]}},
            "3": {"class_type": "ToyDecode", "inputs": {"latent": ["2", 0]}},
        }
        assert carve_stages(g) is None

    def test_non_monotone_highres_fix_no_carve(self):
        # Decode feeding a SECOND sampler (highres fix): stage order runs
        # backwards along that edge — fall back to single dispatch.
        g = _sgraph(4)
        g["4"] = {"class_type": "ToySampler",
                  "inputs": {"cond": ["3", 0], "seed": 4, "work_s": 0.0}}
        assert carve_stages(g) is None

    def test_malformed_graph_no_carve(self):
        assert carve_stages(None) is None
        assert carve_stages({"1": "not-a-node"}) is None


# ---------------------------------------------------------------------------
# pool sizing + role normalization
# ---------------------------------------------------------------------------


class TestRolesPlumbing:
    def test_normalize_role(self):
        assert normalize_role(None) == "all"
        assert normalize_role("") == "all"
        assert normalize_role(" Denoise ") == "denoise"
        with pytest.raises(ValueError):
            normalize_role("dencode")

    def test_suggest_pool_split_canonical_four(self):
        # The shape the e2e fleet below deploys: denoise dominates.
        assert suggest_pool_split(4) == {
            "encode": 1, "denoise": 2, "decode": 1,
        }

    def test_suggest_pool_split_sums_and_floors(self):
        for n in range(0, 12):
            split = suggest_pool_split(n)
            assert sum(split.values()) == n
            assert all(v >= 0 for v in split.values())
            if n >= 3:
                # A zero-sized pool would silently un-disaggregate a stage.
                assert all(v >= 1 for v in split.values()), (n, split)

    def test_suggest_pool_split_follows_measured_stage_p50s(self):
        heavy_decode = suggest_pool_split(
            8, stage_p50s={"encode": 0.01, "eval": 0.05, "decode": 0.60})
        assert heavy_decode["decode"] > suggest_pool_split(8)["decode"]


# ---------------------------------------------------------------------------
# content-addressed stage store
# ---------------------------------------------------------------------------


class TestStageStore:
    def test_roundtrip_and_content_address(self):
        store = StageStore(max_bytes=1 << 20)
        val = (np.arange(6, dtype=np.float32), "meta", 3)
        key = store.put_value(val)
        assert key == fleet_roles.content_key(
            fleet_roles.serialize_value(val))
        got = store.get_value(key)
        assert isinstance(got, tuple)
        assert (got[0] == val[0]).all() and got[1:] == ("meta", 3)
        # Content-addressed: the same value re-inserted keeps one entry.
        assert store.put_value(val) == key
        assert store.stats()["entries"] == 1

    def test_lru_eviction_is_byte_bounded(self):
        store = StageStore(max_bytes=250)
        k1 = store.put(b"a" * 100)
        k2 = store.put(b"b" * 100)
        assert store.get(k1) is not None      # touch k1 → k2 becomes LRU
        k3 = store.put(b"c" * 100)            # 300 > 250: evicts k2
        assert store.get(k2) is None
        assert store.get(k1) is not None and store.get(k3) is not None
        assert store.stats()["bytes"] <= 250
        assert store.evictions == 1

    def test_oversized_blob_hashed_not_retained(self):
        store = StageStore(max_bytes=10)
        blob = b"z" * 100
        key = store.put(blob)
        assert key == fleet_roles.content_key(blob)
        assert store.get(key) is None

    def test_zero_budget_disables_the_store(self):
        off = StageStore(max_bytes=0)
        assert not off.enabled
        assert off.get(off.put(b"ab")) is None

    def test_unpicklable_value_skips_the_handle(self):
        store = StageStore(max_bytes=1 << 20)
        assert store.put_value((threading.Lock(),)) is None


# ---------------------------------------------------------------------------
# journal stage lineage (fold-level; the live path is exercised below and in
# tests/test_fleet.py's decode-kill replay)
# ---------------------------------------------------------------------------


class TestJournalStageLineage:
    def test_fold_accumulates_stage_lineage(self, tmp_path):
        j = PromptJournal(str(tmp_path / "j.jsonl"))
        j.append("submit", "p1", graph=_sgraph(1), key="k", number=1)
        j.append("dispatch", "p1", host="enc-0", backend_pid="b1",
                 attempt=1, stage="encode", stage_idx=0)
        j.append("stage_resolve", "p1", stage="encode", stage_idx=0,
                 host="enc-0", handles={"1": "c0ffee"})
        j.append("stage_dispatch", "p1", host="den-0", backend_pid="b2",
                 attempt=1, stage="denoise", stage_idx=1)
        st = j.replay()["p1"]
        assert st["phase"] == "dispatch"
        assert st["stage"] == "denoise" and st["stage_idx"] == 1
        assert st["host"] == "den-0" and st["backend_pid"] == "b2"
        # The lineage a standby resumes from: resolved stages + handles.
        assert st["stages"] == [{"stage": "encode", "stage_idx": 0,
                                 "host": "enc-0",
                                 "handles": {"1": "c0ffee"}}]


# ---------------------------------------------------------------------------
# live role-pool fleet: staged dispatch end to end
# ---------------------------------------------------------------------------

_SPECS = [("enc-0", "encode"), ("den-0", "denoise"),
          ("den-1", "denoise"), ("dec-0", "decode")]


@pytest.fixture
def role_fleet(tmp_path):
    """1 encode + 2 denoise + 1 decode — suggest_pool_split(4)'s shape."""
    fleet_roles.store.clear()
    base, srv, router, backends = _mk_fleet(tmp_path, _SPECS)
    yield base, router, backends
    _stop_fleet(srv, router, backends)
    fleet_roles.store.clear()


class TestRolePoolDispatch:
    def test_staged_prompt_walks_the_pools(self, role_fleet):
        base, router, backends = role_fleet
        pid = _post(base, "/prompt", {"prompt": _sgraph(5)})["prompt_id"]
        entry = _wait_entry(base, pid)
        assert entry["status"]["status_str"] == "success"
        fp = router.prompts[pid]
        assert fp.plan is not None and fp.stage_idx == 2
        # Every hop landed in its stage's pool.
        assert fp.stage_hosts[0] == "enc-0"
        assert fp.stage_hosts[1] in ("den-0", "den-1")
        assert entry["status"]["fleet"]["host_id"] == "dec-0"
        # Boundary handles banked for both resolved stages.
        assert set(fp.stage_handles) == {"1", "2"}
        for key in fp.stage_handles.values():
            assert fleet_roles.store.get(key) is not None
        # The WHOLE accumulated lineage preseeds each hop, not just the
        # declared needs: denoise resolves {"1"}, decode resolves {"1","2"}
        # (3 hits total) — without the full-lineage dispatch the decode
        # host re-executes the encode node its closure names, paying that
        # class's program/weight warm-up per prompt.
        assert registry.get("pa_role_handle_hits") >= 3
        assert not registry.get("pa_role_handle_misses")

    def test_staged_result_bitwise_equals_single_host_run(self, role_fleet):
        base, router, backends = role_fleet
        pid = _post(base, "/prompt", {"prompt": _sgraph(6)})["prompt_id"]
        assert _wait_entry(base, pid)["status"]["status_str"] == "success"
        # The same graph straight at ONE backend (no router → unstaged).
        ref = backends[1]
        pid2 = _post(ref.base, "/prompt", {"prompt": _sgraph(6)})["prompt_id"]
        assert _wait_entry(ref.base, pid2)["status"]["status_str"] == "success"
        staged = np.load(os.path.join(backends[3].out_dir, "6-dec-0.npy"))
        direct = np.load(os.path.join(ref.out_dir, f"6-{ref.host_id}.npy"))
        assert staged.tobytes() == direct.tobytes()   # bitwise, not approx

    def test_role_views_and_metrics(self, role_fleet):
        base, router, backends = role_fleet
        pid = _post(base, "/prompt", {"prompt": _sgraph(7)})["prompt_id"]
        assert _wait_entry(base, pid)["status"]["status_str"] == "success"
        doc = _get(base, "/fleet/hosts")
        roles = doc["roles"]
        assert roles["disaggregated"] is True
        assert roles["membership"]["enc-0"] == "encode"
        assert sorted(roles["pools"]["denoise"]) == ["den-0", "den-1"]
        assert roles["suggested"] == {"encode": 1, "denoise": 2, "decode": 1}
        # Per-role dispatch counters moved for every stage of the prompt.
        for role, host in (("encode", "enc-0"), ("decode", "dec-0")):
            assert (registry.get("pa_role_dispatch_total",
                                 {"role": role, "host": host}) or 0) >= 1
        assert (registry.get("pa_role_stage_resolved_total",
                             {"role": "encode"}) or 0) >= 1
        slo = _get(base, "/fleet/slo")
        assert "roles" in slo    # per-role verdicts only when disaggregated

    def test_uncarvable_graph_single_dispatches_on_a_role_fleet(
        self, role_fleet
    ):
        base, router, backends = role_fleet
        g = {"1": {"class_type": "ToySampler",
                   "inputs": {"cond": [1.0] * 16, "seed": 8,
                              "work_s": 0.0}}}
        pid = _post(base, "/prompt", {"prompt": g})["prompt_id"]
        entry = _wait_entry(base, pid)
        assert entry["status"]["status_str"] == "success"
        fp = router.prompts[pid]
        assert fp.plan is None and fp.stage_idx == 0

    def test_all_role_fleet_stays_unstaged(self, tmp_path):
        """--role all everywhere: the pre-role fleet, bitwise-unchanged —
        one dispatch, no plan, no pa_stage entry, no roles SLO section."""
        fleet_roles.store.clear()
        base, srv, router, backends = _mk_fleet(
            tmp_path, [("all-0", "all"), ("all-1", "all")])
        try:
            assert not router.roles.disaggregated()
            pid = _post(base, "/prompt", {"prompt": _sgraph(9)})["prompt_id"]
            entry = _wait_entry(base, pid)
            assert entry["status"]["status_str"] == "success"
            fp = router.prompts[pid]
            assert fp.plan is None and fp.stage_idx == 0
            assert fp.stage_handles == {} and fp.stage_hosts == []
            assert "pa_stage" not in entry["status"]
            assert "roles" not in _get(base, "/fleet/slo")
            host = entry["status"]["fleet"]["host_id"]
            got = np.load(os.path.join(
                {b.host_id: b for b in backends}[host].out_dir,
                "9-{}.npy".format(host)))
            assert got.shape == (16,)
        finally:
            _stop_fleet(srv, router, backends)
            fleet_roles.store.clear()

    def test_denoise_kill_mid_stage_fails_over_bitwise(self, tmp_path):
        """Mid-denoise role-host kill: zero lost, survivor bitwise — the
        fold_in replay contract carried through the staged path."""
        fleet_roles.store.clear()
        base, srv, router, backends = _mk_fleet(tmp_path, _SPECS)
        try:
            pid = _post(base, "/prompt",
                        {"prompt": _sgraph(11, den_s=2.5)})["prompt_id"]
            den = {b.host_id: b for b in backends}
            _wait(lambda: any(len(den[h].q.running) > 0
                              for h in ("den-0", "den-1")),
                  what="denoise stage running")
            victim = next(h for h in ("den-0", "den-1")
                          if len(den[h].q.running) > 0)
            den[victim].kill()
            entry = _wait_entry(base, pid, timeout=60)
            assert entry["status"]["status_str"] == "success"
            assert router.stats()["lost"] == 0
            fp = router.prompts[pid]
            assert fp.failovers >= 1
            # The retry stayed in the denoise pool (the sibling survived).
            assert fp.stage_hosts[1] != victim
            assert fp.stage_hosts[1] in ("den-0", "den-1")
            staged = np.load(os.path.join(
                backends[3].out_dir, "11-dec-0.npy"))
            ref = backends[0]      # direct unstaged re-run, any host
            pid2 = _post(ref.base, "/prompt",
                         {"prompt": _sgraph(11)})["prompt_id"]
            assert (_wait_entry(ref.base, pid2)["status"]["status_str"]
                    == "success")
            direct = np.load(os.path.join(ref.out_dir, "11-enc-0.npy"))
            assert staged.tobytes() == direct.tobytes()
        finally:
            _stop_fleet(srv, router, [b for b in backends if b.alive])
            for b in backends:
                if not b.alive:
                    b.q.shutdown()
            fleet_roles.store.clear()


# ---------------------------------------------------------------------------
# request forensics: stitched cross-host timeline + explain conservation
# ---------------------------------------------------------------------------


class TestRequestForensics:
    def test_stitched_timeline_survives_failover_and_conserves_wall(
        self, tmp_path
    ):
        """The round's acceptance gate: ONE staged prompt over the 1+2+1
        role fleet — with a mid-denoise host kill — yields ONE stitched
        Perfetto timeline: >= 3 host-labeled tracks under a single
        trace_id, journal lineage merged as instant events, and
        scripts/explain.py buckets non-negative and conserving the
        client-observed wall within 10%. Reference renders per-thread
        progress prints only (any_device_parallel.py:817-905); the
        distributed timeline is this port's addition. When
        PA_FORENSICS_DUMP is set the stitched doc + wall are written there
        (the scripts/ci_tier1.sh explain-gate input)."""
        import explain

        fleet_roles.store.clear()
        tracing.enable()
        base, srv, router, backends = _mk_fleet(
            tmp_path, _SPECS,
            journal=PromptJournal(str(tmp_path / "journal.jsonl")))
        try:
            t0 = time.time()
            pid = _post(base, "/prompt",
                        {"prompt": _sgraph(21, den_s=2.5)})["prompt_id"]
            den = {b.host_id: b for b in backends}
            _wait(lambda: any(len(den[h].q.running) > 0
                              for h in ("den-0", "den-1")),
                  what="denoise stage running")
            victim = next(h for h in ("den-0", "den-1")
                          if len(den[h].q.running) > 0)
            den[victim].kill()
            entry = _wait_entry(base, pid, timeout=60)
            wall = time.time() - t0
            assert entry["status"]["status_str"] == "success"
            assert router.prompts[pid].failovers >= 1

            doc = _get(base, f"/fleet/trace?prompt_id={pid}")
            assert doc["schema"] == "pa-fleet-trace/v1"
            assert doc["trace_id"] == pid
            assert doc["enabled"] is True
            # >= 3 live host tracks (encode + surviving denoise + decode);
            # the killed host's hop is a marked-unreachable track, not a
            # silent gap.
            ok_hosts = {h["host"] for h in doc["hosts"]
                        if h["role"] != "router" and h["ok"]}
            assert len(ok_hosts) >= 3, doc["hosts"]
            assert any(h["host"] == victim and not h["ok"]
                       for h in doc["hosts"]), doc["hosts"]
            # Every stamped span joins the ONE router trace — the failover
            # re-dispatch did not fork a second trace_id.
            xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
            assert xs
            stamped = {e["args"]["trace_id"] for e in xs
                       if e.get("args", {}).get("trace_id")}
            assert stamped == {pid}
            # Journal stage lineage rides along as instant events.
            inst = {e["name"] for e in doc["traceEvents"]
                    if e.get("ph") == "i"}
            assert "journal:submit" in inst
            assert "journal:stage_dispatch" in inst

            report = explain.explain_doc(doc, wall_s=wall)
            assert explain.check(report, tolerance=0.10, min_hosts=3) == []
            assert report["dominant_bucket"] in explain.BUCKETS
            dump = os.environ.get("PA_FORENSICS_DUMP")
            if dump:
                with open(dump, "w") as f:
                    json.dump({"doc": doc, "wall_s": wall,
                               "prompt_id": pid}, f)
        finally:
            tracing.disable()
            _stop_fleet(srv, router, [b for b in backends if b.alive])
            for b in backends:
                if not b.alive:
                    b.q.shutdown()
            fleet_roles.store.clear()

    def test_disabled_fleet_trace_is_a_noop(self, role_fleet):
        """PA_TRACE off (the default): the serving path records nothing and
        GET /fleet/trace answers the stitched shape with enabled=false and
        zero duration events — forensics cost exactly nothing."""
        base, router, backends = role_fleet
        tracing.disable()
        tracing.tracer.clear()
        assert not tracing.on()
        pid = _post(base, "/prompt", {"prompt": _sgraph(23)})["prompt_id"]
        assert _wait_entry(base, pid)["status"]["status_str"] == "success"
        doc = _get(base, f"/fleet/trace?prompt_id={pid}")
        assert doc["schema"] == "pa-fleet-trace/v1"
        assert doc["enabled"] is False
        assert not [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert tracing.tracer._buffers == {}


# ---------------------------------------------------------------------------
# the CI smoke: fixed host count, disaggregated vs homogeneous
# ---------------------------------------------------------------------------


class TestRolePoolThroughput:
    def test_disaggregated_beats_homogeneous_at_fixed_host_count(
        self, tmp_path, monkeypatch
    ):
        """The round's headline gate (BASELINE "Role-pool protocol"): same 4
        hosts, same mixed load — 1-encode/2-denoise/1-decode sustains
        strictly higher throughput than 4 homogeneous backends, and the
        decode stage wall drops (role hosts never pay the class-switch
        setup a whole-graph host pays ~3× per prompt). scripts/ci_tier1.sh
        runs exactly this test as the role-pool smoke."""
        from loadgen import _append_ledger, run_load

        setup_s, clients, requests = 0.4, 4, 3
        graph = _sgraph(0, den_s=0.02)

        def _run(specs, subdir):
            fleet_roles.store.clear()
            registry.reset()
            base, srv, router, backends = _mk_fleet(
                tmp_path / subdir, specs, setup_s=setup_s)
            try:
                summary = run_load(
                    base, graph, clients=clients, requests=requests,
                    timeout=120, seed_key="2:inputs:seed", seed=7,
                    hosts=[b.base for b in backends],
                )
                dec_p95 = registry.quantile(
                    "pa_slo_stage_seconds", 95, labels={"stage": "decode"})
            finally:
                _stop_fleet(srv, router, backends)
                fleet_roles.store.clear()
            return summary, dec_p95

        hom, hom_dec_p95 = _run(
            [(f"hom-{i}", "all") for i in range(4)], "hom")
        dis, dis_dec_p95 = _run(_SPECS, "dis")

        total = clients * requests
        for name, s in (("homogeneous", hom), ("disaggregated", dis)):
            assert s["completed"] == total, (name, s)
            assert (s["fleet"] or {}).get("prompts_lost") in (0, 0.0, None)
        # Fixed host count: splitting the fleet into role pools WINS.
        assert dis["throughput_rps"] > hom["throughput_rps"], (hom, dis)
        # The decode stage wall collapses once decode hosts stay warm.
        assert dis_dec_p95 is not None and hom_dec_p95 is not None
        assert dis_dec_p95 < hom_dec_p95, (hom_dec_p95, dis_dec_p95)
        # Loadgen's per-role view materialized (kind="roles" ledger shape).
        assert set(dis["roles"]) == {"encode", "denoise", "decode"}
        assert dis["roles"]["denoise"]["hosts"] == ["den-0", "den-1"]
        assert sum(p["completed"] for p in dis["roles"].values()) == total
        disp = (dis["fleet"] or {}).get("role_dispatches") or {}
        assert all(disp.get(r, 0) >= total for r in
                   ("encode", "denoise", "decode")), disp
        assert hom.get("roles") is None     # homogeneous: no role section

        # The kind="roles" ledger record (hermetic: redirected to tmp — the
        # CLI path banks the same record when the summary carries roles).
        ledger_dir = tmp_path / "ledger"
        monkeypatch.setenv("PA_LEDGER_DIR", str(ledger_dir))
        _append_ledger(dis, "http://fixed-host-count-comparison",
                       kind="roles")
        [line] = open(ledger_dir / "perf_ledger.jsonl").read().splitlines()
        rec = json.loads(line)
        assert rec["kind"] == "roles"
        assert set(rec["roles"]) == {"encode", "denoise", "decode"}
