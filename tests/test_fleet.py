"""Fleet tier (fleet/): consistent-hash placement, health-driven admission,
drain, elastic join/leave, and lossless failover — router + real server.py
backends in-process (toy sleep nodes keep the unit/e2e tests fast; the
CI fleet smoke drives scripts/loadgen.py's fleet mode end to end and gates
on prompts_lost == 0)."""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from comfyui_parallelanything_tpu.fleet import (
    FleetRegistry,
    HashRing,
    HeartbeatClient,
    Scoreboard,
    make_router,
    model_key,
)
from comfyui_parallelanything_tpu.server import make_server

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


class _SleepWork:
    """Toy graph node: sleeps ``work_s`` (stands in for device-bound sampler
    time — releases the GIL like a real dispatch) and echoes the seed."""

    CATEGORY = "test"
    RETURN_TYPES = ("INT",)
    FUNCTION = "run"

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"seed": ("INT", {"default": 0}),
                             "work_s": ("FLOAT", {"default": 0.0})}}

    def run(self, seed, work_s):
        time.sleep(float(work_s))
        return (int(seed),)


def _graph(seed, work_s=0.0):
    return {"1": {"class_type": "SleepWork",
                  "inputs": {"seed": seed, "work_s": work_s}}}


def _get(base, path, timeout=15):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _post(base, path, payload=None, timeout=15):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _wait(pred, timeout=20, interval=0.02, what="condition"):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return
        time.sleep(interval)
    raise TimeoutError(f"never saw: {what}")


def _wait_entry(base, pid, timeout=30):
    out = {}

    def have():
        hist = _get(base, f"/history/{pid}")
        if pid in hist:
            out["entry"] = hist[pid]
            return True
        return False

    _wait(have, timeout=timeout, what=f"history entry for {pid}")
    return out["entry"]


class _Backend:
    def __init__(self, tmp_path, host_id):
        self.srv, self.q = make_server(
            port=0, output_dir=str(tmp_path / host_id),
            class_mappings={"SleepWork": _SleepWork}, host_id=host_id,
        )
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()
        self.base = f"http://127.0.0.1:{self.srv.server_address[1]}"
        self.host_id = host_id
        self.alive = True

    def kill(self):
        """Emulate a crash: the HTTP surface vanishes, then in-flight work
        dies (order matters — the router must never be able to fetch a
        post-kill history entry)."""
        self.srv.shutdown()
        self.srv.server_close()
        self.q.interrupt()
        self.alive = False

    def stop(self):
        if self.alive:
            self.srv.shutdown()
            self.srv.server_close()
        self.q.shutdown()


@pytest.fixture
def fleet(tmp_path):
    """Two backends + a fast-polling router (static ring seeds)."""
    backends = [_Backend(tmp_path, f"host-{i}") for i in range(2)]
    srv, router = make_router(
        port=0, backends=[(b.host_id, b.base) for b in backends],
        fleet_registry=FleetRegistry(ttl_s=3.0),
        scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0, fail_after=2,
                              timeout_s=2.0),
        saturation_depth=1, monitor_s=0.05, max_attempts=4,
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    _wait(lambda: all(router.scoreboard.healthy(b.host_id) for b in backends),
          what="both backends healthy on the scoreboard")
    yield base, router, backends
    srv.shutdown()
    srv.server_close()
    router.shutdown()
    for b in backends:
        b.stop()


class TestHashRing:
    def test_deterministic_and_covering(self):
        r = HashRing(vnodes=32)
        r.rebuild(["a", "b", "c"])
        seq = r.sequence("model-x")
        assert sorted(seq) == ["a", "b", "c"]
        assert r.sequence("model-x") == seq  # deterministic
        r2 = HashRing(vnodes=32)
        r2.rebuild(["c", "a", "b"])  # order-independent construction
        assert r2.sequence("model-x") == seq

    def test_join_moves_only_some_keys(self):
        """Consistent hashing's point: adding a host remaps a fraction of
        keys, not the whole map — warm compiled programs mostly stay put."""
        r = HashRing(vnodes=64)
        r.rebuild(["a", "b", "c"])
        keys = [f"model-{i}" for i in range(200)]
        before = {k: r.sequence(k)[0] for k in keys}
        r.rebuild(["a", "b", "c", "d"])
        after = {k: r.sequence(k)[0] for k in keys}
        moved = sum(1 for k in keys if before[k] != after[k])
        assert 0 < moved < len(keys) // 2, moved  # ~1/4 expected
        # Every key that moved, moved TO the new host — never shuffled
        # between the survivors.
        assert all(after[k] == "d" for k in keys if before[k] != after[k])

    def test_model_key_ignores_volatile_inputs(self):
        g1 = {"1": {"class_type": "CheckpointLoaderSimple",
                    "inputs": {"ckpt_name": "a.safetensors"}},
              "2": {"class_type": "KSampler",
                    "inputs": {"seed": 1, "steps": 4}}}
        g2 = json.loads(json.dumps(g1))
        g2["2"]["inputs"].update(seed=99, steps=30)
        assert model_key(g1) == model_key(g2)  # same model → same primary
        g3 = json.loads(json.dumps(g1))
        g3["1"]["inputs"]["ckpt_name"] = "b.safetensors"
        assert model_key(g1) != model_key(g3)  # different model → may move
        # Loaderless graphs key on structure, not inputs.
        assert model_key(_graph(1)) == model_key(_graph(2))


class TestHealthV2:
    def test_health_carries_fleet_fields(self, fleet):
        _, _, backends = fleet
        doc = _get(backends[0].base, "/health")
        assert doc["schema"] == "pa-health/v3"
        assert doc["host_id"] == "host-0"
        assert doc["accepting"] is True
        assert doc["inflight_prompts"] == 0
        assert "queue" in doc and "compile" in doc  # v1 fields intact

    def test_drain_stops_seating_and_resume_reopens(self, fleet):
        _, _, backends = fleet
        b = backends[0]
        state = _post(b.base, "/drain")
        assert state == {"host_id": "host-0", "accepting": False,
                         "pending": 0, "running": 0}
        assert _get(b.base, "/health")["accepting"] is False
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(b.base, "/prompt", {"prompt": _graph(1)})
        assert err.value.code == 503
        assert _post(b.base, "/drain", {"resume": True})["accepting"] is True
        pid = _post(b.base, "/prompt", {"prompt": _graph(2)})["prompt_id"]
        entry = _wait_entry(b.base, pid)
        assert entry["status"]["status_str"] == "success"
        assert entry["status"]["host_id"] == "host-0"


class TestScoreboard:
    def test_poll_reads_health_document(self, fleet):
        _, router, backends = fleet
        snap = router.scoreboard.snapshot()
        for b in backends:
            s = snap[b.host_id]
            assert s["healthy"] and s["accepting"]
            assert s["schema"] == "pa-health/v3"
            assert s["inflight_prompts"] == 0
            assert s["numerics_ok"] is True
            assert s["health_age_s"] is not None

    def test_failure_backoff_and_staleness(self):
        sb = Scoreboard(poll_s=0.1, stale_after_s=0.5, fail_after=3,
                        timeout_s=0.5)
        # Unreachable host: each failure doubles the backoff window.
        assert not sb.poll_host("ghost", "http://127.0.0.1:9")
        e = sb._entries["ghost"]
        assert e.consecutive_failures == 1
        first_backoff = e.next_poll - time.monotonic()
        assert not sb.poll_host("ghost", "http://127.0.0.1:9")
        assert e.consecutive_failures == 2
        assert e.next_poll - time.monotonic() > first_backoff
        assert not sb.healthy("ghost")
        assert not sb.dead("ghost")
        sb.record_failure("ghost")
        assert sb.dead("ghost")
        # Staleness: a host with a FINE last document but an old poll stops
        # counting as healthy — decisions are only as good as their data age.
        sb2 = Scoreboard(poll_s=0.1, stale_after_s=0.05)
        sb2._entry("h", "http://x").last_ok = time.monotonic() - 1.0
        assert not sb2.healthy("h")


class TestRouterPlacement:
    def test_warm_affinity_unsaturated(self, fleet):
        """Sequential prompts for one model land on ONE host — its compiled
        programs stay warm; the other host sees nothing."""
        base, router, backends = fleet
        served = set()
        for i in range(4):
            pid = _post(base, "/prompt", {"prompt": _graph(i)})["prompt_id"]
            entry = _wait_entry(base, pid)
            assert entry["status"]["status_str"] == "success"
            served.add(entry["status"]["fleet"]["host_id"])
            assert entry["status"]["fleet"]["failovers"] == 0
        assert len(served) == 1, served

    def test_spill_when_primary_saturated(self, fleet):
        """depth=1: concurrent prompts spill off the busy primary to the
        next ring host instead of queueing behind it."""
        base, router, backends = fleet
        pids = [
            _post(base, "/prompt",
                  {"prompt": _graph(100 + i, work_s=0.8)})["prompt_id"]
            for i in range(2)
        ]
        served = set()
        for pid in pids:
            entry = _wait_entry(base, pid)
            assert entry["status"]["status_str"] == "success"
            served.add(entry["status"]["fleet"]["host_id"])
        assert len(served) == 2, served  # both hosts worked

    def test_drain_via_router_redirects_traffic(self, fleet):
        base, router, backends = fleet
        # Find the model's primary, then drain it through the router.
        key = model_key(_graph(0))
        primary = router.registry.sequence(key)[0]
        resp = _post(base, "/fleet/drain", {"host_id": primary})
        assert resp["accepting"] is False
        other = next(b.host_id for b in backends if b.host_id != primary)
        for i in range(2):
            pid = _post(base, "/prompt", {"prompt": _graph(200 + i)})["prompt_id"]
            entry = _wait_entry(base, pid)
            assert entry["status"]["fleet"]["host_id"] == other
        # Rejoin: resume + one scoreboard refresh puts it back in rotation.
        primary_base = router.registry.base_of(primary)
        _post(primary_base, "/drain", {"resume": True})
        _wait(lambda: router.scoreboard.accepting(primary),
              what="drained host accepting again")

    def test_backend_client_error_passes_through(self, fleet):
        """A backend 400 (bad graph) is the REQUEST's fault: passed through
        verbatim, never retried on siblings, never counted as lost."""
        base, router, backends = fleet
        bad = {"1": {"class_type": "SleepWork",
                     "inputs": {"seed": "not-an-int", "work_s": 0.0}}}
        # SleepWork.run would TypeError → backend reports an error ENTRY,
        # not a 400 — so use a graph the backend's submit path rejects
        # outright: extra_data with a bad deadline.
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(base, "/prompt", {"prompt": _graph(1),
                                    "extra_data": {"deadline_s": "bogus"}})
        assert err.value.code == 400
        assert router.stats()["lost"] == 0
        # And the fleet keeps serving.
        pid = _post(base, "/prompt", {"prompt": _graph(2)})["prompt_id"]
        assert _wait_entry(base, pid)["status"]["status_str"] == "success"

    def test_resolved_prompts_pruned_beyond_history_budget(self, fleet):
        base, router, backends = fleet
        router.max_history = 3
        pids = []
        for i in range(6):
            pid = _post(base, "/prompt", {"prompt": _graph(300 + i)})["prompt_id"]
            _wait_entry(base, pid)
            pids.append(pid)
        _wait(lambda: len(router.prompts) <= 3, timeout=10,
              what="history pruned to budget")
        # Newest entries survive; the oldest were evicted.
        assert _get(base, f"/history/{pids[-1]}")
        assert _get(base, f"/history/{pids[0]}") == {}

    def test_no_healthy_host_is_503(self, tmp_path):
        srv, router = make_router(port=0, backends=[],
                                  monitor_s=0.05, auto=True)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(base, "/prompt", {"prompt": _graph(1)})
            assert err.value.code == 503
        finally:
            srv.shutdown()
            srv.server_close()
            router.shutdown()


class TestElasticMembership:
    def test_heartbeat_join_and_expiry(self, tmp_path, fleet):
        base, router, backends = fleet
        extra = _Backend(tmp_path, "host-late")
        try:
            hb = HeartbeatClient(base, extra.host_id, extra.base,
                                 interval_s=0.5)
            assert hb.beat_once()
            # Joined AND immediately placeable (the register handler polls
            # the joiner's health inline).
            assert "host-late" in router.registry.hosts()
            _wait(lambda: router.scoreboard.healthy("host-late"),
                  what="joiner healthy")
            # No more beats: the host expires off the ring after ttl.
            _wait(lambda: "host-late" not in router.registry.hosts(),
                  timeout=10, what="joiner expired")
        finally:
            extra.stop()

    def test_explicit_leave(self, fleet):
        base, router, backends = fleet
        assert _post(base, "/fleet/leave",
                     {"host_id": "host-1"})["removed"] is True
        assert "host-1" not in router.registry.hosts()
        # Static hosts never expire by heartbeat, so host-0 is still there.
        assert "host-0" in router.registry.hosts()


class TestFailover:
    def test_kill_host_mid_prompt_lossless(self, fleet):
        """The headline: a host dies mid-prompt; the router detects it via
        failing health polls, re-submits to the sibling, and the client's
        prompt_id resolves successfully — zero prompts lost, the failover
        visible in status.fleet."""
        base, router, backends = fleet
        key = model_key(_graph(0, work_s=3.0))
        victim_id = router.registry.sequence(key)[0]
        victim = next(b for b in backends if b.host_id == victim_id)
        survivor = next(b for b in backends if b.host_id != victim_id)

        pid = _post(base, "/prompt",
                    {"prompt": _graph(7, work_s=3.0)})["prompt_id"]
        _wait(lambda: len(victim.q.running) > 0,
              what="victim mid-prompt")  # genuinely mid-'denoise'
        victim.kill()
        entry = _wait_entry(base, pid, timeout=30)
        assert entry["status"]["status_str"] == "success", entry["status"]
        fleet_meta = entry["status"]["fleet"]
        assert fleet_meta["host_id"] == survivor.host_id
        assert fleet_meta["failovers"] == 1
        assert router.stats()["lost"] == 0
        # The dead host is off the scoreboard's healthy set; new prompts
        # keep flowing to the survivor.
        assert not router.scoreboard.healthy(victim_id)
        pid2 = _post(base, "/prompt", {"prompt": _graph(8)})["prompt_id"]
        entry2 = _wait_entry(base, pid2)
        assert entry2["status"]["fleet"]["host_id"] == survivor.host_id


class TestJournal:
    def test_append_fold_roundtrip(self, tmp_path):
        from comfyui_parallelanything_tpu.fleet import PromptJournal

        j = PromptJournal(str(tmp_path / "j.jsonl"))
        j.append("submit", "p1", graph={"1": {}}, extra=None, key="k1",
                 number=1)
        j.append("dispatch", "p1", host="h0", backend_pid="b1", attempt=1)
        j.append("submit", "p2", graph={"2": {}}, extra=None, key="k2",
                 number=2)
        j.append("resolve", "p1", status="done",
                 entry={"status": {"status_str": "success"}})
        table = j.replay()
        assert table["p1"]["phase"] == "resolve"
        assert table["p1"]["entry"]["status"]["status_str"] == "success"
        assert table["p2"]["phase"] == "submit"
        assert table["p2"]["graph"] == {"2": {}}

    def test_torn_tail_skipped(self, tmp_path):
        from comfyui_parallelanything_tpu.fleet import PromptJournal

        j = PromptJournal(str(tmp_path / "j.jsonl"))
        j.append("submit", "p1", graph={}, key="k", number=1)
        j.close()
        with open(j.path, "ab") as f:
            f.write(b'{"schema": "pa-fleet-journal/v1", "ev": "disp')  # torn
        table = j.replay()
        assert list(table) == ["p1"]

    def test_lease_lifecycle(self, tmp_path):
        from comfyui_parallelanything_tpu.fleet import PromptJournal

        j = PromptJournal(str(tmp_path / "j.jsonl"))
        assert j.lease_stale(ttl_s=1.0)          # no lease yet
        j.write_lease("router-a")
        assert not j.lease_stale(ttl_s=60.0)
        assert j.read_lease()["router_id"] == "router-a"
        # A holder never treats its OWN lease as a dead primary.
        assert not j.lease_stale(ttl_s=0.0, holder_not="router-a")
        time.sleep(0.05)
        assert j.lease_stale(ttl_s=0.01)         # aged out


class TestRouterHA:
    def _standby(self, journal_path, backends, lease_ttl=0.5):
        from comfyui_parallelanything_tpu.fleet import (
            FleetRegistry,
            PromptJournal,
            Scoreboard,
            make_router,
        )

        srv, router = make_router(
            port=0, backends=[(b.host_id, b.base) for b in backends],
            fleet_registry=FleetRegistry(ttl_s=3.0),
            scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0,
                                  fail_after=2, timeout_s=2.0),
            saturation_depth=1, monitor_s=0.05,
            journal=PromptJournal(journal_path),
            standby=True, lease_ttl_s=lease_ttl,
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, router, f"http://127.0.0.1:{srv.server_address[1]}"

    def test_standby_refuses_prompts_503(self, tmp_path, fleet):
        _, _, backends = fleet
        srv, router, base = self._standby(
            str(tmp_path / "j.jsonl"), backends, lease_ttl=3600,
        )
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(base, "/prompt", {"prompt": _graph(1)})
            assert err.value.code == 503
            assert json.loads(err.value.read())["role"] == "standby"
        finally:
            srv.shutdown()
            srv.server_close()
            router.shutdown()

    def test_router_kill_mid_denoise_standby_takeover_zero_lost(
        self, tmp_path
    ):
        """The HA headline: the PRIMARY ROUTER dies mid-denoise; the standby
        tails the shared journal, sees the lease go stale, takes over,
        re-collects/replays every unresolved prompt — zero lost, completed
        entries (including ones resolved before the kill) served by the
        standby it never saw live."""
        from comfyui_parallelanything_tpu.fleet import (
            FleetRegistry,
            PromptJournal,
            Scoreboard,
            make_router,
        )

        backends = [_Backend(tmp_path, f"ha-host-{i}") for i in range(2)]
        jpath = str(tmp_path / "journal.jsonl")
        srv1, primary = make_router(
            port=0, backends=[(b.host_id, b.base) for b in backends],
            fleet_registry=FleetRegistry(ttl_s=3.0),
            scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0,
                                  fail_after=2, timeout_s=2.0),
            saturation_depth=2, monitor_s=0.05,
            journal=PromptJournal(jpath), lease_ttl_s=0.5,
        )
        threading.Thread(target=srv1.serve_forever, daemon=True).start()
        base1 = f"http://127.0.0.1:{srv1.server_address[1]}"
        srv2, standby, base2 = self._standby(jpath, backends, lease_ttl=0.5)
        try:
            _wait(lambda: all(primary.scoreboard.healthy(b.host_id)
                              for b in backends),
                  what="backends healthy on the primary")
            # One prompt completes BEFORE the kill (the journal-resolve
            # record the standby must serve from /history later)...
            pid_done = _post(base1, "/prompt",
                             {"prompt": _graph(70)})["prompt_id"]
            entry_done = _wait_entry(base1, pid_done)
            assert entry_done["status"]["status_str"] == "success"
            # ... and two are MID-DENOISE when the router dies.
            pids = [
                _post(base1, "/prompt",
                      {"prompt": _graph(71 + i, work_s=2.0)})["prompt_id"]
                for i in range(2)
            ]
            _wait(lambda: sum(len(b.q.running) for b in backends) >= 1,
                  what="work running mid-denoise")
            srv1.shutdown()
            srv1.server_close()
            primary.shutdown()   # lease stops refreshing → stale
            _wait(lambda: standby.active, timeout=15,
                  what="standby takeover")
            # The standby serves history it never saw live (journal replay)…
            got = _get(base2, f"/history/{pid_done}")
            assert got[pid_done]["status"]["status_str"] == "success"
            # …and the mid-denoise prompts complete through it: collected
            # from the live backends (or failed over) — zero lost.
            for pid in pids:
                entry = _wait_entry(base2, pid, timeout=60)
                assert entry["status"]["status_str"] == "success", entry
            assert standby.stats()["lost"] == 0
        finally:
            srv2.shutdown()
            srv2.server_close()
            standby.shutdown()
            for b in backends:
                b.stop()

    def test_journal_records_full_lifecycle(self, tmp_path):
        from comfyui_parallelanything_tpu.fleet import (
            FleetRegistry,
            PromptJournal,
            Scoreboard,
            make_router,
        )

        backends = [_Backend(tmp_path, "jr-host-0")]
        jpath = str(tmp_path / "jr.jsonl")
        srv, router = make_router(
            port=0, backends=[(b.host_id, b.base) for b in backends],
            fleet_registry=FleetRegistry(ttl_s=3.0),
            scoreboard=Scoreboard(poll_s=0.1, fail_after=2, timeout_s=2.0),
            monitor_s=0.05, journal=PromptJournal(jpath),
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            _wait(lambda: router.scoreboard.healthy("jr-host-0"),
                  what="backend healthy")
            pid = _post(base, "/prompt", {"prompt": _graph(5)})["prompt_id"]
            _wait_entry(base, pid)
            evs = [r["ev"] for r in PromptJournal.iter_records(jpath)
                   if r["pid"] == pid]
            assert evs[:2] == ["submit", "dispatch"]
            _wait(lambda: "resolve" in [
                r["ev"] for r in PromptJournal.iter_records(jpath)
                if r["pid"] == pid
            ], what="resolve journaled")
            table = PromptJournal(jpath).replay()
            assert table[pid]["phase"] == "resolve"
            assert table[pid]["entry"]["status"]["status_str"] == "success"
        finally:
            srv.shutdown()
            srv.server_close()
            router.shutdown()
            for b in backends:
                b.stop()


class TestStageLineageReplay:
    """Round-20 satellite: a DECODE-tier host dies mid-decode while the
    primary router is also gone — the standby's journal takeover must
    re-dispatch the decode stage from the journaled denoise output handle
    (stage lineage, fleet/journal.py), never re-denoise, and the survivor
    stays bitwise. The decode pool has ONE host, so the re-dispatch also
    exercises place()'s degrade-to-global-ring path."""

    def test_decode_kill_standby_redispatches_from_denoise_handle(
        self, tmp_path
    ):
        from test_roles import _RoleBackend, _sgraph
        from test_roles import _wait as _rwait
        from comfyui_parallelanything_tpu.fleet import (
            FleetRegistry,
            PromptJournal,
            Scoreboard,
            make_router,
        )
        from comfyui_parallelanything_tpu.fleet import roles as fleet_roles

        fleet_roles.store.clear()
        specs = [("sr-enc", "encode"), ("sr-den", "denoise"),
                 ("sr-dec", "decode")]
        backends = [_RoleBackend(tmp_path, hid, role) for hid, role in specs]
        by_id = {b.host_id: b for b in backends}
        jpath = str(tmp_path / "journal.jsonl")

        def _router(standby):
            srv, router = make_router(
                port=0, backends=[(b.host_id, b.base) for b in backends],
                fleet_registry=FleetRegistry(ttl_s=5.0),
                scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0,
                                      fail_after=2, timeout_s=2.0),
                saturation_depth=2, monitor_s=0.05, max_attempts=4,
                journal=PromptJournal(jpath), lease_ttl_s=0.5,
                standby=standby,
            )
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            return srv, router, f"http://127.0.0.1:{srv.server_address[1]}"

        srv1, primary, base1 = _router(standby=False)
        srv2, standby, base2 = _router(standby=True)
        try:
            _wait(lambda: all(primary.scoreboard.healthy(b.host_id)
                              for b in backends),
                  what="role backends healthy on the primary")
            _wait(lambda: primary.roles.disaggregated(),
                  what="roles visible to the primary")
            pid = _post(base1, "/prompt",
                        {"prompt": _sgraph(21, dec_s=4.0)})["prompt_id"]
            # Decode RUNNING means encode + denoise already resolved and
            # their stage_resolve lineage (with handles) is journaled.
            _rwait(lambda: len(by_id["sr-dec"].q.running) > 0,
                   what="decode stage running")
            srv1.shutdown()
            srv1.server_close()
            primary.shutdown()          # lease stops refreshing
            by_id["sr-dec"].kill()      # ... then the decode host crashes
            _wait(lambda: standby.active, timeout=15,
                  what="standby takeover")
            entry = _wait_entry(base2, pid, timeout=60)
            assert entry["status"]["status_str"] == "success"
            assert standby.stats()["lost"] == 0
            recs = [r for r in PromptJournal.iter_records(jpath)
                    if r["pid"] == pid]
            # Denoise ran EXACTLY once across both routers' lifetimes: the
            # standby resumed from the journaled denoise handle.
            den = [r for r in recs if r["ev"] == "stage_dispatch"
                   and r.get("stage") == "denoise"]
            assert len(den) == 1, recs
            resolves = [r for r in recs if r["ev"] == "stage_resolve"]
            assert [r["stage"] for r in resolves[:2]] == [
                "encode", "denoise"]
            den_handle = resolves[1]["handles"]["2"]
            # The handle survived the decode-host crash (content-addressed
            # store on the surviving hosts) — the retry consumed it instead
            # of re-denoising.
            assert fleet_roles.store.get(den_handle) is not None
            dec = [r for r in recs if r["ev"] == "stage_dispatch"
                   and r.get("stage") == "decode"]
            assert len(dec) >= 2            # original + post-takeover retry
            assert dec[-1]["host"] != "sr-dec"   # pool empty → global ring
            # Bitwise: the failed-over decode dumped the same latent a
            # direct single-host run produces.
            survivor = by_id[dec[-1]["host"]]
            staged = np.load(os.path.join(
                survivor.out_dir, f"21-{survivor.host_id}.npy"))
            ref = by_id["sr-enc"]
            pid2 = _post(ref.base, "/prompt",
                         {"prompt": _sgraph(21)})["prompt_id"]
            assert (_wait_entry(ref.base, pid2)["status"]["status_str"]
                    == "success")
            direct = np.load(os.path.join(ref.out_dir, "21-sr-enc.npy"))
            assert staged.tobytes() == direct.tobytes()
        finally:
            srv2.shutdown()
            srv2.server_close()
            standby.shutdown()
            for b in backends:
                if b.alive:
                    b.stop()
                else:
                    b.q.shutdown()
            fleet_roles.store.clear()


class TestResidencyAwarePlacement:
    def test_health_v3_advertises_warm_keys(self, fleet):
        """A backend that served a model advertises its key (pa-health/v3);
        the scoreboard parses it into warm()."""
        base, router, backends = fleet
        pid = _post(base, "/prompt", {"prompt": _graph(1)})["prompt_id"]
        entry = _wait_entry(base, pid)
        hot = entry["status"]["fleet"]["host_id"]
        key = model_key(_graph(1))
        hot_base = next(b.base for b in backends if b.host_id == hot)
        doc = _get(hot_base, "/health")
        assert key in doc["warm_keys"]
        _wait(lambda: router.scoreboard.warm(hot, key),
              what="scoreboard sees the warm key")
        cold = next(b.host_id for b in backends if b.host_id != hot)
        assert not router.scoreboard.warm(cold, key)

    def test_failover_prefers_warm_sibling(self, fleet):
        """place(prefer_warm=True) orders warm hosts first even when ring
        order says otherwise — the replay path's preference."""
        base, router, backends = fleet
        key = model_key(_graph(1))
        seq = router.registry.sequence(key)
        primary, sibling = seq[0], seq[1]

        def _fabricate_warmth():
            # The monitor's background poll rewrites warm_keys from the real
            # health docs — re-fabricate immediately before each placement.
            with router.scoreboard._lock:
                router.scoreboard._entries[sibling].warm_keys = (
                    frozenset({key})
                )
                router.scoreboard._entries[primary].warm_keys = frozenset()

        _fabricate_warmth()
        cold_first, _, _ = router.place(key)
        assert cold_first == primary          # fresh traffic: ring order
        _fabricate_warmth()
        warm_first, _, _ = router.place(key, prefer_warm=True)
        assert warm_first == sibling          # replay: warmth wins
        # Warmth never overrides health: a draining warm host loses.
        try:
            router.scoreboard.mark_draining(sibling)
            _fabricate_warmth()
            with router.scoreboard._lock:
                router.scoreboard._entries[sibling].accepting = False
            again, _, _ = router.place(key, prefer_warm=True)
            assert again == primary
        finally:
            with router.scoreboard._lock:
                router.scoreboard._entries[sibling].accepting = True


class TestHeartbeatRejoin:
    def test_rejoin_fires_callback_and_resumes(self, tmp_path, fleet):
        """A host whose registration lapsed (router lost it) re-JOINS on its
        next beat — the on_rejoin hook fires exactly then (never on refresh
        beats), restoring admission on the returning backend."""
        from comfyui_parallelanything_tpu.fleet import HeartbeatClient

        base, router, backends = fleet
        extra = _Backend(tmp_path, "rejoin-host")
        rejoins = []
        hb = HeartbeatClient(base, extra.host_id, extra.base,
                             interval_s=0.5,
                             on_rejoin=lambda: rejoins.append(1))
        try:
            assert hb.beat_once()            # first join: NOT a rejoin
            assert rejoins == []
            assert hb.beat_once()            # refresh: not a rejoin either
            assert rejoins == []
            router.registry.remove(extra.host_id)  # expiry stand-in
            assert hb.beat_once()            # falls back ON → rejoin
            assert len(rejoins) == 1
        finally:
            extra.stop()


class TestFleetSmoke:
    """The CI gate (scripts/ci_tier1.sh): router + loadgen fleet mode,
    ~10 prompts over 2 backends on CPU, prompts_lost == 0."""

    def test_loadgen_fleet_mode_two_backends(self, fleet):
        from loadgen import print_human_summary, run_load

        base, router, backends = fleet
        summary = run_load(
            base, _graph(0, work_s=0.1), clients=3, requests=4,
            timeout=60, seed_key="1:inputs:seed", seed=7,
            hosts=[b.base for b in backends],
        )
        print_human_summary(summary)
        assert summary["completed"] == 12, summary
        assert summary["failed"] == 0 and summary["rejected_429"] == 0
        assert summary["prompts_lost"] == 0, summary
        assert summary["seed"] == 7
        # Dispatch is at-least-once by design (a POST that errors after the
        # backend accepted is retried on a sibling — same mechanism as
        # failover), so allow a transient-retry margin over the 12 prompts.
        assert 12 <= summary["fleet"]["dispatches"] <= 14, summary["fleet"]
        # Per-host sections: every completion attributed, both hosts seen
        # (depth=1 + 3 concurrent clients forces spill off the primary).
        hosts = summary["hosts"]
        assert sum(h["completed"] for h in hosts.values()) == 12
        assert all(h["reachable"] for h in hosts.values())
        assert sum(1 for h in hosts.values() if h["completed"] > 0) == 2
        for h in hosts.values():
            if h["completed"]:
                assert h["latency_p95_s"] >= h["latency_p50_s"] > 0

    def test_seeded_schedule_reproducible(self, fleet):
        """--seed contract: same seed → identical submitted prompt set."""
        import random

        sched1 = [random.Random(7).randrange(1 << 31) for _ in range(12)]
        sched2 = [random.Random(7).randrange(1 << 31) for _ in range(12)]
        assert sched1 == sched2
        assert sched1 != [random.Random(8).randrange(1 << 31)
                          for _ in range(12)]


class TestOpenLoopSmoke:
    """Round 15 acceptance: open-loop loadgen on the fleet emits a
    latency-under-load curve + SLO decomposition in one summary, the
    kind=openloop ledger record replays through the traffic twin within the
    declared band (the twin_report --check gate), and GET /fleet/metrics
    serves one merged host-labeled Prometheus view."""

    def test_openloop_curve_slo_ledger_and_twin(self, fleet, tmp_path,
                                                monkeypatch):
        import re
        import subprocess

        from loadgen import print_human_summary, run_open_load

        from comfyui_parallelanything_tpu.fleet import twin
        from comfyui_parallelanything_tpu.utils.metrics import registry

        registry.reset()  # lifetime histograms: this run's scrape only
        base, router, backends = fleet
        summary = run_open_load(
            base, _graph(0, work_s=0.05), kind="poisson",
            rps_list=[4.0, 10.0], duration_s=2.0, timeout=60, seed=7,
            seed_key="1:inputs:seed", hosts=[b.base for b in backends],
        )
        print_human_summary(summary)
        # -- the curve: one rung per offered rate, quantiles ordered
        curve = summary["openloop"]["curve"]
        assert len(curve) == 2
        for rung in curve:
            assert rung["completed"] == rung["arrivals"] > 0, rung
            assert (0 < rung["latency_p50_s"] <= rung["latency_p95_s"]
                    <= rung["latency_p99_s"]), rung
        assert summary["failed"] == 0 and summary["prompts_lost"] == 0
        assert summary["openloop"]["kind"] == "poisson"
        assert summary["openloop"]["seed"] == 7
        # -- the SLO decomposition: server stages + the client residual
        slo_view = summary["slo"]
        assert slo_view["stages"]["admission"]["p50_s"] is not None
        assert slo_view["request_p50_s"] > 0
        assert slo_view["collect_p50_s"] >= 0
        assert slo_view["burn_rates"], slo_view
        [obj] = slo_view["objectives"]
        assert obj["ok"] is True and obj["requests"] > 0
        # -- per-host capacity evidence for the twin (hosts the spill
        #    never reached legitimately carry no service history)
        served = [h for h in summary["hosts"].values() if h["completed"]]
        assert served
        assert all(h["service_p50_s"] > 0 and h["workers"] == 1
                   for h in served)
        # -- the kind=openloop ledger record, replayed by the twin within
        #    the declared band (the exact ci_tier1 gate, against this run)
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path / "ledger"))
        from loadgen import _append_ledger

        _append_ledger(summary, base, kind="openloop")
        rep = twin.replay_record({**summary, "base": base})
        assert rep is not None and rep["p95_err_max"] is not None
        assert rep["p95_err_max"] <= summary["openloop"]["twin_band"], rep
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "twin_report.py"),
             "--ledger", str(tmp_path / "ledger"), "--check"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK" in proc.stdout
        # -- GET /fleet/metrics: ONE merged host-labeled Prometheus view
        text = _get_text(base, "/fleet/metrics")
        for b in backends:
            assert re.search(
                rf'^pa_server_queue_pending\{{host="{b.host_id}"\}} ',
                text, re.M), b.host_id
        # the router's own series are host-labeled too
        assert re.search(r'^pa_fleet_completed_total\{host="router-', text,
                         re.M)
        # live hosts are not stale
        for b in backends:
            assert f'pa_fleet_scrape_stale{{host="{b.host_id}"}} 0' in text
        # -- GET /fleet/slo: objective verdicts over the merged view
        doc = _get(base, "/fleet/slo")
        assert doc["schema"] == "pa-fleet-slo/v1"
        assert doc["objectives"][0]["requests"] > 0
        assert doc["objectives"][0]["ok"] is True
        assert set(doc["hosts"]) == {b.host_id for b in backends}


def _get_text(base, path, timeout=15):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


class TestFleetMetricsAggregation:
    def test_dead_backend_degrades_not_stalls(self, fleet):
        """Satellite: with one backend dead, /fleet/metrics still carries
        the survivor's series, marks the dead host stale, and answers
        within the poll timeout (the scrape rides the scoreboard's failure
        backoff — no fresh fetch of a host in backoff)."""
        import re

        base, router, backends = fleet
        victim, survivor = backends[0], backends[1]
        # A warm scrape first, so the dead host has a cached section.
        text = _get_text(base, "/fleet/metrics")
        assert f'host="{victim.host_id}"' in text
        victim.kill()
        _wait(lambda: router.scoreboard.in_backoff(victim.host_id)
              or router.scoreboard.dead(victim.host_id),
              what="victim in failure backoff")
        t0 = time.time()
        text = _get_text(base, "/fleet/metrics")
        elapsed = time.time() - t0
        # never blocks past the poll timeout (fixture timeout_s=2.0) —
        # the dead host's section is served from cache, not re-fetched
        assert elapsed < 2.0 + 1.0, elapsed
        assert re.search(
            rf'^pa_server_queue_pending\{{host="{survivor.host_id}"\}} ',
            text, re.M)
        assert f'pa_fleet_scrape_stale{{host="{victim.host_id}"}} 1' in text
        assert f'pa_fleet_scrape_stale{{host="{survivor.host_id}"}} 0' \
            in text
        # the cached section still carries the dead host's last series
        assert re.search(
            rf'^pa_server_queue_pending\{{host="{victim.host_id}"\}} ',
            text, re.M)


class TestRingChangePreferWarm:
    def test_join_rehomes_to_warm_sibling_first(self, fleet):
        """Satellite (ROADMAP fleet remainder): after a ring CHANGE (join/
        leave), fresh placement runs prefer_warm for a dwell — a key whose
        primary moved (or whose primary is simply cold) goes to the host
        actually holding it warm, instead of paying compile + staging on
        the cold ring primary. Warmth here is REAL (the sibling served the
        model through its own front door), not fabricated."""
        base, router, backends = fleet
        g = _graph(1)
        key = model_key(g)
        seq = router.registry.sequence(key)
        primary, sibling = seq[0], seq[1]
        sib = next(b for b in backends if b.host_id == sibling)
        # Warm the SIBLING directly (bypassing the router): it genuinely
        # serves the model and advertises the key via pa-health/v3.
        pid = _post(sib.base, "/prompt", {"prompt": _graph(91)})["prompt_id"]
        _wait_entry(sib.base, pid)
        _wait(lambda: router.scoreboard.warm(sibling, key),
              what="sibling advertises the warm key")
        assert not router.scoreboard.warm(primary, key)
        # No ring change: ring order wins — the cold primary takes it.
        pid = _post(base, "/prompt", {"prompt": _graph(92)})["prompt_id"]
        assert _wait_entry(base, pid)["status"]["fleet"]["host_id"] \
            == primary
        # Ring change: the prefer-warm dwell re-homes the key to the warm
        # sibling. (note_ring_change is what /fleet/register's join and
        # leave/expiry call; invoked directly so the test pins the
        # placement behavior, not the membership plumbing.)
        _wait(lambda: router.scoreboard.warm(sibling, key),
              what="sibling still warm")  # health re-polls must agree
        router.note_ring_change()
        try:
            pid = _post(base, "/prompt", {"prompt": _graph(93)})["prompt_id"]
            assert _wait_entry(base, pid)["status"]["fleet"]["host_id"] \
                == sibling
        finally:
            router._ring_changed_until = 0.0
        # Dwell expired: ring order is restored.
        pid = _post(base, "/prompt", {"prompt": _graph(94)})["prompt_id"]
        assert _wait_entry(base, pid)["status"]["fleet"]["host_id"] \
            == primary

    def test_membership_events_open_the_dwell(self, tmp_path, fleet):
        base, router, backends = fleet
        assert not router._ring_recently_changed()
        extra = _Backend(tmp_path, "dwell-host")
        try:
            hb = HeartbeatClient(base, extra.host_id, extra.base,
                                 interval_s=0.5)
            assert hb.beat_once()               # join → dwell opens
            assert router._ring_recently_changed()
            router._ring_changed_until = 0.0    # reset
            assert hb.beat_once()               # refresh → NO dwell
            assert not router._ring_recently_changed()
            _post(base, "/fleet/leave", {"host_id": extra.host_id})
            assert router._ring_recently_changed()  # leave → dwell opens
        finally:
            router._ring_changed_until = 0.0
            extra.stop()
