"""Heterogeneous-chain (multi-platform-group) data parallelism — SURVEY §7 hard
part 1. A real tpu+cpu chain can't exist on the CPU-only CI box, so the platform
prober is monkeypatched to split the 8 virtual CPU devices into two fake platform
groups; the weighted host-side scatter / per-group SPMD / gather-concat path then
runs exactly as it would for tpu+cpu."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models import build_unet, sd15_config
from comfyui_parallelanything_tpu.parallel import orchestrator as orch_mod


@pytest.fixture()
def split_platforms(monkeypatch):
    """cpu:0-1 keep platform 'cpu'; cpu:2-3 report a fake accelerator platform."""

    def fake_platform(device_str: str) -> str:
        idx = int(device_str.split(":")[1]) if ":" in device_str else 0
        return "cpu" if idx < 2 else "fake_tpu"

    monkeypatch.setattr(orch_mod, "device_platform", fake_platform)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = sd15_config(
        model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
        context_dim=64, norm_groups=8, dtype=jnp.float32,
    )
    return build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))


class TestHybridChain:
    def test_two_groups_formed(self, split_platforms, tiny_model):
        chain = DeviceChain.from_pairs(
            [("cpu:0", 30), ("cpu:1", 30), ("cpu:2", 20), ("cpu:3", 20)]
        )
        pm = parallelize(tiny_model, chain)
        assert len(pm._groups) == 2
        assert [g.platform for g in pm._groups] == ["cpu", "fake_tpu"]
        assert pm.n_devices == 4

    def test_hybrid_output_matches_single(self, split_platforms, tiny_model):
        chain = DeviceChain.from_pairs(
            [("cpu:0", 40), ("cpu:1", 20), ("cpu:2", 20), ("cpu:3", 20)]
        )
        pm = parallelize(tiny_model, chain)
        x = jax.random.normal(jax.random.key(1), (8, 16, 16, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (8, 12, 64), jnp.float32)
        t = jnp.linspace(999.0, 1.0, 8)
        got = pm(x, t, ctx)
        want = tiny_model(x, t, ctx)
        assert got.shape == want.shape
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )

    def test_weighted_split_respected(self, split_platforms, tiny_model):
        # 75/25 between groups: batch 8 → 6 on group one, 2 on group two.
        chain = DeviceChain.from_pairs(
            [("cpu:0", 37.5), ("cpu:1", 37.5), ("cpu:2", 12.5), ("cpu:3", 12.5)]
        )
        from comfyui_parallelanything_tpu import ParallelConfig

        pm = parallelize(
            tiny_model, chain, ParallelConfig(auto_memory_balance=False)
        )
        gweights = [g.weight for g in pm._groups]
        assert gweights[0] == pytest.approx(0.75)
        assert gweights[1] == pytest.approx(0.25)

    def test_zero_size_group_skipped(self, split_platforms, tiny_model):
        # Tiny batch with an extreme split: the second group gets 0 items and must
        # be skipped (the reference's active-device list, 1324-1337).
        chain = DeviceChain.from_pairs([("cpu:0", 99), ("cpu:2", 1)])
        from comfyui_parallelanything_tpu import ParallelConfig

        pm = parallelize(
            tiny_model, chain,
            ParallelConfig(auto_memory_balance=False, pad_small_batches=True),
        )
        x = jax.random.normal(jax.random.key(3), (2, 16, 16, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(4), (2, 12, 64), jnp.float32)
        out = pm(x, jnp.ones((2,)), ctx)
        assert out.shape == (2, 16, 16, 4)
        assert np.all(np.isfinite(np.asarray(out)))
