"""k-diffusion sampler family: schedules, denoiser wrapper, and the four samplers
against a tractable analytic model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.sampling import (
    SAMPLERS,
    SCHEDULER_NAMES,
    EpsDenoiser,
    karras_sigmas,
    make_sigmas,
    sampling_sigmas,
    sample_dpmpp_2m,
    sample_euler,
    sample_euler_ancestral,
    sample_heun,
    scaled_linear_schedule,
)
from comfyui_parallelanything_tpu.sampling.k_samplers import model_sigmas


class TestSchedules:
    def test_sampling_sigmas_descending_to_zero(self):
        sig = sampling_sigmas(10)
        s = np.asarray(sig)
        assert len(s) == 11
        assert np.all(np.diff(s) < 0) or (np.all(np.diff(s[:-1]) < 0) and s[-1] == 0)
        assert s[-1] == 0.0

    def test_karras_sigmas_range(self):
        sig = np.asarray(karras_sigmas(12, sigma_min=0.03, sigma_max=14.0))
        assert len(sig) == 13
        assert sig[0] == pytest.approx(14.0, rel=1e-5)
        assert sig[-2] == pytest.approx(0.03, rel=1e-5)
        assert sig[-1] == 0.0

    def test_model_sigmas_monotonic(self):
        table = np.asarray(model_sigmas(scaled_linear_schedule()))
        assert np.all(np.diff(table) > 0)

    @pytest.mark.parametrize("name", SCHEDULER_NAMES)
    def test_every_scheduler_descends_to_zero(self, name):
        # Shared contract of the whole KSampler menu: descending sigmas ending in
        # exactly 0, starting at (or within the last integer stride of) the
        # model's sigma_max. ddim_uniform's integer stride means its realized
        # step count can differ slightly from the request — like the reference.
        acp = scaled_linear_schedule()
        sig = np.asarray(make_sigmas(name, 12, acp))
        table = np.asarray(model_sigmas(acp))
        if name == "ddim_uniform":
            assert 11 <= len(sig) <= 15
            assert sig[0] == pytest.approx(float(table[-1]), rel=0.1)
            # Reference stride starts at table index 1 (not 0).
            assert sig[-2] == pytest.approx(float(table[1]), rel=1e-5)
        else:
            assert len(sig) == 13
            assert sig[0] == pytest.approx(float(table[-1]), rel=1e-4)
        if name == "kl_optimal":
            # Inclusive interpolation: last nonzero sigma is exactly sigma_min.
            assert sig[-2] == pytest.approx(float(table[0]), rel=1e-4)
        assert sig[-1] == 0.0
        assert np.all(np.diff(sig[:-1]) < 0), f"{name}: {sig}"

    def test_sgm_uniform_is_trailing(self):
        # The sgm spacing drops the final uniform point: its last nonzero sigma
        # sits a full stride above sigma_min, unlike "normal".
        acp = scaled_linear_schedule()
        normal = np.asarray(make_sigmas("normal", 10, acp))
        sgm = np.asarray(make_sigmas("sgm_uniform", 10, acp))
        assert sgm[-2] > normal[-2] * 5

    def test_beta_denser_at_ends(self):
        # Beta(0.6, 0.6) quantiles cluster TIMESTEPS at both schedule ends (the
        # sigma table's nonlinearity hides this in sigma space, so recover the
        # timestep of each emitted sigma from the table and compare strides).
        acp = scaled_linear_schedule()
        table = np.asarray(model_sigmas(acp))
        sig = np.asarray(make_sigmas("beta", 20, acp))[:-1]
        ts = np.array([int(np.abs(table - s).argmin()) for s in sig])
        strides = -np.diff(ts)
        assert strides[0] < strides[len(strides) // 2]
        assert strides[-1] < strides[len(strides) // 2]

    def test_beta_high_step_count_has_no_duplicates(self):
        # At >=150 steps the rounded Beta quantiles collide at the schedule ends;
        # the reference skips repeated timesteps — a repeated sigma would
        # divide-by-zero the multistep samplers (lms, dpm++ 2m sde).
        acp = scaled_linear_schedule()
        for n in (150, 250):
            sig = np.asarray(make_sigmas("beta", n, acp))
            assert np.all(np.diff(sig[:-1]) < 0), f"duplicate sigmas at {n} steps"

    def test_ddim_uniform_high_step_count_honors_request(self):
        # stride<=1 falls back to uniform trailing spacing — the realized count
        # must track the request, not balloon to the table length. (In the
        # integer-stride regime the reference-faithful overshoot remains, e.g.
        # 400 requested -> stride 2 -> 500 realized.)
        acp = scaled_linear_schedule()
        for n in (600, 999):
            sig = np.asarray(make_sigmas("ddim_uniform", n, acp))
            assert len(sig) == n + 1, (n, len(sig))
        assert len(np.asarray(make_sigmas("ddim_uniform", 400, acp))) == 501

    def test_unknown_scheduler_raises(self):
        with pytest.raises(ValueError, match="unknown scheduler"):
            make_sigmas("cosine", 10)


def _linear_eps_model(true_x0):
    """An oracle eps model: given x = x0 + sigma·eps (k-diffusion forward process),
    the model input is x/sqrt(sigma²+1); recover eps exactly from the known x0.

    eps(x_in, t) with x_in = (x0 + sigma·eps)/sqrt(sigma²+1):
    eps = (x_in·sqrt(sigma²+1) − x0)/sigma, where sigma comes from the timestep.
    """
    table = model_sigmas(scaled_linear_schedule())

    def model(x_in, t_vec, context=None, **kw):
        sigma = jnp.interp(t_vec[0], jnp.arange(len(table), dtype=jnp.float32), table)
        x = x_in * jnp.sqrt(sigma**2 + 1.0)
        return (x - true_x0) / sigma

    return model


class TestSamplersRecoverX0:
    """With an oracle eps model every deterministic sampler must recover x0
    (almost) exactly — the integration error term vanishes when x0 is constant."""

    @pytest.fixture()
    def problem(self):
        x0 = jax.random.normal(jax.random.key(0), (2, 4, 4, 3), jnp.float32)
        sigmas = sampling_sigmas(12)
        noise = jax.random.normal(jax.random.key(1), x0.shape, jnp.float32)
        x_init = x0 + sigmas[0] * noise
        denoise = EpsDenoiser(_linear_eps_model(x0))
        return x0, x_init, sigmas, denoise

    def test_euler(self, problem):
        x0, x_init, sigmas, denoise = problem
        out = sample_euler(denoise, x_init, sigmas)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=1e-2, atol=1e-2)

    def test_heun(self, problem):
        x0, x_init, sigmas, denoise = problem
        out = sample_heun(denoise, x_init, sigmas)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=1e-2, atol=1e-2)

    def test_dpmpp_2m(self, problem):
        x0, x_init, sigmas, denoise = problem
        out = sample_dpmpp_2m(denoise, x_init, sigmas)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=1e-2, atol=1e-2)

    def test_euler_ancestral_converges_near_x0(self, problem):
        x0, x_init, sigmas, denoise = problem
        out = sample_euler_ancestral(denoise, x_init, sigmas, jax.random.key(2))
        # Stochastic: looser tolerance, but must land near the oracle x0.
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=0.15, atol=0.15)

    def test_dpmpp_3m_sde_converges_near_x0(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            sample_dpmpp_3m_sde,
        )

        x0, x_init, sigmas, denoise = problem
        out = sample_dpmpp_3m_sde(denoise, x_init, sigmas, jax.random.key(3))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=0.15, atol=0.15)

    def test_dpmpp_3m_sde_eta_zero_deterministic_and_tight(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            sample_dpmpp_3m_sde,
        )

        x0, x_init, sigmas, denoise = problem
        a = sample_dpmpp_3m_sde(denoise, x_init, sigmas, jax.random.key(3), eta=0.0)
        b = sample_dpmpp_3m_sde(denoise, x_init, sigmas, jax.random.key(9), eta=0.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(a), np.asarray(x0), rtol=1e-2, atol=1e-2)

    def test_lcm_recovers_x0_exactly(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import sample_lcm

        x0, x_init, sigmas, denoise = problem
        out = sample_lcm(denoise, x_init, sigmas, jax.random.key(4))
        # The final LCM step returns the model x0 prediction directly — with an
        # oracle denoiser that is exact regardless of the noisy trajectory.
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=1e-5, atol=1e-5)

    def test_ddpm_converges_near_x0(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import sample_ddpm

        x0, x_init, sigmas, denoise = problem
        out = sample_ddpm(denoise, x_init, sigmas, jax.random.key(5))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=0.15, atol=0.15)

    def test_dpm_2_recovers_x0(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import sample_dpm_2

        x0, x_init, sigmas, denoise = problem
        out = sample_dpm_2(denoise, x_init, sigmas)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=1e-2, atol=1e-2)

    def test_dpm_2_ancestral_converges_near_x0(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            sample_dpm_2_ancestral,
        )

        x0, x_init, sigmas, denoise = problem
        out = sample_dpm_2_ancestral(denoise, x_init, sigmas, jax.random.key(6))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=0.15, atol=0.15)

    def test_dpmpp_2s_ancestral_converges_near_x0(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            sample_dpmpp_2s_ancestral,
        )

        x0, x_init, sigmas, denoise = problem
        out = sample_dpmpp_2s_ancestral(denoise, x_init, sigmas, jax.random.key(7))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=0.15, atol=0.15)

    def test_dpmpp_2s_ancestral_eta_zero_deterministic_and_tight(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            sample_dpmpp_2s_ancestral,
        )

        x0, x_init, sigmas, denoise = problem
        a = sample_dpmpp_2s_ancestral(denoise, x_init, sigmas, jax.random.key(7),
                                      eta=0.0)
        b = sample_dpmpp_2s_ancestral(denoise, x_init, sigmas, jax.random.key(11),
                                      eta=0.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(a), np.asarray(x0), rtol=1e-2, atol=1e-2)

    def test_dpmpp_sde_converges_near_x0(self, problem):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            sample_dpmpp_sde,
        )

        x0, x_init, sigmas, denoise = problem
        out = sample_dpmpp_sde(denoise, x_init, sigmas, jax.random.key(8))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=0.15, atol=0.15)

    @pytest.mark.parametrize("name", ["uni_pc", "uni_pc_bh2"])
    def test_unipc_recovers_x0(self, problem, name):
        x0, x_init, sigmas, denoise = problem
        out = SAMPLERS[name](denoise, x_init, sigmas)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0), rtol=1e-2, atol=1e-2)

    def test_unipc_variants_differ_midway(self, problem):
        # bh1 and bh2 share the base step but weight the corrections
        # differently — a truncated (non-terminal) run must show it.
        x0, x_init, sigmas, denoise = problem
        a = SAMPLERS["uni_pc"](denoise, x_init, sigmas[:6])
        b = SAMPLERS["uni_pc_bh2"](denoise, x_init, sigmas[:6])
        assert float(jnp.abs(a - b).max()) > 0

    def test_unipc_coeff_table_shape_and_order_ramp(self):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            unipc_coeff_table,
        )

        sigmas = sampling_sigmas(8)
        C = unipc_coeff_table(sigmas, order=3)
        assert C.shape == (8, 9)
        # Step 0 runs order 1: no predictor/older-corrector weights, rc_t=0.5.
        assert C[0, 2] == 0 and C[0, 4] == 0 and C[0, 6] == 0.5
        # Step 1 runs order 2: the official UniPC hardcodes the order-2
        # predictor weight to exactly 0.5 (not the 1×1 solve).
        assert C[1, 2] == 0.5 and C[1, 3] == 0
        # The final step also ramps down to order 1 (lower_order_final); the
        # penultimate runs order 2 with the same hardcoded predictor weight.
        assert C[-1, 2] == 0 and C[-1, 7] == 0
        assert C[-2, 2] == 0.5
        # An interior step at full order has predictor + history weights.
        assert C[4, 2] != 0 and C[4, 3] != 0 and C[4, 7] != 0

    def test_flow_oracle_recovers_x0_across_k_samplers(self):
        # prediction="flow": the k-diffusion ODE d = (x − x0)/σ IS the flow
        # velocity, so with an oracle velocity model every deterministic
        # sampler must recover x0 on a flow-time schedule.
        from comfyui_parallelanything_tpu.sampling.flow import flow_timesteps

        x0 = jax.random.normal(jax.random.key(0), (2, 4, 4, 3), jnp.float32)

        def vmodel(x, t_vec, context=None, **kw):
            return (x - x0) / t_vec[0]  # exact velocity under x_t=(1−t)x0+tn

        denoise = EpsDenoiser(vmodel, prediction="flow")
        sigmas = flow_timesteps(10, shift=1.15)
        noise = jax.random.normal(jax.random.key(1), x0.shape)
        x_init = sigmas[0] * noise + (1.0 - sigmas[0]) * x0
        for name in ("euler", "heun", "dpm_2", "dpmpp_2m", "uni_pc", "lms"):
            out = SAMPLERS[name](denoise, x_init, sigmas)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(x0), rtol=1e-2, atol=1e-2,
                err_msg=name,
            )

    def test_registry_complete(self):
        from comfyui_parallelanything_tpu.sampling import RNG_SAMPLERS

        assert set(SAMPLERS) == {
            "euler", "euler_ancestral", "heun", "dpm_2", "dpm_2_ancestral",
            "lms", "dpmpp_2s_ancestral", "dpmpp_sde", "dpmpp_2m",
            "dpmpp_2m_sde", "dpmpp_3m_sde", "lcm", "ddpm", "uni_pc",
            "uni_pc_bh2",
        }
        assert RNG_SAMPLERS <= set(SAMPLERS)


class TestCFGRescale:
    def test_rescale_matches_cond_std(self):
        from comfyui_parallelanything_tpu.sampling.cfg import rescale_guidance

        rng = np.random.default_rng(17)
        cond = jnp.asarray(rng.normal(size=(2, 8, 8, 4)), jnp.float32)
        guided = cond * 3.0 + 1.0  # inflated std (what high cfg does)
        full = rescale_guidance(guided, cond, 1.0)
        # phi=1: per-sample std matches the cond prediction exactly.
        np.testing.assert_allclose(
            np.asarray(full).std(axis=(1, 2, 3)),
            np.asarray(cond).std(axis=(1, 2, 3)), rtol=1e-5,
        )
        # phi=0: identity. phi=0.5: halfway.
        np.testing.assert_array_equal(
            np.asarray(rescale_guidance(guided, cond, 0.0)), np.asarray(guided)
        )
        half = rescale_guidance(guided, cond, 0.5)
        np.testing.assert_allclose(
            np.asarray(half), 0.5 * np.asarray(full) + 0.5 * np.asarray(guided),
            rtol=1e-6,
        )

    def test_run_sampler_accepts_cfg_rescale(self):
        # e2e: rescale changes the output when CFG is active.
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        noise = jax.random.normal(jax.random.key(1), (2, 8, 8, 4))
        ctx = jax.random.normal(jax.random.key(2), (2, 4, 8))
        un = jax.random.normal(jax.random.key(3), (2, 4, 8))

        def model2(x, t, context=None, **kw):
            # PER-SAMPLE context scale (CFG doubles the batch, so a global mean
            # would give cond and uncond halves the identical value) so the two
            # halves differ in STD — a constant offset would leave the rescale
            # factor at exactly 1.
            s = 0.1 + 0.05 * context.mean(axis=(1, 2))[:, None, None, None]
            return x * s

        base = run_sampler(model2, noise, ctx, sampler="euler", steps=3,
                           cfg_scale=5.0, uncond_context=un)
        resc = run_sampler(model2, noise, ctx, sampler="euler", steps=3,
                           cfg_scale=5.0, uncond_context=un, cfg_rescale=0.7)
        assert not np.allclose(np.asarray(base), np.asarray(resc))


class TestCFGBatching:
    def test_cfg_doubles_batch_through_model(self):
        calls = []

        def model(x, t, context=None, **kw):
            calls.append(x.shape[0])
            return jnp.zeros_like(x)

        den = EpsDenoiser(
            model,
            context=jnp.ones((2, 4, 8)),
            cfg_scale=5.0,
            uncond_context=jnp.zeros((2, 4, 8)),
        )
        x = jnp.ones((2, 4, 4, 3))
        den(x, jnp.float32(1.0))
        assert calls == [4]  # cond ‖ uncond fused into one forward


class TestNewSamplers:
    @pytest.mark.parametrize("sampler", ["lms", "dpmpp_2m_sde"])
    def test_converges_on_perfect_denoiser(self, sampler):
        """A denoise fn that always returns the target x0 must be recovered."""
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            karras_sigmas,
            sample_dpmpp_2m_sde,
            sample_lms,
        )

        target = 0.3

        sigmas = karras_sigmas(8)
        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        x = noise * sigmas[0]
        denoise = lambda x_, s: jnp.full_like(x_, target)
        if sampler == "lms":
            out = sample_lms(denoise, x, sigmas)
        else:
            out = sample_dpmpp_2m_sde(denoise, x, sigmas, jax.random.key(1), eta=0.0)
        np.testing.assert_allclose(np.asarray(out), target, rtol=1e-2, atol=2e-2)

    def test_sde_eta_zero_deterministic(self):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            karras_sigmas,
            sample_dpmpp_2m_sde,
        )

        sigmas = karras_sigmas(5)
        x = jax.random.normal(jax.random.key(2), (1, 4, 4, 4)) * sigmas[0]
        denoise = lambda x_, s: x_ * 0.5
        a = sample_dpmpp_2m_sde(denoise, x, sigmas, jax.random.key(3), eta=0.0)
        b = sample_dpmpp_2m_sde(denoise, x, sigmas, jax.random.key(9), eta=0.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sde_noise_depends_on_rng(self):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            karras_sigmas,
            sample_dpmpp_2m_sde,
        )

        sigmas = karras_sigmas(5)
        x = jax.random.normal(jax.random.key(2), (1, 4, 4, 4)) * sigmas[0]
        denoise = lambda x_, s: x_ * 0.5
        a = sample_dpmpp_2m_sde(denoise, x, sigmas, jax.random.key(3))
        b = sample_dpmpp_2m_sde(denoise, x, sigmas, jax.random.key(9))
        assert not np.allclose(np.asarray(a), np.asarray(b))

    def test_run_sampler_dispatch(self):
        from comfyui_parallelanything_tpu.sampling.runner import (
            SAMPLER_NAMES,
            run_sampler,
        )

        assert "lms" in SAMPLER_NAMES and "dpmpp_2m_sde" in SAMPLER_NAMES
        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        for s in ("lms", "dpmpp_2m_sde"):
            out = run_sampler(
                lambda x, t, c=None, **kw: 0.1 * x, noise, None, sampler=s,
                steps=3, rng=jax.random.key(1),
            )
            assert np.isfinite(np.asarray(out)).all()


class TestFlowPredictionRouting:
    """prediction="flow" routes the k-sampler menu onto flow-time schedules —
    the host KSampler's CONST model-sampling wrapper for FLUX/SD3/WAN."""

    def _vmodel(self):
        def vmodel(x, t, context=None, **kw):
            return 0.2 * x + 0.1 * jnp.sin(t)[:, None, None, None]

        return vmodel

    def test_euler_flow_equals_flow_euler(self):
        # k-euler with flow prediction integrates the SAME ODE flow_euler
        # does: d = (x − x0)/σ = v. On an identical schedule the outputs must
        # agree to fp tolerance. (run_sampler's k-branch uses the host's
        # "normal" CONST ladder, which ends at σ_min≈1e-3 rather than
        # flow_euler's raw linspace — so the ladder is pinned explicitly.)
        from comfyui_parallelanything_tpu.sampling.flow import flow_euler_sample
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            flow_sigma_table,
            make_sigmas,
            sample_euler,
        )

        sigmas = make_sigmas("normal", 7, sigma_table=flow_sigma_table(1.3))
        noise = jax.random.normal(jax.random.key(0), (2, 4, 4, 4))
        x_init = sigmas[0] * noise
        a = flow_euler_sample(self._vmodel(), x_init, None, ts=sigmas)
        b = sample_euler(
            EpsDenoiser(self._vmodel(), prediction="flow"), x_init, sigmas
        )
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    def test_flow_guidance_kwarg_reaches_model(self):
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        seen = []

        def vmodel(x, t, context=None, guidance=None, **kw):
            seen.append(guidance)
            return 0.1 * x

        noise = jax.random.normal(jax.random.key(0), (2, 4, 4, 4))
        run_sampler(vmodel, noise, None, sampler="dpmpp_2m", steps=3,
                    prediction="flow", guidance=2.5)
        assert seen and all(
            g is not None and g.shape == (2,) and float(g[0]) == 2.5
            for g in seen
        )

    def test_flow_img2img_mixes_toward_init(self):
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        init = jnp.full((1, 4, 4, 4), 3.0)
        out = run_sampler(self._vmodel(), noise, None, sampler="euler",
                          steps=4, prediction="flow", init_latent=init,
                          denoise=0.4)
        # Low strength keeps the result near the init, not the noise.
        assert float(jnp.abs(out - init).mean()) < float(jnp.abs(out - noise).mean())

    def test_ddim_rejects_flow(self):
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        with pytest.raises(ValueError, match="alpha-bar"):
            run_sampler(self._vmodel(), noise, None, sampler="ddim", steps=3,
                        prediction="flow")

    def test_ddpm_rejects_flow(self):
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        with pytest.raises(ValueError, match="rectified-flow"):
            run_sampler(self._vmodel(), noise, None, sampler="ddpm", steps=3,
                        prediction="flow", rng=jax.random.key(1))

    def test_flow_scheduler_menu_honored(self):
        # The host applies its scheduler menu to CONST (flow) models; karras
        # and normal must produce different flow-time ladders and outputs.
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            flow_sigma_table,
            make_sigmas,
        )
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        table = flow_sigma_table(shift=1.2)
        normal = make_sigmas("normal", 8, sigma_table=table)
        karras = make_sigmas("karras", 8, sigma_table=table)
        for sig in (normal, karras):
            s = np.asarray(sig)
            assert (np.diff(s) < 0).all() and s[-1] == 0.0
            assert s[0] <= 1.0 + 1e-6  # flow time never exceeds 1
        assert not np.allclose(np.asarray(normal), np.asarray(karras))

        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        a = run_sampler(self._vmodel(), noise, None, sampler="euler", steps=6,
                        prediction="flow", scheduler="normal")
        b = run_sampler(self._vmodel(), noise, None, sampler="euler", steps=6,
                        prediction="flow", scheduler="karras")
        assert not np.allclose(np.asarray(a), np.asarray(b))

    def test_euler_ancestral_flow_uses_rf_renoise(self):
        # Oracle flow model: the RF ancestral form must converge near x0,
        # and its output must differ from the VE renoise math.
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            flow_sigma_table,
            make_sigmas,
            sample_euler_ancestral,
            sample_euler_ancestral_rf,
        )

        x0 = jax.random.normal(jax.random.key(0), (2, 4, 4, 3), jnp.float32)

        def vmodel(x, t_vec, context=None, **kw):
            return (x - x0) / t_vec[0]

        denoise = EpsDenoiser(vmodel, prediction="flow")
        sigmas = make_sigmas("normal", 10, sigma_table=flow_sigma_table())
        noise = jax.random.normal(jax.random.key(1), x0.shape)
        x_init = sigmas[0] * noise + (1.0 - sigmas[0]) * x0
        rf = sample_euler_ancestral_rf(denoise, x_init, sigmas, jax.random.key(2))
        np.testing.assert_allclose(np.asarray(rf), np.asarray(x0),
                                   rtol=0.15, atol=0.15)
        # With the oracle denoiser the terminal step returns x0 exactly for
        # BOTH forms — the renoise difference shows on a truncated (non-
        # terminal) trajectory.
        rf_mid = sample_euler_ancestral_rf(
            denoise, x_init, sigmas[:5], jax.random.key(2)
        )
        ve_mid = sample_euler_ancestral(
            denoise, x_init, sigmas[:5], jax.random.key(2)
        )
        assert not np.allclose(np.asarray(rf_mid), np.asarray(ve_mid))

    def test_dpmpp_2s_ancestral_flow_uses_rf_form(self):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            flow_sigma_table,
            make_sigmas,
            sample_dpmpp_2s_ancestral,
            sample_dpmpp_2s_ancestral_rf,
        )

        x0 = jax.random.normal(jax.random.key(0), (2, 4, 4, 3), jnp.float32)

        def vmodel(x, t_vec, context=None, **kw):
            return (x - x0) / t_vec[0]

        denoise = EpsDenoiser(vmodel, prediction="flow")
        sigmas = make_sigmas("normal", 10, sigma_table=flow_sigma_table())
        noise = jax.random.normal(jax.random.key(1), x0.shape)
        x_init = sigmas[0] * noise + (1.0 - sigmas[0]) * x0
        rf = sample_dpmpp_2s_ancestral_rf(denoise, x_init, sigmas,
                                          jax.random.key(2))
        np.testing.assert_allclose(np.asarray(rf), np.asarray(x0),
                                   rtol=0.15, atol=0.15)
        # Renoise forms differ on a truncated (non-terminal) trajectory.
        rf_mid = sample_dpmpp_2s_ancestral_rf(denoise, x_init, sigmas[:5],
                                              jax.random.key(2))
        ve_mid = sample_dpmpp_2s_ancestral(denoise, x_init, sigmas[:5],
                                           jax.random.key(2))
        assert not np.allclose(np.asarray(rf_mid), np.asarray(ve_mid))

    def test_lcm_flow_recovers_x0_exactly(self):
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            flow_sigma_table,
            make_sigmas,
            sample_lcm_rf,
        )

        x0 = jax.random.normal(jax.random.key(0), (2, 4, 4, 3), jnp.float32)

        def vmodel(x, t_vec, context=None, **kw):
            return (x - x0) / t_vec[0]

        denoise = EpsDenoiser(vmodel, prediction="flow")
        sigmas = make_sigmas("normal", 8, sigma_table=flow_sigma_table())
        noise = jax.random.normal(jax.random.key(1), x0.shape)
        x_init = sigmas[0] * noise + (1.0 - sigmas[0]) * x0
        out = sample_lcm_rf(denoise, x_init, sigmas, jax.random.key(2))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0),
                                   rtol=1e-4, atol=1e-4)

    def test_flux_config_declares_flow(self):
        from comfyui_parallelanything_tpu.models import (
            flux_dev_config,
            wan_1_3b_config,
        )

        assert flux_dev_config().prediction == "flow"
        assert wan_1_3b_config().prediction == "flow"


class TestMultiCond:
    """Stock ConditioningCombine/SetArea semantics: per-cond predictions blend
    area-weight-normalized (EpsDenoiser._combine_conds)."""

    @staticmethod
    def _mean_model(x, t_vec, context=None, **kw):
        # Prediction = per-row mean of the context: trivially shows which
        # cond(s) drove each pixel, and respects CFG's batched cond‖uncond.
        m = jnp.mean(context, axis=tuple(range(1, context.ndim)))
        return jnp.ones_like(x) * m.reshape((-1,) + (1,) * (x.ndim - 1))

    def test_area_cond_blends_inside_box_only(self):
        x = jnp.zeros((1, 8, 8, 4), jnp.float32)
        ctx0 = jnp.zeros((1, 3, 5), jnp.float32)
        ctx1 = jnp.ones((1, 7, 5), jnp.float32)  # different token length: own call
        d = EpsDenoiser(
            self._mean_model, ctx0,
            extra_conds=[{"context": ctx1, "area": (4, 4, 0, 0),
                          "strength": 1.0}],
        )
        x0 = d(x, jnp.float32(1.0))
        eps = -(np.asarray(x0))  # x0 = x − σ·eps with x = 0, σ = 1
        # Inside the box both conds contribute: (1·0 + 1·1)/2.
        np.testing.assert_allclose(eps[0, 0, 0, 0], 0.5, atol=1e-6)
        np.testing.assert_allclose(eps[0, 3, 3, 0], 0.5, atol=1e-6)
        # Outside only the primary does.
        np.testing.assert_allclose(eps[0, 7, 7, 0], 0.0, atol=1e-6)
        np.testing.assert_allclose(eps[0, 0, 6, 0], 0.0, atol=1e-6)

    def test_mask_cond_equals_equivalent_area_box(self):
        # A pixel-space mask covering exactly the area box must weight
        # identically to SetArea (the SetMask path resizes pixels → latent
        # cells; box (4,4,0,0) in an 8×8 latent == top-left 32×32 px of 64²).
        x = jnp.zeros((1, 8, 8, 4), jnp.float32)
        ctx0 = jnp.zeros((1, 3, 5), jnp.float32)
        ctx1 = jnp.ones((1, 7, 5), jnp.float32)
        mask = jnp.zeros((1, 64, 64)).at[:, :32, :32].set(1.0)
        d_mask = EpsDenoiser(
            self._mean_model, ctx0,
            extra_conds=[{"context": ctx1, "mask": mask, "strength": 1.0}],
        )
        d_area = EpsDenoiser(
            self._mean_model, ctx0,
            extra_conds=[{"context": ctx1, "area": (4, 4, 0, 0),
                          "strength": 1.0}],
        )
        np.testing.assert_allclose(
            np.asarray(d_mask(x, jnp.float32(1.0))),
            np.asarray(d_area(x, jnp.float32(1.0))), atol=1e-6,
        )

    def test_mask_and_area_compose(self):
        # SetMask then SetArea: the cond carries both — stock composes
        # (area crop × mask weight), so only the INTERSECTION contributes.
        x = jnp.zeros((1, 8, 8, 4), jnp.float32)
        ctx0 = jnp.zeros((1, 3, 5), jnp.float32)
        ctx1 = jnp.ones((1, 7, 5), jnp.float32)
        mask = jnp.zeros((1, 64, 64)).at[:, :, :32].set(1.0)  # left half
        d = EpsDenoiser(
            self._mean_model, ctx0,
            extra_conds=[{"context": ctx1, "mask": mask,
                          "area": (4, 8, 0, 0), "strength": 1.0}],  # top half
        )
        eps = -np.asarray(d(x, jnp.float32(1.0)))
        np.testing.assert_allclose(eps[0, 0, 0, 0], 0.5, atol=1e-6)  # both
        np.testing.assert_allclose(eps[0, 0, 7, 0], 0.0, atol=1e-6)  # top-right: area only
        np.testing.assert_allclose(eps[0, 7, 0, 0], 0.0, atol=1e-6)  # bottom-left: mask only

        # Area strength × mask strength MULTIPLY (stock get_area_and_mult):
        # weight 0.5 × 0.5 = 0.25 against primary weight 1 → 0.25/1.25.
        d2 = EpsDenoiser(
            self._mean_model, ctx0,
            extra_conds=[{"context": ctx1, "mask": mask,
                          "area": (4, 8, 0, 0), "strength": 0.5,
                          "mask_strength": 0.5}],
        )
        eps2 = -np.asarray(d2(x, jnp.float32(1.0)))
        np.testing.assert_allclose(eps2[0, 0, 0, 0], 0.25 / 1.25, atol=1e-6)

    def test_primary_cond_mask_scopes_primary(self):
        # SetMask on the PRIMARY positive: outside the mask no cond covers
        # the pixel → falls back to the primary prediction (the divide-by-
        # zero guard), inside it's primary-as-usual.
        x = jnp.zeros((1, 8, 8, 4), jnp.float32)
        ctx0 = jnp.ones((1, 3, 5), jnp.float32)
        mask = jnp.zeros((1, 64, 64)).at[:, :32, :].set(1.0)
        d = EpsDenoiser(self._mean_model, ctx0, cond_mask=mask)
        out = d(x, jnp.float32(1.0))
        eps = -np.asarray(out)
        np.testing.assert_allclose(eps[0, 0, 0, 0], 1.0, atol=1e-6)
        np.testing.assert_allclose(eps[0, 7, 7, 0], 1.0, atol=1e-6)

    def test_full_frame_combine_averages(self):
        x = jnp.zeros((1, 4, 4, 2), jnp.float32)
        d = EpsDenoiser(
            self._mean_model, jnp.zeros((1, 3, 5)),
            extra_conds=[{"context": jnp.ones((1, 3, 5))}],
        )
        eps = -np.asarray(d(x, jnp.float32(1.0)))
        np.testing.assert_allclose(eps, 0.5, atol=1e-6)

    def test_strengths_weight_the_blend(self):
        x = jnp.zeros((1, 4, 4, 2), jnp.float32)
        d = EpsDenoiser(
            self._mean_model, jnp.zeros((1, 3, 5)),
            extra_conds=[{"context": jnp.ones((1, 3, 5)), "strength": 3.0}],
        )
        eps = -np.asarray(d(x, jnp.float32(1.0)))
        np.testing.assert_allclose(eps, 0.75, atol=1e-6)  # (0·1 + 1·3)/(1+3)

    def test_cfg_applies_extras_to_cond_half_only(self):
        x = jnp.zeros((1, 4, 4, 2), jnp.float32)
        d = EpsDenoiser(
            self._mean_model, jnp.zeros((1, 3, 5)),
            cfg_scale=2.0, uncond_context=jnp.full((1, 3, 5), -1.0),
            extra_conds=[{"context": jnp.ones((1, 3, 5))}],
        )
        eps = -np.asarray(d(x, jnp.float32(1.0)))
        # cond = (0+1)/2 = 0.5 blended; uncond = −1; cfg: −1 + 2·(0.5 − (−1)).
        np.testing.assert_allclose(eps, 2.0, atol=1e-5)

    def test_multi_cond_rejected_on_ddim_and_flow_euler(self):
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        with pytest.raises(ValueError, match="k-sampler"):
            run_sampler(
                lambda x, t, c=None, **k: x, jnp.zeros((1, 4, 4, 4)),
                jnp.zeros((1, 3, 5)), sampler="ddim", steps=2,
                extra_conds=[{"context": jnp.ones((1, 3, 5))}],
            )

    def test_non_divisor_extra_cond_batch_raises(self):
        # Direct run_sampler/EpsDenoiser API callers (no node-layer
        # pre-validation) get the same clear error the node layer raises, not
        # a silent 1x repeat followed by an XLA shape mismatch.
        x = jnp.zeros((3, 4, 4, 2), jnp.float32)
        d = EpsDenoiser(
            self._mean_model, jnp.zeros((3, 3, 5)),
            extra_conds=[{"context": jnp.ones((2, 3, 5))}],
        )
        with pytest.raises(ValueError, match="does not divide"):
            d(x, jnp.float32(1.0))

    def test_timestep_range_gates_extras(self):
        # Stock SetTimestepRange + Combine: the extra prompt contributes only
        # inside its progress window. eps family: progress = 1 - t/999.
        x = jnp.zeros((1, 4, 4, 2), jnp.float32)
        d = EpsDenoiser(
            self._mean_model, jnp.zeros((1, 3, 5)),
            extra_conds=[{"context": jnp.ones((1, 3, 5)),
                          "timestep_range": (0.0, 0.5)}],
        )
        # x0 = x - sigma*eps with x = 0, so eps = -x0/sigma.
        # Early sampling: sigma high -> t near table top -> progress ~0: ON.
        s_hi = float(d.sigma_table[-1])
        eps_early = -np.asarray(d(x, d.sigma_table[-1])) / s_hi
        np.testing.assert_allclose(eps_early, 0.5, atol=1e-5)
        # Late sampling: sigma low -> progress ~1: OFF (primary only).
        s_lo = float(d.sigma_table[0])
        eps_late = -np.asarray(d(x, d.sigma_table[0])) / s_lo
        np.testing.assert_allclose(eps_late, 0.0, atol=1e-5)


class TestAreaPercentage:
    @staticmethod
    def _mean_model(x, t_vec, context=None, **kw):
        m = jnp.mean(context, axis=tuple(range(1, context.ndim)))
        return jnp.ones_like(x) * m.reshape((-1,) + (1,) * (x.ndim - 1))

    def test_fractional_box_equals_pixel_box(self):
        # area_pct (0.5, 0.5, 0, 0) on an 8x8 latent == area (4, 4, 0, 0).
        x = jnp.zeros((1, 8, 8, 4), jnp.float32)
        ctx0 = jnp.zeros((1, 3, 5), jnp.float32)
        ctx1 = jnp.ones((1, 7, 5), jnp.float32)
        d_pct = EpsDenoiser(
            self._mean_model, ctx0,
            extra_conds=[{"context": ctx1,
                          "area_pct": (0.5, 0.5, 0.0, 0.0),
                          "strength": 1.0}],
        )
        d_px = EpsDenoiser(
            self._mean_model, ctx0,
            extra_conds=[{"context": ctx1, "area": (4, 4, 0, 0),
                          "strength": 1.0}],
        )
        np.testing.assert_allclose(
            np.asarray(d_pct(x, jnp.float32(1.0))),
            np.asarray(d_px(x, jnp.float32(1.0))), atol=1e-6,
        )

    def test_primary_pct_scopes(self):
        x = jnp.zeros((1, 8, 8, 4), jnp.float32)
        d = EpsDenoiser(self._mean_model, jnp.ones((1, 3, 5)),
                        cond_area_pct=(0.5, 1.0, 0.0, 0.0))
        out = d(x, jnp.float32(1.0))
        assert np.isfinite(np.asarray(out)).all()
