"""Tests for device discovery + memory probes (reference: get_available_devices
770-786, get_free_vram 724-735)."""

import pytest

from comfyui_parallelanything_tpu.devices.discovery import (
    available_devices,
    default_device,
    device_platform,
    get_device,
)
from comfyui_parallelanything_tpu.devices.memory import (
    free_memory_bytes,
    total_memory_bytes,
)


class TestDiscovery:
    def test_cpu_always_listed_last(self):
        # Parity: 'cpu' is always in the dropdown (771, 837).
        devs = available_devices()
        assert "cpu" in devs
        assert devs[-1] == "cpu"

    def test_platform_parse(self):
        assert device_platform("tpu:3") == "tpu"
        assert device_platform("cpu") == "cpu"
        assert device_platform("TPU:0") == "tpu"

    def test_get_device_cpu_indices(self, cpu_devices):
        assert get_device("cpu").id == 0
        assert get_device("cpu:5").id == 5

    def test_get_device_errors(self):
        with pytest.raises(ValueError):
            get_device("cpu:banana")
        with pytest.raises(ValueError):
            get_device("quantum:0")
        with pytest.raises(ValueError):
            get_device("cpu:9999")

    def test_default_device_exists(self):
        d = default_device()
        assert d.platform in ("cpu", "tpu", "gpu")


class TestMemory:
    def test_cpu_reports_zero_or_stats(self, cpu_devices):
        # Host CPU devices expose no stats → 0, the reference's non-CUDA behavior.
        v = free_memory_bytes(cpu_devices[0])
        assert v >= 0
        assert total_memory_bytes(cpu_devices[0]) >= 0
