"""Continuous-batching serving subsystem (serving/): equivalence of shared-
batch sampling vs serial, step-boundary join/leave, per-lane cancel, policy,
and the dispatch-count batching effect — all off-hardware (CPU + the 8-device
virtual mesh), with deterministic manual pumping (``auto=False``)."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.sampling.k_samplers import make_sigmas
from comfyui_parallelanything_tpu.sampling.lane_specs import (
    LANE_SPECS,
    lane_eval_count,
)
from comfyui_parallelanything_tpu.sampling.runner import run_sampler
from comfyui_parallelanything_tpu.serving import (
    AdmissionQueue,
    ContinuousBatchingScheduler,
    DeadlineExceeded,
    ServingRejected,
    get_scheduler,
)
from comfyui_parallelanything_tpu.utils.metrics import registry
from comfyui_parallelanything_tpu.utils.progress import (
    Interrupted,
    progress_scope,
)

# bf16-scale tolerances (CLAUDE.md: this XLA CPU runs f32 matmuls at bf16).
TOL = dict(rtol=2e-3, atol=1e-4)


def tiny_model(x, t, context=None, **kw):
    """Per-sample-independent stand-in denoiser: every output element depends
    only on its own sample's latent/t/context — the property that makes
    co-batching result-stable, which the equivalence tests then verify."""
    c = jnp.mean(context, axis=tuple(range(1, context.ndim)))
    c = c.reshape((-1,) + (1,) * (x.ndim - 1))
    tt = t.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.tanh(x * 0.9 + c * 0.1) * (0.5 + 0.1 * tt / 1000.0)


def mk_inputs(seed, batch=1):
    r = np.random.default_rng(seed)
    noise = jnp.asarray(r.normal(size=(batch, 8, 8, 4)).astype(np.float32))
    ctx = jnp.asarray(r.normal(size=(batch, 6, 16)).astype(np.float32))
    return noise, ctx


@pytest.fixture
def sched():
    s = ContinuousBatchingScheduler(max_width=4, auto=False).install()
    try:
        yield s
    finally:
        s.uninstall()
        s.shutdown()


def _bg(fn, *args):
    t = threading.Thread(target=fn, args=args, daemon=True)
    t.start()
    return t


def _wait_enqueued(s, n, timeout=20):
    """Block until >= n requests are visible to the scheduler (queued or
    seated) — the deterministic submit/pump handshake for manual mode."""
    t0 = time.time()
    while time.time() - t0 < timeout:
        with s._lock:
            tot = sum(
                len(b.queue) + len(b.active_lanes())
                for b in s.buckets.values()
            )
        if tot >= n:
            return
        time.sleep(0.005)
    raise TimeoutError(f"never saw {n} enqueued requests")


class TestEquivalence:
    def test_concurrent_ragged_batch_matches_serial(self, sched):
        """Acceptance: prompts sampled inside a shared batch (unrelated
        co-resident lanes, ragged schedules) match their serial twins; N
        concurrent prompts cost ~max(steps) dispatches, not sum(steps)."""
        plans = [(1, 4), (2, 6), (3, 8)]
        sched.uninstall()
        serial = {
            s: run_sampler(tiny_model, *mk_inputs(s), sampler="euler", steps=n)
            for s, n in plans
        }
        sched.install()
        results = {}

        def worker(seed, steps):
            noise, ctx = mk_inputs(seed)
            results[seed] = run_sampler(
                tiny_model, noise, ctx, sampler="euler", steps=steps
            )

        threads = [_bg(worker, s, n) for s, n in plans]
        _wait_enqueued(sched, len(plans))
        sched.drain()
        for t in threads:
            t.join(20)
        assert sched.total_dispatches() <= 8 + 2  # max steps + join slack
        [b] = sched.buckets.values()  # one key → one bucket
        for s, _ in plans:
            np.testing.assert_allclose(
                np.asarray(results[s]), np.asarray(serial[s]), **TOL
            )

    def test_mid_flight_join_matches_serial(self, sched):
        """A request entering mid-flight joins at a step boundary with its own
        per-lane step state and still reproduces its serial result."""
        sched.uninstall()
        serial_a = run_sampler(tiny_model, *mk_inputs(10), sampler="euler",
                               steps=8)
        serial_b = run_sampler(tiny_model, *mk_inputs(11), sampler="euler",
                               steps=4)
        sched.install()
        results = {}

        def worker(seed, steps):
            noise, ctx = mk_inputs(seed)
            results[seed] = run_sampler(
                tiny_model, noise, ctx, sampler="euler", steps=steps
            )

        ta = _bg(worker, 10, 8)
        _wait_enqueued(sched, 1)
        for _ in range(3):
            sched.pump()  # A is 3 steps in...
        tb = _bg(worker, 11, 4)
        _wait_enqueued(sched, 2)  # ...when B arrives (A seated + B queued)
        start = sched.total_dispatches()
        sched.drain()
        ta.join(20)
        tb.join(20)
        # B rode along inside A's remaining 5 dispatches — no extra cost.
        assert sched.total_dispatches() - start <= 5 + 1
        np.testing.assert_allclose(np.asarray(results[10]),
                                   np.asarray(serial_a), **TOL)
        np.testing.assert_allclose(np.asarray(results[11]),
                                   np.asarray(serial_b), **TOL)

    def test_cfg_lanes_match_serial(self, sched):
        """Per-lane cfg_scale: two co-resident CFG requests with DIFFERENT
        guidance scales each match their serial twin."""
        plans = [(21, 5, 7.5), (22, 5, 3.0)]
        sched.uninstall()
        serial = {}
        for s, n, cfg in plans:
            noise, ctx = mk_inputs(s)
            _, uctx = mk_inputs(s + 100)
            serial[s] = run_sampler(
                tiny_model, noise, ctx, sampler="euler", steps=n,
                cfg_scale=cfg, uncond_context=uctx,
            )
        sched.install()
        results = {}

        def worker(seed, steps, cfg):
            noise, ctx = mk_inputs(seed)
            _, uctx = mk_inputs(seed + 100)
            results[seed] = run_sampler(
                tiny_model, noise, ctx, sampler="euler", steps=steps,
                cfg_scale=cfg, uncond_context=uctx,
            )

        threads = [_bg(worker, *p) for p in plans]
        _wait_enqueued(sched, 2)
        sched.drain()
        for t in threads:
            t.join(20)
        for s, _, _ in plans:
            np.testing.assert_allclose(np.asarray(results[s]),
                                       np.asarray(serial[s]), **TOL)

    def test_flow_prediction_matches_serial(self, sched):
        """prediction="flow" lanes (FLUX-family k-sampler path): flow time
        rides per-lane, guidance kwarg stacks per-lane."""
        sched.uninstall()
        noise, ctx = mk_inputs(31)
        serial = run_sampler(tiny_model, noise, ctx, sampler="euler", steps=5,
                             prediction="flow", shift=1.15, guidance=3.5)
        sched.install()
        results = {}

        def worker():
            n, c = mk_inputs(31)
            results[0] = run_sampler(
                tiny_model, n, c, sampler="euler", steps=5,
                prediction="flow", shift=1.15, guidance=3.5,
            )

        t = _bg(worker)
        _wait_enqueued(sched, 1)
        sched.drain()
        t.join(20)
        np.testing.assert_allclose(np.asarray(results[0]), np.asarray(serial),
                                   **TOL)
        assert sched.total_dispatches() == 5

    def test_mesh_batch_matches_serial(self, sched, cpu_devices):
        """Acceptance: same equivalence on the 8-device virtual mesh — bucket
        programs compose with the orchestrator's data sharding (lane axis =
        batch axis, width rounded to the mesh's data width)."""
        rng = np.random.default_rng(0)
        params = {
            "w": jnp.asarray(rng.normal(size=(4, 4)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
        }

        def toy_apply(p, x, t, context=None, **kw):
            h = jnp.tanh(x @ p["w"] * 0.1 + p["b"]) * 0.8
            h = h * jnp.cos(t * 1e-3)[:, None]
            return h + 0.01 * context.sum(axis=-1, keepdims=True)

        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize((toy_apply, params), chain)

        def mk(seed):
            r = np.random.default_rng(seed)
            return (jnp.asarray(r.normal(size=(2, 4)), jnp.float32),
                    jnp.asarray(r.normal(size=(2, 6)), jnp.float32))

        sched.uninstall()
        serial = {
            s: run_sampler(pm, *mk(s), sampler="euler", steps=n)
            for s, n in [(41, 4), (42, 6)]
        }
        sched.install()
        results = {}

        def worker(seed, steps):
            noise, ctx = mk(seed)
            results[seed] = run_sampler(pm, noise, ctx, sampler="euler",
                                        steps=steps)

        threads = [_bg(worker, s, n) for s, n in [(41, 4), (42, 6)]]
        _wait_enqueued(sched, 2)
        sched.drain()
        for t in threads:
            t.join(20)
        [bucket] = sched.buckets.values()
        assert bucket.width == 8  # rounded up to the mesh's data width
        assert sched.total_dispatches() <= 6 + 1
        for s in (41, 42):
            np.testing.assert_allclose(np.asarray(results[s]),
                                       np.asarray(serial[s]), **TOL)


class TestCancelAndPolicy:
    def test_cancel_frees_lane_without_perturbing_neighbors(self, sched):
        """Acceptance: cancelling one lane mid-batch frees its slot; the other
        lane's output is identical to its serial run; the freed slot seats a
        later request."""
        sched.uninstall()
        serial_a = run_sampler(tiny_model, *mk_inputs(51), sampler="euler",
                               steps=8)
        sched.install()
        results, errors = {}, {}

        def worker(seed, steps, evt=None):
            try:
                noise, ctx = mk_inputs(seed)
                if evt is not None:
                    with progress_scope(interrupt_event=evt):
                        results[seed] = run_sampler(
                            tiny_model, noise, ctx, sampler="euler",
                            steps=steps,
                        )
                else:
                    results[seed] = run_sampler(
                        tiny_model, noise, ctx, sampler="euler", steps=steps
                    )
            except BaseException as e:  # noqa: BLE001 — assertion target
                errors[seed] = e

        evt = threading.Event()
        ta = _bg(worker, 51, 8)
        tb = _bg(worker, 52, 8, evt)
        _wait_enqueued(sched, 2)
        for _ in range(3):
            sched.pump()
        evt.set()  # per-lane cancel (the per-prompt scope event)
        sched.pump()
        [bucket] = sched.buckets.values()
        assert len(bucket.active_lanes()) == 1  # B's slot freed at boundary
        tc = _bg(worker, 53, 2)
        _wait_enqueued(sched, 2)  # A still seated + C queued
        sched.drain()
        for t in (ta, tb, tc):
            t.join(20)
        assert isinstance(errors.get(52), Interrupted)
        assert 53 in results  # freed slot was reused
        np.testing.assert_allclose(np.asarray(results[51]),
                                   np.asarray(serial_a), **TOL)

    def test_cancel_by_request_id_while_queued(self, sched):
        done = {}

        def worker():
            noise, ctx = mk_inputs(61)
            try:
                done["out"] = run_sampler(tiny_model, noise, ctx,
                                          sampler="euler", steps=50)
            except BaseException as e:  # noqa: BLE001
                done["err"] = e

        t = _bg(worker)
        _wait_enqueued(sched, 1)
        [bucket] = sched.buckets.values()
        rid = None
        with bucket.queue._lock:
            rid = bucket.queue._heap[0][2].rid
        assert sched.cancel(rid)
        t.join(20)
        assert isinstance(done.get("err"), Interrupted)

    def test_deadline_expired_in_queue(self, sched):
        from comfyui_parallelanything_tpu.serving.scheduler import serving_hints

        done = {}

        def worker():
            noise, ctx = mk_inputs(71)
            try:
                with serving_hints(deadline_s=0.0):
                    done["out"] = run_sampler(tiny_model, noise, ctx,
                                              sampler="euler", steps=5)
            except BaseException as e:  # noqa: BLE001
                done["err"] = e

        t = _bg(worker)
        _wait_enqueued(sched, 1)
        time.sleep(0.01)
        sched.pump()
        t.join(20)
        assert isinstance(done.get("err"), DeadlineExceeded)

    def test_deadline_lapse_racing_admission_rejects_not_seats(self, sched,
                                                               monkeypatch):
        """ISSUE 7 satellite: a deadline that lapses while queued but AFTER
        the periodic expiry sweep ran (the admission race window) must be
        rejected with the deadline error at seat time — never seated for
        step 0, which would spend a dispatch on work whose client already
        gave up."""
        from comfyui_parallelanything_tpu.serving.scheduler import (
            serving_hints,
        )

        done = {}

        def worker():
            noise, ctx = mk_inputs(72)
            try:
                with serving_hints(deadline_s=0.02):
                    done["out"] = run_sampler(tiny_model, noise, ctx,
                                              sampler="euler", steps=5)
            except BaseException as e:  # noqa: BLE001
                done["err"] = e

        t = _bg(worker)
        _wait_enqueued(sched, 1)
        [bucket] = sched.buckets.values()
        # Simulate the race: the expiry sweep misses the lapse (returns
        # nothing), so the request reaches the pop-and-seat path expired.
        monkeypatch.setattr(bucket.queue, "expired", lambda now=None: [])
        time.sleep(0.05)  # the deadline lapses while still queued
        sched.pump()
        t.join(20)
        assert isinstance(done.get("err"), DeadlineExceeded), done
        assert "admission" in str(done["err"])
        assert bucket.dispatch_count == 0  # step 0 never ran for it

    def test_priority_fifo_ordering(self):
        q = AdmissionQueue(max_waiting=8)

        class R:
            def __init__(self, rid, priority):
                self.rid, self.priority = rid, priority

        for rid, pr in [("a", 0), ("b", 5), ("c", 0), ("d", 5)]:
            q.push(R(rid, pr))
        assert [q.pop().rid for _ in range(4)] == ["b", "d", "a", "c"]
        assert q.pop() is None

    def test_bounded_depth_rejects(self):
        q = AdmissionQueue(max_waiting=2)

        class R:
            rid, priority = "x", 0

        q.push(R())
        q.push(R())
        with pytest.raises(ServingRejected):
            q.push(R())

    def test_overflow_falls_back_inline(self):
        """A full admission queue must degrade to inline execution (correct
        result, no batching), never an error — HTTP backpressure is the
        server's job, not the sampler's."""
        s = ContinuousBatchingScheduler(max_width=1, max_waiting=1,
                                        auto=False).install()
        try:
            blocker = _bg(
                lambda: run_sampler(tiny_model, *mk_inputs(81),
                                    sampler="euler", steps=3)
            )
            _wait_enqueued(s, 1)
            # Queue now holds the blocker; this submission overflows and runs
            # inline on the calling thread — no pump needed for it to finish.
            out = run_sampler(tiny_model, *mk_inputs(82), sampler="euler",
                              steps=3)
            assert out.shape == (1, 8, 8, 4)
            assert (registry.get("pa_serving_rejected_total",
                                 {"bucket": list(s.buckets.values())[0].label})
                    or 0) >= 1
            s.drain()
            blocker.join(20)
        finally:
            s.uninstall()
            s.shutdown()


class TestModesAndMetrics:
    def test_streaming_model_runs_width_1(self, sched):
        """A weight-streaming-style model (not single-program traceable) gets
        step-boundary scheduling at width 1 — eager per-step, serial-exact."""

        class StreamingModel:
            is_streaming = True

            def __call__(self, x, t, context=None, **kw):
                return tiny_model(x, t, context)

        model = StreamingModel()
        sched.uninstall()
        serial = run_sampler(model, *mk_inputs(91), sampler="euler", steps=4)
        sched.install()
        results = {}

        def worker():
            noise, ctx = mk_inputs(91)
            results[0] = run_sampler(model, noise, ctx, sampler="euler",
                                     steps=4)

        t = _bg(worker)
        _wait_enqueued(sched, 1)
        sched.drain()
        t.join(20)
        [bucket] = sched.buckets.values()
        assert bucket.width == 1 and bucket.spec is None
        np.testing.assert_allclose(np.asarray(results[0]), np.asarray(serial),
                                   **TOL)

    def test_preview_enabled_work_stays_inline(self, sched):
        """Latent previews only exist on the inline loops (report_progress is
        the sole preview call site) — a preview-scoped prompt must never lose
        its frames to a lane."""
        frames = []
        noise, ctx = mk_inputs(94)
        with progress_scope(preview_hook=frames.append):
            out = run_sampler(tiny_model, noise, ctx, sampler="euler", steps=3)
        assert out.shape == noise.shape
        assert len(frames) == 3  # one per step, emitted inline
        assert not sched.buckets  # nothing was admitted

    def test_callback_and_unbatchable_work_stays_inline(self, sched):
        """Callback runs and samplers without a LaneStepSpec (lms/uni_pc —
        order-4 latent history / predictor-corrector structure) never enter a
        bucket. Stochastic samplers DO batch since round 10 — covered by the
        equivalence matrix below."""
        noise, ctx = mk_inputs(95)
        out = run_sampler(tiny_model, noise, ctx, sampler="lms", steps=2)
        assert out.shape == noise.shape
        out2 = run_sampler(tiny_model, noise, ctx, sampler="euler", steps=2,
                           callback=lambda i, x: None)
        assert out2.shape == noise.shape
        assert not sched.buckets  # nothing was admitted

    def test_uninstalled_scheduler_is_inert(self):
        assert get_scheduler() is None
        noise, ctx = mk_inputs(96)
        out = run_sampler(tiny_model, noise, ctx, sampler="euler", steps=2)
        assert out.shape == noise.shape

    def test_serving_metrics_populate_and_render(self, sched):
        def worker():
            noise, ctx = mk_inputs(97)
            run_sampler(tiny_model, noise, ctx, sampler="euler", steps=3)

        t = _bg(worker)
        _wait_enqueued(sched, 1)
        sched.drain()
        t.join(20)
        [bucket] = sched.buckets.values()
        labels = {"bucket": bucket.label}
        assert registry.get("pa_serving_dispatch_total", labels) >= 3
        assert registry.get("pa_serving_completed_total", labels) >= 1
        assert registry.get("pa_serving_occupancy", labels) == 0  # drained
        wait_sum, wait_count = registry.get("pa_serving_lane_wait_seconds",
                                            labels)
        assert wait_count >= 1 and wait_sum >= 0.0
        step_sum, step_count = registry.get("pa_serving_step_seconds", labels)
        assert step_count >= 3 and step_sum > 0.0
        text = registry.render()
        assert "# TYPE pa_serving_dispatch_total counter" in text
        assert "pa_serving_step_seconds_sum" in text

    def test_streaming_model_runs_stateful_samplers_width_1(self, sched):
        """The width-1 eager mode walks the SAME StepPlans — a streaming-style
        model gets the full sampler family (two-eval + stochastic included)
        through step-boundary scheduling."""

        class StreamingModel:
            is_streaming = True

            def __call__(self, x, t, context=None, **kw):
                return tiny_model(x, t, context)

        model = StreamingModel()
        for sampler, rng in (("dpmpp_2m", None),
                             ("dpmpp_sde", jax.random.key(4))):
            kw = dict(sampler=sampler, steps=4)
            if rng is not None:
                kw["rng"] = rng
            sched.uninstall()
            serial = run_sampler(model, *mk_inputs(92), **kw)
            sched.install()
            results = {}

            def worker(_kw=kw):
                noise, ctx = mk_inputs(92)
                results[0] = run_sampler(model, noise, ctx, **_kw)

            t = _bg(worker)
            _wait_enqueued(sched, 1)
            sched.drain()
            t.join(20)
            np.testing.assert_allclose(np.asarray(results[0]),
                                       np.asarray(serial), **TOL)

    def test_progress_hooks_fire_per_lane(self, sched):
        seen = {1: [], 2: []}

        def worker(seed, steps):
            noise, ctx = mk_inputs(seed + 200)
            with progress_scope(hook=lambda v, m, _s=seed: seen[_s].append((v, m))):
                run_sampler(tiny_model, noise, ctx, sampler="euler",
                            steps=steps)

        t1, t2 = _bg(worker, 1, 3), _bg(worker, 2, 5)
        _wait_enqueued(sched, 2)
        sched.drain()
        t1.join(20)
        t2.join(20)
        assert seen[1] == [(1, 3), (2, 3), (3, 3)]
        assert seen[2] == [(i, 5) for i in range(1, 6)]

    def test_progress_reports_intervals_not_evals(self, sched):
        """A two-eval sampler's hooks fire once per σ-interval (the user-facing
        step unit), not once per model eval."""
        seen = []

        def worker():
            noise, ctx = mk_inputs(210)
            with progress_scope(hook=lambda v, m: seen.append((v, m))):
                run_sampler(tiny_model, noise, ctx, sampler="heun", steps=3)

        t = _bg(worker)
        _wait_enqueued(sched, 1)
        sched.drain()
        t.join(20)
        assert seen == [(1, 3), (2, 3), (3, 3)]
        # ...even though the lane consumed 2·3−1 = 5 model evals.
        assert sched.total_dispatches() == 5


# ---------------------------------------------------------------------------
# Round 10: the stateful-lane sampler family. LANE_MATRIX is the explicit
# lane-vs-solo equivalence matrix — TestRegistryCoverage fails the build if a
# sampler is wired into LANE_SPECS but missing here (wired-but-unverified).
# ---------------------------------------------------------------------------

LANE_MATRIX = (
    "euler", "euler_ancestral", "heun", "dpm_2", "dpm_2_ancestral",
    "dpmpp_2s_ancestral", "dpmpp_sde", "dpmpp_2m", "dpmpp_2m_sde",
    "dpmpp_3m_sde", "lcm", "ddpm",
)
LANE_MATRIX_FLOW = tuple(s for s in LANE_MATRIX if LANE_SPECS[s].flow_ok)


def _solo(kw):
    kw = dict(kw)
    noise, ctx = mk_inputs(kw.pop("seed"))
    return run_sampler(tiny_model, noise, ctx, **kw)


def _serve_plans(sched, plans):
    """Run each plan's run_sampler in a worker thread against the installed
    scheduler with the deterministic manual-pump handshake; returns results
    keyed by plan index."""
    results = {}

    def worker(j, kw):
        noise, ctx = mk_inputs(kw.pop("seed"))
        results[j] = run_sampler(tiny_model, noise, ctx, **kw)

    threads = [_bg(worker, j, dict(p)) for j, p in enumerate(plans)]
    _wait_enqueued(sched, len(plans))
    sched.drain()
    for t in threads:
        t.join(30)
    assert len(results) == len(plans)
    return results


class TestLaneEquivalenceMatrix:
    """Acceptance: every newly-batched sampler's lane output matches its solo
    k_samplers chain within bf16-scale tolerances — co-batched with an
    unrelated ragged partner so the shared-dispatch path actually runs."""

    @pytest.mark.parametrize("sampler", LANE_MATRIX)
    def test_eps_lane_matches_solo(self, sched, sampler):
        kw = dict(sampler=sampler, steps=5,
                  seed=500 + LANE_MATRIX.index(sampler))
        if LANE_SPECS[sampler].needs_rng:
            kw["rng"] = jax.random.key(3)
        sched.uninstall()
        solo = _solo(kw)
        sched.install()
        res = _serve_plans(
            sched, [kw, dict(sampler="euler", steps=7, seed=99)]
        )
        assert len(sched.buckets) == 1  # sampler-free key: ONE shared bucket
        np.testing.assert_allclose(np.asarray(res[0]), np.asarray(solo), **TOL)

    @pytest.mark.parametrize("sampler", LANE_MATRIX_FLOW)
    def test_flow_lane_matches_solo(self, sched, sampler):
        kw = dict(sampler=sampler, steps=4, prediction="flow", shift=1.15,
                  seed=600 + LANE_MATRIX.index(sampler))
        if LANE_SPECS[sampler].needs_rng:
            kw["rng"] = jax.random.key(5)
        sched.uninstall()
        solo = _solo(kw)
        sched.install()
        res = _serve_plans(
            sched,
            [kw, dict(sampler="euler", steps=5, prediction="flow",
                      shift=1.15, seed=98)],
        )
        np.testing.assert_allclose(np.asarray(res[0]), np.asarray(solo), **TOL)


class TestMixedSamplerDispatch:
    def test_mixed_families_complete_in_max_evals(self, sched):
        """Acceptance: K concurrent prompts spanning 4 sampler families with
        ragged schedules complete in a model-eval dispatch count equal to the
        MAX per-lane eval count, not the sum — and all match their solo runs."""
        plans = [
            dict(sampler="euler", steps=4, seed=71),
            dict(sampler="heun", steps=3, seed=72),
            dict(sampler="dpmpp_2m", steps=6, seed=73),
            dict(sampler="euler_ancestral", steps=5, seed=74,
                 rng=jax.random.key(1)),
        ]
        sched.uninstall()
        solos = [_solo(p) for p in plans]
        sched.install()
        res = _serve_plans(sched, plans)
        [bucket] = sched.buckets.values()  # 4 families, ONE bucket
        evals = [
            lane_eval_count(p["sampler"],
                            np.asarray(make_sigmas("karras", p["steps"])))
            for p in plans
        ]
        assert sched.total_dispatches() == max(evals)  # 5 (heun), not 18
        assert sum(evals) > max(evals)
        for j, solo in enumerate(solos):
            np.testing.assert_allclose(np.asarray(res[j]), np.asarray(solo),
                                       **TOL)
        frac = registry.get("pa_serving_batched_fraction")
        assert 0.0 < frac <= 1.0
        assert registry.get("pa_serving_lane_steps_total",
                            {"bucket": bucket.label}) >= sum(evals)

    def test_stochastic_occupancy_deterministic(self, sched):
        """Acceptance: same prompt+seed yields IDENTICAL output alone vs
        co-batched — the fold_in(rng, step) key discipline makes noise a pure
        function of (request, step), independent of occupancy."""
        kw = dict(sampler="dpmpp_sde", steps=4, seed=81,
                  rng=jax.random.key(5))
        alone = _serve_plans(sched, [kw])
        co = _serve_plans(sched, [
            kw,
            dict(sampler="lcm", steps=3, seed=82, rng=jax.random.key(6)),
            dict(sampler="dpmpp_3m_sde", steps=6, seed=83,
                 rng=jax.random.key(7)),
        ])
        np.testing.assert_array_equal(np.asarray(alone[0]), np.asarray(co[0]))


class TestRegistryCoverage:
    def test_every_wired_sampler_is_batchable_and_verified(self):
        """Registry-driven coverage gate: a LaneStepSpec wired into the
        registry but absent from BATCHABLE_SAMPLERS or from the equivalence
        matrix above fails the build."""
        from comfyui_parallelanything_tpu.serving.scheduler import (
            BATCHABLE_SAMPLERS,
        )

        assert frozenset(LANE_SPECS) == BATCHABLE_SAMPLERS
        assert set(LANE_MATRIX) == set(LANE_SPECS), (
            "every registered LaneStepSpec must appear in LANE_MATRIX "
            "(the lane-vs-solo equivalence matrix)"
        )
        assert len(LANE_SPECS) >= 10  # ISSUE 5 target: {euler} → ≥10
        # Every flow-capable spec is flow-verified; ddpm stays eps-only
        # (k_samplers.FLOW_REJECT — no rectified-flow form).
        assert set(LANE_MATRIX_FLOW) == {
            s for s in LANE_SPECS if LANE_SPECS[s].flow_ok
        }
        assert not LANE_SPECS["ddpm"].flow_ok
