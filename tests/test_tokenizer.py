"""CLIP byte-BPE tokenizer: merge order, framing/padding, byte fallback."""

import numpy as np
import pytest

from comfyui_parallelanything_tpu.utils.tokenizer import (
    CLIPBPETokenizer,
    _bytes_to_unicode,
)


def _tiny_tokenizer(**kw):
    """Hand-built vocab: single chars + a few merges, so expected BPE output is
    derivable by hand."""
    alphabet = [
        "a", "b", "c", "d", "e", "h", "l", "o", "r", "w",
        "a</w>", "b</w>", "c</w>", "d</w>", "e</w>", "h</w>", "l</w>", "o</w>",
        "r</w>", "w</w>", "1</w>", "!</w>",
    ]
    merges = [
        ("h", "e"), ("l", "l"), ("he", "ll"), ("hell", "o</w>"),  # hello
        ("w", "o"), ("r", "l"), ("wo", "rl"), ("worl", "d</w>"),  # world
    ]
    vocab = {tok: i for i, tok in enumerate(alphabet)}
    for a, b in merges:
        vocab[a + b] = len(vocab)
    vocab["<|startoftext|>"] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)
    return CLIPBPETokenizer(vocab, merges, max_len=8, **kw)


class TestBPE:
    def test_merges_apply_in_rank_order(self):
        tok = _tiny_tokenizer()
        assert tok.encode("hello") == [tok.vocab["hello</w>"]]
        assert tok.encode("world") == [tok.vocab["world</w>"]]
        # Unmergeable word falls back to char pieces that exist in the vocab.
        assert tok.encode("be") == [tok.vocab["b"], tok.vocab["e</w>"]]

    def test_lowercase_and_whitespace_normalization(self):
        tok = _tiny_tokenizer()
        assert tok.encode("  HeLLo   WORLD ") == tok.encode("hello world")

    def test_framing_padding_mask(self):
        tok = _tiny_tokenizer()
        ids, mask = tok(["hello world"])
        assert ids.shape == (1, 8)
        expect = [
            tok.bos_id, tok.vocab["hello</w>"], tok.vocab["world</w>"], tok.eos_id,
        ]
        assert ids[0, :4].tolist() == expect
        # CLIP-L convention: pad with EOS.
        assert (ids[0, 4:] == tok.eos_id).all()
        assert mask[0].tolist() == [1, 1, 1, 1, 0, 0, 0, 0]

    def test_zero_padding_variant(self):
        # OpenCLIP-G pads with 0 instead of EOS.
        tok = _tiny_tokenizer(pad_id=0)
        ids, _ = tok("hello")
        assert ids[0, 3:].tolist() == [0] * 5

    def test_truncation_keeps_eos(self):
        tok = _tiny_tokenizer()
        ids, mask = tok("hello world hello world hello world hello world")
        assert ids.shape == (1, 8)
        assert ids[0, 0] == tok.bos_id
        assert ids[0, -1] == tok.eos_id
        assert mask[0].sum() == 8

    def test_bytes_to_unicode_reversible(self):
        m = _bytes_to_unicode()
        assert len(m) == 256
        assert len(set(m.values())) == 256


class TestJsonTokenizer:
    def test_loads_hf_tokenizer_json(self, tmp_path):
        tokenizers = pytest.importorskip("tokenizers")
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        vocab = {"[UNK]": 0, "hello": 1, "world": 2}
        t = tokenizers.Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
        t.pre_tokenizer = Whitespace()
        path = tmp_path / "tokenizer.json"
        t.save(str(path))

        from comfyui_parallelanything_tpu.utils.tokenizer import load_tokenizer_json

        tok = load_tokenizer_json(path, max_len=6, eos_id=5)
        ids, mask = tok(["hello world"])
        assert ids[0].tolist() == [1, 2, 5, 0, 0, 0]  # T5-style appended EOS
        assert mask[0].tolist() == [1, 1, 1, 0, 0, 0]
