"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

This is the multi-device-without-hardware story the reference lacks entirely
(SURVEY §4): `--xla_force_host_platform_device_count=8` gives every test a real 8-way
mesh on any machine, so the sharding path is exercised exactly as it would be on a
v5e-8, minus the ICI.
"""

import os

# Force the CPU platform: the profile exports JAX_PLATFORMS=axon (the tunneled TPU),
# but the test suite is defined over the virtual 8-device CPU mesh. Dropping the axon
# pool var also keeps the sitecustomize TPU-tunnel registration out of test runs (a
# wedged tunnel otherwise blocks jax import even for CPU work).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Hermetic chunked-attention defaults: once the watchdog's chunk sweep banks a
# measured ops/attn_chunk.json, default-env processes serve it — but the test
# suite asserts against the built-in defaults. Point the tuning path at a
# nonexistent file (tests that exercise the table monkeypatch the module's
# _CHUNK_TUNING_PATH directly).
os.environ["PA_ATTN_CHUNK_TUNING"] = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "nonexistent-attn-chunk.json"
)
os.environ.pop("PA_ATTN_CHUNK_ELEMS", None)
os.environ.pop("PA_ATTN_BF16_SOFTMAX", None)
# Telemetry cost analysis re-lowers each instrumented program once at its
# first compile — valuable accounting on real runs, pure wall-clock overhead
# across a suite that compiles hundreds of tiny programs. Off by default
# here; the telemetry tests that assert FLOPs turn it back on per-test.
os.environ.setdefault("PA_TELEMETRY_COST", "0")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# PA_LOCKCHECK=1 (round 16): install the lock-acquisition-order tracker
# BEFORE jax/the package import so every module-level threading.Lock() in
# the package is born tracked. Path-loaded (utils/lockcheck.py is
# standalone by contract) precisely because importing the package here
# would create its locks un-tracked.
_lockcheck = None
if os.environ.get("PA_LOCKCHECK") == "1":
    import importlib.util as _ilu

    _lc_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "comfyui_parallelanything_tpu", "utils", "lockcheck.py",
    )
    _spec = _ilu.spec_from_file_location("pa_lockcheck_boot", _lc_path)
    _lockcheck = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_lockcheck)
    _lockcheck.install()
    # ONE graph per process: later package imports of utils.lockcheck must
    # resolve to THIS instance (the installed factories close over its
    # edge dict), not a second execution of the file.
    import sys as _sys

    _sys.modules["comfyui_parallelanything_tpu.utils.lockcheck"] = _lockcheck

import jax  # noqa: E402

# This XLA CPU backend executes `default`-precision f32 matmuls at bf16 (matching TPU
# MXU behavior), but partitioned dots lower at full f32 — pin highest precision so
# sharded-vs-single equivalence tests compare at f32 tolerances.
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _no_lock_order_cycles():
    """Under PA_LOCKCHECK=1 every test ends with the lock-order graph
    acyclic — the interleaving-independent deadlock gate (a cycle is an
    ORDER fact: it fails here even when CI never schedules the deadlock).
    Attribution is per-test: the graph is cumulative (an edge from test A
    plus the reverse edge from test B is a real cross-path cycle), so the
    fixture snapshots the cycles already reported and fails only the test
    that closed a NEW one — the first offender goes red, not every test
    after it."""
    if _lockcheck is None:
        yield
        return
    before = {tuple(c) for c in _lockcheck.cycles()}
    yield
    new = [c for c in _lockcheck.cycles() if tuple(c) not in before]
    assert not new, (
        "lock-order cycle(s) recorded (potential deadlock): "
        + "; ".join(" -> ".join(c) for c in new)
    )


@pytest.fixture(autouse=True)
def _no_stale_interrupt():
    """The cooperative sampler interrupt (utils/progress.py) is process-wide
    state: a Cancel that races past its prompt's last checkpoint would poison
    whichever test runs the next workflow (observed as order-dependent
    Interrupted failures in the full suite). Every test ends flag-clean."""
    yield
    from comfyui_parallelanything_tpu.utils.progress import clear_interrupt

    clear_interrupt()
