"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

This is the multi-device-without-hardware story the reference lacks entirely
(SURVEY §4): `--xla_force_host_platform_device_count=8` gives every test a real 8-way
mesh on any machine, so the sharding path is exercised exactly as it would be on a
v5e-8, minus the ICI.
"""

import os

# Force the CPU platform: the profile exports JAX_PLATFORMS=axon (the tunneled TPU),
# but the test suite is defined over the virtual 8-device CPU mesh. Dropping the axon
# pool var also keeps the sitecustomize TPU-tunnel registration out of test runs (a
# wedged tunnel otherwise blocks jax import even for CPU work).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Hermetic chunked-attention defaults: once the watchdog's chunk sweep banks a
# measured ops/attn_chunk.json, default-env processes serve it — but the test
# suite asserts against the built-in defaults. Point the tuning path at a
# nonexistent file (tests that exercise the table monkeypatch the module's
# _CHUNK_TUNING_PATH directly).
os.environ["PA_ATTN_CHUNK_TUNING"] = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "nonexistent-attn-chunk.json"
)
os.environ.pop("PA_ATTN_CHUNK_ELEMS", None)
os.environ.pop("PA_ATTN_BF16_SOFTMAX", None)
# Telemetry cost analysis re-lowers each instrumented program once at its
# first compile — valuable accounting on real runs, pure wall-clock overhead
# across a suite that compiles hundreds of tiny programs. Off by default
# here; the telemetry tests that assert FLOPs turn it back on per-test.
os.environ.setdefault("PA_TELEMETRY_COST", "0")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# This XLA CPU backend executes `default`-precision f32 matmuls at bf16 (matching TPU
# MXU behavior), but partitioned dots lower at full f32 — pin highest precision so
# sharded-vs-single equivalence tests compare at f32 tolerances.
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _no_stale_interrupt():
    """The cooperative sampler interrupt (utils/progress.py) is process-wide
    state: a Cancel that races past its prompt's last checkpoint would poison
    whichever test runs the next workflow (observed as order-dependent
    Interrupted failures in the full suite). Every test ends flag-clean."""
    yield
    from comfyui_parallelanything_tpu.utils.progress import clear_interrupt

    clear_interrupt()
