"""GPipe-style microbatched pipeline throughput (beyond the reference, whose
pipeline mode is batch==1 layer placement only — SURVEY §2e): batch>1 streams
through the stage chain as microbatches, overlapped by XLA's async per-device
queues; outputs must equal the single-device forward exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, ParallelConfig, parallelize
from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux

TINY = FluxConfig(
    in_channels=16,  # patchified dim: p^2 * C for 4-channel latents, patch 2
    hidden_size=32,
    num_heads=2,
    depth=2,
    depth_single_blocks=4,
    context_in_dim=16,
    vec_in_dim=8,
    axes_dim=(4, 6, 6),
    guidance_embed=False,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def model():
    return build_flux(TINY, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=8)


def _inputs(batch, seed=1):
    r = np.random.default_rng(seed)
    x = jnp.asarray(r.normal(size=(batch, 8, 8, 4)), jnp.float32)
    t = jnp.asarray(r.uniform(0.1, 1.0, size=(batch,)), jnp.float32)
    ctx = jnp.asarray(r.normal(size=(batch, 8, TINY.context_in_dim)), jnp.float32)
    y = jnp.asarray(r.normal(size=(batch, TINY.vec_in_dim)), jnp.float32)
    return x, t, ctx, y


class TestMicrobatchedPipeline:
    def test_matches_single_device(self, model, cpu_devices):
        pm = parallelize(
            model,
            DeviceChain.even([f"cpu:{i}" for i in range(4)]),
            ParallelConfig(pipeline_microbatches=4),
        )
        x, t, ctx, y = _inputs(8)
        got = pm(x, t, ctx, y=y)
        assert pm._pipeline_runner is not None
        assert pm._pipeline_runner.n_stages > 1  # stages actually placed
        want = model.apply(model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_uneven_microbatches(self, model, cpu_devices):
        # batch 7 over 3 microbatches: largest-remainder sizes, exact concat.
        pm = parallelize(
            model,
            DeviceChain.even([f"cpu:{i}" for i in range(4)]),
            ParallelConfig(pipeline_microbatches=3),
        )
        x, t, ctx, y = _inputs(7, seed=2)
        got = pm(x, t, ctx, y=y)
        want = model.apply(model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_uneven_batch_pads_to_uniform_chunks(self, model, cpu_devices):
        # Uneven largest-remainder sizes would compile every stage program
        # twice; the router pads to mb * ceil(batch/mb) so all chunks share
        # ONE shape, then slices the concat back.
        pm = parallelize(
            model,
            DeviceChain.even([f"cpu:{i}" for i in range(4)]),
            ParallelConfig(pipeline_microbatches=3),
        )
        x, t, ctx, y = _inputs(7, seed=5)
        pm(x, t, ctx, y=y)  # build the runner
        orig = pm._pipeline_runner
        seen = []

        class Spy:
            n_stages = orig.n_stages

            def __call__(self, xi, ti, ci=None, **kw):
                seen.append(xi.shape[0])
                return orig(xi, ti, ci, **kw)

        pm._pipeline_runner = Spy()
        got = pm(x, t, ctx, y=y)
        assert seen == [3, 3, 3]  # uniform chunk shapes (7 -> 9 padded)
        want = model.apply(model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_no_spec_falls_through_to_dp(self, cpu_devices):
        def f(p, x, t, context=None, **kw):
            return x * p["a"]

        pm = parallelize(
            (f, {"a": jnp.float32(2.0)}),
            DeviceChain.even([f"cpu:{i}" for i in range(4)]),
            ParallelConfig(pipeline_microbatches=4),
        )
        x = jnp.ones((8, 4))
        out = pm(x, jnp.ones((8,)))
        assert pm._pipeline_runner is None  # no spec -> DP handled it
        np.testing.assert_allclose(np.asarray(out), 2.0 * np.asarray(x))

    def test_batch_below_microbatch_count_routes_normally(self, model, cpu_devices):
        pm = parallelize(
            model,
            DeviceChain.even([f"cpu:{i}" for i in range(4)]),
            ParallelConfig(pipeline_microbatches=8),
        )
        x, t, ctx, y = _inputs(4, seed=3)  # batch 4 < mb 8 -> DP path
        got = pm(x, t, ctx, y=y)
        want = model.apply(model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_default_config_unchanged_routing(self, model, cpu_devices):
        pm = parallelize(model, DeviceChain.even([f"cpu:{i}" for i in range(4)]))
        x, t, ctx, y = _inputs(8, seed=4)
        got = pm(x, t, ctx, y=y)
        assert pm._pipeline_runner is None  # DP, not pipeline
        want = model.apply(model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )
