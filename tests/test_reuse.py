"""Cross-request compute reuse (round 17): the content-addressed embed
cache, sibling-seed shared-cond lanes, and the batched decode tail —
correctness (bitwise / bf16-tolerance equivalence), the LRU byte bound, and
the zipf/fanout CI smoke whose gates ride the scraped reuse counters
(``scripts/ci_tier1.sh`` reruns ``ReuseSmoke or SiblingSeed or EmbedCache
or BatchedDecode`` as the explicit contract)."""

import json
import math
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

# bf16-scale tolerances (CLAUDE.md: this XLA CPU runs f32 matmuls at bf16).
TOL = dict(rtol=2e-3, atol=1e-4)


@pytest.fixture(autouse=True)
def _fresh_embed_cache():
    """Deterministic hit/miss/byte accounting per test."""
    from comfyui_parallelanything_tpu.models.embed_cache import cache

    cache.clear()
    yield
    cache.clear()


# ---------------------------------------------------------------------------
# embed cache unit behavior (no encoders needed)
# ---------------------------------------------------------------------------


class TestEmbedCache:
    def _mk(self, max_bytes):
        from comfyui_parallelanything_tpu.models.embed_cache import EmbedCache

        return EmbedCache(max_bytes=max_bytes)

    def test_byte_bound_holds_under_churn_with_eviction_counts(self):
        c = self._mk(10 * 1024)  # ten 1 KiB values fit, forty don't
        val = lambda i: np.full((256,), i, np.float32)  # noqa: E731 — 1 KiB
        for i in range(40):
            c.put(f"k{i}", val(i))
            assert c.stats()["bytes"] <= 10 * 1024  # the bound HOLDS, always
        st = c.stats()
        assert st["entries"] == 10
        assert st["evictions"] == 30
        # LRU order: the oldest 30 are gone, the newest 10 remain.
        assert c.get("k0") is None
        assert c.get("k39") is not None
        assert c.stats()["hits"] == 1 and c.stats()["misses"] == 1

    def test_lru_recency_protects_hot_entries(self):
        c = self._mk(3 * 1024)
        for i in range(3):
            c.put(f"k{i}", np.zeros((256,), np.float32))
        assert c.get("k0") is not None   # k0 is now MRU
        c.put("k3", np.zeros((256,), np.float32))  # evicts k1, not k0
        assert c.get("k0") is not None
        assert c.get("k1") is None

    def test_merge_discipline_incumbent_wins(self):
        # The WorkflowCache.merge rule: a racing double-encode's loser gets
        # the incumbent back; its duplicate stays caller-owned, un-cached.
        c = self._mk(1 << 20)
        first = np.ones((8,), np.float32)
        second = np.ones((8,), np.float32) * 2
        assert c.put("k", first) is first
        assert c.put("k", second) is first
        assert c.get("k") is first

    def test_release_owner_frees_bytes(self):
        c = self._mk(1 << 20)
        c.put("a", np.zeros((256,), np.float32), owner="enc1")
        c.put("b", np.zeros((256,), np.float32), owner="enc1")
        c.put("c", np.zeros((256,), np.float32), owner="enc2")
        assert c.release_owner("enc1") == 2
        st = c.stats()
        assert st["entries"] == 1 and st["bytes"] == 1024
        assert c.get("a") is None and c.get("c") is not None

    def test_disabled_cache_never_stores(self):
        c = self._mk(0)
        v = np.zeros((8,), np.float32)
        assert c.put("k", v) is v
        assert c.get("k") is None
        assert c.stats()["enabled"] is False

    def test_oversized_value_returned_uncached(self):
        c = self._mk(100)
        v = np.zeros((256,), np.float32)
        assert c.put("k", v) is v
        assert c.stats()["entries"] == 0

    def test_stable_key_contract(self):
        from comfyui_parallelanything_tpu.models.embed_cache import stable_key

        ids = np.array([[1, 2, 3]], np.int32)
        assert stable_key("m", "clip", ids) == stable_key("m", "clip", ids)
        assert stable_key("m", "clip", ids) != \
            stable_key("m2", "clip", ids)
        assert stable_key("m", "clip", ids) != stable_key("m", "t5", ids)
        assert stable_key("m", "clip", ids) != \
            stable_key("m", "clip", np.array([[1, 2, 4]], np.int32))
        # Mask participates (t5's attention mask changes the output).
        assert stable_key("m", "t5", ids, np.array([[1, 1, 0]])) != \
            stable_key("m", "t5", ids, np.array([[1, 1, 1]]))


class TestCachedEncode:
    def _tiny_encoder(self):
        import jax

        from comfyui_parallelanything_tpu.models.text_encoders import (
            build_clip_text,
        )
        from tests.test_text_encoders import TINY_CLIP

        return build_clip_text(TINY_CLIP, jax.random.key(0))

    def test_cached_vs_fresh_bitwise_equal_and_one_invocation(self):
        from comfyui_parallelanything_tpu.models import embed_cache
        from comfyui_parallelanything_tpu.utils.metrics import registry

        enc = self._tiny_encoder()
        ids = np.array([[5, 6, 7, 99] + [0] * 12], np.int32)
        calls = [0]

        def compute():
            import jax.numpy as jnp

            calls[0] += 1
            return enc(jnp.asarray(ids, jnp.int32))

        inv0 = registry.get("pa_encoder_invocations_total") or 0.0
        fresh = embed_cache.cached_encode(enc, "mk", "clip", ids, None, compute)
        cached = embed_cache.cached_encode(enc, "mk", "clip", ids, None, compute)
        assert calls[0] == 1  # the hit skipped the encoder program entirely
        assert (registry.get("pa_encoder_invocations_total") or 0.0) - inv0 == 1
        # Hits return the SAME arrays: cached-vs-fresh is bitwise-equal by
        # construction (and the shared object is the sibling-seed seam).
        assert cached[0] is fresh[0]
        # A recompute after a clear reruns the SAME jitted program on the
        # same inputs — bitwise-equal output, no recompile of the encoder.
        embed_cache.cache.clear()
        fresh2 = embed_cache.cached_encode(enc, "mk", "clip", ids, None, compute)
        assert calls[0] == 2
        for a, b in zip(fresh, fresh2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_workflow_cache_eviction_releases_embeds(self):
        # host.WorkflowCache teardown hook: evicting a CLIP wire releases
        # its cached embeds eagerly (owner-token release).
        from comfyui_parallelanything_tpu.host import WorkflowCache
        from comfyui_parallelanything_tpu.models import embed_cache

        enc = self._tiny_encoder()
        ids = np.array([[5, 6, 99] + [0] * 13], np.int32)
        embed_cache.cached_encode(
            enc, None, "clip", ids, None,
            lambda: (np.zeros((4,), np.float32),),
        )
        assert embed_cache.cache.stats()["entries"] == 1
        wc = WorkflowCache()
        wire = {"encoder": enc, "tokenizer": object(), "type": "clip"}
        wc.results["n1"] = (wire,)
        wc.signatures["n1"] = "sig"
        wc.evict("n1")
        assert embed_cache.cache.stats()["entries"] == 0


# ---------------------------------------------------------------------------
# sibling-seed shared-cond lanes (scheduler harness, manual pump)
# ---------------------------------------------------------------------------


def tiny_model(x, t, context=None, **kw):
    """Per-sample-independent stand-in denoiser (tests/test_serving.py)."""
    import jax.numpy as jnp

    c = jnp.mean(context, axis=tuple(range(1, context.ndim)))
    c = c.reshape((-1,) + (1,) * (x.ndim - 1))
    tt = t.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.tanh(x * 0.9 + c * 0.1) * (0.5 + 0.1 * tt / 1000.0)


def _noise(seed, batch=1):
    import jax.numpy as jnp

    r = np.random.default_rng(seed)
    return jnp.asarray(r.normal(size=(batch, 8, 8, 4)).astype(np.float32))


def _ctx(seed=1000, batch=1):
    import jax.numpy as jnp

    r = np.random.default_rng(seed)
    return jnp.asarray(r.normal(size=(batch, 6, 16)).astype(np.float32))


@pytest.fixture
def sched():
    from comfyui_parallelanything_tpu.serving import (
        ContinuousBatchingScheduler,
    )

    s = ContinuousBatchingScheduler(max_width=4, auto=False).install()
    try:
        yield s
    finally:
        s.uninstall()
        s.shutdown()


def _serve_fanout(sched, ctx, seeds, steps=1, timeout=30):
    """Submit one run_sampler per seed — all referencing the SAME ctx object
    (the embed cache's aliasing) — and drain; returns results by seed."""
    from comfyui_parallelanything_tpu.sampling.runner import run_sampler

    results = {}

    def worker(seed):
        results[seed] = run_sampler(
            tiny_model, _noise(seed), ctx, sampler="euler", steps=steps
        )

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in seeds]
    for t in threads:
        t.start()
    t0 = time.time()
    while time.time() - t0 < timeout:
        with sched._lock:
            tot = sum(len(b.queue) + len(b.active_lanes())
                      for b in sched.buckets.values())
        if tot >= len(seeds):
            break
        time.sleep(0.005)
    sched.drain()
    for t in threads:
        t.join(timeout)
    assert len(results) == len(seeds)
    return results


class TestSiblingSeedFanout:
    def test_fanout_costs_ceil_n_over_width_dispatches_bitwise(self, sched):
        """Acceptance: an 8-seed fanout of ONE prompt (one shared cond
        object) completes in ceil(8/width) shared dispatches per eval, with
        every latent bitwise-equal to its solo run — the broadcast-cond
        program at any occupancy is the same program, so the PR 5
        select-mask contract carries the equality."""
        from comfyui_parallelanything_tpu.utils.metrics import registry

        ctx = _ctx()
        seeds = list(range(20, 28))
        solo = {}
        for s in seeds:  # solo legs: one at a time through the scheduler
            solo.update(_serve_fanout(sched, ctx, [s], steps=1))
        start = sched.total_dispatches()
        res = _serve_fanout(sched, ctx, seeds, steps=1)
        n, width = len(seeds), 4
        assert sched.total_dispatches() - start == math.ceil(n / width)
        for s in seeds:
            np.testing.assert_array_equal(
                np.asarray(res[s]), np.asarray(solo[s])
            )
        [bucket] = sched.buckets.values()
        labels = {"bucket": bucket.label}
        # The dispatches really rode the broadcast program, and sibling
        # seats really shared the cond tensor.
        assert registry.get("pa_serving_cond_broadcast_total", labels) >= 2
        assert registry.get("pa_serving_shared_cond_seats_total", labels) >= 6

    def test_multi_step_fanout_matches_solo_bitwise(self, sched):
        ctx = _ctx(7)
        seeds = [31, 32, 33, 34, 35]
        solo = {}
        for s in seeds:
            solo.update(_serve_fanout(sched, ctx, [s], steps=4))
        res = _serve_fanout(sched, ctx, seeds, steps=4)
        for s in seeds:
            np.testing.assert_array_equal(
                np.asarray(res[s]), np.asarray(solo[s])
            )

    def test_foreign_cond_demotes_to_stacked_and_stays_correct(self, sched):
        """A mid-flight join with a DIFFERENT cond demotes the bucket from
        shared to stacked; the incumbent's trajectory is unperturbed (its
        values are re-filled from the shared ref, so demotion is a mode
        change, never a value change)."""
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        ctx_a, ctx_b = _ctx(1), _ctx(2)
        solo_a = _serve_fanout(sched, ctx_a, [41], steps=8)[41]
        solo_b = _serve_fanout(sched, ctx_b, [42], steps=4)[42]
        results = {}

        def worker(seed, ctx, steps):
            results[seed] = run_sampler(
                tiny_model, _noise(seed), ctx, sampler="euler", steps=steps
            )

        ta = threading.Thread(target=worker, args=(41, ctx_a, 8), daemon=True)
        ta.start()
        t0 = time.time()
        while time.time() - t0 < 30 and not any(
            b.active_lanes() or len(b.queue)
            for b in sched.buckets.values()
        ):
            time.sleep(0.005)
        for _ in range(3):
            sched.pump()  # A is 3 steps in, shared-mode...
        tb = threading.Thread(target=worker, args=(42, ctx_b, 4), daemon=True)
        tb.start()
        t0 = time.time()
        while time.time() - t0 < 30:
            with sched._lock:
                tot = sum(len(b.queue) + len(b.active_lanes())
                          for b in sched.buckets.values())
            if tot >= 2:
                break
            time.sleep(0.005)
        sched.drain()  # ...when B's foreign cond joins and demotes
        ta.join(30)
        tb.join(30)
        np.testing.assert_array_equal(np.asarray(results[41]),
                                      np.asarray(solo_a))
        np.testing.assert_array_equal(np.asarray(results[42]),
                                      np.asarray(solo_b))

    def test_shared_mode_reenters_after_bucket_drains(self, sched):
        # Burst 1 demotes (two conds); burst 2 (single cond) must re-enter
        # shared mode — release/idle resets the cond epoch.
        ctx_a, ctx_b = _ctx(3), _ctx(4)
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        results = {}

        def worker(seed, ctx):
            results[seed] = run_sampler(
                tiny_model, _noise(seed), ctx, sampler="euler", steps=2
            )

        ts = [threading.Thread(target=worker, args=(s, c), daemon=True)
              for s, c in ((51, ctx_a), (52, ctx_b))]
        for t in ts:
            t.start()
        t0 = time.time()
        while time.time() - t0 < 30:
            with sched._lock:
                tot = sum(len(b.queue) + len(b.active_lanes())
                          for b in sched.buckets.values())
            if tot >= 2:
                break
            time.sleep(0.005)
        sched.drain()
        for t in ts:
            t.join(30)
        from comfyui_parallelanything_tpu.utils.metrics import registry

        [bucket] = sched.buckets.values()
        labels = {"bucket": bucket.label}
        # Burst 1 demoted; idle release resets the epoch (mode None).
        assert bucket._cond_mode in (None, "stacked")
        before = registry.get("pa_serving_cond_broadcast_total", labels) or 0
        res = _serve_fanout(sched, ctx_a, [53, 54], steps=1)
        after = registry.get("pa_serving_cond_broadcast_total", labels) or 0
        assert after > before  # burst 2 re-entered shared-cond broadcast
        assert len(res) == 2


# ---------------------------------------------------------------------------
# shared traced kwargs (PR 12 remainder): the negative-prompt/uncond traced
# kwargs ride the broadcast lane path too — a sibling-seed fanout stops
# stacking identical y/guidance/uncond rows.
# ---------------------------------------------------------------------------


def tiny_model_kw(x, t, context=None, y=None):
    """tiny_model plus a per-sample traced-kwarg contribution, so a wrong
    y row (or a dropped uncond kwarg) changes the latent."""
    import jax.numpy as jnp

    out = tiny_model(x, t, context)
    yy = jnp.mean(y, axis=-1).reshape((-1,) + (1,) * (x.ndim - 1))
    return out + 0.05 * yy


def _kw_inputs(seed=2000, batch=1):
    import jax.numpy as jnp

    r = np.random.default_rng(seed)
    return (
        jnp.asarray(r.normal(size=(batch, 4)).astype(np.float32)),
        jnp.asarray(r.normal(size=(batch, 4)).astype(np.float32)),
    )


def _serve_kw_fanout(sched, ctx, uctx, y, uy, seeds, steps=1, timeout=30):
    """One CFG run_sampler per seed, every request referencing the SAME
    ctx/uctx/y/uncond-y objects (the embed-cache / node-layer aliasing)."""
    from comfyui_parallelanything_tpu.sampling.runner import run_sampler

    results = {}

    def worker(seed):
        results[seed] = run_sampler(
            tiny_model_kw, _noise(seed), ctx, sampler="euler", steps=steps,
            cfg_scale=2.0, uncond_context=uctx, uncond_kwargs={"y": uy},
            y=y,
        )

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in seeds]
    for t in threads:
        t.start()
    t0 = time.time()
    while time.time() - t0 < timeout:
        with sched._lock:
            tot = sum(len(b.queue) + len(b.active_lanes())
                      for b in sched.buckets.values())
        if tot >= len(seeds):
            break
        time.sleep(0.005)
    sched.drain()
    for t in threads:
        t.join(timeout)
    assert len(results) == len(seeds)
    return results


class TestSharedKwargsFanout:
    def test_uncond_kwargs_ride_the_broadcast_path_bitwise(self, sched):
        """Acceptance (PR 12 remainder): a sibling-seed fanout whose traced
        kwargs — the pooled y AND the uncond y — alias by object identity
        rides the broadcast_kwargs program variant (one [b, ...] tree in
        HBM, not W stacked rows), with every latent bitwise-equal to its
        solo run."""
        from comfyui_parallelanything_tpu.utils.metrics import registry

        ctx, uctx = _ctx(100), _ctx(101)
        y, uy = _kw_inputs(102)
        seeds = list(range(70, 74))
        solo = {}
        for s in seeds:
            solo.update(_serve_kw_fanout(sched, ctx, uctx, y, uy, [s]))
        res = _serve_kw_fanout(sched, ctx, uctx, y, uy, seeds)
        for s in seeds:
            np.testing.assert_array_equal(
                np.asarray(res[s]), np.asarray(solo[s]),
            )
        [bucket] = sched.buckets.values()
        labels = {"bucket": bucket.label}
        assert (registry.get("pa_serving_kwargs_broadcast_total",
                             labels) or 0) >= 1
        assert (registry.get("pa_serving_shared_kwargs_seats_total",
                             labels) or 0) >= 1

    def test_foreign_kwargs_demote_to_stacked_and_stay_correct(self, sched):
        """A mid-flight join sharing the cond but carrying DIFFERENT traced
        kwargs demotes only the kwargs axis to stacked rows; both lanes'
        trajectories stay bitwise-equal to solo (demotion refills rows from
        the seated requests — a mode change, never a value change)."""
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        ctx, uctx = _ctx(110), _ctx(111)
        y_a, uy = _kw_inputs(112)
        y_b, _ = _kw_inputs(113)
        solo_a = _serve_kw_fanout(sched, ctx, uctx, y_a, uy, [81],
                                  steps=8)[81]
        solo_b = _serve_kw_fanout(sched, ctx, uctx, y_b, uy, [82],
                                  steps=4)[82]
        results = {}

        def worker(seed, y, steps):
            results[seed] = run_sampler(
                tiny_model_kw, _noise(seed), ctx, sampler="euler",
                steps=steps, cfg_scale=2.0, uncond_context=uctx,
                uncond_kwargs={"y": uy}, y=y,
            )

        ta = threading.Thread(target=worker, args=(81, y_a, 8), daemon=True)
        ta.start()
        t0 = time.time()
        while time.time() - t0 < 30 and not any(
            b.active_lanes() or len(b.queue)
            for b in sched.buckets.values()
        ):
            time.sleep(0.005)
        for _ in range(3):
            sched.pump()  # A is steps in, kwargs-shared...
        tb = threading.Thread(target=worker, args=(82, y_b, 4), daemon=True)
        tb.start()
        t0 = time.time()
        while time.time() - t0 < 30:
            with sched._lock:
                tot = sum(len(b.queue) + len(b.active_lanes())
                          for b in sched.buckets.values())
            if tot >= 2:
                break
            time.sleep(0.005)
        sched.drain()  # ...when B's foreign y joins and demotes the kwargs
        ta.join(30)
        tb.join(30)
        [bucket] = sched.buckets.values()
        assert bucket._kw_mode in (None, "stacked")
        np.testing.assert_array_equal(np.asarray(results[81]),
                                      np.asarray(solo_a))
        np.testing.assert_array_equal(np.asarray(results[82]),
                                      np.asarray(solo_b))


# ---------------------------------------------------------------------------
# batched tail decode
# ---------------------------------------------------------------------------


class TestBatchedDecode:
    def _vae(self):
        import jax

        from comfyui_parallelanything_tpu.models import build_vae
        from tests.test_vae import TINY

        return build_vae(TINY, jax.random.key(1), sample_hw=16)

    def _z(self, seed):
        import jax.numpy as jnp

        r = np.random.default_rng(seed)
        return jnp.asarray(r.normal(size=(1, 8, 8, 4)).astype(np.float32))

    def test_batched_decode_allclose_to_solo(self):
        from comfyui_parallelanything_tpu.serving.decode import DecodeQueue
        from comfyui_parallelanything_tpu.utils.metrics import registry

        vae = self._vae()
        q = DecodeQueue(width=4, linger_s=100.0, auto=False)
        try:
            zs = [self._z(i) for i in range(4)]
            solo = [np.asarray(vae.decode(z)) for z in zs]
            tickets = [q.submit(vae, z) for z in zs[:3]]
            assert all(t is not None for t in tickets)
            assert q.pump() is False  # 3 < width, linger far away: not ripe
            tickets.append(q.submit(vae, zs[3]))
            d0 = registry.get("pa_decode_dispatch_total") or 0.0
            assert q.pump() is True   # width reached → ONE shared dispatch
            assert (registry.get("pa_decode_dispatch_total") or 0.0) - d0 == 1
            for t, s in zip(tickets, solo):
                # bf16-scale tolerance: the batch dim changes the XLA
                # program exactly like any width change (CLAUDE.md).
                np.testing.assert_allclose(
                    np.asarray(t.result(timeout=10)), s, **TOL
                )
            from comfyui_parallelanything_tpu.serving.decode import (
                batched_fraction,
            )

            assert batched_fraction() > 0.0
        finally:
            q.shutdown()

    def test_padded_partial_batch_allclose(self):
        from comfyui_parallelanything_tpu.serving.decode import DecodeQueue

        vae = self._vae()
        q = DecodeQueue(width=4, linger_s=100.0, auto=False)
        try:
            z = self._z(9)
            solo = np.asarray(vae.decode(z))
            t = q.submit(vae, z)
            q.pump(force=True)  # occupancy 1 of width 4: padded rows inert
            np.testing.assert_allclose(
                np.asarray(t.result(timeout=10)), solo, **TOL
            )
        finally:
            q.shutdown()

    def test_linger_dispatches_without_full_width(self):
        from comfyui_parallelanything_tpu.serving.decode import DecodeQueue

        vae = self._vae()
        q = DecodeQueue(width=4, linger_s=0.0, auto=False)
        try:
            t = q.submit(vae, self._z(10))
            assert q.pump() is True  # linger lapsed → ripe at occupancy 1
            assert t.result(timeout=10) is not None
        finally:
            q.shutdown()

    def test_ineligible_work_returns_none(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.serving.decode import DecodeQueue

        vae = self._vae()
        q = DecodeQueue(width=4, auto=False)
        try:
            assert q.submit(vae, self._z(0), tile=16) is None  # tiled: inline
            assert q.submit(vae, jnp.zeros((1, 2, 8, 8, 4))) is None  # video
            assert q.submit(object(), self._z(0)) is None  # no decode/params
        finally:
            q.shutdown()

    def test_shutdown_resolves_waiters_with_error(self):
        from comfyui_parallelanything_tpu.serving.decode import DecodeQueue

        vae = self._vae()
        q = DecodeQueue(width=4, linger_s=100.0, auto=False)
        t = q.submit(vae, self._z(11))
        q.shutdown()
        with pytest.raises(RuntimeError):
            t.result(timeout=5)

    def test_mixed_shapes_bucket_separately(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.serving.decode import DecodeQueue

        vae = self._vae()
        q = DecodeQueue(width=2, linger_s=100.0, auto=False)
        try:
            a = q.submit(vae, self._z(12))
            b = q.submit(vae, jnp.asarray(
                np.random.default_rng(13).normal(
                    size=(1, 4, 4, 4)
                ).astype(np.float32)
            ))
            q.pump(force=True)
            assert a.result(timeout=10).shape != b.result(timeout=10).shape
        finally:
            q.shutdown()


# ---------------------------------------------------------------------------
# the CI smoke: zipf loadgen rung + fanout acceptance + kind="reuse" record
# ---------------------------------------------------------------------------


class TestReuseSmoke:
    def test_zipf_fanout_reuse_smoke(self, tmp_path, monkeypatch):
        """The ci_tier1 reuse gate: a zipf(s=1.1) prompt mix over a live
        multi-worker server shows the encode stage collapsing
        (``embed_cache_hit_rate > 0``, ``encoder_invocations <= 0.5x``
        prompts, ``prompts_lost == 0``); an 8-seed fanout costs ~1 encode
        and exactly ceil(8/width) shared dispatches with bitwise-equal
        latents; the evidence lands as ONE kind="reuse" ledger record."""
        from loadgen import run_load

        from comfyui_parallelanything_tpu.server import make_server
        from comfyui_parallelanything_tpu.utils.metrics import registry
        from tests.test_server import _stock_graph
        from tests.test_stock_nodes import _synthetic_stock_env

        out_dir = tmp_path / "out"
        srv, q = make_server(port=0, output_dir=str(out_dir), workers=4)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        # Distinct-token prompt vocabulary (the synthetic word-level
        # tokenizer's real words — synthetic 'prompt k' strings would all
        # tokenize to [UNK] and alias in the cache).
        vocab = [
            "a watercolor lighthouse",
            "a blurry lighthouse",
            "low quality dawn",
            "a lighthouse at dawn",
        ]
        try:
            paths = _synthetic_stock_env(tmp_path, monkeypatch)
            graph = _stock_graph(paths["ckpt"], str(out_dir))
            graph["3"]["inputs"]["steps"] = 2

            warm = run_load(base, graph, clients=1, requests=1, timeout=600,
                            seed_key="3:inputs:seed")
            assert warm["completed"] == 1, warm

            zipf = run_load(
                base, graph, clients=4, requests=4, timeout=600,
                seed_key="3:inputs:seed", seed=7,
                prompt_dist="zipf:1.1", prompt_key="6:inputs:text",
                prompt_vocab=vocab,
            )
            assert zipf["completed"] == 16 and zipf["failed"] == 0, zipf
            assert not zipf.get("prompts_lost"), zipf
            # The reuse gates (acceptance): hit rate nonzero; the encode
            # stage collapsed to at most half the prompt count.
            assert zipf["embed_cache_hit_rate"] is not None, zipf
            assert zipf["embed_cache_hit_rate"] > 0, zipf
            assert zipf["encoder_invocations"] is not None, zipf
            assert zipf["encoder_invocations"] <= 0.5 * zipf["requests"], zipf
            assert zipf["distinct_prompts"] <= len(vocab)
            # Decode tail engaged: every prompt decoded, dispatches counted.
            assert zipf["decode_requests"] == 16, zipf
            assert zipf["decode_dispatches"] is not None
            assert zipf["decode_dispatches"] <= zipf["decode_requests"]
            assert zipf["decode_batched_fraction"] is not None

            fanout = run_load(
                base, graph, clients=8, requests=1, timeout=600,
                seed_key="3:inputs:seed", seed=11,
                prompt_dist="zipf:1.1", prompt_key="6:inputs:text",
                prompt_vocab=["a lighthouse at dawn"], seed_fanout=8,
            )
            assert fanout["completed"] == 8 and fanout["failed"] == 0, fanout
            assert not fanout.get("prompts_lost"), fanout
            assert fanout["distinct_prompts"] == 1
            # ~1 encode for the whole fanout: the node cache + embed cache
            # collapse it; concurrent first-sight races bound it by the
            # worker count, never the fanout size.
            assert fanout["encoder_invocations"] <= 4, fanout
        finally:
            srv.shutdown()
            q.shutdown()

        # Deterministic fanout acceptance (scheduler harness — the server's
        # scheduler is uninstalled by shutdown above): 8 sibling seeds, ONE
        # shared cond object, width 4, 1-step schedules → exactly
        # ceil(8/4) = 2 dispatches, latents bitwise-equal to solo.
        from comfyui_parallelanything_tpu.serving import (
            ContinuousBatchingScheduler,
        )

        sched = ContinuousBatchingScheduler(max_width=4, auto=False).install()
        try:
            ctx = _ctx(99)
            seeds = list(range(60, 68))
            solo = {}
            for s in seeds:
                solo.update(_serve_fanout(sched, ctx, [s], steps=1))
            start = sched.total_dispatches()
            res = _serve_fanout(sched, ctx, seeds, steps=1)
            fan_dispatches = sched.total_dispatches() - start
            assert fan_dispatches == math.ceil(8 / 4), fan_dispatches
            bitwise_ok = True
            for s in seeds:
                np.testing.assert_array_equal(
                    np.asarray(res[s]), np.asarray(solo[s])
                )
        finally:
            sched.uninstall()
            sched.shutdown()

        # The kind="reuse" ledger record: the zipf rung's collapse + the
        # fanout arithmetic, appended through bench's stdlib helper (honors
        # PA_LEDGER_DIR like every other evidence writer).
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from bench import _ledger_append

        _ledger_append({
            "rung": "reuse_smoke",
            "platform": "cpu",
            "prompts": zipf["requests"],
            "prompt_dist": "zipf:1.1",
            "distinct_prompts": zipf["distinct_prompts"],
            "embed_cache_hit_rate": zipf["embed_cache_hit_rate"],
            "encoder_invocations": zipf["encoder_invocations"],
            "decode_batched_fraction": zipf["decode_batched_fraction"],
            "decode_dispatches": zipf["decode_dispatches"],
            "decode_requests": zipf["decode_requests"],
            "fanout_n": 8,
            "fanout_width": 4,
            "fanout_dispatches": fan_dispatches,
            "fanout_encoder_invocations": fanout["encoder_invocations"],
            "fanout_bitwise_equal_to_solo": bitwise_ok,
            "prompts_lost": 0,
        }, kind="reuse")
