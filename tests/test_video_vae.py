"""Video VAE: compression math, causal temporal semantics, tiled decode, and the
WAN-layout converter round-trip (same strategy as test_convert_wan.py: invert the
converter's transforms from fresh params, convert back, require bitwise identity,
then same-program forward substitution)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models.convert_wan_vae import (
    convert_wan_vae_checkpoint,
)
from comfyui_parallelanything_tpu.models.video_vae import (
    VideoAutoencoderKL,
    VideoVAEConfig,
    build_video_vae,
    wan_vae_config,
)

TINY = VideoVAEConfig(
    base_channels=8,
    channel_mult=(1, 2, 2),
    num_res_blocks=1,
    temporal_downsample=(False, True),
    z_channels=4,
    latent_mean=(0.0,) * 4,
    latent_std=(1.0,) * 4,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny_vae():
    return build_video_vae(TINY, jax.random.key(0), sample_thw=(3, 8, 8))


class TestConfigMath:
    def test_wan_factors(self):
        cfg = wan_vae_config()
        assert cfg.spatial_factor == 8
        assert cfg.temporal_factor == 4
        assert cfg.latent_frames(81) == 21  # the WAN clip length convention
        assert cfg.latent_frames(1) == 1  # single image degenerates cleanly

    def test_frame_count_must_match_schedule(self):
        with pytest.raises(ValueError):
            wan_vae_config().latent_frames(80)


class TestRoundTrip:
    def test_shapes(self, tiny_vae):
        T = 5  # 2k+1 for tf=2 → k+1 = 3 latent frames
        x = jax.random.normal(jax.random.key(1), (2, T, 16, 16, 3))
        z = tiny_vae.encode(x)
        assert z.shape == (2, 3, 4, 4, TINY.z_channels)
        y = tiny_vae.decode(z)
        assert y.shape == x.shape
        assert np.all(np.isfinite(np.asarray(y)))

    def test_single_frame_is_an_image(self, tiny_vae):
        x = jax.random.normal(jax.random.key(2), (1, 1, 16, 16, 3))
        z = tiny_vae.encode(x)
        assert z.shape == (1, 1, 4, 4, TINY.z_channels)
        assert tiny_vae.decode(z).shape == x.shape

    def test_encode_is_causal(self, tiny_vae):
        """Perturbing the last pixel frame must leave earlier latent frames
        untouched — every temporal conv is front-padded only."""
        x = jax.random.normal(jax.random.key(3), (1, 5, 16, 16, 3))
        z1 = np.asarray(tiny_vae.encode(x))
        z2 = np.asarray(tiny_vae.encode(x.at[:, -1].add(10.0)))
        per_frame = np.abs(z1 - z2).max(axis=(0, 2, 3, 4))
        assert per_frame[:-1].max() < 1e-5
        assert per_frame[-1] > 1e-3  # the perturbation does land somewhere

    def test_latent_normalization_applied(self):
        cfg = VideoVAEConfig(
            base_channels=8,
            channel_mult=(1, 2),
            num_res_blocks=1,
            temporal_downsample=(False,),
            z_channels=4,
            latent_mean=(1.0, 2.0, 3.0, 4.0),
            latent_std=(2.0,) * 4,
            dtype=jnp.float32,
        )
        vae = build_video_vae(cfg, jax.random.key(0), sample_thw=(1, 8, 8))
        x = jnp.zeros((1, 1, 8, 8, 3))
        z = vae.encode(x)
        raw_mean, _ = jax.jit(
            lambda p, v: VideoAutoencoderKL(cfg).apply(
                {"params": p}, v, method="moments"
            )
        )(vae.params, x)
        expect = (np.asarray(raw_mean) - np.array(cfg.latent_mean)) / 2.0
        np.testing.assert_allclose(np.asarray(z), expect, rtol=1e-5, atol=1e-5)


class TestTiledDecode:
    def test_matches_full_decode(self, tiny_vae):
        z = jax.random.normal(jax.random.key(4), (1, 3, 20, 20, TINY.z_channels))
        full = np.asarray(tiny_vae.decode(z), np.float32)
        tiled = np.asarray(tiny_vae.decode_tiled(z, tile=12, overlap=8), np.float32)
        assert tiled.shape == full.shape
        # Conv receptive fields (and the per-frame mid attention) cross tile
        # edges, so exact equality only holds away from seams; the blended
        # output must still track the full decode.
        err = np.abs(tiled - full).mean()
        assert err < 0.1, err

    def test_small_input_skips_tiling(self, tiny_vae):
        z = jax.random.normal(jax.random.key(5), (1, 1, 4, 4, TINY.z_channels))
        np.testing.assert_array_equal(
            np.asarray(tiny_vae.decode_tiled(z, tile=8)),
            np.asarray(tiny_vae.decode(z)),
        )


def _inv_conv3d(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["conv"]["kernel"]).transpose(4, 3, 0, 1, 2)
    sd[f"{key}.bias"] = np.asarray(p["conv"]["bias"])


def _inv_conv2d(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["kernel"])[0].transpose(3, 2, 0, 1)
    sd[f"{key}.bias"] = np.asarray(p["bias"])


def _inv_rms(p, key, sd, images=False):
    shape = (-1, 1, 1) if images else (-1, 1, 1, 1)
    sd[f"{key}.gamma"] = np.asarray(p["scale"]).reshape(shape)
    if "bias" in p:
        sd[f"{key}.bias"] = np.asarray(p["bias"]).reshape(shape)


def _inv_res_block(p, key, sd):
    _inv_rms(p["norm1"], f"{key}.residual.0", sd)
    _inv_conv3d(p["conv1"], f"{key}.residual.2", sd)
    _inv_rms(p["norm2"], f"{key}.residual.3", sd)
    _inv_conv3d(p["conv2"], f"{key}.residual.6", sd)
    if "shortcut" in p:
        _inv_conv3d(p["shortcut"], f"{key}.shortcut", sd)


def _inv_attn(p, key, sd):
    _inv_rms(p["norm"], f"{key}.norm", sd, images=True)
    _inv_conv2d(p["to_qkv"], f"{key}.to_qkv", sd)
    _inv_conv2d(p["proj"], f"{key}.proj", sd)


def _official_layout_sd(cfg: VideoVAEConfig, params) -> dict:
    sd: dict = {}
    n = len(cfg.channel_mult)
    enc, dec = params["encoder"], params["decoder"]
    _inv_conv3d(enc["conv_in"], "encoder.conv1", sd)
    _inv_res_block(enc["mid_block_1"], "encoder.middle.0", sd)
    _inv_attn(enc["mid_attn_1"], "encoder.middle.1", sd)
    _inv_res_block(enc["mid_block_2"], "encoder.middle.2", sd)
    _inv_rms(enc["norm_out"], "encoder.head.0", sd)
    _inv_conv3d(enc["conv_out"], "encoder.head.2", sd)
    seq = 0
    for level in range(n):
        for i in range(cfg.num_res_blocks):
            _inv_res_block(
                enc[f"down_{level}_block_{i}"], f"encoder.downsamples.{seq}", sd
            )
            seq += 1
        if level != n - 1:
            ds = enc[f"down_{level}_downsample"]
            _inv_conv2d(ds["conv"], f"encoder.downsamples.{seq}.resample.1", sd)
            if "time_conv" in ds:
                _inv_conv3d(
                    ds["time_conv"], f"encoder.downsamples.{seq}.time_conv", sd
                )
            seq += 1
    _inv_conv3d(dec["conv_in"], "decoder.conv1", sd)
    _inv_res_block(dec["mid_block_1"], "decoder.middle.0", sd)
    _inv_attn(dec["mid_attn_1"], "decoder.middle.1", sd)
    _inv_res_block(dec["mid_block_2"], "decoder.middle.2", sd)
    _inv_rms(dec["norm_out"], "decoder.head.0", sd)
    _inv_conv3d(dec["conv_out"], "decoder.head.2", sd)
    seq = 0
    for j, level in enumerate(reversed(range(n))):
        for i in range(cfg.num_res_blocks + 1):
            _inv_res_block(
                dec[f"up_{level}_block_{i}"], f"decoder.upsamples.{seq}", sd
            )
            seq += 1
        if j != n - 1:
            us = dec[f"up_{level}_upsample"]
            _inv_conv2d(us["conv"], f"decoder.upsamples.{seq}.resample.1", sd)
            if "time_conv" in us:
                _inv_conv3d(
                    us["time_conv"], f"decoder.upsamples.{seq}.time_conv", sd
                )
            seq += 1
    _inv_conv3d(params["quant_conv"], "conv1", sd)
    _inv_conv3d(params["post_quant_conv"], "conv2", sd)
    return sd


class TestConverter:
    def test_round_trip_bitwise(self, tiny_vae):
        sd = _official_layout_sd(TINY, tiny_vae.params)
        converted = convert_wan_vae_checkpoint(sd, TINY)
        ref = dict(flatten_tree(tiny_vae.params))
        got = dict(flatten_tree(converted))
        assert set(ref) == set(got), set(ref) ^ set(got)
        for k, v in ref.items():
            np.testing.assert_array_equal(np.asarray(v), np.asarray(got[k]), err_msg=k)

    def test_converted_forward_matches(self, tiny_vae):
        sd = _official_layout_sd(TINY, tiny_vae.params)
        vae2 = build_video_vae(TINY, params=convert_wan_vae_checkpoint(sd, TINY))
        x = jax.random.normal(jax.random.key(6), (1, 3, 16, 16, 3))
        np.testing.assert_allclose(
            np.asarray(vae2.encode(x)),
            np.asarray(tiny_vae.encode(x)),
            rtol=1e-6,
            atol=1e-6,
        )

    def test_missing_attn_norm_bias_zero_filled(self, tiny_vae):
        """The torch RMS_norm in the attention block has no bias by default —
        the converter must fill zeros rather than fail."""
        sd = _official_layout_sd(TINY, tiny_vae.params)
        sd = {k: v for k, v in sd.items() if not k.endswith("middle.1.norm.bias")}
        converted = convert_wan_vae_checkpoint(sd, TINY)
        b = np.asarray(converted["encoder"]["mid_attn_1"]["norm"]["bias"])
        assert (b == 0).all()


class TestLoader:
    def test_load_with_prefix_strip(self, tiny_vae):
        from comfyui_parallelanything_tpu.models import load_wan_vae_checkpoint

        sd = {
            f"vae.{k}": v
            for k, v in _official_layout_sd(TINY, tiny_vae.params).items()
        }
        vae2 = load_wan_vae_checkpoint(sd, TINY)
        z = jax.random.normal(jax.random.key(7), (1, 1, 4, 4, TINY.z_channels))
        np.testing.assert_allclose(
            np.asarray(vae2.decode(z)),
            np.asarray(tiny_vae.decode(z)),
            rtol=1e-6,
            atol=1e-6,
        )

    def test_load_bare_layout(self, tiny_vae):
        from comfyui_parallelanything_tpu.models import load_wan_vae_checkpoint

        sd = _official_layout_sd(TINY, tiny_vae.params)
        vae2 = load_wan_vae_checkpoint(sd, TINY)
        assert vae2.cfg == TINY

    def test_load_rejects_wrong_layout(self):
        from comfyui_parallelanything_tpu.models import load_wan_vae_checkpoint

        with pytest.raises(ValueError, match="not the official"):
            load_wan_vae_checkpoint({"decoder.unrelated.weight": np.zeros(3)}, TINY)
