"""Text encoders: golden parity vs the canonical torch implementations.

torch + transformers are CPU-importable here, so CLIP and T5 are checked against
randomly-initialized `transformers` models directly: export the torch state dict,
convert with models/convert_text.py, run both, compare activations. This is a much
stronger check than round-trip inversion — it validates the architecture itself
(pre-LN order, quick-gelu, T5 bucket scheme, unscaled T5 dot products), not just
the layout transposes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models.convert_text import (
    convert_clip_text_checkpoint,
    convert_open_clip_checkpoint,
    convert_t5_checkpoint,
)
from comfyui_parallelanything_tpu.models.text_encoders import (
    CLIPTextConfig,
    T5Config,
    build_clip_text,
    build_t5_encoder,
    clip_l_config,
    open_clip_g_config,
    sdxl_text_conditioning,
    t5_xxl_config,
)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


# eos = vocab-1 like the real tower (49407/49408). Keeping eos_token_id != 2 also
# steers transformers off its legacy pooling path (argmax of raw ids) onto the
# first-EOS-position rule this implementation uses.
TINY_CLIP = CLIPTextConfig(
    vocab_size=100,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    max_len=16,
    eos_id=99,
    dtype=jnp.float32,
)


def _hf_clip(cfg: CLIPTextConfig, act: str):
    hf_cfg = transformers.CLIPTextConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.d_ff,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        max_position_embeddings=cfg.max_len,
        hidden_act=act,
        eos_token_id=cfg.eos_id,
        bos_token_id=0,
        pad_token_id=1,
    )
    torch.manual_seed(0)
    return transformers.CLIPTextModel(hf_cfg).eval()


class TestCLIPGolden:
    @pytest.mark.parametrize("act", ["quick_gelu", "gelu"])
    def test_matches_transformers(self, act):
        import dataclasses

        cfg = dataclasses.replace(TINY_CLIP, act=act)
        hf = _hf_clip(cfg, act)
        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        params = convert_clip_text_checkpoint(sd, cfg)
        enc = build_clip_text(cfg, params=params)

        rng = np.random.default_rng(0)
        tokens = rng.integers(3, cfg.vocab_size - 1, (2, cfg.max_len))
        tokens[:, -3] = cfg.eos_id  # EOS mid-sequence exercises the pool index
        with torch.no_grad():
            out = hf(torch.from_numpy(tokens))
        last, penultimate, pooled = enc(jnp.asarray(tokens, jnp.int32))
        np.testing.assert_allclose(
            np.asarray(last), out.last_hidden_state.numpy(), rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(pooled), out.pooler_output.numpy(), rtol=2e-4, atol=2e-4
        )
        assert penultimate.shape == last.shape

    def test_wrapped_prefix_conversion(self):
        # SD checkpoints wrap the tower under cond_stage_model.transformer.*
        hf = _hf_clip(TINY_CLIP, "quick_gelu")
        sd = {
            f"cond_stage_model.transformer.{k}": v.detach().numpy()
            for k, v in hf.state_dict().items()
        }
        params = convert_clip_text_checkpoint(sd, TINY_CLIP)
        enc = build_clip_text(TINY_CLIP, params=params)
        tokens = jnp.full((1, TINY_CLIP.max_len), 5, jnp.int32)
        last, _, _ = enc(tokens)
        assert last.shape == (1, TINY_CLIP.max_len, TINY_CLIP.hidden_size)


class TestOpenCLIPConversion:
    def test_fused_qkv_roundtrip(self):
        """Synthesize an OpenCLIP-layout dict (fused in_proj, raw text_projection
        matrix) from known per-head weights and check the split lands correctly."""
        import dataclasses

        cfg = dataclasses.replace(TINY_CLIP, act="gelu", projection_dim=32)
        enc = build_clip_text(cfg, rng=jax.random.key(0))
        p = enc.params
        sd = self._openclip_layout(cfg, p)
        got = convert_open_clip_checkpoint(sd, cfg)
        fg, fw = dict(flatten_tree(got)), dict(flatten_tree(p))
        assert sorted(fg) == sorted(fw)
        for k in fw:
            np.testing.assert_array_equal(fg[k], fw[k], err_msg=str(k))

    def test_combined_sdxl_checkpoint_selects_openclip_tower(self):
        """A single-file SDXL checkpoint holds BOTH towers: the HF CLIP-L under
        conditioner.embedders.0.transformer.* and OpenCLIP-G under
        conditioner.embedders.1.model.*. The converter must anchor on the OpenCLIP
        subtree even though the HF tower also contains token_embedding.weight."""
        import dataclasses

        cfg = dataclasses.replace(TINY_CLIP, act="gelu", projection_dim=32)
        enc = build_clip_text(cfg, rng=jax.random.key(2))
        flat_sd = self._openclip_layout(cfg, enc.params)
        combined = {
            # Decoy HF tower key that sorts/iterates first:
            "conditioner.embedders.0.transformer.text_model.embeddings."
            "token_embedding.weight": np.zeros((100, 64), np.float32),
        }
        combined.update(
            {f"conditioner.embedders.1.model.{k}": v for k, v in flat_sd.items()}
        )
        got = convert_open_clip_checkpoint(combined, cfg)
        np.testing.assert_array_equal(
            np.asarray(got["tok_emb"]["embedding"]),
            np.asarray(enc.params["tok_emb"]["embedding"]),
        )

    @staticmethod
    def _openclip_layout(cfg, p):
        sd = {
            "token_embedding.weight": np.asarray(p["tok_emb"]["embedding"]),
            "positional_embedding": np.asarray(p["pos_emb"]),
            "ln_final.weight": np.asarray(p["final_ln"]["scale"]),
            "ln_final.bias": np.asarray(p["final_ln"]["bias"]),
            "text_projection": np.asarray(p["text_proj"]["kernel"]),
        }
        for i in range(cfg.num_layers):
            blk = p[f"layers_{i}"]
            t = f"transformer.resblocks.{i}"
            sd[f"{t}.attn.in_proj_weight"] = np.concatenate(
                [np.asarray(blk[n]["kernel"]).T for n in "qkv"], axis=0
            )
            sd[f"{t}.attn.in_proj_bias"] = np.concatenate(
                [np.asarray(blk[n]["bias"]) for n in "qkv"]
            )
            sd[f"{t}.attn.out_proj.weight"] = np.asarray(blk["out"]["kernel"]).T
            sd[f"{t}.attn.out_proj.bias"] = np.asarray(blk["out"]["bias"])
            sd[f"{t}.mlp.c_fc.weight"] = np.asarray(blk["fc1"]["kernel"]).T
            sd[f"{t}.mlp.c_fc.bias"] = np.asarray(blk["fc1"]["bias"])
            sd[f"{t}.mlp.c_proj.weight"] = np.asarray(blk["fc2"]["kernel"]).T
            sd[f"{t}.mlp.c_proj.bias"] = np.asarray(blk["fc2"]["bias"])
            sd[f"{t}.ln_1.weight"] = np.asarray(blk["ln1"]["scale"])
            sd[f"{t}.ln_1.bias"] = np.asarray(blk["ln1"]["bias"])
            sd[f"{t}.ln_2.weight"] = np.asarray(blk["ln2"]["scale"])
            sd[f"{t}.ln_2.bias"] = np.asarray(blk["ln2"]["bias"])
        return sd

    def test_sdxl_wrapper_prefix(self):
        cfg = open_clip_g_config(
            vocab_size=100, hidden_size=64, num_layers=2, num_heads=4,
            max_len=16, projection_dim=32, dtype=jnp.float32,
        )
        enc = build_clip_text(cfg, rng=jax.random.key(1))
        # Minimal prefixed dict: only check the prefix detection path raises no
        # KeyError on the anchor, then fails on a genuinely absent layer key.
        sd = {
            "conditioner.embedders.1.model.token_embedding.weight": np.zeros(
                (100, 64), np.float32
            )
        }
        with pytest.raises(KeyError):
            convert_open_clip_checkpoint(sd, cfg)


TINY_T5 = T5Config(
    vocab_size=100,
    d_model=64,
    num_layers=2,
    num_heads=4,
    d_kv=16,
    d_ff=128,
    dtype=jnp.float32,
)


class TestT5Golden:
    def test_matches_transformers(self):
        hf_cfg = transformers.T5Config(
            vocab_size=TINY_T5.vocab_size,
            d_model=TINY_T5.d_model,
            d_kv=TINY_T5.d_kv,
            d_ff=TINY_T5.d_ff,
            num_layers=TINY_T5.num_layers,
            num_heads=TINY_T5.num_heads,
            relative_attention_num_buckets=TINY_T5.relative_buckets,
            relative_attention_max_distance=TINY_T5.relative_max_distance,
            feed_forward_proj="gated-gelu",
            dropout_rate=0.0,
        )
        torch.manual_seed(0)
        hf = transformers.T5EncoderModel(hf_cfg).eval()
        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        params = convert_t5_checkpoint(sd, TINY_T5)
        enc = build_t5_encoder(TINY_T5, params=params)

        rng = np.random.default_rng(2)
        tokens = rng.integers(0, TINY_T5.vocab_size, (2, 24))
        mask = np.ones((2, 24), np.int32)
        mask[1, 16:] = 0  # padded second row exercises the bias mask
        with torch.no_grad():
            want = hf(
                torch.from_numpy(tokens), attention_mask=torch.from_numpy(mask)
            ).last_hidden_state.numpy()
        got = np.asarray(enc(jnp.asarray(tokens, jnp.int32), mask=jnp.asarray(mask)))
        # Padded positions produce garbage in both frameworks (masked as keys only);
        # compare real tokens.
        np.testing.assert_allclose(got[0], want[0], rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(got[1, :16], want[1, :16], rtol=2e-4, atol=2e-4)

    def test_full_size_config_constants(self):
        cfg = t5_xxl_config()
        assert (cfg.d_model, cfg.num_layers, cfg.num_heads, cfg.d_ff) == (
            4096, 24, 64, 10240,
        )


class TestSDXLConditioning:
    def test_shapes(self):
        B, S = 2, 16
        l_pen = jnp.zeros((B, S, 768))
        g_pen = jnp.zeros((B, S, 1280))
        g_pool = jnp.zeros((B, 1280))
        ctx, y = sdxl_text_conditioning(l_pen, g_pen, g_pool, 1024, 1024)
        assert ctx.shape == (B, S, 2048)
        assert y.shape == (B, 2816)  # matches sdxl_config().adm_in_channels


class TestUMT5Golden:
    def test_matches_transformers_per_layer_bias(self):
        import dataclasses

        cfg = dataclasses.replace(
            TINY_T5, per_layer_bias=True, vocab_size=TINY_T5.vocab_size
        )
        hf_cfg = transformers.UMT5Config(
            vocab_size=cfg.vocab_size,
            d_model=cfg.d_model,
            d_kv=cfg.d_kv,
            d_ff=cfg.d_ff,
            num_layers=cfg.num_layers,
            num_heads=cfg.num_heads,
            relative_attention_num_buckets=cfg.relative_buckets,
            relative_attention_max_distance=cfg.relative_max_distance,
            feed_forward_proj="gated-gelu",
            dropout_rate=0.0,
        )
        torch.manual_seed(0)
        hf = transformers.UMT5EncoderModel(hf_cfg).eval()
        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        params = convert_t5_checkpoint(sd, cfg)
        # Per-layer tables must exist and be distinct from each other.
        assert "rel_bias_0" in params and "rel_bias_1" in params
        assert not np.allclose(
            np.asarray(params["rel_bias_0"]), np.asarray(params["rel_bias_1"])
        )
        enc = build_t5_encoder(cfg, params=params)

        rng = np.random.default_rng(3)
        tokens = rng.integers(0, cfg.vocab_size, (2, 24))
        mask = np.ones((2, 24), np.int32)
        mask[1, 16:] = 0
        with torch.no_grad():
            want = hf(
                torch.from_numpy(tokens), attention_mask=torch.from_numpy(mask)
            ).last_hidden_state.numpy()
        got = np.asarray(enc(jnp.asarray(tokens, jnp.int32), mask=jnp.asarray(mask)))
        np.testing.assert_allclose(got[0], want[0], rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(got[1, :16], want[1, :16], rtol=2e-4, atol=2e-4)

    def test_umt5_xxl_config_constants(self):
        from comfyui_parallelanything_tpu.models import umt5_xxl_config

        cfg = umt5_xxl_config()
        assert cfg.per_layer_bias and cfg.vocab_size == 256384
        assert (cfg.d_model, cfg.num_layers, cfg.num_heads, cfg.d_ff) == (
            4096, 24, 64, 10240,
        )
