"""Resource telemetry & perf ledger (round 9):

- utils/telemetry.py compile observability: per-program compile accounting
  via the jax.monitoring listeners, instrument_jit attribution, HLO
  cost-analysis FLOPs, compile spans feeding the tracer;
- cross-process compile-cache accounting: a tmp PA_TPU_COMPILE_CACHE dir —
  first process records misses + compile time, a re-run in a fresh
  subprocess records hits with compile_time_s ≈ 0;
- devices/memory.py telemetry surface: deterministic CPU pseudo-limit,
  utilization math off-hardware, pa_hbm_* gauges, ResidencyTracker gauges,
  the HbmWatermark;
- the perf ledger (schema stamps, append) and scripts/perf_ledger.py's
  regression gate (passes on banked records unchanged, flags an injected
  2x step-time regression and a peak-HBM regression, skips stale/dryrun);
- postmortem bundles (write_postmortem artifact set, OOM classifier) and
  bench.py's forced-failure path end to end (PA_FAIL_INJECT: error JSON
  line with null resource fields + a bundle holding trace/metrics/memory/
  logs);
- GET /health on the workflow server;
- the static-analysis guard: no bare print()/time.time() in the package
  outside the explicit allowlist (the PARITY print-site → span/log/metric
  vocabulary, enforced).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import threading
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from comfyui_parallelanything_tpu.devices.memory import (
    ResidencyTracker,
    device_memory_stats,
    memory_snapshot,
    publish_memory_gauges,
)
from comfyui_parallelanything_tpu.utils import telemetry, tracing
from comfyui_parallelanything_tpu.utils.metrics import registry

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _clean_state():
    telemetry.compile_registry.reset()
    telemetry.watermark.reset()
    yield
    telemetry.compile_registry.reset()
    telemetry.watermark.reset()
    tracing.disable()
    tracing.tracer.clear()


def _cpu_env(extra: dict | None = None) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    ).strip()
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


class TestCompileRegistry:
    def test_instrumented_jit_attributes_compiles(self, monkeypatch):
        monkeypatch.setenv("PA_TELEMETRY_COST", "1")  # conftest defaults it off
        telemetry.watch_compiles()
        fn = telemetry.instrument_jit(
            lambda x: (x @ x).sum(), "t-reg-prog"
        )
        out = fn(jnp.ones((32, 32)))
        assert float(out) == pytest.approx(32.0 * 32 * 32)
        snap = telemetry.compile_snapshot()
        prog = snap["programs"]["t-reg-prog"]
        assert prog["compiles"] >= 1
        assert prog["compile_time_s"] > 0
        # HLO cost analysis attached on the first compile: a 32x32x32 matmul
        # is ~2*32^3 FLOPs plus the reduction.
        assert prog["flops"] and prog["flops"] > 2 * 32**3
        assert snap["compiles"] >= prog["compiles"]
        # Second call, same shapes: no new compile for this program.
        n = prog["compiles"]
        fn(jnp.ones((32, 32)))
        assert telemetry.compile_registry.compiles_of("t-reg-prog") == n
        # New shape: a fresh compile under the same program name.
        fn(jnp.ones((16, 16)))
        assert telemetry.compile_registry.compiles_of("t-reg-prog") > n
        # The metrics twin landed.
        assert registry.get(
            "pa_compile_total", {"program": "t-reg-prog"}
        ) >= 1

    def test_unattributed_compiles_still_counted(self):
        telemetry.watch_compiles()
        before = telemetry.compile_snapshot()["compiles"]
        jax.jit(lambda x: x * 3 + 7)(jnp.ones((5,)))  # bare jit, no wrapper
        snap = telemetry.compile_snapshot()
        assert snap["compiles"] > before
        assert "(unattributed)" in snap["programs"]

    def test_compile_span_recorded_when_tracing(self):
        telemetry.watch_compiles()
        tracing.enable()
        telemetry.instrument_jit(
            lambda x: jnp.tanh(x) * 2, "t-span-prog"
        )(jnp.ones((8, 8)))
        xs = [e for e in tracing.export()["traceEvents"]
              if e.get("ph") == "X" and e["name"] == "compile"]
        assert any(
            e["args"].get("program") == "t-span-prog" and e["dur"] > 0
            for e in xs
        )

    def test_donated_loop_program_still_accounted(self):
        """The loop-jit cache (sampling/compiled.py) instruments its donated
        programs — run_sampler(compile_loop=True) must leave a loop:* entry
        in the registry."""
        from comfyui_parallelanything_tpu.sampling.compiled import (
            clear_compiled_loops,
        )
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        telemetry.watch_compiles()
        clear_compiled_loops()

        def model(x, t, context=None, **kw):
            return x * 0.9

        run_sampler(
            model, jnp.ones((1, 4, 4, 4)), jnp.ones((1, 3, 8)),
            sampler="euler", steps=2, compile_loop=True,
        )
        progs = telemetry.compile_snapshot()["programs"]
        assert "loop:k:euler" in progs
        assert progs["loop:k:euler"]["compiles"] >= 1


_XPROC_SCRIPT = r"""
import json, os, sys
import jax, jax.numpy as jnp
from comfyui_parallelanything_tpu.utils import enable_compilation_cache, telemetry
telemetry.watch_compiles()
enable_compilation_cache(sys.argv[1])
fn = telemetry.instrument_jit(lambda x: (x @ x + x).sum(), "xproc-prog")
fn(jnp.ones((256, 256)))
print(json.dumps(telemetry.compile_snapshot()))
"""


class TestCrossProcessCompileCache:
    def test_miss_then_hit_across_processes(self, tmp_path):
        """The satellite contract: a tmp PA_TPU_COMPILE_CACHE dir — the
        first run records misses and real compile time; an identical re-run
        in a FRESH subprocess records hits with compile_time_s ≈ 0 (a
        persistent-cache hit skips backend compile entirely, so no compile
        duration is ever recorded for the program)."""
        cache = tmp_path / "xla-cache"
        env = _cpu_env({
            # Sub-second test programs must still persist (the production
            # threshold of 0.5s would skip them and fake a second-run miss).
            "PA_COMPILE_CACHE_MIN_S": "0",
            "PA_TPU_COMPILE_CACHE": str(cache),
        })

        def run():
            proc = subprocess.run(
                [sys.executable, "-c", _XPROC_SCRIPT, str(cache)],
                env=env, cwd=str(REPO), capture_output=True, text=True,
                timeout=300,
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            return json.loads(proc.stdout.strip().splitlines()[-1])

        first = run()
        prog1 = first["programs"]["xproc-prog"]
        assert prog1["cache_misses"] >= 1 and prog1["cache_hits"] == 0
        assert prog1["compiles"] >= 1 and prog1["compile_time_s"] > 0
        assert os.listdir(cache), "nothing persisted to the cache dir"
        second = run()
        prog2 = second["programs"]["xproc-prog"]
        assert prog2["cache_hits"] >= 1 and prog2["cache_misses"] == 0
        assert prog2["compile_time_s"] == pytest.approx(0.0, abs=0.02), (
            "a cache hit must not pay (or book) a backend compile"
        )


class TestMemoryTelemetry:
    def test_deterministic_cpu_fallback(self, monkeypatch):
        monkeypatch.setenv("PA_CPU_FAKE_HBM_BYTES", str(1 << 31))
        dev = jax.devices("cpu")[0]
        s = device_memory_stats(dev)
        assert s["source"] == "fallback"
        assert s["bytes_limit"] == 1 << 31  # the pseudo-limit, exactly
        assert s["device"] == "cpu:0"

    def test_utilization_math_off_hardware(self, monkeypatch):
        monkeypatch.setenv("PA_CPU_FAKE_HBM_BYTES", str(1 << 30))
        dev = jax.devices("cpu")[0]
        before = device_memory_stats(dev)["bytes_in_use"]
        big = jax.device_put(jnp.ones((512, 512), jnp.float32), dev)
        big.block_until_ready()
        snap = memory_snapshot([dev])[0]
        delta = snap["bytes_in_use"] - before
        assert delta >= big.nbytes  # our MiB shows up in the accounting
        # utilization is bytes_in_use / pseudo-limit, rounded to 6 places
        assert snap["utilization"] == round(
            snap["bytes_in_use"] / (1 << 30), 6
        )
        del big

    def test_publish_memory_gauges(self):
        devs = jax.devices("cpu")[:2]
        snap = publish_memory_gauges(devs)
        assert len(snap) == 2
        for s in snap:
            lbl = {"device": s["device"]}
            assert registry.get("pa_hbm_bytes_limit", lbl) == s["bytes_limit"]
            assert registry.get("pa_hbm_bytes_in_use", lbl) == s["bytes_in_use"]

    def test_residency_tracker_gauges(self):
        t = ResidencyTracker()
        t.add_resident(100)
        t.place("s0", 1000)
        t.place("s1", 2000)
        t.publish_gauges("cpu:7", bound_bytes=4000)
        lbl = {"device": "cpu:7"}
        assert registry.get("pa_hbm_stream_live_bytes", lbl) == 3000
        assert registry.get("pa_hbm_stream_peak_bytes", lbl) == 3000
        assert registry.get("pa_hbm_stream_resident_bytes", lbl) == 100
        assert registry.get("pa_hbm_stream_bound_bytes", lbl) == 4000
        t.retire("s0")
        t.publish_gauges("cpu:7")
        assert registry.get("pa_hbm_stream_live_bytes", lbl) == 2000
        assert registry.get("pa_hbm_stream_peak_bytes", lbl) == 3000

    def test_watermark(self):
        dev = jax.devices("cpu")[0]
        assert telemetry.watermark.peak_bytes == 0
        keep = jax.device_put(jnp.ones((256, 256)), dev)
        keep.block_until_ready()
        snap = telemetry.watermark.sample([dev])
        assert len(snap) == 1
        assert telemetry.watermark.peak_bytes >= keep.nbytes
        peak = telemetry.watermark.peak_bytes
        del keep
        telemetry.watermark.sample([dev])
        # The watermark is a high-water mark: freeing memory never lowers it.
        assert telemetry.watermark.peak_bytes == peak
        assert registry.get("pa_hbm_peak_bytes") == peak


class TestPerfLedger:
    def test_append_stamps_schema(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path / "led"))
        path = telemetry.append_ledger_record(
            {"rung": "smoke", "value": 1.25, "platform": "cpu"}, "bench"
        )
        assert path == str(tmp_path / "led" / "perf_ledger.jsonl")
        [line] = open(path).read().strip().splitlines()
        rec = json.loads(line)
        assert rec["schema"] == telemetry.LEDGER_SCHEMA
        assert rec["kind"] == "bench" and rec["value"] == 1.25
        assert rec["ts"] > 0 and rec["pid"] == os.getpid()

    def _gate(self, ledger_dir, baseline, *extra):
        return subprocess.run(
            [sys.executable, str(REPO / "scripts" / "perf_ledger.py"),
             "--check", "--ledger", str(ledger_dir),
             "--baseline", str(baseline), *extra],
            capture_output=True, text=True, timeout=120,
        )

    def _seed(self, tmp_path, ledger_lines, banked_lines):
        led = tmp_path / "ledger"
        led.mkdir(exist_ok=True)
        with open(led / "perf_ledger.jsonl", "w") as f:
            for r in ledger_lines:
                f.write(json.dumps({
                    "schema": telemetry.LEDGER_SCHEMA, "kind": "bench", **r
                }) + "\n")
        banked = tmp_path / "BASELINE_measured.json"
        with open(banked, "w") as f:
            for r in banked_lines:
                f.write(json.dumps(r) + "\n")
        return led, banked

    BANKED = [
        {"rung": "sd15_16", "platform": "tpu", "value": 2.5, "ts": 1.0,
         "peak_hbm_bytes": 10 * 2**30},
        {"rung": "sd15_16", "platform": "tpu", "value": 2.6, "ts": 2.0,
         "peak_hbm_bytes": 10 * 2**30},
    ]

    def test_passes_on_banked_records_unchanged(self, tmp_path):
        led, banked = self._seed(tmp_path, [
            {"rung": "sd15_16", "platform": "tpu", "value": 2.55,
             "peak_hbm_bytes": 10 * 2**30, "ts": 3.0},
        ], self.BANKED)
        proc = self._gate(led, banked)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK    sd15_16/tpu [banked]" in proc.stdout

    def test_flags_2x_step_time_regression(self, tmp_path):
        led, banked = self._seed(tmp_path, [
            {"rung": "sd15_16", "platform": "tpu", "value": 5.1,
             "peak_hbm_bytes": 10 * 2**30, "ts": 3.0},
        ], self.BANKED)
        proc = self._gate(led, banked)
        assert proc.returncode == 1
        assert "REGRESSION  sd15_16/tpu" in proc.stdout
        assert "step time" in proc.stdout

    def test_flags_peak_hbm_regression(self, tmp_path):
        led, banked = self._seed(tmp_path, [
            {"rung": "sd15_16", "platform": "tpu", "value": 2.5,
             "peak_hbm_bytes": 14 * 2**30, "ts": 3.0},
        ], self.BANKED)
        proc = self._gate(led, banked)
        assert proc.returncode == 1
        assert "peak HBM" in proc.stdout

    def test_hbm_gate_live_when_banked_records_predate_round9(self, tmp_path):
        """Banked evidence without peak_hbm_bytes (everything banked before
        round 9) must not disarm the HBM half of the gate: the HBM baseline
        resolves independently, falling back to the prior ledger records."""
        led, banked = self._seed(tmp_path, [
            {"rung": "sd15_16", "platform": "tpu", "value": 2.5,
             "peak_hbm_bytes": 1 * 2**30, "ts": 3.0},
            {"rung": "sd15_16", "platform": "tpu", "value": 2.5,
             "peak_hbm_bytes": 5 * 2**30, "ts": 4.0},
        ], [
            {"rung": "sd15_16", "platform": "tpu", "value": 2.5, "ts": 1.0},
        ])
        proc = self._gate(led, banked)
        assert proc.returncode == 1, proc.stdout
        assert "peak HBM" in proc.stdout

    def test_stale_dryrun_error_records_never_compared(self, tmp_path):
        led, banked = self._seed(tmp_path, [
            {"rung": "sd15_16", "platform": "tpu", "value": 99.0,
             "stale": True, "ts": 3.0},
            {"rung": "sd15_16", "platform": "tpu", "value": 99.0,
             "dryrun": True, "ts": 4.0},
            {"rung": "sd15_16", "platform": "tpu", "value": 99.0,
             "kind": "error", "ts": 5.0},
        ], self.BANKED)
        proc = self._gate(led, banked)
        assert proc.returncode == 0, proc.stdout
        assert "no comparable bench records" in proc.stdout

    def test_ledger_prior_fallback_when_nothing_banked(self, tmp_path):
        led, banked = self._seed(tmp_path, [
            {"rung": "smoke", "platform": "cpu", "value": 5.0, "ts": 1.0},
            {"rung": "smoke", "platform": "cpu", "value": 5.2, "ts": 2.0},
            {"rung": "smoke", "platform": "cpu", "value": 11.0, "ts": 3.0},
        ], [])
        proc = self._gate(led, banked)
        assert proc.returncode == 1
        assert "ledger[2]" in proc.stdout  # baseline = the 2 prior records
        # A lone record with no history is a SKIP, not a failure.
        led2, banked2 = self._seed(tmp_path, [
            {"rung": "smoke", "platform": "cpu", "value": 5.0, "ts": 1.0},
        ], [])
        proc = self._gate(led2, banked2)
        assert proc.returncode == 0
        assert "SKIP" in proc.stdout


class TestPostmortem:
    def test_looks_like_oom(self):
        assert telemetry.looks_like_oom(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating"))
        assert telemetry.looks_like_oom("XlaRuntimeError: Out of memory")
        assert not telemetry.looks_like_oom(ValueError("bad shape"))

    def test_bundle_artifacts(self, tmp_path, monkeypatch):
        from comfyui_parallelanything_tpu.utils.logging import get_logger

        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        tracing.enable()
        with tracing.span("prompt", prompt_id="pm-test"):
            pass
        get_logger().warning("flight-recorder breadcrumb %d", 42)
        err = RuntimeError("RESOURCE_EXHAUSTED: synthetic")
        path = telemetry.write_postmortem(
            "unit/test tag", error=err, extra={"rung": "smoke"}
        )
        assert path and path.startswith(str(tmp_path / "postmortem"))
        names = sorted(os.listdir(path))
        assert names == ["error.json", "logs.txt", "memory.json",
                         "metrics.prom", "trace.json"]
        info = json.load(open(os.path.join(path, "error.json")))
        assert info["error_type"] == "RuntimeError"
        assert info["oom"] is True
        assert "traceback" not in info or isinstance(info["traceback"], str)
        assert info["extra"] == {"rung": "smoke"}
        assert "compile" in info and "peak_hbm_bytes" in info
        trace = json.load(open(os.path.join(path, "trace.json")))
        assert any(
            e.get("name") == "prompt" for e in trace["traceEvents"]
        )
        assert "flight-recorder breadcrumb 42" in open(
            os.path.join(path, "logs.txt")).read()
        mem = json.load(open(os.path.join(path, "memory.json")))
        assert mem["devices"] and mem["devices"][0]["bytes_limit"] > 0
        # Two bundles in the same second must not collide.
        path2 = telemetry.write_postmortem("unit/test tag", error=err)
        assert path2 != path and os.path.isdir(path2)


class TestBenchForcedFailure:
    def test_injected_oom_produces_error_line_and_bundle(self, tmp_path):
        """The acceptance path end to end: PA_FAIL_INJECT=oom fails the CPU
        smoke child mid-run — the outer still prints exactly one JSON line
        (error schema, resource fields present as nulls) pointing at a
        postmortem bundle with trace + metrics + memory snapshots, and the
        ledger records the failed attempt as kind=error."""
        env = _cpu_env({
            "PA_EVIDENCE_DIR": str(tmp_path),
            "PA_FAIL_INJECT": "oom",
            "BENCH_FORCE_CPU": "1",
            # Hermetic: the smoke child enables the persistent compile cache;
            # keep its writes out of the machine-global ~/.cache dir.
            "PA_TPU_COMPILE_CACHE": str(tmp_path / "xla-cache"),
        })
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            env=env, cwd=str(REPO), capture_output=True, text=True,
            timeout=900,
        )
        assert proc.returncode == 1
        lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
        assert len(lines) == 1, lines
        rec = json.loads(lines[0])
        assert "RESOURCE_EXHAUSTED" in rec["error"]
        for field in ("compile_time_s", "compile_cache_hits",
                      "compile_cache_misses", "peak_hbm_bytes"):
            assert field in rec and rec[field] is None
        bundle = rec["postmortem"]
        assert bundle and os.path.isdir(bundle)
        assert bundle.startswith(str(tmp_path)), (
            "bundle escaped the redirected evidence dir"
        )
        names = sorted(os.listdir(bundle))
        assert {"error.json", "memory.json", "metrics.prom",
                "trace.json"} <= set(names)
        info = json.load(open(os.path.join(bundle, "error.json")))
        assert info["oom"] is True
        # The bundle captured the run's actual telemetry: compiles happened
        # before the injected failure, and warmup steps were traced.
        assert info["compile"]["compiles"] > 0
        trace = json.load(open(os.path.join(bundle, "trace.json")))
        assert any(e.get("name") == "step"
                   for e in trace["traceEvents"] if e.get("ph") == "X")
        ledger = tmp_path / "ledger" / "perf_ledger.jsonl"
        kinds = [json.loads(l)["kind"]
                 for l in open(ledger).read().strip().splitlines()]
        assert "error" in kinds


class _EchoNode:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"x": ("INT", {"default": 0})}}

    RETURN_TYPES = ("INT",)
    FUNCTION = "run"

    def run(self, x):
        return (x + 1,)


class TestHealthEndpoint:
    @pytest.fixture
    def server(self, tmp_path):
        from comfyui_parallelanything_tpu.server import make_server

        srv, q = make_server(
            port=0, output_dir=str(tmp_path / "out"),
            class_mappings={"Echo": _EchoNode},
        )
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        yield base, q
        srv.shutdown()
        q.shutdown()

    def test_health_document(self, server):
        import urllib.request

        base, q = server
        with urllib.request.urlopen(base + "/health", timeout=30) as r:
            health = json.loads(r.read())
        assert health["schema"] == telemetry.HEALTH_SCHEMA
        assert health["ts"] > 0
        assert "cpu" in health["devices"]
        assert health["hbm"] and health["hbm"][0]["bytes_limit"] > 0
        assert 0.0 <= health["hbm_utilization_max"] <= 1.0
        assert set(health["queue"]) >= {"pending", "running", "workers",
                                        "completed", "serving"}
        assert health["queue"]["workers"] == q.workers
        assert "compiles" in health["compile"]

    def test_metrics_carries_hbm_gauges(self, server):
        import urllib.request

        base, _ = server
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            text = r.read().decode()
        assert re.search(r"^pa_hbm_bytes_limit\{", text, re.M)
        assert re.search(r"^pa_hbm_bytes_in_use\{", text, re.M)


class TestObservabilityLint:
    """Round 16: the static-analysis guard moved into scripts/palint.py
    (ONE lint engine — six passes, this file's old print/time.time checks
    among them as the `observability` pass). The central allowlists became
    per-line `# palint: allow[observability] <why>` pragmas next to the
    code, with the engine enforcing the staleness discipline the old
    `test_allowlist_entries_still_exist` carried (a pragma that suppresses
    nothing, or has no justification, is itself a finding). This test is
    the thin subprocess gate; tests/test_palint.py covers the passes."""

    def test_palint_check_green(self, tmp_path):
        env = dict(os.environ, PA_LEDGER_DIR=str(tmp_path))
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "palint.py"), "--check"],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert proc.returncode == 0, (
            "palint --check failed — fix the violation or justify it with "
            "an in-line pragma:\n" + proc.stdout + proc.stderr
        )
