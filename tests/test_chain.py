"""Tests for the DEVICE_CHAIN data type (reference: add_device 819-832,
create_list 872-882, weight normalization 1019-1027)."""

import pytest

from comfyui_parallelanything_tpu.parallel.chain import DeviceChain, DeviceLink


class TestChainBuilding:
    def test_add_is_pure(self):
        c0 = DeviceChain()
        c1 = c0.add("cpu", 60)
        c2 = c1.add("cpu:1", 40)
        assert len(c0) == 0 and len(c1) == 1 and len(c2) == 2
        assert c2.devices == ("cpu", "cpu:1")
        assert c2.percentages == (60.0, 40.0)

    def test_from_pairs_drops_nonpositive(self):
        # Parity: create_list drops entries with pct <= 0 (876-882).
        c = DeviceChain.from_pairs([("cpu:0", 50), ("cpu:1", 0), ("cpu:2", -10), ("cpu:3", 50)])
        assert c.devices == ("cpu:0", "cpu:3")

    def test_even(self):
        c = DeviceChain.even(["cpu:0", "cpu:1", "cpu:2", "cpu:3"])
        w = c.normalized_weights()
        assert w == (0.25, 0.25, 0.25, 0.25)

    def test_empty_device_rejected(self):
        with pytest.raises(ValueError):
            DeviceLink("", 50)


class TestChainSemantics:
    def test_normalized_weights_abort(self):
        c = DeviceChain.from_pairs([])
        assert c.normalized_weights() is None
        c2 = DeviceChain((DeviceLink("cpu", 0.0),))
        assert c2.normalized_weights() is None

    def test_homogeneity(self):
        assert DeviceChain.from_pairs([("cpu:0", 50), ("cpu:1", 50)]).is_homogeneous
        assert not DeviceChain.from_pairs([("tpu:0", 50), ("cpu", 50)]).is_homogeneous

    def test_deduplicated_sums_percentages(self):
        # The reference allows the same device twice (two replicas + threads); SPMD
        # folds repeats into one link with the combined share.
        c = DeviceChain.from_pairs([("cpu", 30), ("cpu", 30), ("cpu:1", 40)])
        d = c.deduplicated()
        assert d.devices == ("cpu", "cpu:1")
        assert d.percentages == (60.0, 40.0)

    def test_validated_drops_unknown(self):
        # Parity: invalid chain entries are skipped (1037-1042).
        c = DeviceChain.from_pairs([("cpu:0", 50), ("tpu:99", 25), ("nonsense:0", 25)])
        v = c.validated()
        assert v.devices == ("cpu:0",)


class TestDeviceResolution:
    def test_jax_devices_resolve(self, cpu_devices):
        c = DeviceChain.from_pairs([("cpu:0", 50), ("cpu:1", 50)])
        devs = c.jax_devices()
        assert [d.id for d in devs] == [0, 1]
        assert all(d.platform == "cpu" for d in devs)

    def test_out_of_range_raises(self):
        c = DeviceChain.from_pairs([("cpu:99", 100)])
        with pytest.raises(ValueError):
            c.jax_devices()
