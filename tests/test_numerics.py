"""Numerics sentinel (utils/numerics.py): non-finite quarantine, latent
fingerprints, drift auditing — all off-hardware.

The contracts under test:

- disabled is a no-op: a serving round with the sentinel off emits no stats,
  no digests, no ``pa_numerics_*`` metrics (the single-flag-check contract);
- fingerprint invariance: a lane's per-eval digest stack is bitwise-equal
  across occupancy (solo vs co-batched), bucket width, execution mode
  (compiled lane program vs width-1 eager StepPlan walk), and the 8-device
  mesh dp placement — for EVERY registered sampler × {eps, flow}, reusing
  the round-10 equivalence harness (tests/test_serving.py);
- NaN quarantine: ``PA_FAIL_INJECT=nan:<lane>`` poisons one lane of a
  4-lane mixed-sampler co-batched dispatch → exactly that lane retires with
  :class:`NonFiniteLatent` and a postmortem bundle naming the first
  non-finite block/step/σ, while survivors stay BITWISE identical to their
  uninjected co-batched runs (the select-mask retirement discipline);
- the per-block bisection names a poisoned PipelineSpec segment; the
  streaming runner names a poisoned stage;
- the drift gate (scripts/numerics_audit.py) passes on stable fingerprints,
  fails on drift or non-finite events, and SKIPs an empty ledger.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models.api import (
    DiffusionModel,
    PipelineSegment,
    PipelineSpec,
)
from comfyui_parallelanything_tpu.sampling.lane_specs import LANE_SPECS
from comfyui_parallelanything_tpu.sampling.runner import run_sampler
from comfyui_parallelanything_tpu.serving import ContinuousBatchingScheduler
from comfyui_parallelanything_tpu.utils import numerics
from comfyui_parallelanything_tpu.utils.metrics import registry

# The round-10 serving equivalence harness — reused on purpose (the ISSUE's
# fingerprint matrix rides the same tiny model, inputs, and manual-pump
# handshake the lane-vs-solo matrix pinned).
from test_serving import (
    LANE_MATRIX,
    LANE_MATRIX_FLOW,
    TOL,
    _wait_enqueued,
    mk_inputs,
    tiny_model,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class StreamingStyleModel:
    """Not single-program traceable → width-1 eager StepPlan walk."""

    is_streaming = True

    def __call__(self, x, t, context=None, **kw):
        return tiny_model(x, t, context)


@pytest.fixture
def sentinel_on():
    numerics.enable()
    numerics.sentinel.reset()
    try:
        yield numerics.sentinel
    finally:
        numerics.sentinel.reset()
        numerics.disable()


def _serve(plans, *, width=4, model=tiny_model, mkfn=mk_inputs):
    """Run each plan through run_sampler against a manual-pump scheduler;
    returns (results, errors) keyed by plan index."""
    s = ContinuousBatchingScheduler(max_width=width, auto=False).install()
    try:
        results, errors = {}, {}

        def worker(j, kw):
            kw = dict(kw)
            noise, ctx = mkfn(kw.pop("seed"))
            try:
                results[j] = run_sampler(model, noise, ctx, **kw)
            except BaseException as e:  # noqa: BLE001 — assertion target
                errors[j] = e

        threads = [
            threading.Thread(target=worker, args=(j, p), daemon=True)
            for j, p in enumerate(plans)
        ]
        for t in threads:
            t.start()
        _wait_enqueued(s, len(plans))
        s.drain()
        for t in threads:
            t.join(30)
        return results, errors
    finally:
        s.uninstall()
        s.shutdown()


def _digests(sampler: str, steps: int | None = None) -> list[list[int]]:
    """Fingerprint stacks recorded for ``sampler`` (optionally filtered by
    σ-interval count — the ragged co-batch partner also records one)."""
    return [r["digests"] for r in numerics.sentinel.recent_fingerprints()
            if r.get("sampler") == sampler
            and (steps is None or r.get("steps") == steps)]


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


class TestHelpers:
    def test_array_stats_counts_nonfinite_and_masks_magnitudes(self):
        x = jnp.asarray([[1.0, -2.0], [3.0, 4.0]])
        st = numerics.stats_to_dict(np.asarray(numerics.array_stats(x)))
        assert st["nonfinite"] == 0
        assert st["max_abs"] == pytest.approx(4.0)
        assert st["mean"] == pytest.approx(1.5)
        bad = x.at[0, 0].set(jnp.nan).at[1, 1].set(jnp.inf)
        st2 = numerics.stats_to_dict(np.asarray(numerics.array_stats(bad)))
        assert st2["nonfinite"] == 2
        assert np.isfinite(st2["max_abs"])  # poisoned entries masked out

    def test_lane_stats_counts_extra_state(self):
        x = jnp.zeros((3, 4))
        xe = jnp.zeros((3, 4)).at[1, 2].set(jnp.nan)
        st = np.asarray(numerics.lane_stats(x, extra=xe))
        assert st.shape == (3, 4)
        assert list(st[:, 0]) == [0.0, 1.0, 0.0]

    def test_digest_value_sensitive_and_lane_local(self):
        r = np.random.default_rng(0)
        x = jnp.asarray(r.normal(size=(2, 8, 8, 4)).astype(np.float32))
        d0 = int(np.asarray(numerics.digest(x[0])))
        d1 = int(np.asarray(numerics.digest(x[1])))
        assert d0 != d1
        ld = np.asarray(numerics.lane_digest(x))
        # lane-local positions: stacked digest == each slice's own digest
        assert [int(ld[0]), int(ld[1])] == [d0, d1]
        # bf16-quantized: a change below bf16 resolution is invisible, a
        # bf16-visible change flips the digest
        assert int(np.asarray(numerics.digest(x[0] * (1.0 + 1e-6)))) == d0
        assert int(np.asarray(numerics.digest(x[0] * 1.5))) != d0

    def test_fingerprint_format(self):
        fp = numerics.latent_fingerprint(jnp.ones((2, 3)))
        assert fp.startswith("bf16:2x3:") and len(fp.split(":")[-1]) == 8

    def test_bisect_names_poisoned_pipeline_segment(self):
        def prepare(params, x, t, context=None, **kw):
            return {"h": x * params["p"]}

        def seg(key):
            def fn(params, carry):
                return {"h": carry["h"] * params[key]}

            return fn

        params = {
            "p": jnp.ones((4,)),
            "s0": jnp.ones((4,)),
            "s1": jnp.full((4,), jnp.inf),  # the poisoned block
            "s2": jnp.ones((4,)),
        }
        spec = PipelineSpec(
            prepare_keys=("p",), prepare=prepare,
            segments=(
                PipelineSegment(("s0",), seg("s0"), "blk0"),
                PipelineSegment(("s1",), seg("s1"), "blk1"),
                PipelineSegment(("s2",), seg("s2"), "blk2"),
            ),
            finalize_keys=(), finalize=lambda p, c, shape: c["h"],
        )
        model = DiffusionModel(
            apply=lambda p, x, t, c=None, **kw: x, params=params,
            pipeline_spec=spec,
        )
        log_sig = jnp.log(jnp.linspace(10.0, 0.01, 50))[::-1]
        out = numerics.bisect_nonfinite(
            model, jnp.ones((1, 4)), 5.0, "eps", log_sig, None
        )
        assert out["block"] == "blk1" and out["segment_index"] == 1
        # poisoned INPUT short-circuits before any stage runs
        out2 = numerics.bisect_nonfinite(
            model, jnp.full((1, 4), jnp.nan), 5.0, "eps", log_sig, None
        )
        assert out2["block"] == "lane-input"


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------


class TestDisabledNoOp:
    def test_default_off(self):
        assert numerics.on() is False

    def test_serving_round_emits_nothing_when_off(self):
        numerics.sentinel.reset()
        before = registry.get("pa_numerics_nonfinite_total",
                              {"where": "serving-lane"})
        res, err = _serve([dict(sampler="dpmpp_2m", steps=3, seed=301)])
        assert not err and res[0].shape == (1, 8, 8, 4)
        assert numerics.sentinel.event_count == 0
        assert numerics.sentinel.recent_fingerprints() == []
        after = registry.get("pa_numerics_nonfinite_total",
                             {"where": "serving-lane"})
        assert before == after  # no metric touched

    def test_injection_unarmed_without_evidence_redirect(self, monkeypatch):
        monkeypatch.setenv("PA_FAIL_INJECT", "nan:0")
        monkeypatch.delenv("PA_LEDGER_DIR", raising=False)
        monkeypatch.delenv("PA_EVIDENCE_DIR", raising=False)
        assert numerics.fail_inject_lane() is None


# ---------------------------------------------------------------------------
# fingerprint invariance matrix (the (request, step) digest stack must be
# identical across every execution configuration)
# ---------------------------------------------------------------------------


def _matrix_kw(sampler: str, prediction: str):
    kw = dict(sampler=sampler, steps=4,
              seed=700 + LANE_MATRIX.index(sampler))
    if prediction == "flow":
        kw.update(prediction="flow", shift=1.15, seed=kw["seed"] + 50)
    if LANE_SPECS[sampler].needs_rng:
        kw["rng"] = jax.random.key(9)
    return kw


class TestFingerprintInvariance:
    @pytest.mark.parametrize("sampler", LANE_MATRIX)
    def test_eps_digest_stack_invariant(self, sentinel_on, sampler):
        """Solo vs co-batched (ragged euler partner): same per-eval digest
        stack AND bitwise-equal outputs (the PR 5 occupancy contract — the
        fingerprint's invariance domain is occupancy/width/sharding, where
        the program is literally the same computation with masked lanes).
        The width-1 eager StepPlan walk is a DIFFERENT XLA program, so it is
        held to the PR 5 equivalence contract instead (bf16-scale TOL): its
        digests still land in the sentinel ring (asserted non-empty) but
        exact digest equality across programs is not a promise the bf16
        quantization can keep for every element near a rounding boundary."""
        kw = _matrix_kw(sampler, "eps")
        solo_res, _ = _serve([kw])
        solo = _digests(sampler, steps=4)[-1]
        co_res, _ = _serve([kw, dict(sampler="euler", steps=6, seed=99)])
        co = _digests(sampler, steps=4)[-1]
        assert co == solo, f"{sampler}: digest stack changed with occupancy"
        np.testing.assert_array_equal(np.asarray(solo_res[0]),
                                      np.asarray(co_res[0]))
        n_before = len(_digests(sampler, steps=4))
        eager_res, _ = _serve([kw], model=StreamingStyleModel())
        assert len(_digests(sampler, steps=4)) == n_before + 1
        np.testing.assert_allclose(np.asarray(eager_res[0]),
                                   np.asarray(solo_res[0]), **TOL)

    @pytest.mark.parametrize("sampler", LANE_MATRIX_FLOW)
    def test_flow_digest_stack_invariant(self, sentinel_on, sampler):
        kw = _matrix_kw(sampler, "flow")
        _serve([kw])
        solo = _digests(sampler, steps=4)[-1]
        _serve([kw, dict(sampler="euler", steps=5, prediction="flow",
                         shift=1.15, seed=98)])
        assert _digests(sampler, steps=4)[-1] == solo

    def test_width_invariance(self, sentinel_on):
        kw = _matrix_kw("dpmpp_2m_sde", "eps")
        _serve([kw], width=4)
        d4 = _digests("dpmpp_2m_sde")[-1]
        _serve([kw], width=8)
        assert _digests("dpmpp_2m_sde")[-1] == d4

    def test_mesh_dp_invariance(self, sentinel_on, cpu_devices):
        """8-device mesh dp: solo vs co-batched digest stacks equal — the
        order-independent modular digest cannot see the sharding."""
        rng = np.random.default_rng(0)
        params = {
            "w": jnp.asarray(rng.normal(size=(4, 4)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
        }

        def toy_apply(p, x, t, context=None, **kw):
            h = jnp.tanh(x @ p["w"] * 0.1 + p["b"]) * 0.8
            h = h * jnp.cos(t * 1e-3)[:, None]
            return h + 0.01 * context.sum(axis=-1, keepdims=True)

        pm = parallelize(
            (toy_apply, params),
            DeviceChain.even([f"cpu:{i}" for i in range(8)]),
        )

        def mk(seed):
            r = np.random.default_rng(seed)
            return (jnp.asarray(r.normal(size=(2, 4)), jnp.float32),
                    jnp.asarray(r.normal(size=(2, 6)), jnp.float32))

        kw = dict(sampler="heun", steps=3, seed=41)
        _serve([kw], width=8, model=pm, mkfn=mk)
        solo = _digests("heun")[-1]
        _serve([kw, dict(sampler="euler", steps=5, seed=42)],
               width=8, model=pm, mkfn=mk)
        assert _digests("heun")[-1] == solo

    def test_compiled_loop_emits_fingerprint(self, sentinel_on):
        noise, ctx = mk_inputs(801)
        run_sampler(tiny_model, noise, ctx, sampler="euler", steps=3,
                    compile_loop=True)
        recs = [r for r in numerics.sentinel.recent_fingerprints()
                if r.get("where") == "loop:k:euler"]
        assert recs and len(recs[-1]["digests"]) == 1
        assert numerics.sentinel.event_count == 0

    def test_compiled_loop_records_nonfinite_event(self, sentinel_on):
        def nan_model(x, t, context=None, **kw):
            return x * jnp.inf

        noise, ctx = mk_inputs(802)
        run_sampler(nan_model, noise, ctx, sampler="euler", steps=2,
                    compile_loop=True)
        assert numerics.sentinel.event_count >= 1
        assert numerics.sentinel.last_event["where"] == "compiled-loop"


# ---------------------------------------------------------------------------
# NaN-injection quarantine
# ---------------------------------------------------------------------------


MIXED_PLANS = (
    dict(sampler="euler", steps=4, seed=711),
    dict(sampler="heun", steps=3, seed=712),
    dict(sampler="dpmpp_2m", steps=6, seed=713),
    dict(sampler="euler_ancestral", steps=5, seed=714),
)


def _mixed_plans():
    plans = [dict(p) for p in MIXED_PLANS]
    plans[3]["rng"] = jax.random.key(2)
    return plans


class TestQuarantine:
    def test_nan_injection_quarantines_one_lane_survivors_bitwise(
            self, sentinel_on, monkeypatch, tmp_path):
        """Acceptance: NaN injected into one lane of a 4-lane mixed-sampler
        co-batched dispatch → that lane quarantined (NonFiniteLatent to its
        submitter, postmortem bundle naming the first non-finite
        block/step/σ), surviving lanes bitwise-unchanged vs their uninjected
        co-batched runs."""
        clean, err0 = _serve(_mixed_plans())
        assert not err0 and len(clean) == 4
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAIL_INJECT", "nan:2")
        numerics.sentinel.reset()  # re-arm the one-shot injection
        res, errs = _serve(_mixed_plans())
        assert len(errs) == 1 and len(res) == 3, (errs, res)
        [bad] = errs.values()
        assert isinstance(bad, numerics.NonFiniteLatent)
        assert "quarantined" in str(bad) and "σ_eval" in str(bad)
        for j, out in res.items():
            np.testing.assert_array_equal(np.asarray(out),
                                          np.asarray(clean[j]))
        q = numerics.sentinel.last_quarantine
        assert q is not None and q["lane"] == 2
        first = q["first_nonfinite"]
        assert first["block"] == "lane-input"  # the injected NaN itself
        assert first["step"] == 0 and first["sigma"] > 0
        assert q["bundle"] and os.path.isdir(q["bundle"])
        with open(os.path.join(q["bundle"], "error.json")) as f:
            bundle = json.load(f)
        extra = bundle["extra"]
        assert extra["first_nonfinite"]["block"] == "lane-input"
        assert extra["first_nonfinite"]["step"] == 0
        # Seating order races, so lane 2 holds SOME plan's sampler — the
        # bundle must name it, whichever it was.
        assert extra["sampler"] in {p["sampler"] for p in MIXED_PLANS}
        assert bundle["error_type"] == "NonFiniteLatent"
        assert numerics.sentinel.quarantined_count == 1
        assert registry.get("pa_numerics_quarantined_total",
                            {"bucket": q["bucket"]}) >= 1

    def test_injection_quarantines_width1_eager_lane(
            self, sentinel_on, monkeypatch, tmp_path):
        """The width-1 eager mode (streaming/hybrid models) runs the same
        quarantine discipline."""
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAIL_INJECT", "nan:0")
        res, errs = _serve([dict(sampler="dpmpp_2m", steps=4, seed=721)],
                           model=StreamingStyleModel())
        assert not res and len(errs) == 1
        assert isinstance(errs[0], numerics.NonFiniteLatent)
        q = numerics.sentinel.last_quarantine
        assert q["first_nonfinite"]["block"] == "lane-input"
        assert q["bundle"] and os.path.isdir(q["bundle"])

    def test_freed_slot_reseats_after_quarantine(
            self, sentinel_on, monkeypatch, tmp_path):
        """A quarantined lane's slot is reusable: a later request seats in it
        and completes (state-pytree re-init on seat)."""
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAIL_INJECT", "nan:0")
        s = ContinuousBatchingScheduler(max_width=1, auto=False).install()
        try:
            results, errors = {}, {}

            def worker(j, seed, steps):
                noise, ctx = mk_inputs(seed)
                try:
                    results[j] = run_sampler(tiny_model, noise, ctx,
                                             sampler="euler", steps=steps)
                except BaseException as e:  # noqa: BLE001
                    errors[j] = e

            ta = threading.Thread(target=worker, args=(0, 731, 4), daemon=True)
            ta.start()
            _wait_enqueued(s, 1)
            s.pump()  # injection fires → lane 0 quarantined
            ta.join(20)  # the submitter re-raises NonFiniteLatent and exits
            assert isinstance(errors.get(0), numerics.NonFiniteLatent)
            tb = threading.Thread(target=worker, args=(1, 732, 3), daemon=True)
            tb.start()
            _wait_enqueued(s, 1)
            s.drain()
            ta.join(20)
            tb.join(20)
            assert 1 in results and results[1].shape == (1, 8, 8, 4)
        finally:
            s.uninstall()
            s.shutdown()


# ---------------------------------------------------------------------------
# streaming per-stage stats
# ---------------------------------------------------------------------------


class TestStreamingStats:
    def _toy_spec_and_params(self, poison: bool):
        def prepare(params, x, t, context=None, **kw):
            return {"h": x * params["p"]}

        def seg(key):
            def fn(params, carry):
                return {"h": carry["h"] * params[key]}

            return fn

        params = {
            "p": jnp.ones((4,)),
            "s0": jnp.ones((4,)),
            "s1": jnp.full((4,), jnp.inf) if poison else jnp.ones((4,)),
        }
        spec = PipelineSpec(
            prepare_keys=("p",), prepare=prepare,
            segments=(
                PipelineSegment(("s0",), seg("s0"), "blk0"),
                PipelineSegment(("s1",), seg("s1"), "blk1"),
            ),
            finalize_keys=(), finalize=lambda p, c, shape: c["h"],
        )
        return spec, params

    def test_poisoned_stage_is_named(self, sentinel_on):
        from comfyui_parallelanything_tpu.parallel.streaming import (
            StreamingRunner,
        )

        spec, params = self._toy_spec_and_params(poison=True)
        runner = StreamingRunner(spec, params, jax.devices("cpu")[0],
                                 n_stages=2)
        out = runner(jnp.ones((1, 4)), jnp.ones((1,)))
        assert not np.isfinite(np.asarray(out)).all()
        assert numerics.sentinel.event_count >= 1
        ev = numerics.sentinel.last_event
        assert ev["where"] in ("stream-stage", "stream-output")
        assert "blk1" in ev["blocks"]

    def test_healthy_stream_records_nothing(self, sentinel_on):
        from comfyui_parallelanything_tpu.parallel.streaming import (
            StreamingRunner,
        )

        spec, params = self._toy_spec_and_params(poison=False)
        runner = StreamingRunner(spec, params, jax.devices("cpu")[0],
                                 n_stages=2)
        runner(jnp.ones((1, 4)), jnp.ones((1,)))
        assert numerics.sentinel.event_count == 0


# ---------------------------------------------------------------------------
# drift gate (scripts/numerics_audit.py) + health/trace surfaces
# ---------------------------------------------------------------------------


def _audit():
    sys.path.insert(0, os.path.join(_REPO, "scripts"))
    import numerics_audit

    return numerics_audit


def _bench_rec(fp: str, nfe=0, ts=1, **kw):
    return {"schema": "pa-perf-ledger/v1", "kind": "bench", "rung": "smoke",
            "platform": "cpu", "value": 5.0, "latent_fingerprint": fp,
            "nonfinite_events": nfe, "ts": ts, **kw}


class TestAuditGate:
    def test_ok_drift_and_skip(self, tmp_path, capsys):
        audit = _audit()
        stable = [_bench_rec("bf16:1:aaaaaaaa", ts=1),
                  _bench_rec("bf16:1:aaaaaaaa", ts=2)]
        assert audit.check(stable, {}, ledger_dir=str(tmp_path)) == 0
        gate = json.loads((tmp_path / "numerics_gate.json").read_text())
        assert gate["status"] == "ok"
        drifted = stable + [_bench_rec("bf16:1:bbbbbbbb", ts=3)]
        assert audit.check(drifted, {}, ledger_dir=str(tmp_path)) == 1
        gate = json.loads((tmp_path / "numerics_gate.json").read_text())
        assert gate["status"] == "drift"
        assert audit.check([], {}, ledger_dir=str(tmp_path)) == 0
        gate = json.loads((tmp_path / "numerics_gate.json").read_text())
        assert gate["status"] == "skip"
        capsys.readouterr()

    def test_golden_beats_prior_and_nonfinite_fails(self, tmp_path, capsys):
        audit = _audit()
        golden = {"smoke/cpu": {"fingerprint": "bf16:1:aaaaaaaa"}}
        # prior drifted but golden matches the latest → OK (the golden is
        # the contract, not the noisy history)
        recs = [_bench_rec("bf16:1:cccccccc", ts=1),
                _bench_rec("bf16:1:aaaaaaaa", ts=2)]
        assert audit.check(recs, golden, ledger_dir=str(tmp_path)) == 0
        # a poisoned latest fails even with a matching fingerprint
        recs.append(_bench_rec("bf16:1:aaaaaaaa", nfe=3, ts=3))
        assert audit.check(recs, golden, ledger_dir=str(tmp_path)) == 1
        capsys.readouterr()

    def test_stale_and_dryrun_never_compared(self, tmp_path, capsys):
        audit = _audit()
        recs = [_bench_rec("bf16:1:aaaaaaaa", ts=1),
                _bench_rec("bf16:1:dddddddd", ts=2, stale=True),
                _bench_rec("bf16:1:eeeeeeee", ts=3, dryrun=True)]
        assert audit.check(recs, {}, ledger_dir=str(tmp_path)) == 0
        capsys.readouterr()

    def test_bank_then_check_roundtrip(self, tmp_path, capsys):
        audit = _audit()
        ledger = tmp_path / "perf_ledger.jsonl"
        with open(ledger, "w") as f:
            f.write(json.dumps(_bench_rec("bf16:1:abcd1234")) + "\n")
        golden_path = str(tmp_path / "numerics_golden.json")
        recs = audit._load_jsonl(str(ledger))
        assert audit.bank(recs, golden_path) == 0
        golden = audit._load_golden(golden_path)
        assert golden["smoke/cpu"]["fingerprint"] == "bf16:1:abcd1234"
        assert audit.check(recs, golden, ledger_dir=str(tmp_path)) == 0
        capsys.readouterr()

    def test_cli_check_over_wedged_tunnel_env(self, tmp_path):
        """The gate is jax-free: runs (and passes) in a child whose env
        points at a temp ledger, never importing jax."""
        with open(tmp_path / "perf_ledger.jsonl", "w") as f:
            f.write(json.dumps(_bench_rec("bf16:1:12341234")) + "\n")
            f.write(json.dumps(_bench_rec("bf16:1:12341234", ts=2)) + "\n")
        env = dict(os.environ, PA_LEDGER_DIR=str(tmp_path))
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts",
                                          "numerics_audit.py"), "--check"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK" in proc.stdout


class TestSurfaces:
    def test_health_snapshot_numerics_section(self, sentinel_on, monkeypatch,
                                              tmp_path):
        from comfyui_parallelanything_tpu.utils.telemetry import (
            health_snapshot,
        )

        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        numerics.sentinel.record_event("unit-test", detail="x")
        snap = health_snapshot()
        n = snap["numerics"]
        assert n["enabled"] is True
        assert n["nonfinite_events"] == 1
        assert n["quarantined_lanes"] == 0
        assert n["last_event"]["where"] == "unit-test"
        assert n["fingerprint_gate"] is None  # gate never ran in this dir
        (tmp_path / "numerics_gate.json").write_text(
            json.dumps({"status": "ok", "ts": 1.0, "groups": {}})
        )
        assert health_snapshot()["numerics"]["fingerprint_gate"]["status"] \
            == "ok"

    def test_publish_gauges(self, sentinel_on):
        numerics.sentinel.publish_gauges()
        assert registry.get("pa_numerics_sentinel_enabled") == 1.0
        assert registry.get("pa_numerics_nonfinite_events") == 0.0

    def test_trace_summary_counts_numerics_spans(self, sentinel_on):
        from comfyui_parallelanything_tpu.utils import tracing

        sys.path.insert(0, os.path.join(_REPO, "scripts"))
        import trace_summary

        tracing.enable()
        try:
            numerics.sentinel.record_event("stream-stage", stage=1)
            numerics.sentinel.record_event("serving-lane", lane=0)
            numerics.sentinel.record_quarantine(bucket="b", lane=0, step=2)
            events = [e for e in tracing.export()["traceEvents"]
                      if e.get("ph") == "X"]
        finally:
            tracing.disable()
        s = trace_summary.summarize(events)
        assert s["numerics"]["nonfinite_events"] == 2
        assert s["numerics"]["quarantines"] == 1
        assert s["numerics"]["nonfinite_by_where"] == {
            "serving-lane": 1, "stream-stage": 1,
        }
