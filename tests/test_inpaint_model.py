"""Dedicated inpainting checkpoints (9-channel UNets): the input-concat
composition, family sniffing, and the InpaintModelConditioning node driving a
sampler run end to end."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models import (
    apply_inpaint_conditioning,
    build_unet,
    build_vae,
    sd15_config,
)


def _tiny9():
    cfg = sd15_config(
        in_channels=9, model_channels=32, channel_mult=(1, 2),
        transformer_depth=(1, 1), attention_levels=(0, 1), context_dim=64,
        num_heads=4, norm_groups=8, dtype=jnp.float32,
    )
    return cfg, build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 9))


class TestInpaintComposition:
    def test_wrap_concats_channels_exactly(self):
        cfg, model = _tiny9()
        mask = jnp.zeros((1, 8, 8, 1)).at[:, 2:6, 2:6, :].set(1.0)
        masked = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
        wrapped = apply_inpaint_conditioning(model, mask, masked)
        x = jax.random.normal(jax.random.key(2), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(3), (2, 5, 64))
        got = wrapped(x, t, ctx)
        manual = jnp.concatenate([
            x,
            jnp.repeat(mask, 2, axis=0),
            jnp.repeat(masked, 2, axis=0),
        ], axis=-1)
        want = model(manual, t, ctx)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
        assert got.shape == (2, 8, 8, 4)  # out_channels unaffected

    def test_per_sample_conditioning_rejected(self):
        cfg, model = _tiny9()
        wrapped = apply_inpaint_conditioning(
            model, jnp.zeros((3, 8, 8, 1)), jnp.zeros((3, 8, 8, 4))
        )
        with pytest.raises(ValueError, match="ONE mask"):
            wrapped.apply(wrapped.params, jnp.zeros((2, 8, 8, 4)),
                          jnp.zeros((2,)), jnp.zeros((2, 5, 64)))


class TestSniffing:
    def test_nine_channel_checkpoints_sniff_inpaint(self):
        from comfyui_parallelanything_tpu.models.loader import (
            sniff_model_family,
        )

        def fake(in_ch, ctx, label=False):
            sd = {
                "input_blocks.0.0.weight": np.zeros((32, in_ch, 3, 3)),
                "input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight":
                    np.zeros((32, ctx)),
            }
            if label:
                sd["label_emb.0.0.weight"] = np.zeros((32, 16))
            return sd

        assert sniff_model_family(fake(4, 768)) == "sd15"
        assert sniff_model_family(fake(9, 768)) == "sd15-inpaint"
        assert sniff_model_family(fake(9, 1024)) == "sd21-inpaint"
        assert sniff_model_family(fake(9, 2048, label=True)) == "sdxl-inpaint"
        assert sniff_model_family(fake(4, 2048, label=True)) == "sdxl"
        # A 9-channel dict of an unknown family must fail loudly, not load a
        # 4-channel config into an opaque conversion shape error.
        with pytest.raises(ValueError, match="inpaint"):
            sniff_model_family(fake(9, 4096))


class TestInpaintSampling:
    def test_conditioning_node_drives_a_sampler_run(self):
        from comfyui_parallelanything_tpu.nodes import (
            TPUInpaintModelConditioning,
            TPUKSampler,
        )
        from tests.test_vae import TINY as TINY_VAE

        cfg, model = _tiny9()
        vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
        f = vae.spatial_factor
        hw = 8 * f  # pixel size whose latent grid is 8x8
        pixels = jax.random.uniform(jax.random.key(2), (1, hw, hw, 3))
        mask = jnp.zeros((hw, hw)).at[: hw // 2, :].set(1.0)

        pos, neg, latent = TPUInpaintModelConditioning().encode(
            {"context": jnp.zeros((1, 5, 64))},
            {"context": jnp.zeros((1, 5, 64))},
            vae, pixels, mask,
        )
        assert pos["inpaint"]["mask"].shape == (1, 8, 8, 1)
        assert pos["inpaint"]["masked_latent"].shape == latent["samples"].shape
        assert "noise_mask" in latent
        # The mask landed at latent resolution with the right polarity.
        assert float(pos["inpaint"]["mask"][0, 0, 0, 0]) == 1.0
        assert float(pos["inpaint"]["mask"][0, -1, 0, 0]) == 0.0
        # Masked pixels neutralize to 0.5-gray = 0.0 in the VAE's [-1, 1]
        # input space (the checkpoints' training convention).
        from comfyui_parallelanything_tpu.models.vae import (
            images_to_vae_input,
        )

        px = images_to_vae_input(pixels)
        m4 = jnp.asarray(mask)[None, ..., None]
        want_ml = vae.encode(px * (1.0 - m4), None)
        np.testing.assert_allclose(
            np.asarray(pos["inpaint"]["masked_latent"]),
            np.asarray(want_ml), rtol=1e-5, atol=1e-5,
        )

        (out,) = TPUKSampler().sample(
            model=model, positive=pos, negative=None, latent=latent,
            seed=3, steps=2, cfg=1.0, sampler_name="euler",
        )
        assert out["samples"].shape == latent["samples"].shape
        assert np.isfinite(np.asarray(out["samples"])).all()

    def test_stock_shim_registered(self):
        from comfyui_parallelanything_tpu.nodes_compat import (
            stock_node_mappings,
        )

        assert "InpaintModelConditioning" in stock_node_mappings()


class TestSoftInpaintNodes:
    def test_vae_encode_for_inpaint(self):
        from comfyui_parallelanything_tpu.nodes_compat import (
            VAEEncodeForInpaint,
        )
        from tests.test_vae import TINY as TINY_VAE

        vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
        f = vae.spatial_factor
        hw = 8 * f
        pixels = jax.random.uniform(jax.random.key(2), (1, hw, hw, 3))
        mask = jnp.zeros((hw, hw)).at[:2, :2].set(1.0)

        (lat,) = VAEEncodeForInpaint().encode(vae, pixels, mask,
                                              grow_mask_by=2)
        assert lat["samples"].shape[1:3] == (8, 8)
        nm = np.asarray(lat["noise_mask"])
        assert nm.shape == (1, 8, 8, 1)
        # grow_mask_by dilated the 2px corner beyond its original extent.
        assert nm.sum() > 0 and float(nm[0, 0, 0, 0]) == 1.0
        assert float(nm[0, -1, -1, 0]) == 0.0
        # No growth: strictly smaller or equal mask.
        (lat0,) = VAEEncodeForInpaint().encode(vae, pixels, mask,
                                               grow_mask_by=0)
        assert np.asarray(lat0["noise_mask"]).sum() <= nm.sum()

    def test_image_pad_for_outpaint(self):
        from comfyui_parallelanything_tpu.nodes_compat import (
            ImagePadForOutpaint,
        )

        img = jax.random.uniform(jax.random.key(3), (1, 16, 12, 3))
        padded, mask = ImagePadForOutpaint().expand_image(
            img, left=8, top=0, right=0, bottom=4, feathering=4
        )
        assert padded.shape == (1, 20, 20, 3)
        assert mask.shape == (1, 20, 20)
        m = np.asarray(mask)
        assert m[0, :, :8].min() == 1.0      # new left border fully masked
        assert m[0, -4:, :].min() == 1.0     # new bottom border fully masked
        assert m[0, 0, -1] == 0.0            # untouched corner (no top/right pad)
        # Feather ramps inside the original region next to the padded edge.
        assert 0.0 < m[0, 8, 10] < 1.0
        # Edge-replication: padded left column equals the original's first col.
        np.testing.assert_allclose(
            np.asarray(padded[0, 0, :8, :]),
            np.broadcast_to(np.asarray(img[0, 0, 0, :]), (8, 3)),
        )


class TestCompositeAndVideoSave:
    def test_image_composite_masked(self):
        from comfyui_parallelanything_tpu.nodes_compat import (
            ImageCompositeMasked,
        )

        dst = jnp.zeros((1, 8, 8, 3))
        src = jnp.ones((1, 4, 4, 3))
        (out,) = ImageCompositeMasked().composite(dst, src, x=2, y=2)
        o = np.asarray(out)
        assert o[0, 2, 2, 0] == 1.0 and o[0, 5, 5, 0] == 1.0
        assert o[0, 0, 0, 0] == 0.0 and o[0, 6, 6, 0] == 0.0
        # Half mask: blended region takes source only where mask=1.
        mask = jnp.zeros((4, 4)).at[:2, :].set(1.0)
        (out2,) = ImageCompositeMasked().composite(dst, src, 2, 2, mask=mask)
        o2 = np.asarray(out2)
        assert o2[0, 2, 2, 0] == 1.0 and o2[0, 5, 2, 0] == 0.0
        # Paste window clips at the destination edge instead of erroring,
        # and a masked edge-paste CROPS the mask (not squish-resizes it).
        (out3,) = ImageCompositeMasked().composite(dst, src, x=6, y=6)
        assert np.asarray(out3)[0, 7, 7, 0] == 1.0
        row_mask = jnp.zeros((4, 4)).at[:1, :].set(1.0)  # only source row 0
        (out3m,) = ImageCompositeMasked().composite(
            dst, src, 6, 6, mask=row_mask
        )
        o3 = np.asarray(out3m)
        # Cropping keeps source rows 0-1: row 0 masked on, row 1 off. A
        # squish-resize would blend the 1s into both rows instead.
        assert o3[0, 6, 6, 0] == 1.0 and o3[0, 7, 7, 0] == 0.0
        # Non-divisor batches cycle like stock repeat_to_batch_size.
        (out4,) = ImageCompositeMasked().composite(
            jnp.zeros((3, 8, 8, 3)), jnp.ones((2, 4, 4, 3)), 0, 0
        )
        assert np.asarray(out4).shape[0] == 3
        # A batched mask matching neither 1 nor the destination batch cycles
        # too (stock repeat_to_batch_size), instead of an XLA broadcast error.
        mask2 = jnp.stack([jnp.ones((4, 4)), jnp.zeros((4, 4))])
        (out5,) = ImageCompositeMasked().composite(
            jnp.zeros((3, 8, 8, 3)), jnp.ones((3, 4, 4, 3)), 0, 0, mask=mask2
        )
        o5 = np.asarray(out5)
        # Cycled mask: batch 0 on, batch 1 off, batch 2 on (cycle restart).
        assert o5[0, 0, 0, 0] == 1.0 and o5[1, 0, 0, 0] == 0.0
        assert o5[2, 0, 0, 0] == 1.0

    def test_latent_composite(self):
        from comfyui_parallelanything_tpu.nodes_compat import LatentComposite

        to = {"samples": jnp.zeros((1, 8, 8, 4))}
        frm = {"samples": jnp.ones((1, 4, 4, 4))}
        (out,) = LatentComposite().composite(to, frm, x=16, y=16)  # /8 → 2,2
        o = np.asarray(out["samples"])
        assert o[0, 2, 2, 0] == 1.0 and o[0, 0, 0, 0] == 0.0

    def test_save_animated_webp(self, tmp_path, monkeypatch):
        from PIL import Image

        from comfyui_parallelanything_tpu.nodes_compat import SaveAnimatedWEBP

        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path))
        frames = np.random.default_rng(0).uniform(size=(4, 16, 16, 3))
        (paths,) = SaveAnimatedWEBP().save_images(
            frames, filename_prefix="clip", fps=8.0
        )
        assert len(paths) == 1 and paths[0].endswith(".webp")
        im = Image.open(paths[0])
        assert getattr(im, "n_frames", 1) == 4
        # Numbered continuation, no overwrite; subfolder prefixes honored.
        (paths2,) = SaveAnimatedWEBP().save_images(frames, "clip")
        assert paths2[0] != paths[0]
        (paths3,) = SaveAnimatedWEBP().save_images(frames, "run1/clip")
        assert os.sep + "run1" + os.sep in paths3[0]
