"""Weight-only int8 quantization: accuracy, byte budget, and transparency
through the whole parallel layer (DP sharding, FSDP leaf sharding, pipeline
staging) — the QuantTensor pytree must never need a special case downstream."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, ParallelConfig, parallelize
from comfyui_parallelanything_tpu.models import (
    QuantTensor,
    build_flux,
    dequantize_params,
    param_bytes,
    quantize_model,
    quantize_params,
)
from comfyui_parallelanything_tpu.models.flux import FluxConfig


TINY = FluxConfig(
    in_channels=16,
    hidden_size=64,
    num_heads=4,
    depth=1,
    depth_single_blocks=2,
    context_in_dim=32,
    vec_in_dim=16,
    axes_dim=(4, 6, 6),
    guidance_embed=False,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def flux_model():
    return build_flux(TINY, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=8)


class TestQuantizeParams:
    def test_round_trip_error_bounded(self):
        w = jax.random.normal(jax.random.key(1), (256, 512)) * jnp.linspace(
            0.1, 3.0, 512
        )  # per-channel dynamic range — what per-channel scales exist for
        q = quantize_params({"w": w}, min_size=1)["w"]
        assert isinstance(q, QuantTensor)
        assert q.q.dtype == jnp.int8
        back = np.asarray(q.dequantize(jnp.float32))
        err = np.abs(back - np.asarray(w))
        # symmetric int8: error ≤ scale/2 per channel = absmax/254
        bound = np.abs(np.asarray(w)).max(axis=0) / 254.0 + 1e-8
        assert (err <= bound[None, :] + 1e-6).all()

    def test_small_and_1d_leaves_untouched(self):
        params = {"bias": jnp.ones((64,)), "norm": jnp.ones((8, 8))}
        out = quantize_params(params, min_size=2**10)
        assert not any(
            isinstance(l, QuantTensor)
            for l in jax.tree.leaves(
                out, is_leaf=lambda x: isinstance(x, QuantTensor)
            )
            if isinstance(l, QuantTensor)
        )
        assert out["bias"] is params["bias"]

    def test_bytes_roughly_halve(self, flux_model):
        # f32 model → int8 payload + f32 scales: large-leaf bytes drop 4×, the
        # whole tree must shrink by well over 2× (norms/biases stay f32).
        before = param_bytes(flux_model.params)
        after = param_bytes(quantize_params(flux_model.params, min_size=2**10))
        assert after < before / 2

    def test_idempotent(self, flux_model):
        q1 = quantize_params(flux_model.params, min_size=2**10)
        q2 = quantize_params(q1, min_size=2**10)
        a = jax.tree.leaves(q1)
        b = jax.tree.leaves(q2)
        assert all(x is y for x, y in zip(a, b))


class TestQuantizedModel:
    def _inputs(self, batch):
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(batch, 8, 8, 4)), jnp.float32)
        t = jnp.linspace(1.0, 0.1, batch)
        ctx = jnp.asarray(rng.normal(size=(batch, 8, TINY.context_in_dim)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(batch, TINY.vec_in_dim)), jnp.float32)
        return x, t, ctx, y

    def test_forward_close_to_full_precision(self, flux_model):
        qm = quantize_model(flux_model, min_size=2**10, dtype=jnp.float32)
        x, t, ctx, y = self._inputs(2)
        full = np.asarray(flux_model.apply(flux_model.params, x, t, ctx, y=y))
        quant = np.asarray(qm.apply(qm.params, x, t, ctx, y=y))
        # int8 weights: relative output error stays in the few-percent regime.
        scale = np.abs(full).mean() + 1e-6
        assert np.abs(quant - full).mean() / scale < 0.05

    def test_int8_sampler_run_close_to_bf16(self, flux_model):
        # VERDICT r2 item 3: bound int8-vs-full-precision error END-TO-END
        # through a sampler run, not just one forward — quantization noise
        # compounds across steps, and this is the regime the flux_16_int8
        # bench rung runs in.
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        qm = quantize_model(flux_model, min_size=2**10, dtype=jnp.float32)
        noise = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        ctx = jax.random.normal(jax.random.key(4), (2, 8, TINY.context_in_dim))
        y = jax.random.normal(jax.random.key(5), (2, TINY.vec_in_dim))
        kw = dict(sampler="flow_euler", steps=6, shift=1.0, y=y)
        full = np.asarray(run_sampler(flux_model, noise, ctx, **kw))
        quant = np.asarray(run_sampler(qm, noise, ctx, **kw))
        assert np.isfinite(quant).all()
        scale = np.abs(full).mean() + 1e-6
        rel = np.abs(quant - full).mean() / scale
        assert rel < 0.10, rel  # compounded over 6 steps, still small

    def test_parallelized_dp(self, flux_model, cpu_devices):
        qm = quantize_model(flux_model, min_size=2**10, dtype=jnp.float32)
        pm = parallelize(qm, DeviceChain.even([f"cpu:{i}" for i in range(8)]))
        x, t, ctx, y = self._inputs(8)
        out = pm(x, t, ctx, y=y)
        assert out.shape == (8, 8, 8, 4)
        assert len(out.sharding.device_set) == 8
        single = np.asarray(qm.apply(qm.params, x, t, ctx, y=y))
        np.testing.assert_allclose(np.asarray(out), single, rtol=2e-3, atol=2e-3)

    def test_parallelized_fsdp(self, flux_model, cpu_devices):
        # The tiny flux model's leaves sit under the FSDP min-size (so they
        # replicate), but the quantized model must still run the fsdp path.
        qm = quantize_model(flux_model, min_size=2**10, dtype=jnp.float32)
        pm = parallelize(
            qm,
            DeviceChain.even([f"cpu:{i}" for i in range(8)]),
            ParallelConfig(weight_sharding="fsdp"),
        )
        x, t, ctx, y = self._inputs(8)
        out = pm(x, t, ctx, y=y)
        assert out.shape == (8, 8, 8, 4)

    def test_fsdp_shards_large_int8_payload(self, cpu_devices):
        # QuantTensor children (int8 payload + scales) shard like any leaves
        # once they clear the FSDP min-size.
        def f(p, x, t, context=None, **kw):
            w = p["w"]
            if hasattr(w, "dequantize"):
                w = w.dequantize(jnp.float32)
            return x @ w

        params = {"w": jax.random.normal(jax.random.key(2), (1024, 1024))}
        from comfyui_parallelanything_tpu.models import quantize_params

        qp = quantize_params(params, min_size=1)
        pm = parallelize(
            (f, qp),
            DeviceChain.even([f"cpu:{i}" for i in range(8)]),
            ParallelConfig(weight_sharding="fsdp"),
        )
        out = pm(jnp.ones((8, 1024)), jnp.zeros((8,)))
        assert out.shape == (8, 1024)
        sharded_int8 = [
            l for l in jax.tree.leaves(pm._groups[0].params)
            if l.dtype == jnp.int8 and len(l.addressable_shards) == 8
            and l.addressable_shards[0].data.size < l.size
        ]
        assert sharded_int8, "expected the int8 payload to be genuinely sharded"

    def test_pipeline_batch1(self, flux_model, cpu_devices):
        qm = quantize_model(flux_model, min_size=2**10, dtype=jnp.float32)
        pm = parallelize(qm, DeviceChain.even([f"cpu:{i}" for i in range(4)]))
        x, t, ctx, y = self._inputs(1)
        out = pm(x, t, ctx, y=y)
        assert out.shape == (1, 8, 8, 4)
        assert pm._pipeline_runner is not None and pm._pipeline_runner.n_stages >= 2
        single = np.asarray(qm.apply(qm.params, x, t, ctx, y=y))
        np.testing.assert_allclose(np.asarray(out), single, rtol=2e-3, atol=2e-3)

    def test_compile_loop_on_quantized_model(self, flux_model):
        # The whole-loop compiled sampler must trace straight through a
        # QuantTensor pytree (dequantize-in-jit) and match the eager loop —
        # the exact combination the flux_16_int8 bench rung runs.
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        qm = quantize_model(flux_model, min_size=2**10, dtype=jnp.float32)
        noise = jax.random.normal(jax.random.key(6), (2, 8, 8, 4))
        ctx = jax.random.normal(jax.random.key(7), (2, 8, TINY.context_in_dim))
        y = jax.random.normal(jax.random.key(8), (2, TINY.vec_in_dim))
        kw = dict(sampler="euler", steps=3, y=y)
        eager = run_sampler(qm, noise, ctx, **kw)
        compiled = run_sampler(qm, noise, ctx, compile_loop=True, **kw)
        np.testing.assert_allclose(
            np.asarray(eager), np.asarray(compiled), rtol=2e-4, atol=2e-5
        )

    def test_bench_synth_int8_rung_logic(self):
        # The flux_16_int8 bench rung synthesizes int8 params straight from
        # abstract shapes (no high-precision pytree ever exists); validate the
        # same code path at tiny scale: structure matches quantize_params'
        # rule, and the dequantize-in-jit forward runs.
        import bench
        from comfyui_parallelanything_tpu.models import flux_abstract_params
        from comfyui_parallelanything_tpu.models.flux import FluxModel

        sds = flux_abstract_params(TINY, sample_shape=(1, 8, 8, 4), txt_len=8)
        params = bench._synth_int8_params(sds, min_size=2**10)
        leaves = jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantTensor)
        )
        qts = [l for l in leaves if isinstance(l, QuantTensor)]
        assert qts and all(l.q.dtype == jnp.int8 for l in qts)
        ref = quantize_params(
            jax.tree.map(lambda s: jnp.zeros(s.shape, jnp.float32), sds),
            min_size=2**10,
        )
        assert jax.tree.structure(
            params, is_leaf=lambda x: isinstance(x, QuantTensor)
        ) == jax.tree.structure(ref, is_leaf=lambda x: isinstance(x, QuantTensor))

        module = FluxModel(TINY)
        out = jax.jit(
            lambda p, x, t, c, y: module.apply(
                {"params": dequantize_params(p, jnp.float32)}, x, t, c, y=y
            )
        )(
            params,
            jnp.ones((1, 8, 8, 4)), jnp.ones((1,)),
            jnp.ones((1, 8, TINY.context_in_dim)), jnp.ones((1, TINY.vec_in_dim)),
        )
        assert out.shape == (1, 8, 8, 4)
        assert np.isfinite(np.asarray(out)).all()

    def test_dequantize_params_inverse_shape(self, flux_model):
        q = quantize_params(flux_model.params, min_size=2**10)
        back = dequantize_params(q, jnp.float32)
        for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(flux_model.params)):
            assert a.shape == b.shape
