"""img2img / denoise-strength: truncated schedules in run_sampler, the
VAE-encode node, and the pipeline init_image path. The reference leaves img2img
to its host app's KSampler ``denoise`` widget + VAEEncode node; standalone this
is that capability (ComfyUI semantics: ``steps`` forwards always run; the
schedule for steps/denoise total steps is truncated to its tail)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.sampling.runner import run_sampler


def _toy_model():
    """A linear 'denoiser' whose eps prediction is a fixed fraction of x —
    enough to make schedules observable without a neural net."""

    def f(x, t, context=None, **kw):
        return 0.1 * x

    return f


class TestRunSamplerDenoise:
    @pytest.mark.parametrize("sampler", ["ddim", "euler", "dpmpp_2m", "flow_euler"])
    def test_full_denoise_unchanged_by_init(self, sampler):
        """denoise=1.0 ignores init entirely (identical to the txt2img path)."""
        noise = jax.random.normal(jax.random.key(0), (1, 8, 8, 4))
        a = run_sampler(_toy_model(), noise, None, sampler=sampler, steps=3)
        b = run_sampler(
            _toy_model(), noise, None, sampler=sampler, steps=3,
            init_latent=jnp.ones_like(noise), denoise=1.0,
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("sampler", ["ddim", "euler", "dpmpp_2m", "flow_euler"])
    def test_low_denoise_stays_near_init(self, sampler):
        """At small strength the output must stay closer to the init latent than
        a full-denoise run does — the whole point of img2img."""
        init = jnp.full((1, 8, 8, 4), 2.0)
        noise = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
        weak = run_sampler(
            _toy_model(), noise, None, sampler=sampler, steps=4,
            init_latent=init, denoise=0.2,
        )
        full = run_sampler(_toy_model(), noise, None, sampler=sampler, steps=4)
        d_weak = float(jnp.abs(weak - init).mean())
        d_full = float(jnp.abs(full - init).mean())
        assert d_weak < d_full, (sampler, d_weak, d_full)

    def test_beta_short_schedule_honors_denoise(self):
        # beta's duplicate-timestep dedup can realize fewer sigmas than the
        # steps/denoise request; the img2img truncation must scale to the
        # realized length. The old fixed sigmas[-(steps+1):] slice kept the
        # whole schedule whenever len(sigmas) <= steps, running every denoise
        # strength at an effective 1.0 (identical outputs below).
        T = 8  # tiny sigma table forces realized < steps+1 after dedup
        acp = jnp.cumprod(1.0 - jnp.linspace(1e-2, 0.3, T))
        init = jnp.full((1, 8, 8, 4), 2.0)
        noise = jax.random.normal(jax.random.key(2), (1, 8, 8, 4))
        out = {
            d: run_sampler(
                _toy_model(), noise, None, sampler="euler", scheduler="beta",
                steps=10, init_latent=init, denoise=d, alphas_cumprod=acp,
            )
            for d in (0.3, 0.95)
        }
        d_weak = float(jnp.abs(out[0.3] - init).mean())
        d_strong = float(jnp.abs(out[0.95] - init).mean())
        assert d_weak < d_strong, (d_weak, d_strong)

    def test_denoise_out_of_range_rejected(self):
        noise = jnp.zeros((1, 4, 4, 4))
        with pytest.raises(ValueError, match="denoise"):
            run_sampler(
                _toy_model(), noise, None, sampler="euler", steps=2,
                init_latent=noise, denoise=0.0,
            )


class TestVAEEncodeNode:
    def test_round_trips_through_decode(self):
        from comfyui_parallelanything_tpu.models import VAEConfig, build_vae
        from comfyui_parallelanything_tpu.nodes import TPUVAEDecode, TPUVAEEncode

        cfg = VAEConfig(
            z_channels=4, base_channels=16, channel_mult=(1, 2),
            num_res_blocks=1, norm_groups=8, dtype=jnp.float32,
        )
        vae = build_vae(cfg, jax.random.key(0), sample_hw=16)
        img = jax.random.uniform(jax.random.key(1), (1, 16, 16, 3))
        (latent,) = TPUVAEEncode().encode(vae, img)
        assert latent["samples"].shape == (1, 8, 8, 4)
        (back,) = TPUVAEDecode().decode(vae, latent)
        assert back.shape == img.shape

    def test_seeded_encode_samples_posterior(self):
        from comfyui_parallelanything_tpu.models import VAEConfig, build_vae
        from comfyui_parallelanything_tpu.nodes import TPUVAEEncode

        cfg = VAEConfig(
            z_channels=4, base_channels=16, channel_mult=(1, 2),
            num_res_blocks=1, norm_groups=8, dtype=jnp.float32,
        )
        vae = build_vae(cfg, jax.random.key(0), sample_hw=16)
        img = jax.random.uniform(jax.random.key(1), (1, 16, 16, 3))
        (mean_latent,) = TPUVAEEncode().encode(vae, img, seed=-1)
        (sampled,) = TPUVAEEncode().encode(vae, img, seed=3)
        assert not np.allclose(
            np.asarray(mean_latent["samples"]), np.asarray(sampled["samples"])
        )


@pytest.fixture(scope="module")
def sd_pipe():
    from comfyui_parallelanything_tpu.models import (
        CLIPTextConfig, VAEConfig, build_clip_text, build_unet, build_vae,
        sd15_config,
    )
    from comfyui_parallelanything_tpu.pipelines import StableDiffusionPipeline
    from test_tokenizer import _tiny_tokenizer

    tok = _tiny_tokenizer()
    ccfg = CLIPTextConfig(
        vocab_size=64, hidden_size=48, num_layers=2, num_heads=4, max_len=8,
        eos_id=tok.eos_id, dtype=jnp.float32,
    )
    ucfg = sd15_config(
        model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
        attention_levels=(0, 1), context_dim=48, num_heads=4, norm_groups=8,
        dtype=jnp.float32,
    )
    vcfg = VAEConfig(
        z_channels=4, base_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        norm_groups=8, dtype=jnp.float32,
    )
    return StableDiffusionPipeline(
        unet=build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4)),
        vae=build_vae(vcfg, jax.random.key(1), sample_hw=16),
        clip=build_clip_text(ccfg, jax.random.key(2)),
        tokenizer=tok,
    )


class TestPipelineImg2Img:
    def test_init_image_shifts_output_toward_input(self, sd_pipe):
        """init_image must pull the sampled LATENT toward the encoded init.

        Asserted pre-decode: the toy VAE's decoder saturates — ANY latent
        perturbation (weak or full) lands ~0.22 mean pixel distance from the
        0.5 init, so the old pixel-space margin (~0.01, wrong-signed) sat
        inside this CPU's bf16-matmul noise floor (CLAUDE.md; pinning
        jax_default_matmul_precision=highest does not move it). The latent
        margin is orders of magnitude wider and measures the same plumbing:
        encode init → noise to the truncated schedule → sample → decode."""
        import dataclasses as dc

        captured = {}

        class _ProbeVAE:
            def __init__(self, vae):
                self._vae = vae

            def __getattr__(self, name):
                return getattr(self._vae, name)

            def encode(self, x):
                z = self._vae.encode(x)
                captured["init"] = z
                return z

            def decode(self, z):
                captured["latent"] = z
                return self._vae.decode(z)

        pipe = dc.replace(sd_pipe, vae=_ProbeVAE(sd_pipe.vae))
        init = jnp.full((1, 16, 16, 3), 0.5)
        kw = dict(steps=2, cfg_scale=1.0, height=16, width=16, rng=jax.random.key(2))
        out_full = pipe("hello", **kw)
        lat_full = captured["latent"]
        out_weak = pipe("hello", init_image=init, denoise=0.3, **kw)
        lat_weak, lat_init = captured["latent"], captured["init"]
        assert np.asarray(out_weak).shape == (1, 16, 16, 3)
        assert np.isfinite(np.asarray(out_weak)).all()
        d_weak = float(jnp.abs(lat_weak - lat_init).mean())
        d_full = float(jnp.abs(lat_full - lat_init).mean())
        assert d_weak < d_full, (d_weak, d_full)

    def test_init_image_with_full_denoise_rejected(self, sd_pipe):
        pipe = sd_pipe
        with pytest.raises(ValueError, match="denoise"):
            pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16,
                init_image=jnp.zeros((1, 16, 16, 3)), denoise=1.0,
            )

    def test_init_image_shape_mismatch_rejected(self, sd_pipe):
        pipe = sd_pipe
        with pytest.raises(ValueError, match="init_image"):
            pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16,
                init_image=jnp.zeros((1, 8, 8, 3)), denoise=0.5,
            )


class TestScheduleEdgeCases:
    def test_ddim_extreme_strength_and_steps(self):
        """steps/denoise > 1000 used to zero-divide in ddim_timesteps; the
        linspace truncation must handle any (steps, denoise) combo."""
        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        out = run_sampler(
            _toy_model(), noise, None, sampler="ddim", steps=200,
            init_latent=jnp.ones_like(noise), denoise=0.15,
        )
        assert np.isfinite(np.asarray(out)).all()

    def test_ddim_strength_monotonic(self):
        """Lower denoise ends closer to the init — the 501-1000 quantization
        plateau of the old integer-stride schedule would break this."""
        init = jnp.full((1, 4, 4, 4), 2.0)
        noise = jax.random.normal(jax.random.key(1), (1, 4, 4, 4))
        dists = []
        for d in (0.2, 0.5, 0.8):
            out = run_sampler(
                _toy_model(), noise, None, sampler="ddim", steps=180,
                init_latent=init, denoise=d,
            )
            dists.append(float(jnp.abs(out - init).mean()))
        assert dists[0] < dists[1] < dists[2], dists


class TestWanLora:
    def test_pretree_with_lora_rejected(self):
        from comfyui_parallelanything_tpu.models import load_wan_checkpoint
        from comfyui_parallelanything_tpu.models.wan import WanConfig

        cfg = WanConfig(
            in_channels=4, out_channels=4, hidden_size=48, ffn_dim=96,
            num_heads=4, depth=1, text_dim=32, freq_dim=16, dtype=jnp.float32,
        )
        with pytest.raises(ValueError, match="lora"):
            load_wan_checkpoint({"patch_embedding": {}}, cfg, lora={"x": 1})


class TestCustomSchedule:
    def test_custom_alphas_cumprod_drives_sigmas(self):
        """A caller schedule must change the actual noise levels (and not crash
        the img2img truncation) for the k-sampler branch, like the ddim one."""
        import jax.numpy as jnp

        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        short = jnp.linspace(0.999, 0.01, 100)  # 100-entry custom table
        default = run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=3, karras=False
        )
        custom = run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=3, karras=False,
            alphas_cumprod=short,
        )
        assert not np.allclose(np.asarray(default), np.asarray(custom))
        out = run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=3, karras=False,
            alphas_cumprod=short, init_latent=jnp.ones_like(noise), denoise=0.5,
        )
        assert np.isfinite(np.asarray(out)).all()


class TestInpainting:
    @pytest.mark.parametrize("sampler", ["ddim", "euler", "dpmpp_2m", "flow_euler"])
    def test_masked_region_preserved(self, sampler):
        """mask=0 regions must end exactly at the init latent (the final keep
        value is the un-noised init); mask=1 regions denoise freely."""
        init = jnp.full((1, 8, 8, 4), 2.0)
        noise = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
        m = jnp.zeros((1, 8, 8, 1)).at[:, :4].set(1.0)  # top half regenerates
        out = run_sampler(
            _toy_model(), noise, None, sampler=sampler, steps=3,
            init_latent=init, latent_mask=m,
        )
        kept = np.asarray(out[:, 4:])
        free = np.asarray(out[:, :4])
        np.testing.assert_allclose(kept, 2.0, rtol=1e-5, atol=1e-5)
        assert np.abs(free - 2.0).mean() > 0.1

    def test_mask_without_init_rejected(self):
        noise = jnp.zeros((1, 4, 4, 4))
        with pytest.raises(ValueError, match="latent_mask"):
            run_sampler(
                _toy_model(), noise, None, sampler="euler", steps=2,
                latent_mask=jnp.ones((1, 4, 4, 1)),
            )

    def test_mask_with_partial_denoise(self):
        """Inpaint + strength compose: the free region is still init-seeded."""
        init = jnp.full((1, 8, 8, 4), 2.0)
        noise = jax.random.normal(jax.random.key(2), (1, 8, 8, 4))
        m = jnp.zeros((1, 8, 8, 1)).at[:, :4].set(1.0)
        out = run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=3,
            init_latent=init, latent_mask=m, denoise=0.4,
        )
        np.testing.assert_allclose(np.asarray(out[:, 4:]), 2.0, rtol=1e-5, atol=1e-5)

    def test_user_callback_still_runs_on_blended(self):
        seen = []
        init = jnp.zeros((1, 4, 4, 4))
        noise = jax.random.normal(jax.random.key(3), (1, 4, 4, 4))
        run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=2,
            init_latent=init, latent_mask=jnp.ones((1, 4, 4, 1)),
            callback=lambda i, x: seen.append(i),
        )
        assert seen == [0, 1]

    def test_pipeline_inpaint(self, sd_pipe):
        init = jnp.full((1, 16, 16, 3), 0.5)
        m = jnp.zeros((1, 16, 16)).at[:, :8].set(1.0)
        img = sd_pipe(
            "hello", steps=2, cfg_scale=1.0, height=16, width=16,
            init_image=init, mask=m,
        )
        assert img.shape == (1, 16, 16, 3)
        assert np.isfinite(np.asarray(img)).all()

    def test_pipeline_mask_without_init_rejected(self, sd_pipe):
        with pytest.raises(ValueError, match="mask"):
            sd_pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16,
                mask=jnp.ones((1, 16, 16)),
            )

    def test_noise_mask_node_chain(self):
        from comfyui_parallelanything_tpu.nodes import TPUSetLatentNoiseMask

        lat = {"samples": jnp.zeros((1, 8, 8, 4))}
        m = jnp.ones((1, 16, 16))  # pixel-res mask gets resized to latent res
        (masked,) = TPUSetLatentNoiseMask().set_mask(lat, m)
        assert masked["noise_mask"].shape == (1, 8, 8, 1)

    def test_ksampler_consumes_noise_mask(self, sd_pipe):
        from comfyui_parallelanything_tpu.nodes import (
            TPUKSampler,
            TPUSetLatentNoiseMask,
            TPUVAEEncode,
        )

        img = jnp.full((1, 16, 16, 3), 0.5)
        (lat,) = TPUVAEEncode().encode(sd_pipe.vae, img)
        m = jnp.zeros((1, 16, 16)).at[:, :8].set(1.0)
        (masked,) = TPUSetLatentNoiseMask().set_mask(lat, m)
        cond = {"context": sd_pipe.encode_prompt(["hello"], 16, 16)[0]}
        (out,) = TPUKSampler().sample(
            sd_pipe.unet, cond, masked, seed=1, steps=2, cfg=1.0,
            sampler_name="euler",
        )
        # Kept region identical to the input latent, free region changed
        # (skip the seam row the bilinear mask resize blends).
        kept = np.asarray(out["samples"][:, 5:])
        np.testing.assert_allclose(
            kept, np.asarray(lat["samples"][:, 5:]), rtol=1e-5, atol=1e-5
        )
        assert not np.allclose(
            np.asarray(out["samples"][:, :4]), np.asarray(lat["samples"][:, :4])
        )

    def test_noise_mask_node_video_latent(self):
        from comfyui_parallelanything_tpu.nodes import TPUSetLatentNoiseMask

        lat = {"samples": jnp.zeros((1, 3, 8, 8, 16))}
        (masked,) = TPUSetLatentNoiseMask().set_mask(lat, jnp.ones((1, 16, 16)))
        assert masked["noise_mask"].shape == (1, 1, 8, 8, 1)  # broadcasts over T

    def test_observer_callback_return_ignored(self):
        """tqdm-style callbacks returning bools must not corrupt the latent."""
        init = jnp.zeros((1, 4, 4, 4))
        noise = jax.random.normal(jax.random.key(4), (1, 4, 4, 4))
        out = run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=2,
            callback=lambda i, x: True,
        )
        ref = run_sampler(_toy_model(), noise, None, sampler="euler", steps=2)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestLatentUpscale:
    def test_image_latent(self):
        from comfyui_parallelanything_tpu.nodes import TPULatentUpscale

        lat = {"samples": jnp.ones((2, 8, 8, 4))}
        (up,) = TPULatentUpscale().upscale(lat, 2.0)
        assert up["samples"].shape == (2, 16, 16, 4)

    def test_video_latent_keeps_time(self):
        from comfyui_parallelanything_tpu.nodes import TPULatentUpscale

        lat = {"samples": jnp.ones((1, 3, 8, 8, 16))}
        (up,) = TPULatentUpscale().upscale(lat, 1.5)
        assert up["samples"].shape == (1, 3, 12, 12, 16)

    def test_noise_mask_rescaled_with_latent(self):
        from comfyui_parallelanything_tpu.nodes import (
            TPULatentUpscale,
            TPUSetLatentNoiseMask,
        )

        lat = {"samples": jnp.zeros((1, 8, 8, 4))}
        (masked,) = TPUSetLatentNoiseMask().set_mask(lat, jnp.ones((1, 16, 16)))
        (up,) = TPULatentUpscale().upscale(masked, 2.0)
        assert up["noise_mask"].shape == (1, 16, 16, 1)


class TestFluxInpaint:
    def test_flux_mask_and_img2img(self):
        from comfyui_parallelanything_tpu.models import (
            CLIPTextConfig, T5Config, VAEConfig, build_clip_text,
            build_t5_encoder, build_vae,
        )
        from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux
        from comfyui_parallelanything_tpu.pipelines import FluxPipeline
        from test_tokenizer import _tiny_tokenizer

        tok = _tiny_tokenizer()
        fcfg = FluxConfig(
            in_channels=16, hidden_size=32, num_heads=4, depth=1,
            depth_single_blocks=1, context_in_dim=24, vec_in_dim=16,
            axes_dim=(4, 2, 2), guidance_embed=False, dtype=jnp.float32,
        )
        pipe = FluxPipeline(
            dit=build_flux(fcfg, jax.random.key(0), sample_shape=(1, 8, 8, 4),
                           txt_len=8),
            vae=build_vae(
                VAEConfig(z_channels=4, base_channels=16, channel_mult=(1, 2),
                          num_res_blocks=1, norm_groups=8, dtype=jnp.float32),
                jax.random.key(1), sample_hw=16),
            clip=build_clip_text(
                CLIPTextConfig(vocab_size=64, hidden_size=16, num_layers=1,
                               num_heads=2, max_len=8, eos_id=tok.eos_id,
                               dtype=jnp.float32), jax.random.key(2)),
            t5=build_t5_encoder(
                T5Config(vocab_size=64, d_model=24, d_kv=8, d_ff=48,
                         num_layers=1, num_heads=2, dtype=jnp.float32),
                jax.random.key(3), sample_len=8),
            tokenizer=tok, t5_tokenizer=tok,
        )
        init = jnp.full((1, 16, 16, 3), 0.5)
        m = jnp.zeros((1, 16, 16)).at[:, :8].set(1.0)
        img = pipe("hello", steps=2, guidance=None, height=16, width=16,
                   init_image=init, mask=m)
        assert img.shape == (1, 16, 16, 3)
        assert np.isfinite(np.asarray(img)).all()
        # plain img2img too (the path the _encode_init rename touched)
        img2 = pipe("hello", steps=2, guidance=None, height=16, width=16,
                    init_image=init, denoise=0.4)
        assert img2.shape == (1, 16, 16, 3)

    def test_upscale_snaps_to_even(self):
        from comfyui_parallelanything_tpu.nodes import TPULatentUpscale

        lat = {"samples": jnp.ones((1, 12, 12, 4))}
        (up,) = TPULatentUpscale().upscale(lat, 1.25)  # 15 -> snapped 16
        assert up["samples"].shape == (1, 16, 16, 4)

    def test_upscale_rejects_degenerate(self):
        from comfyui_parallelanything_tpu.nodes import TPULatentUpscale

        with pytest.raises(ValueError, match="shrinks"):
            TPULatentUpscale().upscale({"samples": jnp.ones((1, 4, 4, 4))}, 0.05)
