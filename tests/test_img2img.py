"""img2img / denoise-strength: truncated schedules in run_sampler, the
VAE-encode node, and the pipeline init_image path. The reference leaves img2img
to its host app's KSampler ``denoise`` widget + VAEEncode node; standalone this
is that capability (ComfyUI semantics: ``steps`` forwards always run; the
schedule for steps/denoise total steps is truncated to its tail)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.sampling.runner import run_sampler


def _toy_model():
    """A linear 'denoiser' whose eps prediction is a fixed fraction of x —
    enough to make schedules observable without a neural net."""

    def f(x, t, context=None, **kw):
        return 0.1 * x

    return f


class TestRunSamplerDenoise:
    @pytest.mark.parametrize("sampler", ["ddim", "euler", "dpmpp_2m", "flow_euler"])
    def test_full_denoise_unchanged_by_init(self, sampler):
        """denoise=1.0 ignores init entirely (identical to the txt2img path)."""
        noise = jax.random.normal(jax.random.key(0), (1, 8, 8, 4))
        a = run_sampler(_toy_model(), noise, None, sampler=sampler, steps=3)
        b = run_sampler(
            _toy_model(), noise, None, sampler=sampler, steps=3,
            init_latent=jnp.ones_like(noise), denoise=1.0,
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("sampler", ["ddim", "euler", "dpmpp_2m", "flow_euler"])
    def test_low_denoise_stays_near_init(self, sampler):
        """At small strength the output must stay closer to the init latent than
        a full-denoise run does — the whole point of img2img."""
        init = jnp.full((1, 8, 8, 4), 2.0)
        noise = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
        weak = run_sampler(
            _toy_model(), noise, None, sampler=sampler, steps=4,
            init_latent=init, denoise=0.2,
        )
        full = run_sampler(_toy_model(), noise, None, sampler=sampler, steps=4)
        d_weak = float(jnp.abs(weak - init).mean())
        d_full = float(jnp.abs(full - init).mean())
        assert d_weak < d_full, (sampler, d_weak, d_full)

    def test_denoise_out_of_range_rejected(self):
        noise = jnp.zeros((1, 4, 4, 4))
        with pytest.raises(ValueError, match="denoise"):
            run_sampler(
                _toy_model(), noise, None, sampler="euler", steps=2,
                init_latent=noise, denoise=0.0,
            )


class TestVAEEncodeNode:
    def test_round_trips_through_decode(self):
        from comfyui_parallelanything_tpu.models import VAEConfig, build_vae
        from comfyui_parallelanything_tpu.nodes import TPUVAEDecode, TPUVAEEncode

        cfg = VAEConfig(
            z_channels=4, base_channels=16, channel_mult=(1, 2),
            num_res_blocks=1, norm_groups=8, dtype=jnp.float32,
        )
        vae = build_vae(cfg, jax.random.key(0), sample_hw=16)
        img = jax.random.uniform(jax.random.key(1), (1, 16, 16, 3))
        (latent,) = TPUVAEEncode().encode(vae, img)
        assert latent["samples"].shape == (1, 8, 8, 4)
        (back,) = TPUVAEDecode().decode(vae, latent)
        assert back.shape == img.shape

    def test_seeded_encode_samples_posterior(self):
        from comfyui_parallelanything_tpu.models import VAEConfig, build_vae
        from comfyui_parallelanything_tpu.nodes import TPUVAEEncode

        cfg = VAEConfig(
            z_channels=4, base_channels=16, channel_mult=(1, 2),
            num_res_blocks=1, norm_groups=8, dtype=jnp.float32,
        )
        vae = build_vae(cfg, jax.random.key(0), sample_hw=16)
        img = jax.random.uniform(jax.random.key(1), (1, 16, 16, 3))
        (mean_latent,) = TPUVAEEncode().encode(vae, img, seed=-1)
        (sampled,) = TPUVAEEncode().encode(vae, img, seed=3)
        assert not np.allclose(
            np.asarray(mean_latent["samples"]), np.asarray(sampled["samples"])
        )


@pytest.fixture(scope="module")
def sd_pipe():
    from comfyui_parallelanything_tpu.models import (
        CLIPTextConfig, VAEConfig, build_clip_text, build_unet, build_vae,
        sd15_config,
    )
    from comfyui_parallelanything_tpu.pipelines import StableDiffusionPipeline
    from test_tokenizer import _tiny_tokenizer

    tok = _tiny_tokenizer()
    ccfg = CLIPTextConfig(
        vocab_size=64, hidden_size=48, num_layers=2, num_heads=4, max_len=8,
        eos_id=tok.eos_id, dtype=jnp.float32,
    )
    ucfg = sd15_config(
        model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
        attention_levels=(0, 1), context_dim=48, num_heads=4, norm_groups=8,
        dtype=jnp.float32,
    )
    vcfg = VAEConfig(
        z_channels=4, base_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        norm_groups=8, dtype=jnp.float32,
    )
    return StableDiffusionPipeline(
        unet=build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4)),
        vae=build_vae(vcfg, jax.random.key(1), sample_hw=16),
        clip=build_clip_text(ccfg, jax.random.key(2)),
        tokenizer=tok,
    )


class TestPipelineImg2Img:
    def test_init_image_shifts_output_toward_input(self, sd_pipe):
        pipe = sd_pipe
        init = jnp.full((1, 16, 16, 3), 0.5)
        kw = dict(steps=2, cfg_scale=1.0, height=16, width=16, rng=jax.random.key(2))
        out_full = np.asarray(pipe("hello", **kw))
        out_weak = np.asarray(pipe("hello", init_image=init, denoise=0.3, **kw))
        assert out_weak.shape == (1, 16, 16, 3)
        d_weak = np.abs(out_weak - 0.5).mean()
        d_full = np.abs(out_full - 0.5).mean()
        assert d_weak < d_full

    def test_init_image_with_full_denoise_rejected(self, sd_pipe):
        pipe = sd_pipe
        with pytest.raises(ValueError, match="denoise"):
            pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16,
                init_image=jnp.zeros((1, 16, 16, 3)), denoise=1.0,
            )

    def test_init_image_shape_mismatch_rejected(self, sd_pipe):
        pipe = sd_pipe
        with pytest.raises(ValueError, match="init_image"):
            pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16,
                init_image=jnp.zeros((1, 8, 8, 3)), denoise=0.5,
            )


class TestScheduleEdgeCases:
    def test_ddim_extreme_strength_and_steps(self):
        """steps/denoise > 1000 used to zero-divide in ddim_timesteps; the
        linspace truncation must handle any (steps, denoise) combo."""
        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        out = run_sampler(
            _toy_model(), noise, None, sampler="ddim", steps=200,
            init_latent=jnp.ones_like(noise), denoise=0.15,
        )
        assert np.isfinite(np.asarray(out)).all()

    def test_ddim_strength_monotonic(self):
        """Lower denoise ends closer to the init — the 501-1000 quantization
        plateau of the old integer-stride schedule would break this."""
        init = jnp.full((1, 4, 4, 4), 2.0)
        noise = jax.random.normal(jax.random.key(1), (1, 4, 4, 4))
        dists = []
        for d in (0.2, 0.5, 0.8):
            out = run_sampler(
                _toy_model(), noise, None, sampler="ddim", steps=180,
                init_latent=init, denoise=d,
            )
            dists.append(float(jnp.abs(out - init).mean()))
        assert dists[0] < dists[1] < dists[2], dists


class TestWanLora:
    def test_pretree_with_lora_rejected(self):
        from comfyui_parallelanything_tpu.models import load_wan_checkpoint
        from comfyui_parallelanything_tpu.models.wan import WanConfig

        cfg = WanConfig(
            in_channels=4, out_channels=4, hidden_size=48, ffn_dim=96,
            num_heads=4, depth=1, text_dim=32, freq_dim=16, dtype=jnp.float32,
        )
        with pytest.raises(ValueError, match="lora"):
            load_wan_checkpoint({"patch_embedding": {}}, cfg, lora={"x": 1})


class TestCustomSchedule:
    def test_custom_alphas_cumprod_drives_sigmas(self):
        """A caller schedule must change the actual noise levels (and not crash
        the img2img truncation) for the k-sampler branch, like the ddim one."""
        import jax.numpy as jnp

        noise = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        short = jnp.linspace(0.999, 0.01, 100)  # 100-entry custom table
        default = run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=3, karras=False
        )
        custom = run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=3, karras=False,
            alphas_cumprod=short,
        )
        assert not np.allclose(np.asarray(default), np.asarray(custom))
        out = run_sampler(
            _toy_model(), noise, None, sampler="euler", steps=3, karras=False,
            alphas_cumprod=short, init_latent=jnp.ones_like(noise), denoise=0.5,
        )
        assert np.isfinite(np.asarray(out)).all()
