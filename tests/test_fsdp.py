"""FSDP weight sharding: per-leaf largest-axis sharding over the data mesh, numerics
identical to replicate mode. Beyond-reference capability — a FLUX-dev-class model in
bf16 cannot hold a full replica per v5e chip (reference README.md:167 'full model per
device' is physically impossible there)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from comfyui_parallelanything_tpu import DeviceChain, ParallelConfig, parallelize
from comfyui_parallelanything_tpu.models import build_unet, sd15_config
from comfyui_parallelanything_tpu.parallel.mesh import (
    AXIS_DATA,
    build_mesh,
    fsdp_spec,
    place_params_fsdp,
)


class TestFsdpSpec:
    def test_large_divisible_shards_largest_axis(self):
        assert fsdp_spec((512, 1024), AXIS_DATA, 8) == P(None, AXIS_DATA)
        assert fsdp_spec((2048, 256), AXIS_DATA, 8) == P(AXIS_DATA, None)

    def test_small_replicates(self):
        assert fsdp_spec((64,), AXIS_DATA, 8) == P()

    def test_indivisible_replicates(self):
        assert fsdp_spec((1000, 999), AXIS_DATA, 8, min_size=1) == P(AXIS_DATA, None)
        assert fsdp_spec((999, 1001), AXIS_DATA, 8, min_size=1) == P()

    def test_scalar_replicates(self):
        assert fsdp_spec((), AXIS_DATA, 8) == P()


class TestFsdpPlacement:
    def test_leaves_actually_sharded(self, cpu_devices):
        mesh = build_mesh(cpu_devices, {AXIS_DATA: 8})
        params = {
            "big": jnp.ones((1024, 512)),
            "small": jnp.ones((16,)),
        }
        placed = place_params_fsdp(params, mesh)
        # big shards over 8 devices; each device holds 1/8 of the rows or cols.
        shard_shapes = {s.data.shape for s in placed["big"].addressable_shards}
        assert shard_shapes in ({(128, 512)}, {(1024, 64)})
        assert len(placed["small"].sharding.device_set) == 8  # replicated


class TestFsdpEndToEnd:
    def test_fsdp_matches_replicate(self, cpu_devices):
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm_rep = parallelize(model, chain)
        pm_fsdp = parallelize(
            model, chain, ParallelConfig(weight_sharding="fsdp")
        )
        x = jax.random.normal(jax.random.key(1), (8, 16, 16, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (8, 12, 64), jnp.float32)
        t = jnp.linspace(999.0, 1.0, 8)
        a = pm_rep(x, t, ctx)
        b = pm_fsdp(x, t, ctx)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3)

    def test_fsdp_single_fallback_stays_sharded(self, cpu_devices):
        # batch==1 (no pipeline spec on a bare-fn model) routes through single();
        # under fsdp the params must NOT be copied whole to the lead device — the
        # fallback runs on the group mesh with replicated inputs.
        def f(p, x, t, context=None, **kw):
            return x @ p["w"]

        params = {"w": jnp.ones((1024, 1024))}
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(
            (f, params), chain, ParallelConfig(weight_sharding="fsdp")
        )
        out = pm(jnp.ones((1, 1024)), jnp.zeros((1,)))
        assert out.shape == (1, 1024)
        assert pm._lead_params is None  # no full-pytree lead copy happened

    def test_fsdp_params_use_less_per_device_memory(self, cpu_devices):
        # Structural check: at least the large kernels are sharded, not replicated.
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(model, chain, ParallelConfig(weight_sharding="fsdp"))
        leaves = jax.tree.leaves(pm._groups[0].params)
        sharded = [
            l for l in leaves
            if l.size >= 2**16 and len(l.addressable_shards) == 8
            and l.addressable_shards[0].data.size < l.size
        ]
        assert sharded, "expected at least one genuinely sharded large parameter"
