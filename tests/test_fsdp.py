"""FSDP weight sharding: per-leaf largest-axis sharding over the data mesh, numerics
identical to replicate mode. Beyond-reference capability — a FLUX-dev-class model in
bf16 cannot hold a full replica per v5e chip (reference README.md:167 'full model per
device' is physically impossible there)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from comfyui_parallelanything_tpu import DeviceChain, ParallelConfig, parallelize
from comfyui_parallelanything_tpu.models import build_unet, sd15_config
from comfyui_parallelanything_tpu.parallel.mesh import (
    AXIS_DATA,
    build_mesh,
    fsdp_spec,
    place_params_fsdp,
)


class TestFsdpSpec:
    def test_large_divisible_shards_largest_axis(self):
        assert fsdp_spec((512, 1024), AXIS_DATA, 8) == P(None, AXIS_DATA)
        assert fsdp_spec((2048, 256), AXIS_DATA, 8) == P(AXIS_DATA, None)

    def test_small_replicates(self):
        assert fsdp_spec((64,), AXIS_DATA, 8) == P()

    def test_indivisible_replicates(self):
        assert fsdp_spec((1000, 999), AXIS_DATA, 8, min_size=1) == P(AXIS_DATA, None)
        assert fsdp_spec((999, 1001), AXIS_DATA, 8, min_size=1) == P()

    def test_scalar_replicates(self):
        assert fsdp_spec((), AXIS_DATA, 8) == P()


class TestFsdpPlacement:
    def test_leaves_actually_sharded(self, cpu_devices):
        mesh = build_mesh(cpu_devices, {AXIS_DATA: 8})
        params = {
            "big": jnp.ones((1024, 512)),
            "small": jnp.ones((16,)),
        }
        placed = place_params_fsdp(params, mesh)
        # big shards over 8 devices; each device holds 1/8 of the rows or cols.
        shard_shapes = {s.data.shape for s in placed["big"].addressable_shards}
        assert shard_shapes in ({(128, 512)}, {(1024, 64)})
        assert len(placed["small"].sharding.device_set) == 8  # replicated

    def test_streamed_put_matches_direct_device_put(self, cpu_devices):
        # streamed_tree_put (the int8-placement OOM fix, VERDICT r3 next-1)
        # must be value- and sharding-identical to a whole-pytree device_put;
        # a tiny in-flight cap forces several drain cycles through the loop.
        import numpy as np

        from comfyui_parallelanything_tpu.parallel.mesh import (
            replicated,
            streamed_tree_put,
        )

        mesh = build_mesh(cpu_devices, {AXIS_DATA: 8})
        params = {f"w{i}": jnp.full((64, 64), float(i)) for i in range(6)}
        sharding = replicated(mesh)
        streamed = streamed_tree_put(
            params, lambda _: sharding, max_inflight_bytes=1
        )
        direct = jax.device_put(params, sharding)
        for k in params:
            assert streamed[k].sharding == direct[k].sharding
            np.testing.assert_array_equal(
                np.asarray(streamed[k]), np.asarray(direct[k])
            )


class TestFsdpEndToEnd:
    def test_fsdp_matches_replicate(self, cpu_devices):
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm_rep = parallelize(model, chain)
        pm_fsdp = parallelize(
            model, chain, ParallelConfig(weight_sharding="fsdp")
        )
        x = jax.random.normal(jax.random.key(1), (8, 16, 16, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (8, 12, 64), jnp.float32)
        t = jnp.linspace(999.0, 1.0, 8)
        a = pm_rep(x, t, ctx)
        b = pm_fsdp(x, t, ctx)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3)

    def test_fsdp_single_fallback_stays_sharded(self, cpu_devices):
        # batch==1 (no pipeline spec on a bare-fn model) routes through single();
        # under fsdp the params must NOT be copied whole to the lead device — the
        # fallback runs on the group mesh with replicated inputs.
        def f(p, x, t, context=None, **kw):
            return x @ p["w"]

        params = {"w": jnp.ones((1024, 1024))}
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(
            (f, params), chain, ParallelConfig(weight_sharding="fsdp")
        )
        out = pm(jnp.ones((1, 1024)), jnp.zeros((1,)))
        assert out.shape == (1, 1024)
        assert pm._lead_params is None  # no full-pytree lead copy happened

    def test_full_size_flux_dev_fsdp_byte_math(self, cpu_devices):
        # The stated reason FSDP exists: flux-dev bf16 (~24 GB) cannot replicate
        # on a 16 GB v5e chip (parallel/mesh.py fsdp_spec docstring). Prove the
        # placement math on the REAL 19/38-depth 12B-param config — abstract
        # shapes (eval_shape, zero bytes materialized) + the exact per-device
        # shard bytes the FSDP policy produces.
        from comfyui_parallelanything_tpu.models import (
            flux_abstract_params,
            flux_dev_config,
        )
        from comfyui_parallelanything_tpu.parallel.mesh import sharded_byte_math

        cfg = flux_dev_config(dtype=jnp.bfloat16)
        assert (cfg.depth, cfg.depth_single_blocks) == (19, 38)
        shapes = flux_abstract_params(cfg, sample_shape=(1, 4, 4, 16), txt_len=4)
        n_params = sum(s.size for s in jax.tree.leaves(shapes))
        assert n_params > 10e9  # genuinely the 12B-class pytree
        # Exact per-device bytes from shard shapes (bf16 checkpoint layout: 2
        # bytes/param — the load path the converters produce).
        per_device, total = sharded_byte_math(
            shapes, build_mesh(cpu_devices, {AXIS_DATA: 8}), AXIS_DATA
        )
        assert total > 20 * 2**30  # the full replica genuinely overflows a v5e
        # Sharded 8-way it fits with room to spare; replication slack (small
        # norms/biases live whole on every chip) stays under 5%.
        assert per_device < total / 8 * 1.05
        assert per_device < 4 * 2**30

    def test_full_width_flux_fsdp_places_and_steps(self, cpu_devices):
        # The mechanics proof at full layer width: materialize a full-WIDTH
        # (hidden 3072, 24 heads) flux pytree directly into its FSDP sharding —
        # the unsharded pytree never exists — verify real buffer bytes are 1/8
        # per device, and run one denoise step through the orchestrator. (The
        # full 57-block 12B forward is not runnable on the virtual mesh: eight
        # host threads each all-gathering full weights needs >8x the pytree in
        # one host's RAM; on a real v5e-8 each chip holds 1/8 + one block's
        # gather. Depth is the only reduction here — every tensor shape that
        # matters to sharding is full-size.)
        from comfyui_parallelanything_tpu.models import (
            build_flux,
            flux_abstract_params,
            flux_dev_config,
        )
        from comfyui_parallelanything_tpu.parallel.mesh import (
            materialize_params_sharded,
        )

        cfg = flux_dev_config(depth=1, depth_single_blocks=2, dtype=jnp.bfloat16)
        shapes = flux_abstract_params(cfg, sample_shape=(1, 4, 4, 16), txt_len=4)
        shapes = jax.tree.map(
            lambda sd: jax.ShapeDtypeStruct(sd.shape, jnp.bfloat16), shapes
        )
        mesh = build_mesh(cpu_devices, {AXIS_DATA: 8})
        params = materialize_params_sharded(shapes, mesh, AXIS_DATA)
        total = sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(params))
        per_dev = {}
        for leaf in jax.tree.leaves(params):
            for sh in leaf.addressable_shards:
                per_dev[sh.device.id] = per_dev.get(sh.device.id, 0) + sh.data.nbytes
        assert len(per_dev) == 8
        for b in per_dev.values():
            assert b < total / 8 * 1.05
        model = build_flux(cfg, params=params, sample_shape=(1, 4, 4, 16), txt_len=4)
        pm = parallelize(
            model,
            DeviceChain.even([f"cpu:{i}" for i in range(8)]),
            ParallelConfig(weight_sharding="fsdp"),
        )
        x = jnp.ones((8, 4, 4, 16), jnp.float32)
        t = jnp.linspace(1.0, 0.1, 8)
        ctx = jnp.ones((8, 4, cfg.context_in_dim), jnp.float32)
        y = jnp.ones((8, cfg.vec_in_dim), jnp.float32)
        out = pm(x, t, ctx, y=y, guidance=jnp.full((8,), 3.5, jnp.float32))
        assert out.shape == (8, 4, 4, 16)
        assert np.isfinite(np.asarray(out, np.float32)).all()

    def test_fsdp_params_use_less_per_device_memory(self, cpu_devices):
        # Structural check: at least the large kernels are sharded, not replicated.
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(model, chain, ParallelConfig(weight_sharding="fsdp"))
        leaves = jax.tree.leaves(pm._groups[0].params)
        sharded = [
            l for l in leaves
            if l.size >= 2**16 and len(l.addressable_shards) == 8
            and l.addressable_shards[0].data.size < l.size
        ]
        assert sharded, "expected at least one genuinely sharded large parameter"


class TestStreamedPutPeakBound:
    def test_inflight_bytes_bounded_on_flux_dev_int8_shapes(self, monkeypatch):
        """The round-3 flux_16_int8 placement OOM fix pinned without hardware
        (VERDICT r4 next-5): over a FLUX-dev-shaped int8 pytree (exact leaf
        shapes via jax.eval_shape — no buffers materialize), the un-drained
        transfer queue must never exceed max_inflight_bytes + one leaf. Byte
        math only; device_put/block_until_ready are instrumented stubs.
        Referenced from BASELINE.md's flux_16_int8 paragraph."""
        from types import SimpleNamespace

        from comfyui_parallelanything_tpu.models.flux import (
            FluxModel,
            flux_dev_config,
        )
        from comfyui_parallelanything_tpu.parallel import mesh as mesh_mod

        cfg = flux_dev_config()  # FULL depth 19/38 — shapes only
        module = FluxModel(cfg)

        def init():
            x = jnp.zeros((1, 8, 8, 16), jnp.float32)  # NHWC latent, 16 tokens
            t = jnp.zeros((1,), jnp.float32)
            ctx = jnp.zeros((1, 16, cfg.context_in_dim), jnp.float32)
            y = jnp.zeros((1, cfg.vec_in_dim), jnp.float32)
            return module.init(jax.random.key(0), x, t, ctx, y=y)

        shapes = jax.eval_shape(init)["params"]
        # int8 quantization: ~1 byte per element (scales are negligible).
        leaves = [
            SimpleNamespace(nbytes=int(np.prod(l.shape)) or 1)
            for l in jax.tree.leaves(shapes)
        ]
        total = sum(l.nbytes for l in leaves)
        biggest = max(l.nbytes for l in leaves)
        assert total > 8 << 30  # sanity: genuinely flux-dev-sized (int8 ~11GB)

        state = {"outstanding": 0, "peak": 0}

        def fake_put(leaf, sharding):
            state["outstanding"] += leaf.nbytes
            state["peak"] = max(state["peak"], state["outstanding"])
            return leaf

        def fake_block(x):
            state["outstanding"] = 0
            return x

        monkeypatch.setattr(jax, "device_put", fake_put)
        monkeypatch.setattr(jax, "block_until_ready", fake_block)
        cap = mesh_mod._MAX_INFLIGHT_BYTES
        mesh_mod.streamed_tree_put(leaves, lambda _: None)
        # Ceiling: the drain triggers AFTER the leaf that crosses the cap.
        assert state["peak"] <= cap + biggest
        # And the bound is meaningful: far below all-concurrent staging.
        assert state["peak"] * 4 < total
