"""Checkpoint loading end-to-end: safetensors file → converted params → model that
matches the init-built reference model numerically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux
from comfyui_parallelanything_tpu.models.loader import (
    load_flux_checkpoint,
    load_safetensors,
    load_sd_unet_checkpoint,
)
from comfyui_parallelanything_tpu.models.unet import build_unet, sd15_config
from tests.test_convert import _torch_layout_sd
from tests.test_convert_unet import _ldm_sd


@pytest.fixture(scope="module")
def flux_pair(tmp_path_factory):
    cfg = FluxConfig(
        in_channels=16, hidden_size=32, num_heads=2, depth=1, depth_single_blocks=1,
        context_in_dim=16, vec_in_dim=8, axes_dim=(4, 6, 6), guidance_embed=False,
        dtype=jnp.float32,
    )
    model = build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=8)
    sd = _torch_layout_sd(cfg, model.params)
    path = tmp_path_factory.mktemp("ckpt") / "flux.safetensors"
    from safetensors.numpy import save_file

    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()}, str(path))
    return cfg, model, path


class TestFluxLoad:
    def test_file_roundtrip_forward(self, flux_pair):
        cfg, model, path = flux_pair
        loaded = load_flux_checkpoint(str(path), cfg)
        assert loaded.pipeline_spec is not None
        x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (1, 8, 16), jnp.float32)
        t = jnp.array([0.5])
        want = model(x, t, ctx)
        got = loaded(x, t, ctx)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_bf16_storage_upcasts(self, flux_pair, tmp_path):
        cfg, model, _ = flux_pair
        import ml_dtypes
        from safetensors.numpy import save_file

        sd = _torch_layout_sd(cfg, model.params)
        bf16_sd = {
            k: np.ascontiguousarray(v.astype(ml_dtypes.bfloat16)) for k, v in sd.items()
        }
        path = tmp_path / "flux_bf16.safetensors"
        save_file(bf16_sd, str(path))
        raw = load_safetensors(path)
        assert all(v.dtype == np.float32 for v in raw.values())
        loaded = load_flux_checkpoint(str(path), cfg)
        x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (1, 8, 16), jnp.float32)
        out = loaded(x, jnp.array([0.5]), ctx)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_lora_applied_at_load(self, flux_pair):
        cfg, model, path = flux_pair
        rank, hs = 2, 32
        down = np.random.default_rng(0).standard_normal((rank, hs)).astype(np.float32)
        up = np.random.default_rng(1).standard_normal((hs, rank)).astype(np.float32)
        lora = {
            "double_blocks.0.img_attn.proj.lora_down.weight": down,
            "double_blocks.0.img_attn.proj.lora_up.weight": up,
        }
        plain = load_flux_checkpoint(str(path), cfg)
        loraed = load_flux_checkpoint(str(path), cfg, lora=lora, lora_strength=1.0)
        k_plain = np.asarray(plain.params["double_blocks_0"]["img_attn_proj"]["kernel"])
        k_lora = np.asarray(loraed.params["double_blocks_0"]["img_attn_proj"]["kernel"])
        np.testing.assert_allclose(k_lora, k_plain + (up @ down).T, rtol=1e-5)


class TestSDLoad:
    def test_comfy_full_checkpoint_subtree(self, tmp_path):
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        sd = {
            f"model.diffusion_model.{k}": np.ascontiguousarray(v)
            for k, v in _ldm_sd(cfg, model.params).items()
        }
        sd["first_stage_model.decoder.junk"] = np.zeros((2,), np.float32)
        from safetensors.numpy import save_file

        path = tmp_path / "sd15.safetensors"
        save_file(sd, str(path))
        loaded = load_sd_unet_checkpoint(str(path), cfg)
        x = jax.random.normal(jax.random.key(3), (2, 16, 16, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(4), (2, 12, 64), jnp.float32)
        t = jnp.array([5.0, 9.0])
        want = model(x, t, ctx)
        got = loaded(x, t, ctx)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
