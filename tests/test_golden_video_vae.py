"""WAN video VAE golden parity vs a minimal torch reference (official layout).

The torch reference below follows the public Wan2.1 causal 3D VAE design in its
non-streaming single-clip form: causal (front-padded) 3D convs, channel RMS norms
(``F.normalize·√C·γ``), per-frame single-head mid attention, (0,1)×(0,1)-padded
stride-2 spatial resampling, and the 2×-channel time conv whose halves interleave
along time on upsampling (first frame emitted once). Exported in the official
``encoder.downsamples.{seq}`` / ``decoder.upsamples.{seq}`` flat-Sequential key
layout and converted with ``convert_wan_vae.py``.

The official torch implementation streams 4-frame chunks through per-conv caches;
this reference computes the same causal math whole-clip (the repo's documented
equivalence, convert_wan_vae.py module docstring) — so this test validates the
conv/norm/resample architecture and the converter's layout map, which round-trip
inversion (test_convert_wan.py) cannot.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models.convert_wan_vae import (
    convert_wan_vae_checkpoint,
)
from comfyui_parallelanything_tpu.models.video_vae import (
    VideoAutoencoderKL,
    VideoVAEConfig,
)

torch = pytest.importorskip("torch")
tnn = torch.nn
F = torch.nn.functional

CFG = dataclasses.replace(
    VideoVAEConfig(),
    z_channels=4,
    base_channels=16,
    channel_mult=(1, 2, 2),
    num_res_blocks=1,
    temporal_downsample=(False, True),
    latent_mean=(0.0,) * 4,
    latent_std=(1.0,) * 4,
    dtype=jnp.float32,
)


class TCausalConv3d(tnn.Conv3d):
    """Conv3d with causal time padding (kt-1 front) and SAME spatial padding."""

    def forward(self, x):
        kt, kh, kw = self.kernel_size
        x = F.pad(x, (kw // 2, kw // 2, kh // 2, kh // 2, kt - 1, 0))
        return super().forward(x)


class TRMSNorm(tnn.Module):
    def __init__(self, dim, images=False, bias=False):
        super().__init__()
        shape = (dim, 1, 1) if images else (dim, 1, 1, 1)
        self.dim = dim
        self.gamma = tnn.Parameter(torch.randn(shape))
        if bias:
            self.bias = tnn.Parameter(torch.randn(shape))

    def forward(self, x):
        y = F.normalize(x.float(), dim=1) * np.sqrt(self.dim) * self.gamma
        if hasattr(self, "bias"):
            y = y + self.bias
        return y


class TResidualBlock(tnn.Module):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.residual = tnn.Sequential(
            TRMSNorm(in_ch), tnn.SiLU(),
            TCausalConv3d(in_ch, out_ch, 3),
            TRMSNorm(out_ch), tnn.SiLU(), tnn.Identity(),
            TCausalConv3d(out_ch, out_ch, 3),
        )
        self.shortcut = (
            TCausalConv3d(in_ch, out_ch, 1) if in_ch != out_ch else tnn.Identity()
        )

    def forward(self, x):
        return self.shortcut(x) + self.residual(x)


class TAttentionBlock(tnn.Module):
    """Per-frame single-head spatial attention (frames fold into batch)."""

    def __init__(self, ch):
        super().__init__()
        self.norm = TRMSNorm(ch, images=True)
        self.to_qkv = tnn.Conv2d(ch, 3 * ch, 1)
        self.proj = tnn.Conv2d(ch, ch, 1)

    def forward(self, x):
        b, c, t, hh, ww = x.shape
        h = x.permute(0, 2, 1, 3, 4).reshape(b * t, c, hh, ww)
        qkv = self.to_qkv(self.norm(h))
        q, k, v = qkv.reshape(b * t, 3 * c, hh * ww).chunk(3, dim=1)
        logits = torch.einsum("bcq,bck->bqk", q.float(), k.float()) / np.sqrt(c)
        w = torch.softmax(logits, dim=-1)
        o = torch.einsum("bqk,bck->bcq", w, v.float()).reshape(b * t, c, hh, ww)
        o = self.proj(o)
        return x + o.reshape(b, t, c, hh, ww).permute(0, 2, 1, 3, 4)


class TDownsample(tnn.Module):
    def __init__(self, ch, temporal):
        super().__init__()
        self.temporal = temporal
        self.resample = tnn.Sequential(
            tnn.ZeroPad2d((0, 1, 0, 1)), tnn.Conv2d(ch, ch, 3, stride=2)
        )
        if temporal:
            self.time_conv = TCausalConv3d(ch, ch, (3, 1, 1), stride=(2, 1, 1))

    def forward(self, x):
        b, c, t, hh, ww = x.shape
        h = x.permute(0, 2, 1, 3, 4).reshape(b * t, c, hh, ww)
        h = self.resample(h)
        hh2, ww2 = h.shape[-2:]
        h = h.reshape(b, t, c, hh2, ww2).permute(0, 2, 1, 3, 4)
        if self.temporal:
            h = self.time_conv(h)
        return h


class TUpsample(tnn.Module):
    def __init__(self, ch, temporal):
        super().__init__()
        self.temporal = temporal
        self.resample = tnn.Sequential(
            tnn.Upsample(scale_factor=(2.0, 2.0), mode="nearest"),
            tnn.Conv2d(ch, ch // 2, 3, padding=1),
        )
        if temporal:
            self.time_conv = TCausalConv3d(ch, 2 * ch, (3, 1, 1))

    def forward(self, x):
        b, c, t, hh, ww = x.shape
        if self.temporal:
            h = self.time_conv(x)  # (b, 2c, t, hh, ww)
            h = h.reshape(b, 2, c, t, hh, ww)
            h = torch.stack((h[:, 0], h[:, 1]), dim=3)  # (b, c, t, 2, hh, ww)
            x = h.reshape(b, c, 2 * t, hh, ww)[:, :, 1:]  # first frame once
            t = 2 * t - 1
        h = x.permute(0, 2, 1, 3, 4).reshape(b * t, c, hh, ww)
        h = self.resample(h)
        return h.reshape(b, t, c // 2, 2 * hh, 2 * ww).permute(0, 2, 1, 3, 4)


class TEncoder(tnn.Module):
    def __init__(self, cfg: VideoVAEConfig):
        super().__init__()
        chans = [cfg.base_channels * m for m in cfg.channel_mult]
        self.conv1 = TCausalConv3d(cfg.in_channels, cfg.base_channels, 3)
        downs = []
        ch = cfg.base_channels
        for level, out_ch in enumerate(chans):
            for _ in range(cfg.num_res_blocks):
                downs.append(TResidualBlock(ch, out_ch))
                ch = out_ch
            if level != len(chans) - 1:
                downs.append(TDownsample(ch, cfg.temporal_downsample[level]))
        self.downsamples = tnn.Sequential(*downs)
        self.middle = tnn.Sequential(
            TResidualBlock(ch, ch), TAttentionBlock(ch), TResidualBlock(ch, ch)
        )
        self.head = tnn.Sequential(
            TRMSNorm(ch), tnn.SiLU(), TCausalConv3d(ch, 2 * cfg.z_channels, 3)
        )

    def forward(self, x):
        return self.head(self.middle(self.downsamples(self.conv1(x))))


class TDecoder(tnn.Module):
    def __init__(self, cfg: VideoVAEConfig):
        super().__init__()
        chans = [cfg.base_channels * m for m in cfg.channel_mult]
        n = len(chans)
        ch = chans[-1]
        self.conv1 = TCausalConv3d(cfg.z_channels, ch, 3)
        self.middle = tnn.Sequential(
            TResidualBlock(ch, ch), TAttentionBlock(ch), TResidualBlock(ch, ch)
        )
        temporal_up = tuple(reversed(cfg.temporal_downsample))
        ups = []
        for j, level in enumerate(reversed(range(n))):
            out_ch = chans[level]
            for _ in range(cfg.num_res_blocks + 1):
                ups.append(TResidualBlock(ch, out_ch))
                ch = out_ch
            if j != n - 1:
                ups.append(TUpsample(ch, temporal_up[j]))
                ch = ch // 2
        self.upsamples = tnn.Sequential(*ups)
        self.head = tnn.Sequential(
            TRMSNorm(chans[0]), tnn.SiLU(),
            TCausalConv3d(chans[0], cfg.in_channels, 3),
        )

    def forward(self, z):
        return self.head(self.upsamples(self.middle(self.conv1(z))))


class TWanVAE(tnn.Module):
    def __init__(self, cfg: VideoVAEConfig):
        super().__init__()
        self.encoder = TEncoder(cfg)
        self.decoder = TDecoder(cfg)
        self.conv1 = TCausalConv3d(2 * cfg.z_channels, 2 * cfg.z_channels, 1)
        self.conv2 = TCausalConv3d(cfg.z_channels, cfg.z_channels, 1)


@pytest.fixture(scope="module")
def pair():
    torch.manual_seed(11)
    tvae = TWanVAE(CFG).eval()
    sd = {k: v.detach() for k, v in tvae.state_dict().items()}
    params = convert_wan_vae_checkpoint(sd, CFG)
    return tvae, params


def test_video_encoder_moments_golden_parity(pair):
    tvae, params = pair
    rng = np.random.default_rng(41)
    x = rng.uniform(-1, 1, size=(1, 5, 16, 16, 3)).astype(np.float32)  # NTHWC
    with torch.no_grad():
        h = tvae.conv1(
            tvae.encoder(torch.from_numpy(x.transpose(0, 4, 1, 2, 3)))
        ).numpy().transpose(0, 2, 3, 4, 1)
    want_mean = np.split(h, 2, axis=-1)[0]
    mean, _ = VideoAutoencoderKL(CFG).apply(
        {"params": params}, jnp.asarray(x), method=VideoAutoencoderKL.moments
    )
    assert mean.shape == (1, 3, 4, 4, CFG.z_channels)  # T: 5 → 3 (one temporal /2)
    np.testing.assert_allclose(np.asarray(mean), want_mean, rtol=1e-3, atol=1e-3)


def test_video_decoder_golden_parity(pair):
    tvae, params = pair
    rng = np.random.default_rng(43)
    z = rng.normal(size=(1, 3, 4, 4, CFG.z_channels)).astype(np.float32)
    with torch.no_grad():
        want = tvae.decoder(
            tvae.conv2(torch.from_numpy(z.transpose(0, 4, 1, 2, 3)))
        ).numpy().transpose(0, 2, 3, 4, 1)
    got = np.asarray(
        VideoAutoencoderKL(CFG).apply(
            {"params": params}, jnp.asarray(z), method=VideoAutoencoderKL.decode
        )
    )
    assert got.shape == (1, 5, 16, 16, 3)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
