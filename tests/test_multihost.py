"""Multi-host helpers — single-process degeneracy (the CI-reachable half; the
multi-process branch is exercised on real pods via jax.distributed)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.parallel.mesh import AXIS_SEQ
from comfyui_parallelanything_tpu.parallel.multihost import (
    host_local_batch,
    hybrid_mesh,
    initialize_distributed,
    is_multihost,
)
from comfyui_parallelanything_tpu.parallel.sequence import sequence_parallel_attention


class TestSingleProcessDegeneracy:
    def test_initialize_noop(self):
        assert initialize_distributed() is False
        assert not is_multihost()

    def test_hybrid_mesh_all_local(self, cpu_devices):
        mesh = hybrid_mesh({AXIS_SEQ: 4}, devices=cpu_devices)
        assert mesh.shape == {"data": 2, "seq": 4}

    def test_hybrid_mesh_pure_data(self, cpu_devices):
        mesh = hybrid_mesh(devices=cpu_devices)
        assert mesh.shape == {"data": 8}

    def test_indivisible_raises(self, cpu_devices):
        with pytest.raises(ValueError, match="do not divide"):
            hybrid_mesh({AXIS_SEQ: 3}, devices=cpu_devices)

    def test_host_local_batch_places_sharded(self, cpu_devices):
        mesh = hybrid_mesh(devices=cpu_devices)
        arr = np.random.default_rng(0).standard_normal((16, 4)).astype(np.float32)
        out = host_local_batch(arr, mesh)
        assert out.shape == (16, 4)
        assert len(out.sharding.device_set) == 8
        np.testing.assert_allclose(np.asarray(out), arr)

    def test_hybrid_mesh_drives_sequence_parallel(self, cpu_devices):
        # The (data, seq) hybrid mesh feeds the seq-parallel program directly.
        mesh = hybrid_mesh({AXIS_SEQ: 4}, devices=cpu_devices)
        sub = jax.sharding.Mesh(mesh.devices[0:1].reshape(4), (AXIS_SEQ,))
        q = jax.random.normal(jax.random.key(0), (1, 32, 4, 8), jnp.float32)
        out = sequence_parallel_attention(q, q, q, sub, method="ring")
        assert out.shape == q.shape
