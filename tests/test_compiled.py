"""Whole-loop compiled sampling (sampling/compiled.py): every sampler's scan
program must match its eager twin step-for-step, on bare models and on a
parallel chain over the virtual mesh, including CFG, img2img, and the traced
inpaint-mask hook; non-traceable cases must fall back to the eager loops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.sampling.runner import run_sampler

SHAPE = (2, 8, 8, 4)


def _toy_model(calls=None):
    def f(x, t, context=None, **kwargs):
        if calls is not None:
            calls.append(1)
        h = 0.12 * x * jnp.cos(t)[:, None, None, None]
        if context is not None:
            h = h + 0.01 * context.sum(axis=(1, 2))[:, None, None, None]
        if kwargs.get("y") is not None:
            h = h + 0.001 * kwargs["y"][:, None, None, :]
        return h

    return f


def _noise(seed=0, shape=SHAPE):
    return jax.random.normal(jax.random.key(seed), shape)


def _ctx(seed=3, batch=SHAPE[0]):
    return jax.random.normal(jax.random.key(seed), (batch, 6, 16))


ALL_SAMPLERS = [
    "euler", "euler_ancestral", "heun", "dpm_2", "dpm_2_ancestral", "lms",
    "dpmpp_2s_ancestral", "dpmpp_sde", "dpmpp_2m", "dpmpp_2m_sde",
    "dpmpp_3m_sde", "lcm", "ddpm", "uni_pc", "uni_pc_bh2", "ddim",
    "flow_euler",
]


def _run(sampler, compile_loop, model=None, **kw):
    model = model or _toy_model()
    args = dict(
        sampler=sampler, steps=5, rng=jax.random.key(7),
        compile_loop=compile_loop,
    )
    args.update(kw)
    return run_sampler(model, _noise(), _ctx(), **args)


class TestEagerCompiledEquivalence:
    @pytest.mark.parametrize("sampler", ALL_SAMPLERS)
    def test_plain(self, sampler):
        a = _run(sampler, False)
        b = _run(sampler, True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("sampler", ["euler", "dpmpp_2m", "ddim", "flow_euler"])
    def test_cfg(self, sampler):
        kw = dict(cfg_scale=4.0, uncond_context=_ctx(seed=9), cfg_rescale=0.3)
        a = _run(sampler, False, **kw)
        b = _run(sampler, True, **kw)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("sampler", ["euler", "euler_ancestral", "ddim",
                                         "flow_euler"])
    def test_img2img_and_mask(self, sampler):
        mask = jnp.zeros((1, 8, 8, 1)).at[:, :4].set(1.0)
        kw = dict(
            init_latent=jnp.full(SHAPE, 0.5), denoise=0.6, latent_mask=mask,
        )
        a = _run(sampler, False, **kw)
        b = _run(sampler, True, **kw)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("sampler", ["euler", "dpmpp_2m", "uni_pc",
                                         "euler_ancestral",
                                         "dpmpp_2s_ancestral", "lcm"])
    def test_flow_prediction(self, sampler):
        # Flow-time k-sampling (FLUX/SD3/WAN routing): the compiled loop must
        # match eager on the flow schedule, including the flow mask blend.
        mask = jnp.zeros((1, 8, 8, 1)).at[:, :4].set(1.0)
        kw = dict(prediction="flow", shift=1.2,
                  init_latent=jnp.full(SHAPE, 0.5), latent_mask=mask)
        a = _run(sampler, False, **kw)
        b = _run(sampler, True, **kw)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    def test_v_prediction_and_scheduler(self):
        kw = dict(prediction="v", scheduler="sgm_uniform")
        a = _run("dpmpp_2m", False, **kw)
        b = _run("dpmpp_2m", True, **kw)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    def test_batch_kwarg_doubles_through_cfg(self):
        y = jnp.linspace(0.0, 1.0, SHAPE[0] * 4).reshape(SHAPE[0], 4)
        kw = dict(cfg_scale=3.0, uncond_context=_ctx(seed=9),
                  uncond_kwargs={"y": -y}, y=y)
        a = _run("euler", False, **kw)
        b = _run("euler", True, **kw)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


class TestParallelChain:
    @pytest.mark.parametrize("sampler", ["euler", "dpmpp_2m"])
    def test_matches_eager_on_mesh(self, cpu_devices, sampler):
        def apply_fn(params, x, t, context=None, **kwargs):
            h = x * params["a"] * jnp.cos(t)[:, None, None, None]
            if context is not None:
                h = h + 0.01 * context.sum(axis=(1, 2))[:, None, None, None]
            return h

        params = {"a": jnp.float32(0.12)}
        pm = parallelize(
            (apply_fn, params), DeviceChain.even([f"cpu:{i}" for i in range(8)])
        )
        noise, ctx = _noise(), _ctx()
        a = run_sampler(pm, noise, ctx, sampler=sampler, steps=4,
                        cfg_scale=3.0, uncond_context=_ctx(seed=9))
        b = run_sampler(pm, noise, ctx, sampler=sampler, steps=4,
                        cfg_scale=3.0, uncond_context=_ctx(seed=9),
                        compile_loop=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    def test_traceable_none_for_hybrid_chain(self, cpu_devices):
        # A multi-platform-group chain needs host-side scatter — not one XLA
        # program. Fake two groups by platform-splitting the chain the way
        # test_hybrid does: simplest honest proxy is to check the single-group
        # invariant directly.
        def apply_fn(params, x, t, context=None, **kwargs):
            return x * params["a"]

        pm = parallelize((apply_fn, {"a": jnp.float32(0.5)}),
                         DeviceChain.even([f"cpu:{i}" for i in range(4)]))
        assert pm.traceable() is not None
        # Force a second platform group to simulate a hybrid chain.
        import copy

        g2 = copy.copy(pm._groups[0])
        pm._groups.append(g2)
        try:
            assert pm.traceable() is None
        finally:
            pm._groups.pop()

    def test_compile_loop_falls_back_with_callback(self):
        seen = []

        def cb(i, x):
            seen.append(i)

        out = _run("euler", True, callback=cb)
        assert seen == [0, 1, 2, 3, 4]  # eager loop ran the python callback
        assert np.isfinite(np.asarray(out)).all()


class TestCompileCaching:
    def test_second_call_does_not_retrace(self):
        calls = []
        model = _toy_model(calls)
        _run("euler", True, model=model)
        first = len(calls)
        assert first > 0  # traced through the python fn
        _run("euler", True, model=model)
        assert len(calls) == first  # cache hit: no re-trace

    def test_eager_path_not_cached_across_models(self):
        # Sanity: two distinct model objects each trace once.
        c1, c2 = [], []
        _run("euler", True, model=_toy_model(c1))
        _run("euler", True, model=_toy_model(c2))
        assert len(c1) > 0 and len(c2) > 0


class TestCompilationCacheUtil:
    def test_enable_compilation_cache(self, tmp_path):
        from comfyui_parallelanything_tpu.utils import enable_compilation_cache

        d = enable_compilation_cache(str(tmp_path / "xla"))
        assert (tmp_path / "xla").is_dir()
        assert d == str(tmp_path / "xla")
