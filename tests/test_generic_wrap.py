"""Wrap-anything genericity (models/generic.py): a third-party flax module
following the reference's block-list naming convention
(any_device_parallel.py:1156) gets batch==1 pipeline mode with NO framework
edits — spec auto-derived from the params pytree; plus the explicit
pipeline_spec hint on (apply, params) tuples, and the reference's fallback
(no block lists -> data parallel only)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import (
    DeviceChain,
    derive_pipeline_spec,
    parallelize,
    wrap_flax_module,
)


class _ToyBlock(nn.Module):
    """carry -> carry, the unit the reference wraps in ParallelBlock (24-87)."""

    width: int

    @nn.compact
    def __call__(self, carry):
        h = nn.Dense(self.width)(carry["h"])
        return {**carry, "h": carry["h"] + nn.gelu(h)}


class NovelDiT(nn.Module):
    """A model family this framework has never seen: setup-style ``layers``
    list (one of the reference's discovery names) + prepare/finalize."""

    width: int = 16
    depth: int = 4

    def setup(self):
        self.embed = nn.Dense(self.width)
        self.layers = [_ToyBlock(self.width) for _ in range(self.depth)]
        self.head = nn.Dense(4)

    def prepare(self, x, t, context=None, **kwargs):
        h = self.embed(x) * jnp.cos(t)[:, None]
        if context is not None:
            h = h + context.sum(axis=(1, 2))[:, None]
        return {"h": h}

    def finalize(self, carry, out_shape):
        return self.head(carry["h"])

    def __call__(self, x, timesteps, context=None, **kwargs):
        carry = self.prepare(x, timesteps, context, **kwargs)
        for blk in self.layers:
            carry = blk(carry)
        return self.finalize(carry, x.shape)


@pytest.fixture(scope="module")
def novel():
    module = NovelDiT()
    x = jnp.ones((1, 4))
    params = module.init(jax.random.key(0), x, jnp.ones((1,)))["params"]
    return module, params


def _inputs(batch=1):
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(batch, 4)), jnp.float32)
    t = jnp.asarray(rng.uniform(0, 1, size=(batch,)), jnp.float32)
    c = jnp.asarray(rng.normal(size=(batch, 3, 2)), jnp.float32)
    return x, t, c


class TestDerive:
    def test_spec_derived_from_layers_list(self, novel):
        module, params = novel
        spec = derive_pipeline_spec(module, params)
        assert spec is not None
        assert len(spec.segments) == 4
        assert [s.param_keys for s in spec.segments] == [
            (f"layers_{i}",) for i in range(4)
        ]
        assert "embed" in spec.prepare_keys and "head" in spec.finalize_keys

    def test_no_convention_no_spec(self):
        class Flat(nn.Module):
            @nn.compact
            def __call__(self, x, t, context=None):
                return nn.Dense(4)(x)

        m = Flat()
        p = m.init(jax.random.key(0), jnp.ones((1, 4)), jnp.ones((1,)))["params"]
        assert derive_pipeline_spec(m, p) is None
        # wrap still works — data-parallel only, the reference's own fallback
        # when no known block list is found (1156-1166).
        dm = wrap_flax_module(m, p)
        assert dm.pipeline_spec is None

    def test_wrap_forward_matches_module(self, novel):
        module, params = novel
        dm = wrap_flax_module(module, params, name="novel")
        x, t, c = _inputs(2)
        np.testing.assert_allclose(
            np.asarray(dm(x, t, c)),
            np.asarray(module.apply({"params": params}, x, t, c)),
            rtol=1e-5, atol=1e-6,
        )
        assert dm.block_lists == {"layers": 4}


class TestPipelinePath:
    def test_batch_one_rides_auto_derived_pipeline(self, novel, cpu_devices):
        module, params = novel
        dm = wrap_flax_module(module, params)
        pm = parallelize(dm, DeviceChain.even([f"cpu:{i}" for i in range(4)]))
        x, t, c = _inputs(1)
        got = pm(x, t, c)
        # The batch==1 routing built and used the pipeline runner (not single).
        assert pm._pipeline_runner is not None
        assert pm._pipeline_runner.n_stages > 1
        np.testing.assert_allclose(
            np.asarray(got),
            np.asarray(module.apply({"params": params}, x, t, c)),
            rtol=1e-5, atol=1e-6,
        )

    def test_explicit_spec_hint_on_tuple(self, novel, cpu_devices):
        # The (apply, params) form cannot carry attributes; the explicit
        # pipeline_spec argument is the segments hint (VERDICT r2 item 5).
        module, params = novel
        spec = derive_pipeline_spec(module, params)

        def apply_fn(p, x, t, context=None, **kw):
            return module.apply({"params": p}, x, t, context, **kw)

        pm = parallelize(
            (apply_fn, params),
            DeviceChain.even([f"cpu:{i}" for i in range(4)]),
            pipeline_spec=spec,
        )
        x, t, c = _inputs(1)
        got = pm(x, t, c)
        assert pm._pipeline_runner is not None
        np.testing.assert_allclose(
            np.asarray(got),
            np.asarray(module.apply({"params": params}, x, t, c)),
            rtol=1e-5, atol=1e-6,
        )
