"""Roofline attribution layer (round 13): the analytic cost model, the
calibration round-trip, the measured-side bucket decomposition, the
instrument_jit integration (+ its disabled-path no-op), the capacity-weighted
fleet ring, and the scripts/roofline_report.py gate.

The calibration acceptance is the round-trip: synthetic ledger records →
fitted per-(program, platform, shape-bucket) scales → calibrated predictions
within bound of the measurements they were fitted on. The attribution
acceptance is conservation: buckets non-negative, summing to the wall. The
stdlib mirror in scripts/trace_summary.py is drift-pinned against
utils/roofline.attribution_from_trace on the same fixture (the
trace_summary/trace_aggregates discipline)."""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import pytest

from comfyui_parallelanything_tpu.fleet import (
    FleetRegistry,
    HashRing,
    ledger_capacity_weights,
)
from comfyui_parallelanything_tpu.utils import roofline, telemetry, tracing

REPO = Path(__file__).resolve().parent.parent

ATTR_BUCKETS = ("compute_s", "exposed_transfer_s", "comms_s", "host_gap_s")


# ---------------------------------------------------------------------------
# the analytic cost model
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_platform_spec_resolution(self, monkeypatch):
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        v5e = roofline.platform_spec("TPU v5e", "tpu")
        assert v5e["generation"] == "v5e"
        assert v5e["peak_flops"] == 197e12 and v5e["hbm_bw"] == 819e9
        # Tunneled device_kind strings often don't name the generation —
        # the env fallback resolves them (the bench._peak_bf16 lesson).
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p")
        assert roofline.platform_spec("axon-device", "axon")["generation"] \
            == "v5p"

    def test_cpu_pseudo_spec_is_deterministic(self, monkeypatch):
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        a = roofline.platform_spec("", "cpu")
        b = roofline.platform_spec("unknown-backend", "cpu")
        assert a["generation"] == "cpu-pseudo"
        assert {k: a[k] for k in ("peak_flops", "hbm_bw", "ici_bw")} \
            == {k: b[k] for k in ("peak_flops", "hbm_bw", "ici_bw")}

    def test_compute_vs_memory_bound(self):
        spec = roofline.platform_spec("TPU v5e", "tpu")
        compute = roofline.predict_time_s(197e12, 1e9, spec)
        assert compute["bound"] == "compute"
        assert compute["predicted_s"] == pytest.approx(1.0)
        memory = roofline.predict_time_s(1e9, 819e9, spec)
        assert memory["bound"] == "memory"
        assert memory["predicted_s"] == pytest.approx(1.0, rel=1e-6)

    def test_spmd_divides_work_over_mesh(self):
        spec = roofline.platform_spec("TPU v5e", "tpu")
        one = roofline.predict_time_s(197e12, 0, spec, n_devices=1)
        eight = roofline.predict_time_s(197e12, 0, spec, n_devices=8)
        assert eight["predicted_s"] == pytest.approx(
            one["predicted_s"] / 8
        )

    def test_collective_term(self):
        spec = roofline.platform_spec("TPU v5e", "tpu")
        # Ring model: each chip moves (n-1)/n of the payload over its link.
        assert roofline.collective_time_s(200e9, 2, spec) \
            == pytest.approx(0.5)
        assert roofline.collective_time_s(200e9, 1, spec) == 0.0
        # DCN link: the multi-host regime is slower by the link ratio.
        assert roofline.collective_time_s(200e9, 2, spec, link="dcn") \
            > roofline.collective_time_s(200e9, 2, spec, link="ici")
        pred = roofline.predict_time_s(
            1e9, 1e6, spec, n_devices=4, collective_bytes=800e9
        )
        assert pred["bound"] == "comms"
        assert pred["predicted_s"] == pytest.approx(
            pred["comms_s"] + max(pred["compute_s"], pred["memory_s"])
        )


# ---------------------------------------------------------------------------
# calibration store
# ---------------------------------------------------------------------------


def _bench_record(rung="smoke", platform="cpu", value=5.0, raw=0.5,
                  flops=1e9, **extra):
    return {
        "schema": "pa-perf-ledger/v1", "kind": "bench", "rung": rung,
        "platform": platform, "value": value,
        "predicted_step_raw_s": raw, "model_flops_per_step": flops,
        **extra,
    }


class TestCalibration:
    def test_scale_hierarchy(self):
        platform, bucket = "cpu", roofline.shape_bucket(1e9)
        calib = {
            roofline.calib_key("rung:smoke", platform, bucket):
                {"scale": 2.0, "n": 3},
            roofline.calib_key("rung:smoke", platform, "*"):
                {"scale": 3.0, "n": 5},
            roofline.calib_key("*", platform, "*"): {"scale": 4.0, "n": 9},
        }
        assert roofline.calibration_scale(
            calib, "rung:smoke", platform, bucket
        ) == 2.0
        # bucket miss → the program's any-bucket scale
        assert roofline.calibration_scale(
            calib, "rung:smoke", platform, roofline.shape_bucket(1e15)
        ) == 3.0
        # unknown program → the platform-wide learned optimism
        assert roofline.calibration_scale(
            calib, "rung:never-seen", platform, bucket
        ) == 4.0
        # empty store → uncalibrated
        assert roofline.calibration_scale({}, "x", "cpu", bucket) == 1.0

    def test_fit_and_round_trip(self, tmp_path):
        records = [_bench_record(value=v) for v in (5.0, 5.2, 4.8)]
        scales = roofline.fit_calibration(records)
        key = roofline.calib_key(
            "rung:smoke", "cpu", roofline.shape_bucket(1e9)
        )
        assert scales[key]["n"] == 3
        # conservative p25 of the measured/raw ratios (9.6, 10.0, 10.4):
        # calibrated predictions sit BELOW typical measurements so an
        # honest speedup doesn't trip the fixed (0, 1.2] gate band
        assert scales[key]["scale"] == pytest.approx(9.6)
        path = tmp_path / "roofline_calib.json"
        assert roofline.save_calibration(scales, str(path)) == str(path)
        loaded = roofline.load_calibration(str(path))
        # The round-trip acceptance: the calibrated prediction lands within
        # bound of the measurements it was fitted on.
        scale = roofline.calibration_scale(
            loaded, "rung:smoke", "cpu", roofline.shape_bucket(1e9)
        )
        calibrated = 0.5 * scale
        assert abs(calibrated - 5.0) <= 0.1 * 5.0

    def test_fit_uses_program_rows_and_skips_unfittable(self):
        records = [
            # program-level rows with a measurement fit per program
            {"schema": "pa-perf-ledger/v1", "kind": "bench",
             "platform": "cpu", "roofline_programs": {
                 "loop:k:euler": {"predicted_raw_s": 0.01, "measured_s": 0.1,
                                  "flops": 1e8, "platform": "cpu"}}},
            # stale / dryrun-marked / error / kind=dryrun records are never
            # fitted — virtual-mesh CPU timings must not calibrate real
            # predictions
            _bench_record(value=500.0, stale=True),
            _bench_record(value=500.0, dryrun=True),
            {"schema": "pa-perf-ledger/v1", "kind": "error", "value": 1.0},
            {"schema": "pa-perf-ledger/v1", "kind": "dryrun",
             "platform": "cpu", "roofline_programs": {
                 "loop:k:euler": {"predicted_raw_s": 0.01,
                                  "measured_s": 99.0, "flops": 1e8,
                                  "platform": "cpu"}}},
        ]
        scales = roofline.fit_calibration(records)
        key = roofline.calib_key(
            "loop:k:euler", "cpu", roofline.shape_bucket(1e8)
        )
        assert scales[key]["scale"] == pytest.approx(10.0)
        assert scales[key]["n"] == 1  # the dryrun's 99.0 ratio never fed in
        assert not any(k.startswith("rung:") for k in scales)

    def test_load_missing_is_empty(self, tmp_path):
        assert roofline.load_calibration(str(tmp_path / "nope.json")) == {}


# ---------------------------------------------------------------------------
# measured-side attribution
# ---------------------------------------------------------------------------


def _ev(name, ts, dur, cat="stream", **args):
    return {"ph": "X", "name": name, "cat": cat, "ts": ts, "dur": dur,
            "tid": 1, "args": args}


class TestAttribution:
    def test_streamed_window(self):
        t0 = 1000.0
        events = [
            _ev("stream-run", t0, 1000.0),
            _ev("stream-stage-compute", t0 + 100, 400.0),
            _ev("stream-stage-compute", t0 + 550, 300.0),
            _ev("stream-prefetch-wait", t0 + 20, 80.0),
        ]
        attr = roofline.attribution_from_trace(events)
        assert attr["compute_s"] == pytest.approx(7e-4)
        assert attr["exposed_transfer_s"] == pytest.approx(8e-5)
        assert attr["comms_s"] == 0.0
        assert attr["wall_s"] == pytest.approx(1e-3)
        # conservation: buckets are non-negative and sum to the wall
        assert all(attr[b] >= 0 for b in ATTR_BUCKETS)
        assert sum(attr[b] for b in ATTR_BUCKETS) \
            == pytest.approx(attr["wall_s"], rel=1e-6)

    def test_step_window_with_comms_and_last_steps(self):
        t0 = 0.0
        events = [
            _ev("step", t0, 100.0, cat="bench"),          # warmup — dropped
            _ev("step", t0 + 1000, 100.0, cat="bench"),
            _ev("fleet-hop", t0 + 1120, 50.0, cat="fleet"),
            _ev("step", t0 + 1200, 100.0, cat="bench"),
        ]
        attr = roofline.attribution_from_trace(events, last_steps=2)
        # dispatch window: host gaps measured (100µs gap, 50µs of it filled
        # by the fleet hop), compute is the residual
        assert attr["comms_s"] == pytest.approx(5e-5)
        assert attr["host_gap_s"] == pytest.approx(5e-5)
        assert attr["compute_s"] == pytest.approx(2e-4)
        assert attr["wall_s"] == pytest.approx(3e-4)
        # an externally pinned wall (the chained loop's readback extends
        # past the last dispatch) widens only the residual COMPUTE bucket —
        # the device was working through that opaque wait, the host was not
        pinned = roofline.attribution_from_trace(
            events, wall_s=1e-3, last_steps=2
        )
        assert pinned["wall_s"] == pytest.approx(1e-3)
        assert pinned["host_gap_s"] == attr["host_gap_s"]
        assert pinned["compute_s"] == pytest.approx(9e-4)
        assert sum(pinned[b] for b in ATTR_BUCKETS) \
            == pytest.approx(1e-3, rel=1e-6)

    def test_empty_trace_is_none(self):
        assert roofline.attribution_from_trace([]) is None
        assert roofline.attribution_from_trace(
            [_ev("lane-wait", 0, 10.0, cat="serving")]
        ) is None

    def test_fractions(self):
        attr = {"compute_s": 0.5, "exposed_transfer_s": 0.25,
                "comms_s": 0.0, "host_gap_s": 0.25, "wall_s": 1.0}
        fr = roofline.attribution_fractions(attr)
        assert fr["compute_fraction"] == 0.5
        assert fr["host_gap_fraction"] == 0.25
        assert roofline.attribution_fractions(None) is None

    def test_traced_streamed_run_buckets_sum_to_wall(self):
        """The acceptance on a REAL traced streamed run: a tiny
        StreamingRunner call under tracing, buckets summing to the
        stream-run wall."""
        import jax

        from comfyui_parallelanything_tpu.models.flux import (
            FluxConfig,
            build_flux,
        )
        from comfyui_parallelanything_tpu.models.loader import params_nbytes
        from comfyui_parallelanything_tpu.parallel.streaming import (
            build_streaming_runner,
        )

        cfg = FluxConfig(
            in_channels=16, hidden_size=64, num_heads=4, depth=1,
            depth_single_blocks=2, context_in_dim=32, vec_in_dim=16,
            axes_dim=(4, 6, 6), guidance_embed=False, dtype=jnp.float32,
        )
        model = build_flux(
            cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=8
        )
        runner = build_streaming_runner(
            model.pipeline_spec, model.params, jax.devices("cpu")[0],
            hbm_budget_bytes=params_nbytes(model.params) // 3,
        )
        tracing.enable()
        try:
            out = runner(
                jnp.zeros((1, 8, 8, 4)), jnp.ones((1,)),
                jnp.zeros((1, 8, cfg.context_in_dim)),
                y=jnp.zeros((1, cfg.vec_in_dim)),
            )
            jax.block_until_ready(out)
            events = tracing.export()
        finally:
            tracing.disable()
        attr = roofline.attribution_from_trace(events)
        assert attr is not None and attr["compute_s"] > 0
        assert all(attr[b] >= 0 for b in ATTR_BUCKETS)
        total = sum(attr[b] for b in ATTR_BUCKETS)
        assert abs(total - attr["wall_s"]) <= 0.1 * attr["wall_s"]


# ---------------------------------------------------------------------------
# instrument_jit integration + flag discipline
# ---------------------------------------------------------------------------


class TestProgramRegistry:
    def test_instrumented_jit_records_prediction(self, monkeypatch):
        monkeypatch.setenv("PA_TELEMETRY_COST", "1")
        monkeypatch.delenv("PA_ROOFLINE", raising=False)
        roofline.programs.reset()
        fn = telemetry.instrument_jit(
            lambda a: (a @ a + a).sum(), "roofline-test-prog"
        )
        fn(jnp.ones((64, 64), jnp.float32))
        rows = roofline.programs.rows()
        assert "roofline-test-prog" in rows, sorted(rows)
        row = rows["roofline-test-prog"]
        assert row["predicted_s"] > 0 and row["predicted_raw_s"] > 0
        assert row["platform"] == "cpu"
        assert row["flops"] or row["bytes_accessed"]
        assert row["bound"] in ("compute", "memory", "comms")
        # the health document carries the same rows
        snap = roofline.programs.snapshot()
        assert snap["enabled"] and "roofline-test-prog" in snap["programs"]
        health = telemetry.health_snapshot()
        assert "roofline-test-prog" in health["roofline"]["programs"]

    def test_sharded_args_feed_the_collective_term(self, monkeypatch,
                                                   cpu_devices):
        """A program whose args are genuinely sharded over the mesh gets a
        nonzero collective_bytes estimate (the FSDP/TP all-gather volume);
        fully-replicated args contribute nothing."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from comfyui_parallelanything_tpu.parallel.mesh import build_mesh

        monkeypatch.setenv("PA_TELEMETRY_COST", "1")
        monkeypatch.delenv("PA_ROOFLINE", raising=False)
        roofline.programs.reset()
        mesh = build_mesh(cpu_devices[:8])
        sharded = jax.device_put(
            jnp.ones((8, 64), jnp.float32), NamedSharding(mesh, P("data"))
        )
        replicated = jax.device_put(
            jnp.ones((64, 64), jnp.float32), NamedSharding(mesh, P())
        )
        fn = telemetry.instrument_jit(
            lambda a, w: (a @ w).sum(), "roofline-sharded-prog"
        )
        fn(sharded, replicated)
        row = roofline.programs.rows()["roofline-sharded-prog"]
        assert row["n_devices"] == 8
        assert row["collective_bytes"] == sharded.nbytes  # not the replica
        assert row["comms_s"] > 0
        roofline.programs.reset()

    def test_disabled_path_is_noop(self, monkeypatch):
        """PA_ROOFLINE=0: no row, no prediction — and telemetry's own FLOPs
        accounting must be untouched (the tracer/sentinel flag discipline)."""
        monkeypatch.setenv("PA_TELEMETRY_COST", "1")
        monkeypatch.setenv("PA_ROOFLINE", "0")
        roofline.programs.reset()
        fn = telemetry.instrument_jit(
            lambda a: (a @ a).sum(), "roofline-off-prog"
        )
        fn(jnp.ones((32, 32), jnp.float32))
        assert "roofline-off-prog" not in roofline.programs.rows()
        assert not roofline.enabled()
        # telemetry cost accounting still ran
        prog = telemetry.compile_snapshot()["programs"].get(
            "roofline-off-prog"
        )
        assert prog is not None and prog["flops"]
        # publish_gauges is a no-op too
        roofline.publish_gauges()

    def test_refresh_calibration_reprices(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        roofline.programs.reset()
        row = roofline.programs.record(
            "reprice-prog", flops=1e9, bytes_accessed=1e6,
            n_devices=1, platform="cpu",
        )
        assert row["calib_scale"] == 1.0
        raw = row["predicted_raw_s"]
        scales = {
            roofline.calib_key("reprice-prog", "cpu",
                               roofline.shape_bucket(1e9)):
                {"scale": 7.0, "n": 1},
        }
        roofline.save_calibration(scales)
        roofline.programs.refresh_calibration()
        row2 = roofline.programs.rows()["reprice-prog"]
        assert row2["calib_scale"] == 7.0
        assert row2["predicted_s"] == pytest.approx(7.0 * raw)
        assert row2["predicted_raw_s"] == pytest.approx(raw)
        roofline.programs.reset()


class TestStepCost:
    def test_unified_accessor_sources_agree(self):
        def apply(p, x, t, ctx):
            return x @ p + t[:, None] + ctx.sum()

        cost = roofline.step_cost(
            apply, jnp.ones((64, 64), jnp.float32),
            jnp.ones((4, 64), jnp.float32), jnp.ones((4,), jnp.float32),
            jnp.ones((4, 8), jnp.float32),
        )
        assert cost["flops"] and cost["flops"] > 0
        assert cost["flops_source"] in ("hlo", "jaxpr")
        # the jaxpr walk always resolves on a dot_general
        assert cost["flops_jaxpr"] == pytest.approx(2 * 4 * 64 * 64, rel=0.5)
        if cost["flops_hlo"]:
            # both sources present → the discrepancy audit must be sane
            assert cost["flops_discrepancy_ratio"] is not None
            assert 0.2 <= cost["flops_discrepancy_ratio"] <= 5.0

    def test_analytic_flops_fallback_counts_dots(self):
        flops = roofline.analytic_flops(
            lambda p, x, t, c: x @ p,
            jnp.ones((16, 16)), jnp.ones((2, 16)), jnp.ones((2,)),
            jnp.ones((2, 4)),
        )
        assert flops == pytest.approx(2 * 2 * 16 * 16)


# ---------------------------------------------------------------------------
# capacity-weighted fleet ring (ROADMAP fleet-hardening item 2)
# ---------------------------------------------------------------------------


class TestCapacityWeightedRing:
    def _primary_share(self, ring: HashRing, n_keys: int = 3000) -> dict:
        counts: dict[str, int] = {}
        for i in range(n_keys):
            primary = ring.sequence(f"model-{i}")[0]
            counts[primary] = counts.get(primary, 0) + 1
        return {h: c / n_keys for h, c in counts.items()}

    def test_placement_distribution_follows_weights(self):
        ring = HashRing(vnodes=128)
        ring.rebuild(["a", "b", "c"], {"a": 2.0})
        share = self._primary_share(ring)
        # a holds 2 vnode shares of 4 total; b and c one each
        assert share["a"] == pytest.approx(0.5, abs=0.07)
        assert share["b"] == pytest.approx(0.25, abs=0.07)
        assert share["c"] == pytest.approx(0.25, abs=0.07)

    def test_equal_weights_fallback(self):
        ring = HashRing(vnodes=128)
        ring.rebuild(["a", "b", "c"])  # no history → equal split
        share = self._primary_share(ring)
        for h in ("a", "b", "c"):
            assert share[h] == pytest.approx(1 / 3, abs=0.07)

    def test_weight_change_moves_only_local_keys(self):
        ring = HashRing(vnodes=64)
        ring.rebuild(["a", "b", "c"])
        before = {f"m{i}": ring.sequence(f"m{i}")[0] for i in range(500)}
        ring.rebuild(["a", "b", "c"], {"a": 1.5})
        moved = sum(
            1 for k, h in before.items() if ring.sequence(k)[0] != h
        )
        # only keys adjacent to a's NEW vnodes move — and they move TO a
        assert 0 < moved < 250
        for k, h in before.items():
            now = ring.sequence(k)[0]
            if now != h:
                assert now == "a"

    def test_registry_uses_ledger_weights(self, tmp_path, monkeypatch):
        ledger = tmp_path / "perf_ledger.jsonl"
        # loadgen history: fast-host serves steps 2x faster than slow-host
        rec = {
            "schema": "pa-perf-ledger/v1", "kind": "loadgen",
            "hosts": {
                "fast-host": {"server_step_p50_s": 1.0},
                "slow-host": {"server_step_p50_s": 2.0},
            },
        }
        ledger.write_text(json.dumps(rec) + "\n")
        weights = ledger_capacity_weights(str(ledger))
        assert weights["fast-host"] == pytest.approx(4 / 3, abs=0.01)
        assert weights["slow-host"] == pytest.approx(2 / 3, abs=0.01)
        # the registry consumes them (explicitly here; by default it reads
        # the process ledger dir) and the ring share follows
        reg = FleetRegistry(vnodes=128, capacity_weights=weights,
                            capacity_from_ledger=False)
        reg.add_static("fast-host", "http://f:1")
        reg.add_static("slow-host", "http://s:1")
        counts = {"fast-host": 0, "slow-host": 0}
        for i in range(2000):
            counts[reg.sequence(f"model-{i}")[0]] += 1
        assert counts["fast-host"] > counts["slow-host"] * 1.4
        # no-history fallback: equal weights
        assert ledger_capacity_weights(str(tmp_path / "nope.jsonl")) == {}
        # the refresh hook rebuilds with new weights
        reg.set_capacity_weights({})
        counts2 = {"fast-host": 0, "slow-host": 0}
        for i in range(2000):
            counts2[reg.sequence(f"model-{i}")[0]] += 1
        assert abs(counts2["fast-host"] - counts2["slow-host"]) < 400

    def test_host_step_weights_sources(self):
        records = [
            {"kind": "loadgen", "hosts": {
                "h1": {"server_step_p50_s": 1.0},
                "h2": {"server_step_p50_s": 4.0},
            }},
            # stale loadgen and bench records never feed the ring: bench
            # s/it is rung-dependent (smoke vs flux_16 would compare two
            # identical hosts as 80x apart), so only the fleet's own
            # same-workload loadgen measurements qualify
            {"kind": "loadgen", "stale": True,
             "hosts": {"h2": {"server_step_p50_s": 400.0}}},
            {"kind": "bench", "host": "h3", "value": 0.1},
            {"kind": "error", "host": "h4", "value": 0.1},
        ]
        w = roofline.host_step_weights(records)
        assert set(w) == {"h1", "h2"}
        assert w["h1"] > w["h2"]  # h1 steps 4x faster
        assert roofline.host_step_weights([]) == {}

    def test_host_step_weights_never_mixes_metrics(self):
        # h-lat's only history is END-TO-END latency (queueing + HTTP
        # included) — comparing it against h-step's per-dispatch step time
        # would starve it; it must simply drop out (weight 1.0 default).
        records = [
            {"kind": "loadgen", "hosts": {
                "h-step": {"server_step_p50_s": 0.2},
                "h-lat": {"latency_p50_s": 2.0},
            }},
        ]
        w = roofline.host_step_weights(records)
        assert "h-lat" not in w and w == {"h-step": 1.0}
        # latency-only fleets still weight — consistently, on one metric
        lat_only = [{"kind": "loadgen", "hosts": {
            "a": {"latency_p50_s": 1.0}, "b": {"latency_p50_s": 3.0},
        }}]
        w2 = roofline.host_step_weights(lat_only)
        assert w2["a"] > 1.0 > w2["b"]


# ---------------------------------------------------------------------------
# scripts/roofline_report.py (the CI gate + the bank)
# ---------------------------------------------------------------------------


def _run_report(tmp_path, *args):
    return subprocess.run(
        [sys.executable, str(REPO / "scripts" / "roofline_report.py"),
         "--ledger", str(tmp_path), *args],
        capture_output=True, text=True, timeout=120, cwd=str(REPO),
    )


def _write_ledger(tmp_path, records):
    (tmp_path / "perf_ledger.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in records)
    )


def _good_record(**over):
    rec = {
        "schema": "pa-perf-ledger/v1", "kind": "bench", "rung": "smoke",
        "platform": "cpu", "value": 5.0, "unit": "s/it",
        "predicted_step_s": 0.5, "predicted_step_raw_s": 0.5,
        "roofline_ratio": 0.1, "model_flops_per_step": 1e9,
        "attribution": {"compute_s": 4.0, "exposed_transfer_s": 0.0,
                        "comms_s": 0.0, "host_gap_s": 1.0, "wall_s": 5.0},
    }
    rec.update(over)
    return rec


class TestRooflineReport:
    def test_empty_ledger_skips(self, tmp_path):
        proc = _run_report(tmp_path, "--check")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "SKIP" in proc.stdout

    def test_good_record_passes(self, tmp_path):
        _write_ledger(tmp_path, [_good_record()])
        proc = _run_report(tmp_path, "--check")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK" in proc.stdout

    def test_out_of_band_ratio_fails(self, tmp_path):
        _write_ledger(tmp_path, [_good_record(roofline_ratio=5.0)])
        proc = _run_report(tmp_path, "--check")
        assert proc.returncode == 1
        assert "roofline_ratio" in proc.stdout

    def test_negative_bucket_fails(self, tmp_path):
        bad = _good_record()
        bad["attribution"]["host_gap_s"] = -1.0
        _write_ledger(tmp_path, [bad])
        assert _run_report(tmp_path, "--check").returncode == 1

    def test_bucket_sum_mismatch_fails(self, tmp_path):
        bad = _good_record()
        bad["attribution"]["wall_s"] = 50.0
        _write_ledger(tmp_path, [bad])
        assert _run_report(tmp_path, "--check").returncode == 1

    def test_stale_and_preroofline_records_skipped(self, tmp_path):
        _write_ledger(tmp_path, [
            _good_record(roofline_ratio=5.0, stale=True),
            # pre-round-13 record: no roofline fields at all
            {"schema": "pa-perf-ledger/v1", "kind": "bench",
             "rung": "old", "platform": "cpu", "value": 3.0},
        ])
        proc = _run_report(tmp_path, "--check")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "SKIP" in proc.stdout

    def test_latest_record_wins(self, tmp_path):
        _write_ledger(tmp_path, [
            _good_record(roofline_ratio=5.0),  # older failure…
            _good_record(),                    # …fixed by the latest
        ])
        assert _run_report(tmp_path, "--check").returncode == 0

    def test_bank_fits_and_persists(self, tmp_path):
        _write_ledger(tmp_path, [_good_record() for _ in range(3)])
        proc = _run_report(tmp_path, "--bank")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        calib = json.loads(
            (tmp_path / "roofline_calib.json").read_text()
        )
        assert calib["schema"] == "pa-roofline-calib/v1"
        key = roofline.calib_key(
            "rung:smoke", "cpu", roofline.shape_bucket(1e9)
        )
        assert calib["scales"][key]["scale"] == pytest.approx(10.0)
        # summary mode reads both files without error
        assert _run_report(tmp_path).returncode == 0


# ---------------------------------------------------------------------------
# trace_summary drift pin (stdlib mirror vs the in-package math)
# ---------------------------------------------------------------------------


class TestTraceSummaryAttributionPin:
    def test_script_matches_roofline_attribution(self, tmp_path):
        tracing.enable()
        try:
            t0 = tracing.now_us()
            tracing.record("stream-run", t0, 1000.0, cat="stream")
            tracing.record("stream-stage-compute", t0 + 100, 400.0,
                           cat="stream", stage=0)
            tracing.record("stream-stage-compute", t0 + 550, 300.0,
                           cat="stream", stage=1)
            tracing.record("stream-prefetch-wait", t0 + 20, 60.0,
                           cat="stream", stage=0)
            export = tracing.export()
        finally:
            tracing.disable()
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(export))
        expect = roofline.attribution_from_trace(export)
        assert expect is not None
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "trace_summary.py"),
             str(path), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        got = json.loads(proc.stdout)["attribution"]
        for key in (*ATTR_BUCKETS, "wall_s"):
            assert got[key] == pytest.approx(expect[key]), key
        # the script additionally surfaces the two headline fractions
        assert got["comms_fraction"] == pytest.approx(
            expect["comms_s"] / expect["wall_s"], abs=1e-3
        )
        assert got["host_gap_fraction"] == pytest.approx(
            expect["host_gap_s"] / expect["wall_s"], abs=1e-3
        )
